// Native paged-binary batch iterator + C ABI.
//
// The TPU-side equivalent of the reference's native data pipeline:
//   * paged pack reading        — iter_thread_imbin-inl.hpp:16-283
//   * background batch prefetch — iter_batch_proc-inl.hpp:136-224
//   * jpeg decode               — utils/decoder.h:21-105 (libjpeg path)
//   * round_batch / num_batch_padd protocol — io/data.h:85-87,
//     iter_batch_proc-inl.hpp:89-106
//   * shard selection for distributed workers — iter_thread_imbin:189-220
//
// One producer thread reads pages, decodes records, applies mean/scale and
// assembles finished float32 batches into a depth-2 bounded queue; the
// consumer (Python via ctypes, or any C caller) memcpys them out.  This
// keeps decode + normalization entirely off the Python interpreter, which
// is the point of having a native loader under a jitted TPU training loop:
// the host side must produce batches faster than ~20k imgs/sec (bench.py)
// and a per-instance Python loop cannot.
//
// Record decode rules (payload is opaque bytes in the page format):
//   len == c*h*w          -> raw u8, CHW
//   len == 4*c*h*w        -> raw f32 little-endian, CHW
//   starts with FF D8     -> JPEG (libjpeg), decoded HWC -> CHW; decoded
//                            dims must equal the configured input_shape
// Output value = (raw - mean_value[c]) * scale   (iter_augment_proc SetData)

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>

#include "binpage.h"
#include "config.h"
#include "thread_buffer.h"

namespace cxn {

struct Batch {
  std::vector<float> data;          // (batch, c, h, w); empty in u8 mode
  std::vector<unsigned char> du8;   // u8 mode (output_u8=1): raw bytes
  std::vector<float> label;         // (batch, label_width)
  std::vector<uint64_t> index;      // (batch,)
  uint32_t num_batch_padd = 0;
  bool end_of_epoch = false;        // sentinel: no data, epoch finished
};

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jmp;
};

static void JpegErrExit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jmp, 1);
}

// decode jpeg -> CHW float (RGB); returns false on failure or dim mismatch
static bool DecodeJpeg(const char* buf, size_t len, int c, int h, int w,
                       float* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = JpegErrExit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, reinterpret_cast<const unsigned char*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (c == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if ((int)cinfo.output_width != w || (int)cinfo.output_height != h ||
      (int)cinfo.output_components != c) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  std::vector<unsigned char> row(w * c);
  unsigned char* rowp = row.data();
  for (int y = 0; y < h; ++y) {
    jpeg_read_scanlines(&cinfo, &rowp, 1);
    for (int x = 0; x < w; ++x)
      for (int ch = 0; ch < c; ++ch)
        out[(ch * h + y) * w + x] = (float)row[x * c + ch];
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// decode jpeg -> CHW u8 (RGB); the device-side-normalization path
// (output_u8=1) never touches floats on the host
static bool DecodeJpeg8(const char* buf, size_t len, int c, int h, int w,
                        unsigned char* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = JpegErrExit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, reinterpret_cast<const unsigned char*>(buf), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (c == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if ((int)cinfo.output_width != w || (int)cinfo.output_height != h ||
      (int)cinfo.output_components != c) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  std::vector<unsigned char> row(w * c);
  unsigned char* rowp = row.data();
  for (int y = 0; y < h; ++y) {
    jpeg_read_scanlines(&cinfo, &rowp, 1);
    for (int x = 0; x < w; ++x)
      for (int ch = 0; ch < c; ++ch)
        out[((size_t)ch * h + y) * w + x] = row[x * c + ch];
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

class ImbinIterator {
 public:
  bool Init(const std::string& cfg_text, std::string* err) {
    Config cfg;
    if (!cfg.Parse(cfg_text, err)) return false;
    batch_size_ = cfg.GetInt("batch_size", 0);
    if (batch_size_ <= 0) {
      *err = "batch_size must be set";
      return false;
    }
    {
      std::string shp = cfg.Get("input_shape");
      if (shp.empty()) {
        *err = "input_shape must be set (c,h,w)";
        return false;
      }
      if (std::sscanf(shp.c_str(), "%d,%d,%d", &c_, &h_, &w_) != 3) {
        *err = "input_shape must be c,h,w";
        return false;
      }
    }
    label_width_ = cfg.GetInt("label_width", 1);
    shuffle_ = cfg.GetInt("shuffle", 0);
    round_batch_ = cfg.GetInt("round_batch", 0);
    seed_data_ = cfg.GetInt("seed_data", 0);
    scale_ = cfg.GetFloat("scale", 1.0);
    silent_ = cfg.GetInt("silent", 0);
    // output_u8=1: emit raw u8 batches; mean/scale normalization moves to
    // the device (fuses into conv1), host memcpy traffic drops 4x and the
    // host<->device transfer halves vs bf16 (quarters vs f32)
    output_u8_ = cfg.GetInt("output_u8", 0);
    // decode fan-out (reference iter_thread_imbin_x decoder threads);
    // 0 = decode inline on the producer.  Default: half the cores — jpeg
    // decode at ~1-3 ms/image single-threaded cannot feed a ~20k imgs/sec
    // training step
    long hw = (long)std::thread::hardware_concurrency();
    decode_threads_ = cfg.GetInt("decode_thread_num",
                                 hw > 1 ? hw / 2 : 0);
    if (decode_threads_ > 0) StartPool();
    mean_.assign(c_, 0.f);
    {
      std::string mv = cfg.Get("mean_value");
      if (!mv.empty()) {
        size_t pos = 0;
        for (int i = 0; i < c_ && pos != std::string::npos; ++i) {
          mean_[i] = std::stof(mv.substr(pos ? pos + 1 : 0));
          pos = mv.find(',', pos ? pos + 1 : 0);
        }
      }
    }
    // shard selection (PS_RANK env beats dist_worker_rank, reference
    // iter_thread_imbin-inl.hpp:195-199)
    long nworker = cfg.GetInt("dist_num_worker", 1);
    long rank = cfg.GetInt("dist_worker_rank", 0);
    if (const char* e = std::getenv("PS_RANK")) rank = std::atol(e);
    long nbin = cfg.GetInt("imgbin_count", 0);
    std::string pbin = cfg.Get("path_imgbin", cfg.Get("image_bin"));
    std::string plst = cfg.Get("path_imglst", cfg.Get("image_list"));
    if (pbin.empty() || plst.empty()) {
      *err = "path_imgbin and path_imglst must be set";
      return false;
    }
    char namebuf[4096];
    if (nbin > 0) {
      for (long i = 0; i < nbin; ++i) {
        if (i % nworker != rank) continue;
        std::snprintf(namebuf, sizeof namebuf, pbin.c_str(), i);
        bins_.push_back(namebuf);
        std::snprintf(namebuf, sizeof namebuf, plst.c_str(), i);
        lsts_.push_back(namebuf);
      }
    } else {
      if (nworker != 1) {
        *err = "distributed sharding needs imgbin_count > 1 shards";
        return false;
      }
      bins_.push_back(pbin);
      lsts_.push_back(plst);
    }
    // read labels/indices in shard order (lockstep with record stream);
    // also record per-shard counts so shard label offsets need no page scan
    shard_rec_count_.assign(lsts_.size(), 0);
    for (size_t si = 0; si < lsts_.size(); ++si) {
      const auto& lst = lsts_[si];
      std::FILE* f = std::fopen(lst.c_str(), "r");
      if (!f) {
        *err = "cannot open list file " + lst;
        return false;
      }
      char line[65536];
      long lineno = 0;
      while (std::fgets(line, sizeof line, f)) {
        ++lineno;
        // "index label[ label..] filename"
        std::vector<std::string> toks;
        for (char* p = std::strtok(line, " \t\r\n"); p;
             p = std::strtok(nullptr, " \t\r\n"))
          toks.emplace_back(p);
        if (toks.empty()) continue;  // blank line
        if (toks.size() < 3) {
          // silently skipping would desynchronize label/record pairing for
          // every later record in the shard — hard error instead
          std::fclose(f);
          *err = lst + " line " + std::to_string(lineno) +
                 ": expected 'index label... filename' (got " +
                 std::to_string(toks.size()) + " tokens)";
          return false;
        }
        char* end = nullptr;
        uint64_t idx = std::strtoull(toks[0].c_str(), &end, 10);
        if (!end || end == toks[0].c_str()) {
          std::fclose(f);
          *err = lst + " line " + std::to_string(lineno) +
                 ": non-numeric index '" + toks[0] + "'";
          return false;
        }
        indices_.push_back(idx);
        // labels are toks[1 .. size-2]; the last token is the filename
        for (int j = 0; j < label_width_; ++j)
          labels_.push_back(
              1 + j <= (int)toks.size() - 2
                  ? (float)std::strtod(toks[1 + j].c_str(), nullptr)
                  : 0.f);
        ++shard_rec_count_[si];
      }
      std::fclose(f);
    }
    // augmentation keys the native loader does not implement: fail loudly
    // rather than silently train without augmentation (the Python
    // ``iter = imgbin`` chain routes these through AugmentIterator)
    static const char* kUnsupported[] = {
        "rand_crop", "rand_mirror", "mirror", "mean_file", "crop_size",
        "max_rotate_angle", "max_shear_ratio", "max_aspect_ratio",
        "min_crop_size", "max_crop_size", "rotate", "rotate_list",
        "max_random_contrast", "max_random_illumination"};
    for (const char* k : kUnsupported) {
      if (cfg.Has(k) && cfg.GetFloat(k, 0) != 0) {
        *err = std::string("imbin_native does not support augmentation key '")
               + k + "'; use the Python `iter = imgbin` chain for augmented "
               "training or preprocess offline";
        return false;
      }
    }
    if (!silent_)
      std::fprintf(stderr, "NativeImbinIterator: %zu images in %zu shard(s)\n",
                   indices_.size(), bins_.size());
    return true;
  }

  void BeforeFirst() {
    ++gen_;
    queue_.Reset(gen_);
    if (producer_.joinable()) producer_.join();
    run_err_.clear();  // a past epoch's error must not outlive its restart
    // re-arm the queue for the new generation (Reset also wakes stale
    // producers blocked on a full queue)
    producer_ = std::thread([this, g = gen_.load()] { Produce(g); });
    exhausted_ = false;
  }

  // 1 = batch written, 0 = epoch end.  ``data`` points at float or u8
  // storage depending on output_u8 (the wrapper queries IsU8).
  int NextBatch(void* data, float* label, uint64_t* index,
                uint32_t* num_batch_padd) {
    if (exhausted_) return 0;
    Batch b = queue_.Pop();
    if (b.end_of_epoch) {
      exhausted_ = true;
      return 0;
    }
    std::memcpy(data, bytes(b), (size_t)batch_size_ * inst_bytes());
    std::memcpy(label, b.label.data(), b.label.size() * sizeof(float));
    std::memcpy(index, b.index.data(), b.index.size() * sizeof(uint64_t));
    *num_batch_padd = b.num_batch_padd;
    return 1;
  }

  bool output_u8() const { return output_u8_ != 0; }

  int batch_size() const { return batch_size_; }
  int c() const { return c_; }
  int h() const { return h_; }
  int w() const { return w_; }
  int label_width() const { return label_width_; }
  size_t num_inst() const { return indices_.size(); }
  const std::string& error() const { return run_err_; }

  ~ImbinIterator() {
    ++gen_;
    queue_.Reset(gen_);
    if (producer_.joinable()) producer_.join();
    {
      std::lock_guard<std::mutex> l(jobs_m_);
      pool_shutdown_ = true;
    }
    jobs_cv_.notify_all();
    for (auto& t : pool_) t.join();
  }

 private:
  size_t inst_size() const { return (size_t)c_ * h_ * w_; }

  bool DecodeInto(const std::vector<char>& rec, float* out) {
    const size_t n = inst_size();
    if (rec.size() == n) {
      const unsigned char* p = (const unsigned char*)rec.data();
      for (size_t i = 0; i < n; ++i) out[i] = (float)p[i];
    } else if (rec.size() == 4 * n) {
      std::memcpy(out, rec.data(), 4 * n);
    } else if (rec.size() >= 2 && (unsigned char)rec[0] == 0xFF &&
               (unsigned char)rec[1] == 0xD8) {
      if (!DecodeJpeg(rec.data(), rec.size(), c_, h_, w_, out)) return false;
    } else {
      return false;
    }
    // normalization fused into the copy loop's cache-warm output
    for (int ch = 0; ch < c_; ++ch) {
      float m = mean_[ch];
      float* o = out + (size_t)ch * h_ * w_;
      for (size_t i = 0, e = (size_t)h_ * w_; i < e; ++i)
        o[i] = (o[i] - m) * (float)scale_;
    }
    return true;
  }

  // u8-mode decode: raw u8 records are a straight memcpy, jpegs decode
  // without any float pass; f32 records cannot be emitted as u8
  bool DecodeInto8(const std::vector<char>& rec, unsigned char* out) {
    const size_t n = inst_size();
    if (rec.size() == n) {
      std::memcpy(out, rec.data(), n);
    } else if (rec.size() >= 2 && (unsigned char)rec[0] == 0xFF &&
               (unsigned char)rec[1] == 0xD8) {
      if (!DecodeJpeg8(rec.data(), rec.size(), c_, h_, w_, out))
        return false;
    } else {
      return false;  // f32 records have no faithful u8 form
    }
    return true;
  }

  // batch data as raw bytes (mode-independent copies for pad/wrap paths)
  char* bytes(Batch& b) const {
    return output_u8_ ? (char*)b.du8.data() : (char*)b.data.data();
  }
  size_t inst_bytes() const {
    return inst_size() * (output_u8_ ? 1 : sizeof(float));
  }

  // Stream shards/pages in (shuffled) order, calling
  // fn(rec_bytes, global_index) per record; returns false on error or
  // generation change (run_err_ set on error).
  template <class FnRecord>
  bool StreamRecords(uint64_t gen, std::mt19937_64& rng, FnRecord&& fn) {
    std::vector<size_t> shard_order(bins_.size());
    for (size_t i = 0; i < shard_order.size(); ++i) shard_order[i] = i;
    if (shuffle_) std::shuffle(shard_order.begin(), shard_order.end(), rng);
    for (size_t so = 0; so < shard_order.size(); ++so) {
      size_t b = shard_order[so];
      // shard b's labels start at offset = sum of record counts of shards
      // before b in file order (counted from the .lst files at Init; a
      // bin/lst count mismatch is caught by the end-of-shard check below)
      size_t off = 0;
      for (size_t i = 0; i < b; ++i) off += shard_rec_count_[i];
      size_t pos = off;
      BinPageReader rd;
      std::string err;
      if (!rd.Open(bins_[b], &err)) { run_err_ = err; return false; }
      Page page;
      while (true) {
        if (queue_.gen() != gen) return false;  // orphaned
        if (!rd.NextPage(&page, &err)) {
          if (!err.empty()) { run_err_ = err; return false; }
          break;
        }
        if (pos + page.recs.size() > off + shard_rec_count_[b]) {
          run_err_ = bins_[b] + ": more records than its list has entries";
          return false;
        }
        std::vector<uint32_t> order(page.recs.size());
        for (uint32_t i = 0; i < order.size(); ++i) order[i] = i;
        if (shuffle_) std::shuffle(order.begin(), order.end(), rng);
        for (uint32_t oi = 0; oi < order.size(); ++oi) {
          uint32_t ri = order[oi];
          // each record is visited exactly once; hand it over by value so
          // the pooled path can move it into its decode job copy-free
          if (!fn(std::move(page.recs[ri]), pos + ri)) return false;
        }
        pos += page.recs.size();
      }
    }
    return true;
  }

  // A batch under construction on the decode pool: jobs decrement
  // `remaining`; the producer waits for 0 before pushing.  Heap-held via
  // shared_ptr so stale jobs of an abandoned generation stay safe.
  struct DecodeSlot {
    Batch batch;
    std::atomic<int> remaining{0};
    std::atomic<bool> failed{false};
    std::mutex m;
    std::condition_variable cv;
    void Done() {
      if (remaining.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> l(m);
        cv.notify_all();
      }
    }
    void Wait() {
      std::unique_lock<std::mutex> l(m);
      cv.wait(l, [&] { return remaining.load() == 0; });
    }
  };

  struct DecodeJob {
    std::vector<char> rec;
    std::shared_ptr<DecodeSlot> slot;
    size_t row = 0;
    uint64_t gen = 0;
  };

  void StartPool() {
    pool_shutdown_ = false;
    for (long i = 0; i < decode_threads_; ++i)
      pool_.emplace_back([this] { PoolWorker(); });
  }

  void PoolWorker() {
    for (;;) {
      DecodeJob job;
      {
        std::unique_lock<std::mutex> l(jobs_m_);
        jobs_cv_.wait(l, [&] { return pool_shutdown_ || !jobs_.empty(); });
        if (pool_shutdown_ && jobs_.empty()) return;
        job = std::move(jobs_.front());
        jobs_.pop_front();
      }
      // stale generations skip the decode but still release the slot
      if (job.gen == gen_.load()) {
        bool ok;
        if (output_u8_)
          ok = DecodeInto8(job.rec, job.slot->batch.du8.data()
                           + job.row * inst_size());
        else
          ok = DecodeInto(job.rec, job.slot->batch.data.data()
                          + job.row * inst_size());
        if (!ok) job.slot->failed = true;
      }
      job.slot->Done();
    }
  }

  void Dispatch(std::vector<char>&& rec,
                const std::shared_ptr<DecodeSlot>& slot, size_t row,
                uint64_t gen) {
    slot->remaining.fetch_add(1);
    {
      std::lock_guard<std::mutex> l(jobs_m_);
      jobs_.push_back(DecodeJob{std::move(rec), slot, row, gen});
    }
    jobs_cv_.notify_one();
  }

  std::shared_ptr<DecodeSlot> NewSlot() {
    auto s = std::make_shared<DecodeSlot>();
    if (output_u8_)
      s->batch.du8.resize((size_t)batch_size_ * inst_size());
    else
      s->batch.data.resize((size_t)batch_size_ * inst_size());
    s->batch.label.resize((size_t)batch_size_ * label_width_);
    s->batch.index.resize(batch_size_);
    return s;
  }

  // producer thread: stream pages -> instances -> batches.  With a decode
  // pool, the producer only parses pages and copies labels; jpeg decode +
  // normalization fan out over `decode_thread_num` workers, two batches in
  // flight (dispatch batch k+1 while batch k finishes decoding) — the
  // reference's dedicated decoder-thread design
  // (iter_thread_imbin_x-inl.hpp:304-330) without its fixed 1:1 pairing.
  void Produce(uint64_t gen) {
    std::mt19937_64 rng(787 + seed_data_ + gen);
    const bool pooled = decode_threads_ > 0;
    // head cache for round_batch wrap (first batch_size instances);
    // byte-typed so float and u8 output modes share the copy paths
    std::vector<char> head_data((size_t)batch_size_ * inst_bytes());
    std::vector<float> head_label((size_t)batch_size_ * label_width_);
    std::vector<uint64_t> head_index(batch_size_);
    size_t head_n = 0;

    std::shared_ptr<DecodeSlot> cur = NewSlot();
    std::shared_ptr<DecodeSlot> in_flight;  // fully dispatched, decoding
    size_t top = 0;
    bool ok = true;

    auto cache_head = [&](Batch& b) {
      if (head_n) return;
      std::memcpy(head_data.data(), bytes(b), head_data.size());
      std::memcpy(head_label.data(), b.label.data(),
                  head_label.size() * sizeof(float));
      std::copy(b.index.begin(), b.index.end(), head_index.begin());
      head_n = batch_size_;
    };
    // wait for a dispatched slot's decodes, cache the head, push it
    auto finish = [&](std::shared_ptr<DecodeSlot> s) -> bool {
      s->Wait();
      if (s->failed.load()) {
        run_err_ = "record decode failed (size/format mismatch)";
        return false;
      }
      cache_head(s->batch);
      return queue_.Push(std::move(s->batch), gen);
    };

    ok = StreamRecords(gen, rng, [&](std::vector<char>&& rec,
                                     size_t gidx) {
      Batch& b = cur->batch;
      std::memcpy(b.label.data() + top * label_width_,
                  labels_.data() + gidx * label_width_,
                  label_width_ * sizeof(float));
      b.index[top] = indices_[gidx];
      if (pooled) {
        Dispatch(std::move(rec), cur, top, gen);
      } else {
        bool dok = output_u8_
            ? DecodeInto8(rec, b.du8.data() + top * inst_size())
            : DecodeInto(rec, b.data.data() + top * inst_size());
        if (!dok) {
          run_err_ = "record decode failed (size/format mismatch)";
          return false;
        }
      }
      if (++top == (size_t)batch_size_) {
        top = 0;
        if (in_flight && !finish(std::move(in_flight))) return false;
        in_flight = std::move(cur);
        cur = NewSlot();
        if (!pooled) {
          // no pool: the batch is already decoded; push immediately
          if (!finish(std::move(in_flight))) return false;
        }
      }
      return true;
    });
    if (ok && in_flight) ok = finish(std::move(in_flight));

    // tail: wrap with head instances if round_batch (batch adapter
    // parity); otherwise pad with replicas of the last instance so the
    // tail still trains (masked via num_batch_padd -> tail_mask_padd in
    // the Python wrapper — see io/iter_proc.py pad+mask rationale)
    if (ok && top > 0 && !round_batch_) {
      cur->Wait();
      Batch& b = cur->batch;
      if (cur->failed.load()) {
        run_err_ = "record decode failed (size/format mismatch)";
      } else {
        size_t need = batch_size_ - top;
        for (size_t i = 0; i < need; ++i) {
          std::memcpy(bytes(b) + (top + i) * inst_bytes(),
                      bytes(b) + (top - 1) * inst_bytes(),
                      inst_bytes());
          std::memcpy(b.label.data() + (top + i) * label_width_,
                      b.label.data() + (top - 1) * label_width_,
                      label_width_ * sizeof(float));
          b.index[top + i] = b.index[top - 1];
        }
        b.num_batch_padd = need;
        if (!queue_.Push(std::move(b), gen)) return;
      }
    } else if (ok && top > 0 && round_batch_) {
      cur->Wait();
      Batch& b = cur->batch;
      if (cur->failed.load()) {
        run_err_ = "record decode failed (size/format mismatch)";
      } else {
        if (head_n == 0) {
          // dataset smaller than one batch: the tail rows ARE the stream's
          // first instances — they serve as the wrap head
          std::memcpy(head_data.data(), bytes(b),
                      top * inst_bytes());
          std::memcpy(head_label.data(), b.label.data(),
                      top * label_width_ * sizeof(float));
          std::copy(b.index.begin(), b.index.begin() + top,
                    head_index.begin());
          head_n = top;
        }
        size_t need = batch_size_ - top;
        if (need <= head_n) {
          for (size_t i = 0; i < need; ++i) {
            std::memcpy(bytes(b) + (top + i) * inst_bytes(),
                        head_data.data() + i * inst_bytes(),
                        inst_bytes());
            std::memcpy(b.label.data() + (top + i) * label_width_,
                        head_label.data() + i * label_width_,
                        label_width_ * sizeof(float));
            b.index[top + i] = head_index[i];
          }
          b.num_batch_padd = need;
          if (!queue_.Push(std::move(b), gen)) return;
        } else {
          run_err_ = "round_batch: dataset smaller than batch";
        }
      }
    }
    Batch sentinel;
    sentinel.end_of_epoch = true;
    queue_.Push(std::move(sentinel), gen);
  }

  int batch_size_ = 0, c_ = 0, h_ = 0, w_ = 0, label_width_ = 1;
  long shuffle_ = 0, round_batch_ = 0, seed_data_ = 0, silent_ = 0;
  long output_u8_ = 0;
  long decode_threads_ = 0;
  std::vector<std::thread> pool_;
  std::deque<DecodeJob> jobs_;
  std::mutex jobs_m_;
  std::condition_variable jobs_cv_;
  bool pool_shutdown_ = false;
  double scale_ = 1.0;
  std::vector<float> mean_;
  std::vector<std::string> bins_, lsts_;
  std::vector<float> labels_;
  std::vector<uint64_t> indices_;
  std::vector<size_t> shard_rec_count_;
  BoundedQueue<Batch> queue_{2};
  std::thread producer_;
  std::atomic<uint64_t> gen_{0};
  bool exhausted_ = true;
  std::string run_err_;
};

}  // namespace cxn

// ---------------------------------------------------------------- C ABI
// Handle-based, mirroring the reference wrapper's CXNIO* surface
// (wrapper/cxxnet_wrapper.h:163-225).
extern "C" {

void* CXNIONativeCreate(const char* cfg, char* errbuf, int errlen) {
  // nothing may throw across the C ABI into ctypes (it would abort the
  // embedding process); parsing uses non-throwing strto* but allocation
  // can still throw, so belt-and-braces catch everything here
  try {
    auto* it = new cxn::ImbinIterator();
    std::string err;
    if (!it->Init(cfg ? cfg : "", &err)) {
      if (errbuf && errlen > 0)
        std::snprintf(errbuf, errlen, "%s", err.c_str());
      delete it;
      return nullptr;
    }
    return it;
  } catch (const std::exception& e) {
    if (errbuf && errlen > 0) std::snprintf(errbuf, errlen, "%s", e.what());
    return nullptr;
  } catch (...) {
    if (errbuf && errlen > 0)
      std::snprintf(errbuf, errlen, "unknown native error");
    return nullptr;
  }
}

void CXNIONativeBeforeFirst(void* h) {
  static_cast<cxn::ImbinIterator*>(h)->BeforeFirst();
}

int CXNIONativeNextBatch(void* h, float* data, float* label,
                         uint64_t* index, uint32_t* num_batch_padd) {
  return static_cast<cxn::ImbinIterator*>(h)->NextBatch(
      data, label, index, num_batch_padd);
}

// u8-mode batch fetch (output_u8=1); `data` must hold batch*c*h*w bytes
int CXNIONativeNextBatchU8(void* h, unsigned char* data, float* label,
                           uint64_t* index, uint32_t* num_batch_padd) {
  return static_cast<cxn::ImbinIterator*>(h)->NextBatch(
      data, label, index, num_batch_padd);
}

// 1 when the iterator emits u8 batches (use NextBatchU8)
int CXNIONativeIsU8(void* h) {
  return static_cast<cxn::ImbinIterator*>(h)->output_u8() ? 1 : 0;
}

// shape query: out = [batch_size, c, h, w, label_width, num_inst]
void CXNIONativeShape(void* h, long long* out) {
  auto* it = static_cast<cxn::ImbinIterator*>(h);
  out[0] = it->batch_size();
  out[1] = it->c();
  out[2] = it->h();
  out[3] = it->w();
  out[4] = it->label_width();
  out[5] = (long long)it->num_inst();
}

const char* CXNIONativeLastError(void* h) {
  return static_cast<cxn::ImbinIterator*>(h)->error().c_str();
}

void CXNIONativeFree(void* h) { delete static_cast<cxn::ImbinIterator*>(h); }

}  // extern "C"
