/*
 * C ABI for the TPU-native cxxnet framework.
 *
 * Mirrors the reference's handle-based wrapper surface
 * (wrapper/cxxnet_wrapper.h:29-225: CXNNet* / CXNIO* functions) for C/C++
 * embedders.  The implementation embeds CPython and dispatches to
 * cxxnet_tpu.wrapper.api (Net / DataIter); the compute itself runs through
 * JAX/XLA exactly as in the Python path.
 *
 * Conventions:
 *  - all functions acquire the interpreter lock internally; the library is
 *    safe to call from one thread at a time.
 *  - returned pointers (arrays, strings) stay valid until the next call on
 *    the same handle, matching the reference wrapper's buffer reuse.
 *  - on error, functions return NULL/-1 and CXNGetLastError() describes it.
 */
#ifndef CXXNET_TPU_CAPI_H_
#define CXXNET_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef float cxx_real_t;
typedef uint64_t cxx_ulong;

const char *CXNGetLastError(void);

/* ---- net ---- */
void *CXNNetCreate(const char *device, const char *cfg);
void CXNNetFree(void *handle);
int CXNNetSetParam(void *handle, const char *name, const char *val);
int CXNNetInitModel(void *handle);
int CXNNetSaveModel(void *handle, const char *fname);
int CXNNetLoadModel(void *handle, const char *fname);
int CXNNetCopyModelFrom(void *handle, const char *fname);
int CXNNetStartRound(void *handle, int round);

/* data/label are dense float32, shapes row-major */
int CXNNetUpdateBatch(void *handle, const cxx_real_t *data,
                      const cxx_ulong *dshape, int dndim,
                      const cxx_real_t *label, const cxx_ulong *lshape,
                      int lndim);
int CXNNetUpdateIter(void *handle, void *data_iter);

/* out_shape must hold 4 entries; returns pointer into handle-owned memory */
const cxx_real_t *CXNNetPredictBatch(void *handle, const cxx_real_t *data,
                                     const cxx_ulong *dshape, int dndim,
                                     cxx_ulong *out_shape, int *out_ndim);
const cxx_real_t *CXNNetPredictIter(void *handle, void *data_iter,
                                    cxx_ulong *out_shape, int *out_ndim);
const cxx_real_t *CXNNetExtractBatch(void *handle, const cxx_real_t *data,
                                     const cxx_ulong *dshape, int dndim,
                                     const char *node_name,
                                     cxx_ulong *out_shape, int *out_ndim);
const cxx_real_t *CXNNetExtractIter(void *handle, void *data_iter,
                                    const char *node_name,
                                    cxx_ulong *out_shape, int *out_ndim);
const char *CXNNetEvaluate(void *handle, void *data_iter, const char *name);

const cxx_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *tag, cxx_ulong *out_shape,
                                  int *out_ndim);
int CXNNetSetWeight(void *handle, const cxx_real_t *weight, cxx_ulong size,
                    const char *layer_name, const char *tag);

/* ---- data iterators ---- */
void *CXNIOCreateFromConfig(const char *cfg);
void CXNIOFree(void *handle);
int CXNIONext(void *handle); /* 1 = has batch, 0 = end, -1 = error */
int CXNIOBeforeFirst(void *handle);
const cxx_real_t *CXNIOGetData(void *handle, cxx_ulong *out_shape,
                               int *out_ndim);
const cxx_real_t *CXNIOGetLabel(void *handle, cxx_ulong *out_shape,
                                int *out_ndim);

/* ---- task driver ---- */
/* Run a full CLI task (train/finetune/pred/pred_raw/extract) from a config
 * file + key=value overrides — argv as for `python -m cxxnet_tpu`, without
 * the program name.  Returns the task's exit code, -1 on error.  Backs the
 * standalone `cxxnet` binary (reference: bin/cxxnet <conf> [k=v...]). */
int CXNRunTask(int argc, const char **argv);

/* Flush the embedded interpreter's stdio buffers and, when this library
 * initialised the interpreter, finalize it.  Call before process exit from
 * plain C/C++ hosts so Python-buffered output reaches redirected files. */
void CXNShutdown(void);

#ifdef __cplusplus
}
#endif
#endif /* CXXNET_TPU_CAPI_H_ */
