// Bounded producer/consumer queue: the ThreadBuffer equivalent.
//
// The reference's ThreadBuffer (src/utils/thread_buffer.h:22-202) is a
// semaphore-protocol double buffer over an ElemFactory concept; this is the
// same idea with std::mutex/condition_variable and a generation counter so
// BeforeFirst can orphan a stale producer without deadlocking (the producer
// rechecks the generation on every blocked push).
#ifndef CXXNET_NATIVE_THREAD_BUFFER_H_
#define CXXNET_NATIVE_THREAD_BUFFER_H_

#include <condition_variable>
#include <deque>
#include <mutex>

namespace cxn {

template <typename T>
class BoundedQueue {
 public:
  explicit BoundedQueue(size_t cap = 2) : cap_(cap) {}

  // returns false if the generation changed (producer must exit)
  bool Push(T&& item, uint64_t gen) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || gen_ != gen; });
    if (gen_ != gen) return false;
    q_.emplace_back(std::move(item));
    not_empty_.notify_one();
    return true;
  }
  // blocking pop; assumes a producer of the current generation is running
  T Pop() {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty(); });
    T item = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return item;
  }
  // bump generation and clear: wakes blocked producers so they can exit
  void Reset(uint64_t new_gen) {
    std::lock_guard<std::mutex> lk(mu_);
    gen_ = new_gen;
    q_.clear();
    not_full_.notify_all();
  }
  uint64_t gen() const {
    std::lock_guard<std::mutex> lk(mu_);
    return gen_;
  }

 private:
  mutable std::mutex mu_;
  std::condition_variable not_full_, not_empty_;
  std::deque<T> q_;
  size_t cap_;
  uint64_t gen_ = 0;
};

}  // namespace cxn
#endif  // CXXNET_NATIVE_THREAD_BUFFER_H_
