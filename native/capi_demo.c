/*
 * C ABI smoke driver: train a tiny MLP from plain C through the embedded
 * interpreter.  Exercises CXNNetCreate/SetParam/InitModel/UpdateBatch/
 * PredictBatch/SaveModel/LoadModel/GetWeight.  Exit 0 when the net learns
 * the synthetic rule (argmax prediction accuracy > 0.9).
 */
#include "capi.h"

#include <stdio.h>
#include <stdlib.h>

#define BATCH 64
#define DIM 16
#define NCLASS 4

static const char *kNetCfg =
    "netconfig=start\n"
    "layer[0->1] = fullc:fc1\n"
    "  nhidden = 32\n"
    "layer[1->2] = relu\n"
    "layer[2->3] = fullc:fc2\n"
    "  nhidden = 4\n"
    "layer[3->3] = softmax\n"
    "netconfig=end\n"
    "input_shape = 1,1,16\n"
    "batch_size = 64\n"
    "updater = sgd\n"
    "eta = 0.1\n";

static void fill_batch(float *data, float *label, unsigned seed) {
  /* class = argmax of 4 disjoint feature blocks */
  unsigned s = seed * 2654435761u + 12345u;
  for (int i = 0; i < BATCH; ++i) {
    int cls = (s = s * 1103515245u + 12345u) >> 16 & (NCLASS - 1);
    for (int j = 0; j < DIM; ++j) {
      float noise = ((s = s * 1103515245u + 12345u) >> 16 & 1023) / 1024.0f;
      data[i * DIM + j] = 0.1f * noise + (j / (DIM / NCLASS) == cls ? 1.f : 0.f);
    }
    label[i] = (float)cls;
  }
}

int main(void) {
  void *net = CXNNetCreate("cpu", kNetCfg);
  if (net == NULL) {
    fprintf(stderr, "create failed: %s\n", CXNGetLastError());
    return 1;
  }
  if (CXNNetInitModel(net) != 0) {
    fprintf(stderr, "init failed: %s\n", CXNGetLastError());
    return 1;
  }

  float data[BATCH * DIM], label[BATCH];
  cxx_ulong dshape[4] = {BATCH, 1, 1, DIM}, lshape[2] = {BATCH, 1};
  for (int step = 0; step < 60; ++step) {
    fill_batch(data, label, step);
    if (CXNNetUpdateBatch(net, data, dshape, 4, label, lshape, 2) != 0) {
      fprintf(stderr, "update failed: %s\n", CXNGetLastError());
      return 1;
    }
  }

  /* save -> reload -> predict */
  if (CXNNetSaveModel(net, "/tmp/capi_demo.model") != 0) return 1;
  void *net2 = CXNNetCreate("cpu", "batch_size = 64\n");
  if (net2 == NULL || CXNNetLoadModel(net2, "/tmp/capi_demo.model") != 0) {
    fprintf(stderr, "reload failed: %s\n", CXNGetLastError());
    return 1;
  }

  cxx_ulong oshape[4];
  int ondim = 0;
  fill_batch(data, label, 999);
  const cxx_real_t *pred =
      CXNNetPredictBatch(net2, data, dshape, 4, oshape, &ondim);
  if (pred == NULL) {
    fprintf(stderr, "predict failed: %s\n", CXNGetLastError());
    return 1;
  }
  int correct = 0;
  for (int i = 0; i < BATCH; ++i)
    if ((int)pred[i] == (int)label[i]) ++correct;
  printf("capi_demo: accuracy %d/%d\n", correct, BATCH);

  cxx_ulong wshape[4];
  int wndim = 0;
  const cxx_real_t *w = CXNNetGetWeight(net2, "fc1", "wmat", wshape, &wndim);
  if (w == NULL || wndim != 2 || wshape[0] != 32 || wshape[1] != DIM) {
    fprintf(stderr, "get_weight failed: %s\n", CXNGetLastError());
    return 1;
  }

  CXNNetFree(net2);
  CXNNetFree(net);
  return correct > BATCH * 9 / 10 ? 0 : 2;
}
