// im2bin: pack files listed in a .lst into a CXTPUBIN page file.
//
// Reference: tools/im2bin.cpp:6-67.  List line format is the reference's
// "index<TAB>label...<TAB>filename"; the payload is the file's raw bytes
// (jpeg, raw u8 CHW, or raw f32 CHW — the reader's decode rules pick the
// format per record).
//
//   im2bin <image.lst> <image_root_dir> <out.bin> [page_size_bytes]

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "binpage.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: im2bin image.lst image_root out.bin [page_size]\n");
    return 1;
  }
  uint64_t page_size = cxn::kDefaultPageSize;
  if (argc > 4) page_size = std::strtoull(argv[4], nullptr, 10);
  std::string err;
  cxn::BinPageWriter w;
  if (!w.Open(argv[3], page_size, &err)) {
    std::fprintf(stderr, "im2bin: %s\n", err.c_str());
    return 1;
  }
  std::FILE* lst = std::fopen(argv[1], "r");
  if (!lst) {
    std::fprintf(stderr, "im2bin: cannot open %s\n", argv[1]);
    return 1;
  }
  char line[65536];
  long n = 0;
  std::vector<char> buf;
  while (std::fgets(line, sizeof line, lst)) {
    // last token = filename
    std::vector<std::string> toks;
    for (char* p = std::strtok(line, " \t\r\n"); p;
         p = std::strtok(nullptr, " \t\r\n"))
      toks.emplace_back(p);
    if (toks.size() < 3) continue;
    std::string path = std::string(argv[2]) + "/" + toks.back();
    std::FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) {
      std::fprintf(stderr, "im2bin: cannot open %s\n", path.c_str());
      return 1;
    }
    std::fseek(f, 0, SEEK_END);
    long len = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    buf.resize(len);
    if (std::fread(buf.data(), 1, len, f) != (size_t)len) {
      std::fprintf(stderr, "im2bin: short read on %s\n", path.c_str());
      return 1;
    }
    std::fclose(f);
    if (!w.Push(buf.data(), (uint32_t)len, &err)) {
      std::fprintf(stderr, "im2bin: %s\n", err.c_str());
      return 1;
    }
    ++n;
    if (n % 1000 == 0) std::fprintf(stderr, "im2bin: %ld packed\n", n);
  }
  std::fclose(lst);
  w.Close();
  std::fprintf(stderr, "im2bin: packed %ld records into %s\n", n, argv[3]);
  return 0;
}
