// CXTPUBIN paged binary pack format, C++ side.
//
// Byte-compatible with the Python implementation (cxxnet_tpu/io/imbin.py):
//   file   := magic "CXTPUBIN" | u32 version | u64 page_size | page*
//   page   := u32 nrec | nrec * (u32 len | len bytes) | zero pad to page_size
// The fixed-size-page design mirrors the reference's BinaryPage
// (src/utils/io.h:254-326): sequential 64MB reads keep the disk/page-cache
// pipeline full regardless of record size.
#ifndef CXXNET_NATIVE_BINPAGE_H_
#define CXXNET_NATIVE_BINPAGE_H_

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace cxn {

constexpr char kMagic[8] = {'C', 'X', 'T', 'P', 'U', 'B', 'I', 'N'};
constexpr uint32_t kVersion = 1;
constexpr uint64_t kDefaultPageSize = 64ull << 20;

class BinPageWriter {
 public:
  bool Open(const std::string& path, uint64_t page_size = kDefaultPageSize,
            std::string* err = nullptr) {
    page_size_ = page_size;
    f_ = std::fopen(path.c_str(), "wb");
    if (!f_) {
      if (err) *err = "cannot open " + path;
      return false;
    }
    std::fwrite(kMagic, 1, 8, f_);
    std::fwrite(&kVersion, 4, 1, f_);
    std::fwrite(&page_size_, 8, 1, f_);
    used_ = 4;
    return true;
  }
  bool Push(const void* data, uint32_t len, std::string* err = nullptr) {
    uint64_t need = 4ull + len;
    if (need + 4 > page_size_) {
      if (err) *err = "record of " + std::to_string(len) +
                      " bytes exceeds page size";
      return false;
    }
    if (used_ + need > page_size_) FlushPage();
    recs_.insert(recs_.end(), (const char*)&len, (const char*)&len + 4);
    recs_.insert(recs_.end(), (const char*)data, (const char*)data + len);
    ++nrec_;
    used_ += need;
    return true;
  }
  void Close() {
    if (!f_) return;
    if (nrec_ > 0) FlushPage();
    std::fclose(f_);
    f_ = nullptr;
  }
  ~BinPageWriter() { Close(); }

 private:
  void FlushPage() {
    std::vector<char> page(page_size_, 0);
    std::memcpy(page.data(), &nrec_, 4);
    std::memcpy(page.data() + 4, recs_.data(), recs_.size());
    std::fwrite(page.data(), 1, page_size_, f_);
    recs_.clear();
    nrec_ = 0;
    used_ = 4;
  }
  std::FILE* f_ = nullptr;
  uint64_t page_size_ = kDefaultPageSize;
  uint64_t used_ = 4;
  uint32_t nrec_ = 0;
  std::vector<char> recs_;
};

// One decoded page: raw record bytes.
struct Page {
  std::vector<std::vector<char>> recs;
};

class BinPageReader {
 public:
  bool Open(const std::string& path, std::string* err) {
    f_ = std::fopen(path.c_str(), "rb");
    if (!f_) {
      *err = "cannot open " + path;
      return false;
    }
    char magic[8];
    uint32_t version = 0;
    if (std::fread(magic, 1, 8, f_) != 8 ||
        std::memcmp(magic, kMagic, 8) != 0) {
      *err = path + ": not a CXTPUBIN file";
      return false;
    }
    if (std::fread(&version, 4, 1, f_) != 1 || version != kVersion) {
      *err = path + ": bad version";
      return false;
    }
    if (std::fread(&page_size_, 8, 1, f_) != 1) {
      *err = path + ": truncated header";
      return false;
    }
    buf_.resize(page_size_);
    return true;
  }
  // false = EOF (or error with *err set)
  bool NextPage(Page* out, std::string* err) {
    size_t got = std::fread(buf_.data(), 1, page_size_, f_);
    if (got == 0) return false;
    if (got != page_size_) {
      *err = "truncated page";
      return false;
    }
    uint32_t nrec;
    std::memcpy(&nrec, buf_.data(), 4);
    uint64_t off = 4;
    out->recs.clear();
    out->recs.reserve(nrec);
    for (uint32_t i = 0; i < nrec; ++i) {
      uint32_t len;
      if (off + 4 > page_size_) {
        *err = "corrupt page (offset overflow)";
        return false;
      }
      std::memcpy(&len, buf_.data() + off, 4);
      off += 4;
      if (off + len > page_size_) {
        *err = "corrupt page (record overflow)";
        return false;
      }
      out->recs.emplace_back(buf_.data() + off, buf_.data() + off + len);
      off += len;
    }
    return true;
  }
  void Close() {
    if (f_) std::fclose(f_);
    f_ = nullptr;
  }
  ~BinPageReader() { Close(); }

 private:
  std::FILE* f_ = nullptr;
  uint64_t page_size_ = 0;
  std::vector<char> buf_;
};

}  // namespace cxn
#endif  // CXXNET_NATIVE_BINPAGE_H_
