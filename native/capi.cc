/*
 * C ABI implementation: embeds CPython, dispatches to cxxnet_tpu.wrapper.api.
 *
 * Reference analogue: wrapper/cxxnet_wrapper.cpp wraps the C++ trainer in
 * extern "C"; here the trainer lives in Python (jax), so the shim runs the
 * interpreter in-process.  When loaded INTO a Python process (ctypes), the
 * existing interpreter is reused; from a plain C/C++ host the interpreter is
 * initialised on first use.
 */
#include "capi.h"

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::string g_last_error;
bool g_shutdown = false;

/* python helper functions, defined once in a private dict */
const char *kHelperSrc = R"PY(
import numpy as np
from cxxnet_tpu.wrapper.api import Net, DataIter

def _arr(mv, shape):
    return np.frombuffer(mv, dtype=np.float32).reshape(shape)

def _c(a):
    return np.ascontiguousarray(a, np.float32)

def net_create(dev, cfg):
    return Net(dev=dev, cfg=cfg)

def net_update_batch(net, data, dshape, label, lshape):
    net.update(_arr(data, dshape), _arr(label, lshape))

def net_predict(net, data, dshape):
    return _c(net.predict(_arr(data, dshape)))

def net_extract(net, data, dshape, node):
    return _c(net.extract(_arr(data, dshape), node))

def _iter_map(it, fn):
    outs = []
    it.before_first()
    while it.next():
        outs.append(fn(it))
    return _c(np.concatenate(outs, axis=0))

def net_predict_iter(net, it):
    return _iter_map(it, net.predict)

def net_extract_iter(net, it, node):
    return _iter_map(it, lambda v: net.extract(v, node))

def net_get_weight(net, layer, tag):
    w = net.get_weight(layer, tag)
    return None if w is None else _c(w)

def net_set_weight(net, buf, size, layer, tag):
    w = net.get_weight(layer, tag)
    if w is None:
        raise KeyError(f"no weight {layer}:{tag}")
    net.set_weight(np.frombuffer(buf, np.float32, count=size).reshape(w.shape),
                   layer, tag)

def io_create(cfg):
    return DataIter(cfg)

def run_task(args):
    from cxxnet_tpu.main import LearnTask
    return LearnTask().run(list(args))

def io_get_data(it):
    return _c(it.get_data())

def io_get_label(it):
    return _c(it.get_label())
)PY";

PyObject *g_helpers = nullptr; /* dict holding the helper functions */

struct Handle {
  PyObject *obj = nullptr; /* Net or DataIter */
  Py_buffer buf{};         /* last returned array, owned */
  bool has_buf = false;
  std::vector<cxx_ulong> shape;
  std::string str_out;
};

void set_error_from_python() {
  PyObject *ptype = nullptr, *pvalue = nullptr, *ptb = nullptr;
  PyErr_Fetch(&ptype, &pvalue, &ptb);
  PyErr_NormalizeException(&ptype, &pvalue, &ptb);
  g_last_error = "python error";
  if (pvalue) {
    PyObject *s = PyObject_Str(pvalue);
    if (s) {
      g_last_error = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(ptype);
  Py_XDECREF(pvalue);
  Py_XDECREF(ptb);
}

bool g_we_initialized = false;

bool ensure_init() {
  static std::once_flag once;
  static bool ok = false;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      g_we_initialized = true;
      Py_InitializeEx(0);
      /* release the GIL taken by Py_Initialize; every entry point below
         re-acquires via PyGILState_Ensure */
      PyEval_SaveThread();
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *globals = PyDict_New();
    PyDict_SetItemString(globals, "__builtins__", PyEval_GetBuiltins());
    PyObject *r =
        PyRun_String(kHelperSrc, Py_file_input, globals, globals);
    if (r == nullptr) {
      set_error_from_python();
      Py_DECREF(globals);
    } else {
      Py_DECREF(r);
      g_helpers = globals;
      ok = true;
    }
    PyGILState_Release(st);
  });
  if (!ok && g_last_error.empty())
    g_last_error = "interpreter init failed";
  return ok;
}

/* call helper fn with already-built args tuple; returns new ref or null */
PyObject *call_helper(const char *fn, PyObject *args) {
  PyObject *f = PyDict_GetItemString(g_helpers, fn); /* borrowed */
  if (f == nullptr) {
    g_last_error = std::string("missing helper ") + fn;
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *r = PyObject_CallObject(f, args);
  Py_XDECREF(args);
  if (r == nullptr) set_error_from_python();
  return r;
}

PyObject *mem_ro(const void *p, Py_ssize_t nbytes) {
  return PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<void *>(p)), nbytes, PyBUF_READ);
}

PyObject *shape_tuple(const cxx_ulong *shape, int ndim) {
  PyObject *t = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(t, i, PyLong_FromUnsignedLongLong(shape[i]));
  return t;
}

cxx_ulong shape_elems(const cxx_ulong *shape, int ndim) {
  cxx_ulong n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

/* stash arr's buffer in the handle; fill out_shape/out_ndim; return data */
const cxx_real_t *return_array(Handle *h, PyObject *arr, cxx_ulong *out_shape,
                               int *out_ndim) {
  if (arr == nullptr) return nullptr;
  if (h->has_buf) {
    PyBuffer_Release(&h->buf);
    h->has_buf = false;
  }
  if (PyObject_GetBuffer(arr, &h->buf, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) !=
      0) {
    set_error_from_python();
    Py_DECREF(arr);
    return nullptr;
  }
  Py_DECREF(arr); /* h->buf keeps its own reference */
  h->has_buf = true;
  int nd = h->buf.ndim;
  if (out_ndim) *out_ndim = nd;
  if (out_shape)
    for (int i = 0; i < nd && i < 4; ++i)
      out_shape[i] = static_cast<cxx_ulong>(h->buf.shape[i]);
  return reinterpret_cast<const cxx_real_t *>(h->buf.buf);
}

struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

#define API_PROLOG(defval)                                  \
  if (g_shutdown) {                                           \
    g_last_error = "CXNShutdown was called; the library "     \
                   "cannot be used afterwards";               \
    return defval;                                            \
  }                                                           \
  if (!ensure_init()) return defval;                          \
  Gil gil_;

}  // namespace

extern "C" {

const char *CXNGetLastError(void) { return g_last_error.c_str(); }

void *CXNNetCreate(const char *device, const char *cfg) {
  API_PROLOG(nullptr);
  PyObject *r =
      call_helper("net_create", Py_BuildValue("(ss)", device, cfg));
  if (r == nullptr) return nullptr;
  Handle *h = new Handle();
  h->obj = r;
  return h;
}

void CXNNetFree(void *handle) {
  if (handle == nullptr) return;
  API_PROLOG();
  Handle *h = static_cast<Handle *>(handle);
  if (h->has_buf) PyBuffer_Release(&h->buf);
  Py_XDECREF(h->obj);
  delete h;
}

int CXNNetSetParam(void *handle, const char *name, const char *val) {
  API_PROLOG(-1);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = PyObject_CallMethod(h->obj, "set_param", "ss", name, val);
  if (r == nullptr) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

static int method0(void *handle, const char *name) {
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = PyObject_CallMethod(h->obj, name, nullptr);
  if (r == nullptr) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

static int method_s(void *handle, const char *name, const char *arg) {
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = PyObject_CallMethod(h->obj, name, "s", arg);
  if (r == nullptr) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int CXNNetInitModel(void *handle) {
  API_PROLOG(-1);
  return method0(handle, "init_model");
}
int CXNNetSaveModel(void *handle, const char *fname) {
  API_PROLOG(-1);
  return method_s(handle, "save_model", fname);
}
int CXNNetLoadModel(void *handle, const char *fname) {
  API_PROLOG(-1);
  return method_s(handle, "load_model", fname);
}
int CXNNetCopyModelFrom(void *handle, const char *fname) {
  API_PROLOG(-1);
  return method_s(handle, "copy_model_from", fname);
}
int CXNNetStartRound(void *handle, int round) {
  API_PROLOG(-1);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = PyObject_CallMethod(h->obj, "start_round", "i", round);
  if (r == nullptr) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

int CXNNetUpdateBatch(void *handle, const cxx_real_t *data,
                      const cxx_ulong *dshape, int dndim,
                      const cxx_real_t *label, const cxx_ulong *lshape,
                      int lndim) {
  API_PROLOG(-1);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *args = Py_BuildValue(
      "(ONONO)", h->obj,
      mem_ro(data, sizeof(cxx_real_t) * shape_elems(dshape, dndim)),
      shape_tuple(dshape, dndim),
      mem_ro(label, sizeof(cxx_real_t) * shape_elems(lshape, lndim)),
      shape_tuple(lshape, lndim));
  PyObject *r = call_helper("net_update_batch", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int CXNNetUpdateIter(void *handle, void *data_iter) {
  API_PROLOG(-1);
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_iter);
  PyObject *r = PyObject_CallMethod(h->obj, "update", "O", it->obj);
  if (r == nullptr) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

const cxx_real_t *CXNNetPredictBatch(void *handle, const cxx_real_t *data,
                                     const cxx_ulong *dshape, int dndim,
                                     cxx_ulong *out_shape, int *out_ndim) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *args = Py_BuildValue(
      "(ONO)", h->obj,
      mem_ro(data, sizeof(cxx_real_t) * shape_elems(dshape, dndim)),
      shape_tuple(dshape, dndim));
  return return_array(h, call_helper("net_predict", args), out_shape,
                      out_ndim);
}

const cxx_real_t *CXNNetPredictIter(void *handle, void *data_iter,
                                    cxx_ulong *out_shape, int *out_ndim) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_iter);
  PyObject *args = Py_BuildValue("(OO)", h->obj, it->obj);
  return return_array(h, call_helper("net_predict_iter", args), out_shape,
                      out_ndim);
}

const cxx_real_t *CXNNetExtractBatch(void *handle, const cxx_real_t *data,
                                     const cxx_ulong *dshape, int dndim,
                                     const char *node_name,
                                     cxx_ulong *out_shape, int *out_ndim) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *args = Py_BuildValue(
      "(ONOs)", h->obj,
      mem_ro(data, sizeof(cxx_real_t) * shape_elems(dshape, dndim)),
      shape_tuple(dshape, dndim), node_name);
  return return_array(h, call_helper("net_extract", args), out_shape,
                      out_ndim);
}

const cxx_real_t *CXNNetExtractIter(void *handle, void *data_iter,
                                    const char *node_name,
                                    cxx_ulong *out_shape, int *out_ndim) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_iter);
  PyObject *args = Py_BuildValue("(OOs)", h->obj, it->obj, node_name);
  return return_array(h, call_helper("net_extract_iter", args), out_shape,
                      out_ndim);
}

const char *CXNNetEvaluate(void *handle, void *data_iter, const char *name) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  Handle *it = static_cast<Handle *>(data_iter);
  PyObject *r =
      PyObject_CallMethod(h->obj, "evaluate", "Os", it->obj, name);
  if (r == nullptr) { set_error_from_python(); return nullptr; }
  const char *s = PyUnicode_AsUTF8(r);
  h->str_out = s ? s : "";
  Py_DECREF(r);
  return h->str_out.c_str();
}

const cxx_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *tag, cxx_ulong *out_shape,
                                  int *out_ndim) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *args = Py_BuildValue("(Oss)", h->obj, layer_name, tag);
  PyObject *r = call_helper("net_get_weight", args);
  if (r == nullptr) return nullptr;
  if (r == Py_None) { /* unknown weight: ndim 0, null ptr, no error */
    Py_DECREF(r);
    if (out_ndim) *out_ndim = 0;
    return nullptr;
  }
  return return_array(h, r, out_shape, out_ndim);
}

int CXNNetSetWeight(void *handle, const cxx_real_t *weight, cxx_ulong size,
                    const char *layer_name, const char *tag) {
  API_PROLOG(-1);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *args = Py_BuildValue(
      "(ONKss)", h->obj, mem_ro(weight, sizeof(cxx_real_t) * size),
      (unsigned long long)size, layer_name, tag);
  PyObject *r = call_helper("net_set_weight", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

/* ---- iterators ---- */

void *CXNIOCreateFromConfig(const char *cfg) {
  API_PROLOG(nullptr);
  PyObject *r = call_helper("io_create", Py_BuildValue("(s)", cfg));
  if (r == nullptr) return nullptr;
  Handle *h = new Handle();
  h->obj = r;
  return h;
}

void CXNIOFree(void *handle) { CXNNetFree(handle); }

int CXNIONext(void *handle) {
  API_PROLOG(-1);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *r = PyObject_CallMethod(h->obj, "next", nullptr);
  if (r == nullptr) { set_error_from_python(); return -1; }
  int v = PyObject_IsTrue(r);
  Py_DECREF(r);
  return v;
}

int CXNIOBeforeFirst(void *handle) {
  API_PROLOG(-1);
  return method0(handle, "before_first");
}

const cxx_real_t *CXNIOGetData(void *handle, cxx_ulong *out_shape,
                               int *out_ndim) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *args = Py_BuildValue("(O)", h->obj);
  return return_array(h, call_helper("io_get_data", args), out_shape,
                      out_ndim);
}

const cxx_real_t *CXNIOGetLabel(void *handle, cxx_ulong *out_shape,
                                int *out_ndim) {
  API_PROLOG(nullptr);
  Handle *h = static_cast<Handle *>(handle);
  PyObject *args = Py_BuildValue("(O)", h->obj);
  return return_array(h, call_helper("io_get_label", args), out_shape,
                      out_ndim);
}

/* ---- task driver ---- */

int CXNRunTask(int argc, const char **argv) {
  API_PROLOG(-1);
  PyObject *lst = PyList_New(argc);
  if (lst == nullptr) { set_error_from_python(); return -1; }
  for (int i = 0; i < argc; ++i) {
    /* DecodeFSDefault: argv may be arbitrary bytes (paths), not UTF-8 */
    PyObject *s = PyUnicode_DecodeFSDefault(argv[i]);
    if (s == nullptr) {
      set_error_from_python();
      Py_DECREF(lst);
      return -1;
    }
    PyList_SetItem(lst, i, s);  /* steals ref */
  }
  PyObject *args = Py_BuildValue("(O)", lst);
  Py_DECREF(lst);
  PyObject *r = call_helper("run_task", args);
  if (r == nullptr) return -1;
  long rc = PyLong_AsLong(r);
  Py_DECREF(r);
  if (rc == -1 && PyErr_Occurred()) {
    /* run_task returned a non-integer: record and clear the conversion
       error so no stale exception state leaks into the next API call */
    set_error_from_python();
    return -1;
  }
  return static_cast<int>(rc);
}

void CXNShutdown(void) {
  if (g_shutdown || !Py_IsInitialized()) return;
  {
    Gil gil_;
    PyRun_SimpleString(
        "import sys; sys.stdout.flush(); sys.stderr.flush()");
    Py_XDECREF(g_helpers);
  }
  g_helpers = nullptr;  /* would dangle across an interpreter cycle */
  if (g_we_initialized) {
    /* re-acquire the thread state released in ensure_init, then tear down */
    PyGILState_Ensure();
    Py_FinalizeEx();
    g_we_initialized = false;
  }
  /* one-way: every later CXN* call fails cleanly via API_PROLOG */
  g_shutdown = true;
}

}  /* extern "C" */
