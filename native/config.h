// Key=value config parser, C++ side.
//
// Parity with the reference's ConfigReaderBase (src/utils/config.h:20-189):
// "name = value" lines, '#' comments, double-quoted values (quotes
// stripped), later pairs win when queried via last().  The same config text
// that drives the Python side drives the native loader, preserving the
// reference's single-config-language design (SURVEY.md §5.6).
#ifndef CXXNET_NATIVE_CONFIG_H_
#define CXXNET_NATIVE_CONFIG_H_

#include <cstdlib>
#include <string>
#include <utility>
#include <vector>

namespace cxn {

class Config {
 public:
  // parse "k = v" lines from text; returns false + sets err on bad syntax
  bool Parse(const std::string& text, std::string* err) {
    size_t pos = 0;
    int lineno = 0;
    while (pos < text.size()) {
      size_t eol = text.find('\n', pos);
      if (eol == std::string::npos) eol = text.size();
      std::string line = text.substr(pos, eol - pos);
      pos = eol + 1;
      ++lineno;
      size_t hash = line.find('#');
      if (hash != std::string::npos) line = line.substr(0, hash);
      line = Trim(line);
      if (line.empty()) continue;
      size_t eq = line.find('=');
      if (eq == std::string::npos) {
        *err = "config line " + std::to_string(lineno) + ": missing '='";
        return false;
      }
      std::string k = Trim(line.substr(0, eq));
      std::string v = Trim(line.substr(eq + 1));
      if (v.size() >= 2 && v.front() == '"' && v.back() == '"')
        v = v.substr(1, v.size() - 2);
      if (k.empty()) {
        *err = "config line " + std::to_string(lineno) + ": empty key";
        return false;
      }
      pairs_.emplace_back(k, v);
    }
    return true;
  }

  // last value for key, or fallback
  std::string Get(const std::string& key, const std::string& dflt = "") const {
    for (auto it = pairs_.rbegin(); it != pairs_.rend(); ++it)
      if (it->first == key) return it->second;
    return dflt;
  }
  // non-throwing: a malformed number keeps the default (callers validate
  // required keys separately; nothing here may throw across the C ABI)
  long GetInt(const std::string& key, long dflt) const {
    std::string v = Get(key);
    if (v.empty()) return dflt;
    char* end = nullptr;
    long r = std::strtol(v.c_str(), &end, 10);
    return (end && end != v.c_str()) ? r : dflt;
  }
  double GetFloat(const std::string& key, double dflt) const {
    std::string v = Get(key);
    if (v.empty()) return dflt;
    char* end = nullptr;
    double r = std::strtod(v.c_str(), &end);
    return (end && end != v.c_str()) ? r : dflt;
  }
  bool Has(const std::string& key) const { return !Get(key).empty(); }

  const std::vector<std::pair<std::string, std::string>>& pairs() const {
    return pairs_;
  }

 private:
  static std::string Trim(const std::string& s) {
    size_t a = s.find_first_not_of(" \t\r");
    if (a == std::string::npos) return "";
    size_t b = s.find_last_not_of(" \t\r");
    return s.substr(a, b - a + 1);
  }
  std::vector<std::pair<std::string, std::string>> pairs_;
};

}  // namespace cxn
#endif  // CXXNET_NATIVE_CONFIG_H_
