/*
 * Standalone trainer binary: `cxxnet <config.conf> [key=value ...]` — the
 * reference's single-binary UX (src/cxxnet_main.cpp, bin/cxxnet) over the
 * C ABI (embedded CPython running the cxxnet_tpu task driver).
 */
#include <cstdio>

#include "capi.h"

int main(int argc, char **argv) {
  if (argc < 2) {
    std::fprintf(stderr, "Usage: %s <config.conf> [key=value ...]\n",
                 argv[0]);
    return 1;
  }
  int rc = CXNRunTask(argc - 1, const_cast<const char **>(argv + 1));
  if (rc != 0) {
    const char *err = CXNGetLastError();
    if (err != nullptr && err[0] != '\0')
      std::fprintf(stderr, "cxxnet: %s\n", err);
  }
  CXNShutdown();  /* flush python-buffered stdout before C exit */
  return rc;
}
