"""SPMD deep lint (analysis/spmdlint.py), ISSUE 14 tentpole.

Negative fixtures: tiny synthetic nets/configs that each trip exactly
one spmdlint finding class — divergent-branch collective, dead-axis
psum, undonated opt leaf, bf16 deep accumulation (downcast-fed), and an
f32 wire despite a declared bf16 reduce dtype — asserted by finding id
through the real ``task=check`` CLI (exit 1 for the error classes).
Golden runs: every shipped example config must pass the full traced
check (config lint + jaxpr lint + SPMD lint) with zero error findings,
and the donation audit's alias map must agree with the compiled step's
``memory_analysis()`` alias bytes on the CPU MNIST e2e.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from cxxnet_tpu import engine
from cxxnet_tpu.analysis import registry as areg
from cxxnet_tpu.analysis import run_check, spmdlint
from cxxnet_tpu.analysis.jaxpr_lint import trace_step
from cxxnet_tpu.layers import registry as layer_registry
from cxxnet_tpu.layers.base import Layer
from cxxnet_tpu.nnet.trainer import NetTrainer, _lowered_arg_aliases
from cxxnet_tpu.parallel import mesh as meshlib
from cxxnet_tpu.updater import updaters as updlib
from cxxnet_tpu.utils.config import parse_config_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "example", "*", "*.conf")))

#: golden configs the tier-1 run traces end to end (GoogLeNet rides the
#: slow marker below; tools/lint.sh covers it on every gate run)
GOLDEN = [os.path.join(REPO, p) for p in (
    "example/MNIST/MNIST.conf", "example/MNIST/mesh.conf",
    "example/MNIST/serve.conf", "example/LM/longctx.conf",
    "example/LM/moe_lm.conf")]


@pytest.fixture(autouse=True)
def _restore_global_knobs():
    snap = engine.snapshot()
    yield
    for k, v in snap.items():
        setattr(engine.opts, k, v)


def errors(findings):
    return [f for f in findings if f.severity == "error"]


def spmd_error_ids(findings):
    return {f.key for f in findings
            if f.scope == "spmd" and f.severity == "error"}


# ------------------------------------------------------------ unit level

def _two_dev_mesh():
    devs = jax.devices("cpu")[:2]
    return meshlib.build_mesh(devs, meshlib.MeshSpec({"data": 2}))


def test_mesh_axis_sizes():
    devs = jax.devices("cpu")[:4]
    mesh = meshlib.build_mesh(
        devs, meshlib.MeshSpec({"data": 2, "model": 2}))
    assert meshlib.mesh_axis_sizes(mesh) == {"data": 2, "model": 2}


def test_collective_walk_extracts_ordered_sequence():
    mesh = _two_dev_mesh()

    def body(x):
        y = lax.psum(x, "data")
        y = lax.all_gather(y, "data", axis=0, tiled=True)
        return lax.ppermute(y, "data", [(0, 1), (1, 0)])

    f = shard_map(body, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"), check_rep=False)
    closed = jax.make_jaxpr(f)(jnp.zeros((8, 4), jnp.float32))
    ops, findings = [], []
    spmdlint.collective_walk(closed.jaxpr, ops, findings)
    assert [op.prim for op in ops] == ["psum", "all_gather", "ppermute"]
    assert all(op.axes == ("data",) for op in ops)
    assert not findings


def test_divergent_cond_branches_error():
    mesh = _two_dev_mesh()

    def body(x):
        return lax.cond(x.sum() > 0,
                        lambda v: lax.psum(v, "data"),
                        lambda v: v * 2.0, x)

    f = shard_map(body, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"), check_rep=False)
    closed = jax.make_jaxpr(f)(jnp.zeros((8, 4), jnp.float32))
    ops, findings = [], []
    spmdlint.collective_walk(closed.jaxpr, ops, findings)
    assert [f.key for f in findings] == ["spmd_divergent_cond"]
    assert findings[0].severity == "error"
    # the representative sequence still carries the branch's psum
    assert [op.prim for op in ops] == ["psum"]


def test_matching_cond_branches_stay_quiet():
    mesh = _two_dev_mesh()

    def body(x):
        return lax.cond(x.sum() > 0,
                        lambda v: lax.psum(v, "data"),
                        lambda v: lax.psum(v * 2.0, "data"), x)

    f = shard_map(body, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"), check_rep=False)
    closed = jax.make_jaxpr(f)(jnp.zeros((8, 4), jnp.float32))
    ops, findings = [], []
    spmdlint.collective_walk(closed.jaxpr, ops, findings)
    assert not findings
    assert [op.prim for op in ops] == ["psum"]


def test_axis_findings_dead_and_unknown():
    op = spmdlint.CollectiveOp("psum", ("model",), "float32", (4,), 16)
    dead = spmdlint.axis_findings([op], {"data": 2, "model": 1})
    assert [f.key for f in dead] == ["spmd_dead_axis"]
    unknown = spmdlint.axis_findings([op], {"data": 2})
    assert [f.key for f in unknown] == ["spmd_unknown_axis"]
    ok = spmdlint.axis_findings([op], {"data": 2, "model": 2})
    assert not ok


def test_dtype_flow_cast_roundtrip():
    def fn(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    closed = jax.make_jaxpr(fn)(jnp.zeros((4,), jnp.float32))
    findings = spmdlint.dtype_flow_findings(closed)
    assert "spmd_cast_roundtrip" in {f.key for f in findings}


def test_dtype_flow_bf16_deep_reduce_severities():
    # jnp.sum upcasts half-precision accumulators to f32 on its own —
    # the lint targets the LAX-level reduce_sums autodiff transposes
    # emit (bias grads), which carry no such protection
    def downcast(x):
        # downcast-then-accumulate: statically certain bug = error
        return lax.reduce_sum_p.bind(x.astype(jnp.bfloat16), axes=(0,))

    closed = jax.make_jaxpr(downcast)(jnp.zeros((8192,), jnp.float32))
    sev = {f.key: f.severity
           for f in spmdlint.dtype_flow_findings(closed)}
    assert sev.get("spmd_bf16_acc") == "error"

    # native bf16 reduce (bias grads in bf16 nets do this) = warn
    def native(x):
        return lax.reduce_sum_p.bind(x, axes=(0,))

    closed = jax.make_jaxpr(native)(jnp.zeros((8192,), jnp.bfloat16))
    sev = {f.key: f.severity
           for f in spmdlint.dtype_flow_findings(closed)}
    assert sev.get("spmd_bf16_acc") == "warn"

    # shallow reduces stay quiet
    closed = jax.make_jaxpr(native)(jnp.zeros((64,), jnp.bfloat16))
    assert not spmdlint.dtype_flow_findings(closed)


def test_wire_findings_only_fire_on_declared_bf16():
    big = spmdlint.CollectiveOp("psum", ("data",), "float32",
                                (1 << 16,), 1 << 18)
    small = spmdlint.CollectiveOp("psum", ("data",), "float32", (4,), 16)
    assert not spmdlint.wire_findings([big], wire_bf16=False)
    assert not spmdlint.wire_findings([small], wire_bf16=True)
    hits = spmdlint.wire_findings([big], wire_bf16=True)
    assert [f.key for f in hits] == ["spmd_f32_wire"]
    assert hits[0].severity == "error"


def test_dist_round_findings_warn_on_sharded_iterator():
    op = spmdlint.CollectiveOp("psum", ("data",), "float32", (4,), 16)
    cfg = [("dist_num_worker", "4"), ("eta", "0.1")]
    hits = spmdlint.dist_round_findings(cfg, [op])
    assert [f.key for f in hits] == ["spmd_dist_round_len"]
    assert hits[0].severity == "warn"
    assert "LOCAL iterator" in hits[0].message
    # did-you-mean points at the empty-rank assert contract
    assert "zero data" in hits[0].suggestion
    # quiet cases: unsharded, collective-free step, unparsable value
    assert not spmdlint.dist_round_findings([("dist_num_worker", "1")],
                                            [op])
    assert not spmdlint.dist_round_findings(cfg, [])
    assert not spmdlint.dist_round_findings([("dist_num_worker", "x")],
                                            [op])
    assert not spmdlint.dist_round_findings([("eta", "0.1")], [op])


def test_donation_findings_classes():
    rows = [
        {"tree": "params", "path": "['fc']['wmat']", "bytes": 1 << 20,
         "donated": False},
        {"tree": "opt_state", "path": "['fc']['m']", "bytes": 1 << 20,
         "donated": True},
    ]
    report = {"source": "lowered", "n_args": 4, "leaves": rows,
              "alias_bytes": 1 << 20}
    fs = spmdlint.donation_findings(report)
    assert {f.key for f in fs} == {"spmd_undonated", "spmd_donation"}
    und = [f for f in fs if f.key == "spmd_undonated"]
    assert und[0].severity == "error" and "wmat" in und[0].message
    skipped = spmdlint.donation_findings(None)
    assert skipped[0].key == "spmd_donation" \
        and skipped[0].severity == "info"


def test_lowered_arg_alias_parser():
    txt = ('module @jit_step {\n  func.func public @main('
           '%arg0: tensor<4x4xf32> {tf.aliasing_output = 0 : i32}, '
           '%arg1: tensor<4x4xf32> {mhlo.sharding = "{replicated}"}, '
           '%arg2: tensor<8xf32>) -> (tensor<4x4xf32>) {\n')
    donated, n = _lowered_arg_aliases(txt)
    assert donated == {0} and n == 3
    assert _lowered_arg_aliases("no main here") == (set(), -1)


# ---------------------------------------------------- negative fixtures
#
# Each fixture layer/updater is registered in-process, a tiny conf is
# written to tmp_path, and the REAL CLI (LearnTask.run, task=check) must
# exit 1 with exactly the expected spmd error id in the check record.

class _DivergentCondLayer(Layer):
    """cond branches with mismatched collective sequences."""

    type_names = ("divcond_test",)

    def infer_shapes(self, in_shapes):
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        x = inputs[0]
        if ctx.mesh is None or "data" not in ctx.mesh.axis_names:
            return [x], buffers

        def body(v):
            return lax.cond(v.sum() > 0,
                            lambda u: lax.psum(u, "data"),
                            lambda u: u * 2.0, v)

        f = shard_map(body, mesh=ctx.mesh,
                      in_specs=P("data"), out_specs=P("data"),
                      check_rep=False)
        return [f(x)], buffers


class _DeadAxisLayer(Layer):
    """psum over a size-1 mesh axis."""

    type_names = ("deadaxis_test",)

    def infer_shapes(self, in_shapes):
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        x = inputs[0]
        if ctx.mesh is None or "model" not in ctx.mesh.axis_names:
            return [x], buffers
        f = shard_map(lambda v: v + lax.psum(v, "model") * 0.0,
                      mesh=ctx.mesh, in_specs=P("data"),
                      out_specs=P("data"), check_rep=False)
        return [f(x)], buffers


class _F32WireLayer(Layer):
    """big f32 psum on the data axis (vs a declared bf16 wire)."""

    type_names = ("f32wire_test",)

    def infer_shapes(self, in_shapes):
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        x = inputs[0]
        if ctx.mesh is None or "data" not in ctx.mesh.axis_names:
            return [x], buffers
        f = shard_map(lambda v: lax.psum(v, "data"),
                      mesh=ctx.mesh, in_specs=P("data"),
                      out_specs=P(), check_rep=False)
        return [x + f(x).mean() * 0.0], buffers


class _Bf16AccLayer(Layer):
    """deliberate f32 -> bf16 downcast feeding a deep accumulation."""

    type_names = ("bf16acc_test",)

    def infer_shapes(self, in_shapes):
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        x = inputs[0]
        # the lax-level bind is what an autodiff bias-grad transpose
        # emits (jnp.sum would auto-upcast the accumulator)
        s = lax.reduce_sum_p.bind(x.astype(jnp.bfloat16),
                                  axes=(0, 1, 2, 3))
        return [x + s.astype(jnp.float32) * 0.0], buffers


class _BadOptUpdater(updlib.SGDUpdater):
    """Momentum state comes back bf16 against an f32 input leaf: the
    aval mismatch silently voids that leaf's donation — the bug class
    the audit exists for."""

    name = "badopt"

    def _apply32(self, p, g, state, hyper, epoch):
        q, new_state = super()._apply32(p, g, state, hyper, epoch)
        return q, {"m": new_state["m"].astype(jnp.bfloat16)}


@pytest.fixture
def _fixture_registry():
    for cls in (_DivergentCondLayer, _DeadAxisLayer, _F32WireLayer,
                _Bf16AccLayer):
        layer_registry.register(cls)
    updlib._UPDATERS["badopt"] = _BadOptUpdater()
    areg.global_scope.cache_clear()
    areg.layer_scope.cache_clear()
    yield
    for cls in (_DivergentCondLayer, _DeadAxisLayer, _F32WireLayer,
                _Bf16AccLayer):
        for name in cls.type_names:
            layer_registry._REGISTRY.pop(name, None)
    updlib._UPDATERS.pop("badopt", None)
    areg.global_scope.cache_clear()
    areg.layer_scope.cache_clear()


def _run_check_cli(tmp_path, conf_text, name="fixture.conf"):
    """Write a conf, run the real task=check CLI in-process, return
    (exit code, findings list from the JSONL check record)."""
    from cxxnet_tpu.main import LearnTask
    conf = tmp_path / name
    conf.write_text(conf_text)
    sink = tmp_path / f"{name}.jsonl"
    rc = LearnTask().run([str(conf), "task=check", "silent=1",
                          f"metrics_sink=jsonl:{sink}"])
    recs = [json.loads(l) for l in sink.read_text().splitlines()]
    checks = [r for r in recs if r["kind"] == "check"]
    assert len(checks) == 1
    return rc, checks[0]["findings"]


def _finding_ids(findings, severity=None):
    return {f["key"] for f in findings
            if f.get("scope") == "spmd"
            and (severity is None or f["severity"] == severity)}


_BODY = ("layer[+1] = fullc\n  nhidden = 4\n"
         "layer[+0] = softmax\nnetconfig=end\n")


def test_fixture_divergent_cond(tmp_path, _fixture_registry):
    rc, findings = _run_check_cli(tmp_path, (
        "netconfig=start\nlayer[+1] = divcond_test\n" + _BODY +
        "input_shape = 1,1,8\nbatch_size = 8\n"
        "dev = cpu:0-1\nmesh = data:2\n"))
    assert rc == 1
    assert _finding_ids(findings, "error") == {"spmd_divergent_cond"}


def test_fixture_dead_axis_psum(tmp_path, _fixture_registry):
    rc, findings = _run_check_cli(tmp_path, (
        "netconfig=start\nlayer[+1] = deadaxis_test\n" + _BODY +
        "input_shape = 1,1,8\nbatch_size = 8\n"
        "dev = cpu:0-1\nmesh = data:2,model:1\n"))
    assert rc == 1
    assert _finding_ids(findings, "error") == {"spmd_dead_axis"}


def test_fixture_undonated_opt_leaf(tmp_path, _fixture_registry):
    rc, findings = _run_check_cli(tmp_path, (
        "netconfig=start\n" + _BODY +
        "updater = badopt\n"
        "input_shape = 1,1,8\nbatch_size = 8\ndev = cpu\n"))
    assert rc == 1
    assert _finding_ids(findings, "error") == {"spmd_undonated"}
    und = [f for f in findings if f["key"] == "spmd_undonated"]
    assert "opt_state" in und[0]["message"]


def test_fixture_bf16_deep_accumulation(tmp_path, _fixture_registry):
    rc, findings = _run_check_cli(tmp_path, (
        "netconfig=start\nlayer[+1] = bf16acc_test\n" + _BODY +
        "input_shape = 1,1,8192\nbatch_size = 8\ndev = cpu\n"))
    assert rc == 1
    assert _finding_ids(findings, "error") == {"spmd_bf16_acc"}


def test_fixture_f32_wire_despite_bf16_config(tmp_path,
                                              _fixture_registry):
    rc, findings = _run_check_cli(tmp_path, (
        "netconfig=start\nlayer[+1] = f32wire_test\n" + _BODY +
        "input_shape = 1,1,8192\nbatch_size = 8\n"
        "dev = cpu:0-1\nmesh = data:2\ndp_reduce_dtype = bf16\n"))
    assert rc == 1
    assert _finding_ids(findings, "error") == {"spmd_f32_wire"}


def test_spmd_check_key_disables_the_pass(tmp_path, _fixture_registry):
    rc, findings = _run_check_cli(tmp_path, (
        "netconfig=start\nlayer[+1] = divcond_test\n" + _BODY +
        "input_shape = 1,1,8\nbatch_size = 8\n"
        "dev = cpu:0-1\nmesh = data:2\nspmd_check = 0\n"))
    assert rc == 0
    assert not _finding_ids(findings)


# ---------------------------------------------------------- golden runs

@pytest.mark.parametrize("conf", GOLDEN,
                         ids=[os.path.basename(c) for c in GOLDEN])
def test_golden_examples_spmd_clean(conf):
    """Every shipped config passes the FULL traced check — config lint,
    jaxpr lint, memory pre-flight, and the SPMD deep lint — with zero
    error-severity findings."""
    findings, code = run_check(parse_config_file(conf), path=conf,
                               trace=True, spmd=True)
    assert code == 0, "\n".join(f.format() for f in findings)
    assert not errors(findings)


@pytest.mark.slow
def test_golden_googlenet_spmd_clean():
    conf = os.path.join(REPO, "example/ImageNet/GoogLeNet.conf")
    findings, code = run_check(parse_config_file(conf), path=conf,
                               trace=True, spmd=True)
    assert code == 0, "\n".join(f.format() for f in findings)


def test_mesh_conf_census_sees_overlap_collectives():
    """mesh.conf (dp_overlap on a data x model mesh) must show explicit
    psums on data and all_gathers on model in the census info."""
    findings, code = run_check(
        parse_config_file(os.path.join(REPO, "example/MNIST/mesh.conf")),
        trace=True, spmd=True)
    assert code == 0
    census = [f for f in findings if f.key == "spmd_collectives"]
    assert census and "psum" in census[0].message \
        and "all_gather" in census[0].message


# ------------------------------------------------- donation audit (e2e)

def _mnist_trainer():
    net = NetTrainer()
    for k, v in parse_config_file(
            os.path.join(REPO, "example/MNIST/MNIST.conf")):
        net.set_param(k, v)
    net.set_param("dev", "cpu")
    net.set_param("silent", "1")
    net.init_model()
    return net


def test_donation_report_agrees_with_memory_stats_mnist():
    """Acceptance: the audit's alias map vs the compiled step's
    measured alias bytes on the CPU MNIST e2e — byte-identical, from
    the same cached AOT compile."""
    net = _mnist_trainer()
    stats = net.step_memory_stats()
    report = net.step_donation_report()
    assert report is not None and report["source"] == "hlo"
    assert all(r["donated"] for r in report["leaves"]), report["leaves"]
    if stats is not None and stats.get("alias_bytes"):
        assert report["alias_bytes"] == stats["alias_bytes"]


def test_donation_report_lowered_path_matches_hlo_path():
    """Without the cached compile the audit parses the lowered module —
    same donation decisions, no XLA compile."""
    net = _mnist_trainer()
    lowered = net.step_donation_report()  # no compile yet -> lowered
    assert lowered is not None and lowered["source"] == "lowered"
    net.step_hlo_text()  # pay the compile; audit switches to the header
    hlo = net.step_donation_report()
    assert hlo["source"] == "hlo"
    assert [r["donated"] for r in lowered["leaves"]] \
        == [r["donated"] for r in hlo["leaves"]]
    assert lowered["alias_bytes"] == hlo["alias_bytes"]


# --------------------------------------------------------- CLI plumbing

def test_run_check_no_trace_warns_about_spmd():
    pairs = parse_config_file(os.path.join(REPO,
                                           "example/MNIST/MNIST.conf"))
    findings, code = run_check(pairs, trace=False, spmd=True)
    assert code == 0
    assert any(f.key == "spmd_check" and "traced-graph" in f.message
               for f in findings)


def test_run_check_spmd_emits_summary_infos():
    pairs = parse_config_file(os.path.join(REPO,
                                           "example/MNIST/MNIST.conf"))
    findings, code = run_check(pairs, trace=True)  # default: spmd on
    assert code == 0
    keys = {f.key for f in findings if f.scope == "spmd"}
    assert {"spmd_collectives", "spmd_donation"} <= keys
    quiet, code = run_check(pairs, trace=True, spmd=False)
    assert code == 0
    assert not any(f.scope == "spmd" for f in quiet)
