"""Sanity guards for bench.py: the driver runs it unattended at round end,
so import errors or broken FLOP accounting must be caught in CI."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def test_bench_imports_and_flop_count():
    import bench
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    t = _make_trainer(ALEXNET_NET, 2, "cpu")
    fwd = bench.conv_flops_per_image(t.net)
    # AlexNet forward is ~1.4-1.5 GFLOP/image (the well-known figure)
    assert 1.2e9 < fwd < 1.7e9, fwd


def test_bench_io_ab_mode():
    """--io-ab payload: batches/sec with prefetch on vs off plus the
    h2d / iter-wait accounting, on the CPU backend."""
    import bench
    payload = bench.bench_io_ab(
        ["dev=cpu", "batch_size=32", "n_inst=256", "num_round=2"])
    assert payload["metric"] == "io_ab_batches_per_sec"
    assert payload["value"] == payload["batches_per_sec_on"] > 0
    assert payload["batches_per_sec_off"] > 0
    assert payload["vs_prefetch_off"] > 0
    for tag in ("on", "off"):
        assert payload[f"h2d_sec_{tag}"] >= 0
        assert 0 <= payload[f"iter_wait_share_{tag}"] <= 1.5
        assert payload[f"dispatch_share_{tag}"] >= 0


def test_bench_baseline_json_shape():
    """The driver parses one JSON object with these exact keys."""
    import json

    import bench
    payload = json.loads(json.dumps(bench.baseline_json(1234.56)))
    assert set(payload) == {"metric", "value", "unit", "vs_baseline"}
    assert payload["metric"] == "alexnet_imgs_per_sec_per_chip"
    assert payload["value"] == 1234.6
    assert payload["vs_baseline"] == round(1234.56 / 1000.0, 3)


def test_bench_mesh_scaling_mode():
    """--mesh-scaling payload on the CPU mesh: named-mesh points with
    per-chip throughput, efficiency vs the first mesh, and the per-axis
    comm-share fields (zero-valued but PRESENT on CPU traces)."""
    import bench
    payload = bench.bench_mesh_scaling(
        ["dev=cpu", "tiny=1", "meshes=data:1;data:2,model:2",
         "models=alexnet"])
    assert payload["metric"] == "mesh_scaling_examples_per_sec_per_chip"
    assert payload["value"] > 0
    assert payload["meshes"] == ["data:1", "data:2,model:2"]
    assert payload["efficiency_baseline_mesh"] == "data:1"
    assert "comm_share_per_axis" in payload
    pts = payload["models"]["alexnet"]["points"]
    assert [p["mesh"] for p in pts] == ["data:1", "data:2,model:2"]
    assert pts[1]["devices"] == 4
    for row in pts:
        for tag in ("overlap_on", "overlap_off"):
            p = row[tag]
            assert p["examples_per_sec_per_chip"] > 0
            assert p["scaling_efficiency"] > 0
            assert 0.0 <= p["comm_share"] <= 1.0
            assert isinstance(p["comm_share_per_axis"], dict)
    assert pts[0]["overlap_on"]["scaling_efficiency"] == 1.0
    # engine options restored (process-global hygiene)
    from cxxnet_tpu.engine import opts
    assert opts.dp_overlap == "0"


def test_bench_mesh_scaling_pipe_line():
    """--mesh-scaling on a pipe mesh: the point runs the 1F1B schedule
    and grows the bubble columns — measured share from the two-point
    microbatch probe, analytic (S-1)/(M+S-1), and the microbatch count
    — plus the pipe row in the payload summary.  Measured magnitude is
    not asserted (CPU timing noise at tiny scale); presence + analytic
    value are."""
    import bench
    payload = bench.bench_mesh_scaling(
        ["dev=cpu", "tiny=1", "meshes=data:2,pipe:2", "models=alexnet"])
    pts = payload["models"]["alexnet"]["points"]
    assert [p["mesh"] for p in pts] == ["data:2,pipe:2"]
    for tag in ("overlap_on", "overlap_off"):
        p = pts[0][tag]
        assert p["pipe_microbatch"] == 4  # 2x the pipe axis
        assert p["pipe_bubble_share_analytic"] == round(1 / 5, 4)
        assert p["pipe_bubble_share_measured"] >= 0.0
        assert p["pipe_bubble_probe"] in (
            "wall-two-point", "serialized-excess-work")
        assert isinstance(p["comm_share_per_axis"], dict)
    assert payload["pipe_bubble"]["mesh"] == "data:2,pipe:2"
    assert payload["pipe_bubble"]["analytic"] == round(1 / 5, 4)
    assert payload["pipe_bubble"]["probe"] in (
        "wall-two-point", "serialized-excess-work")
    from cxxnet_tpu.engine import opts
    assert opts.dp_overlap == "0"


def test_bench_opt_ab_mode():
    """--opt-ab payload on CPU (tiny): one entry per arm with step_ms
    and the arm's engine options, plus base-relative speedups; engine
    options restored afterwards."""
    import bench
    payload = bench.bench_opt_ab(
        ["dev=cpu", "tiny=1", "arms=base,ln_x"])
    assert payload["metric"] == "opt_ab_step_ms"
    assert payload["value"] > 0
    assert set(payload["arms"]) == {"base", "ln_x"}
    for arm, entry in payload["arms"].items():
        assert entry["step_ms"] > 0
        assert entry["opts"] == dict(bench.OPT_AB_ARMS[arm])
    assert payload["speedup_ln_x"] > 0
    from cxxnet_tpu.engine import opts
    assert opts.fused_update == "0" and opts.pallas_ln == "1"


def test_bench_serve_mode():
    """--serve --tiny payload: one offered-QPS point over the serving
    subsystem with latency percentiles, the coalescer's batch-size
    histogram, the per-stage p99 decomposition (trace_sample), and the
    zero-retrace-after-warmup guarantee."""
    import bench
    payload = bench.bench_serve(
        ["--tiny", "dev=cpu", "offered_qps=200", "duration=0.4",
         "clients=4", "trace_sample=1"])
    assert payload["metric"] == "serve_p95_ms"
    assert payload["retraces"] == 0
    assert payload["warmup_sec"] > 0
    assert payload["shapes"] == [1, 8]
    [pt] = payload["points"]
    assert pt["offered_qps"] == 200.0
    assert pt["requests"] > 0 and pt["achieved_qps"] > 0
    assert 0 < pt["p50_ms"] <= pt["p95_ms"] <= pt["p99_ms"]
    assert pt["mean_batch"] >= 1.0
    assert sum(int(k) * v for k, v in pt["batch_hist"].items()) \
        == pt["requests"]
    assert payload["value"] == pt["p95_ms"]
    # the per-stage request-path decomposition rode along: every
    # traced request contributes to every top-level stage, and
    # pad/device/unpad re-decompose dispatch (doc/monitor.md)
    assert pt["traced_requests"] == pt["requests"]
    stages = {s["stage"]: s for s in pt["stages"]}
    for name in ("queue_wait", "coalesce", "dispatch", "pad", "device",
                 "unpad", "respond"):
        assert stages[name]["count"] == pt["requests"], name
        assert stages[name]["p50_ms"] <= stages[name]["p99_ms"]
    top_share = sum(stages[n]["share"] for n in
                    ("queue_wait", "coalesce", "dispatch", "respond"))
    assert 0.9 < top_share < 1.1  # the four stages tile a request
    # thread hygiene: the bench closed its batcher
    import threading
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-serve")]


def test_bench_lm_mode():
    """--lm --tiny payload: tokens/sec + packing efficiency + per-axis
    comm-share fields for both LM flagships on the CPU mesh (shares are
    zero-valued but PRESENT on CPU traces, like --dp-scaling)."""
    import bench
    payload = bench.bench_lm(["--tiny", "dev=cpu", "steps=2",
                              "models=longctx"])
    assert payload["metric"] == "lm_tokens_per_sec"
    assert payload["value"] > 0
    assert payload["packing_efficiency"] >= 0.9
    assert isinstance(payload["comm_share_per_axis"], dict)
    pt = payload["models"]["longctx"]
    assert pt["mesh"] == "data:2,seq:2"
    assert pt["tokens_per_sec"] > 0
    assert pt["tokens_per_sec_per_chip"] > 0
    # the stream-chop packer wastes nothing; the whole-doc packer's
    # number on the same corpus is the comparison baseline
    assert pt["packing_efficiency"] == 1.0
    assert 0 < pt["packing_efficiency_nosplit"] <= 1.0
    assert np.isfinite(pt["loss"])
    assert 0.0 <= pt["comm_share"] <= 1.0


def test_comm_axis_shares_mapping():
    """Per-axis attribution table: data reductions vs model gathers."""
    import bench
    rep = {"device_sec": 2.0,
           "comm_by_kind": {"all-reduce": 200.0, "reduce-scatter": 100.0,
                            "all-gather": 400.0}}
    shares = bench._comm_axis_shares(rep)
    assert shares == {"data": 0.15, "model": 0.2}
    assert bench._comm_axis_shares(
        {"device_sec": 0.0, "comm_by_kind": {"all-reduce": 1.0}}) \
        == {"data": 0.0}


def test_bench_dp_scaling_mode():
    """--dp-scaling payload on the CPU mesh: per-device-count per-chip
    throughput, scaling efficiency vs the 1-device point, and
    comm/compute shares, overlap on vs off."""
    import bench
    payload = bench.bench_dp_scaling(
        ["dev=cpu", "tiny=1", "devices=1,2", "models=alexnet"])
    assert payload["metric"] == "dp_scaling_examples_per_sec_per_chip"
    assert payload["value"] > 0
    assert payload["devices"] == [1, 2]
    pts = payload["models"]["alexnet"]["points"]
    assert [p["devices"] for p in pts] == [1, 2]
    for row in pts:
        for tag in ("overlap_on", "overlap_off"):
            p = row[tag]
            assert p["examples_per_sec_per_chip"] > 0
            assert p["scaling_efficiency"] > 0
            assert 0.0 <= p["comm_share"] <= 1.0
            assert 0.0 <= p["compute_share"] <= 1.0
            assert 0.0 <= p["overlap_frac"] <= 1.0
    # the 1-device point anchors efficiency at exactly 1.0
    assert payload["efficiency_baseline_devices"] == 1
    assert pts[0]["overlap_on"]["scaling_efficiency"] == 1.0
    assert pts[0]["overlap_off"]["scaling_efficiency"] == 1.0
    # engine options restored (process-global hygiene)
    from cxxnet_tpu.engine import opts
    assert opts.dp_overlap == "0"


def test_bench_lm_serve_mode():
    """--lm-serve --tiny payload: aggregate tokens/sec + per-token
    percentiles + occupancy histogram per offered-load point, the
    continuous-vs-request A/B with its speedup field, and the
    zero-retrace contract across the whole sweep."""
    import bench
    payload = bench.bench_lm_serve(["--tiny", "dev=cpu"])
    assert payload["metric"] == "lm_serve_tokens_per_sec"
    assert payload["value"] > 0
    assert payload["retraces"] == 0
    assert payload["kv_cache_bytes"] > 0
    assert payload["warmup_sec"] > 0
    [pt] = payload["points"]
    assert pt["clients"] == 2
    assert pt["tokens_per_sec"] > 0
    assert pt["requests"] == 6 and pt["tokens"] > 0
    assert 0 < pt["tok_p50_ms"] <= pt["tok_p95_ms"] <= pt["tok_p99_ms"]
    assert sum(pt["occupancy_hist"].values()) == pt["steps"]
    assert pt["batching"] == "continuous"
    ab = payload["ab"]
    assert ab["continuous"]["batching"] == "continuous"
    assert ab["request"]["batching"] == "request"
    # same work either way; only the admission policy differs
    assert ab["continuous"]["tokens"] == ab["request"]["tokens"]
    assert payload["speedup_continuous"] > 0
    # thread hygiene: every scheduler closed
    import threading
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-decode")]
