"""monitor/threadcheck.py: the lock-witness sanitizer + interleaving
harness (dynamic half of racelint — doc/lint.md).

Three layers:

* **witness units**: ``checked()`` subclasses of the real telemetry
  classes (Histogram, SentinelBank, FlightCapture, JsonlSink) raise
  :class:`LockWitnessError` on an unlocked touch of a guarded-by
  attribute and stay silent on the disciplined paths.
* **negative fixture**: a pre-fix copy of the unlocked
  ``Histogram.observe`` read-modify-write, driven by
  :func:`run_interleaved` to the exact schedule that loses an update —
  the bug class is *demonstrated*, not assumed.
* **post-fix stress**: the shipped classes under :func:`stress`
  (barrier + aggressive switch interval) keep exact counts and emit
  untorn JSONL — the regression tests for the races racelint surfaced.
"""

import json
import threading

import pytest

from cxxnet_tpu.monitor import threadcheck
from cxxnet_tpu.monitor.metrics import (Histogram, JsonlSink,
                                        MetricsRegistry)
from cxxnet_tpu.monitor.sentinel import SentinelBank
from cxxnet_tpu.serve.admin import FlightCapture, copy_racy


# ------------------------------------------------------------ lock witness

def test_witness_lock_ownership():
    lk = threadcheck.WitnessLock()
    assert not lk.held_by_me() and not lk.locked()
    with lk:
        assert lk.held_by_me() and lk.locked()
        # ownership is per-thread, not per-process
        seen = []
        t = threading.Thread(target=lambda: seen.append(lk.held_by_me()),
                             name="cxxnet-test-owner")
        t.start()
        t.join()
        assert seen == [False]
    assert not lk.held_by_me()
    assert lk.acquisitions == 1


def test_witness_lock_delegates_to_inner():
    """A Condition built over the same inner lock still excludes the
    witness wrapper (mutual exclusion lives in the wrapped lock)."""
    inner = threading.Lock()
    lk = threadcheck.WitnessLock(inner)
    with lk:
        assert inner.locked()
        assert not lk.acquire(blocking=False)
    assert not inner.locked()


def test_held_understands_rlock_and_condition():
    rl = threading.RLock()
    assert not threadcheck._held(rl)
    with rl:
        assert threadcheck._held(rl)
    cv = threading.Condition()
    assert not threadcheck._held(cv)
    with cv:
        assert threadcheck._held(cv)


class ToyBox:
    """Witness fixture: one guarded attribute, annotated exactly like
    production code so collect_policies() reads the map from THIS file."""

    def __init__(self):
        self._lock = threading.Lock()
        self.items = []  # racelint: guarded-by(self._lock)

    def put(self, x):
        with self._lock:
            self.items.append(x)


def test_checked_toy_class():
    Checked = threadcheck.checked(ToyBox)
    assert Checked._threadcheck_guarded == {"items": ("_lock",)}
    box = Checked()
    box.items.append(0)        # un-armed: no witness
    threadcheck.arm(box)
    assert isinstance(box._lock, threadcheck.WitnessLock)
    box.put(1)                 # disciplined path passes
    with box._lock:
        assert box.items == [0, 1]
    with pytest.raises(threadcheck.LockWitnessError) as ei:
        box.items
    assert "items" in str(ei.value) and "_lock" in str(ei.value)
    with pytest.raises(threadcheck.LockWitnessError):
        box.items = []
    threadcheck.disarm(box)
    assert box.items == [0, 1]  # disarmed: free access again


def test_arm_rejects_unchecked_instances():
    with pytest.raises(TypeError):
        threadcheck.arm(ToyBox())


def test_checked_histogram_slots_class():
    """Histogram carries __slots__; the witness subclass delegates
    storage to the slot members and still catches unlocked touches."""
    h = threadcheck.checked(Histogram)()
    threadcheck.arm(h)
    for v in (1.0, 2.0, 3.0):
        h.observe(v)           # internally locked: passes armed
    assert h.summary()["count"] == 3
    assert h.percentile(50) == 2.0
    with pytest.raises(threadcheck.LockWitnessError):
        h.count                # the pre-fix scrape idiom now fails loudly
    with h._lock:
        assert h.count == 3


def test_checked_sentinel_bank_ring():
    bank = threadcheck.checked(SentinelBank)(MetricsRegistry())
    threadcheck.arm(bank)
    bank.observe_step({"examples_per_sec": 10.0})
    assert bank.state()["ring"]          # locked copy passes
    with pytest.raises(threadcheck.LockWitnessError):
        list(bank.ring)                  # the flight_dump bug, witnessed


def test_checked_flight_capture():
    fc = threadcheck.checked(FlightCapture)(MetricsRegistry(), lambda: 0)
    threadcheck.arm(fc)
    assert fc.trigger("test-anomaly") is True
    assert fc.trigger("second") is False    # idempotent while armed
    assert fc.tick() is None                # window 1 of max_ticks
    with pytest.raises(threadcheck.LockWitnessError):
        fc.armed


def test_checked_jsonl_sink(tmp_path):
    sink = threadcheck.checked(JsonlSink)(str(tmp_path / "m.jsonl"))
    threadcheck.arm(sink)
    sink.write({"kind": "step", "n": 1})
    with pytest.raises(threadcheck.LockWitnessError):
        sink._fo
    sink.close()


# ------------------------------------------------------------ interleaving

def test_hook_is_noop_without_callback():
    threadcheck.clear_hooks()
    threadcheck.hook("nobody-listens")    # must not raise
    fired = []
    threadcheck.set_hook("x", lambda: fired.append(1))
    threadcheck.hook("x")
    threadcheck.clear_hooks()
    threadcheck.hook("x")
    assert fired == [1]


class RacyCounter:
    """Negative fixture: the PRE-FIX ``Histogram.observe`` shape — an
    unlocked read-modify-write (racelint: race_undeclared) with the
    harness hook between the read and the write.  Kept so the harness
    demonstrably reproduces the bug class the fix removed."""

    def __init__(self):
        self.count = 0

    def observe(self):
        c = self.count
        threadcheck.hook("racy-counter-mid")
        self.count = c + 1


def test_interleaving_reproduces_the_prefix_lost_update():
    r = RacyCounter()
    threadcheck.run_interleaved(r.observe, r.observe, "racy-counter-mid")
    # two observes, ONE survives: thread A read 0, parked; B read 0 and
    # wrote 1; A resumed and wrote its stale 0 + 1 over B's update
    assert r.count == 1


def test_stress_histogram_keeps_exact_count():
    """Post-fix side: the shipped (locked) Histogram under the same
    contention the fixture loses updates to."""
    h = Histogram()
    threadcheck.stress(lambda i: h.observe(float(i)), threads=4,
                       iters=250)
    s = h.summary()
    assert s["count"] == 1000
    assert s["sum"] == 250 * (0.0 + 1.0 + 2.0 + 3.0)


@pytest.mark.slow
def test_stress_histogram_heavy():
    h = Histogram()
    threadcheck.stress(lambda i: h.observe(1.0), threads=8, iters=2000)
    assert h.summary()["count"] == 16000


def test_stress_registry_observe_single_series():
    """Two threads first-observing one series must converge on ONE
    Histogram (the get-then-insert it replaced dropped the loser's
    instance and its observation)."""
    reg = MetricsRegistry()
    threadcheck.stress(lambda i: reg.observe("lat", 1.0), threads=4,
                       iters=100)
    assert len(reg.histograms) == 1
    assert reg.histograms["lat"].summary()["count"] == 400


# ------------------------------------------------- copy_racy (scrape path)

class _FlakyMap:
    """Mapping whose keys() raises like a dict mutated mid-iteration for
    the first ``fail`` calls — the deterministic stand-in for a writer
    thread growing the dict under the scrape."""

    def __init__(self, data, fail):
        self.data = dict(data)
        self.fail = fail
        self.calls = 0

    def keys(self):
        self.calls += 1
        if self.calls <= self.fail:
            raise RuntimeError("dictionary changed size during iteration")
        return list(self.data.keys())

    def __getitem__(self, k):
        if k == "gone":
            raise KeyError(k)    # deleted between keys() and the read
        return self.data[k]


def test_copy_racy_bounded_retry_converges():
    m = _FlakyMap({"a": 1, "b": 2}, fail=3)
    assert copy_racy(m) == {"a": 1, "b": 2}
    assert m.calls == 4          # 3 failed tries + the one that landed


def test_copy_racy_fallback_tolerates_vanishing_keys():
    m = _FlakyMap({"a": 1, "gone": 2}, fail=8)   # every dict() try fails
    assert copy_racy(m) == {"a": 1}              # item-at-a-time fallback


def test_copy_racy_under_live_writer():
    """Satellite contract: bounded retry under a REAL mutating writer —
    the admin scrape must neither raise nor lock the dispatcher."""
    d = {}
    stop = threading.Event()
    errors = []

    def writer():
        try:
            i = 0
            while not stop.is_set():
                d[f"k{i}"] = i
                i += 1
        except BaseException as e:  # pragma: no cover - failure path
            errors.append(e)

    t = threading.Thread(target=writer, name="cxxnet-test-writer",
                         daemon=True)
    t.start()
    try:
        for _ in range(200):
            snap = copy_racy(d)
            assert isinstance(snap, dict)
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors
    # a snapshot is a prefix of the writer's inserts: every value matches
    assert all(snap[k] == int(k[1:]) for k in snap)


# --------------------------------------------------- JSONL sink under fire

def test_jsonl_sink_concurrent_writers_no_torn_lines(tmp_path):
    """The checkpoint-writer thread and the train thread emit through
    one sink: every line in the file must parse (satellite contract —
    the sink lock is what keeps records from interleaving mid-line)."""
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry()
    reg.configure_sink(f"jsonl:{path}")
    threadcheck.stress(
        lambda i: reg.emit("ckpt" if i % 2 else "step", worker=i,
                           payload="x" * 256),
        threads=4, iters=100)
    reg.close()
    lines = open(path).read().splitlines()
    assert len(lines) == 400
    kinds = {json.loads(l)["kind"] for l in lines}   # every line parses
    assert kinds == {"ckpt", "step"}


def test_emit_concurrent_with_sink_swap(tmp_path):
    """Regression for the sink TOCTOU: emit() snapshots the reference
    once, so a concurrent configure_sink()/close() can no longer turn
    the None-check into an AttributeError inside the train loop."""
    path = str(tmp_path / "m.jsonl")
    reg = MetricsRegistry()
    stop = threading.Event()
    errors = []

    def emitter():
        try:
            while not stop.is_set():
                reg.emit("step", n=1)
        except BaseException as e:
            errors.append(e)

    t = threading.Thread(target=emitter, name="cxxnet-test-emitter",
                         daemon=True)
    t.start()
    try:
        for _ in range(50):
            reg.configure_sink(f"jsonl:{path}")
            reg.configure_sink("none")
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors
    for line in open(path).read().splitlines():
        json.loads(line)       # whatever landed is whole


# ------------------------------------------- sentinel ring under flight

def test_sentinel_ring_append_during_flight_dump():
    """Regression for the 'deque mutated during iteration' crash: the
    reporter thread appends serve windows while the main thread's abort
    path runs flight_dump — post-fix both sides hold the ring lock."""
    bank = SentinelBank(MetricsRegistry())
    stop = threading.Event()
    errors = []

    def reporter():
        try:
            while not stop.is_set():
                bank.observe_serve({"serve_p99_ms": 5.0, "qps": 100.0})
        except BaseException as e:
            errors.append(e)

    t = threading.Thread(target=reporter, name="cxxnet-test-reporter",
                         daemon=True)
    t.start()
    try:
        for _ in range(100):
            bank.flight_dump("test")
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors
