"""Tokenized-LM data path: token shards, document packing, segment-aware
attention/loss, and the pack-state resume contract (io/text.py,
tools/tok2bin.py, doc/io.md "Tokenized text datasets")."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.io.text import (PackedSeqIterator, TextIterator, TokenShard,
                                write_token_shard)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _docs(n=40, vocab=64, mean_len=20, seed=3):
    from make_synth_text import gen_docs
    return gen_docs(n, vocab=vocab, mean_len=mean_len, seed=seed)


def _write_shards(tmp_path, docs, n_shards=2, itemsize=2):
    pattern = str(tmp_path / "c_%d.tok")
    for s in range(n_shards):
        write_token_shard(pattern % s, docs[s::n_shards], itemsize=itemsize)
    return pattern


def _chain(pattern, n_shards, seqlen, batch, shuffle=1, pack_split=1,
           seed_data=0):
    it = TextIterator()
    it.set_param("path_tok", pattern)
    it.set_param("tok_count", str(n_shards))
    it.set_param("shuffle", str(shuffle))
    it.set_param("seed_data", str(seed_data))
    it.set_param("silent", "1")
    p = PackedSeqIterator(it)
    p.set_param("seqlen", str(seqlen))
    p.set_param("batch_size", str(batch))
    p.set_param("pack_split", str(pack_split))
    p.init()
    return p


def _epoch(p):
    p.before_first()
    out = []
    while True:
        b = p.next()
        if b is None:
            return out
        out.append(b)


# --------------------------------------------------------- shard format
def test_token_shard_roundtrip(tmp_path):
    docs = _docs(12)
    for itemsize in (2, 4):
        path = str(tmp_path / f"s{itemsize}.tok")
        assert write_token_shard(path, docs, itemsize=itemsize) == 12
        sh = TokenShard(path)
        assert sh.ndocs == 12
        assert sh.ntokens == sum(d.size for d in docs)
        for i, d in enumerate(docs):
            np.testing.assert_array_equal(sh.doc(i), d)
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


def test_token_shard_validation(tmp_path):
    path = str(tmp_path / "bad.tok")
    with pytest.raises(AssertionError, match="itemsize"):
        write_token_shard(path, [[1, 70000]], itemsize=2)
    with pytest.raises(AssertionError, match="empty"):
        write_token_shard(path, [[]], itemsize=2)
    open(path, "wb").write(b"NOTATOKF" + b"\x00" * 64)
    with pytest.raises(AssertionError, match="CXTPUTOK"):
        TokenShard(path)


def test_tok2bin_cli_roundtrip(tmp_path):
    from tok2bin import pack_shards, read_corpus
    docs = _docs(11)
    corpus = tmp_path / "c.txt"
    with open(corpus, "w") as f:
        for d in docs:
            f.write(" ".join(str(int(t)) for t in d) + "\n")
    back = read_corpus(str(corpus))
    assert len(back) == 11
    np.testing.assert_array_equal(back[3], docs[3])
    pattern = str(tmp_path / "p_%d.tok")
    assert pack_shards(back, pattern, 3, vocab=64) == 11
    # round-robin split: every doc lands in exactly one shard
    total = sum(TokenShard(pattern % s).ndocs for s in range(3))
    assert total == 11


# --------------------------------------------------------- text iterator
def test_text_iterator_epoch_coverage_and_shuffle(tmp_path):
    docs = _docs(30)
    pattern = _write_shards(tmp_path, docs)
    it = TextIterator()
    it.set_param("path_tok", pattern)
    it.set_param("tok_count", "2")
    it.set_param("shuffle", "1")
    it.set_param("silent", "1")
    it.init()
    it.before_first()
    seen = {}
    while True:
        inst = it.next()
        if inst is None:
            break
        seen[inst.index] = np.asarray(inst.data)
    assert len(seen) == 30  # every doc exactly once
    # doc identity: index joins the shuffled stream back to the corpus
    order = []
    for s in range(2):
        order.extend(docs[s::2])
    for idx, toks in seen.items():
        np.testing.assert_array_equal(toks, order[idx])
    # epoch 2 has a different order; the shuffle is gen-seeded
    it.before_first()
    second = [it.next().index for _ in range(30)]
    assert sorted(second) == sorted(seen)
    assert list(seen) != second


def test_text_iterator_gen_state_resumes_shuffle(tmp_path):
    pattern = _write_shards(tmp_path, _docs(20))

    def fresh():
        it = TextIterator()
        it.set_param("path_tok", pattern)
        it.set_param("tok_count", "2")
        it.set_param("shuffle", "1")
        it.set_param("silent", "1")
        it.init()
        return it

    a = fresh()
    for _ in range(3):
        a.before_first()
    st = json.loads(json.dumps(a.state()))
    b = fresh()
    b.set_state(st)
    a.before_first()
    b.before_first()  # epoch 4 in both: orders must match
    ia = [a.next().index for _ in range(20)]
    ib = [b.next().index for _ in range(20)]
    assert ia == ib


def test_text_iterator_worker_sharding(tmp_path):
    docs = _docs(15)
    pattern = _write_shards(tmp_path, docs, n_shards=3)
    counts = []
    for rank in (0, 1):
        it = TextIterator()
        it.set_param("path_tok", pattern)
        it.set_param("tok_count", "3")
        it.set_param("dist_num_worker", "2")
        it.set_param("dist_worker_rank", str(rank))
        it.set_param("silent", "1")
        it.init()
        it.before_first()
        n = 0
        while it.next() is not None:
            n += 1
        counts.append(n)
    assert sum(counts) == 15  # the workers together cover every doc


# ---------------------------------------------------------- packing
def test_packer_row_fields(tmp_path):
    """Targets shift within a doc, -1 exactly at doc boundaries; a doc
    continuing past a row boundary KEEPS its last-position target (the
    one-token lookahead — no supervision lost to row chopping); segments
    renumber 1..k; positions reset at doc starts."""
    docs = [np.arange(10, 17, dtype=np.int32),   # 7 tokens
            np.arange(30, 35, dtype=np.int32),   # 5 tokens
            np.arange(50, 60, dtype=np.int32)]   # 10 tokens
    pattern = str(tmp_path / "d.tok")
    write_token_shard(pattern, docs)
    p = _chain(pattern, 0, seqlen=8, batch=2, shuffle=0)
    # tok_count=0 single shard: fix params
    b = _epoch(p)[0]
    S = 8
    toks = b.data.reshape(2, S).astype(np.int64)
    tgt = b.label[:, :S].astype(np.int64)
    seg = b.label[:, S:2 * S].astype(np.int64)
    pos = b.label[:, 2 * S:].astype(np.int64)
    stream = np.concatenate(docs)
    np.testing.assert_array_equal(toks.reshape(-1), stream[:16])
    # row 0 = doc0[0:7] + doc1[0:1]
    np.testing.assert_array_equal(seg[0], [1] * 7 + [2])
    np.testing.assert_array_equal(pos[0], [0, 1, 2, 3, 4, 5, 6, 0])
    np.testing.assert_array_equal(tgt[0, :6], docs[0][1:7])
    assert tgt[0, 6] == -1  # doc0's last token: target crosses docs
    assert tgt[0, 7] == docs[1][1]  # doc1 continues into row 1: lookahead
    # row 1 = doc1[1:5] + doc2[0:4]: segments renumber from 1 again
    np.testing.assert_array_equal(seg[1], [1] * 4 + [2] * 4)
    np.testing.assert_array_equal(pos[1], [1, 2, 3, 4, 0, 1, 2, 3])
    assert tgt[1, 3] == -1              # doc1 ends inside row 1
    assert tgt[1, 7] == docs[2][4]      # doc2 continues past the batch
    assert p.stats()["packing_efficiency"] == 1.0


def test_packer_conserves_tokens_across_epochs(tmp_path):
    docs = _docs(25)
    total = sum(d.size for d in docs)
    pattern = _write_shards(tmp_path, docs)
    p = _chain(pattern, 2, seqlen=16, batch=4)
    emitted = 0
    for _ in range(3):
        for b in _epoch(p):
            emitted += b.data.size
    # every token of every epoch is either emitted or still buffered —
    # nothing padded away, nothing dropped (the ragged carry)
    assert emitted + len(p._tok) == 3 * total
    assert p.stats()["packing_efficiency"] == 1.0


def test_packer_nosplit_mode(tmp_path):
    docs = [np.arange(5, dtype=np.int32), np.arange(7, dtype=np.int32),
            np.arange(20, dtype=np.int32), np.arange(3, dtype=np.int32)]
    pattern = str(tmp_path / "d.tok")
    write_token_shard(pattern, docs)
    p = _chain(pattern, 0, seqlen=8, batch=2, shuffle=0, pack_split=0)
    batches = []
    for _ in range(1):
        batches.extend(_epoch(p))
    rows = np.concatenate([b.data.reshape(-1, 8) for b in batches])
    segs = np.concatenate([b.label[:, 8:16] for b in batches])
    # docs never split: each row's nonzero segments end where padding
    # starts, and a 20-token doc is truncated to 8
    st = p.stats()
    assert st["truncated_tokens"] == 12
    assert st["packing_efficiency"] < 1.0
    for r in range(segs.shape[0]):
        nz = segs[r] != 0
        # padding only at the tail
        if (~nz).any():
            first_pad = int(np.argmax(~nz))
            assert not nz[first_pad:].any()


def test_packer_state_resume_bitwise(tmp_path):
    """Kill-resume through the ragged buffer: snapshot at an epoch
    boundary with a non-empty carry, restore into a FRESH chain, and the
    continuation must be bitwise identical."""
    docs = _docs(25)
    pattern = _write_shards(tmp_path, docs)
    a = _chain(pattern, 2, seqlen=16, batch=4)
    _epoch(a)  # epoch 1
    assert len(a._tok) > 0, "test needs a ragged carry at the boundary"
    st = json.loads(json.dumps(a.state()))  # round-boundary snapshot
    cont_a = [ _epoch(a) for _ in range(2) ]

    b = _chain(pattern, 2, seqlen=16, batch=4)
    b.set_state(st)
    cont_b = [ _epoch(b) for _ in range(2) ]
    for ea, eb in zip(cont_a, cont_b):
        assert len(ea) == len(eb)
        for x, y in zip(ea, eb):
            np.testing.assert_array_equal(x.data, y.data)
            np.testing.assert_array_equal(x.label, y.label)
            np.testing.assert_array_equal(x.index, y.index)
    # and the post-continuation states agree too
    assert a.state() == b.state()


# ----------------------------------- segment-aware attention & loss
def _packed_two_doc_batch(s=16, d1=9):
    """One row holding two docs (d1 and s-d1 tokens) + the same docs each
    alone in its own row, with matching label fields."""
    rnd = np.random.RandomState(0)
    toks = rnd.randint(1, 32, s)
    seg = np.array([1] * d1 + [2] * (s - d1))
    pos = np.concatenate([np.arange(d1), np.arange(s - d1)])
    return toks, seg, pos


def test_segment_mask_blocks_cross_doc_attention():
    """Logits of doc B inside a packed row == logits of doc B alone —
    the provable no-leak property."""
    from cxxnet_tpu.layers.base import ForwardContext, LabelInfo
    from cxxnet_tpu.layers.registry import create_layer
    s, d1, dim, h = 16, 9, 16, 2
    toks, seg, pos = _packed_two_doc_batch(s, d1)
    layer = create_layer("attention")
    for k, v in {"nhead": h, "causal": 1, "no_bias": 1,
                 "segment_key": "segment"}.items():
        layer.set_param(k, str(v))
    layer.infer_shapes([(1, 1, s, dim)])
    params = layer.init_params(jax.random.PRNGKey(1), [(1, 1, s, dim)])
    rnd = np.random.RandomState(1)
    x = rnd.randn(1, 1, s, dim).astype(np.float32)

    def run(xa, sega):
        ctx = ForwardContext(
            train=True, labels=LabelInfo(fields={
                "segment": jnp.asarray(sega[None].astype(np.float32))}))
        (y,), _ = layer.forward(params, {}, [jnp.asarray(xa)], ctx)
        return np.asarray(y)

    y_packed = run(x, seg)
    # doc2 alone, occupying the row prefix
    x2 = np.zeros_like(x)
    x2[:, :, :s - d1] = x[:, :, d1:]
    y_alone = run(x2, np.concatenate([np.ones(s - d1), np.zeros(d1)]))
    np.testing.assert_allclose(y_packed[:, :, d1:], y_alone[:, :, :s - d1],
                               rtol=2e-5, atol=2e-6)
    # and WITHOUT the segment mask the outputs differ (the leak exists)
    layer.segment_key = ""
    ctx = ForwardContext(train=True)
    (y_noseg,), _ = layer.forward(params, {}, [jnp.asarray(x)], ctx)
    assert not np.allclose(np.asarray(y_noseg)[:, :, d1:],
                           y_alone[:, :, :s - d1], atol=1e-4)


def test_packed_vs_unpacked_loss_parity():
    """Total valid-token cross-entropy of a packed row equals the sum
    over its documents trained separately (segment mask blocks attention,
    packed=1 masks boundary targets)."""
    from cxxnet_tpu.layers.base import ForwardContext, LabelInfo
    from cxxnet_tpu.models import transformer
    from cxxnet_tpu.nnet.netconfig import NetConfig
    from cxxnet_tpu.nnet.net import Network
    from cxxnet_tpu.utils.config import parse_config_string
    s, d1, vocab = 16, 9, 32
    toks, seg, pos = _packed_two_doc_batch(s, d1)
    tgt = np.full(s, -1, np.int64)
    tgt[:d1 - 1] = toks[1:d1]
    tgt[d1:s - 1] = toks[d1 + 1:]
    conf = transformer(vocab=vocab, seq=s, dim=16, nlayer=1, nhead=2,
                       packed=True)
    nc = NetConfig()
    nc.configure(parse_config_string(conf))
    net = Network(nc, 1, jnp.float32)
    params = net.init_params(jax.random.PRNGKey(7))
    buffers = net.init_buffers()

    def run(toks_r, tgt_r, seg_r, pos_r):
        fields = {"label": jnp.asarray(tgt_r[None].astype(np.float32)),
                  "segment": jnp.asarray(seg_r[None].astype(np.float32)),
                  "position": jnp.asarray(pos_r[None].astype(np.float32))}
        ctx = ForwardContext(train=True, labels=LabelInfo(fields=fields),
                             loss_scale=1.0)
        net.forward(params, buffers,
                    {0: jnp.asarray(toks_r[None, None, None]
                                    .astype(np.float32))}, ctx)
        n_valid = int((tgt_r >= 0).sum())
        # per_inst = sum(valid nats)/count; recover the token SUM
        return float(np.asarray(ctx.losses[0])) * max(n_valid, 1)

    packed_nats = run(toks, tgt, seg, pos)
    # each doc alone in its own zero-padded row
    total = 0.0
    for lo, hi in ((0, d1), (d1, s)):
        n = hi - lo
        toks_r = np.zeros(s, np.int64)
        toks_r[:n] = toks[lo:hi]
        tgt_r = np.full(s, -1, np.int64)
        tgt_r[:n - 1] = toks[lo + 1:hi]
        seg_r = np.concatenate([np.ones(n), np.zeros(s - n)])
        pos_r = np.concatenate([np.arange(n), np.zeros(s - n)])
        total += run(toks_r, tgt_r, seg_r, pos_r)
    np.testing.assert_allclose(packed_nats, total, rtol=2e-4)


@pytest.mark.parametrize("d1", [9, 50])
def test_flash_segment_pairtest_interpret(d1):
    """Triangular-flash segment kernel vs the lax fallback, forward and
    backward, in interpret mode (the acceptance pairtest)."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    from cxxnet_tpu.parallel import ring
    if pk.pltpu is None:
        pytest.skip("no pallas TPU module")
    rnd = np.random.RandomState(0)
    b, h, s, d = 2, 2, 128, 16
    q, k, v = (jnp.asarray(rnd.randn(b, h, s, d).astype(np.float32))
               for _ in range(3))
    seg = np.zeros((b, s), np.int64)
    seg[:, :d1] = 1
    seg[:, d1:] = 2
    seg[1, -16:] = 0  # padding tail on row 1 (diagonal-only attention)
    seg = jnp.asarray(seg)
    ref = ring.dense_attention(q, k, v, causal=True, seg=seg)
    out = pk.flash_attention_segmented(q, k, v, seg, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    g_ref = jax.grad(lambda *a: jnp.sum(
        ring.dense_attention(*a, causal=True, seg=seg) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(lambda *a: jnp.sum(
        pk.flash_attention_segmented(*a, seg, interpret=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-4)


def test_ring_segment_matches_dense():
    """Segment ids rotate around the ring with their K/V blocks; the
    sharded result must match the single-device oracle."""
    from jax.sharding import Mesh
    from cxxnet_tpu.parallel import ring
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.array(devs[:4]).reshape(4), ("seq",))
    rnd = np.random.RandomState(0)
    b, h, s, d = 2, 2, 64, 8
    q, k, v = (jnp.asarray(rnd.randn(b, h, s, d).astype(np.float32))
               for _ in range(3))
    seg = np.repeat(np.arange(1, 5), 16)[None].repeat(b, 0)
    seg = jnp.asarray(seg)
    ref = ring.dense_attention(q, k, v, causal=True, seg=seg)
    out = ring.sharded_attention(q, k, v, mesh, causal=True, seg=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


# ------------------------------------------------------------ end to end
def _train_packed_lm(tmp_path, mesh=None, steps=40, seqlen=16, batch=4,
                     moe=0):
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import transformer
    docs = _docs(120, vocab=32, mean_len=12, seed=2)
    pattern = _write_shards(tmp_path, docs)
    chain = _chain(pattern, 2, seqlen=seqlen, batch=batch)
    extra = [("updater", "adam"), ("eta", "0.01"), ("silent", "1"),
             ("eval_train", "0")]
    dev = "cpu"
    if mesh:
        extra.append(("mesh", mesh))
        n = 1
        for part in mesh.split(","):
            n *= int(part.split(":")[1])
        dev = f"cpu:0-{n - 1}"
    t = _make_trainer(
        transformer(vocab=32, seq=seqlen, dim=16, nlayer=1, nhead=2,
                    packed=True, moe_experts=moe),
        batch, dev, extra=extra)
    t.start_round(1)
    losses = []
    while len(losses) < steps:
        chain.before_first()
        while len(losses) < steps:
            b = chain.next()
            if b is None:
                break
            t.update(b)
            losses.append(float(np.asarray(t._last_loss)))
    return losses


def test_packed_lm_trains_single_device(tmp_path):
    losses = _train_packed_lm(tmp_path)
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < losses[0] * 0.75, losses[::10]


@pytest.mark.slow
def test_packed_lm_trains_data_seq_mesh(tmp_path):
    losses = _train_packed_lm(tmp_path, mesh="data:2,seq:2", steps=30)
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < losses[0] * 0.85, losses[::10]


@pytest.mark.slow
def test_packed_moe_lm_trains_data_expert_mesh(tmp_path):
    losses = _train_packed_lm(tmp_path, mesh="data:2,expert:2", steps=30,
                              moe=4)
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < losses[0] * 0.85, losses[::10]


# ------------------------------------------------------------ lint rules
def test_text_lint_rules():
    from cxxnet_tpu.analysis.conflint import lint_pairs
    from cxxnet_tpu.utils.config import parse_config_file
    repo = os.path.join(os.path.dirname(__file__), "..")
    base = parse_config_file(os.path.join(repo, "example/LM/longctx.conf"))
    assert not [f for f in lint_pairs(base) if f.severity == "error"]

    def strip(pairs, key, layer=None):
        out, cur = [], None
        for k, v in pairs:
            if k.startswith("layer["):
                cur = v.split(":", 1)[0]
            if k == key and (layer is None or cur == layer):
                continue
            out.append((k, v))
        return out

    # packing without the packed loss mask: error
    f = [x for x in lint_pairs(strip(base, "packed"))
         if x.severity == "error"]
    assert f and f[0].key == "packed"
    # packing with an unmasked attention layer: error
    f = [x for x in lint_pairs(strip(base, "segment_key"))
         if x.severity == "error"]
    assert f and f[0].key == "segment_key"
    # seqlen vs input width mismatch: error
    mut = [(k, ("128" if k == "seqlen" else v)) for k, v in base]
    f = [x for x in lint_pairs(mut) if x.severity == "error"]
    assert any(x.key == "seqlen" for x in f)
    # seq axis indivisibility: warn
    mut = [(k, ("data:2,seq:3" if k == "mesh" else
                ("cpu:0-5" if k == "dev" else v))) for k, v in base]
    f = [x for x in lint_pairs(mut)
         if "not divisible by the seq mesh axis" in x.message]
    assert f and f[0].severity == "warn"
    # seq axis on a net with no sequence layer: warn
    mnist = parse_config_file(
        os.path.join(repo, "example/MNIST/MNIST.conf")) \
        + [("mesh", "data:2,seq:2"), ("dev", "cpu:0-3")]
    f = [x for x in lint_pairs(mnist) if "no sequence layer" in x.message]
    assert f and f[0].severity == "warn"


def test_text_iterator_keys_in_registry():
    """The new text_*/pack_* KeySpecs are harvested into the iterator
    scope so configs lint against them (analysis/registry.py)."""
    from cxxnet_tpu.analysis import registry
    scope = registry.iterator_scope(("text", "packseq"))
    for key in ("path_tok", "tok_count", "seqlen", "pack_split",
                "text_max_docs"):
        assert scope.match(key), key
    assert not scope.match("path_img")
    assert registry.known_anywhere("pack_split")
