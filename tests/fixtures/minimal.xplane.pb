
κ/device:TPU:0XLA Modules"€δ—ΠXLA Ops"€”λά"€Κµξ€ΒΧ/"€”λά€Κµξ"€¨ΦΉ€Ζ†"€ς‹¨	€„―_"€Π¬σ€ΌΑ–"€ Ωζ€ήΎ"€΄ΔΓ!€"fusion.1"
copy.2"convolution.3"jit_step"all-reduce-start.1"all-reduce-done.1"reduce-scatter.2" loop-all-reduce-fusion.3
3	/host:CPUXLA Ops"€ξ‰"	host-loop