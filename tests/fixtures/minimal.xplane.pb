
¥/device:TPU:0XLA Modules"€ä—Ð0XLA Ops"€”ëÜ"€Êµî"€„¯_"€¼Á–"jit_step"convolution.3"
copy.2"fusion.1
2	/host:CPUXLA Ops"	€Œî‰"		hostloop