"""1F1B schedule x dp_overlap composition (ISSUE 18 tentpole).

The acceptance triangle: the interleaved 1F1B schedule with explicit
cooldown bucket psums (``dp_overlap = 1``) vs the same schedule's
whole-tree implicit psum vs the gpipe fill-drain baseline — BITWISE
trajectory parity at f32 on a CPU ``data:2,pipe:2`` mesh with
``pipe_microbatch = 2`` (two microbatches: the per-key gradient is a
two-term sum, so gpipe's descending and 1F1B's ascending accumulation
orders agree by IEEE addition commutativity; at larger counts the
schedules re-associate and parity is rtol-tight instead —
tests/test_pipeline_net.py).  Plus: the data-axis bucket all_reduces
asserted INSIDE the lowered pipelined step (the dp_overlap x pipe
fallback is retired), the per-stage saved-activation ring staying flat
in the microbatch count, and the ``pipe_bubble`` ledger category
tiling the wall.
"""

import json
import os
import re
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cxxnet_tpu import engine  # noqa: E402
from cxxnet_tpu.io.data import DataBatch  # noqa: E402
from cxxnet_tpu.models.zoo import lenet  # noqa: E402
from test_trainer import make_trainer  # noqa: E402

EXTRA = [("eta", "0.1"), ("momentum", "0.9"), ("silent", "1"),
         ("eval_train", "0"), ("batch_size", "16")]
DP_OPTS = ("dp_overlap", "dp_bucket_mb", "dp_reduce_dtype")


@pytest.fixture(autouse=True)
def _restore_engine_opts():
    saved = {k: getattr(engine.opts, k) for k in DP_OPTS}
    yield
    for k, v in saved.items():
        engine.opts.set(k, v)


def _batches(n=4, bs=16, seed=0, tail_padd=0):
    rnd = np.random.RandomState(seed)
    out = []
    for i in range(n):
        x = rnd.rand(bs, 1, 28, 28).astype(np.float32)
        y = (x.mean(axis=(1, 2, 3)) > 0.5).astype(np.float32) * 2
        out.append(DataBatch(data=x, label=y.reshape(bs, 1),
                             index=np.arange(bs, dtype=np.uint32),
                             num_batch_padd=tail_padd,
                             tail_mask_padd=tail_padd))
    return out


def _train(schedule, overlap, extra=(), tail_padd=0, n_micro=2):
    engine.opts.set("dp_overlap", overlap)
    engine.opts.set("dp_bucket_mb", "0.01")  # several buckets per stage
    t = make_trainer(lenet(num_class=4),
                     extra=EXTRA + [("dev", "cpu:0-3"),
                                    ("mesh", "data:2,pipe:2"),
                                    ("pipe_microbatch", str(n_micro)),
                                    ("pipe_schedule", schedule)]
                     + list(extra))
    losses = []
    for b in _batches(tail_padd=tail_padd):
        t.update(b)
        losses.append(np.asarray(t._last_loss).copy())
    params = jax.tree.map(np.asarray, t.params)
    return losses, params


def _assert_bitwise(a, b, who):
    for la, lb in zip(a[0], b[0]):
        np.testing.assert_array_equal(la, lb, err_msg=f"{who}: loss")
    fa, fb = jax.tree.leaves(a[1]), jax.tree.leaves(b[1])
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(x, y, err_msg=f"{who}: params")


@pytest.mark.parametrize("extra,tail_padd", [
    ((), 0),
    pytest.param((), 3, marks=pytest.mark.slow),
    pytest.param((("update_period", "2"),), 0, marks=pytest.mark.slow),
], ids=["plain", "tail_mask", "update_period"])
def test_1f1b_bitwise_triangle(extra, tail_padd):
    """implicit-1f1b == explicit-1f1b == gpipe, bitwise, at M = 2."""
    imp = _train("1f1b", "0", extra, tail_padd)
    exp = _train("1f1b", "1", extra, tail_padd)
    gp = _train("gpipe", "0", extra, tail_padd)
    _assert_bitwise(imp, exp, "1f1b explicit buckets vs implicit psum")
    _assert_bitwise(imp, gp, "1f1b vs gpipe")


def test_remat_pipe_rejected():
    """remat x pipe stays mutually exclusive (the schedule already
    recomputes each stage's forward inside its backward tick)."""
    t = make_trainer(lenet(num_class=4),
                     extra=EXTRA + [("dev", "cpu:0-3"),
                                    ("mesh", "data:2,pipe:2"),
                                    ("pipe_microbatch", "2"),
                                    ("pipe_schedule", "1f1b"),
                                    ("remat", "2")])
    with pytest.raises(AssertionError, match="mutually exclusive"):
        t.update(_batches(1)[0])


def test_explicit_bucket_all_reduces_in_hlo():
    """The retired-fallback receipt: with dp_overlap = 1 the pipelined
    step itself must lower one (pipe, data) all_reduce per bucket leaf
    — the merged 4-member replica group — instead of warning and
    falling back to the implicit whole-tree psum."""
    engine.opts.set("dp_overlap", "1")
    engine.opts.set("dp_bucket_mb", "0.01")
    t = make_trainer(lenet(num_class=4),
                     extra=EXTRA + [("dev", "cpu:0-3"),
                                    ("mesh", "data:2,pipe:2"),
                                    ("pipe_microbatch", "2"),
                                    ("pipe_schedule", "1f1b")])
    buckets = t._pipe_bucket_plan()
    assert buckets is not None and len(buckets) >= 2, \
        "bucket plan did not engage (fallback not retired?)"
    stages = sorted({st for _, st in buckets})
    assert stages == [0, 1], "buckets must spread over the stages"
    n_leaves = sum(len(jax.tree.leaves(t.params[k]))
                   for keys, _ in buckets for k in keys)
    data = jnp.zeros((16, 1, 28, 28), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    txt = t._train_step.lower(
        t.params, t.opt_state, t.buffers, data, label, (),
        jnp.int32(0), jax.random.PRNGKey(0)).as_text()
    # the merged (pipe, data) group on a 2x2 mesh is all 4 devices
    merged = [m for m in re.findall(
        r"all_reduce.*?replica_groups = dense<(\[\[.*?\]\])>", txt)
        if m.count(",") == 3]
    assert len(merged) >= n_leaves, (
        f"expected >= {n_leaves} bucket all_reduces over the merged "
        f"(pipe, data) group, found {len(merged)}")
    # and the schedule's ppermute handoffs ride in the same program
    assert re.search(r"ppermute|collective_permute", txt)


def test_1f1b_per_stage_ring_flat_in_microbatch_count():
    """Each stage holds at most S in-flight activation sets: the
    saved-input ring (2(S-1-s)+1 slots) is n_micro-independent, so
    temp memory stays ~flat from M = 2 to M = 8 while gpipe's per-tick
    residuals grow — the >= 2x microbatch headroom at fixed per-stage
    activation memory the flagship conf banks on."""
    def measure(schedule, n_micro, mb=8):
        bs = n_micro * mb
        t = make_trainer(
            lenet(num_class=4),
            extra=[("eta", "0.1"), ("momentum", "0.9"), ("silent", "1"),
                   ("eval_train", "0"), ("batch_size", str(bs)),
                   ("dev", "cpu:0-1"), ("mesh", "pipe:2"),
                   ("pipe_microbatch", str(n_micro)),
                   ("pipe_schedule", schedule)])
        stats = t.step_memory_stats()
        if stats is None or not stats.get("temp_bytes"):
            pytest.skip("backend reports no temp size")
        return stats["temp_bytes"]

    f1b_2, f1b_8 = measure("1f1b", 2), measure("1f1b", 8)
    gp_2, gp_8 = measure("gpipe", 2), measure("gpipe", 8)
    assert f1b_8 < 1.3 * f1b_2, (f1b_2, f1b_8)
    # gpipe at 4x the microbatches pays for every live tick residual
    assert gp_8 > 1.5 * gp_2, (gp_2, gp_8)


# ------------------------------------------------- pipe_bubble ledger

def test_ledger_pipe_bubble_tiles_wall():
    """Step/round records stamped with pipe_bubble_frac: the fold
    carves dispatch * frac into the pipe_bubble category, the
    categories still tile the wall, and goodput excludes the bubble."""
    from cxxnet_tpu.monitor import ledger as ledgerlib
    frac = 0.2
    recs = [
        {"ts": 1.0, "kind": "compile", "compile_sec": 2.0, "round": 0},
        {"ts": 2.0, "kind": "step", "dispatch_sec": 1.0,
         "iter_wait_sec": 0.0, "h2d_sec": 0.0, "pipe_bubble_frac": frac},
        {"ts": 3.0, "kind": "round", "round": 1, "wall_sec": 6.0,
         "eval_sec": 1.0, "dispatch_sec": 5.0, "iter_wait_sec": 1.0,
         "h2d_sec": 0.0, "pipe_bubble_frac": frac},
    ]
    led = ledgerlib.build_ledger(recs, wall_sec=10.0)
    c = led["categories"]
    assert c["pipe_bubble"] == pytest.approx(5.0 * frac)
    assert c["dispatch"] == pytest.approx(5.0 * (1 - frac))
    assert sum(c.values()) == pytest.approx(10.0)
    assert led["goodput_pct"] == pytest.approx(40.0)
    assert "pipe_bubble" in ledgerlib.CATEGORIES
    # records without the stamp: zero carve (non-pipelined runs)
    led0 = ledgerlib.build_ledger(
        [{"ts": 1.0, "kind": "round", "round": 1, "wall_sec": 4.0,
          "eval_sec": 0.0, "dispatch_sec": 4.0, "iter_wait_sec": 0.0,
          "h2d_sec": 0.0}], wall_sec=5.0)
    assert led0["categories"]["pipe_bubble"] == 0.0
    assert led0["goodput_pct"] == pytest.approx(80.0)


def test_ledger_pipe_bubble_in_dying_round_and_rollback():
    """Pending step marks keep their bubble split when the round dies,
    and a rollback books the pending bubble as lost work."""
    from cxxnet_tpu.monitor import ledger as ledgerlib
    step = {"ts": 2.0, "kind": "step", "dispatch_sec": 2.0,
            "iter_wait_sec": 0.0, "h2d_sec": 0.0,
            "pipe_bubble_frac": 0.25}
    led = ledgerlib.build_ledger([dict(step)], wall_sec=4.0)
    assert led["categories"]["pipe_bubble"] == pytest.approx(0.5)
    assert led["categories"]["dispatch"] == pytest.approx(1.5)
    rb = [dict(step),
          {"ts": 3.0, "kind": "rollback", "restored_round": 0}]
    led_rb = ledgerlib.build_ledger(rb, wall_sec=4.0)
    assert led_rb["categories"]["pipe_bubble"] == 0.0
    assert led_rb["categories"]["rollback_lost"] == pytest.approx(2.0)


def test_fixture_ledger_carries_pipe_bubble():
    """The checked-in metrics fixture exercises the new category, so
    the lint.sh obsv/self-diff gates cover the schema."""
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "run_report.jsonl")
    recs = [json.loads(l) for l in open(fixture)]
    led = [r for r in recs if r.get("kind") == "ledger"][-1]
    assert led["categories"].get("pipe_bubble", 0.0) > 0.0
    assert sum(led["categories"].values()) == pytest.approx(
        led["wall_sec"], rel=0.02)
    stamped = [r for r in recs if r.get("kind") in ("step", "round")
               and r.get("pipe_bubble_frac")]
    assert stamped, "fixture records lost the pipe_bubble_frac stamp"
    # the analytic share the trainer stamps: (S-1)/(M+S-1)
    assert stamped[0]["pipe_bubble_frac"] == pytest.approx(
        1.0 / 9.0, rel=0.01)


def test_trainer_pipe_bubble_frac_analytic():
    """The trainer's stamped fraction is the analytic (S-1)/(M+S-1)."""
    t = make_trainer(lenet(num_class=4),
                     extra=EXTRA + [("dev", "cpu:0-3"),
                                    ("mesh", "data:2,pipe:2"),
                                    ("pipe_microbatch", "4"),
                                    ("pipe_schedule", "1f1b")])
    assert t.pipe_bubble_frac == pytest.approx(1.0 / 5.0)
    flat = make_trainer(lenet(num_class=4),
                        extra=EXTRA + [("dev", "cpu")])
    assert flat.pipe_bubble_frac == 0.0
