"""Training observatory (doc/monitor.md: layer attribution, regression
sentinels, run-report CLI):

* scope stamping: conn_scope_name contract, named scopes in the
  compiled step HLO, attribution joins against the checked-in fixture
  (tests/fixtures/minimal.xplane.pb carries display_name scope paths);
* layer_profile end-to-end on a CPU MNIST run with a profiling window —
  rows sum to the traced op total and named layers appear;
* prof_every recurring windows emit one trace + layer_profile record
  per window;
* sentinels: EWMA drop/rise triggers, warmup, anomaly schema, the
  flight-recorder ring, and the TrainingDiverged dump through the CLI;
* Histogram percentiles + the pred/extract latency record;
* graftlint cross-key rules for the new knobs;
* tools/obsv.py over the checked-in run-report fixture (the lint.sh
  companion check).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(__file__))

from cxxnet_tpu.layers.base import conn_scope_name
from cxxnet_tpu.monitor import attribution
from cxxnet_tpu.monitor.metrics import Histogram, MetricsRegistry
from cxxnet_tpu.monitor.sentinel import Sentinel, SentinelBank
from cxxnet_tpu.monitor.trace import parse_xspace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "minimal.xplane.pb")
REPORT_FIXTURE = os.path.join(REPO, "tests", "fixtures",
                              "run_report.jsonl")


# ------------------------------------------------------------ scope naming

def test_conn_scope_name_contract():
    class C:  # the scope base IS the param_key base (monitor-key join)
        param_key = "16-fc6"
    assert conn_scope_name(16, C()) == "16-fc6"
    C.param_key = "03-fullc"
    assert conn_scope_name(3, C()) == "03-fullc"
    C.param_key = "00-weird name/|x"  # config names sanitize scope-safe
    assert conn_scope_name(0, C()) == "00-weird_name__x"
    # a shared connection keeps its primary's base under its OWN index
    C.param_key = "03-fc1"
    assert conn_scope_name(7, C()) == "07-fc1"
    # 100+-connection nets grow a third index digit; still recoverable
    C.param_key = "100-conv"
    assert conn_scope_name(100, C()) == "100-conv"
    assert attribution.scopes_from_planes([]) == []  # (shape check)


def test_scope_of_path_innermost_and_wrapped():
    sre = attribution._scope_re(["00-conv", "03-fullc"])
    assert attribution.scope_of_path(
        "jit(step)/jit(main)/00-conv/add.1", sre) == "00-conv"
    # transform wrappers match by substring; the LAST (innermost) wins
    assert attribution.scope_of_path(
        "jit(step)/transpose(jvp(03-fullc))/dot_general", sre) \
        == "03-fullc"
    assert attribution.scope_of_path(
        "jit(step)/00-conv/while/03-fullc/x", sre) == "03-fullc"
    assert attribution.scope_of_path("jit(step)/copy", sre) is None
    assert attribution.scope_of_path("", sre) is None


def test_hlo_op_scopes_parses_optimized_text():
    hlo = """
HloModule jit_step, entry_computation_layout={...}

%fused_computation (p0: f32[16,32]) -> f32[16,32] {
  %p0 = f32[16,32] parameter(0)
  ROOT %mul.3 = f32[16,32] multiply(%p0, %p0), metadata={op_name="jit(step)/01-relu/mul" source_file="x.py"}
}

ENTRY %main {
  %param.1 = f32[16,144] parameter(0)
  %dot.19 = f32[16,32] dot(%param.1), metadata={op_name="jit(step)/00-fc1/dot_general" source_line=3}
  ROOT %fusion.2 = f32[16,32] fusion(%dot.19), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(step)/01-relu/mul"}
}
"""
    m = attribution.hlo_op_scopes(hlo, ["00-fc1", "01-relu"])
    assert m["dot.19"] == "00-fc1"
    assert m["fusion.2"] == "01-relu"
    assert m["mul.3"] == "01-relu"      # fused-computation body included
    assert m["param.1"] is None         # no metadata -> known, unscoped


# ------------------------------------------------------- fixture attribution

def test_layer_table_against_fixture():
    """The checked-in xplane fixture carries display_name scope paths
    (tools/make_xplane_fixture.py): compute buckets to its two layers,
    collectives to their own row, and the substring-trap fusion books
    as the 03-fullc compute its path names — never as comm."""
    planes = parse_xspace(FIXTURE)
    t = attribution.layer_table(planes, ["00-conv", "03-fullc"])
    rows = {r["layer"]: r for r in t["rows"]}
    assert rows["00-conv"]["device_ms"] == pytest.approx(4.5)
    assert rows["00-conv"]["count"] == 3  # fusion.1 x2 + convolution.3
    assert rows["03-fullc"]["device_ms"] == pytest.approx(0.8)
    assert rows["03-fullc"]["comm_ms"] == 0.0  # the trap stays compute
    assert rows[attribution.COMM_ROW]["device_ms"] == pytest.approx(0.8)
    assert rows[attribution.COMM_ROW]["comm_ms"] == pytest.approx(0.8)
    assert t["ops_total_ms"] == pytest.approx(6.1)
    assert t["device_total_ms"] == pytest.approx(5.0)  # XLA Modules line
    assert t["attributed_ms"] == pytest.approx(5.3)
    # rows sum exactly to the counted op total
    assert sum(r["device_ms"] for r in t["rows"]) \
        == pytest.approx(t["ops_total_ms"])
    # per-step division
    t2 = attribution.layer_table(planes, ["00-conv"], steps=2)
    assert {r["layer"]: r for r in t2["rows"]}["00-conv"]["device_ms"] \
        == pytest.approx(2.25)


def test_layer_table_degraded_join_keeps_unattributed(tmp_path):
    """Without an op_scopes map (degraded trainer paths, --trace mode)
    a scope-less op that still carries a framework path lands in
    (unattributed) instead of vanishing — coverage must not read ~1.0
    when half the program has no scope.  Pathless events (module lines,
    host bookkeeping) stay excluded either way."""
    from cxxnet_tpu.monitor.trace import XEvent, XLine, XPlane
    MS = 1_000_000_000
    p = XPlane("/device:TPU:0",
               [XLine("XLA Ops", [XEvent(1, MS), XEvent(2, MS),
                                  XEvent(3, MS)])],
               {1: "fusion.1", 2: "fusion.2", 3: "host-loop"},
               {1: "jit(step)/00-conv/add",
                2: "jit(step)/jit(main)/loss/sub"})  # path, no scope
    t = attribution.layer_table([p], ["00-conv"])
    rows = {r["layer"]: r for r in t["rows"]}
    assert rows["00-conv"]["device_ms"] == pytest.approx(1.0)
    assert rows[attribution.OTHER_ROW]["device_ms"] == pytest.approx(1.0)
    assert "host-loop" not in rows and len(rows) == 2  # pathless: out
    assert t["coverage"] == pytest.approx(0.5)
    # with an op_scopes oracle, membership decides instead (fusion.2
    # deliberately absent -> excluded, the pre-oracle behavior)
    t2 = attribution.layer_table([p], ["00-conv"],
                                 op_scopes={"fusion.1": "00-conv"})
    assert t2["coverage"] == pytest.approx(1.0)
    assert t2["ops_total_ms"] == pytest.approx(1.0)


def test_scopes_recovered_from_trace_metadata():
    assert attribution.scopes_from_planes(parse_xspace(FIXTURE)) == \
        ["00-conv", "03-fullc"]


def test_scopes_from_planes_sees_wrapped_backward_paths():
    """A layer visible ONLY inside a transform wrapper (its forward ops
    fused under a neighbor) is still discovered for --trace mode."""
    from cxxnet_tpu.monitor.trace import XPlane
    p = XPlane("/device:TPU:0", [], {1: "fusion.9"},
               {1: "jit(step)/transpose(jvp(07-norm))/mul"})
    assert attribution.scopes_from_planes([p]) == ["07-norm"]


def test_event_display_parsed():
    tpu = parse_xspace(FIXTURE)[0]
    assert tpu.event_display[1] == "jit(step)/jit(main)/00-conv/add.1"
    assert 4 not in tpu.event_display  # the module event carries none


def test_layer_table_roofline_columns():
    planes = parse_xspace(FIXTURE)
    costs = {"00-conv": {"flops": 1e9, "bytes": 1e6}}
    t = attribution.layer_table(planes, ["00-conv"], costs=costs,
                                peak_flops=100e12, peak_bw=800e9)
    row = {r["layer"]: r for r in t["rows"]}["00-conv"]
    sec = row["device_ms"] / 1e3
    assert row["mfu_pct"] == pytest.approx(1e9 / sec / 100e12 * 100,
                                           abs=0.005)  # rounded to 2dp
    floor_ms = max(1e9 / 100e12, 1e6 / 800e9) * 1e3
    assert row["roofline_ms"] == pytest.approx(floor_ms, rel=1e-3)
    assert row["roofline_x"] == pytest.approx(
        row["device_ms"] / floor_ms, rel=1e-2)
    # unknown chip (CPU): no made-up peaks, no MFU columns
    t2 = attribution.layer_table(planes, ["00-conv"], costs=costs)
    row2 = {r["layer"]: r for r in t2["rows"]}["00-conv"]
    assert "mfu_pct" not in row2 and "roofline_ms" not in row2
    assert row2["flops"] == 1e9


# --------------------------------------------------------- histogram p50/p95

def test_histogram_percentiles():
    h = Histogram()
    assert h.percentile(50) is None
    for v in range(1, 101):
        h.observe(float(v))
    s = h.summary()
    # nearest-rank: ceil(n*q/100)-1 — exact multiples don't round up
    assert s["p50"] == pytest.approx(50.0)
    assert s["p95"] == pytest.approx(95.0)
    assert s["p99"] == pytest.approx(99.0)
    assert s["count"] == 100 and s["max"] == 100.0
    h1 = Histogram()
    h1.observe(1.0)
    h1.observe(2.0)
    assert h1.percentile(50) == 1.0 and h1.percentile(100) == 2.0
    # beyond the reservoir: summary stays sane and deterministic
    h2a, h2b = Histogram(), Histogram()
    for v in range(10000):
        h2a.observe(float(v))
        h2b.observe(float(v))
    assert h2a.summary() == h2b.summary()
    assert 3000 < h2a.summary()["p50"] < 7000


# ---------------------------------------------------------------- sentinels

def test_sentinel_drop_fires_after_warmup():
    s = Sentinel("examples_per_sec", "drop", rel=0.2, warmup=3)
    assert s.observe(100.0) is None  # warmup
    assert s.observe(100.0) is None
    assert s.observe(100.0) is None
    assert s.observe(95.0) is None   # -5%: within band
    hit = s.observe(60.0)            # ~-39% vs ewma: fires
    assert hit is not None
    assert hit["direction"] == "drop" and hit["rel_dev"] < -0.2
    # the anomalous value folded in: the baseline converges and a
    # sustained new level stops alarming
    for _ in range(20):
        s.observe(60.0)
    assert s.observe(60.0) is None


def test_sentinel_rise_direction():
    s = Sentinel("comm_share", "rise", rel=0.2, warmup=1)
    assert s.observe(0.10) is None
    assert s.observe(0.11) is None
    hit = s.observe(0.20)
    assert hit and hit["direction"] == "rise" and hit["rel_dev"] > 0.2
    # drops never fire a rise sentinel
    assert s.observe(0.05) is None


def test_sentinel_bank_anomaly_and_flight_records(tmp_path):
    reg = MetricsRegistry()
    sink = tmp_path / "m.jsonl"
    reg.configure_sink(f"jsonl:{sink}")
    bank = SentinelBank(reg, rel=0.2, warmup=2, ring=3)
    for i, eps in enumerate([100.0, 100.0, 100.0, 99.0, 50.0]):
        bank.observe_step({"round": 0, "step": i,
                           "examples_per_sec": eps})
    recs = [json.loads(l) for l in open(sink)]
    anoms = [r for r in recs if r["kind"] == "anomaly"]
    assert len(anoms) == 1
    a = anoms[0]
    assert a["metric"] == "examples_per_sec" and a["direction"] == "drop"
    assert a["value"] == 50.0 and a["rel_dev"] < -0.2
    assert a["step"] == 4 and a["round"] == 0
    flights = [r for r in recs if r["kind"] == "flight"]
    assert len(flights) == 1
    f = flights[0]
    # ring depth 3: exactly the last three step records, then cleared
    assert f["n_records"] == 3
    assert [r["step"] for r in f["records"]] == [2, 3, 4]
    assert not bank.ring
    assert reg.counters["anomalies"] == 1
    # hbm rise through round records
    for v in [100, 100, 100, 200]:
        bank.observe_round({"round": 1, "hbm_peak_bytes": v})
    recs = [json.loads(l) for l in open(sink)]
    assert [r["metric"] for r in recs if r["kind"] == "anomaly"] \
        == ["examples_per_sec", "hbm_peak_bytes"]


def test_sentinel_bank_empty_ring_writes_nothing(tmp_path):
    reg = MetricsRegistry()
    reg.configure_sink(f"jsonl:{tmp_path}/m.jsonl")
    bank = SentinelBank(reg)
    bank.flight_dump("nothing happened yet")
    assert open(f"{tmp_path}/m.jsonl").read() == ""


# -------------------------------------------------------------- CLI helpers

def _train_conf(tmp_path, extra=""):
    from test_main import MLP_NET, _write_synth_mnist
    _write_synth_mnist(tmp_path, n=64)
    conf = tmp_path / "train.conf"
    conf.write_text(f"""
dev = cpu:0
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
num_round = 2
metric = error
model_dir = {tmp_path}/models
save_model = 0
silent = 1
print_step = 2
{extra}
""")
    return conf


def _records(sink):
    return [json.loads(l) for l in open(sink)]


# --------------------------------------------------- layer_profile e2e (CPU)

def test_layer_profile_record_cpu_end_to_end(tmp_path):
    """The acceptance path: a CPU MNIST run with a profiling window
    emits a layer_profile whose rows sum to the traced op total (well
    within the 10% bound) and whose rows name the MLP's layers — the
    compiled-HLO join, since CPU traces carry no scope paths."""
    from cxxnet_tpu.main import LearnTask
    sink = tmp_path / "metrics.jsonl"
    conf = _train_conf(tmp_path, f"""
prof = {tmp_path}/prof
metrics_sink = jsonl:{sink}
""")
    assert LearnTask().run([str(conf)]) == 0
    lps = [r for r in _records(sink) if r["kind"] == "layer_profile"]
    assert len(lps) == 1
    lp = lps[0]
    assert lp["steps"] >= 1 and lp["round"] == 1
    rows_sum = sum(r["device_ms"] for r in lp["rows"])
    assert rows_sum == pytest.approx(lp["ops_total_ms"], rel=1e-3)
    assert abs(rows_sum - lp["device_total_ms"]) \
        <= 0.1 * lp["device_total_ms"]
    layers = {r["layer"] for r in lp["rows"]}
    assert "00-fc1" in layers and "02-fc2" in layers
    assert lp["coverage"] > 0.3
    fc1 = next(r for r in lp["rows"] if r["layer"] == "00-fc1")
    # analytic cost columns rode along (3x train mult, 2*MACs, b16)
    assert fc1["flops"] == pytest.approx(3 * 2 * 16 * 144 * 32)
    assert "mfu_pct" not in fc1  # no made-up CPU peak
    # trace record from the same window
    assert [r for r in _records(sink) if r["kind"] == "trace"]


def test_prof_every_recurring_windows(tmp_path):
    from cxxnet_tpu.main import LearnTask
    sink = tmp_path / "metrics.jsonl"
    conf = _train_conf(tmp_path, f"""
num_round = 4
prof = {tmp_path}/prof
prof_every = 2
prof_num_steps = 1
metrics_sink = jsonl:{sink}
""")
    assert LearnTask().run([str(conf)]) == 0
    recs = _records(sink)
    # rounds 2 and 4 (rounds_done 1 and 3) each traced one dispatch
    traces = [r for r in recs if r["kind"] == "trace"]
    lps = [r for r in recs if r["kind"] == "layer_profile"]
    assert len(traces) == 2 and len(lps) == 2
    assert [r["steps"] for r in lps] == [1, 1]
    assert os.path.isdir(tmp_path / "prof" / "r0001")
    assert os.path.isdir(tmp_path / "prof" / "r0003")
    assert sorted(r["round"] for r in lps) == [1, 3]


def test_prof_every_conflict_with_start_step_warns(tmp_path, capsys):
    from cxxnet_tpu.main import LearnTask
    conf = _train_conf(tmp_path, f"""
num_round = 1
prof = {tmp_path}/prof
prof_every = 2
prof_start_step = 1
prof_num_steps = 1
""")
    assert LearnTask().run([str(conf)]) == 0
    assert "prof_every ignored" in capsys.readouterr().err
    # the one-shot step window still ran
    import glob
    assert glob.glob(str(tmp_path / "prof" / "**" / "*.xplane.pb"),
                     recursive=True)


# --------------------------------------------- flight recorder on divergence

def test_training_diverged_dumps_flight_ring(tmp_path):
    """TrainingDiverged lands its nan record, the flight ring, AND the
    sink survives the task-level teardown (the metrics_sink finally
    satellite) — eta = nan poisons the weights deterministically."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.monitor import TrainingDiverged
    sink = tmp_path / "metrics.jsonl"
    conf = _train_conf(tmp_path, f"""
print_step = 1
monitor = 1
monitor_interval = 1
monitor_nan = fatal
sentinel = 1
sentinel_ring = 8
metrics_sink = jsonl:{sink}
""")
    task = LearnTask()
    with pytest.raises(TrainingDiverged):
        task.run([str(conf), "eta=nan"])
    recs = _records(sink)
    kinds = [r["kind"] for r in recs]
    assert "nan" in kinds
    flights = [r for r in recs if r["kind"] == "flight"]
    assert len(flights) == 1
    assert "TrainingDiverged" in flights[0]["reason"]
    assert flights[0]["n_records"] >= 1
    assert all(r["kind"] == "step" for r in flights[0]["records"])
    # the flight dump is the last record of the EXCEPTION path; the
    # task-finally goodput ledger folds it and lands after (the
    # stream's true last record), then teardown closed the sink
    assert kinds[-1] == "ledger"
    assert kinds[-2] == "flight"
    assert task.net.metrics.sink is None  # closed, not leaked


def test_training_diverged_flushes_open_profile_window(tmp_path):
    """A mid-round raise inside an OPEN profiling window still lands
    that window's trace + layer_profile records (the task-finally
    flush) — the incident window is the one you most want to read."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.monitor import TrainingDiverged
    sink = tmp_path / "metrics.jsonl"
    conf = _train_conf(tmp_path, f"""
print_step = 1
monitor = 1
monitor_interval = 1
monitor_nan = fatal
prof = {tmp_path}/prof
prof_start_step = 0
prof_num_steps = 100
metrics_sink = jsonl:{sink}
""")
    with pytest.raises(TrainingDiverged):
        LearnTask().run([str(conf), "eta=nan"])
    kinds = [r["kind"] for r in _records(sink)]
    assert "nan" in kinds
    assert "trace" in kinds and "layer_profile" in kinds


# ----------------------------------------------------- pred/extract latency

def test_pred_latency_record(tmp_path):
    from cxxnet_tpu.main import LearnTask
    conf = _train_conf(tmp_path, "save_model = 2\n")
    assert LearnTask().run([str(conf)]) == 0
    sink = tmp_path / "pred_metrics.jsonl"
    pred_conf = tmp_path / "pred.conf"
    from test_main import MLP_NET
    pred_conf.write_text(f"""
dev = cpu:0
task = pred_raw
model_in = {tmp_path}/models/0002.model
pred = {tmp_path}/scores.txt
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
silent = 1
metrics_sink = jsonl:{sink}
""")
    assert LearnTask().run([str(pred_conf)]) == 0
    lats = [r for r in _records(sink) if r["kind"] == "latency"]
    assert len(lats) == 1
    lat = lats[0]
    assert lat["op"] == "pred" and lat["unit"] == "ms"
    assert lat["count"] == 64 // 16
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]


# ------------------------------------------------------- graftlint cross-key

def _lint(cfg_text):
    from cxxnet_tpu.analysis import conflint
    from cxxnet_tpu.utils.config import parse_config_string
    return conflint.lint_pairs(parse_config_string(cfg_text))


def _msgs(findings, key):
    return [f.message for f in findings if f.key == key]


def test_lint_prof_every_rules():
    f = _lint("prof = /tmp/p\nprof_every = 2\nprof_start_step = 5\n")
    assert any("one-shot" in m for m in _msgs(f, "prof_every"))
    f = _lint("prof_every = 2\n")
    assert any("without prof" in m for m in _msgs(f, "prof_every"))
    f = _lint("prof = /tmp/p\nprof_every = 2\nmonitor = 1\n"
              "multi_step = 8\n")
    assert any("per-batch dispatch" in m for m in _msgs(f, "prof_every"))
    # clean recurring config: no prof_every findings
    f = _lint("prof = /tmp/p\nprof_every = 2\nprof_num_steps = 4\n")
    assert not _msgs(f, "prof_every")


def test_lint_sentinel_rules():
    f = _lint("sentinel = 1\n")
    assert any("metrics_sink" in m for m in _msgs(f, "sentinel"))
    f = _lint("sentinel = 1\nmetrics_sink = jsonl:/tmp/m.jsonl\n")
    assert not _msgs(f, "sentinel")
    f = _lint("sentinel_rel = 0.5\n")
    assert any("without sentinel" in m for m in _msgs(f, "sentinel_rel"))


# ------------------------------------------------------------- obsv.py CLI

def test_obsv_cli_table_and_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsv.py"),
         REPORT_FIXTURE], check=True, capture_output=True, text=True,
        cwd=REPO).stdout
    assert "throughput:" in out and "breakdown" in out
    assert "00-conv" in out and "roofline_ms" in out
    assert "anomalies: 1" in out and "examples_per_sec" in out
    assert "pred" in out and "p99" in out
    assert "NON-FINITE" in out
    js = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsv.py"),
         REPORT_FIXTURE, "--json"], check=True, capture_output=True,
        text=True, cwd=REPO).stdout
    rep = json.loads(js)
    assert rep["layers"]["coverage"] == pytest.approx(0.9141)
    assert rep["layers"]["rows"][0]["layer"] == "00-conv"
    assert rep["throughput"]["best"] == 24400.0
    assert rep["comm"]["comm_share"] == pytest.approx(0.1149)
    assert rep["anomalies"][0]["metric"] == "examples_per_sec"
    assert rep["latency"][0]["p95"] == 5.2
    assert rep["flights"] == 1


def test_obsv_cli_trace_reattribution():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsv.py"),
         REPORT_FIXTURE, "--trace", FIXTURE], check=True,
        capture_output=True, text=True, cwd=REPO).stdout
    assert "trace re-attribution" in out
    assert "00-conv" in out and "03-fullc" in out


def test_obsv_cli_empty_file_errors(tmp_path):
    p = tmp_path / "empty.jsonl"
    p.write_text("")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsv.py"),
         str(p)], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    assert "no records" in r.stderr


# ------------------------------------------------------ step_hlo_text joins

def test_step_hlo_text_carries_scopes():
    from __graft_entry__ import _make_trainer
    from test_monitor import TINY_MLP
    t = _make_trainer(TINY_MLP, 16, "cpu:0")
    txt = t.step_hlo_text()
    assert txt is not None
    scopes = t.layer_scopes()
    assert scopes == ["00-fc1", "01-relu", "02-fc2", "03-softmax"]
    op_scopes = attribution.hlo_op_scopes(txt, scopes)
    hit = {s for s in op_scopes.values() if s}
    assert "00-fc1" in hit and "02-fc2" in hit
    # cached: the second call is the same object (one AOT compile total)
    assert t.step_hlo_text() is txt


# ------------------------------------------------- jax-free fast path

def test_obsv_fast_path_stays_jax_free():
    """Importing the package (and the monitor read-side obsv.py uses)
    must NOT pull in jax — the PEP 562 lazy surface in
    cxxnet_tpu/__init__.py keeps ~2.7 s of import cost off every
    tools/obsv.py invocation.  Subprocess-asserted so a stray eager
    import anywhere on this path fails loudly."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "import cxxnet_tpu\n"
         "from cxxnet_tpu.monitor import diff, ledger, metrics, spans\n"
         "assert 'jax' not in sys.modules, 'jax leaked into fast path'\n"
         "assert 'cxxnet_tpu.nnet' not in sys.modules\n"
         "cxxnet_tpu.NetTrainer  # lazy surface still resolves\n"
         "assert 'jax' in sys.modules  # ...by importing on demand\n"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr


def test_obsv_cli_runs_without_jax_import():
    """The obsv CLI over the checked-in fixture: the report path must
    work end to end in a jax-free interpreter (jax hidden from the
    subprocess via a poisoned meta-path entry, so an accidental lazy
    trigger fails rather than silently paying the import)."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "class _NoJax:\n"
         "    def find_module(self, name, path=None):\n"
         "        if name == 'jax' or name.startswith('jax.'):\n"
         "            raise ImportError('jax import on the fast path')\n"
         "sys.meta_path.insert(0, _NoJax())\n"
         "sys.argv = ['obsv', r'%s', '--json']\n"
         "sys.path.insert(0, 'tools')\n"
         "import runpy\n"
         "runpy.run_path('tools/obsv.py', run_name='__main__')\n"
         % REPORT_FIXTURE],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr
    json.loads(r.stdout)
