"""Request-path span tracing (monitor/spans.py — ISSUE 11).

The contracts the p99 decomposition stands on:

* **off = free**: with ``trace_sample = 0`` (or no sink) the tracer
  emits ZERO records and allocates nothing on the hot path;
* **sampling**: ``trace_sample = N`` traces exactly every Nth request,
  and concurrent submitters get disjoint, well-formed trace_ids;
* **complete chains**: every sampled request's spans tile its
  end-to-end wall — queue_wait + coalesce + dispatch + respond sums to
  its ``request`` span (== ``serve_latency_sec``) within 5%, and the
  dispatch span names it as a rider;
* **read side**: ``stage_decomposition`` and ``tools/spans2trace.py``
  agree with the records (percentiles, rider weighting, flow links);
* **sentinels**: the serve-side EWMA watchers fire on p99 rise / QPS
  drop / queue-depth rise over ``serve_window`` records.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.monitor import spans as spans_mod
from cxxnet_tpu.monitor.metrics import MetricsRegistry
from cxxnet_tpu.monitor.sentinel import SentinelBank
from cxxnet_tpu.monitor.spans import (SpanTracer, span_records,
                                      stage_decomposition)
from cxxnet_tpu.serve.batcher import MicroBatcher


def _registry(tmp_path, sample=1, name="m.jsonl"):
    reg = MetricsRegistry()
    reg.configure_sink(f"jsonl:{tmp_path / name}")
    reg.configure_tracer(sample)
    return reg, str(tmp_path / name)


def _read(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# --------------------------------------------------------------- tracer units

def test_disabled_tracer_emits_nothing(tmp_path):
    """trace_sample = 0 (the default): no ids, no records — and the
    span() fast path returns the SHARED no-op (no allocation)."""
    reg, path = _registry(tmp_path, sample=0)
    tr = reg.tracer
    assert not tr.enabled
    assert tr.new_trace() is None
    s1 = tr.span("queue_wait")
    s2 = tr.span("device", bucket=8)
    assert s1 is s2  # the singleton no-op context manager
    with s1:
        pass
    tr.emit("dispatch", 0.0, 1.0, riders=[1])
    assert tr.begin("x") is None
    tr.end(None)
    reg.close()
    assert span_records(_read(path)) == []


def test_tracer_needs_active_sink(tmp_path):
    """Armed but sinkless = still disabled (span records ride the
    JSONL sink; nowhere to land means zero work)."""
    reg = MetricsRegistry()
    reg.configure_tracer(1)
    assert not reg.tracer.enabled
    assert reg.tracer.new_trace() is None
    reg.configure_sink(f"jsonl:{tmp_path / 'm.jsonl'}")
    assert reg.tracer.enabled
    assert reg.tracer.new_trace() == 1
    reg.close()
    # sink closed -> disarmed again, mid-flight
    assert not reg.tracer.enabled
    assert reg.tracer.new_trace() is None


def test_sampling_every_nth(tmp_path):
    reg, _ = _registry(tmp_path, sample=3)
    ids = [reg.tracer.new_trace() for _ in range(9)]
    assert [i is not None for i in ids] == [True, False, False] * 3
    assert [i for i in ids if i is not None] == [1, 2, 3]
    reg.close()


def test_concurrent_trace_ids_disjoint(tmp_path):
    """Concurrent submitters must get disjoint, well-formed ids —
    the one lock the hot path takes."""
    reg, _ = _registry(tmp_path, sample=1)
    got = []
    lock = threading.Lock()

    def worker():
        mine = [reg.tracer.new_trace() for _ in range(200)]
        with lock:
            got.extend(mine)

    ths = [threading.Thread(target=worker) for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert all(isinstance(i, int) for i in got)
    assert len(set(got)) == 1600  # disjoint
    reg.close()


def test_span_nesting_and_begin_end(tmp_path):
    """Nested context-manager spans and the explicit begin/end API
    produce records whose intervals actually nest."""
    reg, path = _registry(tmp_path, sample=1)
    tr = reg.tracer
    with tr.span("dispatch", rows=4):
        tok = tr.begin("device", bucket=4)
        time.sleep(0.002)
        tr.end(tok)
    reg.close()
    recs = {r["span"]: r for r in span_records(_read(path))}
    disp, dev = recs["dispatch"], recs["device"]
    assert disp["rows"] == 4 and dev["bucket"] == 4
    # containment: device starts after dispatch and ends before it
    assert disp["us"] <= dev["us"]
    assert dev["us"] + dev["dur_us"] <= disp["us"] + disp["dur_us"]
    assert dev["dur_us"] >= 1500


def test_link_attaches_riders_thread_locally(tmp_path):
    reg, path = _registry(tmp_path, sample=1)
    tr = reg.tracer
    with tr.link([7, 8]):
        with tr.span("device", bucket=2):
            pass
    with tr.span("device", bucket=2):  # outside the link: no riders
        pass
    reg.close()
    devs = [r for r in span_records(_read(path)) if r["span"] == "device"]
    assert devs[0].get("riders") == [7, 8]
    assert "riders" not in devs[1]


def test_null_tracer_is_inert():
    tr = spans_mod.NULL
    assert tr.new_trace() is None and not tr.enabled
    with tr.span("x"):
        pass
    with tr.link([1]):
        pass
    tr.end(tr.begin("x"))
    tr.emit("x", 0.0, 1.0)


def test_stage_decomposition_rider_weighting():
    """A batch-level span counts once PER RIDER (each rider experienced
    that dispatch); shares are fractions of summed request wall."""
    recs = [
        {"kind": "span", "span": "queue_wait", "us": 0, "dur_us": 1000,
         "trace_id": 1},
        {"kind": "span", "span": "queue_wait", "us": 0, "dur_us": 3000,
         "trace_id": 2},
        {"kind": "span", "span": "dispatch", "us": 1000, "dur_us": 4000,
         "riders": [1, 2]},
        {"kind": "span", "span": "request", "us": 0, "dur_us": 6000,
         "trace_id": 1},
        {"kind": "span", "span": "request", "us": 0, "dur_us": 8000,
         "trace_id": 2},
        {"kind": "step"},  # not a span: ignored
    ]
    dec = stage_decomposition(recs)
    assert dec["requests"] == 2
    by = {s["stage"]: s for s in dec["stages"]}
    assert by["dispatch"]["count"] == 2          # once per rider
    assert by["dispatch"]["total_ms"] == 8.0     # 4 ms x 2 riders
    assert by["queue_wait"]["p99_ms"] == 3.0
    assert by["queue_wait"]["p50_ms"] == 1.0
    assert abs(by["dispatch"]["share"] - 8.0 / 14.0) < 1e-4  # 4-dp round


# ------------------------------------------------------------- batcher e2e

def _run_traced_batcher(reg, n_clients=6, sleep=0.004):
    def runner(x):
        time.sleep(sleep)
        return x * 2.0

    b = MicroBatcher(runner, max_batch=8, max_wait_ms=20.0, metrics=reg)
    b.start()
    outs = {}

    def client(i):
        outs[i] = b.submit(np.full((1, 4), float(i), np.float32))

    ths = [threading.Thread(target=client, args=(i,))
           for i in range(n_clients)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    b.close()
    for i in range(n_clients):
        np.testing.assert_array_equal(outs[i], np.full((1, 4), 2.0 * i))
    return b


def test_batcher_span_chain_complete_and_sums(tmp_path):
    """The acceptance contract: every traced request has a complete
    chain, queue_wait + coalesce + dispatch + respond tiles its
    ``request`` span (== serve_latency_sec) within 5%, and exactly one
    dispatch names it as a rider."""
    reg, path = _registry(tmp_path, sample=1)
    _run_traced_batcher(reg)
    reg.close()
    spans = span_records(_read(path))
    per_req = {}
    for r in spans:
        if r.get("trace_id") is not None:
            per_req.setdefault(r["trace_id"], {})[r["span"]] = r
    assert len(per_req) == 6
    dispatches = [r for r in spans if r["span"] == "dispatch"]
    for tid, chain in per_req.items():
        assert set(chain) == {"queue_wait", "coalesce", "respond",
                              "request"}
        mine = [d for d in dispatches if tid in d["riders"]]
        assert len(mine) == 1
        total = chain["request"]["dur_us"]
        stages = (chain["queue_wait"]["dur_us"]
                  + chain["coalesce"]["dur_us"] + mine[0]["dur_us"]
                  + chain["respond"]["dur_us"])
        assert abs(stages - total) / total < 0.05, (tid, stages, total)
        # the chain is ordered and contiguous on the shared clock
        assert chain["queue_wait"]["us"] <= chain["coalesce"]["us"] \
            <= mine[0]["us"] <= chain["respond"]["us"]
    # rider lists cover every traced request, and the latency histogram
    # saw the same population
    assert sorted(i for d in dispatches for i in d["riders"]) \
        == sorted(per_req)
    assert reg.histograms["serve_latency_sec"].count == 6


def test_batcher_sampled_tracing(tmp_path):
    """trace_sample = 2: half the requests traced, the other half pay
    nothing — and the dispatch riders only name the sampled ones."""
    reg, path = _registry(tmp_path, sample=2)
    _run_traced_batcher(reg, n_clients=8)
    reg.close()
    spans = span_records(_read(path))
    traced = {r["trace_id"] for r in spans if r.get("trace_id")}
    assert len(traced) == 4
    riders = [i for r in spans if r["span"] == "dispatch"
              for i in r["riders"]]
    assert sorted(riders) == sorted(traced)


def test_batcher_spans_off_is_silent(tmp_path):
    """The acceptance contract's off half: tracing disabled, the serve
    path emits ZERO span records (the serve record kinds it always
    emitted still land)."""
    reg, path = _registry(tmp_path, sample=0)
    b = _run_traced_batcher(reg)
    reg.close()
    recs = _read(path)
    assert span_records(recs) == []
    assert b.n_requests == 6  # served normally
    assert reg.histograms["serve_latency_sec"].count == 6


def test_oversize_and_carry_requests_keep_chains(tmp_path):
    """A multi-row request that overflows the open batch (the carry
    path) still gets a contiguous chain: its coalesce span stretches
    into the NEXT dispatch."""
    reg, path = _registry(tmp_path, sample=1)

    def runner(x):
        time.sleep(0.003)
        return x + 1.0

    b = MicroBatcher(runner, max_batch=4, max_wait_ms=15.0, metrics=reg)
    b.start()
    outs = {}

    def client(i, n):
        outs[i] = b.submit(np.full((n, 2), float(i), np.float32))

    ths = [threading.Thread(target=client, args=(i, n))
           for i, n in enumerate((3, 3, 2, 3))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    b.close()
    reg.close()
    spans = span_records(_read(path))
    per_req = {}
    for r in spans:
        if r.get("trace_id") is not None:
            per_req.setdefault(r["trace_id"], {})[r["span"]] = r
    dispatches = [r for r in spans if r["span"] == "dispatch"]
    assert len(per_req) == 4 and len(dispatches) >= 2
    for tid, chain in per_req.items():
        mine = [d for d in dispatches if tid in d["riders"]]
        assert len(mine) == 1
        total = chain["request"]["dur_us"]
        stages = (chain["queue_wait"]["dur_us"]
                  + chain["coalesce"]["dur_us"] + mine[0]["dur_us"]
                  + chain["respond"]["dur_us"])
        assert abs(stages - total) / max(total, 1) < 0.05


# ------------------------------------------------------------ serve sentinels

def _bank(tmp_path, rel=0.2, warmup=3):
    reg, path = _registry(tmp_path, sample=0)
    return SentinelBank(reg, rel=rel, warmup=warmup, ring=8), reg, path


def test_serve_sentinel_p99_rise_fires(tmp_path):
    bank, reg, path = _bank(tmp_path)
    for w in range(5):
        bank.observe_serve({"window": w, "requests": 100, "qps": 100.0,
                            "p99_ms": 10.0, "queue_depth": 1})
    assert not bank.anomalies
    bank.observe_serve({"window": 5, "requests": 100, "qps": 100.0,
                        "p99_ms": 25.0, "queue_depth": 1})
    reg.close()
    hits = [a for a in bank.anomalies if a["metric"] == "serve_p99_ms"]
    assert len(hits) == 1 and hits[0]["direction"] == "rise"
    assert hits[0]["window"] == 5
    # the flight dump carried the serve windows leading into it
    kinds = [r["kind"] for r in _read(path)]
    assert "anomaly" in kinds and "flight" in kinds


def test_serve_sentinel_qps_drop_and_depth_rise(tmp_path):
    bank, reg, _ = _bank(tmp_path)
    for w in range(5):
        bank.observe_serve({"window": w, "requests": 200, "qps": 200.0,
                            "p99_ms": 8.0, "queue_depth": 4})
    bank.observe_serve({"window": 5, "requests": 100, "qps": 90.0,
                        "p99_ms": 8.0, "queue_depth": 9})
    reg.close()
    metrics = {a["metric"] for a in bank.anomalies}
    assert metrics == {"serve_qps", "serve_queue_depth"}


def test_serve_sentinel_state_roundtrip(tmp_path):
    """The serve watchers ride the same resume-state contract as the
    training ones (SentinelBank.state/set_state)."""
    bank, reg, _ = _bank(tmp_path)
    for w in range(4):
        bank.observe_serve({"window": w, "requests": 10, "qps": 50.0,
                            "p99_ms": 12.0, "queue_depth": 0})
    st = bank.state()
    bank2 = SentinelBank(reg, rel=0.2, warmup=3, ring=8)
    bank2.set_state(st)
    s = bank2.sentinels["serve_p99_ms"]
    assert s.seen == 4 and abs(s.ewma.mean - 12.0) < 1e-9
    reg.close()


def test_task_serve_sentinel_config_keys():
    from cxxnet_tpu.serve import ServeConfig
    cfg = ServeConfig.from_pairs([("serve_sentinel", "1"),
                                  ("serve_sentinel_window", "0.25")])
    assert cfg.sentinel == 1 and cfg.sentinel_window == 0.25
    with pytest.raises(ValueError, match="serve_sentinel_window"):
        ServeConfig(sentinel_window=0.0)


# ------------------------------------------------------------ lint rules

def _lint(pairs):
    from cxxnet_tpu.analysis.conflint import lint_pairs
    return lint_pairs(pairs)


def test_lint_trace_sample_without_sink_warns():
    finds = _lint([("task", "train"), ("trace_sample", "100")])
    assert any(f.key == "trace_sample" and f.severity == "warn"
               for f in finds)
    finds = _lint([("task", "train"), ("trace_sample", "100"),
                   ("metrics_sink", "jsonl:/tmp/m.jsonl")])
    assert not any(f.key == "trace_sample" for f in finds)


def test_lint_trace_sample_bounds():
    finds = _lint([("trace_sample", "-1")])
    assert any(f.key == "trace_sample" and f.severity in ("warn", "error")
               for f in finds)


def test_lint_serve_sentinel_rules():
    # serve sentinel keys off task=serve warn
    finds = _lint([("task", "train"), ("serve_sentinel", "1")])
    assert any(f.key == "serve_sentinel" and "task = serve" in f.message
               for f in finds)
    # on-task, without a sink: warn
    finds = _lint([("task", "serve"), ("model_in", "m.model"),
                   ("serve_sentinel", "1")])
    assert any(f.key == "serve_sentinel" and "metrics_sink" in f.message
               for f in finds)
    # window without the sentinel: warn
    finds = _lint([("task", "serve"), ("model_in", "m.model"),
                   ("serve_sentinel_window", "0.5")])
    assert any(f.key == "serve_sentinel_window" for f in finds)


# ------------------------------------------------------- exporters / obsv

def test_spans2trace_export(tmp_path):
    reg, path = _registry(tmp_path, sample=1)
    _run_traced_batcher(reg)
    reg.close()
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import spans2trace
    trace = spans2trace.build_trace(spans2trace.load_spans(path))
    evs = trace["traceEvents"]
    assert evs, "no events exported"
    # every slice is well-formed Chrome trace-event JSON (and the whole
    # object round-trips)
    json.loads(json.dumps(trace))
    slices = [e for e in evs if e["ph"] == "X"]
    assert all(e["dur"] >= 1 and e["ts"] >= 0 for e in slices)
    # thread metadata: one track per host thread seen in the spans
    metas = [e for e in evs if e["ph"] == "M"]
    names = {e["args"]["name"] for e in metas
             if e["name"] == "thread_name"}
    assert any(n.startswith("cxxnet-serve-batcher") for n in names)
    # every named track also carries a sort index, and the dispatcher
    # plane sorts above client threads (admin/scheduler roles are
    # covered by the THREAD_SORT_RANKS table)
    ranked = {e["tid"]: e["args"]["sort_index"] for e in metas
              if e["name"] == "thread_sort_index"}
    tids = {e["tid"] for e in metas if e["name"] == "thread_name"}
    assert set(ranked) == tids
    assert spans2trace.sort_rank("cxxnet-serve-batcher-0") \
        < spans2trace.sort_rank("cxxnet-serve-client-3")
    assert spans2trace.sort_rank("cxxnet-serve-admin") \
        < spans2trace.sort_rank("cxxnet-serve-sentinel")
    assert spans2trace.sort_rank("MainThread") == 90
    # flow events pair up s->f per rider of each dispatch
    starts = [e for e in evs if e["ph"] == "s"]
    finishes = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 6
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    # CLI over the file works and emits one JSON object
    out = str(tmp_path / "trace.json")
    assert spans2trace.main([path, "-o", out]) == 0
    with open(out) as f:
        assert json.load(f)["traceEvents"]


def test_obsv_reports_stage_decomposition(tmp_path):
    reg, path = _registry(tmp_path, sample=1)
    _run_traced_batcher(reg)
    reg.close()
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import obsv
    rep = obsv.build_report(obsv.load_records(path))
    dec = rep["serve_stages"]
    assert dec["requests"] == 6
    stages = {s["stage"] for s in dec["stages"]}
    assert {"queue_wait", "coalesce", "dispatch", "respond"} <= stages
    # render path doesn't blow up on the new sections
    text = obsv.render(rep)
    assert "p99 decomposition" in text


def test_obsv_fixture_has_span_and_window_records():
    """The checked-in fixture exercises the new record kinds, keeping
    the lint.sh schema gate honest."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import obsv
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "run_report.jsonl")
    rep = obsv.build_report(obsv.load_records(fixture))
    assert rep["kinds"].get("span", 0) >= 5
    assert rep["serve_stages"]["requests"] == 1
    assert rep["serve_windows"]["windows"] == 1
    # the fixture chain obeys the sum contract the live path asserts
    by = {s["stage"]: s for s in rep["serve_stages"]["stages"]}
    total = sum(by[s]["total_ms"] for s in
                ("queue_wait", "coalesce", "dispatch", "respond"))
    assert abs(total - rep["serve_stages"]["request_ms_total"]) \
        / rep["serve_stages"]["request_ms_total"] < 0.05


# -------------------------------------------------------- prefetch spans

def test_prefetch_spans_producer_and_consumer(tmp_path):
    """DevicePrefetcher emits the producer-side staging span and the
    consumer-side wait span when traced — and nothing when not."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.io.device_prefetch import DevicePrefetcher

    class _FakeBase:
        def __init__(self, n=4):
            self.n = n
            self.i = 0

        def before_first(self):
            self.i = 0

        def next(self):
            if self.i >= self.n:
                return None
            self.i += 1
            return DataBatch(
                data=np.zeros((2, 3), np.float32),
                label=np.zeros((2, 1), np.float32),
                index=np.arange(2, dtype=np.uint32))

    class _FakeStager:
        def stage_batch(self, b):
            return b

        def stage_group(self, g):
            return g

        def stage_eval_group(self, g):
            return g

    for sample, expect in ((1, True), (0, False)):
        reg, path = _registry(tmp_path, sample=sample,
                              name=f"pf{sample}.jsonl")
        pf = DevicePrefetcher(_FakeBase(), _FakeStager(), depth=2,
                              metrics=reg)
        items = list(pf)
        pf.close()
        reg.close()
        assert len(items) == 4
        spans = span_records(_read(path))
        names = {r["span"] for r in spans}
        if expect:
            assert {"prefetch_stage", "prefetch_wait"} <= names
        else:
            assert spans == []
