"""I/O pipeline tests: mnist reader, batch adapter round_batch protocol,
threadbuffer prefetch, membuffer, attachtxt join, imbin pack/read round trip,
iterator chain factory, determinism."""

import gzip
import os
import struct

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch, DataInst, IIterator
from cxxnet_tpu.io.factory import create_iterator, init_iterator
from cxxnet_tpu.io.iter_proc import (AttachTxtIterator, BatchAdaptIterator,
                                     DenseBufferIterator,
                                     ThreadBufferIterator)


class ListInstIterator(IIterator):
    """Test helper: instance iterator over given arrays."""

    def __init__(self, data, labels):
        self.data = data
        self.labels = labels
        self.pos = 0

    def before_first(self):
        self.pos = 0

    def next(self):
        if self.pos >= len(self.data):
            return None
        i = self.pos
        self.pos += 1
        return DataInst(label=np.atleast_1d(self.labels[i]),
                        data=self.data[i], index=i)


def make_insts(n, shape=(1, 4, 4)):
    rnd = np.random.RandomState(0)
    return rnd.rand(n, *shape).astype(np.float32), \
        rnd.randint(0, 3, n).astype(np.float32)


def test_batch_adapter_pads_tail_by_default():
    # the tail partial batch is padded + masked rather than dropped
    # (reference AdjustBatchSize trains it; see tests/test_tail_batch.py)
    data, labels = make_insts(10)
    it = BatchAdaptIterator(ListInstIterator(data, labels))
    it.set_param("batch_size", "4")
    it.init()
    batches = list(it)
    assert len(batches) == 3
    assert all(b.batch_size == 4 for b in batches)
    assert [b.tail_mask_padd for b in batches] == [0, 0, 2]


def test_batch_adapter_round_batch_wraps_and_terminates():
    data, labels = make_insts(10)
    it = BatchAdaptIterator(ListInstIterator(data, labels))
    it.set_param("batch_size", "4")
    it.set_param("round_batch", "1")
    it.init()
    batches = list(it)
    assert len(batches) == 3, "round_batch epoch must end after the wrap batch"
    assert batches[2].num_batch_padd == 2
    # the wrapped instances are the first two of the epoch
    np.testing.assert_allclose(batches[2].data[-2:], data[:2])
    # second epoch works identically
    batches2 = list(it)
    assert len(batches2) == 3


def test_batch_adapter_test_skipread():
    data, labels = make_insts(8)
    it = BatchAdaptIterator(ListInstIterator(data, labels))
    it.set_param("batch_size", "4")
    it.set_param("test_skipread", "1")
    it.init()
    it.before_first()
    b1 = it.next()
    b2 = it.next()
    assert b1 is b2, "test_skipread must return the cached batch"


def test_threadbuffer_preserves_stream_and_restarts():
    data, labels = make_insts(12)
    base = BatchAdaptIterator(ListInstIterator(data, labels))
    base.set_param("batch_size", "4")
    it = ThreadBufferIterator(base)
    it.init()
    for _ in range(3):  # several epochs incl. restart mid-epoch
        it.before_first()
        seen = [it.next() for _ in range(2)]
        assert all(b is not None for b in seen)
    it.before_first()
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_allclose(batches[0].data, data[:4])


def test_membuffer_caches_and_loops():
    data, labels = make_insts(12)
    base = BatchAdaptIterator(ListInstIterator(data, labels))
    base.set_param("batch_size", "4")
    it = DenseBufferIterator(base)
    it.set_param("max_nbatch", "2")
    it.init()
    first = list(it)
    assert len(first) == 2
    second = list(it)
    assert len(second) == 2
    np.testing.assert_allclose(first[0].data, second[0].data)


def test_attachtxt_joins_extra_data(tmp_path):
    data, labels = make_insts(8)
    txt = tmp_path / "extra.txt"
    with open(txt, "w") as f:
        for i in range(8):
            f.write(f"{i} {i * 1.0} {i * 2.0}\n")
    base = BatchAdaptIterator(ListInstIterator(data, labels))
    base.set_param("batch_size", "4")
    it = AttachTxtIterator(base)
    it.set_param("path_attach_txt", str(txt))
    it.set_param("extra_data_shape[0]", "1,1,2")
    it.init()
    it.before_first()
    b = it.next()
    assert len(b.extra_data) == 1
    assert b.extra_data[0].shape == (4, 1, 1, 2)
    np.testing.assert_allclose(b.extra_data[0][2, 0, 0], [2.0, 4.0])


def test_mnist_iterator(tmp_path):
    from cxxnet_tpu.io.iter_mnist import MNISTIterator
    img_path = tmp_path / "img.gz"
    lab_path = tmp_path / "lab.gz"
    rnd = np.random.RandomState(0)
    imgs = (rnd.rand(25, 5, 5) * 255).astype(np.uint8)
    labs = rnd.randint(0, 10, 25).astype(np.uint8)
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">iiii", 2051, 25, 5, 5))
        f.write(imgs.tobytes())
    with gzip.open(lab_path, "wb") as f:
        f.write(struct.pack(">ii", 2049, 25))
        f.write(labs.tobytes())
    it = MNISTIterator()
    it.set_param("path_img", str(img_path))
    it.set_param("path_label", str(lab_path))
    it.set_param("batch_size", "10")
    it.set_param("silent", "1")
    it.init()
    batches = list(it)
    assert len(batches) == 3  # tail of 5 replica-padded + masked
    assert batches[2].tail_mask_padd == 5
    np.testing.assert_allclose(batches[2].data[5:],
                               np.repeat(batches[2].data[4:5], 5, axis=0))
    np.testing.assert_allclose(
        batches[0].data.reshape(10, 25),
        imgs[:10].reshape(10, 25).astype(np.float32) / 256.0)
    assert batches[0].label[3, 0] == labs[3]
    # round_batch pads
    it.set_param("round_batch", "1")
    batches = list(it)
    assert len(batches) == 3
    assert batches[2].num_batch_padd == 5


def _fake_jpegs(tmp_path, n=10):
    """Tiny real jpegs via cv2 so the decode path is exercised."""
    import cv2
    root = tmp_path / "imgs"
    os.makedirs(root, exist_ok=True)
    lst = tmp_path / "list.lst"
    rnd = np.random.RandomState(0)
    with open(lst, "w") as f:
        for i in range(n):
            img = (rnd.rand(8, 8, 3) * 255).astype(np.uint8)
            cv2.imwrite(str(root / f"{i}.jpg"), img)
            f.write(f"{i}\t{i % 3}\t{i}.jpg\n")
    return root, lst


def test_imbin_pack_and_iterate(tmp_path):
    from cxxnet_tpu.io.imbin import ImageBinIterator, pack_imbin
    root, lst = _fake_jpegs(tmp_path)
    out = tmp_path / "pack.bin"
    n = pack_imbin(str(lst), str(root), str(out), page_size=1 << 14)
    assert n == 10
    it = ImageBinIterator()
    it.set_param("path_imgbin", str(out))
    it.set_param("path_imglst", str(lst))
    it.set_param("silent", "1")
    it.init()
    insts = list(it)
    assert len(insts) == 10
    assert insts[0].data.shape == (3, 8, 8)
    assert [int(i.label[0]) for i in insts] == [i % 3 for i in range(10)]
    # second epoch identical
    insts2 = list(it)
    assert len(insts2) == 10


def test_imbin_shuffle_keeps_label_pairing(tmp_path):
    """Regression: shuffle must permute image and label together."""
    from cxxnet_tpu.io.imbin import ImageBinIterator, pack_imbin
    import cv2
    root = tmp_path / "imgs"
    os.makedirs(root, exist_ok=True)
    lst = tmp_path / "list.lst"
    # image i is a constant image of value 20*i; label = i
    with open(lst, "w") as f:
        for i in range(10):
            img = np.full((8, 8, 3), i * 20, np.uint8)
            cv2.imwrite(str(root / f"{i}.png"), img)  # png = lossless
            f.write(f"{i}\t{i}\t{i}.png\n")
    out = tmp_path / "pack.bin"
    pack_imbin(str(lst), str(root), str(out), page_size=1 << 13)
    it = ImageBinIterator()
    it.set_param("path_imgbin", str(out))
    it.set_param("path_imglst", str(lst))
    it.set_param("shuffle", "1")
    it.set_param("silent", "1")
    it.init()
    insts = list(it)
    assert len(insts) == 10
    order = []
    for inst in insts:
        val = int(round(inst.data.mean() / 20.0))
        assert int(inst.label[0]) == val, "label/image pairing broken"
        order.append(val)
    assert sorted(order) == list(range(10))


def test_iterator_chain_factory():
    cfg = [("iter", "mnist"), ("batch_size", "4"), ("iter", "threadbuffer"),
           ("iter", "end")]
    it = create_iterator(cfg)
    assert isinstance(it, ThreadBufferIterator)
    from cxxnet_tpu.io.iter_mnist import MNISTIterator
    assert isinstance(it.base, MNISTIterator)
    with pytest.raises(ValueError):
        create_iterator([("iter", "bogus")])


def test_imbin_decode_threads_match_inline(tmp_path):
    """decode_thread_num pipeline yields the same stream as inline decode."""
    from cxxnet_tpu.io.imbin import ImageBinIterator, pack_imbin
    root, lst = _fake_jpegs(tmp_path)
    out = tmp_path / "pack.bin"
    pack_imbin(str(lst), str(root), str(out), page_size=1 << 14)
    streams = []
    for threads in ("0", "3"):
        it = ImageBinIterator()
        it.set_param("path_imgbin", str(out))
        it.set_param("path_imglst", str(lst))
        it.set_param("decode_thread_num", threads)
        it.set_param("silent", "1")
        it.init()
        insts = list(it)
        streams.append([(int(i.index), i.data.sum()) for i in insts])
        # restart mid-epoch: the partially consumed epoch drains fully
        # with no stale futures leaking across the rewind
        it.before_first()
        drained = 0
        while it.next() is not None:
            drained += 1
        assert drained == len(insts)
    assert streams[0] == streams[1]


def test_factory_imgbinx_sets_decode_threads(tmp_path):
    from cxxnet_tpu.io.factory import create_iterator
    it = create_iterator([("iter", "imgbinx")])
    base = it.base.base  # BatchAdapt -> Augment -> ImageBin
    assert base.decode_thread_num == 2
    it2 = create_iterator([("iter", "imgbinx"), ("decode_thread_num", "5")])
    assert it2.base.base.decode_thread_num == 5


def test_threadbuffer_rapid_rewind_stress():
    """Producer-thread lifecycle under rapid rewinds: no deadlock, no
    cross-epoch leakage, stream always restarts from the head (the
    semaphore-protocol discipline of utils/thread_buffer.h, stress-tested)."""
    data, labels = make_insts(24)
    it = ThreadBufferIterator(
        BatchAdaptIterator(ListInstIterator(data, labels)))
    it.set_param("batch_size", "4")
    it.set_param("buffer_size", "2")
    it.init()
    first = None
    for trial in range(25):
        it.before_first()
        b = it.next()
        assert b is not None
        if first is None:
            first = b.data.copy()
        else:
            np.testing.assert_array_equal(b.data, first)
        # consume a random prefix, then abandon the epoch
        for _ in range(trial % 4):
            it.next()
    # a final full epoch still yields every batch exactly once
    it.before_first()
    n = 0
    while it.next() is not None:
        n += 1
    assert n == 6


def test_threadbuffer_producer_exception_propagates():
    """Regression: a raise in base.next() used to kill the producer
    thread silently, leaving the consumer blocked forever on queue.get();
    the exception is now enqueued and re-raised in next()."""
    import threading

    class FailingIter(IIterator):
        def __init__(self, fail_after):
            self.fail_after = fail_after
            self.i = 0

        def before_first(self):
            self.i = 0

        def next(self):
            if self.i >= self.fail_after:
                raise ValueError("corrupt record")
            self.i += 1
            return self.i

    baseline_threads = threading.active_count()
    it = ThreadBufferIterator(FailingIter(2))
    it.init()
    assert it.next() == 1
    assert it.next() == 2
    with pytest.raises(ValueError, match="corrupt record"):
        it.next()
    with pytest.raises(ValueError):
        it.next()  # epoch stays dead — re-raise, never a hang
    # the failed producer exited; a rewind starts a fresh epoch
    it.before_first()
    assert it.next() == 1
    it.close()
    assert threading.active_count() == baseline_threads


def test_threadbuffer_thread_hygiene_across_epochs():
    """No producer-thread accumulation across repeated epochs: one live
    producer at most, and active_count() back to baseline after close()."""
    import threading
    baseline = threading.active_count()
    data, labels = make_insts(12)
    base = BatchAdaptIterator(ListInstIterator(data, labels))
    base.set_param("batch_size", "4")
    it = ThreadBufferIterator(base)
    it.init()
    for _ in range(6):
        it.before_first()
        n = 0
        while it.next() is not None:
            n += 1
        assert n == 3
        assert threading.active_count() <= baseline + 1
    it.close()
    assert threading.active_count() == baseline


def test_imbin_decode_pool_rewind_stress(tmp_path):
    """Decode-pool iterator under rapid rewinds: stale futures from
    abandoned epochs never corrupt the restarted stream."""
    from cxxnet_tpu.io.imbin import ImageBinIterator, pack_imbin
    root, lst = _fake_jpegs(tmp_path, n=12)
    out = tmp_path / "pack.bin"
    pack_imbin(str(lst), str(root), str(out), page_size=1 << 12)
    it = ImageBinIterator()
    it.set_param("path_imgbin", str(out))
    it.set_param("path_imglst", str(lst))
    it.set_param("decode_thread_num", "3")
    it.set_param("silent", "1")
    it.init()
    it.before_first()
    ref = []
    while True:
        inst = it.next()
        if inst is None:
            break
        ref.append((int(inst.index), float(inst.data.sum())))
    for trial in range(15):
        it.before_first()
        seen = []
        for _ in range(trial % 5 + 1):
            inst = it.next()
            if inst is None:
                break
            seen.append((int(inst.index), float(inst.data.sum())))
        assert seen == ref[:len(seen)]
