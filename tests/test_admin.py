"""Live serving control plane (serve/admin.py, monitor/promtext.py,
monitor/slo.py — ISSUE 17, doc/serve.md "Operating a serve host").

Covers the contracts the admin plane stands on: the Prometheus
exposition is golden-stable (one mangling rule, one escaping rule,
counters monotone across scrapes, exact ``le``-bucket histograms);
``/readyz`` tracks the warmup->ready->draining lifecycle through the
real CLI task; a 10 Hz scraper under client load neither perturbs
request p99 past the normal A/B band (judged by the ONE comparison
engine) nor leaks threads; SLO burn rates fire fast-before-slow on a
spike and slow on a simmer; and a sentinel anomaly triggers exactly
one boosted-trace flight capture whose ``serve_flight`` record lands
in the sink.
"""

import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.monitor import promtext
from cxxnet_tpu.monitor.metrics import MetricsRegistry
from cxxnet_tpu.monitor.sentinel import SentinelBank
from cxxnet_tpu.monitor.slo import SloSpec, SloTracker
from cxxnet_tpu.serve.admin import AdminServer, FlightCapture, copy_racy
from cxxnet_tpu.serve.batcher import MicroBatcher

from test_serve import trained_model  # noqa: F401 — registers fixture
from test_serve import _serve_conf


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode("utf-8")


def _admin_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("cxxnet-serve-admin")]


# ------------------------------------------------------------ promtext

def test_promtext_golden():
    """The exposition text is a pure function of the snapshot — exact
    output pinned, so a format drift breaks HERE, not on a scraper."""
    snap = {
        "counters": {"serve_flights": 2, "odd name/x": 1},
        "gauges": {"serve_queue_depth": 3.0},
        "histograms": {"serve_latency_sec": {
            "count": 4, "sum": 0.01, "min": 0.001, "max": 0.004,
            "mean": 0.0025, "last": 0.004,
            "p50": 0.002, "p95": 0.004, "p99": 0.004}},
    }
    text = promtext.render(snap, labels={"model": 'a\\b"c\nd'},
                           hists={"serve_batch_hist": {1: 2, 8: 3}})
    lbl = 'model="a\\\\b\\"c\\nd"'
    assert text == "\n".join([
        '# TYPE cxxnet_odd_name_x_total counter',
        'cxxnet_odd_name_x_total{%s} 1' % lbl,
        '# TYPE cxxnet_serve_flights_total counter',
        'cxxnet_serve_flights_total{%s} 2' % lbl,
        '# TYPE cxxnet_serve_queue_depth gauge',
        'cxxnet_serve_queue_depth{%s} 3' % lbl,
        '# TYPE cxxnet_serve_latency_sec summary',
        'cxxnet_serve_latency_sec{%s,quantile="0.5"} 0.002' % lbl,
        'cxxnet_serve_latency_sec{%s,quantile="0.95"} 0.004' % lbl,
        'cxxnet_serve_latency_sec{%s,quantile="0.99"} 0.004' % lbl,
        'cxxnet_serve_latency_sec_sum{%s} 0.01' % lbl,
        'cxxnet_serve_latency_sec_count{%s} 4' % lbl,
        '# TYPE cxxnet_serve_batch_hist histogram',
        'cxxnet_serve_batch_hist_bucket{le="1",%s} 2' % lbl,
        'cxxnet_serve_batch_hist_bucket{le="8",%s} 5' % lbl,
        'cxxnet_serve_batch_hist_bucket{le="+Inf",%s} 5' % lbl,
        'cxxnet_serve_batch_hist_sum{%s} 26' % lbl,
        'cxxnet_serve_batch_hist_count{%s} 5' % lbl,
    ]) + "\n"
    # and the module's own parser round-trips it, labels unescaped
    fams = promtext.parse(text)
    assert fams["cxxnet_serve_batch_hist"]["type"] == "histogram"
    name, labels, v = fams["cxxnet_serve_flights_total"]["samples"][0]
    assert labels["model"] == 'a\\b"c\nd' and v == 2


def test_promtext_counter_monotonicity():
    """Counters must be non-decreasing across scrapes — the property a
    Prometheus ``rate()`` stands on."""
    reg = MetricsRegistry()
    reg.counter_inc("slo_burns", 3)
    v1 = promtext.counter_values(promtext.parse(
        promtext.render(reg.snapshot())))
    reg.counter_inc("slo_burns", 2)
    v2 = promtext.counter_values(promtext.parse(
        promtext.render(reg.snapshot())))
    for k, v in v1.items():
        assert v2[k] >= v
    assert v2["cxxnet_slo_burns_total"] == 5


def test_promtext_parse_rejects_malformed():
    with pytest.raises(ValueError):
        promtext.parse("# TYPE cxxnet_x enum\ncxxnet_x 1\n")
    with pytest.raises(ValueError):
        promtext.parse("# TYPE cxxnet_x counter\ncxxnet_x one\n")
    with pytest.raises(ValueError):  # counters may never go negative
        promtext.parse("# TYPE cxxnet_x counter\ncxxnet_x_total -1\n")


# ------------------------------------------------------- admin endpoints

class _FakeEngine:
    _traces_at_warmup = 2

    def retraces(self):
        return 0

    def stats(self):
        return {"dispatches": 5}


class _FakeCfg:
    dtype = "bf16"


class _FakeModel:
    def __init__(self, batcher=None):
        self.name = "m"
        self.cfg = _FakeCfg()
        self.engine = _FakeEngine()
        self.retraces = 0
        if batcher is not None:
            self.batcher = batcher

    def footprint(self):
        return {"total_bytes": 4096}


class _FakeHost:
    def __init__(self, model):
        self._m = model
        self.names = [model.name]
        self.ready = False

    def model(self, name):
        return self._m


class _FakeBatcherStats:
    n_requests = 12
    n_batches = 3
    rows_served = 12
    depth_max = 2
    batch_hist = {4: 3}


def test_admin_endpoints_lifecycle():
    """/healthz live from bind; /readyz flips 503 -> 200 -> refused;
    /statusz carries the per-model accounting; /metrics parses."""
    host = _FakeHost(_FakeModel(_FakeBatcherStats()))
    reg = MetricsRegistry()
    reg.observe("serve_latency_sec", 0.002)
    adm = AdminServer(host, reg, port=0, config={"serve_shapes": "1,8"})
    try:
        port = adm.start()
        base = f"http://127.0.0.1:{port}"
        assert _get(base + "/healthz") == (200, "ok\n")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/readyz")
        assert ei.value.code == 503
        host.ready = True
        adm.note_ready()  # footprint cached at ready time
        assert _get(base + "/readyz") == (200, "ready\n")
        adm.note_window("m", {"qps": 50.0, "p99_ms": 3.0,
                              "requests": 25, "queue_depth": 1})
        st = json.loads(_get(base + "/statusz")[1])
        assert st["ready"] is True and st["uptime_sec"] >= 0
        assert st["config"]["serve_shapes"] == "1,8"
        m = st["models"]["m"]
        assert m["kind"] == "predict" and m["requests"] == 12
        assert m["mean_batch"] == 4.0 and m["batch_hist"] == {"4": 3}
        assert m["retraces"] == 0 and m["engine"]["dispatches"] == 5
        assert m["last_window"]["p99_ms"] == 3.0
        assert m["footprint"]["total_bytes"] == 4096
        fams = promtext.parse(_get(base + "/metrics")[1])
        assert "cxxnet_serve_latency_sec" in fams
        assert fams["cxxnet_serve_batch_hist"]["type"] == "histogram"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        assert ei.value.code == 404
    finally:
        adm.close()
    time.sleep(0.1)
    assert not _admin_threads()
    # closed means refused, not hanging
    with pytest.raises(OSError):
        _get(f"http://127.0.0.1:{port}/healthz", timeout=0.5)


def test_copy_racy_survives_concurrent_growth():
    """The scrape path's lock-free dict copy: a dispatcher growing the
    dict mid-copy must never propagate RuntimeError to the scraper."""
    d = {i: i for i in range(64)}
    stop = threading.Event()

    def grow():
        i = 64
        while not stop.is_set():
            d[i] = i
            d.pop(i - 64, None)
            i += 1

    t = threading.Thread(target=grow, daemon=True)
    t.start()
    try:
        for _ in range(200):
            out = copy_racy(d)
            assert isinstance(out, dict)
    finally:
        stop.set()
        t.join()


@pytest.mark.parametrize("attempt_budget", [3])
def test_scrape_under_load_keeps_p99(attempt_budget):
    """ISSUE 17 acceptance: a 10 Hz /metrics + /statusz scraper under
    concurrent client load leaves request p99 inside the normal A/B
    band — judged by the one comparison engine, generous CPU-CI band,
    retried to absorb scheduler noise.  The scrape path takes no
    dispatcher locks, so this holds by construction; the test pins it."""

    def run_once(scrape):
        reg = MetricsRegistry()
        b = MicroBatcher(lambda x: x * 2.0, max_batch=8,
                         max_wait_ms=1.0, metrics=reg, name="serve")
        b.start()
        adm = None
        stop = threading.Event()
        scrapers = []
        try:
            if scrape:
                adm = AdminServer(_FakeHost(_FakeModel(b)), reg, port=0)
                adm.start()
                base = f"http://127.0.0.1:{adm.port}"

                def scraper(path):
                    while not stop.is_set():
                        _get(base + path)
                        stop.wait(0.1)  # 10 Hz

                scrapers = [threading.Thread(target=scraper, args=(p,))
                            for p in ("/metrics", "/statusz")]
                for t in scrapers:
                    t.start()

            def client():
                for _ in range(40):
                    b.submit(np.ones((1, 4), np.float32))

            ths = [threading.Thread(target=client) for _ in range(4)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
        finally:
            stop.set()
            for t in scrapers:
                t.join()
            if adm is not None:
                adm.close()
            b.close()
        return reg.histograms["serve_latency_sec"].summary()["p99"] * 1e3

    for attempt in range(attempt_budget):
        from cxxnet_tpu.monitor.diff import LOWER_BETTER, compare
        p99_off = run_once(scrape=False)
        p99_on = run_once(scrape=True)
        judge = compare("serve_p99_ms", a=p99_off, b=p99_on,
                        rel=1.0, direction=LOWER_BETTER, abs_floor=2.0)
        if not judge["regressed"]:
            break
    else:
        pytest.fail(f"10 Hz scrape regressed p99 in every attempt: "
                    f"{judge}")
    time.sleep(0.1)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-serve")]


# ------------------------------------------------------------------ SLO

def _win(requests, viol):
    return {"requests": requests, "viol": viol}


def test_slo_burn_math_and_fast_before_slow():
    """burn == (viol/requests) / (1 - avail); an acute spike fires the
    fast tier while the slow window still averages it away."""
    spec = SloSpec(p99_ms=10.0, avail=0.99, fast_sec=2.0, slow_sec=10.0,
                   fast_burn=5.0, slow_burn=2.0)
    trk = SloTracker(spec, window_sec=1.0)
    for _ in range(9):
        assert trk.observe(_win(100, 0)) is None
    fired = trk.observe(_win(100, 20))  # fast ring = 2 windows
    assert fired is not None and fired["tier"] == "fast"
    # fast burn: 20/200 err over budget 0.01 -> 10.0 >= 5.0
    assert fired["burn"] == pytest.approx(10.0)
    assert fired["requests"] == 200 and fired["viol"] == 20
    v = trk.verdict
    assert v["fast"]["firing"] and not v["slow"]["firing"]
    # slow burn: 20/1000 / 0.01 = 2.0 — at threshold, NOT over it
    assert v["slow"]["burn"] == pytest.approx(2.0)
    assert not v["ok"]


def test_slo_slow_tier_catches_sustained_burn():
    """A simmering violation rate under the fast threshold still fires
    the slow tier once the long window fills — and the record is
    emitted on the rising edge only (no re-fire while latched)."""
    reg = MetricsRegistry()
    spec = SloSpec(p99_ms=10.0, avail=0.99, fast_sec=2.0, slow_sec=6.0,
                   fast_burn=50.0, slow_burn=2.0)
    trk = SloTracker(spec, window_sec=1.0, metrics=reg, model="m")
    fires = [trk.observe(_win(100, 3)) for _ in range(12)]
    fired = [f for f in fires if f]
    assert len(fired) == 1 and fired[0]["tier"] == "slow"
    assert fired[0]["burn"] == pytest.approx(3.0)
    assert reg.counters["slo_burns"] == 1
    # burn clears -> tier unlatches -> a new excursion fires again
    for _ in range(12):
        trk.observe(_win(100, 0))
    assert trk.verdict["ok"]
    assert any(trk.observe(_win(100, 3)) for _ in range(12))


def test_slo_inactive_without_target():
    trk = SloTracker(SloSpec(p99_ms=0.0), window_sec=1.0)
    assert trk.observe(_win(100, 100)) is None
    assert trk.verdict["active"] is False
    with pytest.raises(ValueError):
        SloSpec(p99_ms=5.0, avail=1.0)  # zero budget has no burn rate


# -------------------------------------------------------- flight capture

def test_sentinel_anomaly_triggers_flight(tmp_path):
    """Serve-sentinel e2e: a p99 regression fires an anomaly, the
    on_anomaly hook arms the flight capture, the capture boosts
    trace_sample for K requests and lands ONE serve_flight record with
    the window ring and the boosted trace-id range."""
    sink = tmp_path / "m.jsonl"
    reg = MetricsRegistry()
    reg.configure_sink(f"jsonl:{sink}")
    served = [0]
    flight = FlightCapture(reg, lambda: served[0], model="m", boost=1,
                           requests=4, ring=4,
                           stats_fn=lambda: {"depth_max": 1})
    bank = SentinelBank(reg, rel=0.2, warmup=3, ring=8,
                        on_anomaly=lambda hit: flight.trigger(
                            f"anomaly: {hit['metric']} {hit['direction']}"))
    base = {"model": "m", "qps": 100.0, "queue_depth": 0,
            "requests": 50}
    for i in range(5):
        rec = dict(base, window=i + 1, p99_ms=5.0)
        flight.note_window(rec)
        bank.observe_serve(rec)
        assert flight.tick() is None  # nothing armed yet
    spike = dict(base, window=6, p99_ms=50.0)
    flight.note_window(spike)
    bank.observe_serve(spike)
    assert flight.armed
    assert not flight.trigger("second anomaly")  # one flight per storm
    # boosted requests arrive, each drawing a trace id
    for _ in range(4):
        served[0] += 1
        reg.tracer.new_trace()
    rec = flight.tick()
    assert rec is not None and not flight.armed
    assert rec["requests_boosted"] >= 4
    assert rec["trace_last"] >= rec["trace_first"] >= 1
    assert rec["n_windows"] == 4  # ring depth, NOT cleared by the dump
    assert rec["stats"] == {"depth_max": 1}
    assert reg.tracer.sample == 0  # sampling restored
    reg.sink.close()
    kinds = [json.loads(l)["kind"] for l in open(sink)]
    assert kinds.count("anomaly") >= 1
    assert kinds.count("serve_flight") == 1
    assert kinds.index("flight") < kinds.index("serve_flight")


def test_flight_capture_completes_on_dead_air():
    """No traffic after the trigger: max_ticks bounds the capture so
    the record still lands (with zero boosted requests)."""
    reg = MetricsRegistry()
    flight = FlightCapture(reg, lambda: 0, requests=8, max_ticks=3)
    assert flight.trigger("slo: fast burn")
    recs = [flight.tick() for _ in range(3)]
    assert recs[:2] == [None, None] and recs[2] is not None
    assert recs[2]["requests_boosted"] == 0
    assert recs[2]["trace_first"] == recs[2]["trace_last"] == 0


# --------------------------------------------------------------- CLI e2e

def test_cli_admin_readyz_lifecycle(trained_model):  # noqa: F811
    """ISSUE 17 acceptance, through the real CLI: /readyz answers 503
    while the host is still compiling, 200 once warmup pinned the
    executables, refused after close — and the serve record still says
    zero retraces with the admin plane scraping."""
    from cxxnet_tpu.main import LearnTask
    tmp_path, net, model = trained_model
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    conf = _serve_conf(
        tmp_path, net, model,
        extra=f"serve_admin_port = {port}\nserve_sentinel = 1\n"
              "serve_sentinel_window = 0.05\nserve_slo_p99_ms = 250\n")
    base = f"http://127.0.0.1:{port}"
    seen, got = [], {}
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            try:
                code, _ = _get(base + "/readyz", timeout=0.5)
            except urllib.error.HTTPError as e:
                code = e.code
            except OSError:
                code = None  # not bound yet / already closed
            if code is not None and (not seen or seen[-1] != code):
                seen.append(code)
            if code == 200:
                # keep the LAST ready scrape — the first ready tick may
                # precede the first served request's latency sample,
                # and a scrape during the close drain reads ready=False
                try:
                    st = json.loads(_get(base + "/statusz")[1])
                    if st.get("ready"):
                        got["statusz"] = st
                        got["metrics"] = promtext.parse(
                            _get(base + "/metrics")[1])
                except OSError:
                    pass  # host closed between the polls
            stop.wait(0.01)

    poller = threading.Thread(target=poll)
    poller.start()
    try:
        assert LearnTask().run([str(conf)]) == 0
    finally:
        stop.set()
        poller.join()
    # lifecycle: not-ready strictly before ready (warmup gate)
    assert 503 in seen and 200 in seen, seen
    assert seen.index(503) < seen.index(200)
    # the endpoint died with the host
    with pytest.raises(OSError):
        _get(base + "/healthz", timeout=0.5)
    st = got["statusz"]
    assert st["ready"] is True
    assert st["models"]["default"]["retraces"] == 0
    assert st["slo"]["active"] and st["slo"]["p99_ms_target"] == 250.0
    assert "cxxnet_serve_latency_sec" in got["metrics"]
    # zero retraces with the admin plane on — from the run's own record
    recs = [json.loads(l)
            for l in open(tmp_path / "serve_metrics.jsonl")]
    srv = [r for r in recs if r["kind"] == "serve"]
    assert srv and srv[-1]["retraces"] == 0
    wins = [r for r in recs if r["kind"] == "serve_window"]
    assert wins and all("viol" in w for w in wins)  # SLO-armed batcher
    time.sleep(0.1)
    assert not _admin_threads()


# ------------------------------------------------------- obsv --live

def test_obsv_live_renders_serving_tables():
    """tools/obsv.py --live maps one /statusz + /metrics scrape into
    the same report shapes the JSONL path builds."""
    host = _FakeHost(_FakeModel(_FakeBatcherStats()))
    host.ready = True
    reg = MetricsRegistry()
    reg.counter_inc("serve_flights")
    for v in (0.001, 0.002, 0.004):
        reg.observe("serve_latency_sec", v)
    adm = AdminServer(host, reg, port=0)
    try:
        adm.start()
        adm.note_ready()
        adm.note_window("m", {"qps": 80.0, "p99_ms": 4.0,
                              "requests": 40, "queue_depth": 1})
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "tools"))
        import obsv
        rep = obsv.live_report(f"127.0.0.1:{adm.port}")
    finally:
        adm.close()
    assert rep["live"]["ready"] is True and rep["live"]["flights"] == 1
    assert rep["serving"][0]["model"] == "m"
    assert rep["serving"][0]["requests"] == 12
    assert rep["serve_windows"]["p99_ms_max"] == 4.0
    assert rep["latency"][0]["count"] == 3
    assert rep["latency"][0]["p99"] == pytest.approx(4.0)
    text = obsv.render(rep)
    assert "live:" in text and "serving: 1 run(s)" in text


# ------------------------------------------------------------- conflint

def _lint(text):
    from cxxnet_tpu.analysis.conflint import lint_pairs
    from cxxnet_tpu.utils.config import parse_config_string
    return lint_pairs(parse_config_string(text))


def test_conflint_slo_rules():
    base = "task = serve\nserve_sentinel = 1\nmetrics_sink = jsonl:m\n"
    # burn windows must be whole multiples of the reporter window
    f = _lint(base + "serve_sentinel_window = 0.3\n"
                     "serve_slo_p99_ms = 10\nserve_slo_fast_sec = 1\n")
    assert any(x.severity == "error" and "serve_slo_fast_sec" == x.key
               for x in f)
    # SLO without the sentinel reporter: no window stream to judge
    f = _lint("task = serve\nserve_slo_p99_ms = 10\n")
    assert any(x.severity == "warn" and x.key == "serve_slo_p99_ms"
               for x in f)
    # flight knobs without a sentinel: nothing can ever trigger
    f = _lint("task = serve\nserve_flight_requests = 8\n")
    assert any(x.severity == "warn" and x.key == "serve_flight_requests"
               for x in f)
    # fast window >= slow window defeats the two-tier split
    f = _lint(base + "serve_slo_p99_ms = 10\nserve_slo_fast_sec = 600\n"
                     "serve_slo_slow_sec = 60\n")
    assert any(x.severity == "warn" and "fast" in x.message.lower()
               for x in f)
    # off-task serve keys warn; the KeySpec range bounds the port
    f = _lint("task = train\nserve_admin_port = 9100\n")
    assert any(x.severity == "warn" for x in f)
    f = _lint("task = serve\nserve_admin_port = 70000\n")
    assert any(x.severity == "warn" and x.key == "serve_admin_port"
               and "65535" in x.message for x in f)
