"""Pipeline parallelism: pipelined stages == sequential composition,
gradients flow, and a full pipelined train step learns (CPU mesh)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from cxxnet_tpu.parallel.pipeline import (pipeline_apply,
                                          pipeline_train_step,
                                          stack_stage_params)
from cxxnet_tpu.parallel.mesh import MeshSpec, build_mesh


def _mesh(n=4, axis="pipe"):
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    return build_mesh(devs, MeshSpec({axis: n}))


def _stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _make_params(n_stage, d, seed=0):
    rnd = np.random.RandomState(seed)
    return [
        {"w": jnp.asarray(rnd.randn(d, d).astype(np.float32) * 0.5),
         "b": jnp.asarray(rnd.randn(d).astype(np.float32) * 0.1)}
        for _ in range(n_stage)]


def test_pipeline_matches_sequential():
    mesh = _mesh(4)
    d, n_micro, mb = 8, 6, 4
    plist = _make_params(4, d)
    stacked = stack_stage_params(plist)
    rnd = np.random.RandomState(1)
    x = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32))
    got = pipeline_apply(_stage, stacked, x, mesh=mesh)
    want = x
    for p in plist:
        want = jax.vmap(lambda m: _stage(p, m))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential():
    mesh = _mesh(4)
    d, n_micro, mb = 8, 5, 2
    plist = _make_params(4, d, seed=2)
    stacked = stack_stage_params(plist)
    rnd = np.random.RandomState(3)
    x = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32))

    def loss_pipe(params):
        return (pipeline_apply(_stage, params, x, mesh=mesh) ** 2).sum()

    def loss_seq(params):
        out = x
        for i in range(4):
            p = jax.tree.map(lambda a: a[i], params)
            out = jax.vmap(lambda m: _stage(p, m))(out)
        return (out ** 2).sum()

    gp = jax.grad(loss_pipe)(stacked)
    gs = jax.grad(loss_seq)(stacked)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_pipeline_train_step_learns():
    mesh = _mesh(4)
    d, n_micro, mb = 8, 4, 8
    stacked = stack_stage_params(_make_params(4, d, seed=4))
    rnd = np.random.RandomState(5)
    x = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32))
    target = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32) * 0.1)

    def loss_fn(out, labels):
        return jnp.mean((out - labels) ** 2)

    step = jax.jit(lambda p: pipeline_train_step(
        _stage, loss_fn, p, x, target, mesh=mesh, lr=0.2))
    loss0 = None
    for i in range(150):
        stacked, loss = step(stacked)
        if i == 0:
            loss0 = float(loss)
    final = float(loss_fn(pipeline_apply(_stage, stacked, x, mesh=mesh),
                          target))
    assert final < 0.2 * loss0, (loss0, final)


def test_1f1b_matches_gpipe_grads():
    """pipeline_1f1b computes the same (loss, grads) as differentiating
    the GPipe fill-drain schedule — the schedule is a pure re-ordering;
    only the residual-memory behavior differs (ring of 2S-1 saved
    microbatch inputs vs all n_micro)."""
    from cxxnet_tpu.parallel.pipeline import pipeline_1f1b
    mesh = _mesh(4)
    # n_micro > ring (2S-1 = 7): the saved-activation ring buffer must
    # wrap for the parity to hold in the deep-pipeline regime
    d, n_micro, mb = 8, 10, 2
    plist = _make_params(4, d, seed=4)
    stacked = stack_stage_params(plist)
    rnd = np.random.RandomState(5)
    x = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32))
    labels = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32))

    def loss_fn(y, lab):
        return ((y - lab) ** 2).sum()

    loss, grads = jax.jit(
        lambda p: pipeline_1f1b(_stage, loss_fn, p, x, labels,
                                mesh=mesh))(stacked)

    def ref(params):
        ys = pipeline_apply(_stage, params, x, mesh=mesh)
        return sum(loss_fn(ys[m], labels[m]) for m in range(n_micro))

    want_loss, want_grads = jax.value_and_grad(ref)(stacked)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(want_grads[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_1f1b_activation_memory_capped():
    """The 1F1B residual footprint is a ring of 2S-1 microbatch inputs
    per stage; GPipe-by-autodiff stores residuals for every scan tick.
    Growing n_micro 8 -> 64 must grow GPipe's temp memory ~8x while
    1F1B's stays flat (measured from XLA's memory analysis on the
    virtual mesh; skipped if the backend doesn't report it)."""
    from cxxnet_tpu.parallel.pipeline import pipeline_1f1b
    mesh = _mesh(4)
    d, mb = 64, 32
    plist = _make_params(4, d, seed=6)
    stacked = stack_stage_params(plist)

    def loss_fn(y, lab):
        return ((y - lab) ** 2).sum()

    def measure(n_micro, which):
        rnd = np.random.RandomState(7)
        x = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32))
        labels = jnp.asarray(rnd.randn(n_micro, mb, d).astype(np.float32))
        if which == "1f1b":
            fn = lambda p: pipeline_1f1b(_stage, loss_fn, p, x, labels,
                                         mesh=mesh)[1]
        else:
            def ref(params):
                ys = pipeline_apply(_stage, params, x, mesh=mesh)
                return sum(loss_fn(ys[m], labels[m])
                           for m in range(n_micro))
            fn = jax.grad(ref)
        comp = jax.jit(fn).lower(stacked).compile()
        mem = comp.memory_analysis()
        size = getattr(mem, "temp_size_in_bytes", None)
        if size is None:
            pytest.skip("backend reports no temp_size_in_bytes")
        return size

    gpipe_8, gpipe_64 = measure(8, "gpipe"), measure(64, "gpipe")
    f1b_8, f1b_64 = measure(8, "1f1b"), measure(64, "1f1b")
    assert gpipe_64 > 4 * gpipe_8, (gpipe_8, gpipe_64)
    assert f1b_64 < 2 * f1b_8, (f1b_8, f1b_64)
