"""Collection-integrity guard: ``pytest --collect-only`` over tests/
must report ZERO collection errors.

The tier-1 command runs with ``--continue-on-collection-errors``, so a
test file that stops importing (a renamed module, a stale symbol) shows
up only as silently-missing dots — every test in the broken file skips
without failing the run.  This guard turns an import break into a real
failure."""

import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_collect_only_has_zero_errors():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "--collect-only",
         "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    tail = (r.stdout + r.stderr)[-4000:]
    assert r.returncode == 0, f"collection failed:\n{tail}"
    assert "error" not in r.stdout.lower().splitlines()[-1], tail
    # sanity: the suite actually collected a healthy number of tests
    m = re.search(r"(\d+) tests? collected", r.stdout)
    assert m, tail
    assert int(m.group(1)) > 200, f"only {m.group(1)} tests collected"
