"""Torch plugin adapter tests: the caffe-adapter-analogue oracle.

Differential strategy mirrors the reference's PairTest usage of the caffe
adapter (``caffe_adapter-inl.hpp:23-24``): the same inputs + weights through
the native TPU layer and through torch must agree in outputs AND gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.layers.base import ForwardContext
from cxxnet_tpu.layers.registry import create_layer
from cxxnet_tpu.plugin import torch_available

from helpers import rand4

pytestmark = pytest.mark.skipif(not torch_available(), reason="torch missing")


def _run_pair(native_name, torch_op, x, cfg):
    native = create_layer(native_name)
    plug = create_layer("torch")
    plug.set_param("op", torch_op)
    for k, v in cfg.items():
        native.set_param(k, str(v))
        plug.set_param(k, str(v))
    shapes = [tuple(x.shape)]
    assert native.infer_shapes(shapes) == plug.infer_shapes(shapes)
    params = native.init_params(jax.random.PRNGKey(7), shapes)
    ctx = ForwardContext(train=False)

    def loss_native(p, xv):
        (o,), _ = native.forward(p, {}, [xv], ctx)
        return (o * o).sum(), o

    def loss_torch(p, xv):
        (o,), _ = plug.forward(p, {}, [xv], ctx)
        return (o * o).sum(), o

    xv = jnp.asarray(x)
    (gn, on), (gt, ot) = [jax.grad(f, argnums=(0, 1), has_aux=True)(params, xv)
                          for f in (loss_native, loss_torch)]
    # forward outputs
    (o_n,), _ = native.forward(params, {}, [xv], ctx)
    (o_t,), _ = plug.forward(params, {}, [xv], ctx)
    np.testing.assert_allclose(np.asarray(o_n), np.asarray(o_t),
                               rtol=1e-4, atol=1e-5)
    # input gradient + weight gradients
    np.testing.assert_allclose(np.asarray(gn[1]), np.asarray(gt[1]),
                               rtol=1e-4, atol=1e-4)
    for tag in params:
        np.testing.assert_allclose(np.asarray(gn[0][tag]),
                                   np.asarray(gt[0][tag]),
                                   rtol=1e-4, atol=1e-4, err_msg=tag)


def test_conv_vs_torch():
    _run_pair("conv", "conv", rand4(2, 4, 9, 9),
              {"nchannel": 6, "kernel_size": 3, "stride": 2, "pad": 1})


def test_grouped_conv_vs_torch():
    _run_pair("conv", "conv", rand4(2, 4, 8, 8),
              {"nchannel": 8, "kernel_size": 3, "ngroup": 2, "pad": 1})


def test_fullc_vs_torch():
    _run_pair("fullc", "fullc", rand4(3, 1, 1, 17), {"nhidden": 5})


def test_activations_vs_torch():
    for op in ("relu", "sigmoid", "tanh"):
        _run_pair(op, op, rand4(2, 3, 4, 4), {})


PAIRTEST_CONF = """
netconfig=start
layer[+1:pt] = pairtest-conv-torch:pt
  slave:op = conv
  nchannel = 4
  kernel_size = 3
  init_sigma = 0.1
layer[+1] = relu
layer[+1] = flatten
layer[+1:fc] = fullc:fc
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,7,7
batch_size = 8
dev = cpu
eta = 0.01
metric = error
"""


def test_pairtest_config_driven_training():
    """The reference's key validation flow: a config embedding
    pairtest-conv-torch trains, and every step's diagnostics carry
    fwd/in-grad/wgrad relative errors that stay ~0 for a faithful slave
    (pairtest_layer-inl.hpp:75-118)."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.io.data import DataBatch
    t = NetTrainer()
    for k, v in parse_config_string(PAIRTEST_CONF):
        t.set_param(k, v)
    t.init_model()
    rnd = np.random.RandomState(0)
    for step in range(3):
        batch = DataBatch(
            data=rnd.rand(8, 3, 7, 7).astype(np.float32),
            label=rnd.randint(0, 3, (8, 1)).astype(np.float32),
            index=np.arange(8, dtype=np.uint32))
        t.update(batch)
        d = {k: float(np.asarray(v)) for k, v in t._last_diags.items()}
        for suffix in ("fwd_rel_err", "in_grad_rel_err", "wgrad_rel_err",
                       "weight_rel_err"):
            (v,) = [d[k] for k in d if k.endswith(suffix)]
            assert v < 5e-4, (step, suffix, v, d)


def test_pairtest_conv_torch_in_net():
    """pairtest-conv-torch reports ~zero forward divergence inside a net
    forward (the reference's config-level differential harness)."""
    layer = create_layer("pairtest-conv-torch")
    layer.set_param("slave:op", "conv")
    for k, v in {"nchannel": 4, "kernel_size": 3}.items():
        layer.set_param(k, str(v))
    shapes = [(2, 3, 7, 7)]
    layer.infer_shapes(shapes)
    params = layer.init_params(jax.random.PRNGKey(0), shapes)
    bufs = layer.init_buffers(shapes)
    ctx = ForwardContext(train=False)
    (out,), _ = layer.forward(params, bufs, [jnp.asarray(rand4(2, 3, 7, 7))], ctx)
    (err,) = [v for k, v in ctx.diagnostics.items() if "fwd_rel_err" in k]
    assert float(err) < 1e-4
