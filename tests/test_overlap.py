"""Bucketed backward-overlapped DP gradient reduction
(cxxnet_tpu/parallel/overlap.py): bitwise trajectory parity against the
implicit-psum step on a CPU ``data:4`` mesh (tail-mask, update_period,
shard_opt_state configs), per-bucket reduction calls visible in the
lowered HLO, deferred once-per-apply reduction, ZeRO reduce-scatter
composition, bf16 wire dtype, and the fallback gates."""

import os
import re
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cxxnet_tpu import engine  # noqa: E402
from cxxnet_tpu.io.data import DataBatch  # noqa: E402

from __graft_entry__ import _make_trainer  # noqa: E402

CONV_NET = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  stride = 2
  nchannel = 8
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 2
  stride = 2
layer[3->4] = flatten
layer[4->5] = fullc:fc1
  nhidden = 32
layer[5->6] = relu
layer[6->7] = fullc:fc2
  nhidden = 4
layer[7->7] = softmax
netconfig=end
input_shape = 3,16,16
metric = error
eta = 0.1
momentum = 0.9
silent = 1
"""

# fc1 (256, 144) = 147k f32: crosses the ZeRO size floor (2^14 leaves)
MLP_ZERO_NET = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 256
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,144
metric = error
eta = 0.1
momentum = 0.9
silent = 1
"""

DP_OPTS = ("dp_overlap", "dp_bucket_mb", "dp_reduce_dtype", "dp_reduce_at")


@pytest.fixture(autouse=True)
def _restore_engine_opts():
    saved = {k: getattr(engine.opts, k) for k in DP_OPTS}
    yield
    for k, v in saved.items():
        engine.opts.set(k, v)


def _batches(n, batch=16, shape=(3, 16, 16), classes=4, tail_padd=0):
    rnd = np.random.RandomState(0)
    out = []
    for i in range(n):
        b = DataBatch(
            data=rnd.rand(batch, *shape).astype(np.float32),
            label=rnd.randint(0, classes, (batch, 1)).astype(np.float32),
            index=np.arange(batch, dtype=np.uint32))
        if tail_padd and i == n - 1:
            b.tail_mask_padd = tail_padd
        out.append(b)
    return out


def _train(net, overlap, extra=(), *, bucket_mb="0.001",
           reduce_at="apply", reduce_dtype="f32", n_steps=4,
           shape=(3, 16, 16), tail_padd=0, mesh="data:4"):
    """One fresh trainer, n_steps updates; returns (losses, params,
    opt_state, trainer).  Engine options are process-global and read at
    trace time, so each run sets them BEFORE its first update and the
    autouse fixture restores them (the experiments/ab.py discipline)."""
    engine.opts.set("dp_overlap", "1" if overlap else "0")
    engine.opts.set("dp_bucket_mb", bucket_mb)
    engine.opts.set("dp_reduce_at", reduce_at)
    engine.opts.set("dp_reduce_dtype", reduce_dtype)
    t = _make_trainer(net, 16, "cpu:0-3", extra=[("mesh", mesh)]
                      + list(extra))
    t.start_round(1)
    losses = []
    for b in _batches(n_steps, shape=shape, tail_padd=tail_padd):
        t.update(b)
        losses.append(float(np.asarray(t._last_loss)))
    return (losses, jax.tree.map(np.asarray, t.params),
            jax.tree.map(np.asarray, t.opt_state), t)


def _assert_trees_equal(a, b, what):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y, err_msg=what)


# ------------------------------------------------------------- parity

@pytest.mark.parametrize("tag,net,extra,kw", [
    ("plain", CONV_NET, (), {}),
    ("tail_mask", CONV_NET, (), {"tail_padd": 5}),
    ("zero", MLP_ZERO_NET, (("shard_opt_state", "1"),),
     {"shape": (1, 1, 144)}),
    # update_period at dp_reduce_at=step: reductions per micro-step, in
    # the implicit path's summation order -> bitwise
    ("update_period", CONV_NET, (("update_period", "2"),),
     {"reduce_at": "step"}),
])
def test_dp_overlap_bitwise_parity(tag, net, extra, kw):
    """dp_overlap=1 trajectory == the implicit-psum DP step, bitwise, at
    dp_reduce_dtype=f32 on a CPU data:4 mesh: per-step losses, final
    params, AND optimizer state (including ZeRO-sharded leaves fed by
    reduce-scatter)."""
    off = _train(net, False, extra, **kw)
    on = _train(net, True, extra, **kw)
    assert off[0] == on[0], f"{tag}: per-step losses must be bitwise equal"
    _assert_trees_equal(off[1], on[1], f"{tag}: params diverged")
    _assert_trees_equal(off[2], on[2], f"{tag}: optimizer state diverged")


def test_dp_overlap_deferred_reduce_once_per_apply():
    """dp_reduce_at=apply (the default): micro-steps run ZERO gradient
    collectives (the accumulate program's only all-reduce is the loss
    scalar), the apply step reduces each bucket once with the
    accumulator folded in.  The cross-chip sum reassociates, so the
    trajectory matches the implicit path to FP tolerance, with losses
    (pure forward) still bitwise."""
    off = _train(CONV_NET, False, (("update_period", "2"),))
    on = _train(CONV_NET, True, (("update_period", "2"),),
                reduce_at="apply")
    assert off[0] == on[0], "forward losses must be bitwise equal"
    for x, y in zip(jax.tree.leaves(off[1]), jax.tree.leaves(on[1])):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-7)
    t = on[3]
    assert t._overlap_defer
    acc_fn, apply_fn = t._build_overlap_steps(False)
    data = jnp.zeros((16, 3, 16, 16), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    rng = jax.random.PRNGKey(0)
    acc = t._grad_acc_init()
    acc_txt = acc_fn.lower(t.params, t.buffers, acc, data, label,
                           jnp.int32(0), rng).as_text()
    apply_txt = apply_fn.lower(t.params, t.opt_state, t.buffers, acc,
                               data, label, jnp.int32(0), rng).as_text()
    assert len(re.findall(r"all_reduce", acc_txt)) == 1, \
        "accumulate micro-step must reduce nothing but the loss scalar"
    assert len(re.findall(r"all_reduce", apply_txt)) >= 3, \
        "apply step must carry the per-bucket reductions"


# ------------------------------------------------------ lowered programs

def test_dp_overlap_hlo_has_per_bucket_reductions():
    """The overlapped step's lowered HLO contains one reduction PER
    BUCKET (>= 2 distinct calls beyond the loss scalar — proving
    per-bucket issue, not one fused end-of-backward reduce); the
    implicit step lowers zero explicit collectives (GSPMD inserts its
    psum later, at partitioning time)."""
    on = _train(CONV_NET, True, n_steps=1)
    t = on[3]
    n_buckets = len(t._dp_overlap_plan().stages)
    assert n_buckets >= 2
    data = jnp.zeros((16, 3, 16, 16), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    args = (t.params, t.opt_state, t.buffers, data, label, (),
            jnp.int32(0), jax.random.PRNGKey(0))
    engine.opts.set("dp_overlap", "1")
    txt = t._train_step.lower(*args).as_text()
    # buckets + the loss psum; >= 2 distinct reductions is the
    # acceptance floor, the plan predicts the exact count
    n_red = len(re.findall(r"all_reduce", txt))
    assert n_red >= 2
    assert n_red >= n_buckets

    off = _train(CONV_NET, False, n_steps=1)
    t0 = off[3]
    txt0 = t0._train_step.lower(
        t0.params, t0.opt_state, t0.buffers, data, label, (),
        jnp.int32(0), jax.random.PRNGKey(0)).as_text()
    assert "all_reduce" not in txt0


def test_dp_overlap_zero_leaves_reduce_scatter():
    """shard_opt_state=1 composes: buckets holding ZeRO-sharded leaves
    REDUCE-SCATTER those grads (each device receives only the shard its
    optimizer state owns) instead of all-reducing."""
    on = _train(MLP_ZERO_NET, True, (("shard_opt_state", "1"),),
                shape=(1, 1, 144), n_steps=1)
    t = on[3]
    assert any(jax.tree.leaves(t.dp_zero_grads)), \
        "test net must have at least one ZeRO-sharded leaf"
    data = jnp.zeros((16, 1, 1, 144), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    engine.opts.set("dp_overlap", "1")
    txt = t._train_step.lower(
        t.params, t.opt_state, t.buffers, data, label, (),
        jnp.int32(0), jax.random.PRNGKey(0)).as_text()
    assert "reduce_scatter" in txt


# ------------------------------------------------------------- variants

def test_dp_overlap_bf16_reduce_dtype():
    """dp_reduce_dtype=bf16: grads cross the wire in bf16, apply stays
    f32-mastered — the trajectory tracks the f32 run loosely (one bf16
    mantissa of reduction noise per step)."""
    f32 = _train(CONV_NET, True, n_steps=3)
    bf16 = _train(CONV_NET, True, n_steps=3, reduce_dtype="bf16")
    assert np.isfinite(bf16[0]).all()
    np.testing.assert_allclose(bf16[0], f32[0], rtol=0.05)
    for x, y in zip(jax.tree.leaves(bf16[1]), jax.tree.leaves(f32[1])):
        np.testing.assert_allclose(x, y, rtol=0.1, atol=5e-3)


def test_dp_overlap_multi_step_scan_parity():
    """update_many (the multi_step grouped dispatch) routes through the
    same overlapped loss_and_grads inside its lax.scan."""
    def run(overlap):
        engine.opts.set("dp_overlap", "1" if overlap else "0")
        engine.opts.set("dp_bucket_mb", "0.0001")
        t = _make_trainer(CONV_NET, 16, "cpu:0-3",
                          extra=[("mesh", "data:4")])
        rnd = np.random.RandomState(0)
        datas = rnd.rand(3, 16, 3, 16, 16).astype(np.float32)
        labels = rnd.randint(0, 4, (3, 16, 1)).astype(np.float32)
        t.start_round(1)
        losses = np.asarray(t.update_many(datas, labels))
        return losses, jax.tree.map(np.asarray, t.params)

    off = run(False)
    on = run(True)
    np.testing.assert_array_equal(off[0], on[0])
    _assert_trees_equal(off[1], on[1], "multi_step params diverged")


def test_dp_overlap_falls_back_for_batch_norm(capsys):
    """Running-buffer layers (batch_norm) can't thread through the
    sliced vjp: the trainer warns once and keeps the implicit step —
    never silently wrong math."""
    net = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 32
layer[1->2] = batch_norm
layer[2->3] = relu
layer[3->4] = fullc:fc2
  nhidden = 4
layer[4->4] = softmax
netconfig=end
input_shape = 1,1,144
metric = error
eta = 0.1
silent = 1
"""
    engine.opts.set("dp_overlap", "1")
    t = _make_trainer(net, 16, "cpu:0-3", extra=[("mesh", "data:4")])
    t.start_round(1)
    (b,) = _batches(1, shape=(1, 1, 144))
    t.update(b)
    assert np.isfinite(float(np.asarray(t._last_loss)))
    err = capsys.readouterr().err
    assert "dp_overlap = 1 ignored" in err and "batch_norm" in err


def test_dp_overlap_single_device_falls_back(capsys):
    """A one-device mesh has nothing to reduce: implicit step, warning."""
    engine.opts.set("dp_overlap", "1")
    t = _make_trainer(CONV_NET, 16, "cpu:0")
    t.start_round(1)
    (b,) = _batches(1)
    t.update(b)
    assert np.isfinite(float(np.asarray(t._last_loss)))
    assert "dp_overlap = 1 ignored" in capsys.readouterr().err


def test_dp_overlap_cli_config_keys(tmp_path):
    """dp_overlap / dp_bucket_mb / dp_reduce_dtype ride the config
    surface end to end: a .conf trains through LearnTask on a data:4
    mesh bitwise-identically with the explicit step on vs off."""
    import json

    from cxxnet_tpu.main import LearnTask
    sys.path.insert(0, os.path.dirname(__file__))
    from test_main import MLP_NET, _write_synth_mnist
    _write_synth_mnist(tmp_path, n=64)
    conf = tmp_path / "dp.conf"
    conf.write_text(f"""
dev = cpu:0-3
mesh = data:4
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
num_round = 2
metric = error
print_step = 1
silent = 1
save_model = 0
dp_bucket_mb = 0.0001
""")
    losses = {}
    for ov in ("0", "1"):
        sink = tmp_path / f"m{ov}.jsonl"
        task = LearnTask()
        assert task.run([str(conf), f"dp_overlap={ov}",
                         f"metrics_sink=jsonl:{sink}"]) == 0
        recs = [json.loads(l) for l in open(sink)]
        losses[ov] = [r["loss"] for r in recs if r["kind"] == "step"]
        engine.opts.set("dp_overlap", "0")
    assert losses["0"] and losses["0"] == losses["1"]


# ------------------------------------------------- 2-D (data x model) mesh

# conv wmat (256, 3, 5, 5) = 19.2k leaves: 4-D (never model-sharded),
# crosses the ZeRO size floor -> reduce-scatter over data; the fullc
# wmats are 2-D with even leading dims -> model-sharded under
# fullc_gather (all-gathered at their segment's forward entry)
MESH_NET = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 5
  stride = 2
  nchannel = 256
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:fc1
  nhidden = 32
layer[4->5] = relu
layer[5->6] = fullc:fc2
  nhidden = 4
layer[6->6] = softmax
netconfig=end
input_shape = 3,16,16
metric = error
eta = 0.1
momentum = 0.9
silent = 1
"""

MESH = "data:2,model:2"


@pytest.mark.parametrize("tag,extra,kw", [
    ("plain", (("fullc_gather", "1"),), {}),
    ("tail_mask", (("fullc_gather", "1"),), {"tail_padd": 5}),
    ("zero", (("fullc_gather", "1"), ("shard_opt_state", "1")), {}),
    # update_period at dp_reduce_at=step: per-micro-step reductions in
    # the implicit path's order -> bitwise on the 2-D mesh too
    ("update_period", (("fullc_gather", "1"), ("update_period", "2")),
     {"reduce_at": "step"}),
])
def test_mesh_overlap_bitwise_parity(tag, extra, kw):
    """The overlapped step on a data:2,model:2 mesh with MODEL-SHARDED
    weights (fullc wmats P("model", None), gathered at segment entry,
    gradients psum'd over data at their bucket's grad-ready point) is
    trajectory-BITWISE-identical to the implicit step with replicated
    weights at f32: per-device compute is identical (the gathered shards
    reconstruct the full weight bit-for-bit; compute replicates across
    model) and the data-axis psum groups are the same 2-member sets."""
    on = _train(MESH_NET, True, extra, mesh=MESH, **kw)
    t = on[3]
    assert any(jax.tree.leaves(t.dp_model_sharded)), \
        "test net must model-shard at least one leaf"
    assert t._dp_overlap_active(), "must run the overlapped step, not " \
        "the fallback"
    # the implicit anchor: same mesh, same net, weights replicated
    # (fullc_gather off) — the model axis then carries redundant compute,
    # exactly what the overlap path's gathered forward computes
    off = _train(MESH_NET, False,
                 tuple(kv for kv in extra if kv[0] != "fullc_gather"),
                 mesh=MESH, **kw)
    assert on[0] == off[0], f"{tag}: per-step losses must be bitwise equal"
    _assert_trees_equal(off[1], on[1], f"{tag}: params diverged")
    _assert_trees_equal(off[2], on[2], f"{tag}: optimizer state diverged")


def test_mesh_overlap_tracks_gspmd_sharded_implicit():
    """Against the implicit step with the SAME model-sharded
    NamedShardings (GSPMD places the tensor-parallel collectives and may
    reassociate contractions), the overlapped trajectory agrees to FP
    tolerance — the sharded implicit path is a different but equivalent
    schedule, not the bitwise anchor."""
    on = _train(MESH_NET, True, (("fullc_gather", "1"),), mesh=MESH)
    off = _train(MESH_NET, False, (("fullc_gather", "1"),), mesh=MESH)
    np.testing.assert_allclose(on[0], off[0], rtol=1e-6)
    for x, y in zip(jax.tree.leaves(on[1]), jax.tree.leaves(off[1])):
        np.testing.assert_allclose(x, y, rtol=1e-4, atol=1e-6)


def test_mesh_overlap_hlo_composes_collectives():
    """The lowered 2-D-mesh overlapped step carries the bucketed
    DATA-axis all-reduces (>= one per bucket) COMPOSED with the
    model-axis weight all-gathers, plus the ZeRO reduce-scatter — the
    acceptance shape for the mesh generalization."""
    on = _train(MESH_NET, True,
                (("fullc_gather", "1"), ("shard_opt_state", "1")),
                n_steps=1, mesh=MESH)
    t = on[3]
    n_buckets = len(t._dp_overlap_plan().stages)
    assert n_buckets >= 2
    n_gather_leaves = sum(jax.tree.leaves(t.dp_model_sharded))
    assert n_gather_leaves >= 2
    assert any(jax.tree.leaves(t.dp_zero_grads))
    data = jnp.zeros((16, 3, 16, 16), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    engine.opts.set("dp_overlap", "1")
    txt = t._train_step.lower(
        t.params, t.opt_state, t.buffers, data, label, (),
        jnp.int32(0), jax.random.PRNGKey(0)).as_text()
    assert len(re.findall(r"all_reduce", txt)) >= n_buckets
    assert len(re.findall(r"all_gather", txt)) >= n_gather_leaves
    assert "reduce_scatter" in txt


def test_mesh_overlap_apply_defer_falls_back_to_step(capsys):
    """dp_reduce_at = apply is pure-DP: on a model mesh the trainer
    warns once and reduces every micro-step (step semantics) — which is
    also the bitwise mode, asserted against the replicated implicit
    run."""
    on = _train(MESH_NET, True,
                (("fullc_gather", "1"), ("update_period", "2")),
                mesh=MESH, reduce_at="apply")
    assert not on[3]._overlap_defer
    assert "pure-DP" in capsys.readouterr().err
    off = _train(MESH_NET, False, (("update_period", "2"),), mesh=MESH,
                 reduce_at="apply")
    assert on[0] == off[0]
    _assert_trees_equal(off[1], on[1], "apply-defer fallback diverged")


def test_mesh_overlap_moe_model_axis_falls_back(capsys):
    """MoE on a model mesh axis: the model axis HOSTS the experts
    (moe.expert_host_axis) and their dispatch/combine all-to-alls are
    GSPMD-placed — dp_overlap warns once and keeps the implicit step
    (the explicit step's mesh-less forward would silently resolve
    moe_dispatch=auto to the differently-associated sorted path)."""
    net = """
netconfig=start
layer[0->1] = embedding
  vocab_size = 32
  nhidden = 16
layer[1->2] = moe
  num_expert = 4
  nhidden = 32
layer[2->3] = seq_fullc
  nhidden = 32
layer[3->3] = softmax_seq
netconfig=end
label_vec[0,8) = label
input_shape = 1,1,8
metric = error
eta = 0.05
updater = adam
silent = 1
"""
    engine.opts.set("dp_overlap", "1")
    t = _make_trainer(net, 8, "cpu:0-3", extra=[("mesh", MESH)])
    t.start_round(1)
    rnd = np.random.RandomState(0)
    toks = rnd.randint(0, 32, (8, 8)).astype(np.float32)
    from cxxnet_tpu.io.data import DataBatch
    t.update(DataBatch(data=toks.reshape(8, 1, 1, 8), label=toks,
                       index=np.arange(8, dtype=np.uint32)))
    assert np.isfinite(float(np.asarray(t._last_loss)))
    err = capsys.readouterr().err
    assert "dp_overlap = 1 ignored" in err and "MoE experts" in err


def test_mesh_overlap_seq_axis_still_falls_back(capsys):
    """Axes the segment walk can't host (seq/expert/pipe) keep the
    warn-once implicit fallback."""
    engine.opts.set("dp_overlap", "1")
    t = _make_trainer(CONV_NET, 16, "cpu:0-3",
                      extra=[("mesh", "data:2,seq:2")])
    t.start_round(1)
    (b,) = _batches(1)
    t.update(b)
    assert np.isfinite(float(np.asarray(t._last_loss)))
    err = capsys.readouterr().err
    assert "dp_overlap = 1 ignored" in err and "seq" in err


def test_plan_buckets_reverse_order_sizing():
    """Bucket boundaries honor the size target in reverse layer order:
    a tiny target gives one bucket per param-owning segment, a huge one
    collapses to a single bucket."""
    from cxxnet_tpu.parallel import overlap
    t = _train(CONV_NET, False, n_steps=0)[3]
    eval_ids = tuple(dict.fromkeys(t.eval_node_ids))
    tiny = overlap.plan_buckets(t.net, t.params, 1e-6, eval_ids)
    assert len(tiny.stages) == 3  # cv1 | fc1 | fc2 segments
    assert tiny.stages[0][0] == 0
    assert tiny.stages[-1][1] == tiny.body_end
    big = overlap.plan_buckets(t.net, t.params, 1024.0, eval_ids)
    assert len(big.stages) == 1
    # contiguity: stage k ends where stage k+1 starts
    for (a0, a1), (b0, b1) in zip(tiny.stages, tiny.stages[1:]):
        assert a1 == b0
