"""Bucketed backward-overlapped DP gradient reduction
(cxxnet_tpu/parallel/overlap.py): bitwise trajectory parity against the
implicit-psum step on a CPU ``data:4`` mesh (tail-mask, update_period,
shard_opt_state configs), per-bucket reduction calls visible in the
lowered HLO, deferred once-per-apply reduction, ZeRO reduce-scatter
composition, bf16 wire dtype, and the fallback gates."""

import os
import re
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from cxxnet_tpu import engine  # noqa: E402
from cxxnet_tpu.io.data import DataBatch  # noqa: E402

from __graft_entry__ import _make_trainer  # noqa: E402

CONV_NET = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  stride = 2
  nchannel = 8
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 2
  stride = 2
layer[3->4] = flatten
layer[4->5] = fullc:fc1
  nhidden = 32
layer[5->6] = relu
layer[6->7] = fullc:fc2
  nhidden = 4
layer[7->7] = softmax
netconfig=end
input_shape = 3,16,16
metric = error
eta = 0.1
momentum = 0.9
silent = 1
"""

# fc1 (256, 144) = 147k f32: crosses the ZeRO size floor (2^14 leaves)
MLP_ZERO_NET = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 256
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,144
metric = error
eta = 0.1
momentum = 0.9
silent = 1
"""

DP_OPTS = ("dp_overlap", "dp_bucket_mb", "dp_reduce_dtype", "dp_reduce_at")


@pytest.fixture(autouse=True)
def _restore_engine_opts():
    saved = {k: getattr(engine.opts, k) for k in DP_OPTS}
    yield
    for k, v in saved.items():
        engine.opts.set(k, v)


def _batches(n, batch=16, shape=(3, 16, 16), classes=4, tail_padd=0):
    rnd = np.random.RandomState(0)
    out = []
    for i in range(n):
        b = DataBatch(
            data=rnd.rand(batch, *shape).astype(np.float32),
            label=rnd.randint(0, classes, (batch, 1)).astype(np.float32),
            index=np.arange(batch, dtype=np.uint32))
        if tail_padd and i == n - 1:
            b.tail_mask_padd = tail_padd
        out.append(b)
    return out


def _train(net, overlap, extra=(), *, bucket_mb="0.001",
           reduce_at="apply", reduce_dtype="f32", n_steps=4,
           shape=(3, 16, 16), tail_padd=0):
    """One fresh trainer, n_steps updates; returns (losses, params,
    opt_state, trainer).  Engine options are process-global and read at
    trace time, so each run sets them BEFORE its first update and the
    autouse fixture restores them (the experiments/ab.py discipline)."""
    engine.opts.set("dp_overlap", "1" if overlap else "0")
    engine.opts.set("dp_bucket_mb", bucket_mb)
    engine.opts.set("dp_reduce_at", reduce_at)
    engine.opts.set("dp_reduce_dtype", reduce_dtype)
    t = _make_trainer(net, 16, "cpu:0-3", extra=[("mesh", "data:4")]
                      + list(extra))
    t.start_round(1)
    losses = []
    for b in _batches(n_steps, shape=shape, tail_padd=tail_padd):
        t.update(b)
        losses.append(float(np.asarray(t._last_loss)))
    return (losses, jax.tree.map(np.asarray, t.params),
            jax.tree.map(np.asarray, t.opt_state), t)


def _assert_trees_equal(a, b, what):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y, err_msg=what)


# ------------------------------------------------------------- parity

@pytest.mark.parametrize("tag,net,extra,kw", [
    ("plain", CONV_NET, (), {}),
    ("tail_mask", CONV_NET, (), {"tail_padd": 5}),
    ("zero", MLP_ZERO_NET, (("shard_opt_state", "1"),),
     {"shape": (1, 1, 144)}),
    # update_period at dp_reduce_at=step: reductions per micro-step, in
    # the implicit path's summation order -> bitwise
    ("update_period", CONV_NET, (("update_period", "2"),),
     {"reduce_at": "step"}),
])
def test_dp_overlap_bitwise_parity(tag, net, extra, kw):
    """dp_overlap=1 trajectory == the implicit-psum DP step, bitwise, at
    dp_reduce_dtype=f32 on a CPU data:4 mesh: per-step losses, final
    params, AND optimizer state (including ZeRO-sharded leaves fed by
    reduce-scatter)."""
    off = _train(net, False, extra, **kw)
    on = _train(net, True, extra, **kw)
    assert off[0] == on[0], f"{tag}: per-step losses must be bitwise equal"
    _assert_trees_equal(off[1], on[1], f"{tag}: params diverged")
    _assert_trees_equal(off[2], on[2], f"{tag}: optimizer state diverged")


def test_dp_overlap_deferred_reduce_once_per_apply():
    """dp_reduce_at=apply (the default): micro-steps run ZERO gradient
    collectives (the accumulate program's only all-reduce is the loss
    scalar), the apply step reduces each bucket once with the
    accumulator folded in.  The cross-chip sum reassociates, so the
    trajectory matches the implicit path to FP tolerance, with losses
    (pure forward) still bitwise."""
    off = _train(CONV_NET, False, (("update_period", "2"),))
    on = _train(CONV_NET, True, (("update_period", "2"),),
                reduce_at="apply")
    assert off[0] == on[0], "forward losses must be bitwise equal"
    for x, y in zip(jax.tree.leaves(off[1]), jax.tree.leaves(on[1])):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-7)
    t = on[3]
    assert t._overlap_defer
    acc_fn, apply_fn = t._build_overlap_steps(False)
    data = jnp.zeros((16, 3, 16, 16), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    rng = jax.random.PRNGKey(0)
    acc = t._grad_acc_init()
    acc_txt = acc_fn.lower(t.params, t.buffers, acc, data, label,
                           jnp.int32(0), rng).as_text()
    apply_txt = apply_fn.lower(t.params, t.opt_state, t.buffers, acc,
                               data, label, jnp.int32(0), rng).as_text()
    assert len(re.findall(r"all_reduce", acc_txt)) == 1, \
        "accumulate micro-step must reduce nothing but the loss scalar"
    assert len(re.findall(r"all_reduce", apply_txt)) >= 3, \
        "apply step must carry the per-bucket reductions"


# ------------------------------------------------------ lowered programs

def test_dp_overlap_hlo_has_per_bucket_reductions():
    """The overlapped step's lowered HLO contains one reduction PER
    BUCKET (>= 2 distinct calls beyond the loss scalar — proving
    per-bucket issue, not one fused end-of-backward reduce); the
    implicit step lowers zero explicit collectives (GSPMD inserts its
    psum later, at partitioning time)."""
    on = _train(CONV_NET, True, n_steps=1)
    t = on[3]
    n_buckets = len(t._dp_overlap_plan().stages)
    assert n_buckets >= 2
    data = jnp.zeros((16, 3, 16, 16), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    args = (t.params, t.opt_state, t.buffers, data, label, (),
            jnp.int32(0), jax.random.PRNGKey(0))
    engine.opts.set("dp_overlap", "1")
    txt = t._train_step.lower(*args).as_text()
    # buckets + the loss psum; >= 2 distinct reductions is the
    # acceptance floor, the plan predicts the exact count
    n_red = len(re.findall(r"all_reduce", txt))
    assert n_red >= 2
    assert n_red >= n_buckets

    off = _train(CONV_NET, False, n_steps=1)
    t0 = off[3]
    txt0 = t0._train_step.lower(
        t0.params, t0.opt_state, t0.buffers, data, label, (),
        jnp.int32(0), jax.random.PRNGKey(0)).as_text()
    assert "all_reduce" not in txt0


def test_dp_overlap_zero_leaves_reduce_scatter():
    """shard_opt_state=1 composes: buckets holding ZeRO-sharded leaves
    REDUCE-SCATTER those grads (each device receives only the shard its
    optimizer state owns) instead of all-reducing."""
    on = _train(MLP_ZERO_NET, True, (("shard_opt_state", "1"),),
                shape=(1, 1, 144), n_steps=1)
    t = on[3]
    assert any(jax.tree.leaves(t.dp_zero_grads)), \
        "test net must have at least one ZeRO-sharded leaf"
    data = jnp.zeros((16, 1, 1, 144), jnp.float32)
    label = jnp.zeros((16, 1), jnp.float32)
    engine.opts.set("dp_overlap", "1")
    txt = t._train_step.lower(
        t.params, t.opt_state, t.buffers, data, label, (),
        jnp.int32(0), jax.random.PRNGKey(0)).as_text()
    assert "reduce_scatter" in txt


# ------------------------------------------------------------- variants

def test_dp_overlap_bf16_reduce_dtype():
    """dp_reduce_dtype=bf16: grads cross the wire in bf16, apply stays
    f32-mastered — the trajectory tracks the f32 run loosely (one bf16
    mantissa of reduction noise per step)."""
    f32 = _train(CONV_NET, True, n_steps=3)
    bf16 = _train(CONV_NET, True, n_steps=3, reduce_dtype="bf16")
    assert np.isfinite(bf16[0]).all()
    np.testing.assert_allclose(bf16[0], f32[0], rtol=0.05)
    for x, y in zip(jax.tree.leaves(bf16[1]), jax.tree.leaves(f32[1])):
        np.testing.assert_allclose(x, y, rtol=0.1, atol=5e-3)


def test_dp_overlap_multi_step_scan_parity():
    """update_many (the multi_step grouped dispatch) routes through the
    same overlapped loss_and_grads inside its lax.scan."""
    def run(overlap):
        engine.opts.set("dp_overlap", "1" if overlap else "0")
        engine.opts.set("dp_bucket_mb", "0.0001")
        t = _make_trainer(CONV_NET, 16, "cpu:0-3",
                          extra=[("mesh", "data:4")])
        rnd = np.random.RandomState(0)
        datas = rnd.rand(3, 16, 3, 16, 16).astype(np.float32)
        labels = rnd.randint(0, 4, (3, 16, 1)).astype(np.float32)
        t.start_round(1)
        losses = np.asarray(t.update_many(datas, labels))
        return losses, jax.tree.map(np.asarray, t.params)

    off = run(False)
    on = run(True)
    np.testing.assert_array_equal(off[0], on[0])
    _assert_trees_equal(off[1], on[1], "multi_step params diverged")


def test_dp_overlap_falls_back_for_batch_norm(capsys):
    """Running-buffer layers (batch_norm) can't thread through the
    sliced vjp: the trainer warns once and keeps the implicit step —
    never silently wrong math."""
    net = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 32
layer[1->2] = batch_norm
layer[2->3] = relu
layer[3->4] = fullc:fc2
  nhidden = 4
layer[4->4] = softmax
netconfig=end
input_shape = 1,1,144
metric = error
eta = 0.1
silent = 1
"""
    engine.opts.set("dp_overlap", "1")
    t = _make_trainer(net, 16, "cpu:0-3", extra=[("mesh", "data:4")])
    t.start_round(1)
    (b,) = _batches(1, shape=(1, 1, 144))
    t.update(b)
    assert np.isfinite(float(np.asarray(t._last_loss)))
    err = capsys.readouterr().err
    assert "dp_overlap = 1 ignored" in err and "batch_norm" in err


def test_dp_overlap_single_device_falls_back(capsys):
    """A one-device mesh has nothing to reduce: implicit step, warning."""
    engine.opts.set("dp_overlap", "1")
    t = _make_trainer(CONV_NET, 16, "cpu:0")
    t.start_round(1)
    (b,) = _batches(1)
    t.update(b)
    assert np.isfinite(float(np.asarray(t._last_loss)))
    assert "dp_overlap = 1 ignored" in capsys.readouterr().err


def test_dp_overlap_cli_config_keys(tmp_path):
    """dp_overlap / dp_bucket_mb / dp_reduce_dtype ride the config
    surface end to end: a .conf trains through LearnTask on a data:4
    mesh bitwise-identically with the explicit step on vs off."""
    import json

    from cxxnet_tpu.main import LearnTask
    sys.path.insert(0, os.path.dirname(__file__))
    from test_main import MLP_NET, _write_synth_mnist
    _write_synth_mnist(tmp_path, n=64)
    conf = tmp_path / "dp.conf"
    conf.write_text(f"""
dev = cpu:0-3
mesh = data:4
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
num_round = 2
metric = error
print_step = 1
silent = 1
save_model = 0
dp_bucket_mb = 0.0001
""")
    losses = {}
    for ov in ("0", "1"):
        sink = tmp_path / f"m{ov}.jsonl"
        task = LearnTask()
        assert task.run([str(conf), f"dp_overlap={ov}",
                         f"metrics_sink=jsonl:{sink}"]) == 0
        recs = [json.loads(l) for l in open(sink)]
        losses[ov] = [r["loss"] for r in recs if r["kind"] == "step"]
        engine.opts.set("dp_overlap", "0")
    assert losses["0"] and losses["0"] == losses["1"]


def test_plan_buckets_reverse_order_sizing():
    """Bucket boundaries honor the size target in reverse layer order:
    a tiny target gives one bucket per param-owning segment, a huge one
    collapses to a single bucket."""
    from cxxnet_tpu.parallel import overlap
    t = _train(CONV_NET, False, n_steps=0)[3]
    eval_ids = tuple(dict.fromkeys(t.eval_node_ids))
    tiny = overlap.plan_buckets(t.net, t.params, 1e-6, eval_ids)
    assert len(tiny.stages) == 3  # cv1 | fc1 | fc2 segments
    assert tiny.stages[0][0] == 0
    assert tiny.stages[-1][1] == tiny.body_end
    big = overlap.plan_buckets(t.net, t.params, 1024.0, eval_ids)
    assert len(big.stages) == 1
    # contiguity: stage k ends where stage k+1 starts
    for (a0, a1), (b0, b1) in zip(tiny.stages, tiny.stages[1:]):
        assert a1 == b0
