"""Pipeline parallelism integrated with the netconfig graph.

VERDICT round-2 item 4: `mesh = pipe:K` must pipeline a *real* layered
network from the config surface (heterogeneous stage shapes), not just the
shape-preserving library demo.  The acceptance bar: a zoo model (LeNet)
trains pipelined with the same trajectory as the single-device run.
"""

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models.zoo import lenet
from cxxnet_tpu.nnet.pipeline_net import partition_network
from test_trainer import make_trainer

EXTRA = [("eta", "0.1"), ("momentum", "0.9"), ("silent", "1"),
         ("eval_train", "0"), ("batch_size", "16")]


def _lenet_conf():
    return lenet(num_class=4)


def _batches(n=6, bs=16, seed=0):
    rnd = np.random.RandomState(seed)
    out = []
    for i in range(n):
        x = rnd.rand(bs, 1, 28, 28).astype(np.float32)
        y = (x.mean(axis=(1, 2, 3)) > 0.5).astype(np.float32) * 2
        out.append(DataBatch(data=x, label=y.reshape(bs, 1),
                             index=np.arange(bs, dtype=np.uint32)))
    return out


def test_partition_lenet():
    t = make_trainer(_lenet_conf(), extra=EXTRA + [("dev", "cpu")])
    stages, body_end = partition_network(t.net, 4)
    assert len(stages) == 4
    assert stages[0][0] == 0 and stages[-1][1] == body_end
    # contiguous, non-empty
    for (a0, a1), (b0, b1) in zip(stages, stages[1:]):
        assert a1 == b0 and a1 > a0
    assert stages[-1][1] > stages[-1][0]
    # loss layer excluded from the body
    assert t.net.connections[body_end].layer.is_loss


@pytest.mark.parametrize("mesh", ["pipe:4", "data:2,pipe:2"])
def test_pipelined_lenet_matches_single_device(mesh):
    """Same data, same seed: the pipelined trajectory must match the
    single-device trajectory (the schedule is a pure re-ordering of the
    same math; only reduction order may differ -> tight tolerance)."""
    n_dev = int(np.prod([int(p.split(":")[1]) for p in mesh.split(",")]))
    batches = _batches()
    ref = make_trainer(_lenet_conf(), extra=EXTRA + [("dev", "cpu")])
    pp = make_trainer(_lenet_conf(),
                      extra=EXTRA + [("dev", f"cpu:0-{n_dev - 1}"),
                                     ("mesh", mesh),
                                     ("pipe_microbatch", "4")])
    ref_losses, pp_losses = [], []
    for b in batches:
        ref.update(b)
        ref_losses.append(float(np.asarray(ref._last_loss)))
        pp.update(b)
        pp_losses.append(float(np.asarray(pp._last_loss)))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4,
                               err_msg=f"pipelined trajectory diverged "
                               f"({mesh})")
    # end-state weights match too
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_allclose(
                np.asarray(pp.params[pkey][tag]), np.asarray(v),
                rtol=1e-3, atol=1e-5, err_msg=f"{pkey}/{tag}")


@pytest.mark.parametrize("mesh", ["pipe:4", "data:2,pipe:2"])
def test_1f1b_pipelined_lenet_matches_single_device(mesh):
    """pipe_schedule = 1f1b: the interleaved schedule computes its own
    gradients (per-stage vjp recompute); the trajectory must match the
    single-device run like the GPipe schedule does."""
    n_dev = int(np.prod([int(p.split(":")[1]) for p in mesh.split(",")]))
    batches = _batches()
    ref = make_trainer(_lenet_conf(), extra=EXTRA + [("dev", "cpu")])
    pp = make_trainer(_lenet_conf(),
                      extra=EXTRA + [("dev", f"cpu:0-{n_dev - 1}"),
                                     ("mesh", mesh),
                                     ("pipe_microbatch", "4"),
                                     ("pipe_schedule", "1f1b")])
    ref_losses, pp_losses = [], []
    for b in batches:
        ref.update(b)
        ref_losses.append(float(np.asarray(ref._last_loss)))
        pp.update(b)
        pp_losses.append(float(np.asarray(pp._last_loss)))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4,
                               err_msg=f"1f1b trajectory diverged ({mesh})")
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_allclose(
                np.asarray(pp.params[pkey][tag]), np.asarray(v),
                rtol=1e-3, atol=1e-5, err_msg=f"{pkey}/{tag}")


def test_1f1b_netconfig_memory_flat():
    """Growing the microbatch count must leave the 1F1B step's XLA temp
    memory ~flat (ring of 2S-1 saved boundaries) while the GPipe
    step's grows with n_micro (residuals for every scan tick)."""
    import jax
    import jax.numpy as jnp

    def measure(schedule, n_micro, mb=8):
        bs = n_micro * mb
        t = make_trainer(
            _lenet_conf(),
            extra=[("eta", "0.1"), ("momentum", "0.9"), ("silent", "1"),
                   ("eval_train", "0"), ("batch_size", str(bs)),
                   ("dev", "cpu:0-3"), ("mesh", "pipe:4"),
                   ("pipe_microbatch", str(n_micro)),
                   ("pipe_schedule", schedule)])
        data = jnp.zeros((bs, 1, 28, 28), jnp.float32)
        label = jnp.zeros((bs, 1), jnp.float32)
        rng = jax.random.PRNGKey(0)
        comp = t._train_step.lower(
            t.params, t.opt_state, t.buffers, data, label, (),
            jnp.int32(0), rng).compile()
        mem = comp.memory_analysis()
        size = getattr(mem, "temp_size_in_bytes", None)
        if size is None:
            pytest.skip("backend reports no temp_size_in_bytes")
        return size

    # fixed microbatch size, growing microbatch count (deep-pipeline
    # regime: more microbatches shrink the bubble for free)
    gpipe_4, gpipe_16 = measure("gpipe", 4), measure("gpipe", 16)
    f1b_4, f1b_16 = measure("1f1b", 4), measure("1f1b", 16)
    # GPipe stores per-tick residuals: memory rises with n_micro.
    assert gpipe_16 > 1.5 * gpipe_4, (gpipe_4, gpipe_16)
    # 1F1B's ring (2S-1 slots) is n_micro-independent.
    assert f1b_16 < 1.3 * f1b_4, (f1b_4, f1b_16)


def test_pipelined_eval_matches():
    batches = _batches(2)
    pp = make_trainer(_lenet_conf(),
                      extra=EXTRA + [("dev", "cpu:0-3"), ("mesh", "pipe:4"),
                                     ("pipe_microbatch", "4")])
    ref = make_trainer(_lenet_conf(), extra=EXTRA + [("dev", "cpu")])
    # copy weights ref -> pp so predictions must agree exactly
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            layer_name = pkey.split("-", 1)[1]
            pp.set_weight(np.asarray(v), layer_name, tag)
    pred_ref = ref.predict(batches[0])
    pred_pp = pp.predict(batches[0])
    np.testing.assert_array_equal(pred_ref, pred_pp)


def test_remat_matches_plain_trajectory():
    """remat = K recomputes activations in backward; the math is
    unchanged, so a dropout-free net's trajectory matches exactly."""
    batches = _batches(4)
    ref = make_trainer(_lenet_conf(), extra=EXTRA + [("dev", "cpu")])
    rm = make_trainer(_lenet_conf(),
                      extra=EXTRA + [("dev", "cpu"), ("remat", "3")])
    for b in batches:
        ref.update(b)
        rm.update(b)
        np.testing.assert_array_equal(np.asarray(rm._last_loss),
                                      np.asarray(ref._last_loss))
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_array_equal(
                np.asarray(rm.params[pkey][tag]), np.asarray(v),
                err_msg=f"{pkey}/{tag}")


MOE_CONF = """
netconfig=start
layer[0->1] = embedding
  vocab_size = 32
  nhidden = 16
layer[1->2] = moe
  num_expert = 4
  nhidden = 32
layer[2->3] = seq_fullc
  nhidden = 32
layer[3->3] = softmax_seq
netconfig=end
label_vec[0,8) = label
input_shape = 1,1,8
batch_size = 8
eta = 0.05
updater = sgd
momentum = 0.0
metric = error
silent = 1
"""


def _moe_trainer(extra):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    t = NetTrainer()
    for k, v in parse_config_string(MOE_CONF):
        t.set_param(k, v)
    for k, v in extra:
        t.set_param(k, v)
    t.init_model()
    return t


def test_moe_aux_loss_survives_remat_body():
    """The MoE Switch load-balance aux loss is appended mid-body; the
    remat/pipeline stage fns must thread it out (ADVICE r3: it was
    silently dropped).  remat runs the full batch, so the partitioned
    trajectory must match the plain run, whose total includes the aux
    term."""
    ref = _moe_trainer([("dev", "cpu")])
    part = _moe_trainer([("dev", "cpu"), ("remat", "2")])
    # identical init: copy weights ref -> part
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            layer_name = pkey.split("-", 1)[1]
            part.set_weight(np.asarray(v), layer_name, tag)
    rnd = np.random.RandomState(0)
    toks = rnd.randint(0, 32, (8, 8)).astype(np.float32)
    b = DataBatch(data=toks.reshape(8, 1, 1, 8), label=toks,
                  index=np.arange(8, dtype=np.uint32))
    for _ in range(3):
        ref.update(b)
        part.update(b)
        np.testing.assert_allclose(
            np.asarray(part._last_loss), np.asarray(ref._last_loss),
            rtol=1e-5, err_msg="partitioned body lost the MoE aux loss")
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_allclose(
                np.asarray(part.params[pkey][tag]), np.asarray(v),
                rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")


def test_moe_aux_loss_threads_through_pipeline():
    """Under ``mesh = pipe:K`` the MoE aux loss is computed per
    microbatch (GShard semantics: dispatch capacity and load balance are
    per dispatch group), so the trajectory need not match the dense run
    — but the threaded term MUST arrive in ctx.losses (it was silently
    dropped before the r3 ADVICE fix)."""
    import jax
    import jax.numpy as jnp
    part = _moe_trainer([("dev", "cpu:0-1"), ("mesh", "pipe:2"),
                         ("pipe_microbatch", "2")])
    rnd = np.random.RandomState(0)
    toks = rnd.randint(0, 32, (8, 8)).astype(np.float32)
    data = jnp.asarray(toks.reshape(8, 1, 1, 8))
    label_vec = jnp.asarray(toks)
    _, ctx = part._pipeline_forward(
        part.params, data, label_vec, train=True,
        rng=jax.random.PRNGKey(0), epoch=0)
    # tail softmax loss + the threaded mid-body MoE load-balance term
    assert len(ctx.losses) == 2, "mid-body aux loss was dropped"
    aux = float(np.asarray(ctx.losses[-1]))
    assert np.isfinite(aux) and aux > 0.0


def test_moe_aux_loss_mask_reaches_remat_stages():
    """Masked tail batch (tail_mask_padd): the stage fns must hand the
    loss mask to mid-body contributors so MoE's load-balance statistics
    exclude replica tokens, matching the plain masked path exactly
    (r4 review finding: stage ctxs were built without labels/mask)."""
    ref = _moe_trainer([("dev", "cpu")])
    part = _moe_trainer([("dev", "cpu"), ("remat", "2")])
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            layer_name = pkey.split("-", 1)[1]
            part.set_weight(np.asarray(v), layer_name, tag)
    rnd = np.random.RandomState(3)
    toks = rnd.randint(0, 32, (8, 8)).astype(np.float32)
    b = DataBatch(data=toks.reshape(8, 1, 1, 8), label=toks,
                  index=np.arange(8, dtype=np.uint32),
                  num_batch_padd=2, tail_mask_padd=2)
    for _ in range(2):
        ref.update(b)
        part.update(b)
        np.testing.assert_allclose(
            np.asarray(part._last_loss), np.asarray(ref._last_loss),
            rtol=1e-5, err_msg="masked remat diverged from plain path")
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_allclose(
                np.asarray(part.params[pkey][tag]), np.asarray(v),
                rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")


SKIP_CONF = """
netconfig=start
layer[0->1] = fullc:s_fc1
  nhidden = 24
layer[1->2,3] = split
layer[2->4] = fullc:s_fc2
  nhidden = 24
layer[4->5] = relu
layer[5->6] = fullc:s_fc3
  nhidden = 24
layer[6,3->7] = eltsum
layer[7->8] = fullc:s_fc4
  nhidden = 4
layer[8->8] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.1
momentum = 0.9
metric = error
silent = 1
"""

AUX_CONF = """
netconfig=start
layer[0->1] = fullc:a_fc1
  nhidden = 24
layer[1->2] = relu
layer[2->3,4] = split
layer[4->5] = fullc:a_aux
  nhidden = 4
layer[5->5] = softmax
  grad_scale = 0.3
layer[3->6] = fullc:a_fc2
  nhidden = 24
layer[6->7] = relu
layer[7->8] = fullc:a_fc3
  nhidden = 4
layer[8->8] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.1
momentum = 0.9
metric = error
silent = 1
"""


def _mk(conf, extra):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    t = NetTrainer()
    for k, v in parse_config_string(conf):
        t.set_param(k, v)
    for k, v in extra:
        t.set_param(k, v)
    t.init_model()
    return t


def _toy_batches(n=4, bs=16, seed=5):
    rnd = np.random.RandomState(seed)
    out = []
    for i in range(n):
        x = rnd.randn(bs, 8).astype(np.float32)
        y = (np.abs(x).argmax(axis=1) % 4).astype(np.float32)
        out.append(DataBatch(data=x.reshape(bs, 1, 1, 8),
                             label=y.reshape(bs, 1),
                             index=np.arange(bs, dtype=np.uint32)))
    return out


@pytest.mark.parametrize("conf,extra", [
    (SKIP_CONF, [("dev", "cpu"), ("remat", "3")]),
    (SKIP_CONF, [("dev", "cpu:0-1"), ("mesh", "pipe:2"),
                 ("pipe_microbatch", "2")]),
    (AUX_CONF, [("dev", "cpu"), ("remat", "3")]),
    (AUX_CONF, [("dev", "cpu:0-1"), ("mesh", "pipe:2"),
                ("pipe_microbatch", "2")]),
], ids=["skip-remat", "skip-pipe", "aux-remat", "aux-pipe"])
def test_multi_node_frontier_partition(conf, extra):
    """VERDICT r3 item 7: cuts may now cross multi-node frontiers (skip
    connections) and mid-body loss layers (aux heads).  Dropout-free
    nets: the partitioned trajectory must match the plain run exactly
    (aux-head losses sum identically: per-instance-sum scaling)."""
    ref = _mk(conf, [("dev", "cpu")])
    part = _mk(conf, extra)
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            layer_name = pkey.split("-", 1)[1]
            part.set_weight(np.asarray(v), layer_name, tag)
    for b in _toy_batches():
        ref.update(b)
        part.update(b)
        np.testing.assert_allclose(
            np.asarray(part._last_loss), np.asarray(ref._last_loss),
            rtol=1e-5)
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_allclose(
                np.asarray(part.params[pkey][tag]), np.asarray(v),
                rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")
