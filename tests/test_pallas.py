"""Pallas kernel parity tests (interpreter mode on the CPU mesh).

PairTest-style differential check: the Pallas LRN kernel against the plain
XLA path (``nn.lrn``'s shifted-adds formulation), forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops import nn as N
from cxxnet_tpu.ops.pallas_kernels import lrn_pallas


def _xla_lrn(x, nsize, alpha, beta, knorm):
    salpha = alpha / nsize
    norm = N.chpool_sum(jnp.square(x), nsize) * salpha + knorm
    return x * jnp.power(norm, -beta)


@pytest.mark.parametrize("nsize,beta", [(5, 0.75), (3, 0.5), (4, 0.75)])
def test_lrn_pallas_forward(nsize, beta):
    x = jnp.asarray(np.random.RandomState(0).randn(3, 16, 5, 7),
                    jnp.float32)
    got = lrn_pallas(x, nsize, 0.001, beta, 1.0)
    want = _xla_lrn(x, nsize, 0.001, beta, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("nsize,beta", [(5, 0.75), (3, 0.5), (4, 0.75)])
def test_lrn_pallas_grad(nsize, beta):
    x = jnp.asarray(np.random.RandomState(1).randn(2, 16, 4, 5),
                    jnp.float32)
    w = jnp.asarray(np.random.RandomState(2).randn(*x.shape), jnp.float32)

    g_pallas = jax.grad(
        lambda v: (lrn_pallas(v, nsize, 0.001, beta, 1.0) * w).sum())(x)
    g_xla = jax.grad(
        lambda v: (_xla_lrn(v, nsize, 0.001, beta, 1.0) * w).sum())(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                               rtol=1e-4, atol=1e-5)


def test_lrn_dispatch_forced_pallas(monkeypatch):
    """nn.lrn routes through the Pallas kernel when CXXNET_PALLAS_LRN=1."""
    monkeypatch.setattr(N, "_PALLAS_LRN", "1")
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 3, 3), jnp.float32)
    got = N.lrn(x, 5, 0.001, 0.75, 1.0)
    want = _xla_lrn(x, 5, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_flash_attention_matches_dense():
    """Pallas flash attention (interpret mode on CPU) == dense attention,
    forward and backward, causal and not, bf16 and f32."""
    from cxxnet_tpu.ops.pallas_kernels import (flash_attention,
                                               flash_attention_available)
    from cxxnet_tpu.parallel.ring import dense_attention
    assert flash_attention_available(256, 64)
    assert not flash_attention_available(250, 64)  # not divisible by 128
    rnd = np.random.RandomState(0)
    for dtype, tol in ((np.float32, 5e-6), (jnp.bfloat16, 5e-2)):
        q, k, v = (jnp.asarray(
            rnd.randn(1, 2, 256, 64).astype(np.float32) * 0.5).astype(dtype)
            for _ in range(3))
        for causal in (False, True):
            out = flash_attention(q, k, v, causal)
            ref = dense_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(
                np.asarray(out, np.float32), np.asarray(ref, np.float32),
                atol=tol)
            gf = jax.grad(lambda *a: jnp.sum(
                flash_attention(*a, causal).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(lambda *a: jnp.sum(
                dense_attention(*a, causal=causal).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            for a, b in zip(gf, gr):
                np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    atol=tol * 40)


def test_flash_attention_asymmetric_blocks():
    """Sequence lengths hitting the bq!=bk path (512/1024 blocks)."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    from cxxnet_tpu.parallel.ring import dense_attention
    assert pk._fa_blocks(8192) == (512, 1024)
    assert pk._fa_blocks(512) == (512, 512)
    assert pk._fa_blocks(128) == (128, 128)
    rnd = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rnd.randn(1, 1, 1024, 32).astype(np.float32) * 0.5)
               for _ in range(3))
    out = pk.flash_attention(q, k, v, True)
    # chunked reference at this length
    import cxxnet_tpu.parallel.ring as ring
    old = ring.CHUNKED_ATTN_THRESHOLD
    try:
        ring.CHUNKED_ATTN_THRESHOLD = 128
        ref = dense_attention(q, k, v, causal=True)
    finally:
        ring.CHUNKED_ATTN_THRESHOLD = old
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
