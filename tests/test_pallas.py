"""Pallas kernel parity tests (interpreter mode on the CPU mesh).

PairTest-style differential check: the Pallas LRN kernel against the plain
XLA path (``nn.lrn``'s shifted-adds formulation), forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops import nn as N
from cxxnet_tpu.ops.pallas_kernels import lrn_pallas


def _xla_lrn(x, nsize, alpha, beta, knorm):
    salpha = alpha / nsize
    norm = N.chpool_sum(jnp.square(x), nsize) * salpha + knorm
    return x * jnp.power(norm, -beta)


@pytest.mark.parametrize("nsize,beta", [(5, 0.75), (3, 0.5), (4, 0.75)])
def test_lrn_pallas_forward(nsize, beta):
    x = jnp.asarray(np.random.RandomState(0).randn(3, 16, 5, 7),
                    jnp.float32)
    got = lrn_pallas(x, nsize, 0.001, beta, 1.0)
    want = _xla_lrn(x, nsize, 0.001, beta, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("nsize,beta", [(5, 0.75), (3, 0.5), (4, 0.75)])
def test_lrn_pallas_grad(nsize, beta):
    x = jnp.asarray(np.random.RandomState(1).randn(2, 16, 4, 5),
                    jnp.float32)
    w = jnp.asarray(np.random.RandomState(2).randn(*x.shape), jnp.float32)

    g_pallas = jax.grad(
        lambda v: (lrn_pallas(v, nsize, 0.001, beta, 1.0) * w).sum())(x)
    g_xla = jax.grad(
        lambda v: (_xla_lrn(v, nsize, 0.001, beta, 1.0) * w).sum())(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                               rtol=1e-4, atol=1e-5)


def test_lrn_dispatch_forced_pallas(monkeypatch):
    """nn.lrn routes through the Pallas kernel when CXXNET_PALLAS_LRN=1."""
    monkeypatch.setattr(N, "_PALLAS_LRN", "1")
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 3, 3), jnp.float32)
    got = N.lrn(x, 5, 0.001, 0.75, 1.0)
    want = _xla_lrn(x, 5, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
