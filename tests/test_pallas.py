"""Pallas kernel parity tests (interpreter mode on the CPU mesh).

PairTest-style differential check: the Pallas LRN kernel against the plain
XLA path (``nn.lrn``'s shifted-adds formulation), forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops import nn as N
from cxxnet_tpu.ops.pallas_kernels import lrn_pallas


def _xla_lrn(x, nsize, alpha, beta, knorm):
    salpha = alpha / nsize
    norm = N.chpool_sum(jnp.square(x), nsize) * salpha + knorm
    return x * jnp.power(norm, -beta)


@pytest.mark.parametrize("nsize,beta", [(5, 0.75), (3, 0.5), (4, 0.75)])
def test_lrn_pallas_forward(nsize, beta):
    x = jnp.asarray(np.random.RandomState(0).randn(3, 16, 5, 7),
                    jnp.float32)
    got = lrn_pallas(x, nsize, 0.001, beta, 1.0)
    want = _xla_lrn(x, nsize, 0.001, beta, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("nsize,beta", [(5, 0.75), (3, 0.5), (4, 0.75)])
def test_lrn_pallas_grad(nsize, beta):
    x = jnp.asarray(np.random.RandomState(1).randn(2, 16, 4, 5),
                    jnp.float32)
    w = jnp.asarray(np.random.RandomState(2).randn(*x.shape), jnp.float32)

    g_pallas = jax.grad(
        lambda v: (lrn_pallas(v, nsize, 0.001, beta, 1.0) * w).sum())(x)
    g_xla = jax.grad(
        lambda v: (_xla_lrn(v, nsize, 0.001, beta, 1.0) * w).sum())(x)
    np.testing.assert_allclose(np.asarray(g_pallas), np.asarray(g_xla),
                               rtol=1e-4, atol=1e-5)


def test_lrn_dispatch_forced_pallas(monkeypatch):
    """nn.lrn routes through the Pallas kernel when pallas_lrn = 1."""
    from cxxnet_tpu.engine import opts
    monkeypatch.setattr(opts, "pallas_lrn", "1")
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, 3, 3), jnp.float32)
    got = N.lrn(x, 5, 0.001, 0.75, 1.0)
    want = _xla_lrn(x, 5, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("shape,conv",
                         [((4, 3, 23, 23, 8, 11, 4, 0), "alexnet-conv1"),
                          ((2, 3, 16, 16, 16, 5, 2, 2), "padded"),
                          ((8, 4, 15, 15, 8, 7, 3, 1), "odd")])
def test_conv_wgrad_pallas_matches_vjp(shape, conv):
    """Space-to-depth Pallas weight/bias-grad == XLA's conv VJP."""
    from cxxnet_tpu.ops.pallas_kernels import conv_wgrad_s2d_pallas
    n, c, h, w, co, k, s, p = shape
    rnd = np.random.RandomState(0)
    x = jnp.asarray(rnd.rand(n, c, h, w).astype(np.float32))
    wt = jnp.asarray((rnd.rand(co, c, k, k) - 0.5).astype(np.float32))
    y = N.conv2d(x, wt, stride=s, pad_y=p, pad_x=p)
    dy = jnp.asarray(rnd.rand(*y.shape).astype(np.float32))
    dw_ref = jax.vjp(
        lambda wv: N.conv2d(x, wv, stride=s, pad_y=p, pad_x=p), wt)[1](dy)[0]
    dw, db = conv_wgrad_s2d_pallas(x, dy, kh=k, kw=k, stride=s,
                                   pad_y=p, pad_x=p)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(db),
                               np.asarray(dy.sum(axis=(0, 2, 3))),
                               rtol=1e-4, atol=1e-4)


def test_conv_bias_fast_full_vjp():
    """conv_bias_fast == conv2d+bias in value and all three gradients."""
    rnd = np.random.RandomState(1)
    n, c, h, w, co, k, s = 2, 3, 23, 23, 8, 11, 4
    x = jnp.asarray(rnd.rand(n, c, h, w).astype(np.float32))
    wt = jnp.asarray((rnd.rand(co, c, k, k) - 0.5).astype(np.float32))
    b = jnp.asarray(rnd.rand(co).astype(np.float32))

    def ref(wt, b, xv):
        return N.conv2d(xv, wt, stride=s) + b.reshape(1, -1, 1, 1)

    def fast(wt, b, xv):
        return N.conv_bias_fast(xv, wt, b, s, 0, 0)

    y_ref, y_fast = ref(wt, b, x), fast(wt, b, x)
    np.testing.assert_allclose(np.asarray(y_fast), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    dy = jnp.asarray(rnd.rand(*y_ref.shape).astype(np.float32))
    gr = jax.vjp(ref, wt, b, x)[1](dy)
    gf = jax.vjp(fast, wt, b, x)[1](dy)
    for a, bb, name in zip(gr, gf, ("dw", "db", "dx")):
        np.testing.assert_allclose(np.asarray(bb), np.asarray(a),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def _insanity_oracle(x, mask, k, s, p_keep):
    """Direct transcription of the reference's InsanityPoolingExp /
    InsanityUnPoolingExp Eval loops (insanity_pooling_layer-inl.hpp:70-93,
    :178-210) for a single (n, c) plane stack."""
    n, c, h, w = x.shape
    d = (1.0 - p_keep) / 4.0
    # jittered read location per input position
    loc = np.empty((n, c, h, w, 2), np.int64)
    for ni in range(n):
        for ci in range(c):
            for y in range(h):
                for xx in range(w):
                    ly, lx = y, xx
                    f = mask[ni, ci, y, xx]
                    if f < p_keep:
                        pass
                    elif f < p_keep + d:
                        ly = ly - 1 if ly > 0 else ly
                    elif f < p_keep + 2 * d:
                        ly = ly + 1 if ly + 1 < h else h - 1
                    elif f < p_keep + 3 * d:
                        lx = lx - 1 if lx > 0 else lx
                    else:
                        lx = lx + 1 if lx + 1 < w else w - 1
                    loc[ni, ci, y, xx] = (ly, lx)
    oh = min(h - k + s - 1, h - 1) // s + 1
    ow = min(w - k + s - 1, w - 1) // s + 1
    out = np.full((n, c, oh, ow), -np.inf, np.float32)
    for ni in range(n):
        for ci in range(c):
            for py in range(oh):
                for px in range(ow):
                    for y in range(py * s, min(py * s + k, h)):
                        for xx in range(px * s, min(px * s + k, w)):
                            ly, lx = loc[ni, ci, y, xx]
                            out[ni, ci, py, px] = max(
                                out[ni, ci, py, px], x[ni, ci, ly, lx])
    # backward: grad to window positions whose jittered value ties the max
    def bwd(dy):
        dx = np.zeros_like(x)
        for ni in range(n):
            for ci in range(c):
                for y in range(h):
                    for xx in range(w):
                        ly, lx = loc[ni, ci, y, xx]
                        vsrc = x[ni, ci, ly, lx]
                        py_min = 0 if y < k else (y - k + s) // s
                        px_min = 0 if xx < k else (xx - k + s) // s
                        py_max = min((y + s) // s, oh)
                        px_max = min((xx + s) // s, ow)
                        val = 0.0
                        for py in range(py_min, py_max):
                            for px in range(px_min, px_max):
                                if vsrc == out[ni, ci, py, px]:
                                    val += dy[ni, ci, py, px]
                        dx[ni, ci, y, xx] = val
        return dx
    return out, bwd


def test_insanity_pool_exact_semantics():
    """insanity_max_pool == the reference expression's Eval loops, forward
    and backward (numpy oracle transcription)."""
    rnd = np.random.RandomState(0)
    for (h, w, k, s, keep) in [(7, 7, 3, 2, 0.6), (6, 8, 2, 2, 0.0),
                               (9, 9, 3, 3, 0.9)]:
        x = rnd.randint(0, 6, (2, 3, h, w)).astype(np.float32)
        mask = rnd.rand(2, 3, h, w).astype(np.float32)
        want, oracle_bwd = _insanity_oracle(x, mask, k, s, keep)
        got, vjp = jax.vjp(
            lambda v: N.insanity_max_pool(jnp.asarray(v), jnp.asarray(mask),
                                          k, k, s, keep), x)
        np.testing.assert_allclose(np.asarray(got), want, err_msg=(h, k, s))
        dy = rnd.rand(*want.shape).astype(np.float32)
        (dx,) = vjp(jnp.asarray(dy))
        np.testing.assert_allclose(np.asarray(dx), oracle_bwd(dy),
                                   rtol=1e-6, atol=1e-6,
                                   err_msg=(h, k, s, keep))


def test_insanity_pool_layer_eval_is_max_pool():
    from cxxnet_tpu.layers.registry import create_layer
    from cxxnet_tpu.layers.base import ForwardContext
    layer = create_layer("insanity_max_pooling")
    layer.set_param("kernel_size", "3")
    layer.set_param("stride", "2")
    layer.set_param("keep", "0.7")
    x = jnp.asarray(np.random.RandomState(0).rand(2, 3, 9, 9), jnp.float32)
    (out,), _ = layer.forward({}, {}, [x], ForwardContext(train=False))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(N.max_pool2d(x, 3, 3, 2)))


def test_relu_vjp_masks_from_output():
    """Relu's custom VJP (mask from the output, reference op.h relu_grad)
    matches jax.nn.relu's gradient everywhere except the measure-zero x=0."""
    from cxxnet_tpu.layers.activation import _relu_out_grad
    x = jnp.asarray([[-2.0, -0.5, 0.0, 0.5, 2.0]])
    np.testing.assert_array_equal(np.asarray(_relu_out_grad(x)),
                                  np.asarray(jax.nn.relu(x)))
    g = jax.grad(lambda v: _relu_out_grad(v).sum())(x)
    np.testing.assert_array_equal(np.asarray(g),
                                  np.asarray([[0.0, 0.0, 0.0, 1.0, 1.0]]))


def test_flash_attention_matches_dense():
    """Pallas flash attention (interpret mode on CPU) == dense attention,
    forward and backward, causal and not, bf16 and f32."""
    from cxxnet_tpu.ops.pallas_kernels import (flash_attention,
                                               flash_attention_available)
    from cxxnet_tpu.parallel.ring import dense_attention
    assert flash_attention_available(256, 64)
    assert not flash_attention_available(250, 64)  # not divisible by 128
    rnd = np.random.RandomState(0)
    for dtype, tol in ((np.float32, 5e-6), (jnp.bfloat16, 5e-2)):
        q, k, v = (jnp.asarray(
            rnd.randn(1, 2, 256, 64).astype(np.float32) * 0.5).astype(dtype)
            for _ in range(3))
        for causal in (False, True):
            out = flash_attention(q, k, v, causal)
            ref = dense_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(
                np.asarray(out, np.float32), np.asarray(ref, np.float32),
                atol=tol)
            gf = jax.grad(lambda *a: jnp.sum(
                flash_attention(*a, causal).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(lambda *a: jnp.sum(
                dense_attention(*a, causal=causal).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            for a, b in zip(gf, gr):
                np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    atol=tol * 40)


def test_flash_attention_asymmetric_blocks():
    """The bq!=bk path stays correct (the v5e-tuned default is square
    1024x1024, so asymmetric blocks are exercised via override)."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    from cxxnet_tpu.parallel.ring import dense_attention
    assert pk._fa_blocks(8192) == (1024, 1024)
    assert pk._fa_blocks(512) == (512, 512)
    assert pk._fa_blocks(128) == (128, 128)
    rnd = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rnd.randn(1, 1, 1024, 32).astype(np.float32) * 0.5)
               for _ in range(3))
    old_blocks = pk._fa_blocks
    try:
        pk._fa_blocks = lambda s, d=64: (256, 512)  # asymmetric, multi-block
        out = pk.flash_attention(q, k, v, True)
    finally:
        pk._fa_blocks = old_blocks
    # chunked reference at this length
    import cxxnet_tpu.parallel.ring as ring
    old = ring.CHUNKED_ATTN_THRESHOLD
    try:
        ring.CHUNKED_ATTN_THRESHOLD = 128
        ref = dense_attention(q, k, v, causal=True)
    finally:
        ring.CHUNKED_ATTN_THRESHOLD = old
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_lrn_hwcn_matches_xla():
    """Native-layout (H,W,C,N) LRN kernel == XLA path, fwd + grad."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops import nn as N
    from cxxnet_tpu.ops.pallas_kernels import lrn_pallas_hwcn
    x = jnp.asarray(np.random.RandomState(0).randn(4, 96, 9, 9),
                    jnp.float32)
    a = lrn_pallas_hwcn(x, 5, 0.001, 0.75, 1.0)
    b = N.lrn(x, 5, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=1e-6)
    ga = jax.grad(lambda v: (lrn_pallas_hwcn(v, 5, .001, .75, 1.) ** 2
                             ).sum())(x)
    gb = jax.grad(lambda v: (N.lrn(v, 5, .001, .75, 1.) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("shape,k,s", [
    ((4, 16, 27, 27), 3, 2),   # AlexNet pool2 family
    ((2, 8, 13, 13), 3, 2),    # clipped tail
    ((2, 8, 12, 12), 2, 2),    # VGG/LeNet family
    ((2, 8, 9, 9), 3, 1),      # inception same-size branch (no pad)
    ((2, 8, 12, 12), 3, 2),    # even width + clipped tail: the tap slice
    ((2, 8, 14, 14), 3, 2),    # needs (k-1)//s + ow > ceil(w/s) phase
    ((2, 8, 56, 56), 3, 2),    # entries (GoogLeNet pool shapes 112/56/14)
])
def test_max_pool_hwcn_matches_eq(shape, k, s):
    """Native-layout pool kernel == reference rule fwd; backward == exact
    all-ties eq-mask unpool (mshadow semantics)."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops import nn as N
    from cxxnet_tpu.ops.pallas_kernels import max_pool_hwcn
    x = jnp.asarray(np.random.RandomState(1).randn(*shape), jnp.float32)
    a = max_pool_hwcn(x, k, s)
    b = N._max_pool_raw(x, k, k, s, 0, 0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    g = jnp.asarray(np.random.RandomState(2).randn(*a.shape), jnp.float32)
    da = jax.vjp(lambda v: max_pool_hwcn(v, k, s), x)[1](g)[0]
    db = jax.vjp(lambda v: N._max_pool_eq(v, k, k, s, 0, 0), x)[1](g)[0]
    np.testing.assert_allclose(np.asarray(da), np.asarray(db), atol=1e-4)


@pytest.mark.parametrize("shape,k,s", [
    ((4, 16, 27, 27), 3, 2),   # AlexNet pool2 family (overlapping)
    ((2, 8, 13, 13), 3, 2),    # clipped tail
    ((2, 8, 12, 12), 2, 2),    # VGG/LeNet family
    ((2, 8, 9, 9), 3, 1),      # inception same-size branch (no pad)
    ((2, 8, 56, 56), 3, 2),    # GoogLeNet stage pool family
])
def test_max_pool_relu_fused_matches_unfused(shape, k, s):
    """relu-fused multi-row pool backward (pool_relu_fuse;
    pallas_kernels.max_pool_relu_hwcn): forward AND gradient are
    bitwise ALL-TIES-identical to the unfused pair relu∘max_pool_hwcn
    in interpret mode — the in-kernel ``pv > 0`` mask epilogue is
    exactly relu's where(out > 0, dy, 0) because pv is the pre-relu
    pool output."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops.pallas_kernels import (max_pool_hwcn,
                                               max_pool_relu_hwcn)
    # shifted below zero so a real fraction of WINDOW MAXIMA are negative
    # (a max of k*k unit Gaussians is almost never negative unshifted —
    # the relu mask would be vacuously all-ones)
    x = jnp.asarray(np.random.RandomState(1).randn(*shape) - 1.5,
                    jnp.float32)
    fused = max_pool_relu_hwcn(x, k, s)
    unfused = jnp.maximum(max_pool_hwcn(x, k, s), 0)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(unfused))
    assert (np.asarray(fused) == 0).mean() > 0.2
    g = jnp.asarray(np.random.RandomState(2).randn(*fused.shape),
                    jnp.float32)
    da = jax.vjp(lambda v: max_pool_relu_hwcn(v, k, s), x)[1](g)[0]
    db = jax.vjp(lambda v: jnp.maximum(max_pool_hwcn(v, k, s), 0),
                 x)[1](g)[0]
    np.testing.assert_array_equal(np.asarray(da), np.asarray(db))


def test_max_pool2d_relu_dispatcher_unfused_identity():
    """ops.nn.max_pool2d_relu with pool_relu_fuse=0 (default) is exactly
    apply_relu(max_pool2d(.)) — the pre-fusion execution form — for both
    values and gradients; pool_relu_fuse=1 on CPU keeps the same path
    (the fused kernel is gated to shapes the TPU hwcn kernel takes)."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu import engine
    from cxxnet_tpu.layers.activation import apply_relu
    from cxxnet_tpu.ops import nn as N
    x = jnp.asarray(np.random.RandomState(3).randn(2, 4, 10, 10),
                    jnp.float32)
    ref_fn = lambda v: apply_relu(N.max_pool2d(v, 3, 3, 2))  # noqa: E731
    ref = ref_fn(x)
    g = jnp.asarray(np.random.RandomState(4).randn(*ref.shape),
                    jnp.float32)
    dref = jax.vjp(ref_fn, x)[1](g)[0]
    saved = engine.opts.pool_relu_fuse
    try:
        for fuse in ("0", "1"):
            engine.opts.set("pool_relu_fuse", fuse)
            got = N.max_pool2d_relu(x, 3, 3, 2)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
            dgot = jax.vjp(lambda v: N.max_pool2d_relu(v, 3, 3, 2),
                           x)[1](g)[0]
            np.testing.assert_array_equal(np.asarray(dgot),
                                          np.asarray(dref))
    finally:
        engine.opts.set("pool_relu_fuse", saved)


@pytest.mark.parametrize("geom", [
    (8, 3, 23, 23, 16, 11, 4),   # AlexNet conv1 class (kb=3)
    (4, 3, 18, 18, 8, 5, 2),     # 5x5/s2 class (kb=3)
])
def test_conv_wgrad_hwcn_matches_xla(geom):
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops import nn as N
    from cxxnet_tpu.ops.pallas_kernels import conv_wgrad_hwcn_pallas
    n, c, h, w_, co, k, s = geom
    rnd = np.random.RandomState(3)
    x = jnp.asarray(rnd.randn(n, c, h, w_), jnp.float32)
    wt = jnp.asarray(rnd.randn(co, c, k, k) * 0.1, jnp.float32)
    oh = (h - k) // s + 1
    dy = jnp.asarray(rnd.randn(n, co, oh, oh), jnp.float32)
    _, vjp = jax.vjp(lambda wv: N.conv2d(x, wv, stride=s), wt)
    (dw_ref,) = vjp(dy)
    dw, db = conv_wgrad_hwcn_pallas(x, dy, kh=k, kw=k, stride=s)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(db),
                               np.asarray(dy.sum(axis=(0, 2, 3))),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("nsize,beta", [(5, 0.75), (3, 0.5), (4, 0.75)])
def test_lrn_band_matches_xla(nsize, beta):
    """Banded-matmul LRN (pallas_lrn = band) == chpool formulation,
    fwd + grad, including clipped edge windows and the asymmetric
    even-nsize window (lo != hi)."""
    x = jnp.asarray(np.random.RandomState(7).randn(3, 96, 5, 5),
                    jnp.float32)
    a = N.lrn_band(x, nsize, 0.001, beta, 1.0)
    b = _xla_lrn(x, nsize, 0.001, beta, 1.0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=1e-6)
    ga = jax.grad(
        lambda v: (N.lrn_band(v, nsize, .001, beta, 1.) ** 2).sum())(x)
    gb = jax.grad(
        lambda v: (_xla_lrn(v, nsize, .001, beta, 1.) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               rtol=2e-4, atol=1e-5)


def test_pool_channel_tile_legality():
    """_pick_cb must return a tile that divides c and is a multiple of 8
    (or c itself): the old halving loop landed on 60 for GoogLeNet's
    480-channel stage-3 pool, which Mosaic rejects."""
    from cxxnet_tpu.ops.pallas_kernels import (_pick_cb,
                                               max_pool_hwcn_supported)
    for c in (480, 240, 832, 96, 256, 192, 512, 64, 528):
        for per in (28 * 128 * 4 * 8, 14 * 128 * 12 * 6, 55 * 128 * 4 * 5):
            cb = _pick_cb(c, per, 10 << 20)
            assert c % cb == 0
            assert cb == c or cb % 8 == 0
    # every GoogLeNet/AlexNet pool geometry is supported; w=224 (no legal
    # tile fits the multi-row backward budget) is not
    for shape, s in [((128, 64, 112, 112), 2),
                     ((128, 192, 56, 56), 2),
                     ((128, 480, 28, 28), 2),
                     ((128, 832, 14, 14), 2),
                     ((128, 96, 55, 55), 2),
                     ((128, 256, 27, 27), 2)]:
        assert max_pool_hwcn_supported(shape, s), shape
    assert not max_pool_hwcn_supported((128, 64, 224, 224), 2)
    assert not max_pool_hwcn_supported((100, 64, 28, 28), 2)  # lanes


def _ln_rel_err(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    denom = max(np.abs(b).max(), 1e-30)
    return float(np.abs(a - b).max() / denom)


def _ln_ref(x, g, b, eps=1e-5):
    """The layer's XLA fallback formulation (two-pass f32 moments)."""
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = jnp.square(x32 - mean).mean(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y * g.astype(jnp.float32) + b.astype(jnp.float32)
    return y.astype(x.dtype)


def test_layernorm_pallas_residuals_stats_only():
    """The custom-vjp residual pytree holds NO (rows, d) buffer beyond the
    op's own output: the only (rows, d) leaf IS the primal output (same
    array — under jit the buffer aliases), the input x is absent, and the
    remaining leaves are O(rows) stats / (d,) vectors.  This is the
    round-6 un-pinning contract (the round-5 kernel saved x, pinning
    ~64 MB x 25 sites on the d2048 flagship)."""
    from cxxnet_tpu.ops.pallas_kernels import _ln_fwd_res, layernorm_pallas
    rnd = np.random.RandomState(0)
    rows, d = 512, 256
    x = jnp.asarray(rnd.randn(rows, d).astype(np.float32))
    g = jnp.asarray(rnd.rand(d).astype(np.float32) + 0.5)
    b = jnp.asarray(rnd.randn(d).astype(np.float32))
    y, res = _ln_fwd_res(x, g, b, 1e-5, True)
    leaves = jax.tree_util.tree_leaves(res)
    big = [l for l in leaves if l.size >= rows * d]
    assert big and all(l is y for l in big), (
        "residuals must not contain any (rows, d) array besides the "
        "aliased primal output")
    assert not any(l.shape == x.shape and np.allclose(l, x)
                   for l in leaves if l is not y), "input x was saved"
    # every other leaf is O(rows) or O(d)
    assert all(l.size <= max(rows, d) for l in leaves if l is not y)
    # and the vjp closure (what jax actually keeps live for backward)
    # carries exactly ONE distinct (rows, d) buffer — the output
    yv, vjp = jax.vjp(lambda *a: layernorm_pallas(*a, 1e-5, True), x, g, b)
    closure_big = [l for l in jax.tree_util.tree_leaves(vjp)
                   if hasattr(l, "size") and l.size >= rows * d]
    ptrs = {l.unsafe_buffer_pointer() for l in closure_big}
    assert len(ptrs) == 1
    assert yv.unsafe_buffer_pointer() in ptrs


@pytest.mark.parametrize("rows,d,dtype,tol", [
    # flagship-shaped (d2048 L12 s4096): ~50 s each on CPU, slow-marked
    # — the (384, 640) params cover the same kernel paths in tier 1
    pytest.param(16384, 2048, jnp.float32, 1e-5,
                 marks=pytest.mark.slow),
    pytest.param(16384, 2048, jnp.bfloat16, 1e-1,
                 marks=pytest.mark.slow),
    (384, 640, jnp.float32, 1e-5),      # non-square, odd row-block shape
    (384, 640, jnp.bfloat16, 1e-1),
])
def test_layernorm_pallas_bwd_parity(rows, d, dtype, tol):
    """Output-derived backward == the jnp reference LN for dx, dgamma,
    dbeta (max rel-err: f32 <= 1e-5, bf16 <= 1e-1 — the documented
    pairtest envelope), at the flagship shape and a non-square one."""
    from cxxnet_tpu.ops.pallas_kernels import (layernorm_pallas,
                                               layernorm_pallas_supported)
    assert layernorm_pallas_supported(rows, d)
    rnd = np.random.RandomState(42)
    x = jnp.asarray(rnd.randn(rows, d).astype(np.float32)).astype(dtype)
    g = jnp.asarray((rnd.rand(d).astype(np.float32) + 0.5)).astype(dtype)
    b = jnp.asarray((rnd.randn(d).astype(np.float32) * 0.5)).astype(dtype)
    dy = jnp.asarray(rnd.randn(rows, d).astype(np.float32)).astype(dtype)
    y1, vjp1 = jax.vjp(lambda *a: layernorm_pallas(*a, 1e-5, True), x, g, b)
    y2, vjp2 = jax.vjp(_ln_ref, x, g, b)
    assert _ln_rel_err(y1, y2) <= tol
    g1, g2 = vjp1(dy), vjp2(dy)
    for a, bb, nm in zip(g1, g2, ("dx", "dgamma", "dbeta")):
        err = _ln_rel_err(a, bb)
        assert err <= tol, f"{nm}: rel err {err:.3e} > {tol}"


def test_layernorm_pallas_save_x_small_gamma():
    """The output-derived rebuild amplifies stored-dtype rounding by
    ~(|y|+|beta|)/|gamma| (cancellation in y - beta), so bf16 columns
    with |beta| >> |gamma| can exceed the 1e-1 envelope.  The save_x
    escape hatch (pallas_ln = x) must stay tight there: it reads the
    saved input, no gamma division."""
    from cxxnet_tpu.ops.pallas_kernels import _ln_fwd_res, layernorm_pallas
    rnd = np.random.RandomState(11)
    rows, d = 256, 256
    x = jnp.asarray(rnd.randn(rows, d).astype(np.float32)).astype(
        jnp.bfloat16)
    g = jnp.full((d,), 0.01, jnp.bfloat16)       # small-but-nonzero gamma
    b = jnp.asarray(rnd.randn(d).astype(np.float32)).astype(jnp.bfloat16)
    dy = jnp.asarray(rnd.randn(rows, d).astype(np.float32)).astype(
        jnp.bfloat16)
    g1 = jax.vjp(lambda *a: layernorm_pallas(*a, 1e-5, True, True),
                 x, g, b)[1](dy)
    g2 = jax.vjp(_ln_ref, x, g, b)[1](dy)
    for a, bb, nm in zip(g1, g2, ("dx", "dgamma", "dbeta")):
        err = _ln_rel_err(a, bb)
        assert err <= 1e-1, f"save_x {nm}: rel err {err:.3e}"
    # and save_x residuals are the round-5 set: x IS saved
    _, res = _ln_fwd_res(x, g, b, 1e-5, True, True)
    assert any(l.shape == x.shape and np.array_equal(
        np.asarray(l, np.float32), np.asarray(x, np.float32))
        for l in jax.tree_util.tree_leaves(res))


def test_layernorm_pallas_zero_gamma_guard():
    """Columns where gamma is EXACTLY zero can't rebuild xhat from the
    output; the kernel substitutes xhat=0 there.  The backward must stay
    finite, dbeta stays exact, and the zeroed column's dgamma is 0."""
    from cxxnet_tpu.ops.pallas_kernels import layernorm_pallas
    rnd = np.random.RandomState(3)
    rows, d = 64, 256
    x = jnp.asarray(rnd.randn(rows, d).astype(np.float32))
    g = jnp.asarray(rnd.rand(d).astype(np.float32) + 0.5).at[7].set(0.0)
    b = jnp.asarray(rnd.randn(d).astype(np.float32))
    dy = jnp.asarray(rnd.randn(rows, d).astype(np.float32))
    _, vjp = jax.vjp(lambda *a: layernorm_pallas(*a, 1e-5, True), x, g, b)
    dx, dg, db = vjp(dy)
    assert np.isfinite(np.asarray(dx)).all()
    assert float(dg[7]) == 0.0
    np.testing.assert_allclose(np.asarray(db), np.asarray(dy.sum(0)),
                               rtol=1e-6, atol=1e-5)


def test_layernorm_default_on_and_layer_route(monkeypatch):
    """pallas_ln defaults ON; on (emulated) TPU the layernorm layer routes
    through layernorm_pallas wherever layernorm_pallas_supported holds."""
    import cxxnet_tpu.engine as engine
    from cxxnet_tpu.layers.base import ForwardContext
    from cxxnet_tpu.layers.sequence import LayerNormLayer
    from cxxnet_tpu.ops import pallas_kernels as pk
    # the fresh-default assert must not read a CXXNET_PALLAS_LN the shell
    # exported for an A/B session (doc/pallas_ln.md recipe)
    monkeypatch.delenv("CXXNET_PALLAS_LN", raising=False)
    assert engine._Options().pallas_ln == "1"  # fresh default (no env)
    monkeypatch.setattr(engine.opts, "pallas_ln", "1")
    monkeypatch.setattr(pk, "_on_tpu", lambda: True)
    calls = []
    real = pk.layernorm_pallas

    def spy(x, g, b, eps, interpret=None, save_x=False):
        calls.append(x.shape)
        return real(x, g, b, eps, True, save_x)  # interpret: still on CPU
    monkeypatch.setattr(pk, "layernorm_pallas", spy)
    layer = LayerNormLayer()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 1, 8, 128),
                    jnp.float32)
    params = layer.init_params(jax.random.PRNGKey(0), [x.shape])
    (y,), _ = layer.forward(params, {}, [x], ForwardContext(train=True))
    assert calls == [(16, 128)]
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_ln_ref(x, params["wmat"],
                                          params["bias"])).reshape(x.shape),
        rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("wd,clip,epoch", [(0.0, 0.0, 0), (0.001, 0.5, 7)])
def test_fused_adam_matches_reference(wd, clip, epoch):
    """fused_adam_pallas == AdamUpdater's XLA path (param, moments, and
    master) for bf16-master tensors, including clip/wd and bias
    correction, over multiple chained steps."""
    from cxxnet_tpu.engine import opts
    from cxxnet_tpu.ops import pallas_kernels as pk
    from cxxnet_tpu.updater.updaters import AdamUpdater, UpdaterHyper
    rnd = np.random.RandomState(1)
    p = jnp.asarray(rnd.randn(16, 1024) * 0.1).astype(jnp.bfloat16)
    u = AdamUpdater()
    hyper = UpdaterHyper(tag="wmat", base_lr=0.01, wd=wd,
                         clip_gradient=clip)
    assert pk.fused_adam_supported(p)
    assert not pk.fused_adam_supported(p.astype(jnp.float32))  # no master
    assert not pk.fused_adam_supported(  # odd size
        jnp.zeros((3, 1000), jnp.bfloat16))
    s_ref = u.make_state(p)
    s_fu = jax.tree.map(lambda a: a, s_ref)
    p_ref = p_fu = p
    for step in range(3):
        g = jnp.asarray(rnd.randn(16, 1024) * 0.01).astype(jnp.bfloat16)
        if step == 1 and clip:
            g = g.at[0, 0].set(jnp.nan).at[0, 1].set(5.0)  # clip paths
        p_ref, s_ref = u.apply(p_ref, g, s_ref, hyper, epoch + step)
        saved = opts.fused_update
        try:
            opts.set("fused_update", "1")
            p_fu, s_fu = u.apply(p_fu, g, s_fu, hyper, epoch + step)
        finally:
            opts.set("fused_update", saved)
        # tolerances: the two lowerings contract multiply-adds
        # differently (FMA), so states differ by a couple of f32 ULPs;
        # params by at most one bf16 rounding step
        np.testing.assert_allclose(np.asarray(p_fu, np.float32),
                                   np.asarray(p_ref, np.float32),
                                   atol=4e-3, rtol=0)
        for k in ("m1", "m2", "w32"):
            np.testing.assert_allclose(
                np.asarray(s_fu[k]), np.asarray(s_ref[k]),
                rtol=1e-5, atol=1e-7, err_msg=f"{k} step {step}")


def test_flash_attention_multiblock_causal_grads():
    """jax.grad parity vs dense_attention through the TRIANGULAR causal
    grids with several blocks per row/column: asymmetric (256, 512)
    blocks and a square bq==bk (256, 256) case.  Exercises the
    _fa_dq_kernel_tri jlast and _fa_dkv_kernel_tri ifirst boundaries
    past one block (ADVICE r5 medium: they were previously never run
    with nq, nk > 1)."""
    from cxxnet_tpu.ops import pallas_kernels as pk
    from cxxnet_tpu.parallel.ring import dense_attention
    rnd = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rnd.randn(1, 2, 1024, 32).astype(np.float32)
                           * 0.5) for _ in range(3))
    gr = jax.grad(lambda *a: jnp.sum(
        dense_attention(*a, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    old_blocks = pk._fa_blocks
    try:
        for blocks in ((256, 512), (256, 256)):
            pk._fa_blocks = lambda s, d=64, b=blocks: b
            out = pk.flash_attention(q, k, v, True)
            ref = dense_attention(q, k, v, causal=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=1e-5, err_msg=str(blocks))
            gf = jax.grad(lambda *a: jnp.sum(
                pk.flash_attention(*a, True) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            for a, b, nm in zip(gf, gr, ("dq", "dk", "dv")):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=2e-4,
                    err_msg=f"{nm} blocks={blocks}")
    finally:
        pk._fa_blocks = old_blocks


def test_layernorm_pallas_matches_xla():
    """layernorm_pallas fwd + all three grads == the XLA formulation
    (sequence.LayerNormLayer's fallback path)."""
    from cxxnet_tpu.ops.pallas_kernels import layernorm_pallas
    rnd = np.random.RandomState(0)
    x = jnp.asarray(rnd.randn(64, 256).astype(np.float32))
    g = jnp.asarray(rnd.rand(256).astype(np.float32) + 0.5)
    b = jnp.asarray(rnd.randn(256).astype(np.float32))

    def ref(x, g, b):
        mean = x.mean(-1, keepdims=True)
        var = jnp.square(x - mean).mean(-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + 1e-5) * g + b

    y1 = layernorm_pallas(x, g, b, 1e-5, True)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(ref(x, g, b)),
                               rtol=1e-5, atol=1e-5)
    dy = jnp.asarray(rnd.randn(64, 256).astype(np.float32))
    g1 = jax.vjp(lambda *a: layernorm_pallas(*a, 1e-5, True), x, g, b)[1](dy)
    g2 = jax.vjp(ref, x, g, b)[1](dy)
    for a, bb, nm in zip(g1, g2, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-5, err_msg=nm)
