"""C ABI tests (native/capi.cc — wrapper/cxxnet_wrapper.h parity).

Two layers of coverage:
* in-process ctypes: the .so reuses this interpreter (Py_IsInitialized path),
  exercising CXNNet train/predict and the CXNIO iterator surface;
* subprocess: ``capi_demo`` embeds a FRESH interpreter from plain C and
  trains/saves/reloads a net (built + run only when the lib compiles).
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "native", "libcxxnet_capi.so")


def _build_lib():
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                            "libcxxnet_capi.so"], capture_output=True)
        if r.returncode != 0:
            pytest.skip(f"cannot build capi lib: {r.stderr.decode()[-200:]}")
    return LIB


@pytest.fixture(scope="module")
def capi():
    lib = ctypes.CDLL(_build_lib())
    u64p = ctypes.POINTER(ctypes.c_uint64)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.CXNNetCreate.restype = ctypes.c_void_p
    lib.CXNNetCreate.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.CXNNetFree.argtypes = [ctypes.c_void_p]
    lib.CXNNetSetParam.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p]
    lib.CXNNetInitModel.argtypes = [ctypes.c_void_p]
    lib.CXNNetUpdateBatch.argtypes = [ctypes.c_void_p, f32p, u64p,
                                      ctypes.c_int, f32p, u64p, ctypes.c_int]
    lib.CXNNetPredictBatch.restype = f32p
    lib.CXNNetPredictBatch.argtypes = [ctypes.c_void_p, f32p, u64p,
                                       ctypes.c_int, u64p,
                                       ctypes.POINTER(ctypes.c_int)]
    lib.CXNGetLastError.restype = ctypes.c_char_p
    lib.CXNIOCreateFromConfig.restype = ctypes.c_void_p
    lib.CXNIOCreateFromConfig.argtypes = [ctypes.c_char_p]
    lib.CXNIONext.argtypes = [ctypes.c_void_p]
    lib.CXNIOBeforeFirst.argtypes = [ctypes.c_void_p]
    lib.CXNIOGetData.restype = f32p
    lib.CXNIOGetData.argtypes = [ctypes.c_void_p, u64p,
                                 ctypes.POINTER(ctypes.c_int)]
    lib.CXNIOGetLabel.restype = f32p
    lib.CXNIOGetLabel.argtypes = [ctypes.c_void_p, u64p,
                                  ctypes.POINTER(ctypes.c_int)]
    lib.CXNIOFree.argtypes = [ctypes.c_void_p]
    return lib


NET_CFG = b"""
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 8
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 2
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,6
batch_size = 16
updater = sgd
eta = 0.3
"""


def _f32(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u64(*vals):
    return (ctypes.c_uint64 * len(vals))(*vals)


def test_capi_train_predict(capi):
    net = capi.CXNNetCreate(b"cpu", NET_CFG)
    assert net, capi.CXNGetLastError()
    assert capi.CXNNetInitModel(net) == 0, capi.CXNGetLastError()

    rng = np.random.RandomState(0)

    def train_steps(n):
        for _ in range(n):
            xb = rng.rand(16, 1, 1, 6).astype(np.float32)
            yb = (xb.reshape(16, 6).sum(1) > 3).astype(np.float32) \
                .reshape(16, 1)
            xb[:, 0, 0, 0] += 2.0 * yb[:, 0]  # make it clearly separable
            assert capi.CXNNetUpdateBatch(net, _f32(xb), _u64(16, 1, 1, 6),
                                          4, _f32(yb), _u64(16, 1), 2) == 0

    train_steps(80)

    x = rng.rand(16, 1, 1, 6).astype(np.float32)
    y = (x.reshape(16, 6).sum(1) > 3).astype(np.float32)
    x[:, 0, 0, 0] += 2.0 * y
    oshape = _u64(0, 0, 0, 0)
    ondim = ctypes.c_int(0)

    def accuracy():
        pred = capi.CXNNetPredictBatch(net, _f32(x), _u64(16, 1, 1, 6), 4,
                                       oshape, ctypes.byref(ondim))
        assert pred, capi.CXNGetLastError()
        got = np.ctypeslib.as_array(pred, shape=(16,)).copy()
        return (got == y).mean()

    acc = accuracy()
    for _ in range(3):  # marginal under parallel-reduction
        if acc > 0.8:   # nondeterminism: keep training rather than flake
            break
        train_steps(80)
        acc = accuracy()
    assert acc > 0.8, acc
    capi.CXNNetFree(net)


def test_capi_bad_config_sets_error(capi):
    net = capi.CXNNetCreate(b"cpu", b"netconfig=start\nlayer[0->1] = nosuch\n"
                                    b"netconfig=end\nbatch_size=4\n"
                                    b"input_shape=1,1,4\n")
    # failure may surface at create or init_model depending on laziness
    if net:
        assert capi.CXNNetInitModel(net) != 0
        capi.CXNNetFree(net)
    assert b"nosuch" in capi.CXNGetLastError() or capi.CXNGetLastError()


def test_capi_io_iterator(capi, tmp_path):
    subprocess.run([sys.executable,
                    os.path.join(REPO, "tools", "make_synth_mnist.py"),
                    "--out", str(tmp_path), "--train", "64",
                    "--test", "32"], check=True)
    cfg = (f"iter = mnist\n"
           f"path_img = {tmp_path}/train-images-idx3-ubyte.gz\n"
           f"path_label = {tmp_path}/train-labels-idx1-ubyte.gz\n"
           f"input_flat = 0\n"
           f"batch_size = 16\n").encode()
    it = capi.CXNIOCreateFromConfig(cfg)
    assert it, capi.CXNGetLastError()
    assert capi.CXNIOBeforeFirst(it) == 0
    nbatch = 0
    oshape = _u64(0, 0, 0, 0)
    ondim = ctypes.c_int(0)
    while capi.CXNIONext(it) == 1:
        d = capi.CXNIOGetData(it, oshape, ctypes.byref(ondim))
        assert d and ondim.value == 4
        assert tuple(oshape) == (16, 1, 28, 28)
        lab = capi.CXNIOGetLabel(it, oshape, ctypes.byref(ondim))
        assert lab and ondim.value == 2
        nbatch += 1
    assert nbatch == 4  # 64 / 16
    capi.CXNIOFree(it)


def test_capi_demo_subprocess():
    """Fresh-interpreter embedding: the pure-C demo trains and reloads."""
    r = subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                        "capi_demo"], capture_output=True)
    if r.returncode != 0:
        pytest.skip("cannot build capi_demo")
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([os.path.join(REPO, "native", "capi_demo")],
                       capture_output=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr.decode()[-400:]
    assert b"accuracy" in r.stdout

def test_cxxnet_binary_trains(tmp_path):
    """The standalone `cxxnet` binary (reference bin/cxxnet UX) runs the
    full train task from a config file."""
    r = subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                        "cxxnet"], capture_output=True)
    if r.returncode != 0:
        pytest.skip("cannot build cxxnet binary")
    subprocess.run([sys.executable,
                    os.path.join(REPO, "tools", "make_synth_mnist.py"),
                    "--out", str(tmp_path), "--train", "256", "--test", "64"],
                   check=True)
    conf = tmp_path / "t.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = mnist
  path_img = {tmp_path}/train-images-idx3-ubyte.gz
  path_label = {tmp_path}/train-labels-idx1-ubyte.gz
  shuffle = 1
iter = end
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 32
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 10
layer[+0] = softmax
netconfig=end
input_shape = 1,1,784
batch_size = 32
eta = 0.1
num_round = 2
metric = error
model_dir = {tmp_path}/models
silent = 1
""")
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([os.path.join(REPO, "native", "cxxnet"), str(conf)],
                       capture_output=True, env=env, timeout=600)
    assert r.returncode == 0, r.stderr.decode()[-400:]
    assert b"train-error" in r.stderr
    assert (tmp_path / "models" / "0002.model").exists()
