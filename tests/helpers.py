"""Shared test harness: drive a single layer through infer_shapes /
init_params / forward against numpy inputs (the PairTest-style differential
strategy, used by test_layers.py and test_sequence.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from cxxnet_tpu.layers.base import ForwardContext
from cxxnet_tpu.layers.registry import create_layer


def ctx_eval():
    return ForwardContext(train=False)


def ctx_train(seed=0):
    return ForwardContext(train=True, rng=jax.random.PRNGKey(seed))


def run_layer(type_name, x, cfg=None, train=False, in_shapes=None, seed=0,
              ctx=None):
    layer = create_layer(type_name)
    for k, v in (cfg or {}).items():
        layer.set_param(k, str(v))
    xs = x if isinstance(x, list) else [x]
    shapes = in_shapes or [tuple(a.shape) for a in xs]
    out_shapes = layer.infer_shapes(shapes)
    params = layer.init_params(jax.random.PRNGKey(42), shapes)
    buffers = layer.init_buffers(shapes)
    if ctx is None:
        ctx = ctx_train(seed) if train else ctx_eval()
    outs, _ = layer.forward(params, buffers,
                            [jnp.asarray(a) for a in xs], ctx)
    for o, s in zip(outs, out_shapes):
        assert tuple(o.shape) == s, f"{type_name}: shape {o.shape} != {s}"
    return [np.asarray(o) for o in outs], params


def rand4(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)
