"""Device-side input prefetch (io/device_prefetch.py): bitwise parity of
prefetch-on vs prefetch-off training, device-residency of staged inputs
(zero device_put inside the dispatch window), h2d/staging-depth telemetry,
producer-exception propagation, and thread hygiene."""

import json
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

import jax  # noqa: E402

from cxxnet_tpu.io.data import DataBatch, IIterator  # noqa: E402
from cxxnet_tpu.io.device_prefetch import DevicePrefetcher  # noqa: E402
from cxxnet_tpu.main import LearnTask  # noqa: E402
from cxxnet_tpu.nnet.trainer import NetTrainer  # noqa: E402
from cxxnet_tpu.utils import serializer  # noqa: E402

from test_main import MLP_NET, _write_synth_mnist  # noqa: E402


# --------------------------------------------------------------- CLI parity

def _write_conf(tmp_path, n, extra_cfg, sink):
    _write_synth_mnist(tmp_path, n=n)
    conf = tmp_path / f"train_{len(extra_cfg)}.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 1
iter = end
eval = val
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
num_round = 3
metric = error
print_step = 1
silent = 1
metrics_sink = jsonl:{sink}
{extra_cfg}
""")
    return conf


def _train_once(tmp_path, n, extra_cfg, tag, prefetch):
    sink = tmp_path / f"metrics_{tag}_{prefetch}.jsonl"
    model_dir = tmp_path / f"models_{tag}_{prefetch}"
    conf = _write_conf(tmp_path, n, extra_cfg, sink)
    task = LearnTask()
    assert task.run([str(conf), f"prefetch_device={prefetch}",
                     f"model_dir={model_dir}", "save_model=3"]) == 0
    recs = [json.loads(l) for l in open(sink)]
    losses = [r["loss"] for r in recs if r["kind"] == "step"]
    rounds = [r for r in recs if r["kind"] == "round"]
    _, params, _, _ = serializer.load_model(str(model_dir / "0003.model"))
    return losses, rounds, params


# tail masking (40 = 2 full + masked tail of 8), round_batch wrap,
# multi_step grouping, and gradient accumulation — the four paths whose
# staging differs (ISSUE 3 satellite: prefetch correctness coverage)
@pytest.mark.parametrize("tag,n,extra_cfg", [
    ("tail", 40, ""),
    ("roundb", 40, "round_batch = 1"),
    ("mstep", 64, "multi_step = 2"),
    ("uperiod", 64, "update_period = 2"),
])
def test_prefetch_on_off_bitwise_identical(tmp_path, tag, n, extra_cfg):
    off = _train_once(tmp_path, n, extra_cfg, tag, prefetch=0)
    on = _train_once(tmp_path, n, extra_cfg, tag, prefetch=2)
    assert len(off[0]) == len(on[0]) and len(off[0]) > 0
    assert off[0] == on[0], "per-step losses must be bitwise identical"
    # eval ran through the prefetcher in the 'on' run: same metrics
    for r_off, r_on in zip(off[1], on[1]):
        assert r_off["val-error"] == r_on["val-error"]
        assert r_off["train-error"] == r_on["train-error"]
    flat_off = jax.tree.leaves(off[2])
    flat_on = jax.tree.leaves(on[2])
    assert len(flat_off) == len(flat_on)
    for a, b in zip(flat_off, flat_on):
        np.testing.assert_array_equal(a, b)


def test_pred_raw_prefetch_matches(tmp_path):
    """task=pred_raw through the staged inference path gives the same
    scores file as the unprefetched loop."""
    sink = tmp_path / "m.jsonl"
    conf = _write_conf(tmp_path, 40, "", sink)
    task = LearnTask()
    assert task.run([str(conf), f"model_dir={tmp_path}/models",
                     "save_model=3"]) == 0
    pred_conf = tmp_path / "pred.conf"
    pred_conf.write_text(f"""
dev = cpu
task = pred_raw
model_in = {tmp_path}/models/0003.model
pred = {tmp_path}/scores.txt
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
silent = 1
""")
    outs = []
    for pf in (0, 2):
        out = tmp_path / f"scores_{pf}.txt"
        assert LearnTask().run([str(pred_conf), f"prefetch_device={pf}",
                                f"pred={out}"]) == 0
        outs.append(out.read_text())
    assert outs[0] == outs[1]


# ----------------------------------------------- device residency + records

def _spy_trainer(monkeypatch, state):
    """Count host->device conversions performed by the dispatch thread
    INSIDE update/update_many, and assert staged inputs arrive as
    jax.Arrays.  The producer thread stages concurrently by design, so
    only calls from the thread that entered the dispatch count."""
    orig_put = NetTrainer._device_put
    orig_update = NetTrainer.update
    orig_many = NetTrainer.update_many

    def spy_put(self, arr, dtype, sharding, global_shape_fn):
        host_input = not (isinstance(arr, jax.Array)
                          and not isinstance(arr, np.ndarray))
        if host_input and \
                threading.get_ident() == state.get("dispatch_thread"):
            state["violations"] += 1
        return orig_put(self, arr, dtype, sharding, global_shape_fn)

    def spy_update(self, batch):
        assert isinstance(batch.data, jax.Array)
        assert isinstance(batch.label, jax.Array)
        assert all(isinstance(e, jax.Array) for e in batch.extra_data)
        state["updates"] += 1
        state["dispatch_thread"] = threading.get_ident()
        try:
            return orig_update(self, batch)
        finally:
            state["dispatch_thread"] = None

    def spy_many(self, datas, labels, with_outs=False):
        assert isinstance(datas, jax.Array)
        assert isinstance(labels, jax.Array)
        state["update_manys"] += 1
        state["dispatch_thread"] = threading.get_ident()
        try:
            return orig_many(self, datas, labels, with_outs)
        finally:
            state["dispatch_thread"] = None

    monkeypatch.setattr(NetTrainer, "_device_put", spy_put)
    monkeypatch.setattr(NetTrainer, "update", spy_update)
    monkeypatch.setattr(NetTrainer, "update_many", spy_many)


@pytest.mark.parametrize("extra_cfg,expect", [
    ("", "updates"),                    # per-batch path (incl. masked tail)
    ("multi_step = 2", "update_manys"),  # grouped scan path
])
def test_staged_inputs_device_resident_zero_h2d_in_dispatch(
        tmp_path, monkeypatch, extra_cfg, expect):
    state = {"violations": 0, "updates": 0, "update_manys": 0,
             "dispatch_thread": None}
    _spy_trainer(monkeypatch, state)
    sink = tmp_path / "m.jsonl"
    conf = _write_conf(tmp_path, 40, extra_cfg, sink)
    assert LearnTask().run([str(conf), "save_model=0",
                            "prefetch_device=2"]) == 0
    assert state[expect] > 0
    assert state["violations"] == 0, (
        "device_put of host data ran inside the dispatch window")
    steps = [json.loads(l) for l in open(sink)]
    steps = [r for r in steps if r["kind"] == "step"]
    assert steps and all("h2d_sec" in r and "staging_depth" in r
                         and "dispatch_sec" in r for r in steps)
    # transfers happened — on the producer thread, reported separately
    assert sum(r["h2d_sec"] for r in steps) > 0


def test_round_record_carries_h2d(tmp_path):
    sink = tmp_path / "m.jsonl"
    conf = _write_conf(tmp_path, 40, "", sink)
    assert LearnTask().run([str(conf), "save_model=0"]) == 0
    rounds = [json.loads(l) for l in open(sink)]
    rounds = [r for r in rounds if r["kind"] == "round"]
    assert rounds and all("h2d_sec" in r for r in rounds)


# ------------------------------------------------- prefetcher unit behavior

class _ListBatchIter(IIterator):
    """Assembled-batch iterator over given arrays, optionally raising
    after ``fail_after`` batches."""

    def __init__(self, nbatch=4, fail_after=None):
        rnd = np.random.RandomState(0)
        self.batches = [
            DataBatch(data=rnd.rand(4, 1, 4, 4).astype(np.float32),
                      label=np.zeros((4, 1), np.float32),
                      index=np.arange(4, dtype=np.uint32))
            for _ in range(nbatch)]
        self.fail_after = fail_after
        self.pos = 0

    def before_first(self):
        self.pos = 0

    def next(self):
        if self.fail_after is not None and self.pos >= self.fail_after:
            raise RuntimeError("host decode failed")
        if self.pos >= len(self.batches):
            return None
        self.pos += 1
        return self.batches[self.pos - 1]


class _FakeStager:
    """Stager stub: staging identity, no device work (unit tests only
    exercise the queue/thread protocol)."""

    def stage_batch(self, b):
        b.h2d_sec = 0.0
        return b

    def stage_group(self, group):  # pragma: no cover - group_n=1 in tests
        raise AssertionError("not used")

    stage_eval_group = stage_group


def test_prefetcher_producer_exception_propagates():
    pf = DevicePrefetcher(_ListBatchIter(fail_after=2), _FakeStager(),
                          group_n=1, depth=2)
    pf.before_first()
    assert pf.next() is not None
    assert pf.next() is not None
    with pytest.raises(RuntimeError, match="host decode failed"):
        pf.next()
    with pytest.raises(RuntimeError):
        pf.next()  # the epoch stays dead — re-raise, never a hang
    pf.close()


def test_prefetcher_sync_mode_exception_propagates():
    pf = DevicePrefetcher(_ListBatchIter(fail_after=1), _FakeStager(),
                          group_n=1, depth=0)
    pf.before_first()
    assert pf.next() is not None
    with pytest.raises(RuntimeError, match="host decode failed"):
        pf.next()
    with pytest.raises(RuntimeError):
        pf.next()  # latched like async mode — never a silent clean end
    pf.close()


def test_training_diverged_joins_producer_threads(tmp_path):
    """monitor_nan=fatal mid-round: TrainingDiverged must propagate out
    of the CLI run WITHOUT leaking the device-staging producer thread
    (ISSUE 4 satellite: the task's finally joins it, not process exit)."""
    from cxxnet_tpu.monitor import TrainingDiverged
    baseline = threading.active_count()
    sink = tmp_path / "m.jsonl"
    conf = _write_conf(tmp_path, 64, """
monitor = 1
monitor_interval = 1
monitor_nan = fatal
""", sink)
    task = LearnTask()
    with pytest.raises(TrainingDiverged):
        # eta large enough that the first monitored step sees a
        # non-finite loss deterministically
        task.run([str(conf), "prefetch_device=2", "save_model=0",
                  "eta=1e30"])
    assert threading.active_count() == baseline, \
        "producer thread leaked past TrainingDiverged"


def test_midround_exception_joins_eval_prefetchers(tmp_path, monkeypatch):
    """An exception in round 2 — after the per-eval prefetchers were
    created by round 1's evaluation — joins THEIR producer threads too
    (they are closed in task_train's finally, not only at run() exit)."""
    baseline = threading.active_count()
    sink = tmp_path / "m.jsonl"
    conf = _write_conf(tmp_path, 64, "", sink)
    calls = {"n": 0}
    orig = NetTrainer.update

    def boom(self, batch):
        calls["n"] += 1
        if calls["n"] > 5:  # 4 steps/round: round 2, mid-round
            raise RuntimeError("mid-round failure")
        return orig(self, batch)

    monkeypatch.setattr(NetTrainer, "update", boom)
    task = LearnTask()
    with pytest.raises(RuntimeError, match="mid-round failure"):
        task.run([str(conf), "prefetch_device=2", "save_model=0"])
    assert task._eval_prefetchers is None, \
        "eval prefetchers must be closed by the task's finally"
    assert threading.active_count() == baseline


def test_prefetcher_thread_hygiene_across_epochs():
    """threading.active_count() returns to baseline after close(), with
    no per-epoch thread accumulation across before_first() cycles."""
    baseline = threading.active_count()
    pf = DevicePrefetcher(_ListBatchIter(nbatch=6), _FakeStager(),
                          group_n=1, depth=2)
    for _ in range(5):
        pf.before_first()
        n = 0
        while pf.next() is not None:
            n += 1
        assert n == 6
        # one producer at most (may already have exited after the epoch)
        assert threading.active_count() <= baseline + 1
    pf.close()
    assert threading.active_count() == baseline
