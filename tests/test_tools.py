"""Partition-maker tool tests (reference tools/imgbin-partition-maker.py).

Round-trip: shard a list, pack each shard, read the multi-part set back via
the imgbin iterator's %d sharding with dist_worker_rank worker splits.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from test_io import _fake_jpegs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

cv2 = pytest.importorskip("cv2")


def _run_tool(*args):
    subprocess.run([sys.executable, os.path.join(REPO, "tools/partition_maker.py"),
                    *args], check=True, cwd=REPO)


def test_import_pretrained_torch_roundtrip(tmp_path):
    """tools/import_pretrained.py maps a torch state_dict onto net layers
    (the caffe plugin's pretrained-blob import role,
    caffe_adapter-inl.hpp:172-183) and the saved model reloads with the
    imported values."""
    torch = pytest.importorskip("torch")
    import sys
    sys.path.insert(0, "/root/repo/tools")
    from import_pretrained import import_pretrained

    conf = tmp_path / "net.conf"
    conf.write_text("""
netconfig=start
layer[0->1] = conv:c1
  kernel_size = 3
  nchannel = 4
  init_sigma = 0.1
layer[1->2] = flatten
layer[2->3] = fullc:f1
  nhidden = 3
  init_sigma = 0.1
layer[3->3] = softmax
netconfig=end
input_shape = 2,6,6
batch_size = 4
dev = cpu
eta = 0.1
metric = error
silent = 1
""")
    tm = torch.nn.Sequential(
        torch.nn.Conv2d(2, 4, 3), torch.nn.Flatten(),
        torch.nn.Linear(4 * 4 * 4, 3))
    pt = tmp_path / "w.pt"
    torch.save(tm.state_dict(), str(pt))
    mp = tmp_path / "map.conf"
    mp.write_text("""
c1/wmat = 0.weight
c1/bias = 0.bias
f1/wmat = 2.weight
f1/bias = 2.bias
""")
    out = tmp_path / "imported.model"
    t = import_pretrained(str(conf), str(pt), str(mp), str(out))
    np.testing.assert_allclose(
        t.get_weight("c1", "wmat"),
        tm[0].weight.detach().numpy(), rtol=1e-6)
    # reload into a fresh trainer: imported values survive the checkpoint
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_file
    t2 = NetTrainer()
    for k, v in parse_config_file(str(conf)):
        t2.set_param(k, v)
    t2.load_model(str(out))
    np.testing.assert_allclose(
        t2.get_weight("f1", "wmat"),
        tm[2].weight.detach().numpy(), rtol=1e-6)
    # wrong shape aborts with both shapes in the message
    bad = tmp_path / "bad.conf"
    bad.write_text("f1/wmat = 0.weight\n")
    with pytest.raises(AssertionError, match="shape"):
        import_pretrained(str(conf), str(pt), str(bad),
                          str(tmp_path / "x.model"))


def test_partition_counts_and_pack(tmp_path):
    root, lst = _fake_jpegs(tmp_path, n=11)
    out = tmp_path / "parts"
    _run_tool("--img_list", str(lst), "--img_root", str(root),
              "--out", str(out), "--prefix", "tr", "--num_parts", "3",
              "--shuffle", "1", "--pack", "1")
    lsts = sorted(p for p in os.listdir(out) if p.endswith(".lst"))
    bins = sorted(p for p in os.listdir(out) if p.endswith(".bin"))
    assert lsts == ["tr_0.lst", "tr_1.lst", "tr_2.lst"]
    assert bins == ["tr_0.bin", "tr_1.bin", "tr_2.bin"]
    sizes = [sum(1 for _ in open(out / p)) for p in lsts]
    assert sizes == [4, 4, 3]  # equal split, remainder spread

    # multi-part read-back with worker sharding (dist_num_worker=2)
    from cxxnet_tpu.io.imbin import ImageBinIterator
    seen = []
    for rank in (0, 1):
        it = ImageBinIterator()
        it.set_param("path_imgbin", str(out / "tr_%d.bin"))
        it.set_param("path_imglst", str(out / "tr_%d.lst"))
        it.set_param("imgbin_count", "3")
        it.set_param("dist_num_worker", "2")
        it.set_param("dist_worker_rank", str(rank))
        it.set_param("silent", "1")
        it.init()
        seen.append(len(list(it)))
    assert sum(seen) == 11  # the two workers together cover every instance


def test_partition_makefile(tmp_path):
    root, lst = _fake_jpegs(tmp_path, n=6)
    out = tmp_path / "parts"
    mk = tmp_path / "Gen.mk"
    _run_tool("--img_list", str(lst), "--img_root", str(root),
              "--out", str(out), "--prefix", "tr", "--num_parts", "2",
              "--makefile", str(mk), "--im2bin", "echo")
    text = mk.read_text()
    assert "tr_0.bin" in text and "tr_1.bin" in text
    subprocess.run(["make", "-f", str(mk), "-j2"], check=True, cwd=tmp_path)
