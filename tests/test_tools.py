"""Partition-maker tool tests (reference tools/imgbin-partition-maker.py).

Round-trip: shard a list, pack each shard, read the multi-part set back via
the imgbin iterator's %d sharding with dist_worker_rank worker splits.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from test_io import _fake_jpegs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

cv2 = pytest.importorskip("cv2")


def _run_tool(*args):
    subprocess.run([sys.executable, os.path.join(REPO, "tools/partition_maker.py"),
                    *args], check=True, cwd=REPO)


def test_partition_counts_and_pack(tmp_path):
    root, lst = _fake_jpegs(tmp_path, n=11)
    out = tmp_path / "parts"
    _run_tool("--img_list", str(lst), "--img_root", str(root),
              "--out", str(out), "--prefix", "tr", "--num_parts", "3",
              "--shuffle", "1", "--pack", "1")
    lsts = sorted(p for p in os.listdir(out) if p.endswith(".lst"))
    bins = sorted(p for p in os.listdir(out) if p.endswith(".bin"))
    assert lsts == ["tr_0.lst", "tr_1.lst", "tr_2.lst"]
    assert bins == ["tr_0.bin", "tr_1.bin", "tr_2.bin"]
    sizes = [sum(1 for _ in open(out / p)) for p in lsts]
    assert sizes == [4, 4, 3]  # equal split, remainder spread

    # multi-part read-back with worker sharding (dist_num_worker=2)
    from cxxnet_tpu.io.imbin import ImageBinIterator
    seen = []
    for rank in (0, 1):
        it = ImageBinIterator()
        it.set_param("path_imgbin", str(out / "tr_%d.bin"))
        it.set_param("path_imglst", str(out / "tr_%d.lst"))
        it.set_param("imgbin_count", "3")
        it.set_param("dist_num_worker", "2")
        it.set_param("dist_worker_rank", str(rank))
        it.set_param("silent", "1")
        it.init()
        seen.append(len(list(it)))
    assert sum(seen) == 11  # the two workers together cover every instance


def test_partition_makefile(tmp_path):
    root, lst = _fake_jpegs(tmp_path, n=6)
    out = tmp_path / "parts"
    mk = tmp_path / "Gen.mk"
    _run_tool("--img_list", str(lst), "--img_root", str(root),
              "--out", str(out), "--prefix", "tr", "--num_parts", "2",
              "--makefile", str(mk), "--im2bin", "echo")
    text = mk.read_text()
    assert "tr_0.bin" in text and "tr_1.bin" in text
    subprocess.run(["make", "-f", str(mk), "-j2"], check=True, cwd=tmp_path)
