"""Pairtest tolerance gate (VERDICT r5 #4 / round-6 item 4).

The round-5 pairtest-on-TPU sweep measured the shipping lowering stack's
semantic envelope against reference-literal lowerings and DOCUMENTED the
tolerances (BASELINE.md: f32-highest fwd <= 1e-6, one-step grad delta
<= 5e-3) — but ``experiments/pairtest_tpu.py`` stayed a manual harness,
so nothing re-checked the envelope when a lowering changed.  This module
promotes that check into an opt-in pytest gate: it reuses the harness's
``run_variant`` (reference vs shipping stack, identical init, same batch,
per-node forward rel-err + one-step weight-delta rel-err) and asserts the
documented numbers.

Opt-in (marked ``slow`` — two full AlexNet trainers are built and
traced); run it after any lowering change:

    python -m pytest tests/test_pairtest_gate.py -m slow

On the CPU mesh the same gate is strictly tighter (no MXU rounding), so a
pass here is necessary-but-cheaper evidence; the TPU session re-runs it
under hardware before accepting a round.  Batch is pinned to the
documented envelope's b64 (the grad residue is pool-tie ROUTING, whose
max-rel-err statistics are batch-dependent: b16 measures 8.2e-3 on CPU
where b64 sits inside the 5e-3 envelope); CXXNET_PAIRTEST_BATCH
overrides for probing only.
"""

import importlib.util
import os
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

FWD_TOL = 1e-6   # f32-highest forward envelope (BASELINE.md round 5)
GRAD_TOL = 5e-3  # f32-highest one-step grad-delta envelope


def _load_harness():
    spec = importlib.util.spec_from_file_location(
        "pairtest_tpu", REPO / "experiments" / "pairtest_tpu.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_shipping_stack_within_documented_envelope():
    import jax
    jax.config.update("jax_default_matmul_precision", "highest")
    pt = _load_harness()
    from cxxnet_tpu import engine
    batch = int(os.environ.get("CXXNET_PAIRTEST_BATCH", "64"))
    rnd = np.random.RandomState(7)
    data = rnd.rand(batch, 3, 227, 227).astype(np.float32)
    label = rnd.randint(0, 1000, (batch, 1)).astype(np.float32)
    saved = {k: getattr(engine.opts, k) for k in engine._DEFS}
    try:
        ref = pt.run_variant("alexnet", batch, "float32", "ref",
                             pt.REF, data, label)
        ship = pt.run_variant("alexnet", batch, "float32", "ship",
                              pt.SHIP, data, label)
    finally:
        for k, v in saved.items():
            engine.set_engine_option(k, v)
    ref_nodes, ref_wb, ref_wa = ref
    nodes, wb, wa = ship
    winit = max(pt.rel_err(ref_wb[k], wb[k]) for k in ref_wb)
    assert winit == 0.0, "variants must start bit-identical"
    fwd = max(pt.rel_err(ref_nodes[nm], nodes[nm]) for nm in ref_nodes
              if nm in nodes and ref_nodes[nm].shape == nodes[nm].shape)
    assert fwd <= FWD_TOL, (
        f"forward envelope broken: max node rel-err {fwd:.3e} > {FWD_TOL}")
    grad = max(pt.rel_err(ref_wa[k] - ref_wb[k], wa[k] - wb[k])
               for k in ref_wb)
    assert grad <= GRAD_TOL, (
        f"gradient envelope broken: max one-step weight-delta rel-err "
        f"{grad:.3e} > {GRAD_TOL}")
