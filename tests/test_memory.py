"""Memory observatory (doc/memory.md): per-layer HBM attribution,
peak-live timeline, and the OOM pre-flight in task=check.

* HLO buffer parsing + liveness over the checked-in fixture
  (tests/fixtures/step_mlp.hlo) with exact hand-computed numbers —
  donated-alias exclusion, in-place reuse, dead-temp skipping;
* mem_profile end-to-end on a CPU MNIST run with a profiling window —
  per-layer act rows sum to within 10% of the executable's reported
  temp allocation (the acceptance gate), param/opt rows match the
  trainer's placed trees;
* the analytic model (analysis/memmodel.py): remat / batch_split /
  accumulator corrections, chip resolution, pre-flight error with
  remediation text, task=check exit 1 on an over-budget config;
* satellites: per-device HBM gauge min/spread, the sentinel fallback
  feed, serve per-model footprint, graftlint cross-key rules.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(__file__))

from cxxnet_tpu.analysis import costmodel, memmodel, run_check
from cxxnet_tpu.monitor import memory as memlib
from cxxnet_tpu.monitor.metrics import device_memory_gauges

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HLO_FIXTURE = os.path.join(REPO, "tests", "fixtures", "step_mlp.hlo")
SCOPES = ["00-fc1", "01-act", "02-loss"]


def _fixture_text():
    with open(HLO_FIXTURE) as f:
        return f.read()


# ------------------------------------------------------------ shape parsing

def test_parse_shape_bytes():
    assert memlib.parse_shape_bytes("f32[16,16]{1,0}") == 1024
    assert memlib.parse_shape_bytes("bf16[32,32]{1,0}") == 2048
    assert memlib.parse_shape_bytes("f32[]") == 4
    assert memlib.parse_shape_bytes("pred[8]") == 8
    # tuples sum their components
    assert memlib.parse_shape_bytes(
        "(f32[16,16]{1,0}, f32[16]{0}, f32[])") == 1024 + 64 + 4
    # unknown element types count zero, never invent sizes
    assert memlib.parse_shape_bytes("token[]") == 0
    assert memlib.parse_shape_bytes("u8[100]") == 100


def test_output_aliases_balanced_braces():
    # the alias map nests braces ({0}: (0, {}, may-alias)) — the parse
    # must not stop at the first '}'
    assert memlib.output_aliases(_fixture_text()) == {0: 0, 1: 1}
    assert memlib.output_aliases("HloModule x\nENTRY e {\n}\n") == {}


# ------------------------------------------------- fixture: exact numbers

def test_entry_buffer_classes_exact():
    bufs = memlib.hlo_entry_buffers(_fixture_text(), SCOPES)
    by_class = {}
    for b in bufs:
        by_class.setdefault(b.klass, []).append(b)
    assert sum(b.bytes for b in by_class["param"]) == 1024 + 64 + 512
    # new_w/new_b write back over donated args — alias, never temp
    assert sorted(b.name for b in by_class["alias"]) \
        == ["new_b.1", "new_w.1"]
    assert sum(b.bytes for b in by_class["alias"]) == 1024 + 64
    # fresh outputs: the loss scalar + the zero-byte tuple shell
    assert sum(b.bytes for b in by_class["output"]) == 4
    temp_names = {b.name for b in by_class["temp"]}
    assert temp_names == {"dot.1", "wide.1", "fusion.1", "narrow.1",
                          "unused.1"}
    by_name = {b.name: b for b in bufs}
    assert by_name["dot.1"].scope == "00-fc1"
    assert by_name["fusion.1"].scope == "01-act"
    assert by_name["red.1"].scope == "02-loss"
    # the transform-wrapped backward path still joins
    assert by_name["new_w.1"].scope == "00-fc1"
    assert by_name["unused.1"].scope is None


def test_live_timeline_exact():
    bufs = memlib.hlo_entry_buffers(_fixture_text(), SCOPES)
    tl = memlib.live_timeline(bufs)
    # peak = dot.1 (512) + wide.1 (2048) live together at index 4;
    # at index 5 dot.1 dies INTO fusion.1 (in-place reuse: freed before
    # the fusion's own 512 allocates), so the peak stays at 4
    assert tl["peak_bytes"] == 2560
    assert tl["peak_index"] == 4
    assert tl["at_peak"] == {"00-fc1": 2560}
    # unused.1 (16 KB, read by nobody) never enters the curve
    assert max(tl["timeline"]) == 2560
    assert tl["timeline"] == [0, 0, 0, 512, 2560, 2560, 768, 768,
                              0, 0, 0, 0]


def test_mem_table_rows_and_model_join():
    table = memlib.mem_table(
        _fixture_text(), SCOPES,
        exec_stats={"temp_bytes": 2560, "args_bytes": 1600},
        param_rows={"00-fc1": {"param_bytes": 1088, "opt_bytes": 1088}},
        model_rows={"00-fc1": {"param_bytes": 1088, "opt_bytes": 1088,
                               "act_bytes": 512}})
    assert table["peak_live_bytes"] == 2560
    assert table["exec"]["temp_bytes"] == 2560
    assert table["coverage"] == 1.0  # every peak byte carries a scope
    [row] = table["rows"]
    assert row["layer"] == "00-fc1"
    assert row["act_bytes"] == 2560
    assert row["total_bytes"] == 1088 + 1088 + 2560
    assert row["share"] == 1.0
    assert row["model_bytes"] == 1088 + 1088 + 512
    assert row["model_x"] == pytest.approx(
        row["total_bytes"] / row["model_bytes"], abs=0.01)


# --------------------------------------------------------- analytic model

def _trainer(extra=(), batch=8):
    from test_serve import MLP_NET
    from __graft_entry__ import _make_trainer
    return _make_trainer(MLP_NET, batch, "cpu", extra=list(extra))


def test_param_rows_match_placed_trees():
    t = _trainer()
    rows = memmodel.param_rows(t)
    assert set(rows) == {"00-fc1", "02-fc2"}
    # fc1: (24 x 16 wmat + 24 bias) f32; sgd momentum doubles as opt
    assert rows["00-fc1"]["param_bytes"] == (24 * 16 + 24) * 4
    assert rows["00-fc1"]["opt_bytes"] == (24 * 16 + 24) * 4
    # shared-free net: every connection owns its params exactly once
    total = sum(r["param_bytes"] for r in rows.values())
    import jax
    assert total == sum(leaf.size * leaf.dtype.itemsize
                        for leaf in jax.tree.leaves(t.params))


def test_totals_schedule_corrections():
    t = _trainer()
    base = memmodel.totals(t)
    assert base["acc_bytes"] == 0
    assert base["est_peak_bytes"] > base["param_bytes"]
    # remat: held boundaries + one live window, never above the plain
    # sum (on this shallow net the correction caps at equality)
    t.remat = 2
    remat = memmodel.totals(t)
    assert remat["act_bytes"] <= base["act_bytes"]
    # on a deeper profile the window math bites: 8 equal layers in 2
    # segments -> 2 boundaries held + one 4-layer window live
    deep = {f"{i:02d}-l": {"param_bytes": 0, "grad_bytes": 0,
                           "opt_bytes": 0, "act_bytes": 100}
            for i in range(8)}
    assert memmodel.totals(t, deep)["act_bytes"] == 600
    t.remat = 0
    assert memmodel.totals(t, deep)["act_bytes"] == 800
    # batch_split halves live activations
    t.batch_split = 2
    assert memmodel.totals(t)["act_bytes"] \
        == base["act_bytes"] // 2
    t.batch_split = 1
    # update_period > 1 persists a param-shaped accumulator
    t.update_period = 2
    assert memmodel.totals(t)["acc_bytes"] == base["param_bytes"]


def test_resolve_chip():
    assert costmodel.resolve_chip("v5e") == "TPU v5e"
    assert costmodel.resolve_chip("TPU v4") == "TPU v4"
    assert costmodel.resolve_chip("v5 lite") == "TPU v5 lite"
    assert costmodel.resolve_chip("TPU v5p chip") == "TPU v5p"
    # ambiguous / junk selectors must NOT silently pick a chip — a v5p
    # user checked against v5e's 16 GB would get a spurious OOM error
    assert costmodel.resolve_chip("v5") is None
    assert costmodel.resolve_chip("v") is None
    assert costmodel.resolve_chip("tpu") is None
    assert costmodel.resolve_chip("cpu") is None
    assert costmodel.resolve_chip("") is None
    assert costmodel.hbm_bytes("TPU v5e chip") == 16e9


BIG_ACT_CONF = """
netconfig = start
layer[0->1] = fullc:fc1
  nhidden = 4096
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4096
layer[3->4] = softmax
netconfig = end
input_shape = 1,1,4096
batch_size = 262144
updater = adam
eta = 0.05
metric = error
"""


def _pairs(text):
    import tempfile
    from cxxnet_tpu.utils.config import parse_config_file
    fn = tempfile.mktemp(suffix=".conf")
    with open(fn, "w") as f:
        f.write(text)
    try:
        return list(parse_config_file(fn))
    finally:
        os.unlink(fn)


@pytest.mark.slow
def test_preflight_over_budget_errors_with_remediation():
    cfg = _pairs(BIG_ACT_CONF + "mem_check = 1\nmem_chip = v5e\n")
    findings, code = run_check(cfg)
    assert code == 1
    [err] = [f for f in findings if f.severity == "error"]
    assert err.key == "mem_check" and err.scope == "mem"
    assert "exceeds TPU v5e capacity" in err.message
    # did-you-mean remediation knobs ride in the finding text
    assert "remat" in err.message and "batch_split" in err.message


@pytest.mark.slow
def test_preflight_fits_and_margin():
    # same net, roomier chip: headroom is an info finding
    cfg = _pairs(BIG_ACT_CONF + "mem_check = 1\nmem_chip = v5p\n")
    findings, code = run_check(cfg)
    assert code == 0
    infos = [f for f in findings
             if f.key == "mem_check" and f.severity == "info"]
    assert infos and "estimated peak HBM" in infos[0].message
    # a wide margin turns the same estimate into a warning
    cfg = _pairs(BIG_ACT_CONF
                 + "mem_check = 1\nmem_chip = v5p\nmem_margin_pct = 85\n")
    findings, code = run_check(cfg)
    assert code == 0
    assert any(f.severity == "warn" and "is within 85" in f.message
               for f in findings)


def test_preflight_unresolvable_chip_warns():
    from test_serve import MLP_NET
    cfg = _pairs(MLP_NET + "batch_size = 8\nmem_check = 1\n")
    findings, code = run_check(cfg)
    assert code == 0
    assert any(f.key in ("mem_check", "mem_chip")
               and "no known chip" in f.message.lower()
               or "cannot resolve" in f.message.lower()
               for f in findings if f.severity == "warn")


@pytest.mark.slow
def test_preflight_multi_device_dev_without_mesh():
    # dev = cpu:0-7 with NO mesh= key auto-builds a data:8 mesh at
    # runtime — the pre-flight must model per-device shards, not
    # charge all 8 chips' activations to one HBM (the same 17 GB of
    # activations that fail v5e on one device fit at ~2.2 GB/chip)
    cfg = _pairs(BIG_ACT_CONF.replace("batch_size = 262144",
                                      "batch_size = 262144\n"
                                      "dev = cpu:0-7")
                 + "mem_check = 1\nmem_chip = v5e\n")
    findings, code = run_check(cfg)
    assert code == 0
    infos = [f for f in findings
             if f.key == "mem_check" and f.severity == "info"]
    assert infos and "estimated peak HBM" in infos[0].message


def test_preflight_warns_when_mesh_exceeds_host():
    # a CI gate must not read exit 0 as "it fits" when the pre-flight
    # never ran because the host can't emulate the config's mesh
    from test_serve import MLP_NET
    cfg = _pairs(MLP_NET + "batch_size = 64\nmesh = data:64\n"
                 "dev = cpu:0-63\nmem_check = 1\nmem_chip = v5e\n")
    findings, _ = run_check(cfg)
    assert any(f.key == "mem_check" and f.severity == "warn"
               and "did NOT run" in f.message for f in findings)


def test_preflight_needs_trace_pass():
    from test_serve import MLP_NET
    cfg = _pairs(MLP_NET + "batch_size = 8\nmem_check = 1\n"
                 + "mem_chip = v5e\n")
    findings, _ = run_check(cfg, trace=False)
    assert any(f.key == "mem_check" and "--no-trace" in f.message
               for f in findings)


# ------------------------------------------------------------- lint rules

def _lint(text):
    from cxxnet_tpu.analysis import conflint
    return conflint.lint_pairs(_pairs(text))


def test_lint_mem_keys_without_mem_check_warn():
    from test_serve import MLP_NET
    fs = _lint(MLP_NET + "batch_size = 8\nmem_margin_pct = 5\n")
    assert any(f.key == "mem_margin_pct"
               and "without mem_check" in f.message for f in fs)


def test_lint_mem_check_off_task_warns():
    from test_serve import MLP_NET
    fs = _lint(MLP_NET + "batch_size = 8\ntask = pred\nmodel_in = x\n"
               "mem_check = 1\nmem_chip = v5e\n")
    assert any(f.key == "mem_check" and "TRAIN step" in f.message
               for f in fs)


def test_lint_mem_check_remat_info():
    from test_serve import MLP_NET
    fs = _lint(MLP_NET + "batch_size = 8\nremat = 2\nmem_check = 1\n"
               "mem_chip = v5e\n")
    assert any(f.key == "mem_check" and f.severity == "info"
               and "segment-boundary" in f.message for f in fs)


# --------------------------------------------------- per-device HBM gauges

class _Dev:
    def __init__(self, peak=None, in_use=None):
        self._s = {}
        if peak is not None:
            self._s["peak_bytes_in_use"] = peak
        if in_use is not None:
            self._s["bytes_in_use"] = in_use

    def memory_stats(self):
        if not self._s:
            raise RuntimeError("no stats")
        return self._s


def test_device_memory_gauges_spread():
    # a skewed shard (one device 4x its peers) reads as spread, not
    # hidden under the max; the sentinel's series (the max) is intact
    g = device_memory_gauges([_Dev(peak=4000, in_use=100),
                              _Dev(peak=1000, in_use=90)])
    assert g["hbm_peak_bytes"] == 4000
    assert g["hbm_peak_bytes_min"] == 1000
    assert g["hbm_peak_spread_pct"] == 75.0
    assert g["hbm_bytes_in_use"] == 100
    # single reporting device: no spread fields
    g1 = device_memory_gauges([_Dev(peak=4000)])
    assert g1 == {"hbm_peak_bytes": 4000}
    # no backend support at all: empty, not zeros
    assert device_memory_gauges([_Dev(), _Dev()]) == {}


# --------------------------------------------------- mem_profile e2e (CPU)

def _records(sink):
    return [json.loads(l) for l in open(sink)]


def test_mem_profile_record_cpu_end_to_end(tmp_path):
    """The acceptance path: a CPU MNIST run with a profiling window
    emits a mem_profile whose per-layer act rows sum to within 10% of
    the executable's reported temp allocation, with param/opt rows
    matching the trainer's placed trees."""
    from test_observatory import _train_conf
    from cxxnet_tpu.main import LearnTask
    sink = tmp_path / "metrics.jsonl"
    conf = _train_conf(tmp_path, f"""
prof = {tmp_path}/prof
metrics_sink = jsonl:{sink}
""")
    assert LearnTask().run([str(conf)]) == 0
    mps = [r for r in _records(sink) if r["kind"] == "mem_profile"]
    assert len(mps) == 1
    mp = mps[0]
    temp = mp["exec"]["temp_bytes"]
    act_sum = sum(r["act_bytes"] for r in mp["rows"])
    assert abs(act_sum - temp) <= 0.10 * temp
    assert act_sum == mp["peak_live_bytes"]
    layers = {r["layer"] for r in mp["rows"]}
    assert "00-fc1" in layers
    fc1 = next(r for r in mp["rows"] if r["layer"] == "00-fc1")
    # param/opt from the placed trees: (32x144 + 32) f32, x2 momentum
    assert fc1["param_bytes"] == (32 * 144 + 32) * 4
    assert fc1["opt_bytes"] == fc1["param_bytes"]
    assert fc1["model_bytes"] > 0 and fc1["model_x"] > 0
    assert mp["coverage"] > 0.5
    assert len(mp["timeline"]) > 4 and max(mp["timeline"]) \
        == mp["peak_live_bytes"]
    assert mp["model"]["est_peak_bytes"] > mp["model"]["param_bytes"]
    # CPU: no made-up capacity, no fake measured gauges
    assert "hbm_capacity_bytes" not in mp
    assert "hbm_peak_bytes" not in mp


def test_mem_profile_feeds_hbm_sentinel_fallback(tmp_path, capsys):
    """On a backend without memory_stats the HBM watcher warns at arm
    time and the mem_profile path feeds it the executable-derived temp
    bytes (satellite: the fallback signal)."""
    from test_observatory import _train_conf
    from cxxnet_tpu.main import LearnTask
    sink = tmp_path / "metrics.jsonl"
    conf = _train_conf(tmp_path, f"""
prof = {tmp_path}/prof
metrics_sink = jsonl:{sink}
sentinel = 1
silent = 0
""")
    task = LearnTask()
    assert task.run([str(conf)]) == 0
    err = capsys.readouterr().err
    assert "no memory_stats" in err
    bank = task._sentinel_bank
    s = bank.sentinels["hbm_peak_bytes"]
    assert s.seen >= 1  # the executable-derived bytes reached the EWMA
    assert s.ewma.mean == pytest.approx(
        [r for r in _records(sink)
         if r["kind"] == "mem_profile"][0]["exec"]["temp_bytes"])


def test_mem_profile_cached_across_prof_every_windows(tmp_path):
    from test_observatory import _train_conf
    from cxxnet_tpu.main import LearnTask
    sink = tmp_path / "metrics.jsonl"
    conf = _train_conf(tmp_path, f"""
num_round = 4
prof = {tmp_path}/prof
prof_every = 2
prof_num_steps = 1
metrics_sink = jsonl:{sink}
""")
    assert LearnTask().run([str(conf)]) == 0
    mps = [r for r in _records(sink) if r["kind"] == "mem_profile"]
    assert len(mps) == 2  # one per closed window
    assert mps[0]["peak_live_bytes"] == mps[1]["peak_live_bytes"]
    assert sorted(r["round"] for r in mps) == [1, 3]


def test_task_check_cli_over_budget_exit_1(tmp_path):
    """The CLI acceptance: an over-HBM example config fails task=check
    with a remediation-bearing finding and exit code 1."""
    from cxxnet_tpu.main import LearnTask
    sink = tmp_path / "check.jsonl"
    conf = tmp_path / "big.conf"
    conf.write_text(BIG_ACT_CONF + f"""
mem_check = 1
mem_chip = v5e
metrics_sink = jsonl:{sink}
""")
    assert LearnTask().run([str(conf), "task=check"]) == 1
    [chk] = [r for r in _records(sink) if r["kind"] == "check"]
    assert chk["n_error"] >= 1
    errs = [f for f in chk["findings"]
            if f["severity"] == "error" and f["key"] == "mem_check"]
    assert errs and "remat" in errs[0]["message"]


# ----------------------------------------------------- serve footprint

def test_serve_footprint_per_model():
    from cxxnet_tpu.serve.engine import PredictEngine
    t = _trainer()
    eng = PredictEngine(t, shapes=(1, 4), dtype="f32")
    assert eng.footprint() == {}  # nothing warmed yet
    eng.warmup()
    fp = eng.footprint()
    import jax
    weight = sum(leaf.size * leaf.dtype.itemsize
                 for leaf in jax.tree.leaves(t.params))
    assert fp["weight_bytes"] == weight
    # the live trainer's optimizer state is resident too (sgd momentum
    # = 1x param bytes on this f32 MLP) — packing must count it
    assert fp["opt_bytes"] == weight
    assert fp["buckets"] == 2
    assert fp["total_bytes"] == fp["weight_bytes"] + fp["opt_bytes"] \
        + fp["exec_temp_bytes"] + fp["exec_out_bytes"] \
        + fp["exec_code_bytes"]
    # a cast variant keeps BOTH trees resident: the bf16 copy plus the
    # trainer's f32 originals -> 1.5x the f32 weight bytes
    eng16 = PredictEngine(_trainer(), shapes=(1, 4), dtype="bf16")
    eng16.warmup()
    assert eng16.footprint()["weight_bytes"] == weight // 2 + weight


def test_model_host_footprint_sums():
    from cxxnet_tpu.serve import ServeConfig
    from cxxnet_tpu.serve.host import ModelHost
    host = ModelHost()
    cfg = ServeConfig(shapes=(1, 4))
    a = host.add("a", _trainer(), cfg)
    b = host.add("b", _trainer(), cfg)
    try:
        fp = host.footprint()
        assert set(fp["models"]) == {"a", "b"}
        assert fp["total_bytes"] == sum(
            m["total_bytes"] for m in fp["models"].values())
        assert fp["total_bytes"] > 0
    finally:
        a.close()
        b.close()


# --------------------------------------------------------------- obsv CLI

def test_obsv_renders_memory_section():
    fixture = os.path.join(REPO, "tests", "fixtures", "run_report.jsonl")
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import obsv
    rep = obsv.build_report(obsv.load_records(fixture))
    mem = rep["memory"]
    assert mem["peak_live_bytes"] > 0
    assert mem["rows"] and mem["rows"][0]["layer"] == "16-fc6"
    text = obsv.render(rep)
    assert "memory (round" in text and "x_model" in text
    # the serve table picked up the footprint column
    assert "footprint" in text
