"""Sequence stack tests: layer oracles, ring-vs-dense attention equivalence
on the 8-device CPU mesh, and end-to-end transformer LM training with
sequence parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from cxxnet_tpu.layers.base import ForwardContext
from cxxnet_tpu.layers.registry import create_layer
from cxxnet_tpu.parallel import ring
from helpers import rand4 as rand, run_layer


# ------------------------------------------------------------------ layers
def test_layernorm_oracle():
    x = rand(2, 1, 5, 16)
    (y,), _ = run_layer("layernorm", x)
    mu = x.mean(-1, keepdims=True)
    sd = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(y, (x - mu) / sd, rtol=1e-4, atol=1e-5)


def test_embedding_and_positions():
    ids = np.array([[[[1, 3, 0]]], [[[2, 2, 1]]]], np.float32)  # (2,1,1,3)
    (y,), params = run_layer("embedding", ids,
                             {"vocab_size": 5, "nhidden": 8, "pos_embed": 1})
    w, wp = np.asarray(params["wmat"]), np.asarray(params["wpos"])
    expect = w[ids[:, 0, 0].astype(int)] + wp[None, :, :]
    np.testing.assert_allclose(y[:, 0], expect, rtol=1e-5)


def test_seq_fullc_is_positionwise():
    x = rand(2, 1, 4, 8)
    (y,), params = run_layer("seq_fullc", x, {"nhidden": 6})
    w, b = np.asarray(params["wmat"]), np.asarray(params["bias"])
    np.testing.assert_allclose(y, x @ w.T + b, rtol=1e-4, atol=1e-5)


def test_eltsum():
    a, b = rand(2, 3, 4, 5), rand(2, 3, 4, 5, seed=1)
    (y,), _ = run_layer("eltsum", [a, b])
    np.testing.assert_allclose(y, a + b, rtol=1e-6)


def test_attention_dense_oracle():
    """Dense attention vs a straightforward numpy softmax-attention."""
    b, s, d, h = 2, 6, 16, 4
    x = rand(b, 1, s, d)
    (y,), params = run_layer("attention", x, {"nhead": h, "no_bias": 1})
    wqkv, wout = np.asarray(params["wqkv"]), np.asarray(params["wout"])
    qkv = x[:, 0] @ wqkv.T  # (b, s, 3d)
    q, k, v = np.split(qkv, 3, axis=-1)

    def split_heads(t):
        return t.reshape(b, s, h, d // h).transpose(0, 2, 1, 3)
    q, k, v = map(split_heads, (q, k, v))
    sc = q @ k.transpose(0, 1, 3, 2) / np.sqrt(d // h)
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    att = (p @ v).transpose(0, 2, 1, 3).reshape(b, 1, s, d)
    np.testing.assert_allclose(y, att @ wout.T, rtol=1e-3, atol=1e-4)


def test_attention_causal_masks_future():
    """With causal=1, output at position t must not depend on tokens > t."""
    b, s, d, h = 1, 5, 8, 2
    x = rand(b, 1, s, d)
    layer = create_layer("attention")
    for k, v in {"nhead": h, "causal": 1, "no_bias": 1}.items():
        layer.set_param(k, str(v))
    layer.infer_shapes([x.shape])
    params = layer.init_params(jax.random.PRNGKey(3), [x.shape])
    ctx = ForwardContext(train=False)
    (y1,), _ = layer.forward(params, {}, [jnp.asarray(x)], ctx)
    x2 = x.copy()
    x2[:, :, -1, :] += 100.0  # perturb the last token only
    (y2,), _ = layer.forward(params, {}, [jnp.asarray(x2)], ctx)
    np.testing.assert_allclose(np.asarray(y1)[:, :, :-1],
                               np.asarray(y2)[:, :, :-1], rtol=1e-5)
    assert not np.allclose(np.asarray(y1)[:, :, -1], np.asarray(y2)[:, :, -1])


# ----------------------------------------------------------- ring attention
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("mesh_axes", [(("seq", 8),), (("data", 2), ("seq", 4))])
def test_ring_equals_dense(causal, mesh_axes):
    devs = jax.devices()
    n = int(np.prod([s for _, s in mesh_axes]))
    mesh = Mesh(np.array(devs[:n]).reshape([s for _, s in mesh_axes]),
                [a for a, _ in mesh_axes])
    b, h, s, d = 2, 2, 16, 8
    q, k, v = rand(b, h, s, d), rand(b, h, s, d, seed=1), rand(b, h, s, d, seed=2)
    dense = ring.dense_attention(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), causal=causal)
    ringed = ring.sharded_attention(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ringed), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_under_jit_grad():
    """Ring attention must be differentiable inside jit (training path)."""
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:4]).reshape(4), ["seq"])
    b, h, s, d = 1, 2, 8, 4
    q, k, v = (jnp.asarray(rand(b, h, s, d, seed=i)) for i in range(3))

    @jax.jit
    def loss(q, k, v):
        return ring.sharded_attention(q, k, v, mesh, causal=True).sum()

    g = jax.grad(loss)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()
    # matches dense-attention gradient
    g_dense = jax.grad(
        lambda q, k, v: ring.dense_attention(q, k, v, causal=True).sum()
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_dense),
                               rtol=2e-4, atol=2e-5)


# ------------------------------------------------------------- end to end
def _train_lm(mesh_cfg, steps=80, batch=8):
    """Tiny copy-task LM: predict the previous token (trivially learnable
    with a causal model)."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import transformer
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    vocab, seq = 8, 16
    conf = transformer(vocab=vocab, seq=seq, dim=16, nlayer=1, nhead=2)
    t = NetTrainer()
    for k, v in parse_config_string(conf):
        t.set_param(k, v)
    t.set_param("batch_size", str(batch))
    t.set_param("dev", mesh_cfg["dev"])
    if mesh_cfg.get("mesh"):
        t.set_param("mesh", mesh_cfg["mesh"])
    t.set_param("updater", "adam")
    t.set_param("eta", "0.01")
    t.set_param("silent", "1")
    t.init_model()
    rnd = np.random.RandomState(0)
    t.start_round(1)
    losses = []
    for i in range(steps):
        toks = rnd.randint(1, vocab, (batch, seq)).astype(np.float32)
        label = np.concatenate([np.zeros((batch, 1), np.float32),
                                toks[:, :-1]], axis=1)  # predict prev token
        b = DataBatch(data=toks.reshape(batch, 1, 1, seq), label=label,
                      index=np.arange(batch, dtype=np.uint32))
        t.update(b)
        losses.append(float(np.asarray(t._last_loss)))
    return losses, t


def test_transformer_trains_single_device():
    losses, _ = _train_lm({"dev": "cpu"})
    assert losses[-1] < losses[0] * 0.5, losses[::20]


def test_transformer_trains_sequence_parallel():
    """Same LM over a data:2,seq:4 mesh: ring attention + dp; loss must
    drop and replicas stay consistent."""
    losses, t = _train_lm({"dev": "cpu:0-7", "mesh": "data:2,seq:4"})
    assert losses[-1] < losses[0] * 0.5, losses[::20]
    assert t.check_weight_consistency() == 0.0


def test_transformer_seq_parallel_matches_single():
    """First-step loss must be identical (same seed) with and without the
    seq mesh — sequence parallelism is an implementation detail, not a
    model change."""
    l1, _ = _train_lm({"dev": "cpu"}, steps=3)
    l2, _ = _train_lm({"dev": "cpu:0-7", "mesh": "data:2,seq:4"}, steps=3)
    np.testing.assert_allclose(l1, l2, rtol=1e-4)


def test_chunked_dense_attention_matches_direct():
    """Past the chunk threshold, attention runs online-softmax chunks under
    scan (O(s*chunk) memory) and must match the direct path bit-for-bit-ish,
    forward and backward, causal and not."""
    import cxxnet_tpu.parallel.ring as ring
    rnd = np.random.RandomState(0)
    b, h, s, d = 1, 2, 64, 8
    q, k, v = (jnp.asarray(rnd.randn(b, h, s, d).astype(np.float32))
               for _ in range(3))
    old_thresh, old_chunk = ring.CHUNKED_ATTN_THRESHOLD, ring._chunk_for
    try:
        for causal in (False, True):
            ring.CHUNKED_ATTN_THRESHOLD = 4096
            ref = ring.dense_attention(q, k, v, causal=causal)
            g_ref = jax.grad(lambda *a: jnp.sum(
                ring.dense_attention(*a, causal=causal) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            ring.CHUNKED_ATTN_THRESHOLD = 16
            ring._chunk_for = lambda s_len: 16  # 4 real chunks
            out = ring.dense_attention(q, k, v, causal=causal)
            g_out = jax.grad(lambda *a: jnp.sum(
                ring.dense_attention(*a, causal=causal) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            ring._chunk_for = old_chunk
            np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                       atol=2e-6)
            for a, b_ in zip(g_ref, g_out):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                           atol=1e-5)
    finally:
        ring.CHUNKED_ATTN_THRESHOLD = old_thresh
        ring._chunk_for = old_chunk


def test_ring_attention_chunked_local_blocks():
    """Each ring step folds its K/V block in k-chunks (no s_local^2 score
    matrix); must still match dense attention exactly."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))
    rnd = np.random.RandomState(0)
    b, h, s, d = 1, 2, 64, 8
    q, k, v = (jnp.asarray(rnd.randn(b, h, s, d).astype(np.float32))
               for _ in range(3))
    old = ring._chunk_for
    old_thresh = ring.CHUNKED_ATTN_THRESHOLD
    ring._chunk_for = lambda n: max(n // 4, 1) if n % 4 == 0 else n
    ring.CHUNKED_ATTN_THRESHOLD = 8  # force the chunked path for tiny blocks
    try:
        for causal in (False, True):
            out = ring.sharded_attention(q, k, v, mesh, causal=causal)
            # reference must not chunk: restore the real threshold for it
            ring.CHUNKED_ATTN_THRESHOLD = old_thresh
            ref = ring.dense_attention(q, k, v, causal=causal)
            ring.CHUNKED_ATTN_THRESHOLD = 8
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-6)
    finally:
        ring._chunk_for = old
        ring.CHUNKED_ATTN_THRESHOLD = old_thresh
