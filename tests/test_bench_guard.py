"""Bench regression guard (VERDICT r5 #4 / round-6 item 4).

``bench.py`` records ``device_step_ms`` (on-chip time from a trace — the
session-comparable number) in each round's ``BENCH_r*.json``; BASELINE.md
records the accepted number.  Nothing previously GATED on the two
agreeing, so a lowering change that silently regressed device time would
only surface when a human re-read the tables.  This module compares the
newest bench record against the baseline with a ±10% budget, routed
through the ONE comparison engine (``cxxnet_tpu/monitor/diff.py`` — the
same verdict ``tools/obsv.py --diff`` and ``bench.py --against`` use),
so exactly one threshold/comparison implementation exists.

Marked ``slow``: it is excluded from the tier-1 CPU suite (the JSONs are
produced on TPU sessions; a CPU checkout may carry stale ones) and meant
to run right after a bench session:

    python -m pytest tests/test_bench_guard.py -m slow

The semantic twin of this guard — the pairtest tolerance envelope — lives
in ``tests/test_pairtest_gate.py``.
"""

import json
import re
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUDGET = 0.10  # fractional regression allowed before the guard trips


def _newest_bench():
    recs = sorted(REPO.glob("BENCH_r*.json"))
    if not recs:
        pytest.skip("no BENCH_r*.json records in the repo")
    return recs[-1]


def _baseline_device_ms():
    """The accepted AlexNet device step from BASELINE.md: last table row
    naming it, last ms figure in the row (columns are oldest->newest)."""
    text = (REPO / "BASELINE.md").read_text()
    rows = [ln for ln in text.splitlines()
            if "AlexNet" in ln and "device step" in ln]
    if not rows:
        pytest.skip("BASELINE.md has no 'AlexNet ... device step' row")
    ms = re.findall(r"([0-9]+(?:\.[0-9]+)?)\s*ms", rows[-1])
    if not ms:
        pytest.skip("could not parse a ms figure from the baseline row")
    return float(ms[-1])


@pytest.mark.slow
def test_device_step_within_budget():
    from cxxnet_tpu.monitor.diff import LOWER_BETTER, compare
    rec = json.loads(_newest_bench().read_text())
    parsed = rec.get("parsed") or {}
    dev = parsed.get("device_step_ms")
    if dev is None:
        pytest.skip(f"{_newest_bench().name} has no device_step_ms "
                    "(trace failed that session)")
    base = _baseline_device_ms()
    verdict = compare("device_step_ms", base, dev, rel=BUDGET,
                      direction=LOWER_BETTER)
    assert not verdict["regressed"], (
        f"device_step_ms regressed: {dev:.2f} ms vs baseline {base:.2f} ms "
        f"({verdict['rel_delta']:+.1%}, budget +{BUDGET * 100:.0f}%) — "
        "either find the regression or re-baseline BASELINE.md with the "
        "explanation")
    # a big IMPROVEMENT is also a finding: it means BASELINE.md is stale
    if verdict["improved"]:
        pytest.skip(f"device_step_ms improved past the budget "
                    f"({dev:.2f} vs {base:.2f} ms) — update BASELINE.md")
