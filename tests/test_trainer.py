"""End-to-end trainer tests: training convergence, checkpointing,
data parallelism on the virtual 8-device mesh, grad accumulation."""

import os

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

MLP_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:ac1] = relu
layer[ac1->fc2] = fullc:fc2
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 32
dev = cpu
eta = 0.5
momentum = 0.9
wd = 0.0
metric = error
"""


def make_trainer(conf, extra=()):
    t = NetTrainer()
    for k, v in parse_config_string(conf):
        t.set_param(k, v)
    for k, v in extra:
        t.set_param(k, v)
    t.init_model()
    return t


def synth_batches(n_batches=20, bs=32, dim=8, seed=0):
    """Linearly separable 2-class toy data."""
    rnd = np.random.RandomState(seed)
    w = rnd.randn(dim)
    batches = []
    for i in range(n_batches):
        x = rnd.randn(bs, dim).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        batches.append(DataBatch(
            data=x.reshape(bs, 1, 1, dim),
            label=y.reshape(bs, 1),
            index=np.arange(i * bs, (i + 1) * bs, dtype=np.uint32)))
    return batches


def accuracy(trainer, batches):
    correct = total = 0
    for b in batches:
        pred = trainer.predict(b)
        correct += (pred == b.label[:, 0]).sum()
        total += len(pred)
    return correct / total


def test_mlp_trains_to_high_accuracy():
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    batches = synth_batches()
    t.start_round(1)
    for _ in range(5):
        for b in batches:
            t.update(b)
    assert accuracy(t, batches) > 0.95


def test_train_metric_reporting():
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    batches = synth_batches(5)
    t.start_round(1)
    for b in batches:
        t.update(b)
    line = t.train_eval_line("train")
    assert "train-error:" in line


def test_update_many_matches_update_sequence():
    """The multi-step scan path (with stacked eval outputs) follows the
    exact same parameter trajectory and train metric as k update() calls."""
    ta = make_trainer(MLP_CONF, extra=[("silent", "1")])
    tb = make_trainer(MLP_CONF, extra=[("silent", "1")])
    batches = synth_batches(6)
    ta.start_round(1)
    tb.start_round(1)
    for b in batches:
        ta.update(b)
    datas = np.stack([b.data for b in batches])
    labels = np.stack([b.label for b in batches])
    _, outs = tb.update_many(datas, labels, with_outs=True)
    for pkey, group in ta.params.items():
        for tag, p in group.items():
            np.testing.assert_allclose(
                np.asarray(p), np.asarray(tb.params[pkey][tag]),
                rtol=1e-5, atol=1e-6, err_msg=f"{pkey}/{tag}")
    # train metric from the stacked outputs equals the per-step one
    outs_np = {nid: np.asarray(v) for nid, v in outs.items()}
    for j, b in enumerate(batches):
        preds = [outs_np[nid][j] for nid in tb.eval_node_ids]
        tb.train_metric.add_eval(
            preds, {name: b.label[:, a:bb]
                    for name, a, bb in tb._label_fields})
    assert ta.train_eval_line() == tb.train_eval_line()


def test_evaluate_excludes_padding():
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    b = synth_batches(1)[0]
    padded = DataBatch(data=b.data, label=b.label, index=b.index,
                       num_batch_padd=30)
    line = t.evaluate([padded], "test")
    assert "test-error:" in line
    # only 2 valid instances were scored
    assert t.metric.evals[0].cnt_inst == 2


def test_save_load_roundtrip(tmp_path):
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    batches = synth_batches(5)
    t.start_round(1)
    for b in batches:
        t.update(b)
    path = str(tmp_path / "0001.model")
    t.save_model(path)
    t2 = NetTrainer()
    for k, v in parse_config_string(MLP_CONF):
        t2.set_param(k, v)
    t2.set_param("silent", "1")
    t2.load_model(path)
    for b in batches:
        np.testing.assert_allclose(t.predict_raw(b), t2.predict_raw(b),
                                   rtol=1e-5, atol=1e-6)
    assert t2.epoch_counter == t.epoch_counter


def test_finetune_copy_model(tmp_path):
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    path = str(tmp_path / "base.model")
    t.save_model(path)
    # new net with same fc1 but different fc2 width: only fc1 is copied
    conf2 = MLP_CONF.replace("nhidden = 2", "nhidden = 4")
    t2 = make_trainer(conf2, extra=[("silent", "1")])
    t2.copy_model_from(path)
    np.testing.assert_allclose(t2.get_weight("fc1", "wmat"),
                               t.get_weight("fc1", "wmat"))
    assert t2.get_weight("fc2", "wmat").shape[0] == 4


def test_get_set_weight():
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    w = t.get_weight("fc1", "wmat")
    t.set_weight(w * 0.0, "fc1", "wmat")
    assert np.abs(t.get_weight("fc1", "wmat")).max() == 0.0


def test_update_period_accumulation():
    """update_period=2 with half lr*... should track update_period=1 with the
    same total data: exact parity check of the accumulate path vs two
    half-batches."""
    t1 = make_trainer(MLP_CONF, extra=[("silent", "1")])
    t2 = make_trainer(MLP_CONF, extra=[("silent", "1"),
                                       ("update_period", "2")])
    # same init (deep copy: the jitted step donates its inputs)
    import jax.numpy as jnp
    for pkey in t1.params:
        for tag in t1.params[pkey]:
            t2.params[pkey][tag] = jnp.array(np.asarray(t1.params[pkey][tag]))
    batches = synth_batches(4)
    t1.start_round(1)
    t2.start_round(1)
    # t2 sees each batch twice via two updates of the same data → equivalent
    # to t1 seeing it once (loss scaled by 1/(bs*2) per micro-batch)
    for b in batches:
        t1.update(b)
        t2.update(b)
        t2.update(b)
    w1 = t1.get_weight("fc1", "wmat")
    w2 = t2.get_weight("fc1", "wmat")
    np.testing.assert_allclose(w1, w2, rtol=1e-4, atol=1e-5)


def test_multi_device_data_parallel_matches_single():
    import jax
    assert len(jax.devices()) >= 8, "conftest should force 8 CPU devices"
    t1 = make_trainer(MLP_CONF, extra=[("silent", "1")])
    t8 = make_trainer(MLP_CONF, extra=[("silent", "1"),
                                       ("dev", "cpu:0-7")])
    assert t8.mesh.devices.size == 8
    for pkey in t1.params:
        for tag in t1.params[pkey]:
            t8.params[pkey][tag] = jax.device_put(
                np.asarray(t1.params[pkey][tag]),
                t8.param_shardings[pkey][tag])
    batches = synth_batches(6)
    t1.start_round(1)
    t8.start_round(1)
    for b in batches:
        t1.update(b)
        t8.update(b)
    np.testing.assert_allclose(t1.get_weight("fc2", "wmat"),
                               t8.get_weight("fc2", "wmat"),
                               rtol=1e-4, atol=1e-5)
    assert t8.check_weight_consistency() == 0.0


def test_conv_net_end_to_end():
    conf = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  stride = 2
  nchannel = 8
layer[1->2] = max_pooling
  kernel_size = 2
  stride = 2
layer[2->3] = flatten
layer[3->4] = fullc:fc1
  nhidden = 4
  init_sigma = 0.1
layer[4->4] = softmax
netconfig=end
input_shape = 1,12,12
batch_size = 8
dev = cpu
eta = 0.1
metric = error
silent = 1
"""
    t = make_trainer(conf)
    rnd = np.random.RandomState(3)
    x = rnd.rand(8, 1, 12, 12).astype(np.float32)
    y = rnd.randint(0, 4, (8, 1)).astype(np.float32)
    b = DataBatch(data=x, label=y, index=np.arange(8, dtype=np.uint32))
    t.start_round(1)
    losses = []
    for _ in range(30):
        t.update(b)
        losses.append(float(t._last_loss))
    assert losses[-1] < losses[0] * 0.5, f"loss did not drop: {losses[:3]} -> {losses[-3:]}"


def test_bf16_checkpoint_roundtrip(tmp_path):
    """bfloat16 params survive save/load (numpy's npz cannot round-trip
    ml_dtypes extension types — the serializer stores them as exact float32
    and restores the dtype from the header)."""
    import jax.numpy as jnp
    t = make_trainer(MLP_CONF, extra=[("silent", "1"),
                                      ("dtype", "bfloat16")])
    t.start_round(1)
    for b in synth_batches(3):
        t.update(b)
    path = str(tmp_path / "m.model")
    t.save_model(path, with_opt_state=True)
    t2 = make_trainer(MLP_CONF, extra=[("silent", "1"),
                                       ("dtype", "bfloat16")])
    t2.load_model(path)
    for pkey, group in t.params.items():
        for tag, p in group.items():
            q = t2.params[pkey][tag]
            assert q.dtype == jnp.bfloat16, (pkey, tag, q.dtype)
            np.testing.assert_array_equal(
                np.asarray(p, np.float32), np.asarray(q, np.float32))
    # master copies restored with the optimizer state
    leaf = next(iter(t2.opt_state.values()))
    tagstate = next(iter(leaf.values()))
    assert "w32" in tagstate


def test_bf16_finetune_weights_survive_update(tmp_path):
    """copy_model_from / set_weight on a bf16 model must refresh the f32
    master copies — otherwise the first optimizer step reverts the written
    weights to (stale master) - lr*grad."""
    t = make_trainer(MLP_CONF, extra=[("silent", "1"),
                                      ("dtype", "bfloat16")])
    batches = synth_batches(4)
    t.start_round(1)
    for b in batches:
        t.update(b)
    path = str(tmp_path / "pre.model")
    t.save_model(path)
    t2 = make_trainer(MLP_CONF, extra=[("silent", "1"),
                                       ("dtype", "bfloat16"),
                                       ("seed", "9"), ("eta", "1e-6")])
    t2.copy_model_from(path)
    w_copied = t2.get_weight("fc1", "wmat").astype(np.float32)
    t2.start_round(1)
    t2.update(batches[0])  # tiny lr: weights must stay ~at the copied values
    w_after = t2.get_weight("fc1", "wmat").astype(np.float32)
    assert np.abs(w_after - w_copied).max() < 1e-3, \
        np.abs(w_after - w_copied).max()
    # set_weight path too
    val = np.full_like(w_copied, 0.25)
    t2.set_weight(val, "fc1", "wmat")
    t2.update(batches[1])
    w3 = t2.get_weight("fc1", "wmat").astype(np.float32)
    assert np.abs(w3 - 0.25).max() < 1e-3, np.abs(w3 - 0.25).max()


def test_bf16_master_weights_accumulate_small_updates():
    """bf16 params carry an f32 master copy in the optimizer state: many
    updates each below bf16's mantissa resolution must still accumulate
    (without the master, w += m rounds to nothing and training stalls —
    the AlexNet bf16 plateau found in round 2)."""
    from cxxnet_tpu.updater import create_updater, UpdaterHyper
    import jax.numpy as jnp
    u = create_updater("sgd")
    h = UpdaterHyper()
    h.base_lr, h.momentum = 1e-4, 0.0
    p = jnp.full((8,), 1.0, jnp.bfloat16)
    s = u.make_state(p)
    assert "w32" in s
    g = jnp.full((8,), 1.0, jnp.float32)  # step 1e-4 << bf16 eps at 1.0
    for i in range(64):
        p, s = u.apply(p, g, s, h, i)
    # 64 * 1e-4 = 6.4e-3: visible in bf16 only because the master carried it
    assert float(p[0].astype(jnp.float32)) < 0.999, float(p[0])
    np.testing.assert_allclose(float(s["w32"][0]), 1.0 - 64e-4, rtol=1e-5)
    # float32 params take no master copy
    assert "w32" not in u.make_state(jnp.ones((4,), jnp.float32))


def test_bf16_trainer_converges_with_small_lr():
    """End-to-end: a bf16 model with a small learning rate keeps making
    progress (master-weight path through the jitted step)."""
    t = make_trainer(MLP_CONF, extra=[("silent", "1"),
                                      ("dtype", "bfloat16"),
                                      ("eta", "0.02"), ("momentum", "0.9")])
    batches = synth_batches()
    t.start_round(1)
    for _ in range(8):
        for b in batches:
            t.update(b)
    assert accuracy(t, batches) > 0.9


def test_nag_and_adam_updaters():
    for upd in ("nag", "adam"):
        conf = MLP_CONF + f"\nupdater = {upd}\n"
        extra = [("silent", "1")]
        if upd == "adam":
            extra.append(("eta", "0.01"))
        t = make_trainer(conf, extra=extra)
        batches = synth_batches(10)
        t.start_round(1)
        for _ in range(3):
            for b in batches:
                t.update(b)
        assert accuracy(t, batches) > 0.9, f"{upd} failed to train"


def test_lr_schedule_in_graph():
    conf = MLP_CONF + """
lr:schedule = factor
lr:step = 2
lr:factor = 0.5
"""
    t = make_trainer(conf, extra=[("silent", "1")])
    b = synth_batches(1)[0]
    t.start_round(1)
    for _ in range(4):
        t.update(b)
    # just verify it runs and trains without recompiling per step
    assert t.epoch_counter == 4


def test_init_determinism():
    """Same config + seed must give identical initial weights (regression:
    param keys were hashed with Python's salted hash)."""
    t1 = make_trainer(MLP_CONF, extra=[("silent", "1"), ("seed", "7")])
    t2 = make_trainer(MLP_CONF, extra=[("silent", "1"), ("seed", "7")])
    np.testing.assert_array_equal(t1.get_weight("fc1", "wmat"),
                                  t2.get_weight("fc1", "wmat"))
    t3 = make_trainer(MLP_CONF, extra=[("silent", "1"), ("seed", "8")])
    assert np.abs(t3.get_weight("fc1", "wmat")
                  - t1.get_weight("fc1", "wmat")).max() > 0


def test_load_model_applies_config_overrides(tmp_path):
    """Regression: hyperparameter overrides passed at load time must win
    over the checkpointed config."""
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    path = str(tmp_path / "m.model")
    t.save_model(path)
    t2 = NetTrainer()
    for k, v in parse_config_string(MLP_CONF):
        t2.set_param(k, v)
    t2.set_param("silent", "1")
    t2.set_param("eta", "0.001")
    t2.set_param("wmat:wd", "0.125")
    t2.load_model(path)
    h = t2.hypers[t2._resolve_param_key("fc1")]["wmat"]
    assert h.base_lr == 0.001
    assert h.wd == 0.125
    assert t2.hypers[t2._resolve_param_key("fc1")]["bias"].wd != 0.125


def test_update_many_matches_update_loop():
    """update_many(k) must reproduce the exact parameter/optimizer
    trajectory of k update() calls, including the per-step PRNG keys
    (dropout nets would silently diverge on an RNG mismatch)."""
    conf = MLP_CONF + "\nsilent = 1\n"
    # a dropout layer makes the equivalence sensitive to the RNG stream
    conf = conf.replace("layer[+1:ac1] = relu",
                        "layer[+1:ac1] = relu\nlayer[+0] = dropout\n"
                        "  threshold = 0.25")
    t1 = make_trainer(conf, extra=[("seed", "3")])
    t2 = make_trainer(conf, extra=[("seed", "3")])
    rnd = np.random.RandomState(0)
    k, bs = 4, 32
    datas = rnd.rand(k, bs, 1, 1, 8).astype(np.float32)
    labels = rnd.randint(0, 2, (k, bs, 1)).astype(np.float32)
    t1.start_round(1)
    t2.start_round(1)
    for i in range(k):
        t1.update(DataBatch(data=datas[i], label=labels[i],
                            index=np.arange(bs, dtype=np.uint32)))
    losses = t2.update_many(datas, labels)
    assert losses.shape == (k,)
    np.testing.assert_array_equal(t1.get_weight("fc1", "wmat"),
                                  t2.get_weight("fc1", "wmat"))
    np.testing.assert_array_equal(t1.get_weight("fc2", "bias"),
                                  t2.get_weight("fc2", "bias"))
    np.testing.assert_allclose(float(np.asarray(t1._last_loss)),
                               float(np.asarray(losses[-1])), rtol=1e-6)
    # mixing the APIs must continue the same trajectory
    t1.update(DataBatch(data=datas[0], label=labels[0],
                        index=np.arange(bs, dtype=np.uint32)))
    t2.update(DataBatch(data=datas[0], label=labels[0],
                        index=np.arange(bs, dtype=np.uint32)))
    np.testing.assert_array_equal(t1.get_weight("fc1", "wmat"),
                                  t2.get_weight("fc1", "wmat"))


def test_grouped_eval_matches_per_batch():
    """evaluate() groups batches into one scanned dispatch + one D2H per
    group (VERDICT r3 weak 7); the metric line must equal the per-batch
    path, including tail batches with num_batch_padd and a remainder
    group smaller than eval_group."""
    t = make_trainer(MLP_CONF, extra=[("silent", "1")])
    batches = synth_batches(7)  # 7 = 2 full groups of 3 + remainder 1
    for b in batches:
        t.update(b)
    # give the last batch padding so n_valid trimming is exercised
    tail = batches[-1]
    tail = type(tail)(data=tail.data, label=tail.label, index=tail.index,
                      num_batch_padd=5)
    eval_set = batches[:6] + [tail]
    t.eval_group = 1
    line_per_batch = t.evaluate(iter(eval_set), "test")
    t.eval_group = 3
    line_grouped = t.evaluate(iter(eval_set), "test")
    assert line_grouped == line_per_batch


S2D_CONF = """
netconfig=start
layer[0->1] = conv:c1
  kernel_size = 5
  stride = 2
  nchannel = 8
  init_sigma = 0.1
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 2
  stride = 2
layer[3->4] = flatten
layer[4->5] = fullc:f1
  nhidden = 4
  init_sigma = 0.1
layer[5->5] = softmax
netconfig=end
input_shape = 3,21,21
batch_size = 16
dev = cpu
eta = 0.1
momentum = 0.9
metric = error
silent = 1
"""


@pytest.mark.parametrize("u8", [False, True], ids=["f32", "u8"])
def test_input_s2d_matches_plain(u8):
    """input_s2d = 1 stages batches in space-to-depth layout and runs
    conv1 as the dense stride-1 conv — the same contraction reordered,
    so train trajectory, predict, and evaluate match the plain path
    (VERDICT r3 item 1: the transform moved OUT of the step)."""
    extra = [("mean_value", "10,12,14"), ("scale", "0.01")] if u8 else []
    ref = make_trainer(S2D_CONF, extra=extra)
    s2d = make_trainer(S2D_CONF, extra=extra + [("input_s2d", "1")])
    assert s2d._s2d_args is not None
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            s2d.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
    rnd = np.random.RandomState(9)
    batches = []
    for i in range(4):
        if u8:
            x = rnd.randint(0, 256, (16, 3, 21, 21)).astype(np.uint8)
        else:
            x = rnd.randn(16, 3, 21, 21).astype(np.float32)
        y = (rnd.rand(16) * 4).astype(np.float32)
        batches.append(DataBatch(data=x, label=y.reshape(16, 1),
                                 index=np.arange(16, dtype=np.uint32)))
    for b in batches:
        ref.update(b)
        s2d.update(b)
        np.testing.assert_allclose(
            np.asarray(s2d._last_loss), np.asarray(ref._last_loss),
            rtol=1e-4)
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_allclose(
                np.asarray(s2d.params[pkey][tag]), np.asarray(v),
                rtol=1e-3, atol=1e-5, err_msg=f"{pkey}/{tag}")
    np.testing.assert_allclose(s2d.predict_raw(batches[0]),
                               ref.predict_raw(batches[0]),
                               rtol=1e-4, atol=1e-6)
    line_ref = ref.evaluate(iter(batches), "t")
    line_s2d = s2d.evaluate(iter(batches), "t")
    assert line_ref == line_s2d


def test_input_s2d_pre_staged_delivery():
    """The product contract: the input pipeline delivers s2d-SHAPED
    batches and _s2d_transform passes them through.  Parity with the
    plain path, u8 mean-repeat branch included; u8 + padded conv is
    rejected (u8 can't encode normalized zero padding)."""
    import jax.numpy as jnp
    from cxxnet_tpu.ops import nn as N
    extra = [("mean_value", "10,12,14"), ("scale", "0.01")]
    ref = make_trainer(S2D_CONF, extra=extra)
    s2d = make_trainer(S2D_CONF, extra=extra + [("input_s2d", "1")])
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            s2d.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
    s, kh, kw, oh, ow, py, px = s2d._s2d_args
    rnd = np.random.RandomState(11)
    x = rnd.randint(0, 256, (16, 3, 21, 21)).astype(np.uint8)
    y = (rnd.rand(16) * 4).astype(np.float32)
    # host-side s2d (what an iterator would emit), on raw u8
    xb = np.asarray(N.s2d_input(jnp.asarray(x), s, kh, kw, oh, ow,
                                py, px)[0])
    assert xb.shape[1:] == N.s2d_staged_shape(3, s, kh, kw, oh, ow)
    assert xb.dtype == np.uint8
    b_plain = DataBatch(data=x, label=y.reshape(16, 1),
                        index=np.arange(16, dtype=np.uint32))
    b_s2d = DataBatch(data=xb, label=y.reshape(16, 1),
                      index=np.arange(16, dtype=np.uint32))
    ref.update(b_plain)
    s2d.update(b_s2d)
    np.testing.assert_allclose(np.asarray(s2d._last_loss),
                               np.asarray(ref._last_loss), rtol=1e-4)
    np.testing.assert_allclose(s2d.predict_raw(b_s2d),
                               ref.predict_raw(b_plain),
                               rtol=1e-4, atol=1e-6)
    # padded conv + pre-s2d u8 must be rejected
    pad_conf = S2D_CONF.replace("  stride = 2", "  stride = 2\n  pad = 2",
                                1)
    padded = make_trainer(pad_conf, extra=extra + [("input_s2d", "1")])
    s2, kh2, kw2, oh2, ow2, py2, px2 = padded._s2d_args
    xb2 = np.asarray(N.s2d_input(jnp.asarray(x), s2, kh2, kw2, oh2, ow2,
                                 py2, px2)[0])
    with pytest.raises(AssertionError, match="padded first conv"):
        padded.update(DataBatch(data=xb2, label=y.reshape(16, 1),
                                index=np.arange(16, dtype=np.uint32)))


def test_relu_pool_reorder_matches():
    """pool_relu_reorder moves relu after max pooling (they commute);
    the trajectory must match the unreordered path, since differing
    argmax ties all receive zero gradient through the relu mask."""
    from cxxnet_tpu.engine import opts, set_engine_option
    old = opts.pool_relu_reorder
    try:
        set_engine_option("pool_relu_reorder", "0")
        ref = make_trainer(S2D_CONF)
        set_engine_option("pool_relu_reorder", "1")
        ro = make_trainer(S2D_CONF)
        assert any(getattr(c.layer, "relu_after", False)
                   for c in ro.net.connections), "reorder did not fire"
        assert not any(getattr(c.layer, "relu_after", False)
                       for c in ref.net.connections), \
            "reference trainer must build the unreordered graph"
        assert any(getattr(c.layer, "deferred_bias_key", None)
                   for c in ro.net.connections), "bias deferral did not fire"
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                ro.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
        rnd = np.random.RandomState(21)
        for _ in range(4):
            x = rnd.randn(16, 3, 21, 21).astype(np.float32)
            y = (rnd.rand(16) * 4).astype(np.float32)
            b = DataBatch(data=x, label=y.reshape(16, 1),
                          index=np.arange(16, dtype=np.uint32))
            ref.update(b)
            ro.update(b)
            np.testing.assert_allclose(
                np.asarray(ro._last_loss), np.asarray(ref._last_loss),
                rtol=1e-5)
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                np.testing.assert_allclose(
                    np.asarray(ro.params[pkey][tag]), np.asarray(v),
                    rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")
    finally:
        set_engine_option("pool_relu_reorder", old)


SELF_LOOP_CONF = """
netconfig=start
layer[0->1] = conv:c1
  kernel_size = 5
  stride = 2
  nchannel = 8
  init_sigma = 0.1
layer[1->1] = relu
layer[1->2] = max_pooling
  kernel_size = 2
  stride = 2
layer[2->3] = flatten
layer[3->4] = fullc:f1
  nhidden = 4
  init_sigma = 0.1
layer[4->4] = softmax
netconfig=end
input_shape = 3,21,21
batch_size = 16
dev = cpu
eta = 0.1
momentum = 0.9
metric = error
silent = 1
"""


def test_relu_pool_reorder_self_loop_matches():
    """The zoo builders emit ``layer[+0] = relu`` self-loops; the reorder
    must fire there too (the node holds the pre-activation between relu
    and pool) and the trajectory must match the unreordered path."""
    from cxxnet_tpu.engine import opts, set_engine_option
    old = opts.pool_relu_reorder
    try:
        set_engine_option("pool_relu_reorder", "0")
        ref = make_trainer(SELF_LOOP_CONF)
        set_engine_option("pool_relu_reorder", "1")
        ro = make_trainer(SELF_LOOP_CONF)
        assert any(getattr(c.layer, "relu_after", False)
                   for c in ro.net.connections), \
            "reorder did not fire on the self-loop relu"
        assert any(getattr(c.layer, "deferred_bias_key", None)
                   for c in ro.net.connections), "bias deferral did not fire"
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                ro.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
        rnd = np.random.RandomState(77)
        for _ in range(4):
            x = rnd.randn(16, 3, 21, 21).astype(np.float32)
            y = (rnd.rand(16) * 4).astype(np.float32)
            b = DataBatch(data=x, label=y.reshape(16, 1),
                          index=np.arange(16, dtype=np.uint32))
            ref.update(b)
            ro.update(b)
            np.testing.assert_allclose(
                np.asarray(ro._last_loss), np.asarray(ref._last_loss),
                rtol=1e-5)
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                np.testing.assert_allclose(
                    np.asarray(ro.params[pkey][tag]), np.asarray(v),
                    rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")
        # extract on the self-loop node returns the post-relu value
        x = rnd.randn(16, 3, 21, 21).astype(np.float32)
        b = DataBatch(data=x, label=np.zeros((16, 1), np.float32),
                      index=np.arange(16, dtype=np.uint32))
        np.testing.assert_allclose(
            ro.extract_feature(b, "1"), ref.extract_feature(b, "1"),
            rtol=1e-5, atol=1e-6)
    finally:
        set_engine_option("pool_relu_reorder", old)


INCEPTION_CONF = """
netconfig=start
layer[0->s] = conv:stem
  kernel_size = 3
  nchannel = 8
  pad = 1
  init_sigma = 0.1
layer[s->s] = relu
layer[s->a,b,c,d] = split
layer[a->a1] = conv:b0
  kernel_size = 1
  nchannel = 8
  init_sigma = 0.1
layer[a1->a1] = relu
layer[b->b1] = conv:r3
  kernel_size = 1
  nchannel = 4
  init_sigma = 0.1
layer[b1->b1] = relu
layer[b1->b2] = conv:c3
  kernel_size = 3
  nchannel = 8
  pad = 1
  init_sigma = 0.1
layer[b2->b2] = relu
layer[c->c1] = conv:r5
  kernel_size = 1
  nchannel = 4
  init_sigma = 0.1
layer[c1->c1] = relu
layer[c1->c2] = conv:c5
  kernel_size = 5
  nchannel = 8
  pad = 2
  init_sigma = 0.1
layer[c2->c2] = relu
layer[d->d1] = max_pooling
  kernel_size = 3
  stride = 1
  pad = 1
layer[d1->d2] = conv:proj
  kernel_size = 1
  nchannel = 8
  init_sigma = 0.1
layer[d2->d2] = relu
layer[a1,b2,c2,d2->cc] = ch_concat
layer[cc->e,f,g] = split
layer[e->e1] = conv:m2_1x1
  kernel_size = 1
  nchannel = 8
  init_sigma = 0.1
layer[e1->e1] = relu
layer[f->f1] = conv:m2_r3
  kernel_size = 1
  nchannel = 4
  init_sigma = 0.1
layer[f1->f1] = relu
layer[f1->f2] = conv:m2_c3
  kernel_size = 3
  nchannel = 8
  pad = 1
  init_sigma = 0.1
layer[f2->f2] = relu
layer[g->g1] = max_pooling
  kernel_size = 3
  stride = 1
  pad = 1
layer[g1->g2] = conv:m2_proj
  kernel_size = 1
  nchannel = 4
  init_sigma = 0.1
layer[g2->g2] = relu
layer[e1,f2,g2->cc2] = ch_concat
layer[cc2->gp] = avg_pooling
  kernel_size = 12
  stride = 1
layer[gp->fl] = flatten
layer[fl->fc] = fullc:f1
  nhidden = 4
  init_sigma = 0.1
layer[fc->fc] = softmax
netconfig=end
input_shape = 3,12,12
batch_size = 16
dev = cpu
eta = 0.05
momentum = 0.9
metric = error
silent = 1
"""


def test_conv_sibling_fuse_matches():
    """conv_sibling_fuse=1 runs the inception 1x1 reduce convs as one
    fused conv + slices; the trajectory must match the unfused path
    (identical math up to fp reduction order)."""
    from cxxnet_tpu.engine import opts, set_engine_option
    old = opts.conv_sibling_fuse
    try:
        set_engine_option("conv_sibling_fuse", "0")
        ref = make_trainer(INCEPTION_CONF)
        set_engine_option("conv_sibling_fuse", "1")
        fu = make_trainer(INCEPTION_CONF)
        assert fu.net.fuse_groups, "sibling fuse did not fire"
        assert sum(len(m) for m in fu.net.fuse_groups.values()) == 5, \
            fu.net.fuse_groups  # {b0,r3,r5} on the stem + {m2_1x1,m2_r3}
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                fu.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
        rnd = np.random.RandomState(11)
        for _ in range(4):
            x = rnd.randn(16, 3, 12, 12).astype(np.float32)
            y = (rnd.rand(16) * 4).astype(np.float32)
            b = DataBatch(data=x, label=y.reshape(16, 1),
                          index=np.arange(16, dtype=np.uint32))
            ref.update(b)
            fu.update(b)
            np.testing.assert_allclose(
                np.asarray(fu._last_loss), np.asarray(ref._last_loss),
                rtol=1e-5)
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                np.testing.assert_allclose(
                    np.asarray(fu.params[pkey][tag]), np.asarray(v),
                    rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")
    finally:
        set_engine_option("conv_sibling_fuse", old)


@pytest.mark.parametrize("fuse", ["0", "1"])
def test_concat_virtual_matches(fuse):
    """concat_virtual=1 keeps ch_concat values as segment tuples (convs
    consume K-sliced sums, pools/split map per segment, unaware
    consumers materialize); trajectory must match the materializing
    path, alone and composed with conv_sibling_fuse."""
    from cxxnet_tpu.engine import opts, set_engine_option
    old_v, old_f = opts.concat_virtual, opts.conv_sibling_fuse
    try:
        set_engine_option("concat_virtual", "0")
        set_engine_option("conv_sibling_fuse", "0")
        ref = make_trainer(INCEPTION_CONF)
        set_engine_option("concat_virtual", "1")
        set_engine_option("conv_sibling_fuse", fuse)
        vt = make_trainer(INCEPTION_CONF)
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                vt.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
        rnd = np.random.RandomState(13)
        for _ in range(3):
            x = rnd.randn(16, 3, 12, 12).astype(np.float32)
            y = (rnd.rand(16) * 4).astype(np.float32)
            b = DataBatch(data=x, label=y.reshape(16, 1),
                          index=np.arange(16, dtype=np.uint32))
            ref.update(b)
            vt.update(b)
            np.testing.assert_allclose(
                np.asarray(vt._last_loss), np.asarray(ref._last_loss),
                rtol=1e-5)
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                np.testing.assert_allclose(
                    np.asarray(vt.params[pkey][tag]), np.asarray(v),
                    rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")
    finally:
        set_engine_option("concat_virtual", old_v)
        set_engine_option("conv_sibling_fuse", old_f)


def test_batch_split_matches():
    """batch_split=K runs K independent sub-batch chains with summed
    losses; on a dropout-free net the trajectory matches the unsplit
    path (same math, summation order aside)."""
    ref = make_trainer(S2D_CONF)
    sp = make_trainer(S2D_CONF, extra=[("batch_split", "2")])
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            sp.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
    rnd = np.random.RandomState(5)
    for _ in range(4):
        x = rnd.randn(16, 3, 21, 21).astype(np.float32)
        y = (rnd.rand(16) * 4).astype(np.float32)
        b = DataBatch(data=x, label=y.reshape(16, 1),
                      index=np.arange(16, dtype=np.uint32))
        ref.update(b)
        sp.update(b)
        np.testing.assert_allclose(
            np.asarray(sp._last_loss), np.asarray(ref._last_loss),
            rtol=1e-5)
        # eval outs concatenate in sub-batch order
        np.testing.assert_allclose(
            np.asarray(sp._last_outs[ref.eval_node_ids[0]]),
            np.asarray(ref._last_outs[ref.eval_node_ids[0]]),
            rtol=1e-4, atol=1e-6)
    for pkey, group in ref.params.items():
        for tag, v in group.items():
            np.testing.assert_allclose(
                np.asarray(sp.params[pkey][tag]), np.asarray(v),
                rtol=1e-4, atol=1e-6, err_msg=f"{pkey}/{tag}")


def test_extract_feature_on_deferred_nodes():
    """extract_feature on nodes inside a deferred conv->relu->pool block
    must return the undeferred values: the relu node physically holds the
    pre-activation and the defer_bias conv node holds bias-less output,
    so the trainer re-applies relu/bias on read (_apply_read_fixup)."""
    from cxxnet_tpu.engine import opts, set_engine_option
    old = opts.pool_relu_reorder
    try:
        set_engine_option("pool_relu_reorder", "0")
        ref = make_trainer(S2D_CONF)
        set_engine_option("pool_relu_reorder", "1")
        ro = make_trainer(S2D_CONF)
        assert ro._read_fixups, "deferral fired but no read fixups recorded"
        for pkey, group in ref.params.items():
            for tag, v in group.items():
                ro.set_weight(np.asarray(v), pkey.split("-", 1)[1], tag)
        rnd = np.random.RandomState(33)
        x = rnd.randn(16, 3, 21, 21).astype(np.float32)
        b = DataBatch(data=x, label=np.zeros((16, 1), np.float32),
                      index=np.arange(16, dtype=np.uint32))
        for node in ("1", "2", "3"):  # conv out, relu out, pool out
            got = ro.extract_feature(b, node)
            want = ref.extract_feature(b, node)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                       err_msg=f"node {node}")
        assert ref.extract_feature(b, "2").min() >= 0.0
    finally:
        set_engine_option("pool_relu_reorder", old)


def test_evaluate_extra_data_grouped_fallback():
    """evaluate() with eval_group > 1 must fall back to the per-batch
    path for batches carrying extra_data (the grouped scan doesn't
    thread side inputs; trainer.py flush()/extra_data fallback —
    untested per VERDICT r4 weak #7).  A net consuming in_1 makes every
    batch take the fallback, so correctness is checked against an
    independent oracle (predict_raw per batch), including a padded tail
    batch whose padding must be excluded from the metric."""
    conf = """extra_data_num = 1
extra_data_shape[0] = 1,1,2
netconfig=start
layer[0->a] = fullc:f1
  nhidden = 4
layer[in_1->b] = fullc:f2
  nhidden = 4
layer[a,b->c] = eltsum
layer[c->d] = fullc:f3
  nhidden = 3
layer[d->d] = softmax
netconfig=end
input_shape = 1,1,4
batch_size = 4
dev = cpu
metric = error
eta = 0.1
"""
    rnd = np.random.RandomState(0)
    bs = []
    for i in range(3):
        bs.append(DataBatch(
            data=rnd.rand(4, 1, 1, 4).astype(np.float32),
            label=rnd.randint(0, 3, (4, 1)).astype(np.float32),
            index=np.arange(4, dtype=np.uint32),
            num_batch_padd=2 if i == 2 else 0,
            extra_data=[rnd.rand(4, 1, 1, 2).astype(np.float32)]))

    t = make_trainer(conf, extra=[("eval_group", "4")])
    line = t.evaluate(list(bs), "test")
    # oracle: per-batch predictions through the independent predict path
    wrong = total = 0
    for b in bs:
        pred = t.predict(b)  # already strips num_batch_padd
        lab = b.label[:b.batch_size - b.num_batch_padd, 0]
        wrong += int((pred != lab).sum())
        total += lab.shape[0]
    want = wrong / total
    got = float(line.split("test-error:")[1])
    assert abs(got - want) < 1e-6, (line, want)
