"""Native (C++) data loader tests: format interop with the Python writer,
label pairing under shuffle, round_batch padding protocol, sharded reads,
and the im2bin packer binary."""

import os
import subprocess

import numpy as np
import pytest

from cxxnet_tpu.io.factory import create_iterator, init_iterator
from cxxnet_tpu.io.imbin import BinaryPageWriter

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def _have_toolchain():
    try:
        subprocess.run(["make", "-C", NATIVE_DIR], check=True,
                       capture_output=True)
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


pytestmark = pytest.mark.skipif(not _have_toolchain(),
                                reason="no native toolchain")


def write_dataset(tmp_path, n=23, c=3, h=8, w=8, page_size=1 << 12,
                  dtype="u8", nshard=1):
    """Pack n deterministic instances; instance i has data filled with
    (i % 251) and label [i, i*2]."""
    rnd = np.random.RandomState(5)
    per = (n + nshard - 1) // nshard
    paths = []
    for s in range(nshard):
        bin_p = str(tmp_path / f"d{s}.bin")
        lst_p = str(tmp_path / f"d{s}.lst")
        wtr = BinaryPageWriter(bin_p, page_size=page_size)
        with open(lst_p, "w") as lf:
            for i in range(s * per, min(n, (s + 1) * per)):
                if dtype == "u8":
                    payload = np.full(c * h * w, i % 251, np.uint8).tobytes()
                else:
                    payload = (np.full(c * h * w, i, np.float32)
                               + 0.25).tobytes()
                wtr.push(payload)
                lf.write(f"{i}\t{float(i)}\t{float(i * 2)}\tf{i}.bin\n")
        wtr.close()
        paths.append((bin_p, lst_p))
    return paths


def make_native(tmp_path, extra="", nshard=1, **kw):
    paths = write_dataset(tmp_path, nshard=nshard, **kw)
    if nshard == 1:
        pb, pl = paths[0]
        binspec, lstspec = pb, pl
        count = ""
    else:
        binspec = str(tmp_path / "d%d.bin")
        lstspec = str(tmp_path / "d%d.lst")
        count = f"imgbin_count = {nshard}\n"
    cfg = [("iter", "imbin_native")]
    conf_text = f"""
path_imgbin = {binspec}
path_imglst = {lstspec}
{count}label_width = 2
input_shape = 3,8,8
silent = 1
{extra}
"""
    for line in conf_text.strip().splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            cfg.append((k.strip(), v.strip()))
    it = create_iterator(cfg)
    return init_iterator(it, [("batch_size", "4")])


def collect_epoch(it):
    batches = []
    it.before_first()
    while True:
        b = it.next()
        if b is None:
            return batches
        batches.append(b)


def test_native_basic_contents(tmp_path):
    it = make_native(tmp_path)
    batches = collect_epoch(it)
    # 23 instances, batch 4: tail replica-padded + masked -> 6 batches
    assert len(batches) == 6
    seen = {}
    for b in batches[:-1]:
        assert b.num_batch_padd == 0
        assert b.tail_mask_padd == 0
    tail = batches[-1]
    assert tail.num_batch_padd == 1 and tail.tail_mask_padd == 1
    # the replica row copies the last real instance
    np.testing.assert_array_equal(tail.data[3], tail.data[2])
    for b in batches:
        assert b.data.shape == (4, 3, 8, 8)
        assert b.label.shape == (4, 2)
        for j in range(4 - b.tail_mask_padd):
            i = int(b.index[j])
            seen[i] = (b.data[j], b.label[j])
    assert len(seen) == 23
    for i, (d, l) in seen.items():
        np.testing.assert_array_equal(d, np.full((3, 8, 8), i % 251,
                                                 np.float32))
        np.testing.assert_array_equal(l, [i, 2 * i])
    # second epoch identical
    assert len(collect_epoch(it)) == 6


def test_native_round_batch_and_f32(tmp_path):
    it = make_native(tmp_path, extra="round_batch = 1", dtype="f32")
    batches = collect_epoch(it)
    assert len(batches) == 6
    assert batches[-1].num_batch_padd == 1  # 23 = 5*4 + 3 -> pad 1
    for b in batches:
        for j in range(4):
            i = int(b.index[j])
            np.testing.assert_allclose(b.data[j],
                                       np.full((3, 8, 8), i + 0.25), rtol=0)


def test_native_shuffle_pairs_labels(tmp_path):
    it = make_native(tmp_path, extra="shuffle = 1\nround_batch = 1", n=37)
    seen = set()
    for b in collect_epoch(it):
        for j in range(4 - b.num_batch_padd):
            i = int(b.index[j])
            np.testing.assert_array_equal(b.label[j], [i, 2 * i])
            np.testing.assert_array_equal(
                b.data[j], np.full((3, 8, 8), i % 251, np.float32))
            assert i not in seen
            seen.add(i)
    assert seen == set(range(37))


def test_native_mean_scale(tmp_path):
    it = make_native(tmp_path, extra="mean_value = 1,2,3\nscale = 0.5", n=8)
    b = collect_epoch(it)[0]
    i = int(b.index[0])
    expect = (np.full((3, 8, 8), i % 251, np.float32)
              - np.array([1, 2, 3], np.float32)[:, None, None]) * 0.5
    np.testing.assert_allclose(b.data[0], expect)


def test_native_sharded(tmp_path):
    it = make_native(tmp_path, nshard=3, n=24, extra="round_batch = 1")
    seen = set()
    for b in collect_epoch(it):
        for j in range(4 - b.num_batch_padd):
            i = int(b.index[j])
            np.testing.assert_array_equal(b.label[j], [i, 2 * i])
            seen.add(i)
    assert seen == set(range(24))


def test_native_worker_sharding(tmp_path):
    """dist_num_worker/dist_worker_rank split shards across workers."""
    write_dataset(tmp_path, n=24, nshard=4)
    got = set()
    for rank in (0, 1):
        cfg = [("iter", "imbin_native"),
               ("path_imgbin", str(tmp_path / "d%d.bin")),
               ("path_imglst", str(tmp_path / "d%d.lst")),
               ("imgbin_count", "4"), ("label_width", "2"),
               ("input_shape", "3,8,8"), ("silent", "1"),
               ("dist_num_worker", "2"), ("dist_worker_rank", str(rank)),
               ("round_batch", "1")]
        it = init_iterator(create_iterator(cfg), [("batch_size", "4")])
        ranks_seen = set()
        for b in collect_epoch(it):
            for j in range(4 - b.num_batch_padd):
                ranks_seen.add(int(b.index[j]))
        assert len(ranks_seen) == 12
        got |= ranks_seen
    assert got == set(range(24))


def test_native_jpeg_records(tmp_path):
    cv2 = pytest.importorskip("cv2")
    bin_p = str(tmp_path / "j.bin")
    lst_p = str(tmp_path / "j.lst")
    rnd = np.random.RandomState(0)
    w = BinaryPageWriter(bin_p, page_size=1 << 14)
    imgs = []
    with open(lst_p, "w") as lf:
        for i in range(6):
            img = (rnd.rand(8, 8, 3) * 255).astype(np.uint8)
            ok, enc = cv2.imencode(".jpg", img,
                                   [cv2.IMWRITE_JPEG_QUALITY, 95])
            assert ok
            w.push(enc.tobytes())
            imgs.append(img)
            lf.write(f"{i}\t{float(i)}\tf{i}.jpg\n")
    w.close()
    cfg = [("iter", "imbin_native"), ("path_imgbin", bin_p),
           ("path_imglst", lst_p), ("input_shape", "3,8,8"), ("silent", "1")]
    it = init_iterator(create_iterator(cfg), [("batch_size", "3")])
    batches = collect_epoch(it)
    assert len(batches) == 2
    for b in batches:
        for j in range(3):
            i = int(b.index[j])
            # libjpeg decodes RGB; cv2 encoded BGR -> compare via cv2 RGB
            ref = cv2.cvtColor(cv2.imdecode(
                np.frombuffer(
                    cv2.imencode(".jpg", imgs[i],
                                 [cv2.IMWRITE_JPEG_QUALITY, 95])[1], np.uint8),
                cv2.IMREAD_COLOR), cv2.COLOR_BGR2RGB)
            np.testing.assert_allclose(
                b.data[j], ref.transpose(2, 0, 1).astype(np.float32),
                atol=16)  # decoder rounding differences


def test_im2bin_binary_roundtrip(tmp_path):
    """The C++ im2bin packer output is readable by the native iterator."""
    raw_dir = tmp_path / "raw"
    raw_dir.mkdir()
    lst_p = str(tmp_path / "pack.lst")
    with open(lst_p, "w") as lf:
        for i in range(5):
            blob = np.full(3 * 8 * 8, i + 10, np.uint8)
            with open(raw_dir / f"f{i}.raw", "wb") as f:
                f.write(blob.tobytes())
            lf.write(f"{i}\t{float(i)}\tf{i}.raw\n")
    bin_p = str(tmp_path / "pack.bin")
    subprocess.run([os.path.join(NATIVE_DIR, "im2bin"), lst_p, str(raw_dir),
                    bin_p, "4096"], check=True, capture_output=True)
    cfg = [("iter", "imbin_native"), ("path_imgbin", bin_p),
           ("path_imglst", lst_p), ("input_shape", "3,8,8"),
           ("silent", "1"), ("round_batch", "1")]
    it = init_iterator(create_iterator(cfg), [("batch_size", "2")])
    seen = set()
    for b in collect_epoch(it):
        for j in range(2 - b.num_batch_padd):
            i = int(b.index[j])
            np.testing.assert_array_equal(
                b.data[j], np.full((3, 8, 8), i + 10, np.float32))
            seen.add(i)
    assert seen == set(range(5))


def test_native_trains_net(tmp_path):
    """End-to-end: native loader feeding the jitted trainer."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    write_dataset(tmp_path, n=32, c=3, h=8, w=8)
    conf = [("iter", "imbin_native"),
            ("path_imgbin", str(tmp_path / "d0.bin")),
            ("path_imglst", str(tmp_path / "d0.lst")),
            ("input_shape", "3,8,8"), ("silent", "1"),
            ("label_width", "2"), ("round_batch", "1"),
            ("scale", "0.01")]
    it = init_iterator(create_iterator(conf), [("batch_size", "8")])
    net_conf = """
netconfig=start
layer[0->1] = flatten
layer[1->2] = fullc:fc
  nhidden = 4
layer[2->2] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 8
dev = cpu
eta = 0.1
silent = 1
"""
    from cxxnet_tpu.utils.config import parse_config_string
    t = NetTrainer()
    for k, v in parse_config_string(net_conf):
        t.set_param(k, v)
    t.init_model()
    t.start_round(1)
    from cxxnet_tpu.io.data import DataBatch
    losses = []
    for _ in range(4):
        for b in it:
            # class = instance index % 4
            lb = DataBatch(data=b.data, label=b.label[:, :1] % 4,
                           index=b.index, num_batch_padd=b.num_batch_padd)
            t.update(lb)
            losses.append(float(np.asarray(t._last_loss)))
    assert losses[-1] < losses[0]


def test_native_decode_pool_matches_inline(tmp_path):
    """The decode thread pool (decode_thread_num > 0) yields exactly the
    inline path's batches: same contents, order, round_batch tail, and
    repeated epochs (the pooled producer pipelines two batches in flight)."""
    it0 = make_native(tmp_path, extra="round_batch = 1")
    it2 = make_native(tmp_path, extra="round_batch = 1\n"
                                      "decode_thread_num = 3")
    for _ in range(3):  # several epochs: generation/restart machinery
        b0 = collect_epoch(it0)
        b2 = collect_epoch(it2)
        assert len(b0) == len(b2)
        for x, y in zip(b0, b2):
            np.testing.assert_array_equal(x.data, y.data)
            np.testing.assert_array_equal(x.label, y.label)
            np.testing.assert_array_equal(x.index, y.index)
            assert x.num_batch_padd == y.num_batch_padd
    it0.close()
    it2.close()


def test_native_decode_pool_shuffle_and_jpeg(tmp_path):
    """Pooled decode with shuffle + jpeg records keeps (data, label, index)
    in lockstep and survives mid-epoch restart (generation bump)."""
    it = make_native(tmp_path, extra="shuffle = 1\nround_batch = 1\n"
                                     "decode_thread_num = 2", n=37)
    it.before_first()
    it.next()  # abandon mid-epoch: stale jobs must drain harmlessly
    batches = collect_epoch(it)
    seen = set()
    for b in batches:
        for j in range(b.batch_size - b.num_batch_padd):
            i = int(b.index[j])
            np.testing.assert_array_equal(
                b.data[j], np.full((3, 8, 8), i % 251, np.float32))
            seen.add(i)
    assert seen == set(range(37))
    it.close()


def test_native_round_batch_small_dataset(tmp_path):
    """round_batch with dataset < batch_size: the tail wraps with the
    stream's own first instances (reference batch-adapter parity), in both
    inline and pooled decode modes."""
    for extra in ("round_batch = 1",
                  "round_batch = 1\ndecode_thread_num = 2"):
        it = make_native(tmp_path, extra=extra, n=3)
        batches = collect_epoch(it)
        assert len(batches) == 1
        b = batches[0]
        assert b.num_batch_padd == 1  # 3 real + 1 wrapped of batch 4
        for j in range(4):
            i = int(b.index[j])
            np.testing.assert_array_equal(
                b.data[j], np.full((3, 8, 8), i % 251, np.float32))
        it.close()


def test_native_malformed_lst_is_error(tmp_path):
    """1-2 token lines must fail init, not silently desync label pairing."""
    write_dataset(tmp_path, n=6)
    lst = tmp_path / "d0.lst"
    lines = lst.read_text().splitlines()
    lines[2] = "2 2.0"  # drop the filename token
    lst.write_text("\n".join(lines) + "\n")
    cfg = [("iter", "imbin_native"), ("path_imgbin", str(tmp_path / "d0.bin")),
           ("path_imglst", str(lst)), ("label_width", "2"),
           ("input_shape", "3,8,8"), ("silent", "1")]
    with pytest.raises(RuntimeError, match="line 3"):
        init_iterator(create_iterator(cfg), [("batch_size", "2")])


def test_native_rejects_augmentation_keys(tmp_path):
    """Augmentation config must fail loudly, not silently train without it."""
    write_dataset(tmp_path, n=6)
    cfg = [("iter", "imbin_native"), ("path_imgbin", str(tmp_path / "d0.bin")),
           ("path_imglst", str(tmp_path / "d0.lst")), ("label_width", "2"),
           ("input_shape", "3,8,8"), ("silent", "1"), ("rand_mirror", "1")]
    with pytest.raises(RuntimeError, match="rand_mirror"):
        init_iterator(create_iterator(cfg), [("batch_size", "2")])


def test_native_error_cleared_on_restart(tmp_path):
    """A failed epoch's error must not poison a later epoch's normal end."""
    it = make_native(tmp_path, n=3)  # 3 insts < batch 4, round_batch off
    # first: force an error epoch via a dataset that trips round_batch
    # wrap with too few instances
    (tmp_path / "b").mkdir()
    it2 = make_native(tmp_path / "b", n=1, extra="round_batch = 1")
    it2.before_first()
    with pytest.raises(RuntimeError, match="smaller than batch"):
        while it2.next() is not None:
            pass
    # restart: a fresh iterator over good data must work cleanly after an
    # earlier error — 3 insts pad to one masked batch, then a clean end
    it.before_first()
    b = it.next()
    assert b is not None and b.tail_mask_padd == 1
    assert it.next() is None  # clean end, no stale error


def test_native_u8_output_mode(tmp_path):
    """output_u8=1 emits raw uint8 batches (device-side normalization
    path): same instances/order as the float path, no mean/scale applied
    on the host."""
    it8 = make_native(tmp_path, extra="output_u8 = 1")
    itf = make_native(tmp_path, extra="")  # same dataset files
    b8s = collect_epoch(it8)
    bfs = collect_epoch(itf)
    assert len(b8s) == len(bfs) == 6
    for b8, bf in zip(b8s, bfs):
        assert b8.data.dtype == np.uint8
        np.testing.assert_array_equal(b8.data.astype(np.float32), bf.data)
        np.testing.assert_array_equal(b8.label, bf.label)
        np.testing.assert_array_equal(b8.index, bf.index)
        assert b8.tail_mask_padd == bf.tail_mask_padd


def test_u8_device_normalization_matches_host(tmp_path):
    """Training on u8 batches with trainer-side (x-mean)*scale must match
    training on host-normalized float batches bit-for-... closely."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.io.data import DataBatch

    CONF = """
netconfig=start
layer[+1] = flatten
layer[+1] = fullc:fc
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 4
dev = cpu
eta = 0.1
mean_value = 10,20,30
scale = 0.01
silent = 1
"""

    def trainer():
        t = NetTrainer()
        for k, v in parse_config_string(CONF):
            t.set_param(k, v)
        t.init_model()
        return t

    rnd = np.random.RandomState(0)
    raw = rnd.randint(0, 255, (4, 3, 8, 8)).astype(np.uint8)
    label = rnd.randint(0, 4, (4, 1)).astype(np.float32)
    mean = np.array([10, 20, 30], np.float32).reshape(1, 3, 1, 1)
    host_norm = (raw.astype(np.float32) - mean) * 0.01

    tu = trainer()
    tf = trainer()
    tu.update(DataBatch(data=raw, label=label,
                        index=np.arange(4, dtype=np.uint32)))
    tf.update(DataBatch(data=host_norm, label=label,
                        index=np.arange(4, dtype=np.uint32)))
    for pkey in tu.params:
        for tag, v in tu.params[pkey].items():
            np.testing.assert_allclose(np.asarray(v),
                                       np.asarray(tf.params[pkey][tag]),
                                       rtol=1e-6, atol=1e-7,
                                       err_msg=f"{pkey}/{tag}")


def test_native_jpeg_u8_records(tmp_path):
    """jpeg + output_u8: DecodeJpeg8's planar deinterleave must match the
    float decoder exactly (same pixels, u8 dtype)."""
    cv2 = pytest.importorskip("cv2")
    bin_p = str(tmp_path / "j.bin")
    lst_p = str(tmp_path / "j.lst")
    rnd = np.random.RandomState(7)
    w = BinaryPageWriter(bin_p, page_size=1 << 14)
    with open(lst_p, "w") as lf:
        for i in range(6):
            img = (rnd.rand(8, 8, 3) * 255).astype(np.uint8)
            ok, enc = cv2.imencode(".jpg", img,
                                   [cv2.IMWRITE_JPEG_QUALITY, 95])
            assert ok
            w.push(enc.tobytes())
            lf.write(f"{i}\t{float(i)}\tf{i}.jpg\n")
    w.close()

    def make(extra):
        cfg = [("iter", "imbin_native"), ("path_imgbin", bin_p),
               ("path_imglst", lst_p), ("input_shape", "3,8,8"),
               ("silent", "1")] + extra
        return init_iterator(create_iterator(cfg), [("batch_size", "3")])

    b8s = collect_epoch(make([("output_u8", "1")]))
    bfs = collect_epoch(make([]))
    assert len(b8s) == len(bfs) == 2
    for b8, bf in zip(b8s, bfs):
        assert b8.data.dtype == np.uint8
        np.testing.assert_array_equal(b8.data.astype(np.float32), bf.data)


def test_cli_train_e2e_on_u8_native_pipeline(tmp_path):
    """Full CLI train over the native loader in u8 mode: raw u8 records
    stream through C++ untouched, the trainer normalizes on device, and
    a linearly-separable task trains to zero error — the whole
    output_u8 path exercised at the task-driver level."""
    from cxxnet_tpu.main import LearnTask

    rnd = np.random.RandomState(0)
    n, c, h, w = 96, 1, 8, 8
    bin_p = str(tmp_path / "u8.bin")
    lst_p = str(tmp_path / "u8.lst")
    wtr = BinaryPageWriter(bin_p, page_size=1 << 12)
    with open(lst_p, "w") as lf:
        for i in range(n):
            label = i % 2
            img = rnd.randint(0, 60, (c, h, w)).astype(np.uint8)
            if label:
                img[:, :4] = np.minimum(img[:, :4] + 150, 255)
            wtr.push(img.tobytes())
            lf.write(f"{i}\t{float(label)}\tu{i}.bin\n")
    wtr.close()
    conf = tmp_path / "u8.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = imbin_native
  path_imgbin = {bin_p}
  path_imglst = {lst_p}
  output_u8 = 1
  decode_thread_num = 0
iter = end
eval = val
iter = imbin_native
  path_imgbin = {bin_p}
  path_imglst = {lst_p}
  output_u8 = 1
  decode_thread_num = 0
iter = end
netconfig=start
layer[+1] = flatten
layer[+1] = fullc:fc
  nhidden = 2
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = {c},{h},{w}
mean_value = 64
scale = 0.01
batch_size = 16
eta = 0.5
num_round = 8
metric = error
model_dir = {tmp_path}/models
save_model = 0
silent = 1
""")
    import io as _io
    import contextlib
    import re
    err = _io.StringIO()
    with contextlib.redirect_stderr(err):
        assert LearnTask().run([str(conf)]) == 0
    lines = [ln for ln in err.getvalue().splitlines() if "val-error" in ln]
    assert lines, err.getvalue()[-500:]
    final_err = float(re.search(r"val-error:([0-9.eE+-]+)",
                                lines[-1]).group(1))
    assert final_err == 0.0, lines[-3:]
