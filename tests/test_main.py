"""End-to-end CLI task tests (LearnTask) and the cv-affine augmenter.

Reference behaviors: task driver ``src/cxxnet_main.cpp`` (train/pred_raw),
affine augmentation ``src/io/image_augmenter-inl.hpp``.
"""

import os

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataInst
from cxxnet_tpu.io.iter_proc import AffineAugmenter
from cxxnet_tpu.main import LearnTask


# --------------------------------------------------------------- affine aug

def _inst(shape=(3, 12, 12)):
    rnd = np.random.RandomState(0)
    return rnd.rand(*shape).astype(np.float32)


def test_affine_noop_when_params_off():
    a = AffineAugmenter()
    assert not a.need_process


def test_affine_rotation_shape_and_determinism():
    a = AffineAugmenter()
    assert a.set_param("max_rotate_angle", "180")
    assert a.need_process
    d = _inst()
    o1 = a.process(d, np.random.RandomState(7), (12, 12))
    o2 = a.process(d, np.random.RandomState(7), (12, 12))
    assert o1.shape == (3, 12, 12)
    np.testing.assert_array_equal(o1, o2)
    # a different seed draws a different angle
    o3 = a.process(d, np.random.RandomState(8), (12, 12))
    assert np.abs(o1 - o3).max() > 1e-3


def test_affine_rotate_180_flips_both_axes():
    a = AffineAugmenter()
    a.set_param("rotate", "180")
    d = _inst((1, 9, 9))  # odd size: exact center, no interpolation drift
    out = a.process(d, np.random.RandomState(0), (9, 9))
    np.testing.assert_allclose(out[0], d[0, ::-1, ::-1], atol=1e-4)


def test_affine_rotate_list_and_crop_resize():
    a = AffineAugmenter()
    a.set_param("rotate_list", "0,90,180,270")
    a.set_param("min_crop_size", "8")
    a.set_param("max_crop_size", "12")
    out = a.process(_inst(), np.random.RandomState(3), (10, 10))
    assert out.shape == (3, 10, 10)
    assert out.dtype == np.float32


def test_affine_shear_aspect_changes_image():
    a = AffineAugmenter()
    a.set_param("max_shear_ratio", "0.3")
    a.set_param("max_aspect_ratio", "0.5")
    d = _inst()
    out = a.process(d, np.random.RandomState(1), (12, 12))
    assert out.shape == d.shape
    assert np.abs(out - d).max() > 1e-3


def test_augment_iterator_applies_affine_and_mean_crop(tmp_path):
    """Mean image built at base size must still subtract after the affine
    stage resizes instances to input_shape (center-crop of the mean)."""
    from cxxnet_tpu.io.iter_proc import AugmentIterator

    class _Base:
        def __init__(self):
            self.d = np.ones((3, 12, 12), np.float32)
            self.i = 0

        def set_param(self, n, v):
            pass

        def init(self):
            pass

        def before_first(self):
            self.i = 0

        def next(self):
            if self.i >= 4:
                return None
            self.i += 1
            return DataInst(label=np.zeros(1, np.float32), data=self.d,
                            index=self.i)

    it = AugmentIterator(_Base())
    it.set_param("min_crop_size", "8")
    it.set_param("max_crop_size", "12")
    it.set_param("input_shape", "3,8,8")
    it.set_param("image_mean", str(tmp_path / "mean.npz"))
    it.init()  # builds the mean (all ones)
    it.before_first()
    inst = it.next()
    assert inst.data.shape == (3, 8, 8)
    # ones minus mean-of-ones == 0 everywhere, regardless of the crop drawn
    np.testing.assert_allclose(inst.data, 0.0, atol=1e-5)


# ------------------------------------------------------------ CLI end-to-end

MLP_NET = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 32
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
"""


def _write_synth_mnist(tmp_path, n=64, classes=4, side=12):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import make_synth_mnist as sm
    rnd = np.random.RandomState(0)
    labels = rnd.randint(0, classes, n)
    imgs = np.stack([
        np.clip(sm.class_pattern(l, side, side) * 255
                + rnd.rand(side, side) * 32, 0, 255)
        for l in labels])
    sm.write_idx_images(str(tmp_path / "img.gz"), imgs)
    sm.write_idx_labels(str(tmp_path / "lbl.gz"), labels)


@pytest.fixture
def mnist_conf(tmp_path):
    _write_synth_mnist(tmp_path, n=128)
    conf = tmp_path / "train.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 1
iter = end
eval = val
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
num_round = 12
metric = error
model_dir = {tmp_path}/models
save_model = 4
silent = 1
""")
    return conf, tmp_path


def test_cli_train_then_pred_raw(mnist_conf, capsys):
    conf, tmp_path = mnist_conf
    assert LearnTask().run([str(conf)]) == 0
    model = tmp_path / "models" / "0012.model"
    assert model.exists()

    pred_conf = tmp_path / "pred.conf"
    pred_conf.write_text(f"""
dev = cpu
task = pred_raw
model_in = {model}
pred = {tmp_path}/scores.txt
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
silent = 1
""")
    assert LearnTask().run([str(pred_conf)]) == 0
    rows = np.loadtxt(tmp_path / "scores.txt")
    assert rows.shape == (128, 4)
    np.testing.assert_allclose(rows.sum(axis=1), 1.0, atol=1e-3)

    # the trained model should mostly predict the true classes
    import gzip
    with gzip.open(tmp_path / "lbl.gz", "rb") as f:
        f.read(8)
        labels = np.frombuffer(f.read(), np.uint8)
    acc = (rows.argmax(axis=1) == labels).mean()
    assert acc > 0.8, f"pred_raw accuracy {acc}"


def test_cli_pred_argmax(mnist_conf):
    conf, tmp_path = mnist_conf
    assert LearnTask().run([str(conf), "num_round=4"]) == 0
    pred_conf = tmp_path / "predc.conf"
    pred_conf.write_text(f"""
dev = cpu
task = pred
model_in = {tmp_path}/models/0004.model
pred = {tmp_path}/cls.txt
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
silent = 1
""")
    assert LearnTask().run([str(pred_conf)]) == 0
    cls = np.loadtxt(tmp_path / "cls.txt")
    assert cls.shape == (128,)
    assert set(np.unique(cls)) <= {0.0, 1.0, 2.0, 3.0}


def test_cli_extract_binary_output(mnist_conf):
    conf, tmp_path = mnist_conf
    assert LearnTask().run([str(conf), "num_round=4"]) == 0
    ex_conf = tmp_path / "ex.conf"
    ex_conf.write_text(f"""
dev = cpu
task = extract
model_in = {tmp_path}/models/0004.model
extract_node_name = 2
output_format = bin
pred = {tmp_path}/feat.bin
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
silent = 1
""")
    assert LearnTask().run([str(ex_conf)]) == 0
    dim = int((tmp_path / "feat.bin.meta").read_text().strip())
    assert dim == 32  # fc1 width
    raw = np.fromfile(tmp_path / "feat.bin", dtype="<f4")
    assert raw.shape == (128 * 32,)
    # text output of the same extraction must match the binary numbers
    assert LearnTask().run([str(ex_conf), "output_format=txt",
                            f"pred={tmp_path}/feat.txt"]) == 0
    txt = np.loadtxt(tmp_path / "feat.txt").reshape(-1)
    np.testing.assert_allclose(raw, txt, rtol=1e-4, atol=1e-5)


def test_cli_train_test_on_server(mnist_conf):
    """test_on_server=1 runs the replica-consistency check each round."""
    conf, tmp_path = mnist_conf
    assert LearnTask().run([str(conf), "num_round=3",
                            "test_on_server=1", "dev=cpu:0-1"]) == 0


@pytest.fixture
def conv_s2d_conf(tmp_path):
    """Strided-conv net on synthetic 12x12 mnist-format data, input_s2d
    on: the CLI driver must wrap every iterator with host-side s2d
    emission and train/evaluate through the full chain."""
    _write_synth_mnist(tmp_path, n=128)
    conf = tmp_path / "train.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = mnist
  input_flat = 0
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 1
iter = end
eval = val
iter = mnist
  input_flat = 0
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 5
  stride = 2
  nchannel = 8
  init_sigma = 0.1
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:fc1
  nhidden = 4
  init_sigma = 0.1
layer[4->4] = softmax
netconfig=end
input_shape = 1,12,12
batch_size = 16
input_s2d = 1
eta = 0.1
momentum = 0.9
num_round = 8
metric = error
model_dir = {tmp_path}/models
save_model = 8
silent = 1
""")
    return conf, tmp_path


def test_cli_train_with_input_s2d(conv_s2d_conf, capsys):
    """input_s2d=1 through the CLI: host s2d emission wraps the
    iterators (no device fallback), the net trains to low error, and
    the trainer confirms the delivery shape."""
    conf, tmp_path = conv_s2d_conf
    task = LearnTask()
    assert task.run([str(conf)]) == 0
    from cxxnet_tpu.io.iter_proc import S2DEmitIterator
    assert isinstance(task.itr_train, S2DEmitIterator)
    assert all(isinstance(it, S2DEmitIterator) for it in task.itr_evals)
    err = capsys.readouterr().err
    last = [l for l in err.splitlines() if "val-error" in l][-1]
    assert float(last.rsplit(":", 1)[1]) < 0.2, last
