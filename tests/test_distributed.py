"""Two-process distributed training smoke test.

Spawns two real processes that join a jax.distributed coordinator on
localhost (CPU backend, 2 virtual devices each → a 4-device global data
mesh) and run the full CLI train path on a shared config — the multi-host
analogue of the reference's dist parameter-server launch
(``example/MNIST/mpi.conf``, ``nnet_ps_server.cpp:162-170``), with the PS
replaced by in-graph psum over the global mesh.

Each worker reads its own shard of the data (dist_num_worker /
dist_worker_rank are set from the process env automatically) and both must
converge to the same model: the test asserts the two processes' final
checkpoints are bit-identical, the multi-host equivalent of
``test_on_server`` weight checking (``async_updater-inl.hpp:144-154``).
"""

import os
import socket
import struct
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")

WORKER = r"""
import os, sys
sys.path.insert(0, sys.argv[1])
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
from cxxnet_tpu.main import LearnTask
rc = LearnTask().run(sys.argv[2:])
sys.exit(rc)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_synth_data(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import make_synth_mnist as sm
    rnd = np.random.RandomState(0)
    labels = rnd.randint(0, 4, 128)
    imgs = np.stack([np.clip(sm.class_pattern(l, 12, 12) * 255
                             + rnd.rand(12, 12) * 16, 0, 255)
                     for l in labels])
    sm.write_idx_images(str(tmp_path / "img.gz"), imgs)
    sm.write_idx_labels(str(tmp_path / "lbl.gz"), labels)


def _run_workers(conf, tmp_path, extra_args=()):
    """Launch two coordinated worker processes; return their outputs."""
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(CXN_COORDINATOR=f"127.0.0.1:{port}",
                   CXN_NUM_PROC="2", CXN_PROC_RANK=str(rank))
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER, ROOT, str(conf),
             f"model_dir={tmp_path}/m{rank}", *extra_args],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
    return outs


def _assert_checkpoints_identical(tmp_path, name, min_arrays=4):
    w0 = np.load(tmp_path / "m0" / name, allow_pickle=True)
    w1 = np.load(tmp_path / "m1" / name, allow_pickle=True)
    assert sorted(w0.files) == sorted(w1.files)
    n_arrays = 0
    for k in w0.files:
        if k == "__header__":
            # legitimately differs: captured config embeds the per-worker
            # model_dir and dist_worker_rank
            continue
        a, b = w0[k], w1[k]
        if a.dtype == object:
            continue
        np.testing.assert_array_equal(
            a, b, err_msg=f"replica weight {k} diverged across processes")
        n_arrays += 1
    assert n_arrays >= min_arrays


def test_two_process_training_identical_weights(tmp_path):
    _write_synth_data(tmp_path)

    conf = tmp_path / "dist.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 16
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,144
batch_size = 16
eta = 0.1
num_round = 3
metric = error
save_model = 1
silent = 1
""")
    outs = _run_workers(conf, tmp_path)
    # npz container metadata embeds timestamps; compare the tensors
    _assert_checkpoints_identical(tmp_path, "0003.model")
    # both workers evaluated the same global model: identical metric lines
    m0 = [l for l in outs[0].splitlines() if "train-error" in l]
    m1 = [l for l in outs[1].splitlines() if "train-error" in l]
    assert m0 and m0 == m1, f"metric lines diverged: {m0} vs {m1}"

    # ---- kill-and-continue: restart both workers with continue=1; the
    # resumed run must come up on the global mesh (load_model goes through
    # the same mesh bring-up as init_model) and end bit-identical across
    # processes (reference restart flow, cxxnet_main.cpp:135-157)
    outs2 = _run_workers(conf, tmp_path, ("continue=1", "num_round=5"))
    assert (tmp_path / "m0" / "0005.model").exists(), outs2[0][-2000:]
    _assert_checkpoints_identical(tmp_path, "0005.model")
    m0 = [l for l in outs2[0].splitlines() if "train-error" in l]
    m1 = [l for l in outs2[1].splitlines() if "train-error" in l]
    assert m0 and m0 == m1, f"continue metric lines diverged: {m0} vs {m1}"
    # the continued run really did load the round-3 checkpoint
    assert any("[4]" in l for l in m0), m0
