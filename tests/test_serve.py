"""Serving subsystem (serve/, task=serve — ISSUE 8, doc/serve.md).

Covers the contracts serving stands on: the micro-batcher coalesces
concurrent requests and NEVER hangs a client (timeout flush, exception
fan-out, shutdown hygiene — the ThreadBufferIterator discipline run in
reverse); the pinned-shape engine pads requests up to declared buckets
and never retraces after warmup; coalesced-vs-single predict is bitwise
identical at f32 (the property that makes dynamic batching safe to
enable); bf16/int8 quantized variants stay inside their declared
SERVE_TOL envelopes; multi-model hosting routes by name; and the CLI
task emits the latency/serve records the observatory reads.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.monitor.metrics import MetricsRegistry
from cxxnet_tpu.serve import ServeConfig, parse_shapes, shapes_check
from cxxnet_tpu.serve.batcher import MicroBatcher, ServeClosed
from cxxnet_tpu.serve.engine import (SERVE_TOL, PredictEngine,
                                     quantize_per_channel)
from cxxnet_tpu.serve.host import ModelHost, ServeModel, load_serve_model


def _serve_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("cxxnet-serve")]


# ------------------------------------------------------------ batcher units
# Fake runners keep these pure thread-protocol tests: no jax, no model.

def _echo_runner(calls):
    """Row-aligned identity that records each dispatched batch size."""
    def run(x):
        calls.append(x.shape[0])
        time.sleep(0.01)  # wide-enough dispatch for coalescing to bite
        return x * 2.0
    return run


def test_batcher_coalesces_concurrent_requests():
    calls = []
    b = MicroBatcher(_echo_runner(calls), max_batch=16, max_wait_ms=50.0)
    b.start()
    try:
        outs = [None] * 8

        def client(i):
            outs[i] = b.submit(np.full((1, 4), float(i), np.float32))

        ths = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        # every client got ITS rows back (row alignment through the
        # coalesced batch), and the 8 requests rode in < 8 dispatches
        for i in range(8):
            np.testing.assert_array_equal(outs[i],
                                          np.full((1, 4), 2.0 * i))
        assert b.n_requests == 8 and b.rows_served == 8
        assert b.n_batches < 8, calls
        assert sum(calls) == 8
    finally:
        b.close()


def test_batcher_timeout_flushes_partial_batch():
    """A lone request must be served after ~max_wait_ms, not held until
    max_batch fills."""
    calls = []
    b = MicroBatcher(_echo_runner(calls), max_batch=64, max_wait_ms=5.0)
    b.start()
    try:
        t0 = time.perf_counter()
        out = b.submit(np.ones((1, 3), np.float32))
        took = time.perf_counter() - t0
        np.testing.assert_array_equal(out, 2 * np.ones((1, 3)))
        assert calls == [1]
        assert took < 2.0, f"timeout flush took {took:.3f}s"
    finally:
        b.close()


def test_batcher_respects_max_batch():
    calls = []
    b = MicroBatcher(_echo_runner(calls), max_batch=4, max_wait_ms=100.0)
    b.start()
    try:
        ths = [threading.Thread(
            target=lambda: b.submit(np.zeros((1, 2), np.float32)))
            for _ in range(12)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert max(calls) <= 4
        assert sum(calls) == 12
    finally:
        b.close()


def test_batcher_multirow_requests_split_correctly():
    calls = []
    b = MicroBatcher(_echo_runner(calls), max_batch=32, max_wait_ms=30.0)
    b.start()
    try:
        outs = {}

        def client(i, n):
            outs[i] = b.submit(np.full((n, 2), float(i), np.float32))

        ths = [threading.Thread(target=client, args=(i, n))
               for i, n in enumerate((1, 3, 2))]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        for i, n in enumerate((1, 3, 2)):
            assert outs[i].shape == (n, 2)
            np.testing.assert_array_equal(outs[i], np.full((n, 2), 2.0 * i))
    finally:
        b.close()


def test_batcher_runner_exception_reaches_all_clients():
    """A runner failure must fan out to every rider of the batch AND
    everything queued behind it, then latch the batcher dead — the
    DevicePrefetcher ProducerError contract: clients get the exception,
    never a hang."""
    def boom(x):
        time.sleep(0.005)
        raise RuntimeError("device on fire")

    b = MicroBatcher(boom, max_batch=4, max_wait_ms=5.0, queue_depth=64)
    b.start()
    errs = []

    def client():
        try:
            b.submit(np.zeros((1, 2), np.float32))
        except RuntimeError as e:
            errs.append(str(e))

    ths = [threading.Thread(target=client) for _ in range(6)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=10.0)
    assert not any(t.is_alive() for t in ths), "a client hung"
    assert errs == ["device on fire"] * 6
    # latched: later submits fail fast with the same error
    with pytest.raises(RuntimeError, match="device on fire"):
        b.submit(np.zeros((1, 2), np.float32))
    b.close()
    assert not _serve_threads()


def test_batcher_close_thread_hygiene():
    b = MicroBatcher(_echo_runner([]), max_batch=4, max_wait_ms=1.0,
                     name="hygiene")
    b.start()
    assert any(t.name == "cxxnet-serve-batcher-hygiene"
               for t in threading.enumerate())
    b.submit(np.zeros((1, 2), np.float32))
    b.close()
    assert not any(t.name == "cxxnet-serve-batcher-hygiene"
                   for t in threading.enumerate())
    with pytest.raises(ServeClosed):
        b.submit(np.zeros((1, 2), np.float32))
    b.close()  # idempotent


def test_batcher_stats_accounting():
    b = MicroBatcher(_echo_runner([]), max_batch=8, max_wait_ms=1.0)
    b.start()
    try:
        for _ in range(3):
            b.submit(np.zeros((2, 2), np.float32))
        s = b.stats()
        assert s["requests"] == 3 and s["rows"] == 6
        assert sum(int(k) * v for k, v in s["batch_hist"].items()) == 6
        assert s["queue_depth_max"] >= 0
    finally:
        b.close()


def test_batcher_depth_accounting_sees_bursts():
    """Queue depth is sampled at submit() too (ISSUE 11 satellite): a
    burst that arrives and fully drains between two dispatches used to
    be invisible — the dispatcher's only sample runs AFTER it drained
    the queue into the open batch, so depth_max read 0."""
    gate = threading.Event()
    entered = threading.Event()

    def runner(x):
        entered.set()
        gate.wait(5.0)
        return x

    b = MicroBatcher(runner, max_batch=32, max_wait_ms=1.0,
                     queue_depth=64)
    b.start()
    outs = []

    def client():
        outs.append(b.submit(np.zeros((1, 2), np.float32)))

    ths = [threading.Thread(target=client)]
    ths[0].start()
    assert entered.wait(5.0)  # dispatcher stuck inside the runner
    # burst: five more requests pile up while no dispatch samples run
    for k in range(5):
        th = threading.Thread(target=client)
        th.start()
        ths.append(th)
        deadline = time.perf_counter() + 5.0
        while b._q.qsize() < k + 1 and time.perf_counter() < deadline:
            time.sleep(0.001)
    deadline = time.perf_counter() + 5.0
    while b.depth_max < 5 and time.perf_counter() < deadline:
        time.sleep(0.001)
    depth_seen = b.depth_max
    gate.set()
    for th in ths:
        th.join(timeout=10.0)
    b.close()
    assert len(outs) == 6
    # the whole burst drained in the dispatch AFTER the stuck one, so
    # dispatch-time sampling alone would have recorded depth_max = 0
    assert depth_seen >= 5, depth_seen
    s = b.stats()
    assert s["queue_depth_max"] >= 5
    assert 0 < s["queue_depth_mean"] <= s["queue_depth_max"]
    # mean is over ALL samples (arrivals + dispatches), kept consistent
    assert b.depth_samples >= b.n_requests + b.n_batches


def test_batcher_latency_histogram():
    reg = MetricsRegistry()
    b = MicroBatcher(_echo_runner([]), max_batch=4, max_wait_ms=1.0,
                     metrics=reg)
    b.start()
    try:
        for _ in range(4):
            b.submit(np.zeros((1, 2), np.float32))
    finally:
        b.close()
    h = reg.histograms["serve_latency_sec"]
    assert h.count == 4
    s = h.summary()
    assert 0 < s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
    assert "serve_queue_depth" in reg.gauges


# ----------------------------------------------------------- engine + model

MLP_NET = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 24
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 5
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,16
eta = 0.1
"""

IN_SHAPE = (1, 1, 16)


def _trainer(net=MLP_NET, batch=8):
    from __graft_entry__ import _make_trainer
    return _make_trainer(net, batch, "cpu")


@pytest.fixture(scope="module")
def mlp_trainer():
    return _trainer()


@pytest.fixture(scope="module")
def mlp_engine(mlp_trainer):
    eng = PredictEngine(mlp_trainer, shapes=(1, 4, 8), dtype="f32")
    eng.warmup()
    return eng


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, *IN_SHAPE) \
        .astype(np.float32)


def _databatch(x):
    return DataBatch(data=x,
                     label=np.zeros((x.shape[0], 1), np.float32),
                     index=np.arange(x.shape[0], dtype=np.uint32))


def test_bucket_for_mapping(mlp_engine):
    assert [mlp_engine.bucket_for(n) for n in (1, 2, 4, 5, 8, 99)] \
        == [1, 4, 4, 8, 8, 8]


def test_engine_pads_and_unpads(mlp_engine):
    """n=3 pads up to the 4-bucket but returns exactly 3 rows; an
    oversize request splits across max-bucket dispatches."""
    out = mlp_engine.predict(_rows(3))
    assert out.shape == (3, 5)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-5)
    big = mlp_engine.predict(_rows(19))
    assert big.shape == (19, 5)
    assert mlp_engine.retraces == 0


def test_engine_zero_retrace_after_warmup(mlp_engine, mlp_trainer):
    before = mlp_trainer.metrics.counters.get("serve_step_traces", 0)
    for n in (1, 2, 3, 4, 5, 8, 11, 20):
        mlp_engine.predict(_rows(n, seed=n))
    assert mlp_trainer.metrics.counters["serve_step_traces"] == before
    assert mlp_engine.retraces == 0


def test_engine_batched_vs_single_bitwise_f32(mlp_engine):
    """THE dynamic-batching safety property: a row served alone (padded
    1-bucket) and the same row inside a full batch produce identical
    bytes — eval-mode forward is row-independent."""
    x = _rows(8, seed=3)
    batched = mlp_engine.predict(x)
    for i in range(8):
        single = mlp_engine.predict(x[i:i + 1])
        np.testing.assert_array_equal(single[0], batched[i])


def test_engine_input_shape_rejected(mlp_engine):
    with pytest.raises(ValueError, match="predict"):
        mlp_engine.predict(np.zeros((2, 1, 1, 7), np.float32))


def test_engine_bad_dtype_rejected(mlp_trainer):
    with pytest.raises(ValueError, match="serve_dtype"):
        PredictEngine(mlp_trainer, dtype="fp8")


@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_quantized_variants_inside_envelope(mlp_trainer, dtype):
    eng = PredictEngine(mlp_trainer, shapes=(4,), dtype=dtype)
    eng.warmup()
    err = eng.pairtest(_rows(4, seed=7))
    assert err <= SERVE_TOL[dtype], \
        f"{dtype}: rel err {err} > envelope {SERVE_TOL[dtype]}"
    assert err > 0.0  # the variant really does transform the weights
    assert eng.retraces == 0


def test_quantize_per_channel_roundtrip():
    w = np.random.RandomState(0).randn(6, 9).astype(np.float32)
    w[2] = 0.0  # dead channel: scale 0, no div-by-zero
    q, s = quantize_per_channel(w)
    assert q.dtype == np.int8 and np.abs(q).max() <= 127
    assert s.shape == (6, 1)
    assert s[2] == 0.0 and not q[2].any()
    # per-channel absmax quantization: error bounded by scale/2 per entry
    np.testing.assert_allclose(q * s, w, atol=float(s.max()) / 2 + 1e-7)
    # conv-layout weights keep dim 0 as the channel
    wc = np.random.RandomState(1).randn(4, 2, 3, 3).astype(np.float32)
    qc, sc = quantize_per_channel(wc)
    assert sc.shape == (4, 1, 1, 1)
    np.testing.assert_allclose(qc * sc, wc, atol=float(sc.max()) / 2 + 1e-7)


def test_serve_model_concurrent_parity(mlp_trainer):
    """Concurrent clients through the full ServeModel stack (batcher ->
    engine): every client's answer equals the engine's single-shot
    prediction for its row, zero retraces, clean shutdown."""
    sm = ServeModel(mlp_trainer, ServeConfig(shapes=(1, 4, 8),
                                             max_wait_ms=5.0),
                    name="parity")
    sm.warmup()
    try:
        x = _rows(16, seed=11)
        want = sm.engine.predict(x)
        got = [None] * 16

        def client(i):
            got[i] = sm.predict(x[i:i + 1])

        ths = [threading.Thread(target=client, args=(i,))
               for i in range(16)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=30.0)
        assert not any(t.is_alive() for t in ths)
        for i in range(16):
            np.testing.assert_array_equal(got[i][0], want[i])
        assert sm.retraces == 0
        assert sm.batcher.n_requests == 16
    finally:
        sm.close()
    assert not any(t.name == "cxxnet-serve-batcher-parity"
                   for t in threading.enumerate())


# ------------------------------------------------------------- multi-model

def test_model_host_routes_by_name():
    t_a = _trainer()
    t_b = _trainer(MLP_NET.replace("nhidden = 5", "nhidden = 3"))
    host = ModelHost()
    try:
        host.add("alpha", t_a, ServeConfig(shapes=(1, 4)))
        host.add("beta", t_b, ServeConfig(shapes=(1, 4)))
        assert host.names == ["alpha", "beta"]
        x = _rows(2, seed=5)
        # routing is observable: the two nets have different widths
        assert host.predict("alpha", x).shape == (2, 5)
        assert host.predict("beta", x).shape == (2, 3)
        np.testing.assert_array_equal(host.predict("alpha", x),
                                      host.model("alpha").engine.predict(x))
        with pytest.raises(KeyError, match="gamma"):
            host.predict("gamma", x)
        with pytest.raises(ValueError, match="already hosted"):
            host.add("alpha", t_a)
        assert host.retraces() == 0
    finally:
        host.close()
    assert not _serve_threads()
    assert host.names == []


def test_load_serve_model_from_snapshot(tmp_path):
    """The CLI/wrapper-shared loader: net structure + weights restored
    from the snapshot, serve_* pairs configure the front."""
    t = _trainer()
    snap = str(tmp_path / "0001.model")
    t.save_model(snap)
    sm = load_serve_model(
        [("dev", "cpu"), ("batch_size", "8"), ("model_in", snap),
         ("serve_shapes", "1,4"), ("serve_dtype", "f32")], name="reloaded")
    try:
        x = _rows(4, seed=2)
        np.testing.assert_array_equal(sm.predict(x),
                                      t.predict_raw(_databatch(x)))
    finally:
        sm.close()
    with pytest.raises(ValueError, match="model_in"):
        load_serve_model([("dev", "cpu"), ("batch_size", "8")])


# ------------------------------------------------------------- ServeConfig

def test_serve_config_defaults_and_pairs():
    cfg = ServeConfig()
    assert cfg.shapes == (1, 8, 32)
    assert cfg.max_batch == 32  # 0 -> the largest bucket
    cfg = ServeConfig.from_pairs([
        ("serve_shapes", "1,8"), ("serve_dtype", "bf16"),
        ("serve_max_wait_ms", "3.5"), ("serve_clients", "2"),
        ("serve_shapes", "2,16"),  # last occurrence wins
        ("unrelated", "x")])
    assert cfg.shapes == (2, 16) and cfg.dtype == "bf16"
    assert cfg.max_wait_ms == 3.5 and cfg.max_batch == 16


def test_parse_shapes_rejects_malformed():
    assert parse_shapes("1,8,32") == [1, 8, 32]
    for bad in ("8,1", "1,1,8", "0,8", "-1", "a,b", ""):
        assert shapes_check(bad) is not None, bad
        with pytest.raises(ValueError, match="serve_shapes"):
            parse_shapes(bad)
    with pytest.raises(ValueError, match="serve_dtype"):
        ServeConfig(dtype="fp8")


# -------------------------------------------------------------- lint rules

def _lint(cfg_text):
    from cxxnet_tpu.analysis import conflint
    from cxxnet_tpu.utils.config import parse_config_string
    return conflint.lint_pairs(parse_config_string(cfg_text))


def _findings_for(findings, key, severity=None):
    return [f for f in findings if f.key == key
            and (severity is None or f.severity == severity)]


def test_lint_serve_keys_warn_off_task():
    fs = _lint("task = train\nserve_shapes = 1,8\n")
    assert _findings_for(fs, "serve_shapes", "warn")


def test_lint_int8_without_calib_warns():
    base = ("task = serve\nmodel_in = m.model\npred = out.txt\n"
            "iter = mnist\niter = end\nbatch_size = 8\n")
    fs = _lint(base + "serve_dtype = int8\n")
    assert _findings_for(fs, "serve_dtype", "warn")
    fs = _lint(base + "serve_dtype = int8\nserve_calib = 2\n")
    assert not _findings_for(fs, "serve_dtype", "warn")


def test_lint_max_batch_above_bucket_warns():
    fs = _lint("task = serve\nmodel_in = m.model\npred = out.txt\n"
               "iter = mnist\niter = end\nbatch_size = 8\n"
               "serve_shapes = 1,8\nserve_max_batch = 64\n")
    assert _findings_for(fs, "serve_max_batch", "warn")


def test_lint_serve_requires_snapshot_and_pred():
    fs = _lint("task = serve\n")
    assert _findings_for(fs, "model_in", "error")
    assert _findings_for(fs, "pred", "error")


def test_lint_malformed_shapes_is_error():
    fs = _lint("task = serve\nmodel_in = m.model\npred = out.txt\n"
               "iter = mnist\niter = end\nserve_shapes = 8,1\n")
    assert _findings_for(fs, "serve_shapes", "error")


def _lm_serve_conf(extra="", causal=1, packed=False, seq=32):
    from cxxnet_tpu.models import transformer
    net = transformer(vocab=64, seq=seq, dim=32, nlayer=1, nhead=2,
                      causal=causal, packed=packed)
    return ("task = serve\nmodel_in = m.model\npred = out.txt\n"
            "iter = text\n  path_tok = c_%d.tok\n  tok_count = 1\n"
            f"iter = packseq\n  seqlen = {seq}\niter = end\n"
            f"{net}batch_size = 4\nserve_gen = 1\n{extra}")


def test_lint_decode_keys_warn_off_task():
    fs = _lint("task = train\nserve_gen = 1\ndecode_slots = 4\n")
    assert _findings_for(fs, "serve_gen", "warn")


def test_lint_decode_detail_keys_warn_without_gen():
    fs = _lint("task = serve\nmodel_in = m.model\npred = out.txt\n"
               "iter = mnist\niter = end\ndecode_slots = 4\n")
    assert _findings_for(fs, "decode_slots", "warn")


def test_lint_serve_gen_needs_lm_netconfig():
    """serve_gen over an MLP netconfig: incremental decode only speaks
    token-id transformers — error, not a runtime surprise."""
    fs = _lint("task = serve\nmodel_in = m.model\npred = out.txt\n"
               "iter = mnist\niter = end\n" + MLP_NET
               + "batch_size = 8\nserve_gen = 1\n")
    assert _findings_for(fs, "serve_gen", "error")


def test_lint_serve_gen_needs_causal_attention():
    fs = _lint(_lm_serve_conf(causal=0))
    assert _findings_for(fs, "causal", "error")
    assert not _findings_for(_lint(_lm_serve_conf()), "causal")


def test_lint_decode_max_seqlen_mismatches_are_errors():
    """The prefill executable runs the net at its declared width and
    prompts arrive at the packer's seqlen: both mismatches error."""
    fs = _lint(_lm_serve_conf("decode_max_seqlen = 64\n"))
    assert len(_findings_for(fs, "decode_max_seqlen", "error")) == 2
    assert not _findings_for(
        _lint(_lm_serve_conf("decode_max_seqlen = 32\n")),
        "decode_max_seqlen")


def test_lint_decode_kv_cache_over_hbm_is_error():
    """The analytic KV-cache bytes (the live engine's footprint()
    number) against the selected chip's HBM — the task=check memory
    pre-flight, without tracing anything."""
    fs = _lint(_lm_serve_conf("mem_chip = v5e\n"
                              "decode_slots = 4000000\n"))
    [f] = _findings_for(fs, "decode_slots", "error")
    assert "HBM" in f.message
    assert not _findings_for(
        _lint(_lm_serve_conf("mem_chip = v5e\ndecode_slots = 4\n")),
        "decode_slots")


def test_lint_sampling_knob_consistency():
    fs = _lint(_lm_serve_conf("serve_gen_temp = 0.7\n"))
    assert _findings_for(fs, "serve_gen_temp", "warn")
    fs = _lint(_lm_serve_conf("serve_gen_sample = temperature\n"
                              "serve_gen_topk = 10\n"))
    assert _findings_for(fs, "serve_gen_topk", "warn")
    fs = _lint(_lm_serve_conf("serve_gen_sample = topk\n"))
    assert _findings_for(fs, "serve_gen_sample", "warn")
    assert not _findings_for(
        _lint(_lm_serve_conf("serve_gen_sample = topk\n"
                             "serve_gen_topk = 10\n")),
        "serve_gen_sample")


# ------------------------------------------------------------ wrapper path

def test_wrapper_enable_serving_parity():
    from cxxnet_tpu.wrapper import Net
    net = Net(dev="cpu", cfg=MLP_NET + "batch_size = 8\n")
    net.init_model()
    x = _rows(4, seed=9)
    legacy = net.predict(x)
    net.enable_serving("serve_shapes = 1,4\nserve_max_wait_ms = 1.0")
    try:
        with pytest.raises(RuntimeError, match="already enabled"):
            net.enable_serving()
        served = net.predict(x)
        np.testing.assert_array_equal(served, legacy)
    finally:
        net.disable_serving()
    assert not _serve_threads()
    np.testing.assert_array_equal(net.predict(x), legacy)


def test_wrapper_serving_host_multi_model(tmp_path):
    from cxxnet_tpu.wrapper.api import ServingHost
    t = _trainer()
    snap = str(tmp_path / "m.model")
    t.save_model(snap)
    host = ServingHost(dev="cpu")
    try:
        host.add_model("one", f"model_in = {snap}\nbatch_size = 8\n"
                              "serve_shapes = 1,4")
        host.add_model("two", f"model_in = {snap}\nbatch_size = 8\n"
                              "serve_shapes = 1,4\nserve_dtype = bf16")
        assert host.models == ["one", "two"]
        x = _rows(2, seed=4)
        np.testing.assert_array_equal(host.predict("one", x),
                                      t.predict_raw(_databatch(x)))
        # the bf16 co-hosted variant answers too, inside its envelope
        rel = np.abs(host.predict("two", x) - host.predict("one", x))
        assert float(rel.max()) <= SERVE_TOL["bf16"] * \
            (float(np.abs(host.predict("one", x)).max()) + 1e-6)
        assert host.retraces() == 0
    finally:
        host.close()
    assert not _serve_threads()


# ----------------------------------------------------- span tracing e2e

def test_serve_model_traced_span_chain(tmp_path):
    """ISSUE 11 acceptance, real engine: with trace_sample > 0 every
    request's stage durations (queue_wait + coalesce + dispatch +
    respond) sum to within 5% of its recorded end-to-end wall, the
    engine's pad/device/unpad decompose the dispatch, and the engine
    still never retraces."""
    import json

    t = _trainer()
    sink = str(tmp_path / "serve_spans.jsonl")
    t.metrics.configure_sink(f"jsonl:{sink}")
    t.metrics.configure_tracer(1)
    sm = ServeModel(t, ServeConfig(shapes=(1, 4), max_wait_ms=10.0),
                    name="traced")
    sm.warmup()
    try:
        outs = {}

        def client(i):
            outs[i] = sm.predict(_rows(1, seed=i))

        ths = [threading.Thread(target=client, args=(i,))
               for i in range(6)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        assert sm.retraces == 0
        # flip tracing off mid-flight (same model, same sink): the hot
        # path goes silent — zero NEW span records — while
        # batched-vs-single parity and zero-retrace stay intact (the
        # acceptance's off half, at zero extra compile cost)
        t.metrics.configure_tracer(0)
        n_spans_before = sum(1 for r in map(json.loads, open(sink))
                             if r["kind"] == "span")
        x = _rows(3, seed=11)
        got = sm.predict(x)
        alone = np.stack([sm.predict(x[i:i + 1])[0] for i in range(3)])
        np.testing.assert_array_equal(got, alone)
        assert sm.retraces == 0
        assert sum(1 for r in map(json.loads, open(sink))
                   if r["kind"] == "span") == n_spans_before
    finally:
        sm.close()
        t.metrics.close()
    spans = [r for r in map(json.loads, open(sink))
             if r["kind"] == "span"]
    per_req = {}
    for r in spans:
        if r.get("trace_id") is not None:
            per_req.setdefault(r["trace_id"], {})[r["span"]] = r
    dispatches = [r for r in spans if r["span"] == "dispatch"]
    assert len(per_req) == 6
    for tid, chain in per_req.items():
        assert set(chain) == {"queue_wait", "coalesce", "respond",
                              "request"}
        mine = [d for d in dispatches if tid in d["riders"]]
        assert len(mine) == 1
        total = chain["request"]["dur_us"]
        stages = (chain["queue_wait"]["dur_us"]
                  + chain["coalesce"]["dur_us"] + mine[0]["dur_us"]
                  + chain["respond"]["dur_us"])
        assert abs(stages - total) / total < 0.05, (tid, stages, total)
    # the engine decomposed each dispatch: pad/device/unpad nest inside
    # it (same riders, contained interval, summing to ~the dispatch)
    for d in dispatches:
        sub = [r for r in spans
               if r["span"] in ("pad", "device", "unpad")
               and r.get("riders") == d["riders"]
               and r["us"] >= d["us"]
               and r["us"] + r["dur_us"] <= d["us"] + d["dur_us"] + 1]
        assert {r["span"] for r in sub} == {"pad", "device", "unpad"}
        assert sum(r["dur_us"] for r in sub) <= d["dur_us"] + 3
    # warmup got its own span
    assert [r for r in spans if r["span"] == "serve_warmup"]


# ------------------------------------------------------------- CLI e2e

@pytest.fixture
def trained_model(tmp_path):
    from cxxnet_tpu.main import LearnTask
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import make_synth_mnist as sm
    rnd = np.random.RandomState(0)
    labels = rnd.randint(0, 4, 96)
    imgs = np.stack([
        np.clip(sm.class_pattern(l, 12, 12) * 255
                + rnd.rand(12, 12) * 32, 0, 255) for l in labels])
    sm.write_idx_images(str(tmp_path / "img.gz"), imgs)
    sm.write_idx_labels(str(tmp_path / "lbl.gz"), labels)
    net = MLP_NET.replace("input_shape = 1,1,16", "input_shape = 1,1,144")
    conf = tmp_path / "train.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{net}
batch_size = 16
num_round = 2
model_dir = {tmp_path}/models
save_model = 2
silent = 1
""")
    assert LearnTask().run([str(conf)]) == 0
    return tmp_path, net, str(tmp_path / "models" / "0002.model")


def _serve_conf(tmp_path, net, model, extra=""):
    conf = tmp_path / "serve.conf"
    conf.write_text(f"""
dev = cpu
task = serve
model_in = {model}
pred = {tmp_path}/serve_out.txt
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{net}
batch_size = 16
serve_shapes = 1,8
serve_clients = 4
silent = 1
metrics_sink = jsonl:{tmp_path}/serve_metrics.jsonl
{extra}
""")
    return conf


def test_cli_serve_end_to_end(trained_model):
    """task=serve under concurrent clients: output identical to
    task=pred, zero retraces, one latency record with percentiles plus
    the serve record with queue-depth gauges — the ISSUE 8 acceptance
    run, now traced (trace_sample + serve_sentinel ride the same run:
    the ISSUE 11 CLI acceptance, at zero extra test cost)."""
    import json

    from cxxnet_tpu.main import LearnTask
    tmp_path, net, model = trained_model
    conf = _serve_conf(
        tmp_path, net, model,
        extra="trace_sample = 4\nserve_sentinel = 1\n"
              "serve_sentinel_window = 0.05\n")
    assert LearnTask().run([str(conf)]) == 0
    out = np.loadtxt(tmp_path / "serve_out.txt")
    assert out.shape == (96,)

    pred_conf = tmp_path / "pred.conf"
    pred_conf.write_text(
        _serve_conf(tmp_path, net, model).read_text()
        .replace("task = serve", "task = pred")
        .replace("pred = " + str(tmp_path) + "/serve_out.txt",
                 "pred = " + str(tmp_path) + "/cls.txt")
        .replace("metrics_sink", "# metrics_sink"))
    assert LearnTask().run([str(pred_conf)]) == 0
    np.testing.assert_array_equal(out, np.loadtxt(tmp_path / "cls.txt"))

    recs = [json.loads(l)
            for l in open(tmp_path / "serve_metrics.jsonl")]
    lat = [r for r in recs if r["kind"] == "latency"]
    srv = [r for r in recs if r["kind"] == "serve"]
    assert len(lat) == 1 and len(srv) == 1
    assert lat[0]["op"] == "serve" and lat[0]["count"] == 96
    assert 0 < lat[0]["p50"] <= lat[0]["p95"] <= lat[0]["p99"]
    assert srv[0]["retraces"] == 0
    assert srv[0]["requests"] == 96
    assert srv[0]["rows"] == 96
    assert srv[0]["queue_depth_max"] >= srv[0]["queue_depth_mean"] >= 0
    assert sum(int(k) * v for k, v in srv[0]["batch_hist"].items()) == 96

    # --- ISSUE 11: the same run's span chains + sentinel windows ---
    spans = [r for r in recs if r["kind"] == "span"]
    per_req = {}
    for r in spans:
        if r.get("trace_id") is not None:
            per_req.setdefault(r["trace_id"], {})[r["span"]] = r
    assert len(per_req) == 24  # every 4th of 96 requests
    dispatches = [r for r in spans if r["span"] == "dispatch"]
    for tid, chain in per_req.items():
        assert set(chain) == {"queue_wait", "coalesce", "respond",
                              "request"}
        mine = [d for d in dispatches if tid in d["riders"]]
        assert len(mine) == 1
        total = chain["request"]["dur_us"]
        stages = (chain["queue_wait"]["dur_us"]
                  + chain["coalesce"]["dur_us"] + mine[0]["dur_us"]
                  + chain["respond"]["dur_us"])
        assert abs(stages - total) / total < 0.05
    wins = [r for r in recs if r["kind"] == "serve_window"]
    assert wins and all(w["model"] == "default" for w in wins)
    assert sum(w["requests"] for w in wins) == 96
    # the read side parses what the run wrote
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import obsv
    import spans2trace
    rep = obsv.build_report(recs)
    assert rep["serve_stages"]["requests"] == 24
    assert rep["serve_windows"]["windows"] == len(wins)
    trace = spans2trace.build_trace(spans)
    assert len([e for e in trace["traceEvents"] if e["ph"] == "s"]) == 24
    assert not _serve_threads()


def test_cli_serve_int8_with_calibration(trained_model):
    """serve_dtype=int8 + serve_calib: the startup pairtest measures the
    quantization error on real request batches and lands it in the
    serve record, inside the declared envelope."""
    import json

    from cxxnet_tpu.main import LearnTask
    tmp_path, net, model = trained_model
    conf = _serve_conf(tmp_path, net, model,
                       extra="serve_dtype = int8\nserve_calib = 2\n")
    assert LearnTask().run([str(conf)]) == 0
    recs = [json.loads(l)
            for l in open(tmp_path / "serve_metrics.jsonl")]
    srv = [r for r in recs if r["kind"] == "serve"][-1]
    assert srv["dtype"] == "int8"
    assert 0 < srv["quant_rel_err"] <= SERVE_TOL["int8"]
    assert srv["retraces"] == 0
    # int8 argmax predictions still agree with f32 on a trained net
    out = np.loadtxt(tmp_path / "serve_out.txt")
    assert out.shape == (96,)
