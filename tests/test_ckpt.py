"""Fault-tolerant checkpoints: atomic snapshots, exact resume, rollback.

The acceptance surface of doc/checkpoint.md:

* a kill at ANY byte of a checkpoint write leaves the previous snapshot
  loadable and the new one detectably partial (manifest-last protocol);
* ``continue = 1`` skips partial/corrupt snapshots and resumes from the
  newest valid one;
* a run killed mid-training and resumed reproduces the unkilled run's
  params / opt state / rng / iterator trajectory BITWISE at f32 on CPU;
* a snapshot saved on a ``data:2`` mesh restores onto 1 device (and
  vice versa) by resharding the host shards;
* ``rollback = N`` survives a NaN-poisoned batch: restore, reseed,
  retry, complete.
"""

import json
import os

import numpy as np
import pytest

import cxxnet_tpu.ckpt as ckptlib
import cxxnet_tpu.ckpt.writer as ckpt_writer
from cxxnet_tpu.ckpt.writer import AsyncCheckpointWriter
from cxxnet_tpu.io.data import IIterator
from cxxnet_tpu.main import LearnTask
from cxxnet_tpu.monitor import TrainingDiverged
from cxxnet_tpu.utils.config import parse_config_file, parse_keyval_args


# ------------------------------------------------------- snapshot format

def _shards(seed=0):
    rnd = np.random.RandomState(seed)
    return {"params": {"params/fc1/wmat": rnd.rand(4, 3).astype(np.float32),
                       "params/fc1/bias": rnd.rand(3).astype(np.float32)},
            "opt": {"opt/fc1/wmat/mom": np.zeros((4, 3), np.float32)}}


def _meta(round_=1):
    return {"net": {}, "epoch": round_, "has_opt_state": True,
            "dtypes": {}, "extra": {"round": round_}}


def test_snapshot_roundtrip(tmp_path):
    path = str(tmp_path / "0001.ckpt")
    stats = ckptlib.write_snapshot(path, _shards(), _meta())
    assert stats["shards"] == 2 and stats["bytes"] > 0
    manifest = ckptlib.validate_snapshot(path)
    assert manifest is not None and manifest["epoch"] == 1
    m2, arrays = ckptlib.load_snapshot(path)
    for shard, flat in _shards().items():
        for k, v in flat.items():
            np.testing.assert_array_equal(arrays[shard][k], v)
    assert not [n for n in os.listdir(path) if n.endswith(".tmp")]


def test_snapshot_corruption_detected(tmp_path):
    path = str(tmp_path / "0001.ckpt")
    ckptlib.write_snapshot(path, _shards(), _meta())
    # flip bytes in a shard: crc mismatch
    f = os.path.join(path, "params.npz")
    data = bytearray(open(f, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(f, "wb").write(bytes(data))
    assert ckptlib.validate_snapshot(path) is None
    with pytest.raises(ValueError):
        ckptlib.load_snapshot(path)
    # a torn manifest is also invalid
    path2 = str(tmp_path / "0002.ckpt")
    ckptlib.write_snapshot(path2, _shards(), _meta(2))
    mp = os.path.join(path2, ckptlib.MANIFEST)
    open(mp, "wb").write(open(mp, "rb").read()[:20])
    assert ckptlib.validate_snapshot(path2) is None


def test_kill_mid_write_preserves_previous(tmp_path):
    """A crash between the shard writes and the manifest commit leaves
    the previous snapshot valid and the new dir uncommitted."""
    prev = str(tmp_path / "0001.ckpt")
    ckptlib.write_snapshot(prev, _shards(1), _meta(1))

    class Kill(BaseException):
        pass

    def die_before_manifest(stage):
        if stage == "manifest":
            raise Kill()

    cur = str(tmp_path / "0002.ckpt")
    with pytest.raises(Kill):
        ckptlib.write_snapshot(cur, _shards(2), _meta(2),
                               fault_hook=die_before_manifest)
    assert ckptlib.validate_snapshot(prev) is not None
    assert ckptlib.validate_snapshot(cur) is None  # no manifest
    # a partial dir also never shadows the valid one in the scan
    cands = ckptlib.list_snapshots(str(tmp_path))
    assert [c for c, _ in cands] == [1, 2]


def test_rewrite_drops_manifest_first(tmp_path):
    """Overwriting a committed snapshot (rollback retry) must not leave
    a manifest pointing at mixed-age shards: the old manifest goes away
    before any shard is touched."""
    path = str(tmp_path / "0003.ckpt")
    ckptlib.write_snapshot(path, _shards(1), _meta(3))

    class Kill(BaseException):
        pass

    def die_after_first_shard(stage):
        if stage.startswith("shard:"):
            raise Kill()

    with pytest.raises(Kill):
        ckptlib.write_snapshot(path, _shards(2), _meta(3),
                               fault_hook=die_after_first_shard)
    assert ckptlib.validate_snapshot(path) is None


def test_prune_retention_and_debris(tmp_path):
    for i in range(1, 5):
        ckptlib.write_snapshot(str(tmp_path / f"{i:04d}.ckpt"),
                               _shards(i), _meta(i))
    # an uncommitted partial older than the newest commit (kill debris)
    os.makedirs(tmp_path / "0000.ckpt")
    removed = ckptlib.prune_snapshots(str(tmp_path), keep=2)
    assert removed == 3  # 0001, 0002, and the 0000 debris
    left = sorted(n for n in os.listdir(tmp_path) if n.endswith(".ckpt"))
    assert left == ["0003.ckpt", "0004.ckpt"]
    # legacy .model files are never pruned
    open(tmp_path / "0001.model", "wb").write(b"x")
    assert ckptlib.prune_snapshots(str(tmp_path), keep=1) == 1
    assert os.path.exists(tmp_path / "0001.model")


# ------------------------------------------------------------ async writer

def test_writer_commits_and_reports(tmp_path):
    done = []
    w = AsyncCheckpointWriter(on_done=done.append)
    w.submit(str(tmp_path / "0001.ckpt"), _shards(), _meta(),
             counter=1, keep=3)
    w.close()
    assert len(done) == 1
    st = done[0]
    assert st["counter"] == 1 and st["shards"] == 2
    assert st["write_sec"] >= 0 and st["pruned"] == 0
    assert ckptlib.validate_snapshot(str(tmp_path / "0001.ckpt"))


def test_writer_failure_latches_and_reraises(tmp_path):
    class Boom(RuntimeError):
        pass

    def explode(stage):
        raise Boom("disk on fire")

    old = ckpt_writer.FAULT_HOOK
    ckpt_writer.FAULT_HOOK = explode
    try:
        w = AsyncCheckpointWriter()
        w.submit(str(tmp_path / "0001.ckpt"), _shards(), _meta(),
                 counter=1, keep=3)
        with pytest.raises(Boom):
            w.drain()
        with pytest.raises(Boom):  # latched: every later call re-raises
            w.submit(str(tmp_path / "0002.ckpt"), _shards(), _meta(),
                     counter=2, keep=3)
        with pytest.raises(Boom):
            w.close()
    finally:
        ckpt_writer.FAULT_HOOK = old
    assert ckptlib.validate_snapshot(str(tmp_path / "0001.ckpt")) is None


def _traced_registry(path):
    from cxxnet_tpu.monitor.metrics import MetricsRegistry
    reg = MetricsRegistry()
    reg.configure_sink(f"jsonl:{path}")
    reg.configure_tracer(1)
    return reg


def _span_records(path):
    with open(path) as f:
        return [r for r in map(json.loads, f) if r.get("kind") == "span"]


def test_writer_spans_full_write(tmp_path):
    """A committed async snapshot leaves the full writer-thread span
    sequence: one ckpt_shard per shard, ckpt_manifest, ckpt_prune —
    all on the writer thread's track (doc/monitor.md span schema)."""
    sink = str(tmp_path / "m.jsonl")
    reg = _traced_registry(sink)
    w = AsyncCheckpointWriter(tracer=reg.tracer)
    w.submit(str(tmp_path / "0001.ckpt"), _shards(), _meta(),
             counter=1, keep=3)
    w.close()
    reg.close()
    spans = _span_records(sink)
    shards = [r for r in spans if r["span"] == "ckpt_shard"]
    assert sorted(r["shard"] for r in shards) == ["opt", "params"]
    assert all(r["tid"] == "cxxnet-ckpt-writer" for r in shards)
    assert [r["span"] for r in spans if r["span"] == "ckpt_manifest"]
    assert [r["span"] for r in spans if r["span"] == "ckpt_prune"]
    # writer-thread timeline is ordered: shards before the manifest
    manifest_us = next(r["us"] for r in spans
                       if r["span"] == "ckpt_manifest")
    assert all(r["us"] <= manifest_us for r in shards)


def test_writer_spans_ride_fault_hook(tmp_path):
    """The FAULT_HOOK crash test with tracing on: shards written before
    the simulated kill have spans, the never-written manifest does not
    — the span stream shows exactly how far the write got."""
    class Boom(RuntimeError):
        pass

    def die_before_manifest(stage):
        if stage == "manifest":
            raise Boom("killed before manifest")

    sink = str(tmp_path / "m.jsonl")
    reg = _traced_registry(sink)
    old = ckpt_writer.FAULT_HOOK
    ckpt_writer.FAULT_HOOK = die_before_manifest
    try:
        w = AsyncCheckpointWriter(tracer=reg.tracer)
        w.submit(str(tmp_path / "0001.ckpt"), _shards(), _meta(),
                 counter=1, keep=3)
        with pytest.raises(Boom):
            w.close()
    finally:
        ckpt_writer.FAULT_HOOK = old
    reg.close()
    spans = _span_records(sink)
    assert sorted(r["shard"] for r in spans
                  if r["span"] == "ckpt_shard") == ["opt", "params"]
    assert not [r for r in spans if r["span"] == "ckpt_manifest"]
    # and the snapshot is exactly as partial as the spans say
    assert ckptlib.validate_snapshot(str(tmp_path / "0001.ckpt")) is None


# ------------------------------------------------ legacy single-file path

def test_legacy_save_is_atomic(tmp_path, monkeypatch):
    """save_model through a crash mid-np.savez: the original file stays
    intact and no .tmp debris survives (the utils/serializer.py:80 fix)."""
    from cxxnet_tpu.utils import serializer
    path = str(tmp_path / "0001.model")
    serializer.save_model(path, net_structure={}, epoch=1,
                          params={"fc": {"wmat": np.ones(3, np.float32)}},
                          buffers={})
    header, params, _, _ = serializer.load_model(path)
    assert header["epoch"] == 1

    class Kill(BaseException):
        pass

    real_savez = np.savez

    def torn_savez(f, **arrays):
        f.write(b"PK\x03\x04 torn")
        raise Kill()

    monkeypatch.setattr(np, "savez", torn_savez)
    with pytest.raises(Kill):
        serializer.save_model(
            path, net_structure={}, epoch=2,
            params={"fc": {"wmat": np.zeros(3, np.float32)}}, buffers={})
    monkeypatch.setattr(np, "savez", real_savez)
    header, params, _, _ = serializer.load_model(path)  # old file intact
    assert header["epoch"] == 1
    np.testing.assert_array_equal(params["fc"]["wmat"], np.ones(3))
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


# ----------------------------------------------------- iterator state

def test_iterator_chain_state_roundtrip():
    from cxxnet_tpu.io.iter_proc import AugmentIterator
    from cxxnet_tpu.io.data import DataInst

    class _Base(IIterator):
        def __init__(self):
            self.i = 0

        def before_first(self):
            self.i = 0

        def next(self):
            if self.i >= 100:
                return None
            self.i += 1
            return DataInst(label=np.zeros(1, np.float32),
                            data=np.ones((1, 4, 4), np.float32),
                            index=self.i)

        def state(self):
            return {"i": self.i}

        def set_state(self, st):
            self.i = st["i"]

    it = AugmentIterator(_Base())
    it.set_param("rand_mirror", "1")
    it.init()
    it.before_first()
    for _ in range(7):
        it.next()
    st = it.state()
    # the augment rng is cross-epoch state: advancing past the capture
    # and then restoring must reproduce the SAME downstream draws
    a = [bool(it.rnd.rand() < 0.5) for _ in range(20)]
    it.set_state(st)
    assert it.base.i == 7
    b = [bool(it.rnd.rand() < 0.5) for _ in range(20)]
    assert a == b
    # json round-trip (the manifest carries it)
    st2 = json.loads(json.dumps(st))
    it.set_state(st2)
    c = [bool(it.rnd.rand() < 0.5) for _ in range(20)]
    assert a == c


def test_membuffer_resume_survives_producer_prepulls():
    """A threadbuffer stacked over a membuffer primes its producer at
    init() — BEFORE resume state can be applied — pulling batches
    through the unfilled cache and advancing the base's cross-epoch rng.
    set_state must drop those pulls and rewind to the recorded pre-fill
    state so the rebuilt cache is bitwise the original fill."""
    import time as _time
    from cxxnet_tpu.io.iter_proc import (DenseBufferIterator,
                                         ThreadBufferIterator)

    class _RngBase(IIterator):
        """Deterministic stream whose values come from a cross-epoch rng
        (the augment discipline, distilled)."""

        def __init__(self):
            self.i = 0
            self.rnd = np.random.RandomState(7)

        def before_first(self):
            self.i = 0

        def next(self):
            if self.i >= 4:
                return None
            self.i += 1
            # value couples the CURSOR and the rng draw (augment batch =
            # f(item, noise)): a rebuild whose cursor rewound but whose
            # rng kept advancing pairs the wrong noise with each item
            return (self.i * 10 + self.rnd.rand(3)).astype(np.float32)

        def state(self):
            name, keys, pos, g, c = self.rnd.get_state()
            return {"i": self.i,
                    "rnd": [name, np.asarray(keys).tolist(), int(pos),
                            int(g), float(c)]}

        def set_state(self, st):
            self.i = int(st["i"])
            name, keys, pos, g, c = st["rnd"]
            self.rnd.set_state((name, np.asarray(keys, np.uint32),
                                int(pos), int(g), float(c)))

    def _chain(max_buffer):
        # the two runs get DIFFERENT buffer depths: the producer primes
        # a different number of pre-pulls before resume state arrives,
        # as real thread timing would
        it = ThreadBufferIterator(DenseBufferIterator(_RngBase()),
                                  max_buffer=max_buffer)
        it.set_param("max_nbatch", "4")
        it.init()
        return it

    def _epoch(it):
        it.before_first()
        out = []
        while True:
            b = it.next()
            if b is None:
                return out
            out.append(b)

    a = _chain(2)
    _epoch(a)          # epoch 1: the fill
    st = json.loads(json.dumps(a.state()))  # round-boundary snapshot
    ca = _epoch(a)     # epoch 2: cache replay == the canonical data
    a.close()

    b = _chain(1)      # resume: init() primed the producer, which has
    _time.sleep(0.05)  # already pulled batches through the empty cache
    b.set_state(st)
    cb = _epoch(b)
    b.close()
    assert len(cb) == len(ca) == 4
    for x, y in zip(ca, cb):
        np.testing.assert_array_equal(x, y)


def test_imgbin_epoch_shuffle_state():
    """ImageBinIterator's per-epoch shuffle is seeded ``787 + seed_data
    + gen``: the epoch counter must survive resume or the restarted
    process replays epoch-1 order for every epoch."""
    from cxxnet_tpu.io.imbin import ImageBinIterator
    it = ImageBinIterator.__new__(ImageBinIterator)
    it._gen, it._thread, it._queue = 6, None, None
    st = json.loads(json.dumps(it.state()))
    it2 = ImageBinIterator.__new__(ImageBinIterator)
    it2._gen, it2._thread = 1, None  # a primed fresh process
    it2.set_state(st)
    assert it2._gen == 6  # next before_first seeds epoch 7, as unkilled


def test_image_iterator_shuffle_epoch_state():
    """ImageIterator mutates ``order`` in place with a fixed-seed
    shuffle each epoch; set_state replays k shuffles instead of storing
    the permutation."""
    from cxxnet_tpu.io.imbin import ImageIterator

    def fresh():
        it = ImageIterator()
        it.shuffle, it.seed_data = 1, 3
        it.items = list(range(10))
        it.order = np.arange(10)
        it._epochs = 0
        return it

    a = fresh()
    for _ in range(4):
        a.before_first()
    st = json.loads(json.dumps(a.state()))
    b = fresh()
    b.set_state(st)
    np.testing.assert_array_equal(a.order, b.order)
    a.before_first()
    b.before_first()  # and the NEXT epoch's order matches too
    np.testing.assert_array_equal(a.order, b.order)


def test_sentinel_state_roundtrip():
    from cxxnet_tpu.monitor.metrics import MetricsRegistry
    from cxxnet_tpu.monitor.sentinel import SentinelBank
    b1 = SentinelBank(MetricsRegistry(), rel=0.2, warmup=2, ring=8)
    for i, v in enumerate([100.0, 101.0, 99.0, 100.5]):
        b1.observe_step({"examples_per_sec": v, "step": i})
    st = json.loads(json.dumps(b1.state()))
    b2 = SentinelBank(MetricsRegistry(), rel=0.2, warmup=2, ring=8)
    b2.set_state(st)
    s1 = b1.sentinels["examples_per_sec"]
    s2 = b2.sentinels["examples_per_sec"]
    assert s2.seen == s1.seen
    assert abs(s2.ewma.mean - s1.ewma.mean) < 1e-9
    assert len(b2.ring) == len(b1.ring)


# --------------------------------------------------------- CLI end-to-end

MLP_DROPOUT_NET = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 32
layer[1->2] = relu
layer[2->2] = dropout
  threshold = 0.5
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
"""


def _write_synth_mnist(tmp_path, n=128, classes=4, side=12):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import make_synth_mnist as sm
    rnd = np.random.RandomState(0)
    labels = rnd.randint(0, classes, n)
    imgs = np.stack([
        np.clip(sm.class_pattern(l, side, side) * 255
                + rnd.rand(side, side) * 32, 0, 255)
        for l in labels])
    sm.write_idx_images(str(tmp_path / "img.gz"), imgs)
    sm.write_idx_labels(str(tmp_path / "lbl.gz"), labels)


def _write_conf(tmp_path, model_dir, extra=""):
    conf = tmp_path / f"{os.path.basename(model_dir)}.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 1
iter = end
{MLP_DROPOUT_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
momentum = 0.9
num_round = 6
model_dir = {model_dir}
save_model = 1
ckpt_async = 1
silent = 1
{extra}
""")
    return conf


def _make_task(conf, *args):
    task = LearnTask()
    for k, v in parse_config_file(str(conf)):
        task.set_param(k, v)
    for k, v in parse_keyval_args(list(args)):
        task.set_param(k, v)
    task._conf_path = str(conf)
    return task


def _run_task(task):
    try:
        task.init()
        task.task_train()
    finally:
        for it in ([task.itr_train] if task.itr_train else []) \
                + task.itr_evals:
            it.close()
        if task.net is not None:
            task.net.metrics.close()


def _snapshot_arrays(path):
    manifest, shards = ckptlib.load_snapshot(path)
    flat = {}
    for name, arrays in sorted(shards.items()):
        for k, v in arrays.items():
            flat[f"{name}:{k}"] = v
    return manifest, flat


class _KillAtBatch(IIterator):
    """Raises mid-round after ``at`` batches — the process-kill stand-in
    (everything after the last committed snapshot is lost either way).
    Transparent to the resume contract: it simulates a dead process, not
    a pipeline stage, so state() must be the BASE's state verbatim (the
    resumed run rebuilds the chain without the wrapper; the default
    IIterator.state would nest it under "base" and corrupt the capture)."""

    class Killed(Exception):
        pass

    def __init__(self, base, at):
        self.base = base
        self.at = at
        self.count = 0

    def before_first(self):
        self.base.before_first()

    def next(self):
        if self.count >= self.at:
            raise self.Killed(f"injected kill at batch {self.count}")
        b = self.base.next()
        if b is not None:
            self.count += 1
        return b

    def state(self):
        return self.base.state()

    def set_state(self, st):
        self.base.set_state(st)


@pytest.mark.slow
def test_kill_resume_trajectory_bitwise(tmp_path):
    """The tentpole acceptance: train 6 rounds (run A); train the same
    config killed MID-ROUND-5 and resume with continue=1 (run B).  The
    final snapshots must agree bitwise — params, opt state, buffers,
    rng stream, sample counter — at f32 on CPU.  Dropout makes the rng
    stream load-bearing; momentum makes the opt state load-bearing;
    the per-round snapshots exercise the async writer + retention."""
    _write_synth_mnist(tmp_path)
    conf_a = _write_conf(tmp_path, str(tmp_path / "A"))
    _run_task(_make_task(conf_a))
    # run B: identical, killed during round 5 (after snapshot 0004)
    conf_b = _write_conf(tmp_path, str(tmp_path / "B"))
    task_b = _make_task(conf_b)
    task_b.init()
    task_b.itr_train = _KillAtBatch(task_b.itr_train, at=4 * 8 + 3)
    with pytest.raises(_KillAtBatch.Killed):
        try:
            task_b.task_train()
        finally:
            task_b.net.metrics.close()
    assert ckptlib.validate_snapshot(str(tmp_path / "B" / "0004.ckpt"))
    # resume: a FRESH process image (new LearnTask) continues to 6
    _run_task(_make_task(conf_b, "continue=1"))

    ma, fa = _snapshot_arrays(str(tmp_path / "A" / "0006.ckpt"))
    mb, fb = _snapshot_arrays(str(tmp_path / "B" / "0006.ckpt"))
    assert fa.keys() == fb.keys()
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)
    tsa = ma["extra"]["train_state"]
    tsb = mb["extra"]["train_state"]
    assert tsa["sample_counter"] == tsb["sample_counter"] == 48
    assert tsa["rng_key"] == tsb["rng_key"]
    assert ma["extra"]["iter_state"] == mb["extra"]["iter_state"]
    # retention: ckpt_keep=3 pruned the early snapshots in both runs
    for d in ("A", "B"):
        kept = sorted(n for n in os.listdir(tmp_path / d)
                      if n.endswith(".ckpt"))
        assert kept == ["0004.ckpt", "0005.ckpt", "0006.ckpt"]


def test_continue_skips_partial_snapshot(tmp_path):
    """continue=1 with a corrupted NEWEST snapshot resumes from the
    previous one (the scan skips, warns, and the next save overwrites
    the debris)."""
    _write_synth_mnist(tmp_path)
    conf = _write_conf(tmp_path, str(tmp_path / "C"), extra="num_round = 3")
    _run_task(_make_task(conf))
    # corrupt the newest (0003) the way a kill does: no manifest
    os.remove(tmp_path / "C" / "0003.ckpt" / ckptlib.MANIFEST)
    task = _make_task(conf, "continue=1", "num_round=4")
    task.init()
    assert task.start_counter == 3  # resumed from 0002, not the debris
    try:
        task.task_train()
    finally:
        task.net.metrics.close()
        for it in [task.itr_train] + task.itr_evals:
            it.close()
    assert ckptlib.validate_snapshot(str(tmp_path / "C" / "0004.ckpt"))
    # the debris round was re-saved and committed on the way through
    assert ckptlib.validate_snapshot(str(tmp_path / "C" / "0003.ckpt"))


def test_continue_skips_nonfinite_snapshot(tmp_path):
    """A rollback that walked past a NaN-poisoned snapshot leaves it on
    disk (crc-valid, loadable): a later continue=1 must apply the same
    finite-params gate and resume from the older good one."""
    _write_synth_mnist(tmp_path)
    conf = _write_conf(tmp_path, str(tmp_path / "P"), extra="num_round = 3")
    _run_task(_make_task(conf))
    # poison the NEWEST snapshot the way a diverged-then-saved round
    # does: params all-NaN, manifest recommitted (checksums valid)
    path = str(tmp_path / "P" / "0003.ckpt")
    manifest, shards = ckptlib.load_snapshot(path)
    for k in shards["params"]:
        shards["params"][k] = np.full_like(shards["params"][k], np.nan)
    meta = {k: manifest[k] for k in
            ("net", "epoch", "has_opt_state", "dtypes", "extra")}
    ckptlib.write_snapshot(path, shards, meta)
    assert ckptlib.validate_snapshot(path) is not None  # loadable...
    task = _make_task(conf, "continue=1")
    task.init()
    try:
        assert task.start_counter == 3  # ...but resumed from 0002
        assert all(np.isfinite(np.asarray(leaf)).all()
                   for leaf in __import__("jax").tree.leaves(
                       task.net.params))
    finally:
        task.net.metrics.close()
        for it in [task.itr_train] + task.itr_evals:
            it.close()


def test_writer_fault_fails_the_run_then_resume(tmp_path):
    """A writer failure latches and re-raises IN the train loop (never a
    silent no-more-snapshots run); after the fault clears, continue=1
    resumes from the last committed snapshot, skipping the partial."""
    _write_synth_mnist(tmp_path)
    conf = _write_conf(tmp_path, str(tmp_path / "F"))

    class Boom(RuntimeError):
        pass

    manifests = [0]

    def die_on_third_manifest(stage):
        if stage == "manifest":
            manifests[0] += 1
            if manifests[0] == 3:  # 0000, 0001 commit; 0002 dies
                raise Boom("injected writer fault")

    old = ckpt_writer.FAULT_HOOK
    ckpt_writer.FAULT_HOOK = die_on_third_manifest
    try:
        with pytest.raises(Boom):
            _run_task(_make_task(conf))
    finally:
        ckpt_writer.FAULT_HOOK = old
    assert ckptlib.validate_snapshot(str(tmp_path / "F" / "0001.ckpt"))
    assert ckptlib.validate_snapshot(
        str(tmp_path / "F" / "0002.ckpt")) is None
    task = _make_task(conf, "continue=1", "num_round=3")
    task.init()
    assert task.start_counter == 2
    try:
        task.task_train()
    finally:
        task.net.metrics.close()
        for it in [task.itr_train] + task.itr_evals:
            it.close()
    assert ckptlib.validate_snapshot(str(tmp_path / "F" / "0003.ckpt"))


def test_reshard_restore_data2_to_1_and_back(tmp_path):
    """A snapshot saved on a data:2 mesh restores onto 1 device (and
    vice versa): the host shards are logical arrays, load_model reshards
    through the current NamedShardings.  The restore itself is bitwise;
    training then proceeds on the new mesh."""
    import jax
    _write_synth_mnist(tmp_path)
    conf2 = _write_conf(tmp_path, str(tmp_path / "M2"),
                        extra="num_round = 2")
    _run_task(_make_task(conf2, "dev=cpu:0-1"))
    _, saved = _snapshot_arrays(str(tmp_path / "M2" / "0002.ckpt"))
    # restore onto ONE device and keep training
    task = _make_task(conf2, "continue=1", "dev=cpu", "num_round=3")
    task.init()
    assert task.net.mesh.devices.size == 1
    for k, v in saved.items():
        if not k.startswith("params:params/"):
            continue
        parts = k.split("/")[1:]
        leaf = task.net.params
        for p in parts:
            leaf = leaf[p]
        np.testing.assert_array_equal(np.asarray(leaf), v, err_msg=k)
    try:
        task.task_train()
    finally:
        task.net.metrics.close()
        for it in [task.itr_train] + task.itr_evals:
            it.close()
    assert ckptlib.validate_snapshot(str(tmp_path / "M2" / "0003.ckpt"))
    # and the other direction: 1-device save -> data:2 restore
    conf1 = _write_conf(tmp_path, str(tmp_path / "M1"),
                        extra="num_round = 2")
    _run_task(_make_task(conf1))
    task = _make_task(conf1, "continue=1", "dev=cpu:0-1", "num_round=3")
    task.init()
    assert task.net.mesh.devices.size == 2
    try:
        task.task_train()
    finally:
        task.net.metrics.close()
        for it in [task.itr_train] + task.itr_evals:
            it.close()
    assert ckptlib.validate_snapshot(str(tmp_path / "M1" / "0003.ckpt"))


class _PoisonOnce(IIterator):
    """NaN-poisons one batch, once — the divergence injection."""

    def __init__(self, base, at):
        self.base = base
        self.at = at
        self.count = 0
        self.fired = False

    def before_first(self):
        self.base.before_first()

    def next(self):
        b = self.base.next()
        if b is None:
            return None
        self.count += 1
        if not self.fired and self.count == self.at:
            self.fired = True
            import dataclasses
            b = dataclasses.replace(
                b, data=np.full_like(b.data, np.nan))
        return b


@pytest.mark.slow
def test_rollback_recovers_from_nan_poison(tmp_path):
    """monitor_nan=fatal raises TrainingDiverged on the poisoned batch;
    rollback=2 restores the last good snapshot, reseeds the rng, and the
    retried run (poison is one-shot) completes all rounds.  The sink
    carries the rollback record and the final snapshot is committed."""
    _write_synth_mnist(tmp_path)
    sink = tmp_path / "m.jsonl"
    conf = _write_conf(
        tmp_path, str(tmp_path / "R"),
        extra=f"""num_round = 5
monitor = 1
monitor_interval = 1
monitor_nan = fatal
rollback = 2
metrics_sink = jsonl:{sink}
""")
    task = _make_task(conf)
    task.init()
    task.itr_train = _PoisonOnce(task.itr_train, at=2 * 8 + 3)  # round 3
    try:
        task.task_train()
    finally:
        task.net.metrics.close()
        task.itr_train.close()
        for it in task.itr_evals:
            it.close()
    assert ckptlib.validate_snapshot(str(tmp_path / "R" / "0005.ckpt"))
    recs = [json.loads(l) for l in open(sink) if l.strip()]
    kinds = {}
    for r in recs:
        kinds.setdefault(r["kind"], []).append(r)
    assert len(kinds.get("rollback", [])) == 1
    rb = kinds["rollback"][0]
    assert rb["retry"] == 1 and rb["restored_round"] == 2
    assert "TrainingDiverged" in rb["reason"]
    assert kinds.get("nan"), "the nan record should precede the rollback"
    assert kinds.get("ckpt"), "ckpt records should be in the stream"
    # rollback exhaustion still re-raises: poison EVERY pass, rollback=1
    conf2 = _write_conf(
        tmp_path, str(tmp_path / "R2"),
        extra="""num_round = 4
monitor = 1
monitor_interval = 1
monitor_nan = fatal
rollback = 1
""")
    task2 = _make_task(conf2)
    task2.init()

    class _PoisonAlways(_PoisonOnce):
        def next(self):
            b = self.base.next()
            if b is None:
                return None
            self.count += 1
            if self.count % (2 * 8 + 3) == 0:
                import dataclasses
                b = dataclasses.replace(
                    b, data=np.full_like(b.data, np.nan))
            return b

    task2.itr_train = _PoisonAlways(task2.itr_train, at=0)
    with pytest.raises(TrainingDiverged):
        try:
            task2.task_train()
        finally:
            task2.net.metrics.close()
            task2.itr_train.close()
            for it in task2.itr_evals:
                it.close()


# ------------------------------------------------ text/LM iterator chain

def _write_lm_corpus(tmp_path, n_docs=120, vocab=32, mean_len=12):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from make_synth_text import gen_docs
    from cxxnet_tpu.io.text import write_token_shard
    docs = gen_docs(n_docs, vocab=vocab, mean_len=mean_len, seed=5)
    for s in range(2):
        write_token_shard(str(tmp_path / f"lm_{s}.tok"), docs[s::2])
    return sum(d.size for d in docs)


def _write_lm_conf(tmp_path, model_dir, extra=""):
    from cxxnet_tpu.models import transformer
    net = transformer(vocab=32, seq=16, dim=16, nlayer=1, nhead=2,
                      packed=True)
    conf = tmp_path / f"{os.path.basename(model_dir)}_lm.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = text
  path_tok = {tmp_path}/lm_%d.tok
  tok_count = 2
  shuffle = 1
iter = packseq
  seqlen = 16
iter = end
{net}
batch_size = 4
updater = adam
eta = 0.005
num_round = 6
model_dir = {model_dir}
save_model = 1
ckpt_async = 1
silent = 1
eval_train = 0
{extra}
""")
    return conf


def _lm_batches_in_rounds(tmp_path, n_rounds):
    """Deterministic batch count of the first ``n_rounds`` epochs of the
    text+packseq chain (the ragged carry makes per-epoch counts vary)."""
    from cxxnet_tpu.io.text import PackedSeqIterator, TextIterator
    it = TextIterator()
    it.set_param("path_tok", str(tmp_path / "lm_%d.tok"))
    it.set_param("tok_count", "2")
    it.set_param("shuffle", "1")
    it.set_param("silent", "1")
    p = PackedSeqIterator(it)
    p.set_param("seqlen", "16")
    p.set_param("batch_size", "4")
    p.init()
    n = 0
    for _ in range(n_rounds):
        p.before_first()
        while p.next() is not None:
            n += 1
    return n


@pytest.mark.slow
def test_text_kill_resume_trajectory_bitwise(tmp_path):
    """Kill-resume through TextIterator + PackedSeqIterator: the kill
    lands MID-EPOCH with the packer's ragged buffer non-empty at every
    round boundary, so the resumed run must restore the buffered
    token/uid/position stream bitwise — final snapshots (params, opt,
    rng, iterator chain incl. the ragged buffer) must agree with the
    unkilled run's."""
    _write_lm_corpus(tmp_path)
    conf_a = _write_lm_conf(tmp_path, str(tmp_path / "LA"))
    _run_task(_make_task(conf_a))
    # the pack buffer must actually be ragged at the boundary, or this
    # test wouldn't exercise the carry
    ma, _ = _snapshot_arrays(str(tmp_path / "LA" / "0006.ckpt"))
    pack_state = ma["extra"]["iter_state"]
    assert len(pack_state["tok"]) > 0, "corpus must leave a ragged carry"
    assert pack_state["base"]["gen"] == 6

    conf_b = _write_lm_conf(tmp_path, str(tmp_path / "LB"))
    task_b = _make_task(conf_b)
    task_b.init()
    kill_at = _lm_batches_in_rounds(tmp_path, 4) + 3  # mid round 5
    task_b.itr_train = _KillAtBatch(task_b.itr_train, at=kill_at)
    with pytest.raises(_KillAtBatch.Killed):
        try:
            task_b.task_train()
        finally:
            task_b.net.metrics.close()
    assert ckptlib.validate_snapshot(str(tmp_path / "LB" / "0004.ckpt"))
    _run_task(_make_task(conf_b, "continue=1"))

    mb, fb = _snapshot_arrays(str(tmp_path / "LB" / "0006.ckpt"))
    _, fa = _snapshot_arrays(str(tmp_path / "LA" / "0006.ckpt"))
    assert fa.keys() == fb.keys()
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)
    assert ma["extra"]["iter_state"] == mb["extra"]["iter_state"]
    tsa, tsb = ma["extra"]["train_state"], mb["extra"]["train_state"]
    assert tsa["sample_counter"] == tsb["sample_counter"]
    assert tsa["rng_key"] == tsb["rng_key"]


def test_text_stateless_stage_cold_resume_warns_once(tmp_path, capsys,
                                                     monkeypatch):
    """A text stage without resume support (the native C++ iterator
    discipline: state() raises) must warn ONCE and snapshot without
    iterator state — cold resume, never a crash or a silent {}."""
    from cxxnet_tpu.io.text import TextIterator
    _write_lm_corpus(tmp_path, n_docs=30)
    conf = _write_lm_conf(tmp_path, str(tmp_path / "LC"))
    task = _make_task(conf)
    task.init()

    def raising_state(self):
        raise NotImplementedError(
            "stateless text stage resumes cold")

    monkeypatch.setattr(TextIterator, "state", raising_state)
    try:
        extra = task._ckpt_extra_state()
        assert "iter_state" not in extra
        extra2 = task._ckpt_extra_state()
        assert "iter_state" not in extra2
    finally:
        task.net.metrics.close()
        for it in [task.itr_train] + task.itr_evals:
            it.close()
    err = capsys.readouterr().err
    assert err.count("iterator state capture failed") == 1


# --------------------------------------------------------- lint rules

def test_ckpt_lint_rules():
    from cxxnet_tpu.analysis.conflint import lint_pairs

    def msgs(pairs, sev=None):
        return [f for f in lint_pairs(pairs)
                if f.key in ("rollback", "ckpt_keep", "ckpt_async",
                             "save_opt", "ckpt_iter_state")
                and (sev is None or f.severity == sev)]

    # rollback without the fatal NaN guard: warned
    f = msgs([("task", "train"), ("rollback", "2"),
              ("model_dir", "/tmp/m")])
    assert any("monitor_nan = fatal" in x.message for x in f)
    # properly configured: no rollback findings
    f = msgs([("task", "train"), ("rollback", "2"), ("monitor", "1"),
              ("monitor_nan", "fatal"), ("model_dir", "/tmp/m"),
              ("ckpt_async", "1"), ("ckpt_keep", "3")])
    assert not f, [x.format() for x in f]
    # save_model=0 defeats rollback: error
    f = msgs([("task", "train"), ("rollback", "1"), ("monitor", "1"),
              ("monitor_nan", "fatal"), ("model_dir", "/tmp/m"),
              ("save_model", "0")], sev="error")
    assert f and "save_model = 0" in f[0].message
    # ckpt_keep=1 with rollback: no fallback snapshot
    f = msgs([("task", "train"), ("rollback", "1"), ("monitor", "1"),
              ("monitor_nan", "fatal"), ("model_dir", "/tmp/m"),
              ("ckpt_async", "1"), ("ckpt_keep", "1")])
    assert any("ckpt_keep = 1" in x.message for x in f)
    # retention without async snapshots: warned
    f = msgs([("task", "train"), ("ckpt_keep", "5")])
    assert any(".ckpt" in x.message for x in f)
    # ckpt keys off-task: warned
    f = msgs([("task", "pred"), ("ckpt_async", "1")])
    assert any("task = train" in x.message for x in f)
    # unknown-key detection still catches typos of the new keys
    f = [x for x in lint_pairs([("task", "train"), ("ckpt_asynk", "1")])
         if x.key == "ckpt_asynk"]
    assert f and f[0].severity == "error" \
        and f[0].suggestion == "ckpt_async"
