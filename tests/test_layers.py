"""Layer zoo unit tests against numpy oracles.

This is the PairTest-style differential strategy from the reference
(pairtest_layer-inl.hpp) turned into a real unit suite: each TPU/XLA layer
is checked against an independent numpy implementation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.layers.base import ForwardContext, LabelInfo
from cxxnet_tpu.layers.registry import create_layer
from cxxnet_tpu.ops import nn as N
from helpers import ctx_eval, ctx_train, rand4, run_layer


# ---------------------------------------------------------------- activations
def test_relu_sigmoid_tanh_softplus():
    x = rand4(2, 3, 4, 5)
    (y,), _ = run_layer("relu", x)
    np.testing.assert_allclose(y, np.maximum(x, 0), rtol=1e-6)
    (y,), _ = run_layer("sigmoid", x)
    np.testing.assert_allclose(y, 1 / (1 + np.exp(-x)), rtol=1e-5)
    (y,), _ = run_layer("tanh", x)
    np.testing.assert_allclose(y, np.tanh(x), rtol=1e-5)
    (y,), _ = run_layer("softplus", x)
    np.testing.assert_allclose(y, np.log1p(np.exp(x)), rtol=1e-5)


def test_xelu():
    x = rand4(2, 1, 1, 8)
    (y,), _ = run_layer("xelu", x, {"b": 4.0})
    np.testing.assert_allclose(y, np.where(x > 0, x, x / 4.0), rtol=1e-6)


def test_insanity_eval_uses_mean_slope():
    x = rand4(2, 1, 1, 8)
    (y,), _ = run_layer("insanity", x, {"lb": 2, "ub": 4})
    np.testing.assert_allclose(y, np.where(x > 0, x, x / 3.0), rtol=1e-6)


def test_insanity_train_bounds():
    x = -np.ones((4, 1, 1, 64), np.float32)
    (y,), _ = run_layer("insanity", x, {"lb": 2, "ub": 4}, train=True)
    # each element is -1/d with d in [2,4]
    assert ((y <= -1 / 4.001) & (y >= -1 / 1.999)).all()


def test_prelu_eval():
    x = rand4(2, 3, 4, 4)
    (y,), params = run_layer("prelu", x, {"init_slope": 0.25})
    slope = np.asarray(params["bias"])
    assert slope.shape == (3,)
    expect = np.where(x > 0, x, x * slope.reshape(1, 3, 1, 1))
    np.testing.assert_allclose(y, expect, rtol=1e-6)


def test_bias_layer():
    x = rand4(2, 1, 1, 6)
    (y,), params = run_layer("bias", x, {"init_bias": 0.5})
    np.testing.assert_allclose(y, x + 0.5, rtol=1e-6)


# --------------------------------------------------------------------- fullc
def test_fullc_matches_numpy():
    x = rand4(4, 1, 1, 10)
    (y,), params = run_layer("fullc", x, {"nhidden": 7})
    w = np.asarray(params["wmat"])
    b = np.asarray(params["bias"])
    expect = x.reshape(4, 10) @ w.T + b
    np.testing.assert_allclose(y.reshape(4, 7), expect, rtol=1e-4)


def test_fullc_no_bias_and_init():
    x = rand4(4, 1, 1, 10)
    (y,), params = run_layer("fullc", x,
                             {"nhidden": 7, "no_bias": 1,
                              "random_type": "xavier"})
    assert "bias" not in params
    w = np.asarray(params["wmat"])
    bound = np.sqrt(3.0 / (10 + 7))
    assert np.abs(w).max() <= bound + 1e-6


def test_fixconn(tmp_path):
    p = tmp_path / "w.txt"
    p.write_text("3 4 2\n0 1 2.0\n2 3 -1.0\n")
    x = rand4(2, 1, 1, 4)
    (y,), _ = run_layer("fixconn", x,
                        {"nhidden": 3, "fixconn_weight": str(p)})
    w = np.zeros((3, 4), np.float32)
    w[0, 1] = 2.0
    w[2, 3] = -1.0
    np.testing.assert_allclose(y.reshape(2, 3), x.reshape(2, 4) @ w.T,
                               rtol=1e-5)


# ----------------------------------------------------------------------- conv
def conv_ref(x, w, b, stride, pad, groups=1):
    n, c, h, ww = x.shape
    oc, icg, kh, kw = w.shape
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (ww + 2 * pad - kw) // stride + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    out = np.zeros((n, oc, oh, ow), np.float32)
    cg = c // groups
    ocg = oc // groups
    for g in range(groups):
        for o in range(g * ocg, (g + 1) * ocg):
            for i in range(oh):
                for j in range(ow):
                    patch = xp[:, g * cg:(g + 1) * cg,
                               i * stride:i * stride + kh,
                               j * stride:j * stride + kw]
                    out[:, o, i, j] = (patch * w[o]).sum(axis=(1, 2, 3))
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def test_conv_matches_reference_impl():
    x = rand4(2, 3, 8, 8)
    (y,), params = run_layer("conv", x,
                             {"nchannel": 4, "kernel_size": 3, "stride": 2,
                              "pad": 1})
    expect = conv_ref(x, np.asarray(params["wmat"]),
                      np.asarray(params["bias"]), 2, 1)
    np.testing.assert_allclose(y, expect, rtol=1e-3, atol=1e-4)


def test_grouped_conv():
    x = rand4(2, 4, 6, 6)
    (y,), params = run_layer("conv", x,
                             {"nchannel": 6, "kernel_size": 3, "ngroup": 2,
                              "no_bias": 1})
    expect = conv_ref(x, np.asarray(params["wmat"]), None, 1, 0, groups=2)
    np.testing.assert_allclose(y, expect, rtol=1e-3, atol=1e-4)


# -------------------------------------------------------------------- pooling
def pool_ref(x, k, s, mode):
    n, c, h, w = x.shape
    oh = min(h - k + s - 1, h - 1) // s + 1
    ow = min(w - k + s - 1, w - 1) // s + 1
    out = np.zeros((n, c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            win = x[:, :, i * s:min(i * s + k, h), j * s:min(j * s + k, w)]
            if mode == "max":
                out[:, :, i, j] = win.max(axis=(2, 3))
            elif mode == "sum":
                out[:, :, i, j] = win.sum(axis=(2, 3))
            else:
                out[:, :, i, j] = win.sum(axis=(2, 3)) / (k * k)
    return out


@pytest.mark.parametrize("mode,layer", [("max", "max_pooling"),
                                        ("sum", "sum_pooling"),
                                        ("avg", "avg_pooling")])
@pytest.mark.parametrize("hw,k,s", [(6, 2, 2), (7, 3, 2), (28, 3, 2)])
def test_pooling(mode, layer, hw, k, s):
    x = rand4(2, 3, hw, hw)
    (y,), _ = run_layer(layer, x, {"kernel_size": k, "stride": s})
    np.testing.assert_allclose(y, pool_ref(x, k, s, mode),
                               rtol=1e-5, atol=1e-5)


def test_padded_pooling_no_all_padding_windows():
    """Tail windows lying entirely inside the padding must be dropped:
    stride > input extent with pad used to emit -inf rows."""
    x = np.ones((1, 1, 3, 3), np.float32)
    (y,), _ = run_layer("max_pooling", x,
                        {"kernel_size": 2, "stride": 4, "pad": 1})
    assert y.shape == (1, 1, 1, 1)
    assert np.isfinite(y).all() and y[0, 0, 0, 0] == 1.0
    # stride <= kernel variant: kernel=3, stride=2, pad=2 on h=2
    x = np.ones((1, 1, 2, 2), np.float32)
    (y,), _ = run_layer("max_pooling", x,
                        {"kernel_size": 3, "stride": 2, "pad": 2})
    assert np.isfinite(y).all()


def test_relu_max_pooling():
    x = rand4(2, 3, 6, 6)
    (y,), _ = run_layer("relu_max_pooling", x, {"kernel_size": 2, "stride": 2})
    np.testing.assert_allclose(y, pool_ref(np.maximum(x, 0), 2, 2, "max"),
                               rtol=1e-6)


def test_insanity_pooling_eval_is_max_pool():
    x = rand4(2, 3, 6, 6)
    (y,), _ = run_layer("insanity_max_pooling", x,
                        {"kernel_size": 2, "stride": 2})
    np.testing.assert_allclose(y, pool_ref(x, 2, 2, "max"), rtol=1e-6)


# ------------------------------------------------------------------------ lrn
def lrn_ref(x, nsize, alpha, beta, knorm):
    n, c, h, w = x.shape
    lo = nsize // 2
    hi = nsize - 1 - lo
    out = np.zeros_like(x)
    for ci in range(c):
        a = max(0, ci - lo)
        b = min(c, ci + hi + 1)
        norm = (x[:, a:b] ** 2).sum(axis=1) * (alpha / nsize) + knorm
        out[:, ci] = x[:, ci] * norm ** (-beta)
    return out


def test_lrn():
    x = rand4(2, 8, 4, 4)
    (y,), _ = run_layer("lrn", x, {"local_size": 5, "alpha": 0.001,
                                   "beta": 0.75, "knorm": 1.0})
    np.testing.assert_allclose(y, lrn_ref(x, 5, 0.001, 0.75, 1.0),
                               rtol=1e-4, atol=1e-5)


# ----------------------------------------------------------------- batch_norm
def test_batch_norm_conv_branch():
    x = rand4(8, 3, 4, 4)
    (y,), _ = run_layer("batch_norm", x, {"eps": 1e-5})
    mean = x.mean(axis=(0, 2, 3), keepdims=True)
    var = ((x - mean) ** 2).mean(axis=(0, 2, 3), keepdims=True)
    expect = (x - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(y, expect, rtol=1e-3, atol=1e-4)


def test_batch_norm_fc_branch():
    x = rand4(16, 1, 1, 6)
    (y,), _ = run_layer("batch_norm", x, {"eps": 1e-5})
    mean = x.mean(axis=(0, 1, 2), keepdims=True)
    var = ((x - mean) ** 2).mean(axis=(0, 1, 2), keepdims=True)
    np.testing.assert_allclose(y, (x - mean) / np.sqrt(var + 1e-5),
                               rtol=1e-3, atol=1e-4)


# -------------------------------------------------------------------- dropout
def test_dropout_eval_is_identity():
    x = rand4(2, 1, 1, 16)
    (y,), _ = run_layer("dropout", x, {"threshold": 0.5})
    np.testing.assert_allclose(y, x)


def test_dropout_train_mask_and_scale():
    x = np.ones((8, 1, 1, 1000), np.float32)
    (y,), _ = run_layer("dropout", x, {"threshold": 0.5}, train=True)
    vals = np.unique(np.round(y, 4))
    assert set(vals).issubset({0.0, 2.0})
    assert abs((y != 0).mean() - 0.5) < 0.05


# ------------------------------------------------------------------ shape ops
def test_flatten():
    x = rand4(2, 3, 4, 5)
    (y,), _ = run_layer("flatten", x)
    np.testing.assert_allclose(y.reshape(2, -1), x.reshape(2, -1))


def test_split_and_concat():
    x = rand4(2, 1, 1, 6)
    layer = create_layer("split")
    layer.num_out = 2
    outs, _ = layer.forward({}, {}, [jnp.asarray(x)], ctx_eval())
    assert len(outs) == 2
    a, b = rand4(2, 1, 1, 3), rand4(2, 1, 1, 5, seed=1)
    (y,), _ = run_layer("concat", [a, b])
    np.testing.assert_allclose(y, np.concatenate([a, b], axis=3))
    a, b = rand4(2, 3, 4, 4), rand4(2, 5, 4, 4, seed=1)
    (y,), _ = run_layer("ch_concat", [a, b])
    np.testing.assert_allclose(y, np.concatenate([a, b], axis=1))


def test_maxout():
    x = rand4(2, 6, 4, 4)
    (y,), _ = run_layer("maxout", x, {"ngroup": 3})
    expect = x.reshape(2, 2, 3, 4, 4).max(axis=2)
    np.testing.assert_allclose(y, expect)


# ---------------------------------------------------------------------- loss
def test_softmax_forward_and_loss():
    x = rand4(4, 1, 1, 10)
    layer = create_layer("softmax")
    layer.set_param("batch_size", "4")
    labels = LabelInfo(fields={"label": jnp.asarray(
        np.array([[1.0], [3.0], [0.0], [7.0]], np.float32))})
    ctx = ForwardContext(train=True, labels=labels, loss_scale=1.0 / 4)
    outs, _ = layer.forward({}, {}, [jnp.asarray(x)], ctx)
    p = np.asarray(outs[0]).reshape(4, 10)
    e = np.exp(x.reshape(4, 10) - x.reshape(4, 10).max(1, keepdims=True))
    np.testing.assert_allclose(p, e / e.sum(1, keepdims=True), rtol=1e-5)
    assert len(ctx.losses) == 1
    expect_loss = -np.log(p[np.arange(4), [1, 3, 0, 7]]).sum() / 4
    np.testing.assert_allclose(float(ctx.losses[0]), expect_loss, rtol=1e-5)


def test_softmax_gradient_matches_reference_rule():
    """Reference rule: d loss / d x = (p - onehot(y)) * scale
    (softmax_layer-inl.hpp:23-31, loss_layer_base-inl.hpp:61-62)."""
    x = rand4(4, 1, 1, 10)
    y = np.array([[1.0], [3.0], [0.0], [7.0]], np.float32)
    layer = create_layer("softmax")
    scale = 1.0 / 4

    def loss_fn(xj):
        ctx = ForwardContext(train=True,
                             labels=LabelInfo(fields={"label": jnp.asarray(y)}),
                             loss_scale=scale)
        layer.forward({}, {}, [xj], ctx)
        return ctx.losses[0]

    g = np.asarray(jax.grad(loss_fn)(jnp.asarray(x))).reshape(4, 10)
    e = np.exp(x.reshape(4, 10) - x.reshape(4, 10).max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    onehot = np.eye(10, dtype=np.float32)[y[:, 0].astype(int)]
    np.testing.assert_allclose(g, (p - onehot) * scale, rtol=1e-4, atol=1e-6)


def test_l2_loss_gradient():
    x = rand4(4, 1, 1, 3)
    y = rand4(4, 1, 1, 3, seed=9).reshape(4, 3)
    layer = create_layer("l2_loss")

    def loss_fn(xj):
        ctx = ForwardContext(train=True,
                             labels=LabelInfo(fields={"label": jnp.asarray(y)}),
                             loss_scale=0.25)
        layer.forward({}, {}, [xj], ctx)
        return ctx.losses[0]

    g = np.asarray(jax.grad(loss_fn)(jnp.asarray(x))).reshape(4, 3)
    np.testing.assert_allclose(g, (x.reshape(4, 3) - y) * 0.25,
                               rtol=1e-4, atol=1e-6)


def test_multi_logistic_gradient():
    x = rand4(4, 1, 1, 3)
    y = (rand4(4, 1, 1, 3, seed=5).reshape(4, 3) > 0).astype(np.float32)
    layer = create_layer("multi_logistic")

    def loss_fn(xj):
        ctx = ForwardContext(train=True,
                             labels=LabelInfo(fields={"label": jnp.asarray(y)}),
                             loss_scale=1.0)
        layer.forward({}, {}, [xj], ctx)
        return ctx.losses[0]

    g = np.asarray(jax.grad(loss_fn)(jnp.asarray(x))).reshape(4, 3)
    sig = 1 / (1 + np.exp(-x.reshape(4, 3)))
    np.testing.assert_allclose(g, sig - y, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------------- pairtest
def test_pairtest_identical_layers_agree():
    x = rand4(2, 3, 6, 6)
    layer = create_layer("pairtest-max_pooling-max_pooling")
    layer.set_param("kernel_size", "2")
    layer.set_param("stride", "2")
    shapes = [tuple(x.shape)]
    layer.infer_shapes(shapes)
    params = layer.init_params(jax.random.PRNGKey(0), shapes)
    ctx = ctx_eval()
    outs, _ = layer.forward(params, {"master": {}, "slave": {}},
                            [jnp.asarray(x)], ctx)
    (key,) = [k for k in ctx.diagnostics if k.endswith("fwd_rel_err")]
    assert float(ctx.diagnostics[key]) < 1e-5


def test_pairtest_detects_divergence():
    x = rand4(2, 3, 6, 6)
    layer = create_layer("pairtest-max_pooling-avg_pooling")
    layer.set_param("kernel_size", "2")
    layer.set_param("stride", "2")
    layer.infer_shapes([tuple(x.shape)])
    ctx = ctx_eval()
    outs, _ = layer.forward({}, {}, [jnp.asarray(x)], ctx)
    (key,) = [k for k in ctx.diagnostics if k.endswith("fwd_rel_err")]
    assert float(ctx.diagnostics[key]) > 1e-3


def test_pairtest_gradient_comparison():
    """Train-mode pairtest records input-grad + weight-grad relative errors
    (reference After-Backprop comparisons, pairtest_layer-inl.hpp:95-118)."""
    x = rand4(2, 3, 8, 8)
    layer = create_layer("pairtest-conv-conv")
    layer.set_param("nchannel", "4")
    layer.set_param("kernel_size", "3")
    shapes = [tuple(x.shape)]
    layer.infer_shapes(shapes)
    params = layer.init_params(jax.random.PRNGKey(0), shapes)
    bufs = layer.init_buffers(shapes)
    ctx = ForwardContext(train=True, rng=jax.random.PRNGKey(3))
    outs, _ = layer.forward(params, bufs, [jnp.asarray(x)], ctx)
    d = ctx.diagnostics
    for suffix in ("fwd_rel_err", "in_grad_rel_err", "wgrad_rel_err",
                   "weight_rel_err"):
        (v,) = [d[k] for k in d if k.endswith(suffix)]
        assert float(v) < 1e-5, (suffix, float(v))


def test_pairtest_catches_broken_backward():
    """A deliberately-broken slave (different pad => different gradient
    geometry is caught at infer; here: different stride-compatible layer
    with same shapes but different math) trips the gradient comparison."""
    x = rand4(2, 3, 8, 8)
    layer = create_layer("pairtest-relu-sigmoid")
    shapes = [tuple(x.shape)]
    layer.infer_shapes(shapes)
    ctx = ForwardContext(train=True, rng=jax.random.PRNGKey(3))
    layer.forward({}, {}, [jnp.asarray(x)], ctx)
    d = ctx.diagnostics
    (fwd,) = [d[k] for k in d if k.endswith("fwd_rel_err")]
    (bwd,) = [d[k] for k in d if k.endswith("in_grad_rel_err")]
    assert float(fwd) > 1e-3
    assert float(bwd) > 1e-3


def test_pairtest_straight_through_is_master():
    """Pairtest output values must be exactly the master's (slave joins
    only through a zero-valued straight-through term)."""
    x = rand4(2, 3, 6, 6)
    layer = create_layer("pairtest-max_pooling-avg_pooling")
    layer.set_param("kernel_size", "2")
    layer.set_param("stride", "2")
    layer.infer_shapes([tuple(x.shape)])
    ctx = ForwardContext(train=True, rng=jax.random.PRNGKey(0))
    (out,), _ = layer.forward({}, {}, [jnp.asarray(x)], ctx)
    from cxxnet_tpu.ops import nn as N
    ref = N.max_pool2d(jnp.asarray(x), 2, 2, 2)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_diff_layers_harness():
    """cxxnet_tpu.testing.diff_layers: clean pair ~0 err; broken pair big."""
    from cxxnet_tpu.testing import diff_layers
    a = create_layer("conv")
    b = create_layer("conv")
    for l in (a, b):
        l.set_param("nchannel", "4")
        l.set_param("kernel_size", "3")
        l.set_param("pad", "1")
    d = diff_layers(a, b, [(2, 3, 8, 8)])
    assert d["fwd_rel_err"] < 1e-5
    assert d["in_grad_rel_err"] < 1e-5
    assert d["wgrad_rel_err"] < 1e-5

    broken = create_layer("relu")
    ok = create_layer("tanh")
    d = diff_layers(ok, broken, [(2, 3, 8, 8)])
    assert d["fwd_rel_err"] > 1e-3
    assert d["in_grad_rel_err"] > 1e-3


def test_max_pool_bwd_gather_matches_dilate():
    """The candidate-window gather unpool (CXXNET_POOL_BWD=gather) equals
    the dilate-and-add formulation on strided/padded/tail geometries."""
    from cxxnet_tpu.ops import nn as N
    rnd = np.random.RandomState(0)
    for (h, w, k, s, p) in [(55, 55, 3, 2, 0), (13, 13, 3, 2, 0),
                            (28, 28, 2, 2, 0), (27, 27, 3, 1, 1),
                            (9, 9, 3, 3, 0), (8, 10, 4, 3, 2)]:
        x = jnp.asarray(rnd.randint(0, 5, (2, 3, h, w)).astype(np.float32))
        y = N._max_pool_raw(x, k, k, s, p, p)
        dy = jnp.asarray(rnd.rand(*y.shape).astype(np.float32))
        d1 = N._max_pool_eq_bwd(k, k, s, p, p, (x, y), dy)[0]
        d2 = N._max_pool_eq_bwd_gather(k, k, s, p, p, (x, y), dy)[0]
        np.testing.assert_allclose(np.asarray(d2), np.asarray(d1),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=str((h, w, k, s, p)))


def test_conv2d_s2d_matches_conv2d():
    """Space-to-depth lowering is numerically the same conv (fwd + grads)."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.ops import nn as N
    rnd = np.random.RandomState(0)
    for (n, c, h, w, co, k, s, p) in [(2, 3, 23, 23, 8, 11, 4, 0),
                                      (2, 3, 16, 16, 4, 5, 2, 2),
                                      (1, 4, 15, 15, 4, 7, 3, 1)]:
        x = jnp.asarray(rnd.rand(n, c, h, w).astype(np.float32))
        wt = jnp.asarray((rnd.rand(co, c, k, k) - 0.5).astype(np.float32))
        a = N.conv2d(x, wt, stride=s, pad_y=p, pad_x=p)
        b = N.conv2d_s2d(x, wt, stride=s, pad_y=p, pad_x=p)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
        ga = jax.grad(lambda xx, ww: jnp.sum(
            N.conv2d(xx, ww, stride=s, pad_y=p, pad_x=p) ** 2),
            argnums=(0, 1))(x, wt)
        gb = jax.grad(lambda xx, ww: jnp.sum(
            N.conv2d_s2d(xx, ww, stride=s, pad_y=p, pad_x=p) ** 2),
            argnums=(0, 1))(x, wt)
        for u, v in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                       rtol=1e-3, atol=1e-3)


def test_conv_layer_space_to_depth_key():
    """conv layer with space_to_depth=1 produces the same outputs."""
    import jax
    import jax.numpy as jnp
    from cxxnet_tpu.layers.base import ForwardContext
    from cxxnet_tpu.layers.registry import create_layer
    rnd = np.random.RandomState(1)
    x = jnp.asarray(rnd.rand(2, 3, 23, 23).astype(np.float32))
    outs = []
    for flag in ("0", "1"):
        l = create_layer("conv")
        l.set_param("kernel_size", "11")
        l.set_param("stride", "4")
        l.set_param("nchannel", "8")
        l.set_param("space_to_depth", flag)
        params = l.init_params(jax.random.PRNGKey(0), [(2, 3, 23, 23)])
        assert l.infer_shapes([(2, 3, 23, 23)]) == [(2, 8, 4, 4)]
        (out,), _ = l.forward(params, {}, [x], ForwardContext(train=True))
        outs.append(np.asarray(out))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4, atol=1e-4)


def test_engine_options_config_keys():
    """VERDICT r3 item 10: lowering toggles are config keys, not just env
    vars.  `pool_bwd = eq` set through NetTrainer.set_param must route
    max_pool2d to the exact all-ties backward."""
    import jax
    from cxxnet_tpu.engine import opts, set_engine_option
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.ops import nn as N
    t = NetTrainer()
    old = opts.pool_bwd
    try:
        t.set_param("pool_bwd", "eq")
        assert opts.pool_bwd == "eq"
        # tied input: all-ties semantics gives EVERY tied maximum the full
        # window gradient (mshadow unpool<red::maximum>)
        x = jnp.ones((1, 1, 4, 4), jnp.float32)
        d_eq = jax.grad(lambda v: N.max_pool2d(v, 2, 2, 2).sum())(x)
        np.testing.assert_allclose(np.asarray(d_eq),
                                   np.ones((1, 1, 4, 4)))
        t.set_param("pool_bwd", "sas")
        d_sas = jax.grad(lambda v: N.max_pool2d(v, 2, 2, 2).sum())(x)
        # one winner per window: each 2x2 window holds a single 1.0
        assert np.asarray(d_sas).sum() == 4.0
        assert (np.asarray(d_sas) > 0).sum() == 4
        # invalid values are rejected — ValueError since ISSUE 5 (asserts
        # vanish under python -O)
        with pytest.raises(ValueError):
            set_engine_option("pool_bwd", "bogus")
    finally:
        set_engine_option("pool_bwd", old)


def test_kaiming_uses_fan_in():
    """kaiming sigma must be sqrt(2/fan_in): the fan_OUT formula it
    shipped with under-scales deep relu stacks (GoogLeNet trunk
    activations decayed ~3x per stage and the loss went data-independent
    at chance; experiments/gl_stream.py)."""
    import numpy as np
    from cxxnet_tpu.layers.base import LayerParam
    p = LayerParam()
    p.set_param("random_type", "kaiming")
    p.set_param("nhidden", 1000)      # fan_out - must NOT drive sigma
    key = jax.random.PRNGKey(0)
    fan_in = 50
    w = np.asarray(p.rand_init_weight(key, (1000, fan_in), fan_in, 1000))
    want = np.sqrt(2.0 / fan_in)
    assert abs(w.std() - want) / want < 0.05, (w.std(), want)
