"""Config tokenizer + NetConfig parsing tests."""

import pytest

from cxxnet_tpu.utils.config import (ConfigError, parse_config_string,
                                     parse_keyval_args)
from cxxnet_tpu.nnet.netconfig import NetConfig


def test_basic_pairs():
    pairs = parse_config_string("a = 1\nb=2\n# comment\nc = hello\n")
    assert pairs == [("a", "1"), ("b", "2"), ("c", "hello")]


def test_quoted_values():
    pairs = parse_config_string('path = "./data/my file.gz"\n')
    assert pairs == [("path", "./data/my file.gz")]


def test_order_and_repeats():
    pairs = parse_config_string("iter = mnist\niter = end\niter = mnist\n")
    assert [v for _, v in pairs] == ["mnist", "end", "mnist"]


def test_inline_comment_and_ws():
    pairs = parse_config_string("x  =  3   # trailing\n  y=z\n")
    assert pairs == [("x", "3"), ("y", "z")]


def test_keyval_args():
    assert parse_keyval_args(["dev=tpu", "num_round=3"]) == \
        [("dev", "tpu"), ("num_round", "3")]
    with pytest.raises(ConfigError):
        parse_keyval_args(["noequals"])


MLP_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
layer[+0] = softmax
netconfig=end
input_shape = 1,1,784
batch_size = 16
"""


def test_netconfig_mlp():
    nc = NetConfig()
    nc.configure(parse_config_string(MLP_CONF))
    assert len(nc.layers) == 4
    assert nc.layers[0].type_name == "fullc"
    assert nc.layers[0].nindex_in == [0]
    # fc1 output node is a new node named fc1
    fc1_out = nc.layers[0].nindex_out[0]
    assert nc.node_names[fc1_out] == "fc1"
    # sigmoid reads from fc1's out
    assert nc.layers[1].nindex_in == [fc1_out]
    # layer[sg1->fc2] named-node wiring
    sg1 = nc.node_name_map["sg1"]
    assert nc.layers[2].nindex_in == [sg1]
    # softmax is a self-loop (layer[+0])
    assert nc.layers[3].nindex_in == nc.layers[3].nindex_out
    # captured layer config
    assert ("nhidden", "100") in nc.layercfg[0]
    assert nc.input_shape == (1, 1, 784)
    assert nc.layer_name_map["fc1"] == 0


def test_netconfig_numeric_nodes():
    conf = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
layer[1->2] = max_pooling
  kernel_size = 2
layer[2->2] = dropout
netconfig=end
input_shape = 1,28,28
"""
    nc = NetConfig()
    nc.configure(parse_config_string(conf))
    assert nc.num_nodes == 3
    assert nc.layers[2].nindex_in == nc.layers[2].nindex_out == [2]


def test_netconfig_multi_input():
    conf = """
netconfig=start
layer[0->a] = fullc:f1
  nhidden = 8
layer[0->b] = fullc:f2
  nhidden = 8
layer[a,b->c] = concat
layer[+1] = softmax
netconfig=end
input_shape = 1,1,4
"""
    nc = NetConfig()
    nc.configure(parse_config_string(conf))
    assert len(nc.layers[2].nindex_in) == 2
    # layer[+1] allocates an anonymous node after c
    assert nc.layers[3].nindex_in == [nc.node_name_map["c"]]


def test_netconfig_share_layer():
    conf = """
netconfig=start
layer[0->x] = fullc:enc
  nhidden = 4
layer[x->y] = sigmoid
layer[y->z] = share[enc]
netconfig=end
input_shape = 1,1,4
"""
    nc = NetConfig()
    nc.configure(parse_config_string(conf))
    assert nc.layers[2].is_shared
    assert nc.layers[2].primary_layer_index == 0


def test_netconfig_label_vec():
    conf = """
label_vec[0,1) = label
label_vec[1,4) = extra_label
netconfig=start
layer[+1] = fullc
  nhidden = 4
netconfig=end
input_shape = 1,1,4
"""
    nc = NetConfig()
    nc.configure(parse_config_string(conf))
    fields = dict((n, (a, b)) for n, a, b in nc.label_fields())
    assert fields == {"label": (0, 1), "extra_label": (1, 4)}
    assert nc.label_width() == 4


def test_netconfig_roundtrip():
    nc = NetConfig()
    nc.configure(parse_config_string(MLP_CONF))
    d = nc.to_dict()
    nc2 = NetConfig.from_dict(d)
    assert nc2.node_names == nc.node_names
    assert [l.type_name for l in nc2.layers] == \
        [l.type_name for l in nc.layers]
    assert nc2.layercfg == nc.layercfg
