"""Incremental decode (serve/decode.py + StepScheduler — ISSUE 16).

Covers the contracts KV-cached generation stands on: prefill and
single-token step logits are BITWISE equal to the O(N²) full forward at
f32 (the property that makes the cache safe to enable); the two AOT
executables never retrace after warmup, asserted through the real
task=serve CLI; the step scheduler admits requests into the in-flight
batch BETWEEN decode steps (continuous batching) and degrades to
request-level batching under ``continuous=False``; a runner exception
latches the scheduler dead and reaches every client (no hangs); and
sampling off the LM head is deterministic per request seed.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.serve.batcher import ServeClosed, StepScheduler
from cxxnet_tpu.serve.decode import DecodeEngine, sample_token


# ------------------------------------------------------------ engine parity

@pytest.fixture(scope="module")
def lm_trainer():
    from cxxnet_tpu.models import transformer
    from __graft_entry__ import _make_trainer
    return _make_trainer(
        transformer(vocab=64, seq=32, dim=32, nlayer=2, nhead=2),
        2, "cpu", extra=[("updater", "sgd"), ("eta", "0.01"),
                         ("eval_train", "0"), ("silent", "1")])


@pytest.fixture(scope="module")
def engine(lm_trainer):
    eng = DecodeEngine(lm_trainer, slots=2, max_seqlen=32)
    eng.warmup()
    return eng


def _prompt(n, seed=0, vocab=64):
    return np.random.RandomState(seed).randint(0, vocab, n) \
        .astype(np.int32)


def test_prefill_matches_full_forward_bitwise(engine):
    """Prefill logits at the last prompt position are byte-identical to
    the cache-free eval forward: capture is a tee, not a rewrite."""
    for L in (1, 5, 17, 32):
        p = _prompt(L, seed=L)
        inc = engine.prefill(0, p)
        full = engine.full_logits(p)
        assert inc.dtype == np.float32
        assert np.array_equal(inc, full[L - 1]), f"prompt len {L}"


def test_incremental_steps_match_full_forward_bitwise(engine):
    """Greedy decode through the cache: every step's logits row equals
    the full forward over the grown sequence, bitwise at f32 — masked
    cache positions softmax to exactly 0.0 and drop out of the p·V
    reduction, so stale garbage in unwritten slots is invisible."""
    p = list(_prompt(6, seed=42))
    logits = engine.prefill(1, np.asarray(p, np.int32))
    seq = list(p) + [int(np.argmax(logits))]
    for _ in range(8):
        pos = len(seq) - 1
        step = engine.step(np.asarray([0, seq[-1]], np.int32),
                           np.asarray([0, pos], np.int32))
        full = engine.full_logits(np.asarray(seq, np.int32))
        assert np.array_equal(step[1], full[pos])
        seq.append(int(np.argmax(step[1])))
    assert engine.retraces == 0


def test_engine_zero_retrace_and_footprint(engine):
    """Mixed prefill/step traffic after warmup: zero retraces, and the
    footprint's kv_cache_bytes matches the analytic sizing the lint
    rule uses (2 · layers · slots · nhead · seqlen · head_dim · 4)."""
    for L in (3, 9, 30):
        engine.prefill(L % 2, _prompt(L, seed=L))
        engine.step(np.zeros(2, np.int32),
                    np.asarray([L, 0], np.int32))
    assert engine.retraces == 0
    fp = engine.footprint()
    if fp:  # backend memory_analysis is optional
        assert fp["kv_cache_bytes"] == engine.kv_cache_bytes()
        assert fp["buckets"] == 2
        assert fp["total_bytes"] >= fp["weight_bytes"]
    assert engine.kv_cache_bytes() \
        == 2 * 2 * 2 * engine.nhead * 32 * engine.head_dim * 4


def test_engine_validation(engine, lm_trainer):
    with pytest.raises(ValueError, match="decode_max_seqlen"):
        DecodeEngine(lm_trainer, slots=2, max_seqlen=64)
    with pytest.raises(ValueError, match="prompt of 33"):
        engine.prefill(0, _prompt(33))
    with pytest.raises(ValueError, match="slot 7"):
        engine.prefill(7, _prompt(4))


def test_engine_rejects_bidirectional_attention():
    from cxxnet_tpu.models import transformer
    from __graft_entry__ import _make_trainer
    t = _make_trainer(
        transformer(vocab=16, seq=8, dim=8, nlayer=1, nhead=1, causal=0),
        1, "cpu", extra=[("updater", "sgd"), ("eta", "0.01"),
                         ("eval_train", "0"), ("silent", "1")])
    with pytest.raises(ValueError, match="causal"):
        DecodeEngine(t, slots=1)


# ---------------------------------------------------------------- sampling

def test_sample_token_modes():
    logits = np.array([0.1, 3.0, -1.0, 2.9], np.float32)
    assert sample_token(logits, "greedy") == 1
    # topk=1 degenerates to argmax no matter the rng draw
    rng = np.random.RandomState(0)
    assert sample_token(logits, "topk", topk=1, rng=rng) == 1
    # topk support restriction: ids outside the top-2 never sampled
    rng = np.random.RandomState(1)
    draws = {sample_token(logits, "topk", temp=2.0, topk=2, rng=rng)
             for _ in range(64)}
    assert draws <= {1, 3}
    # temperature sampling is deterministic per rng state
    a = sample_token(logits, "temperature", temp=1.5,
                     rng=np.random.RandomState(7))
    b = sample_token(logits, "temperature", temp=1.5,
                     rng=np.random.RandomState(7))
    assert a == b
    with pytest.raises(ValueError, match="serve_gen_sample"):
        sample_token(logits, "nucleus")


# ------------------------------------------------- scheduler (fake runner)
# A fake runner keeps these pure thread-protocol tests: no jax, no model.
# Logits are rigged so greedy always emits token (slot + 1) — never the
# eos (0), so generation length is controlled by max_new_tokens alone.

class FakeRunner:
    def __init__(self, slots=2, max_seqlen=64, step_sleep=0.004,
                 fail_after=None):
        self.slots = slots
        self.max_seqlen = max_seqlen
        self.step_sleep = step_sleep
        self.fail_after = fail_after
        self.prefill_log = []            # (slot, prompt_len)
        self.step_actives = []           # tuple of active slots per step
        self.block_log = []              # (width, positions) per block
        self.lock = threading.Lock()

    def _logits(self, slot):
        row = np.zeros(8, np.float32)
        row[slot + 1] = 1.0
        return row

    def prefill(self, slot, tokens):
        with self.lock:
            self.prefill_log.append((slot, len(tokens)))
        return self._logits(slot)

    def step(self, tokens, positions):
        with self.lock:
            self.step_actives.append(
                tuple(int(i) for i in np.nonzero(positions)[0]))
            if self.fail_after is not None \
                    and len(self.step_actives) > self.fail_after:
                raise RuntimeError("device fell over")
        time.sleep(self.step_sleep)
        return np.stack([self._logits(s) for s in range(self.slots)])

    def block(self, tokens, positions):
        # multi-column dispatch (chunked prefill / speculative verify):
        # every row repeats the slot's rigged logits
        w = tokens.shape[1]
        with self.lock:
            self.block_log.append((w, tuple(int(p) for p in positions)))
        time.sleep(self.step_sleep)
        return np.stack([np.tile(self._logits(s), (w, 1))
                         for s in range(self.slots)])


def _submit_async(sched, prompt, max_new):
    out = {}

    def run():
        try:
            out["tokens"] = sched.submit(prompt, max_new)
        except BaseException as e:  # noqa: BLE001 — asserted by tests
            out["error"] = e
        out["done_at"] = time.perf_counter()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th, out


def _wait(pred, timeout=5.0):
    t0 = time.perf_counter()
    while not pred():
        assert time.perf_counter() - t0 < timeout, "test timed out"
        time.sleep(0.002)


def test_scheduler_joins_and_leaves_between_steps():
    """Continuous batching: a request submitted mid-flight joins the
    active batch between steps, a short one finishes and frees its slot
    while the long one keeps decoding, and the freed slot is REUSED by
    the next admission — no head-of-line blocking."""
    fr = FakeRunner(slots=2)
    s = StepScheduler(fr, max_new_tokens=40, eos=0, queue_depth=8)
    s.start()
    try:
        prompt = np.arange(1, 4, dtype=np.int32)
        ta, a = _submit_async(s, prompt, 40)
        _wait(lambda: len(fr.step_actives) >= 2)
        tb, b = _submit_async(s, prompt, 3)
        tb.join(5.0)
        assert b["tokens"] is not None and len(b["tokens"]) == 3
        assert "error" not in b
        assert ta.is_alive()  # B finished while A still decodes
        # B rode the same batch as A for at least one step
        assert any(len(act) == 2 for act in fr.step_actives)
        slot_b = fr.prefill_log[1][0]
        # the freed slot is immediately reusable: C lands on B's slot
        tc, c = _submit_async(s, prompt, 2)
        tc.join(5.0)
        assert len(c["tokens"]) == 2
        assert fr.prefill_log[2][0] == slot_b
        ta.join(10.0)
        assert len(a["tokens"]) == 40
    finally:
        s.close()
    st = s.stats()
    assert st["requests"] == 3 and st["prefills"] == 3
    assert st["tokens"] == 45
    assert st["batching"] == "continuous"
    # every step is histogrammed; tokens = prefill samples + step samples
    assert sum(st["occupancy_hist"].values()) == st["steps"]
    assert sum(int(k) * v for k, v in st["occupancy_hist"].items()) \
        == st["tokens"] - st["prefills"]
    assert st["tok_p50_ms"] <= st["tok_p95_ms"] <= st["tok_p99_ms"]


def test_scheduler_request_mode_runs_batch_to_completion():
    """continuous=False is the A/B baseline: a request submitted after
    the batch started stepping waits for the WHOLE batch to drain —
    the head-of-line blocking --lm-serve measures against."""
    fr = FakeRunner(slots=2)
    s = StepScheduler(fr, max_new_tokens=40, eos=0, continuous=False,
                      queue_depth=8)
    s.start()
    try:
        prompt = np.arange(1, 4, dtype=np.int32)
        ta, a = _submit_async(s, prompt, 12)
        _wait(lambda: len(fr.step_actives) >= 2)
        tb, b = _submit_async(s, prompt, 2)
        ta.join(10.0)
        tb.join(10.0)
        assert len(a["tokens"]) == 12 and len(b["tokens"]) == 2
        # B never joined A's in-flight batch...
        assert all(len(act) == 1 for act in fr.step_actives)
        # ...and despite being 6x shorter, finished after A (blocked)
        assert b["done_at"] > a["done_at"]
    finally:
        s.close()
    assert s.stats()["batching"] == "request"


def test_scheduler_exception_reaches_all_clients():
    """A runner exception latches the scheduler dead and fans out to
    every active AND later request — clients get the error, never a
    hang (the MicroBatcher discipline at step granularity)."""
    fr = FakeRunner(slots=2, fail_after=3)
    s = StepScheduler(fr, max_new_tokens=40, eos=0, queue_depth=8)
    s.start()
    try:
        prompt = np.arange(1, 4, dtype=np.int32)
        ta, a = _submit_async(s, prompt, 30)
        tb, b = _submit_async(s, prompt, 30)
        ta.join(5.0)
        tb.join(5.0)
        assert not ta.is_alive() and not tb.is_alive()
        assert isinstance(a["error"], RuntimeError)
        assert isinstance(b["error"], RuntimeError)
        with pytest.raises(RuntimeError, match="device fell over"):
            s.submit(prompt, 2)
    finally:
        s.close()


def test_scheduler_rejects_oversize_prompt_and_close():
    fr = FakeRunner(slots=1, max_seqlen=4)
    s = StepScheduler(fr, max_new_tokens=4, eos=0)
    s.start()
    with pytest.raises(ValueError, match="cache holds"):
        s.submit(np.arange(5, dtype=np.int32))
    s.close()
    s.close()  # idempotent
    with pytest.raises(ServeClosed):
        s.submit(np.asarray([1], np.int32))
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-decode")]


# --------------------------------------------- scheduler over the real engine

def test_continuous_batching_matches_serial_greedy(engine):
    """Concurrent mixed-length generation through the step scheduler is
    token-identical to serial single-slot greedy decoding: slot
    placement, join order, and batch composition never leak into the
    sampled sequences (the bitwise-parity property, end to end)."""
    prompts = [_prompt(3 + (i % 5), seed=100 + i) for i in range(6)]
    lens = [4 + (i % 3) for i in range(6)]

    def serial(p, n):
        logits = engine.prefill(0, p)
        seq = [int(np.argmax(logits))]
        pos = len(p)
        while len(seq) < n:
            step = engine.step(np.asarray([seq[-1], 0], np.int32),
                               np.asarray([pos, 0], np.int32))
            seq.append(int(np.argmax(step[0])))
            pos += 1
        return seq

    want = [serial(p, n) for p, n in zip(prompts, lens)]
    s = StepScheduler(engine, max_new_tokens=8, eos=-1, queue_depth=8)
    s.start()
    got = [None] * 6
    try:
        def client(i):
            got[i] = s.submit(prompts[i], lens[i])

        ths = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(6)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
    finally:
        s.close()
    assert got == want
    assert engine.retraces == 0


# ------------------------------------------------------------- CLI task=serve

@pytest.fixture(scope="module")
def trained_lm(tmp_path_factory):
    """A 1-layer LM trained for one round over a synthetic packed
    corpus — the snapshot + token shards the serve_gen CLI run loads."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.models import transformer
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from make_synth_text import gen_docs
    from cxxnet_tpu.io.text import write_token_shard
    tmp_path = tmp_path_factory.mktemp("decode_cli")
    docs = gen_docs(60, vocab=64, mean_len=24, seed=3)
    for sh in range(2):
        write_token_shard(str(tmp_path / f"c_{sh}.tok"),
                          docs[sh::2], itemsize=2)
    net = transformer(vocab=64, seq=32, dim=32, nlayer=1, nhead=2,
                      packed=True)
    conf = tmp_path / "train.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = text
  path_tok = {tmp_path}/c_%d.tok
  tok_count = 2
iter = packseq
  seqlen = 32
iter = end
{net}
batch_size = 4
num_round = 1
model_dir = {tmp_path}/models
save_model = 1
updater = sgd
eta = 0.05
silent = 1
""")
    assert LearnTask().run([str(conf)]) == 0
    return tmp_path, net, str(tmp_path / "models" / "0001.model")


def test_cli_serve_gen_end_to_end(trained_lm):
    """task=serve + serve_gen=1 through the real CLI: every pred-stream
    prompt gets its generated ids in name_pred, the serve_gen record
    lands with ZERO retraces (the two-executable contract under real
    concurrent traffic), per-token/per-request latency records carry
    percentiles, and the prefill/decode/sample span stages ride the
    request traces — the ISSUE 16 acceptance run."""
    import json

    from cxxnet_tpu.main import LearnTask
    tmp_path, net, model = trained_lm
    conf = tmp_path / "serve_gen.conf"
    conf.write_text(f"""
dev = cpu
task = serve
model_in = {model}
pred = {tmp_path}/gen_out.txt
iter = text
  path_tok = {tmp_path}/c_%d.tok
  tok_count = 2
iter = packseq
  seqlen = 32
iter = end
{net}
batch_size = 4
serve_gen = 1
decode_slots = 2
decode_max_seqlen = 32
serve_gen_tokens = 5
serve_gen_prompt = 4
serve_clients = 3
trace_sample = 2
silent = 1
metrics_sink = jsonl:{tmp_path}/gen_metrics.jsonl
""")
    assert LearnTask().run([str(conf)]) == 0
    lines = open(tmp_path / "gen_out.txt").read().splitlines()
    assert lines, "no generations written"
    for ln in lines:
        toks = [int(x) for x in ln.split()]
        assert 1 <= len(toks) <= 5
        assert all(0 <= t < 64 for t in toks)

    recs = [json.loads(l) for l in open(tmp_path / "gen_metrics.jsonl")]
    [gen] = [r for r in recs if r["kind"] == "serve_gen"]
    assert gen["retraces"] == 0          # the acceptance criterion
    assert gen["requests"] == len(lines)
    assert gen["tokens"] == sum(len(l.split()) for l in lines)
    assert gen["tokens_per_sec"] > 0
    assert gen["slots"] == 2 and gen["max_seqlen"] == 32
    assert gen["batching"] == "continuous"
    assert sum(gen["occupancy_hist"].values()) == gen["steps"]
    assert gen["footprint"]["kv_cache_bytes"] > 0
    lat = {r["op"]: r for r in recs if r["kind"] == "latency"}
    assert {"token", "gen"} <= set(lat)
    for op in ("token", "gen"):
        assert lat[op]["count"] > 0
        assert 0 < lat[op]["p50"] <= lat[op]["p95"] <= lat[op]["p99"]
    spans = [r for r in recs if r["kind"] == "span"]
    kinds = {r["span"] for r in spans}
    assert {"prefill", "decode", "sample", "request"} <= kinds
    # decode/sample spans fan out over the riders they stepped for
    riders = [r for r in spans if r["span"] in ("decode", "sample")]
    assert riders and all(r["riders"] for r in riders)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-decode")
                or t.name.startswith("cxxnet-serve-gen")]


# ------------------------------------- speculative decoding (ISSUE 19)
# Contract under test: greedy speculative output is BITWISE identical
# to plain greedy decode (np.array_equal), whatever the draft proposes
# — every verify row of the block dispatch is the sequential step's
# logits row, and the acceptance loop emits the VERIFIED token at the
# first disagreement.  Chunked prefill rides the same block executable
# and must land the same cache contents as whole-prompt prefill.

@pytest.fixture(scope="module")
def block_engine(lm_trainer):
    """The flagship engine with block widths warmed for spec_k=3
    verification (width 4) and chunk-8 prefill."""
    eng = DecodeEngine(lm_trainer, slots=2, max_seqlen=32,
                       block_widths=(4, 8))
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def draft_engine(lm_trainer):
    """Degenerate draft: the SAME net as the flagship, so every
    proposal agrees and acceptance is total."""
    eng = DecodeEngine(lm_trainer, slots=2, max_seqlen=32)
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def small_draft_engine():
    """A genuinely different (smaller, untrained) draft net — the
    realistic partial/zero-agreement regime."""
    from cxxnet_tpu.models import transformer
    from __graft_entry__ import _make_trainer
    t = _make_trainer(
        transformer(vocab=64, seq=32, dim=16, nlayer=1, nhead=2),
        2, "cpu", extra=[("updater", "sgd"), ("eta", "0.01"),
                         ("eval_train", "0"), ("silent", "1")])
    eng = DecodeEngine(t, slots=2, max_seqlen=32)
    eng.warmup()
    return eng


class ShiftedDraft:
    """Adversarial draft: the flagship's logits rolled one vocab slot,
    so the greedy proposal NEVER matches the verified argmax — every
    round rejects everything and rolls the caches back."""

    def __init__(self, eng):
        self.eng = eng
        self.slots = eng.slots
        self.max_seqlen = eng.max_seqlen
        self.vocab = eng.vocab

    def prefill(self, slot, tokens):
        return np.roll(self.eng.prefill(slot, tokens), 1, axis=-1)

    def step(self, tokens, positions):
        return np.roll(self.eng.step(tokens, positions), 1, axis=-1)


def _serial_greedy(engine, prompt, max_new):
    """Plain greedy reference through the sequential step path."""
    logits = engine.prefill(0, prompt)
    seq = [int(np.argmax(logits))]
    pos = len(prompt)
    while len(seq) < max_new and pos < engine.max_seqlen:
        step = engine.step(np.asarray([seq[-1], 0], np.int32),
                           np.asarray([pos, 0], np.int32))
        seq.append(int(np.argmax(step[0])))
        pos += 1
    return seq


def _spec_generate(flagship, draft, prompts, max_new, **kw):
    s = StepScheduler(flagship, max_new_tokens=max_new, eos=-1,
                      queue_depth=8, draft=draft, **kw)
    s.start()
    try:
        outs = [s.submit(p, max_new) for p in prompts]
    finally:
        s.close()
    return outs, s


def test_block_matches_sequential_steps_bitwise(block_engine):
    """The multi-column cache advance: one width-4 block dispatch over
    the tokens k sequential steps would feed produces the SAME four
    logits rows, bitwise — each block row's mask stops at its own
    position, so its reduction is the sequential step's."""
    eng = block_engine
    p = _prompt(9, seed=11)
    logits = eng.prefill(0, p)
    toks = [int(np.argmax(logits))]
    rows = []
    pos = len(p)
    for i in range(4):
        step = eng.step(np.asarray([toks[-1], 0], np.int32),
                        np.asarray([pos + i, 0], np.int32))
        rows.append(step[0])
        toks.append(int(np.argmax(step[0])))
    blk = eng.block(
        np.asarray([toks[:4], [0, 0, 0, 0]], np.int32),
        np.asarray([len(p), 0], np.int32))
    for i in range(4):
        assert np.array_equal(blk[0, i], rows[i]), f"row {i}"
    assert eng.retraces == 0


def test_spec_greedy_bitwise_degenerate_draft(engine, block_engine,
                                              draft_engine):
    """draft == flagship: every proposal is accepted (the full-accept /
    draft-lag path runs every round) and the output is still bitwise
    plain greedy."""
    prompts = [_prompt(5, seed=1), _prompt(17, seed=2),
               _prompt(29, seed=3)]
    want = [_serial_greedy(engine, p, 12) for p in prompts]
    got, s = _spec_generate(block_engine, draft_engine, prompts, 12,
                            spec_k=3)
    assert [list(g) for g in got] == want
    assert s.n_spec_proposed > 0
    assert s.n_spec_accepted == s.n_spec_proposed
    # multi-column advance: far fewer flagship dispatches than tokens
    assert s.n_verify_calls < sum(len(w) for w in want)
    assert block_engine.retraces == 0


def test_spec_greedy_bitwise_adversarial_draft(engine, block_engine,
                                               draft_engine):
    """Forced total disagreement: zero acceptance, every round rolls
    both caches back (rollback-then-continue), and the output stream is
    STILL bitwise plain greedy — the verified row at the first
    disagreement is the sequential step's row."""
    prompts = [_prompt(5, seed=1), _prompt(17, seed=2),
               _prompt(29, seed=3)]
    want = [_serial_greedy(engine, p, 12) for p in prompts]
    got, s = _spec_generate(block_engine, ShiftedDraft(draft_engine),
                            prompts, 12, spec_k=3)
    assert [list(g) for g in got] == want
    assert s.n_spec_accepted == 0 and s.n_spec_proposed > 0
    # zero acceptance degrades to one emitted token per verify call
    assert s.n_verify_calls == sum(len(w) for w in want) \
        - len(prompts)  # first token of each request comes from prefill
    assert block_engine.retraces == 0


def test_spec_greedy_bitwise_real_draft(engine, block_engine,
                                        small_draft_engine):
    """A genuinely different draft net (partial agreement, whatever it
    happens to be): parity must hold regardless of the acceptance
    rate."""
    prompts = [_prompt(5, seed=4), _prompt(13, seed=5),
               _prompt(23, seed=6)]
    want = [_serial_greedy(engine, p, 10) for p in prompts]
    got, s = _spec_generate(block_engine, small_draft_engine, prompts,
                            10, spec_k=3)
    assert [list(g) for g in got] == want
    st = s.stats()
    assert st["spec_k"] == 3 and st["verify_calls"] == s.n_verify_calls
    assert 0.0 <= st["acceptance_rate"] <= 1.0
    assert st["draft_ms"] >= 0.0 and st["verify_ms"] >= 0.0


def test_spec_composes_with_chunked_prefill(engine, block_engine,
                                            draft_engine):
    """Speculation x chunked prefill x continuous batching in one
    scheduler: still bitwise greedy, chunk ticks counted, zero
    retraces (both block widths were AOT-warmed)."""
    prompts = [_prompt(5, seed=7), _prompt(17, seed=8),
               _prompt(29, seed=9)]
    want = [_serial_greedy(engine, p, 12) for p in prompts]
    got, s = _spec_generate(block_engine, draft_engine, prompts, 12,
                            spec_k=3, prefill_chunk=8)
    assert [list(g) for g in got] == want
    st = s.stats()
    assert st["prefill_chunks"] == sum(
        -(-len(p) // 8) for p in prompts)
    assert st["prefills"] == len(prompts)
    assert block_engine.retraces == 0
    assert draft_engine.retraces == 0


def test_chunked_prefill_logits_bitwise(block_engine):
    """Chunked prefill streams the prompt through the width-8 block
    executable; the last chunk's logits row at the final prompt
    position is bitwise the whole-prompt prefill's (and the cache-free
    full forward's) row."""
    eng = block_engine
    for L in (5, 16, 17, 32):
        p = _prompt(L, seed=40 + L)
        full = eng.full_logits(p)
        last = None
        for off in range(0, L, 8):
            tokens = np.zeros((2, 8), np.int32)
            chunk = p[off:off + 8]
            tokens[1, :len(chunk)] = chunk
            blk = eng.block(tokens, np.asarray([0, off], np.int32))
            last = blk[1, L - 1 - off] if off + 8 >= L else None
        assert last is not None
        assert np.array_equal(last, full[L - 1]), f"prompt len {L}"
    assert eng.retraces == 0


def test_bf16_kv_cache_within_envelope(engine, lm_trainer):
    """decode_kv_dtype = bf16 halves the KV bytes; decoding the SAME
    token sequence through the bf16 cache stays inside the declared
    SERVE_TOL envelope vs the f32 reference (prefill rows are bitwise —
    the cast only touches cache reads, which start at the first
    step)."""
    from cxxnet_tpu.serve.engine import SERVE_TOL
    eng16 = DecodeEngine(lm_trainer, slots=2, max_seqlen=32,
                         kv_dtype="bf16")
    eng16.warmup()
    assert eng16.kv_cache_bytes() * 2 == engine.kv_cache_bytes()
    p = _prompt(9, seed=77)
    ref = engine.prefill(0, p)
    got = eng16.prefill(0, p)
    assert np.array_equal(got, ref)     # prefill reads no cache
    seq = [int(np.argmax(ref))]
    worst = 0.0
    for i in range(8):
        pos = len(p) + i
        r = engine.step(np.asarray([seq[-1], 0], np.int32),
                        np.asarray([pos, 0], np.int32))[0]
        g = eng16.step(np.asarray([seq[-1], 0], np.int32),
                       np.asarray([pos, 0], np.int32))[0]
        denom = float(np.max(np.abs(r))) + 1e-6
        worst = max(worst, float(np.max(np.abs(g - r))) / denom)
        seq.append(int(np.argmax(r)))   # both follow the f32 choices
    assert worst <= SERVE_TOL["bf16"], f"bf16 KV err {worst}"
    fp = eng16.footprint()
    if fp:
        assert fp["kv_saved_bytes"] == eng16.kv_cache_bytes()
    assert eng16.stats()["kv_dtype"] == "bf16"
    assert eng16.retraces == 0


# ---------------------------------- scheduler units over the fake runner

class FakeDraft:
    """Fake draft over FakeRunner logits: proposes exactly what the
    fake flagship verifies (slot + 1), so every proposal is accepted."""

    def __init__(self, fr):
        self.fr = fr
        self.slots = fr.slots
        self.max_seqlen = fr.max_seqlen
        self.prefills = 0
        self.steps = 0

    def prefill(self, slot, tokens):
        self.prefills += 1
        return self.fr._logits(slot)

    def step(self, tokens, positions):
        self.steps += 1
        return np.stack([self.fr._logits(s)
                         for s in range(self.slots)])


def test_scheduler_spec_round_accounting():
    """Pure thread-protocol spec unit: an always-agreeing fake draft
    emits spec_k+1 tokens per verify dispatch; draft catch-up ticks run
    only after full-accept rounds; counters add up."""
    fr = FakeRunner(slots=2, step_sleep=0.0)
    fd = FakeDraft(fr)
    s = StepScheduler(fr, max_new_tokens=9, eos=0, queue_depth=8,
                      draft=fd, spec_k=3)
    s.start()
    try:
        out = s.submit(np.asarray([1, 2, 3], np.int32), 9)
    finally:
        s.close()
    slot = fr.prefill_log[0][0]
    assert out == [slot + 1] * 9    # the slot's rigged token throughout
    # 1 activation token + 2 full rounds of 4 = 9 tokens
    assert s.n_verify_calls == 2
    assert s.n_spec_proposed == 6 and s.n_spec_accepted == 6
    # round 1: 3 proposal steps; round 2: 1 catch-up (post full-accept
    # lag) + 3 proposals
    assert s.n_draft_steps == 7 and fd.steps == 7
    assert fd.prefills == 1
    st = s.stats()
    assert st["acceptance_rate"] == 1.0
    assert st["draft_steps"] == 7 and st["verify_calls"] == 2


def test_scheduler_chunked_prefill_interleaves():
    """Chunk ticks interleave with decode rounds: a long prompt joining
    a busy scheduler streams in one chunk per loop iteration while the
    in-flight request keeps emitting tokens — head-of-line blocking is
    bounded at one chunk, not one whole prefill."""
    fr = FakeRunner(slots=2, step_sleep=0.004)
    s = StepScheduler(fr, max_new_tokens=60, eos=0, queue_depth=8,
                      prefill_chunk=4)
    s.start()
    try:
        ta, a = _submit_async(s, np.arange(1, 4, dtype=np.int32), 60)
        _wait(lambda: len(fr.step_actives) >= 2)
        steps_before = len(fr.step_actives)
        tb, b = _submit_async(s, np.arange(1, 11, dtype=np.int32), 2)
        tb.join(5.0)
        assert b["tokens"] is not None and len(b["tokens"]) == 2
        assert ta.is_alive()            # A never drained for B's prompt
        ta.join(10.0)
        assert len(a["tokens"]) == 60
    finally:
        s.close()
    # both prompts chunked: ceil(3/4) + ceil(10/4) = 1 + 3 block ticks
    assert len(fr.block_log) == 4
    assert all(w == 4 for w, _ in fr.block_log)
    # A kept stepping while B's 3 chunks streamed in
    assert len(fr.step_actives) > steps_before + 1
    st = s.stats()
    assert st["prefill_chunks"] == 4 and st["prefills"] == 2


def test_scheduler_spec_failure_reaches_all_clients():
    """A draft failure mid-round latches the scheduler exactly like a
    flagship failure — every active and queued client gets the error."""

    class DyingDraft(FakeDraft):
        def step(self, tokens, positions):
            raise RuntimeError("draft fell over")

    fr = FakeRunner(slots=2, step_sleep=0.0)
    s = StepScheduler(fr, max_new_tokens=8, eos=0, queue_depth=8,
                      draft=DyingDraft(fr), spec_k=2)
    s.start()
    try:
        with pytest.raises(RuntimeError, match="draft fell over"):
            s.submit(np.asarray([1, 2], np.int32), 8)
        with pytest.raises(RuntimeError, match="draft fell over"):
            s.submit(np.asarray([1, 2], np.int32), 8)
    finally:
        s.close()


# --------------------------------------------- CLI task=serve + speculation

@pytest.fixture(scope="module")
def trained_draft(trained_lm):
    """A smaller 1-layer draft LM trained over the same token shards —
    the serve_draft_model snapshot for the speculative CLI run."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.models import transformer
    tmp_path, _, _ = trained_lm
    net = transformer(vocab=64, seq=32, dim=16, nlayer=1, nhead=2,
                      packed=True)
    conf = tmp_path / "draft_train.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = text
  path_tok = {tmp_path}/c_%d.tok
  tok_count = 2
iter = packseq
  seqlen = 32
iter = end
{net}
batch_size = 4
num_round = 1
model_dir = {tmp_path}/draft_models
save_model = 1
updater = sgd
eta = 0.05
silent = 1
""")
    assert LearnTask().run([str(conf)]) == 0
    return str(tmp_path / "draft_models" / "0001.model")


def test_cli_serve_gen_speculative_end_to_end(trained_lm, trained_draft):
    """task=serve with speculation + chunked prefill + bf16 KV cache
    through the real CLI: retraces stay 0 (every executable AOT-warmed
    — the ISSUE 19 acceptance criterion), the greedy token stream is
    identical to a plain non-speculative run, and the serve_gen record
    carries the acceptance/dispatch telemetry obsv.py renders."""
    import json

    from cxxnet_tpu.main import LearnTask
    tmp_path, net, model = trained_lm
    def conf_text(pred, extra=""):
        return f"""
dev = cpu
task = serve
model_in = {model}
pred = {pred}
iter = text
  path_tok = {tmp_path}/c_%d.tok
  tok_count = 2
iter = packseq
  seqlen = 32
iter = end
{net}
batch_size = 4
serve_gen = 1
decode_slots = 2
decode_max_seqlen = 32
serve_gen_tokens = 6
serve_gen_prompt = 4
serve_clients = 3
silent = 1
{extra}"""

    plain = tmp_path / "spec_plain.conf"
    plain.write_text(conf_text(f"{tmp_path}/plain_out.txt"))
    assert LearnTask().run([str(plain)]) == 0
    spec = tmp_path / "spec_serve.conf"
    spec.write_text(conf_text(f"{tmp_path}/spec_out.txt", f"""
serve_draft_model = {trained_draft}
spec_k = 2
decode_prefill_chunk = 8
decode_kv_dtype = f32
trace_sample = 2
metrics_sink = jsonl:{tmp_path}/spec_metrics.jsonl
"""))
    assert LearnTask().run([str(spec)]) == 0
    # greedy speculative == plain greedy, end to end through the CLI
    assert open(tmp_path / "spec_out.txt").read() \
        == open(tmp_path / "plain_out.txt").read()

    recs = [json.loads(l)
            for l in open(tmp_path / "spec_metrics.jsonl")]
    [gen] = [r for r in recs if r["kind"] == "serve_gen"]
    assert gen["retraces"] == 0          # the acceptance criterion
    assert gen["spec_k"] == 2
    assert gen["verify_calls"] > 0 and gen["draft_steps"] > 0
    assert 0.0 <= gen["acceptance_rate"] <= 1.0
    assert gen["draft_ms"] >= 0.0 and gen["verify_ms"] >= 0.0
    assert gen["prefill_chunk"] == 8 and gen["prefill_chunks"] > 0
    assert gen["footprint"]["draft_bytes"] > 0
    spans = {r["span"] for r in recs if r["kind"] == "span"}
    assert {"draft", "verify", "sample", "request"} <= spans
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-decode")
                or t.name.startswith("cxxnet-serve-gen")]
