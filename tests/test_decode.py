"""Incremental decode (serve/decode.py + StepScheduler — ISSUE 16).

Covers the contracts KV-cached generation stands on: prefill and
single-token step logits are BITWISE equal to the O(N²) full forward at
f32 (the property that makes the cache safe to enable); the two AOT
executables never retrace after warmup, asserted through the real
task=serve CLI; the step scheduler admits requests into the in-flight
batch BETWEEN decode steps (continuous batching) and degrades to
request-level batching under ``continuous=False``; a runner exception
latches the scheduler dead and reaches every client (no hangs); and
sampling off the LM head is deterministic per request seed.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.serve.batcher import ServeClosed, StepScheduler
from cxxnet_tpu.serve.decode import DecodeEngine, sample_token


# ------------------------------------------------------------ engine parity

@pytest.fixture(scope="module")
def lm_trainer():
    from cxxnet_tpu.models import transformer
    from __graft_entry__ import _make_trainer
    return _make_trainer(
        transformer(vocab=64, seq=32, dim=32, nlayer=2, nhead=2),
        2, "cpu", extra=[("updater", "sgd"), ("eta", "0.01"),
                         ("eval_train", "0"), ("silent", "1")])


@pytest.fixture(scope="module")
def engine(lm_trainer):
    eng = DecodeEngine(lm_trainer, slots=2, max_seqlen=32)
    eng.warmup()
    return eng


def _prompt(n, seed=0, vocab=64):
    return np.random.RandomState(seed).randint(0, vocab, n) \
        .astype(np.int32)


def test_prefill_matches_full_forward_bitwise(engine):
    """Prefill logits at the last prompt position are byte-identical to
    the cache-free eval forward: capture is a tee, not a rewrite."""
    for L in (1, 5, 17, 32):
        p = _prompt(L, seed=L)
        inc = engine.prefill(0, p)
        full = engine.full_logits(p)
        assert inc.dtype == np.float32
        assert np.array_equal(inc, full[L - 1]), f"prompt len {L}"


def test_incremental_steps_match_full_forward_bitwise(engine):
    """Greedy decode through the cache: every step's logits row equals
    the full forward over the grown sequence, bitwise at f32 — masked
    cache positions softmax to exactly 0.0 and drop out of the p·V
    reduction, so stale garbage in unwritten slots is invisible."""
    p = list(_prompt(6, seed=42))
    logits = engine.prefill(1, np.asarray(p, np.int32))
    seq = list(p) + [int(np.argmax(logits))]
    for _ in range(8):
        pos = len(seq) - 1
        step = engine.step(np.asarray([0, seq[-1]], np.int32),
                           np.asarray([0, pos], np.int32))
        full = engine.full_logits(np.asarray(seq, np.int32))
        assert np.array_equal(step[1], full[pos])
        seq.append(int(np.argmax(step[1])))
    assert engine.retraces == 0


def test_engine_zero_retrace_and_footprint(engine):
    """Mixed prefill/step traffic after warmup: zero retraces, and the
    footprint's kv_cache_bytes matches the analytic sizing the lint
    rule uses (2 · layers · slots · nhead · seqlen · head_dim · 4)."""
    for L in (3, 9, 30):
        engine.prefill(L % 2, _prompt(L, seed=L))
        engine.step(np.zeros(2, np.int32),
                    np.asarray([L, 0], np.int32))
    assert engine.retraces == 0
    fp = engine.footprint()
    if fp:  # backend memory_analysis is optional
        assert fp["kv_cache_bytes"] == engine.kv_cache_bytes()
        assert fp["buckets"] == 2
        assert fp["total_bytes"] >= fp["weight_bytes"]
    assert engine.kv_cache_bytes() \
        == 2 * 2 * 2 * engine.nhead * 32 * engine.head_dim * 4


def test_engine_validation(engine, lm_trainer):
    with pytest.raises(ValueError, match="decode_max_seqlen"):
        DecodeEngine(lm_trainer, slots=2, max_seqlen=64)
    with pytest.raises(ValueError, match="prompt of 33"):
        engine.prefill(0, _prompt(33))
    with pytest.raises(ValueError, match="slot 7"):
        engine.prefill(7, _prompt(4))


def test_engine_rejects_bidirectional_attention():
    from cxxnet_tpu.models import transformer
    from __graft_entry__ import _make_trainer
    t = _make_trainer(
        transformer(vocab=16, seq=8, dim=8, nlayer=1, nhead=1, causal=0),
        1, "cpu", extra=[("updater", "sgd"), ("eta", "0.01"),
                         ("eval_train", "0"), ("silent", "1")])
    with pytest.raises(ValueError, match="causal"):
        DecodeEngine(t, slots=1)


# ---------------------------------------------------------------- sampling

def test_sample_token_modes():
    logits = np.array([0.1, 3.0, -1.0, 2.9], np.float32)
    assert sample_token(logits, "greedy") == 1
    # topk=1 degenerates to argmax no matter the rng draw
    rng = np.random.RandomState(0)
    assert sample_token(logits, "topk", topk=1, rng=rng) == 1
    # topk support restriction: ids outside the top-2 never sampled
    rng = np.random.RandomState(1)
    draws = {sample_token(logits, "topk", temp=2.0, topk=2, rng=rng)
             for _ in range(64)}
    assert draws <= {1, 3}
    # temperature sampling is deterministic per rng state
    a = sample_token(logits, "temperature", temp=1.5,
                     rng=np.random.RandomState(7))
    b = sample_token(logits, "temperature", temp=1.5,
                     rng=np.random.RandomState(7))
    assert a == b
    with pytest.raises(ValueError, match="serve_gen_sample"):
        sample_token(logits, "nucleus")


# ------------------------------------------------- scheduler (fake runner)
# A fake runner keeps these pure thread-protocol tests: no jax, no model.
# Logits are rigged so greedy always emits token (slot + 1) — never the
# eos (0), so generation length is controlled by max_new_tokens alone.

class FakeRunner:
    def __init__(self, slots=2, max_seqlen=64, step_sleep=0.004,
                 fail_after=None):
        self.slots = slots
        self.max_seqlen = max_seqlen
        self.step_sleep = step_sleep
        self.fail_after = fail_after
        self.prefill_log = []            # (slot, prompt_len)
        self.step_actives = []           # tuple of active slots per step
        self.lock = threading.Lock()

    def _logits(self, slot):
        row = np.zeros(8, np.float32)
        row[slot + 1] = 1.0
        return row

    def prefill(self, slot, tokens):
        with self.lock:
            self.prefill_log.append((slot, len(tokens)))
        return self._logits(slot)

    def step(self, tokens, positions):
        with self.lock:
            self.step_actives.append(
                tuple(int(i) for i in np.nonzero(positions)[0]))
            if self.fail_after is not None \
                    and len(self.step_actives) > self.fail_after:
                raise RuntimeError("device fell over")
        time.sleep(self.step_sleep)
        return np.stack([self._logits(s) for s in range(self.slots)])


def _submit_async(sched, prompt, max_new):
    out = {}

    def run():
        try:
            out["tokens"] = sched.submit(prompt, max_new)
        except BaseException as e:  # noqa: BLE001 — asserted by tests
            out["error"] = e
        out["done_at"] = time.perf_counter()

    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th, out


def _wait(pred, timeout=5.0):
    t0 = time.perf_counter()
    while not pred():
        assert time.perf_counter() - t0 < timeout, "test timed out"
        time.sleep(0.002)


def test_scheduler_joins_and_leaves_between_steps():
    """Continuous batching: a request submitted mid-flight joins the
    active batch between steps, a short one finishes and frees its slot
    while the long one keeps decoding, and the freed slot is REUSED by
    the next admission — no head-of-line blocking."""
    fr = FakeRunner(slots=2)
    s = StepScheduler(fr, max_new_tokens=40, eos=0, queue_depth=8)
    s.start()
    try:
        prompt = np.arange(1, 4, dtype=np.int32)
        ta, a = _submit_async(s, prompt, 40)
        _wait(lambda: len(fr.step_actives) >= 2)
        tb, b = _submit_async(s, prompt, 3)
        tb.join(5.0)
        assert b["tokens"] is not None and len(b["tokens"]) == 3
        assert "error" not in b
        assert ta.is_alive()  # B finished while A still decodes
        # B rode the same batch as A for at least one step
        assert any(len(act) == 2 for act in fr.step_actives)
        slot_b = fr.prefill_log[1][0]
        # the freed slot is immediately reusable: C lands on B's slot
        tc, c = _submit_async(s, prompt, 2)
        tc.join(5.0)
        assert len(c["tokens"]) == 2
        assert fr.prefill_log[2][0] == slot_b
        ta.join(10.0)
        assert len(a["tokens"]) == 40
    finally:
        s.close()
    st = s.stats()
    assert st["requests"] == 3 and st["prefills"] == 3
    assert st["tokens"] == 45
    assert st["batching"] == "continuous"
    # every step is histogrammed; tokens = prefill samples + step samples
    assert sum(st["occupancy_hist"].values()) == st["steps"]
    assert sum(int(k) * v for k, v in st["occupancy_hist"].items()) \
        == st["tokens"] - st["prefills"]
    assert st["tok_p50_ms"] <= st["tok_p95_ms"] <= st["tok_p99_ms"]


def test_scheduler_request_mode_runs_batch_to_completion():
    """continuous=False is the A/B baseline: a request submitted after
    the batch started stepping waits for the WHOLE batch to drain —
    the head-of-line blocking --lm-serve measures against."""
    fr = FakeRunner(slots=2)
    s = StepScheduler(fr, max_new_tokens=40, eos=0, continuous=False,
                      queue_depth=8)
    s.start()
    try:
        prompt = np.arange(1, 4, dtype=np.int32)
        ta, a = _submit_async(s, prompt, 12)
        _wait(lambda: len(fr.step_actives) >= 2)
        tb, b = _submit_async(s, prompt, 2)
        ta.join(10.0)
        tb.join(10.0)
        assert len(a["tokens"]) == 12 and len(b["tokens"]) == 2
        # B never joined A's in-flight batch...
        assert all(len(act) == 1 for act in fr.step_actives)
        # ...and despite being 6x shorter, finished after A (blocked)
        assert b["done_at"] > a["done_at"]
    finally:
        s.close()
    assert s.stats()["batching"] == "request"


def test_scheduler_exception_reaches_all_clients():
    """A runner exception latches the scheduler dead and fans out to
    every active AND later request — clients get the error, never a
    hang (the MicroBatcher discipline at step granularity)."""
    fr = FakeRunner(slots=2, fail_after=3)
    s = StepScheduler(fr, max_new_tokens=40, eos=0, queue_depth=8)
    s.start()
    try:
        prompt = np.arange(1, 4, dtype=np.int32)
        ta, a = _submit_async(s, prompt, 30)
        tb, b = _submit_async(s, prompt, 30)
        ta.join(5.0)
        tb.join(5.0)
        assert not ta.is_alive() and not tb.is_alive()
        assert isinstance(a["error"], RuntimeError)
        assert isinstance(b["error"], RuntimeError)
        with pytest.raises(RuntimeError, match="device fell over"):
            s.submit(prompt, 2)
    finally:
        s.close()


def test_scheduler_rejects_oversize_prompt_and_close():
    fr = FakeRunner(slots=1, max_seqlen=4)
    s = StepScheduler(fr, max_new_tokens=4, eos=0)
    s.start()
    with pytest.raises(ValueError, match="cache holds"):
        s.submit(np.arange(5, dtype=np.int32))
    s.close()
    s.close()  # idempotent
    with pytest.raises(ServeClosed):
        s.submit(np.asarray([1], np.int32))
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-decode")]


# --------------------------------------------- scheduler over the real engine

def test_continuous_batching_matches_serial_greedy(engine):
    """Concurrent mixed-length generation through the step scheduler is
    token-identical to serial single-slot greedy decoding: slot
    placement, join order, and batch composition never leak into the
    sampled sequences (the bitwise-parity property, end to end)."""
    prompts = [_prompt(3 + (i % 5), seed=100 + i) for i in range(6)]
    lens = [4 + (i % 3) for i in range(6)]

    def serial(p, n):
        logits = engine.prefill(0, p)
        seq = [int(np.argmax(logits))]
        pos = len(p)
        while len(seq) < n:
            step = engine.step(np.asarray([seq[-1], 0], np.int32),
                               np.asarray([pos, 0], np.int32))
            seq.append(int(np.argmax(step[0])))
            pos += 1
        return seq

    want = [serial(p, n) for p, n in zip(prompts, lens)]
    s = StepScheduler(engine, max_new_tokens=8, eos=-1, queue_depth=8)
    s.start()
    got = [None] * 6
    try:
        def client(i):
            got[i] = s.submit(prompts[i], lens[i])

        ths = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(6)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
    finally:
        s.close()
    assert got == want
    assert engine.retraces == 0


# ------------------------------------------------------------- CLI task=serve

@pytest.fixture(scope="module")
def trained_lm(tmp_path_factory):
    """A 1-layer LM trained for one round over a synthetic packed
    corpus — the snapshot + token shards the serve_gen CLI run loads."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.models import transformer
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from make_synth_text import gen_docs
    from cxxnet_tpu.io.text import write_token_shard
    tmp_path = tmp_path_factory.mktemp("decode_cli")
    docs = gen_docs(60, vocab=64, mean_len=24, seed=3)
    for sh in range(2):
        write_token_shard(str(tmp_path / f"c_{sh}.tok"),
                          docs[sh::2], itemsize=2)
    net = transformer(vocab=64, seq=32, dim=32, nlayer=1, nhead=2,
                      packed=True)
    conf = tmp_path / "train.conf"
    conf.write_text(f"""
dev = cpu
data = train
iter = text
  path_tok = {tmp_path}/c_%d.tok
  tok_count = 2
iter = packseq
  seqlen = 32
iter = end
{net}
batch_size = 4
num_round = 1
model_dir = {tmp_path}/models
save_model = 1
updater = sgd
eta = 0.05
silent = 1
""")
    assert LearnTask().run([str(conf)]) == 0
    return tmp_path, net, str(tmp_path / "models" / "0001.model")


def test_cli_serve_gen_end_to_end(trained_lm):
    """task=serve + serve_gen=1 through the real CLI: every pred-stream
    prompt gets its generated ids in name_pred, the serve_gen record
    lands with ZERO retraces (the two-executable contract under real
    concurrent traffic), per-token/per-request latency records carry
    percentiles, and the prefill/decode/sample span stages ride the
    request traces — the ISSUE 16 acceptance run."""
    import json

    from cxxnet_tpu.main import LearnTask
    tmp_path, net, model = trained_lm
    conf = tmp_path / "serve_gen.conf"
    conf.write_text(f"""
dev = cpu
task = serve
model_in = {model}
pred = {tmp_path}/gen_out.txt
iter = text
  path_tok = {tmp_path}/c_%d.tok
  tok_count = 2
iter = packseq
  seqlen = 32
iter = end
{net}
batch_size = 4
serve_gen = 1
decode_slots = 2
decode_max_seqlen = 32
serve_gen_tokens = 5
serve_gen_prompt = 4
serve_clients = 3
trace_sample = 2
silent = 1
metrics_sink = jsonl:{tmp_path}/gen_metrics.jsonl
""")
    assert LearnTask().run([str(conf)]) == 0
    lines = open(tmp_path / "gen_out.txt").read().splitlines()
    assert lines, "no generations written"
    for ln in lines:
        toks = [int(x) for x in ln.split()]
        assert 1 <= len(toks) <= 5
        assert all(0 <= t < 64 for t in toks)

    recs = [json.loads(l) for l in open(tmp_path / "gen_metrics.jsonl")]
    [gen] = [r for r in recs if r["kind"] == "serve_gen"]
    assert gen["retraces"] == 0          # the acceptance criterion
    assert gen["requests"] == len(lines)
    assert gen["tokens"] == sum(len(l.split()) for l in lines)
    assert gen["tokens_per_sec"] > 0
    assert gen["slots"] == 2 and gen["max_seqlen"] == 32
    assert gen["batching"] == "continuous"
    assert sum(gen["occupancy_hist"].values()) == gen["steps"]
    assert gen["footprint"]["kv_cache_bytes"] > 0
    lat = {r["op"]: r for r in recs if r["kind"] == "latency"}
    assert {"token", "gen"} <= set(lat)
    for op in ("token", "gen"):
        assert lat[op]["count"] > 0
        assert 0 < lat[op]["p50"] <= lat[op]["p95"] <= lat[op]["p99"]
    spans = [r for r in recs if r["kind"] == "span"]
    kinds = {r["span"] for r in spans}
    assert {"prefill", "decode", "sample", "request"} <= kinds
    # decode/sample spans fan out over the riders they stepped for
    riders = [r for r in spans if r["span"] in ("decode", "sample")]
    assert riders and all(r["riders"] for r in riders)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cxxnet-decode")
                or t.name.startswith("cxxnet-serve-gen")]
