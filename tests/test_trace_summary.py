"""Shared xplane trace parser (cxxnet_tpu/monitor/trace.py) and the
tools/trace_summary.py CLI, against the checked-in minimal fixture
(tests/fixtures/minimal.xplane.pb: one TPU plane with an XLA Modules
line [jit_step 5 ms] and an XLA Ops line [fusion.1 x2 = 1.5 ms,
copy.2 0.2 ms, convolution.3 3.0 ms], plus a host plane that the
default filters must exclude)."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.monitor.trace import (device_total_ms, find_xplane,
                                      op_totals, parse_xspace, top_ops)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "minimal.xplane.pb")


def test_parse_planes_and_metadata():
    planes = parse_xspace(FIXTURE)
    assert [p.name for p in planes] == ["/device:TPU:0", "/host:CPU"]
    tpu = planes[0]
    assert [l.name for l in tpu.lines] == ["XLA Modules", "XLA Ops"]
    assert tpu.event_names == {1: "fusion.1", 2: "copy.2",
                               3: "convolution.3", 4: "jit_step"}


def test_device_total_and_op_totals():
    assert device_total_ms(FIXTURE) == pytest.approx(5.0)
    totals = op_totals(FIXTURE)
    assert totals == {"fusion.1": (pytest.approx(1.5), 2),
                      "copy.2": (pytest.approx(0.2), 1),
                      "convolution.3": (pytest.approx(3.0), 1)}
    # the host plane is excluded by the TPU filter but reachable
    assert device_total_ms(FIXTURE, plane_filter="CPU",
                           line_filter="XLA Ops") == pytest.approx(7.0)


def test_top_ops_ranking():
    assert [(n, round(ms, 3)) for n, ms, _ in top_ops(FIXTURE, k=2)] == \
        [("convolution.3", 3.0), ("fusion.1", 1.5)]


def test_find_xplane_dir_and_missing(tmp_path):
    sub = tmp_path / "a" / "b"
    sub.mkdir(parents=True)
    dst = sub / "t.xplane.pb"
    dst.write_bytes(open(FIXTURE, "rb").read())
    assert find_xplane(str(tmp_path)) == str(dst)
    with pytest.raises(FileNotFoundError):
        find_xplane(str(tmp_path / "empty-nothing"))


def test_parser_agrees_with_tensorflow_proto():
    """The pure-python wire decoder reads exactly what the canonical
    proto implementation reads (skipped where TF is absent)."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")
    xs = xplane_pb2.XSpace()
    xs.ParseFromString(open(FIXTURE, "rb").read())
    ref = 0.0
    for plane in xs.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            if "XLA Modules" not in line.name:
                continue
            for ev in line.events:
                ref += ev.duration_ps / 1e9
    assert device_total_ms(FIXTURE) == pytest.approx(ref)


def test_cli_table_and_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_summary.py"),
         FIXTURE, "--top", "2"],
        check=True, capture_output=True, text=True, cwd=REPO).stdout
    assert "device total" in out and "5.000 ms" in out
    assert "convolution.3" in out and "fusion.1" in out
    assert "copy.2" not in out  # below top-2, reported as dropped
    assert "1 more ops" in out
    js = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_summary.py"),
         FIXTURE, "--json"],
        check=True, capture_output=True, text=True, cwd=REPO).stdout
    payload = json.loads(js)
    assert payload["device_total_ms"] == 5.0
    assert payload["top_ops"][0] == {"op": "convolution.3",
                                     "total_ms": 3.0, "count": 1}


def test_cli_missing_trace_errors(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_summary.py"),
         str(tmp_path)], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    assert "no *.xplane.pb" in r.stderr


def test_bench_shares_parser(tmp_path):
    """bench.py's device-time path reads through the same module."""
    import bench
    sub = tmp_path / "plugins"
    sub.mkdir()
    (sub / "x.xplane.pb").write_bytes(open(FIXTURE, "rb").read())
    assert bench._trace_device_ms(str(tmp_path)) == pytest.approx(5.0)


def test_bench_emits_sink_record(tmp_path):
    import bench
    sink = tmp_path / "bench.jsonl"
    payload = bench.baseline_json(1234.5, {"device_step_ms": 42.0})
    bench.emit_bench_record(payload, argv=[f"metrics_sink=jsonl:{sink}"])
    (rec,) = [json.loads(l) for l in open(sink)]
    assert rec["kind"] == "bench"
    assert rec["metric"] == "alexnet_imgs_per_sec_per_chip"
    assert rec["device_step_ms"] == 42.0
    # no spec -> no write
    bench.emit_bench_record(payload, argv=[])
    assert len(open(sink).readlines()) == 1
