"""graftlint: config lint, cross-key rules, jaxpr lint, task=check CLI.

Covers ISSUE 5: the declared-key registry must accept every shipped
example config with zero error-severity findings (the golden guard
against key-registry drift), flag typos with did-you-mean suggestions,
enforce each cross-key rule, and the traced-graph lint must catch the
closure-capture / weak-type / dp-escape bug classes on synthetic nets.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu import engine
from cxxnet_tpu.analysis import conflint, jaxpr_lint, run_check
from cxxnet_tpu.analysis.schema import Finding, did_you_mean
from cxxnet_tpu.layers import base as layer_base
from cxxnet_tpu.layers import registry as layer_registry
from cxxnet_tpu.layers.base import Layer
from cxxnet_tpu.utils.config import parse_config_file, parse_config_string

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "example", "*", "*.conf")))


@pytest.fixture(autouse=True)
def _restore_global_knobs():
    """Engine options are a process-global singleton and strict_config a
    module flag; configs under lint set both — restore around each test."""
    snap = engine.snapshot()
    strict = layer_base.strict_config_enabled()
    yield
    for k, v in snap.items():
        setattr(engine.opts, k, v)
    layer_base.set_strict_config(strict)


def errors(findings):
    return [f for f in findings if f.severity == "error"]


def by_key(findings, key):
    return [f for f in findings if f.key == key]


# ------------------------------------------------------------ golden guard

def test_examples_exist():
    assert len(EXAMPLES) >= 9  # the shipped zoo


@pytest.mark.parametrize("conf", EXAMPLES, ids=[os.path.basename(c)
                                                for c in EXAMPLES])
def test_example_configs_lint_clean(conf):
    """Every shipped config must pass the static lint with zero
    error-severity findings — key-registry drift fails here first."""
    findings = conflint.lint_pairs(parse_config_file(conf), path=conf)
    assert not errors(findings), \
        "\n".join(f.format() for f in findings)


def test_mnist_full_check_including_trace():
    """run_check with tracing on the MNIST MLP: exits clean in seconds,
    on CPU, with no data files present."""
    pairs = parse_config_file(os.path.join(REPO, "example/MNIST/MNIST.conf"))
    findings, code = run_check(pairs, trace=True)
    assert code == 0, "\n".join(f.format() for f in findings)
    assert any(f.scope == "jaxpr" and "traced train step" in f.message
               for f in findings)


# ------------------------------------------------------- typo suggestions

def test_global_typo_gets_suggestion_and_error():
    pairs = parse_config_string("batch_size = 8\ndp_buckt_mb = 8\n")
    findings = conflint.lint_pairs(pairs)
    bad = by_key(findings, "dp_buckt_mb")
    assert bad and bad[0].severity == "error"
    assert bad[0].suggestion == "dp_bucket_mb"


def test_layer_section_typo_gets_suggestion():
    pairs = parse_config_string(
        "netconfig=start\n"
        "layer[+1] = conv\n"
        "  nchanel = 32\n"
        "  kernel_size = 3\n"
        "netconfig=end\n"
        "input_shape = 3,8,8\nbatch_size = 4\n")
    findings = conflint.lint_pairs(pairs)
    bad = by_key(findings, "nchanel")
    assert bad and bad[0].severity == "error"
    assert bad[0].suggestion == "nchannel"
    assert bad[0].scope.startswith("layer:conv")


def test_iterator_section_typo_and_misplaced_key():
    pairs = parse_config_string(
        "data = train\n"
        "iter = mnist\n"
        "  path_imgg = x.gz\n"      # typo -> error + suggestion
        "  buffer_size = 4\n"       # threadbuffer key in an mnist chain
        "iter = end\n")
    findings = conflint.lint_pairs(pairs)
    typo = by_key(findings, "path_imgg")
    assert typo and typo[0].severity == "error"
    assert typo[0].suggestion == "path_img"
    misplaced = by_key(findings, "buffer_size")
    assert misplaced and misplaced[0].severity == "warn"


def test_unknown_layer_and_iterator_types():
    pairs = parse_config_string(
        "data = train\niter = mnsit\niter = end\n"
        "netconfig=start\nlayer[+1] = fullcc\nnetconfig=end\n")
    findings = conflint.lint_pairs(pairs)
    assert any(f.severity == "error" and f.suggestion == "mnist"
               for f in by_key(findings, "iter"))
    layer_errs = [f for f in findings if "unknown layer type" in f.message]
    assert layer_errs and layer_errs[0].suggestion == "fullc"


def test_did_you_mean_thresholds():
    assert did_you_mean("dp_buckt_mb", ["dp_bucket_mb", "x"]) \
        == "dp_bucket_mb"
    assert did_you_mean("zzzzzz", ["dp_bucket_mb"]) == ""


# --------------------------------------------------------- value checking

def test_type_violation_is_error():
    findings = conflint.lint_pairs(
        parse_config_string("batch_size = lots\n"))
    bad = by_key(findings, "batch_size")
    assert bad and bad[0].severity == "error"


def test_enum_violation_is_error():
    findings = conflint.lint_pairs(
        parse_config_string("pool_bwd = zzz\n"))
    bad = by_key(findings, "pool_bwd")
    assert bad and bad[0].severity == "error"


def test_range_violation_is_warn():
    pairs = parse_config_string(
        "netconfig=start\n"
        "layer[+1] = fullc\n  nhidden = 4\n"
        "layer[+0] = dropout\n  threshold = 1.5\n"
        "netconfig=end\ninput_shape = 1,1,4\nbatch_size = 2\n")
    findings = conflint.lint_pairs(pairs)
    bad = by_key(findings, "threshold")
    assert bad and bad[0].severity == "warn"


def test_bad_metric_name_is_error():
    findings = conflint.lint_pairs(parse_config_string("metric = errr\n"))
    assert errors(by_key(findings, "metric"))


# -------------------------------------------------------- cross-key rules

def test_rule_monitor_disables_multi_step():
    findings = conflint.lint_pairs(
        parse_config_string("monitor = 1\nmulti_step = 4\n"))
    assert any("grouping will be disabled" in f.message
               for f in by_key(findings, "multi_step"))


def test_rule_multi_step_needs_update_period_one():
    findings = conflint.lint_pairs(
        parse_config_string("multi_step = 4\nupdate_period = 2\n"))
    assert any("update_period = 1" in f.message
               for f in by_key(findings, "multi_step"))


def test_rule_dp_overlap_fallback_combos():
    findings = conflint.lint_pairs(
        parse_config_string("dp_overlap = 1\nbatch_split = 2\n"
                            "batch_size = 8\n"))
    assert any("fall back" in f.message
               for f in by_key(findings, "dp_overlap"))


def test_rule_dp_reduce_at_apply_needs_accumulation():
    findings = conflint.lint_pairs(
        parse_config_string("dp_overlap = 1\ndp_reduce_at = apply\n"))
    assert any("update_period > 1" in f.message
               for f in by_key(findings, "dp_reduce_at"))
    # with accumulation configured the rule stays quiet
    quiet = conflint.lint_pairs(
        parse_config_string("dp_overlap = 1\ndp_reduce_at = apply\n"
                            "update_period = 4\n"))
    assert not by_key(quiet, "dp_reduce_at")


def test_mesh_unknown_axis_errors_with_suggestion():
    """mesh axis names are validated at parse (MeshSpec.parse): a typo'd
    axis is a value error with a did-you-mean suggestion."""
    findings = conflint.lint_pairs(
        parse_config_string("mesh = data:2,modle:2\n"))
    ms = errors(by_key(findings, "mesh"))
    assert ms and any("model" in f.message for f in ms)


def test_rule_mesh_axis_product_vs_device_count():
    findings = conflint.lint_pairs(
        parse_config_string("mesh = data:2,model:2\ndev = cpu:0-2\n"))
    assert any("needs 4 device" in f.message
               for f in errors(by_key(findings, "mesh")))
    quiet = conflint.lint_pairs(
        parse_config_string("mesh = data:2,model:2\ndev = cpu:0-3\n"
                            "fullc_gather = 1\n"))
    assert not errors(by_key(quiet, "mesh"))
    # dev without explicit ids (dev = tpu): count unknowable, no finding
    quiet2 = conflint.lint_pairs(
        parse_config_string("mesh = data:2,model:2\ndev = tpu\n"
                            "fullc_gather = 1\n"))
    assert not errors(by_key(quiet2, "mesh"))


def test_rule_mesh_batch_divisibility():
    findings = conflint.lint_pairs(
        parse_config_string("mesh = data:4\nbatch_size = 10\n"))
    assert any("not divisible by the data axis" in f.message
               for f in errors(by_key(findings, "mesh")))
    quiet = conflint.lint_pairs(
        parse_config_string("mesh = data:4\nbatch_size = 16\n"))
    assert not errors(by_key(quiet, "mesh"))


def test_rule_mesh_dead_model_axis_info():
    findings = conflint.lint_pairs(
        parse_config_string("mesh = data:2,model:2\n"))
    assert any("shards nothing" in f.message
               for f in by_key(findings, "mesh"))
    quiet = conflint.lint_pairs(
        parse_config_string("mesh = data:2,model:2\nfullc_gather = 1\n"))
    assert not any("shards nothing" in f.message
                   for f in by_key(quiet, "mesh"))


def test_rule_dp_overlap_mesh_combos():
    """The dp_overlap x mesh interaction surfaces at check time instead
    of the trainer's trace-time warn-once fallback: seq/expert/pipe
    axes warn (fallback), a 1-wide data axis warns, a model axis with
    deferred reduction gets the step-semantics info, and the supported
    data x model combination stays quiet."""
    f1 = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = data:2,seq:2\n"))
    assert any("fall back" in f.message
               for f in by_key(f1, "dp_overlap"))
    f2 = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = model:4\nfullc_gather = 1\n"))
    assert any("no data axis" in f.message
               for f in by_key(f2, "dp_overlap"))
    f3 = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = data:2,model:2\nfullc_gather = 1\n"
        "update_period = 2\ndp_reduce_at = apply\n"))
    assert any("every micro-step" in f.message
               for f in by_key(f3, "dp_reduce_at"))
    f4 = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = data:2,model:2\n"
        "netconfig=start\nlayer[+1] = moe\n  num_expert = 4\n"
        "  nhidden = 8\nnetconfig=end\ninput_shape = 1,1,8\n"))
    assert any("hosts the experts" in f.message
               for f in by_key(f4, "dp_overlap"))
    quiet = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = data:2,model:2\nfullc_gather = 1\n"))
    assert not by_key(quiet, "dp_overlap")


def test_rule_pipe_axis_needs_multi_stage_net():
    """A pipe axis with a net too shallow to cut into that many stages
    warns; a config with no netconfig block warns too (ISSUE 14
    satellite, ahead of the 1F1B graduation)."""
    shallow = conflint.lint_pairs(parse_config_string(
        "mesh = pipe:4\ndev = cpu:0-3\n"
        "netconfig=start\nlayer[+1] = fullc\n  nhidden = 4\n"
        "netconfig=end\ninput_shape = 1,1,8\nbatch_size = 4\n"))
    assert any("pipeline stages" in f.message
               for f in by_key(shallow, "mesh"))
    nonet = conflint.lint_pairs(parse_config_string(
        "mesh = pipe:2\ndev = cpu:0-1\n"))
    assert any("nothing to cut into stages" in f.message
               for f in by_key(nonet, "mesh"))
    deep = conflint.lint_pairs(parse_config_string(
        "mesh = pipe:2\ndev = cpu:0-1\n"
        "netconfig=start\n"
        "layer[+1] = fullc\n  nhidden = 8\nlayer[+1] = relu\n"
        "layer[+1] = fullc\n  nhidden = 4\nlayer[+0] = softmax\n"
        "netconfig=end\ninput_shape = 1,1,8\nbatch_size = 4\n"))
    assert not any("stages" in f.message for f in by_key(deep, "mesh"))


def test_rule_pipe_with_dp_overlap_gpipe_only():
    """dp_overlap x pipe: the gpipe schedule still takes the trainer's
    warn-once fallback (lint info); pipe_schedule = 1f1b COMPOSES
    (bucketed reductions at cooldown grad-ready ticks) and must stay
    quiet — the PR 14 INFO rule retired with the fallback."""
    findings = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = data:2,pipe:2\ndev = cpu:0-3\n"))
    hits = [f for f in by_key(findings, "dp_overlap")
            if "gpipe" in f.message]
    assert hits and hits[0].severity == "info"
    composed = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = data:2,pipe:2\ndev = cpu:0-3\n"
        "pipe_schedule = 1f1b\n"))
    assert not by_key(composed, "dp_overlap")
    # a seq axis still gets the generic fallback WARN, not the info
    seq = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\nmesh = data:2,seq:2\ndev = cpu:0-3\n"))
    assert any(f.severity == "warn" and "fall back" in f.message
               for f in by_key(seq, "dp_overlap"))


def test_rule_pipe_schedule_cross_keys():
    """The 1F1B cross-key rules: microbatch-count divisibility by the
    pipe axis is an error, the defaulted 2*S count must divide the
    batch, a schedule key without a pipe axis warns, and remat x pipe
    gets the interaction note."""
    ragged = conflint.lint_pairs(parse_config_string(
        "mesh = pipe:2\ndev = cpu:0-1\npipe_microbatch = 3\n"
        "batch_size = 6\n"))
    assert any(f.severity == "error" and "staggers" in f.message
               for f in by_key(ragged, "pipe_microbatch"))
    dflt = conflint.lint_pairs(parse_config_string(
        "mesh = pipe:2\ndev = cpu:0-1\nbatch_size = 6\n"))
    assert any(f.severity == "error" and "defaulted" in f.message
               for f in by_key(dflt, "pipe_microbatch"))
    nopipe = conflint.lint_pairs(parse_config_string(
        "mesh = data:2\ndev = cpu:0-1\npipe_schedule = 1f1b\n"))
    assert any(f.severity == "warn" and "no pipe axis" in f.message
               for f in by_key(nopipe, "pipe_schedule"))
    nomesh = conflint.lint_pairs(parse_config_string(
        "pipe_schedule = 1f1b\n"))
    assert any(f.severity == "warn" for f in by_key(nomesh,
                                                    "pipe_schedule"))
    rm = conflint.lint_pairs(parse_config_string(
        "mesh = pipe:2\ndev = cpu:0-1\nremat = 2\n"))
    assert any(f.severity == "info" and "recompute twice" in f.message
               for f in by_key(rm, "remat"))
    clean = conflint.lint_pairs(parse_config_string(
        "mesh = data:2,pipe:2\ndev = cpu:0-3\npipe_schedule = 1f1b\n"
        "pipe_microbatch = 4\nbatch_size = 16\n"))
    assert not by_key(clean, "pipe_microbatch")
    assert not by_key(clean, "pipe_schedule")


def test_rule_dp_reduce_dtype_without_overlap_warns():
    findings = conflint.lint_pairs(
        parse_config_string("dp_reduce_dtype = bf16\n"))
    assert any("silently ignored" in f.message
               for f in by_key(findings, "dp_reduce_dtype"))
    quiet = conflint.lint_pairs(parse_config_string(
        "dp_overlap = 1\ndp_reduce_dtype = bf16\n"))
    assert not by_key(quiet, "dp_reduce_dtype")


def test_rule_monitor_nan_without_monitor():
    findings = conflint.lint_pairs(
        parse_config_string("monitor_nan = fatal\n"))
    assert any("no effect" in f.message
               for f in by_key(findings, "monitor_nan"))


def test_rule_batch_split_divisibility():
    findings = conflint.lint_pairs(
        parse_config_string("batch_size = 10\nbatch_split = 4\n"))
    assert errors(by_key(findings, "batch_split"))


def test_trace_lint_restores_engine_options():
    """One config's engine options must not leak into the next config's
    trace lint (engine.opts is a process-global singleton)."""
    assert engine.opts.dp_overlap == "0"
    pairs = parse_config_string(
        "dp_overlap = 1\nfused_update = 1\n"
        "netconfig=start\n"
        "layer[+1] = fullc\n  nhidden = 4\nlayer[+0] = softmax\n"
        "netconfig=end\ninput_shape = 1,1,8\nbatch_size = 4\n")
    findings, code = run_check(pairs, trace=True)
    assert code == 0, "\n".join(f.format() for f in findings)
    assert engine.opts.dp_overlap == "0"
    assert engine.opts.fused_update == "0"


def test_rule_pallas_ln_bf16_caveat():
    pairs = parse_config_string(
        "dtype = bfloat16\n"
        "netconfig=start\n"
        "layer[+1] = layernorm\n"
        "netconfig=end\ninput_shape = 1,8,16\nbatch_size = 2\n")
    findings = conflint.lint_pairs(pairs)
    notes = by_key(findings, "pallas_ln")
    assert notes and notes[0].severity == "info"
    # no layernorm in the net -> no caveat
    quiet = conflint.lint_pairs(parse_config_string("dtype = bfloat16\n"))
    assert not by_key(quiet, "pallas_ln")
    # pallas_ln = x (the input-saving escape hatch) -> caveat is moot
    escaped = conflint.lint_pairs(parse_config_string(
        "dtype = bfloat16\npallas_ln = x\n"
        "netconfig=start\nlayer[+1] = layernorm\nnetconfig=end\n"
        "input_shape = 1,8,16\nbatch_size = 2\n"))
    assert not by_key(escaped, "pallas_ln")


def test_rule_pred_task_requirements():
    findings = conflint.lint_pairs(parse_config_string("task = pred\n"))
    assert errors(by_key(findings, "pred"))
    assert errors(by_key(findings, "model_in"))


def test_structural_netconfig_error_is_finding():
    pairs = parse_config_string(
        "netconfig=start\n"
        "layer[nosuch->out] = fullc\n  nhidden = 4\n"
        "netconfig=end\ninput_shape = 1,1,4\nbatch_size = 2\n")
    findings = conflint.lint_pairs(pairs)
    assert errors(by_key(findings, "netconfig"))


# -------------------------------------------------- engine.py satellite

def test_engine_unknown_option_raises_valueerror_with_suggestion():
    with pytest.raises(ValueError) as ei:
        engine.set_engine_option("dp_buckt_mb", "8")
    assert "dp_bucket_mb" in str(ei.value)
    assert not isinstance(ei.value, AssertionError)


def test_engine_bad_value_raises_valueerror():
    with pytest.raises(ValueError):
        engine.set_engine_option("pool_bwd", "zzz")


# ------------------------------------------------------------- jaxpr lint

class _BigConstLayer(Layer):
    """Deliberate closure-capture bug: a >1 MiB array baked into forward."""

    type_names = ("bigconst_test",)

    def __init__(self):
        super().__init__()
        self._big = np.ones((512, 600), np.float32)  # 1.2 MiB

    def infer_shapes(self, in_shapes):
        return [in_shapes[0]]

    def forward(self, params, buffers, inputs, ctx):
        x = inputs[0]
        return [x + jnp.asarray(self._big).sum() * 0], buffers


class _WeakParamLayer(Layer):
    """Weak-typed param leaf (built from a bare python scalar)."""

    type_names = ("weakparam_test",)

    def infer_shapes(self, in_shapes):
        return [in_shapes[0]]

    def init_params(self, key, in_shapes, dtype=jnp.float32):
        return {"bias": jnp.asarray(0.5)}

    def forward(self, params, buffers, inputs, ctx):
        return [inputs[0] + params["bias"]], buffers


@pytest.fixture
def _test_layers():
    layer_registry.register(_BigConstLayer)
    layer_registry.register(_WeakParamLayer)
    yield
    for cls in (_BigConstLayer, _WeakParamLayer):
        for name in cls.type_names:
            layer_registry._REGISTRY.pop(name, None)
    from cxxnet_tpu.analysis import registry as areg
    areg.layer_scope.cache_clear()


def _tiny_trainer(body_layer):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    net = NetTrainer()
    for k, v in parse_config_string(
            "netconfig=start\n"
            f"layer[+1] = {body_layer}\n"
            "layer[+1] = fullc\n  nhidden = 4\n"
            "layer[+0] = softmax\n"
            "netconfig=end\n"
            "input_shape = 1,1,8\nbatch_size = 4\ndev = cpu\nsilent = 1\n"):
        net.set_param(k, v)
    net.init_model()
    return net


def test_jaxpr_lint_flags_big_closure_constant(_test_layers):
    findings = jaxpr_lint.lint_trainer(_tiny_trainer("bigconst_test"))
    hits = [f for f in findings
            if f.severity == "error" and "closure-captured" in f.message]
    assert hits, "\n".join(f.format() for f in findings)
    assert "(512, 600)" in hits[0].message


def test_jaxpr_lint_flags_weak_param_leaf(_test_layers):
    findings = jaxpr_lint.lint_trainer(_tiny_trainer("weakparam_test"))
    hits = [f for f in findings if "weak-typed" in f.message]
    assert hits, "\n".join(f.format() for f in findings)


def test_jaxpr_lint_clean_on_plain_net(_test_layers):
    findings = jaxpr_lint.lint_trainer(_tiny_trainer("sigmoid"))
    assert not errors(findings), "\n".join(f.format() for f in findings)
    assert not any("weak-typed" in f.message for f in findings)


def test_jaxpr_lint_flags_f64_promotion():
    from jax.experimental import enable_x64
    with enable_x64():
        closed = jax.make_jaxpr(lambda x: x * 2.0)(np.zeros(3, np.float64))
    findings = jaxpr_lint.jaxpr_findings(closed)
    assert any("float64" in f.message for f in findings)


def test_dp_coverage_findings():
    hits = jaxpr_lint.dp_coverage_findings(["a", "b", "c"], ["a", "c"])
    assert len(hits) == 1 and hits[0].severity == "error"
    assert "'b'" in hits[0].message
    assert not jaxpr_lint.dp_coverage_findings(["a"], ["a"])


# --------------------------------------------------------- strict_config

def test_strict_config_reports_unknown_layer_key(capsys):
    layer_base.set_strict_config(True)
    conflint._reported.clear()
    layer = layer_registry.create_layer("conv")
    layer.set_param("nchanel", "32")       # typo -> warn with suggestion
    layer.set_param("eta", "0.1")          # global broadcast -> silent
    layer.set_param("kernel_size", "3")    # declared -> silent
    err = capsys.readouterr().err
    assert "nchanel" in err and "nchannel" in err
    assert "eta" not in err


def test_strict_config_off_is_silent(capsys):
    layer_base.set_strict_config(False)
    conflint._reported.clear()
    layer = layer_registry.create_layer("conv")
    layer.set_param("nchanel", "32")
    assert "nchanel" not in capsys.readouterr().err


def test_strict_config_retoggle_resets_dedup(capsys):
    """A new net built under a fresh strict_config=1 must warn again for
    the same (type, key) — the dedup window is per toggle, not process-
    lifetime."""
    layer_base.set_strict_config(True)
    layer_registry.create_layer("conv").set_param("nchanel", "1")
    assert "nchanel" in capsys.readouterr().err
    layer_registry.create_layer("conv").set_param("nchanel", "1")
    assert "nchanel" not in capsys.readouterr().err  # deduped
    layer_base.set_strict_config(True)  # new toggle -> fresh window
    layer_registry.create_layer("conv").set_param("nchanel", "1")
    assert "nchanel" in capsys.readouterr().err


def test_strict_config_via_trainer_key():
    from cxxnet_tpu.nnet.trainer import NetTrainer
    net = NetTrainer()
    net.set_param("strict_config", "1")
    assert layer_base.strict_config_enabled()
    net.set_param("strict_config", "0")
    assert not layer_base.strict_config_enabled()


# ----------------------------------------------------------- task=check

def test_task_check_cli_exit_codes(tmp_path, capsys):
    from cxxnet_tpu.main import LearnTask
    conf = os.path.join(REPO, "example/MNIST/MNIST.conf")
    sink = tmp_path / "m.jsonl"
    rc = LearnTask().run(
        [conf, "task=check", "silent=1", f"metrics_sink=jsonl:{sink}"])
    assert rc == 0
    import json
    recs = [json.loads(l) for l in sink.read_text().splitlines()]
    check = [r for r in recs if r["kind"] == "check"]
    assert len(check) == 1 and check[0]["n_error"] == 0
    assert check[0]["config"].endswith("MNIST.conf")

    capsys.readouterr()
    rc = LearnTask().run([conf, "task=check", "silent=1", "dp_buckt_mb=8"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "dp_bucket_mb" in err  # did-you-mean printed


def test_task_check_emits_only_check_record(tmp_path):
    """The check task's traced pass builds a trainer but must NOT open
    the config's telemetry sink for it: a lint is read-only — the only
    record in the stream is the `check` record, never the trainer's
    `run` header (regression: graftlint over example confs with relative
    sink paths used to drop run-header debris into the linter's CWD)."""
    from cxxnet_tpu.main import LearnTask
    conf = os.path.join(REPO, "example/MNIST/MNIST.conf")
    sink = tmp_path / "m.jsonl"
    rc = LearnTask().run(
        [conf, "task=check", "silent=1", f"metrics_sink=jsonl:{sink}"])
    assert rc == 0
    import json
    kinds = [json.loads(l)["kind"] for l in sink.read_text().splitlines()]
    assert kinds == ["check"]


def test_task_check_no_netconfig_skips_trace():
    pairs = parse_config_file(
        os.path.join(REPO, "example/MNIST/MNIST_pred.conf"))
    findings, code = run_check(pairs, trace=True)
    assert code == 0
    assert any("traced-graph lint skipped" in f.message for f in findings)


def test_finding_json_roundtrip():
    f = Finding("error", "k", "msg", suggestion="kk", scope="global")
    d = f.to_dict()
    assert d["severity"] == "error" and d["suggestion"] == "kk"
    assert "error" in f.format() and "kk" in f.format()
