"""Telemetry subsystem tests (cxxnet_tpu/monitor/, doc/monitor.md):

* monitor = 0 leaves the traced train step's HLO unchanged (zero graph
  overhead) and traces none of the monitor code;
* monitor = 1 computes per-layer norms matching host numpy;
* the NaN/inf loss guard warns or fails fast per monitor_nan;
* jit retrace counters increment on forced shape changes;
* the JSONL sink carries the documented record schema end-to-end
  through the CLI driver;
* the step-addressed profiling window writes a trace.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from __graft_entry__ import _make_trainer
from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.monitor import TrainingDiverged
from cxxnet_tpu.nnet.net import iter_param_leaves

TINY_MLP = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,12
metric = error
eta = 0.1
silent = 1
"""


def _batch(n=16, d=12, nclass=4, seed=0, nan=False):
    rnd = np.random.RandomState(seed)
    data = rnd.rand(n, 1, 1, d).astype(np.float32)
    if nan:
        data[0, 0, 0, 0] = np.nan
    return DataBatch(data=data,
                     label=rnd.randint(0, nclass, (n, 1)).astype(np.float32),
                     index=np.arange(n, dtype=np.uint32))


def _lower_text(t, n=16, d=12):
    import jax.numpy as jnp
    import jax
    data = jnp.zeros((n, 1, 1, d), jnp.float32)
    label = jnp.zeros((n, 1), jnp.float32)
    lowered = t._train_step.lower(
        t.params, t.opt_state, t.buffers, data, label, (),
        jnp.int32(0), jax.random.PRNGKey(0))
    return lowered.as_text()


# ------------------------------------------------------------- zero overhead

def test_monitor_off_hlo_unchanged():
    """monitor=0 (explicit or absent) lowers to the identical program:
    telemetry off means zero graph overhead."""
    t_plain = _make_trainer(TINY_MLP, 16, "cpu:0")
    t_off = _make_trainer(TINY_MLP, 16, "cpu:0",
                          extra=[("monitor", "0"), ("monitor_nan", "warn"),
                                 ("metrics_sink", "none")])
    assert _lower_text(t_plain) == _lower_text(t_off)


def test_monitor_off_traces_no_monitor_code(monkeypatch):
    """With monitor=0 the in-graph monitor module is never even called
    at trace time."""
    from cxxnet_tpu.monitor import ingraph

    def boom(*a, **k):
        raise AssertionError("monitor code traced with monitor=0")

    monkeypatch.setattr(ingraph, "group_stats", boom)
    t = _make_trainer(TINY_MLP, 16, "cpu:0")
    t.start_round(1)
    t.update(_batch())
    assert t._last_monitor is None


# ------------------------------------------------------------- norm parity

def test_monitor_norms_match_host_numpy():
    t = _make_trainer(TINY_MLP, 16, "cpu:0",
                      extra=[("monitor", "1"), ("monitor_interval", "0")])
    before = {k: np.asarray(v).astype(np.float64)
              for k, v in iter_param_leaves(t.params)}
    t.start_round(1)
    t.update(_batch())
    after = {k: np.asarray(v).astype(np.float64)
             for k, v in iter_param_leaves(t.params)}
    mon = {k: np.asarray(v) for k, v in t._last_monitor.items()}
    assert set(mon) == set(before)
    for name, (w_norm, g_norm, u_norm) in mon.items():
        np.testing.assert_allclose(
            w_norm, np.linalg.norm(before[name]), rtol=1e-5, atol=1e-7,
            err_msg=f"{name} w_norm")
        np.testing.assert_allclose(
            u_norm, np.linalg.norm(after[name] - before[name]),
            rtol=1e-4, atol=1e-7, err_msg=f"{name} u_norm")
        assert np.isfinite(g_norm) and g_norm >= 0.0, (name, g_norm)
    # the step moved the weights, so at least one grad/update is nonzero
    assert any(v[1] > 0 for v in mon.values())
    assert any(v[2] > 0 for v in mon.values())


# --------------------------------------------------------------- NaN guard

def test_nan_guard_fatal(tmp_path):
    sink = tmp_path / "m.jsonl"
    t = _make_trainer(TINY_MLP, 16, "cpu:0",
                      extra=[("monitor", "1"), ("monitor_interval", "1"),
                             ("monitor_nan", "fatal"), ("eval_train", "0"),
                             ("metrics_sink", f"jsonl:{sink}")])
    t.start_round(1)
    with pytest.raises(TrainingDiverged, match="non-finite loss"):
        t.update(_batch(nan=True))
    # the per-layer norms of the diverged step land in the sink BEFORE
    # the raise — the record of which layer blew up survives the abort
    recs = [json.loads(l) for l in open(sink)]
    kinds = [r["kind"] for r in recs]
    assert "monitor" in kinds and "nan" in kinds
    assert kinds.index("monitor") < kinds.index("nan")


def test_sink_write_failure_disables_not_raises(tmp_path, capsys):
    from cxxnet_tpu.monitor.metrics import MetricsRegistry
    reg = MetricsRegistry()
    reg.configure_sink(f"jsonl:{tmp_path}/m.jsonl")
    reg.sink._fo.close()  # simulate the descriptor dying mid-run
    reg.emit("step", x=1)  # must not raise
    assert reg.sink is None
    assert "telemetry disabled" in capsys.readouterr().err
    reg.emit("step", x=2)  # further emits are clean no-ops


def test_nan_guard_warn_continues(capsys, tmp_path):
    sink = tmp_path / "m.jsonl"
    t = _make_trainer(TINY_MLP, 16, "cpu:0",
                      extra=[("monitor", "1"), ("monitor_interval", "1"),
                             ("monitor_nan", "warn"), ("eval_train", "0"),
                             ("metrics_sink", f"jsonl:{sink}")])
    t.start_round(1)
    t.update(_batch(nan=True))  # must not raise
    assert "non-finite loss" in capsys.readouterr().err
    recs = [json.loads(l) for l in open(sink)]
    nan_recs = [r for r in recs if r["kind"] == "nan"]
    assert nan_recs and nan_recs[0]["action"] == "warn"
    assert t.metrics.counters.get("nonfinite_loss_steps") == 1
    # clean batches keep training afterwards
    t.update(_batch(seed=1))


# ---------------------------------------------------------- retrace counters

def test_retrace_counter_increments_on_shape_change():
    t = _make_trainer(TINY_MLP, 16, "cpu:0", extra=[("eval_train", "0")])
    t.start_round(1)
    t.update(_batch(n=16))
    assert t.metrics.counters["train_step_traces"] == 1
    t.update(_batch(n=16, seed=1))  # same shapes: cached, no retrace
    assert t.metrics.counters["train_step_traces"] == 1
    t.update(_batch(n=8, seed=2))  # forced shape change: silent recompile
    assert t.metrics.counters["train_step_traces"] == 2
    # masked tail batch compiles the separate masked step: counted too
    tail = _batch(n=16, seed=3)
    tail.tail_mask_padd = 4
    t.update(tail)
    assert t.metrics.counters["train_step_traces"] == 3


def test_eval_step_trace_counter():
    t = _make_trainer(TINY_MLP, 16, "cpu:0", extra=[("eval_train", "0")])
    t.start_round(1)
    t.predict_raw(_batch(n=16))
    assert t.metrics.counters["eval_step_traces"] == 1
    t.predict_raw(_batch(n=16, seed=1))
    assert t.metrics.counters["eval_step_traces"] == 1
    t.predict_raw(_batch(n=8, seed=2))
    assert t.metrics.counters["eval_step_traces"] == 2


# ------------------------------------------------------------ JSONL schema

STEP_KEYS = {"ts", "kind", "round", "step", "global_step", "elapsed_sec",
             "examples_per_sec", "iter_wait_sec", "dispatch_sec",
             "h2d_sec", "staging_depth", "loss"}
MONITOR_KEYS = {"ts", "kind", "round", "step", "layer",
                "w_norm", "g_norm", "u_norm", "u_ratio"}
ROUND_KEYS = {"ts", "kind", "round", "wall_sec", "eval_sec", "examples",
              "examples_per_sec", "iter_wait_sec", "dispatch_sec",
              "h2d_sec", "train_step_traces", "eval_step_traces",
              "train-error", "val-error"}
LEDGER_KEYS = {"ts", "kind", "wall_sec", "categories", "shares",
               "goodput_pct", "h2d_overlapped_sec", "rounds",
               "rounds_lost", "rollbacks", "anomalies",
               "nonfinite_steps", "source"}


def _run_cli(tmp_path, extra_cfg="", num_round=2):
    sys.path.insert(0, os.path.dirname(__file__))
    from test_main import MLP_NET, _write_synth_mnist
    from cxxnet_tpu.main import LearnTask
    _write_synth_mnist(tmp_path, n=64)
    conf = tmp_path / "train.conf"
    conf.write_text(f"""
dev = cpu:0
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
eval = val
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
num_round = {num_round}
metric = error
model_dir = {tmp_path}/models
save_model = 0
silent = 1
print_step = 2
{extra_cfg}
""")
    task = LearnTask()
    assert task.run([str(conf)]) == 0
    return task


def test_jsonl_schema_golden(tmp_path):
    sink = tmp_path / "metrics.jsonl"
    _run_cli(tmp_path, extra_cfg=f"""
monitor = 1
monitor_interval = 2
metrics_sink = jsonl:{sink}
""")
    recs = [json.loads(l) for l in open(sink)]
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    assert set(by_kind) == {"run", "compile", "step", "round", "monitor",
                            "ledger"}
    run = by_kind["run"][0]
    assert run["batch_size"] == 16 and run["updater"] == "sgd"
    assert "pool_bwd" in run["engine_opts"]
    (compile_rec,) = by_kind["compile"]
    assert compile_rec["compile_sec"] > 0
    for r in by_kind["step"]:
        assert set(r) == STEP_KEYS, r
        assert r["examples_per_sec"] >= 0
    for r in by_kind["monitor"]:
        assert set(r) == MONITOR_KEYS, r
    # per-layer records cover every param leaf at each monitored step
    layers = {r["layer"] for r in by_kind["monitor"]}
    assert layers == {"00-fc1/wmat", "00-fc1/bias",
                      "02-fc2/wmat", "02-fc2/bias"}
    # the end-of-run goodput ledger is the stream's LAST record and
    # carries the documented schema (doc/monitor.md; the deep fold is
    # covered in tests/test_ledger.py)
    (ledger,) = by_kind["ledger"]
    assert recs[-1]["kind"] == "ledger"
    assert set(ledger) == LEDGER_KEYS, ledger
    assert set(ledger["categories"]) == set(ledger["shares"])
    assert ledger["source"] == "run"
    assert len(by_kind["round"]) == 2
    first, second = by_kind["round"]
    assert set(first) == ROUND_KEYS | {"compile_sec"}, first
    assert set(second) == ROUND_KEYS, second  # compile_sec first round only
    assert first["round"] == 1 and second["round"] == 2
    assert first["examples"] == 64
    # 64 imgs / b16 = 4 steps/round: monitor fired at interval 2
    assert len(by_kind["monitor"]) == 4 * 4  # 4 ticks x 4 param leaves


def test_sink_off_and_monitor_off_no_file(tmp_path):
    """Defaults write nothing and add no monitor state."""
    task = _run_cli(tmp_path, num_round=1)
    assert task.net.metrics.sink is None
    assert task.net._last_monitor is None
    assert [p for p in os.listdir(tmp_path) if p.endswith(".jsonl")] == []


# ------------------------------------------------------- compile_sec window

def test_compile_sec_reported_once(tmp_path):
    sink = tmp_path / "metrics.jsonl"
    task = _run_cli(tmp_path, extra_cfg=f"metrics_sink = jsonl:{sink}\n")
    assert task.compile_sec is not None and task.compile_sec > 0
    recs = [json.loads(l) for l in open(sink)]
    assert sum(r["kind"] == "compile" for r in recs) == 1
    rounds = [r for r in recs if r["kind"] == "round"]
    assert "compile_sec" in rounds[0] and "compile_sec" not in rounds[1]


# ------------------------------------------------------------- prof window

def test_prof_window_step_addressed(tmp_path):
    prof_dir = tmp_path / "prof"
    _run_cli(tmp_path, extra_cfg=f"""
prof = {prof_dir}
prof_start_step = 1
prof_num_steps = 2
""", num_round=1)
    import glob
    assert glob.glob(str(prof_dir / "**" / "*.xplane.pb"), recursive=True)


# ---------------------------------------------------------------- logging

def test_silent_maps_to_log_levels(tmp_path, capsys):
    _run_cli(tmp_path, num_round=1)
    out, err = capsys.readouterr()
    assert "update round" not in out  # silent=1 suppresses chatter
    assert "train-error" in err       # eval lines always reach stderr
    # non-silent: the historical progress lines come back, same format
    from cxxnet_tpu.main import LearnTask
    conf = tmp_path / "train.conf"
    task = LearnTask()
    assert task.run([str(conf), "silent=0", "num_round=1"]) == 0
    out, err = capsys.readouterr()
    assert "update round 0" in out
    assert "examples/sec" in out
    assert "compile:" in out
    assert "train-error" in err


def test_metricset_values_match_print_line():
    from cxxnet_tpu.utils.metric import MetricSet
    ms = MetricSet()
    ms.add_metric("error", "label")
    ms.add_eval([np.array([[0.9, 0.1], [0.2, 0.8]])],
                {"label": np.array([[0.0], [0.0]])})
    vals = ms.values("val")
    assert set(vals) == {"val-error"}
    assert f"val-error:{vals['val-error']:f}" in ms.print_line("val")


# --------------------------- fused_update x update_period > 1 x monitor = 1

FUSED_NET = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 64
  init_sigma = 0.1
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,128
metric = error
updater = adam
eta = 0.01
silent = 1
"""


def _run_fused_monitor(fused: str, n_steps: int = 4):
    """bf16 adam trainer with grad accumulation + the in-graph monitor;
    fc1's wmat (64, 128) = 8192 leaves takes the fused kernel when
    fused_update=1 (fused_adam_supported), fc2 stays on the XLA path —
    the mixed case.  Returns per-step (loss, monitor stats, params)."""
    from cxxnet_tpu import engine
    from cxxnet_tpu.monitor import ingraph
    saved = engine.opts.fused_update
    engine.opts.set("fused_update", fused)
    try:
        t = _make_trainer(FUSED_NET, 8, "cpu", extra=[
            ("dtype", "bfloat16"), ("update_period", "2"),
            ("monitor", "1"), ("monitor_interval", "1000")])
        from cxxnet_tpu.ops import pallas_kernels as pk
        assert pk.fused_adam_supported(t.params["00-fc1"]["wmat"])
        rnd = np.random.RandomState(0)
        t.start_round(1)
        hist = []
        for _ in range(n_steps):
            w_before = np.asarray(t.params["00-fc1"]["wmat"],
                                  np.float32)
            b = DataBatch(
                data=rnd.rand(8, 1, 1, 128).astype(np.float32),
                label=rnd.randint(0, 4, (8, 1)).astype(np.float32),
                index=np.arange(8, dtype=np.uint32))
            t.update(b)
            stats = ingraph.unpack_stats(
                {k: np.asarray(v) for k, v in t._last_monitor.items()})
            w_after = np.asarray(t.params["00-fc1"]["wmat"], np.float32)
            hist.append((float(np.asarray(t._last_loss)), stats,
                         w_before, w_after))
        return hist
    finally:
        engine.opts.set("fused_update", saved)


def test_fused_update_with_accumulation_and_monitor():
    """fused_update=1 x update_period=2 x monitor=1: the fused adam path
    tracks the XLA path under gradient accumulation, and the in-graph
    monitor's ||delta w|| reflects the FUSED apply — zero on non-apply
    micro-steps, equal to the actual parameter delta on apply steps,
    and matching the XLA path's update magnitude."""
    xla = _run_fused_monitor("0")
    fused = _run_fused_monitor("1")
    for (lx, sx, _, _), (lf, sf, _, _) in zip(xla, fused):
        # same forward (bf16 params updated through different lowerings):
        # losses track within bf16 noise
        np.testing.assert_allclose(lf, lx, rtol=0.05, atol=1e-3)
    for i, (loss, stats, w_before, w_after) in enumerate(fused):
        s = stats["00-fc1/wmat"]
        is_apply = (i % 2) == 1  # update_period=2: steps 2, 4 apply
        if not is_apply:
            assert s["u_norm"] == 0.0, \
                f"micro-step {i}: ||dw|| must be 0 before the apply"
            np.testing.assert_array_equal(w_before, w_after)
        else:
            assert s["u_norm"] > 0.0
            actual = float(np.linalg.norm(
                (w_after - w_before).astype(np.float32)))
            np.testing.assert_allclose(
                s["u_norm"], actual, rtol=1e-3,
                err_msg="monitor ||dw|| must reflect the fused apply")
            # update magnitude parity vs the XLA adam path
            np.testing.assert_allclose(
                s["u_norm"], xla[i][1]["00-fc1/wmat"]["u_norm"],
                rtol=0.02)
    # trajectories stay close after the full run (bf16 rounding budget,
    # tolerance per test_pallas fused-adam parity)
    np.testing.assert_allclose(fused[-1][3], xla[-1][3],
                               atol=4e-3, rtol=0)
