"""tools/disclint.py: the repo-discipline AST lint (doc/lint.md).

Unit tests drive each rule over synthetic sources; the tree guard runs
the real CLI over the shipped code and asserts exit 0 — a new discipline
violation (or a regression in the linter itself) fails tier-1 here, the
``tests/test_collect.py`` pattern applied to code discipline.
"""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DISCLINT = os.path.join(REPO, "tools", "disclint.py")

_spec = importlib.util.spec_from_file_location("disclint", DISCLINT)
disclint = importlib.util.module_from_spec(_spec)
sys.modules["disclint"] = disclint  # dataclasses resolve __module__
_spec.loader.exec_module(disclint)


def findings_for(tmp_path, src, name="mod.py"):
    p = tmp_path / name
    p.write_text(src)
    return disclint.lint_file(str(p))


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------ the rules

def test_print_rule(tmp_path):
    hits = findings_for(tmp_path, "print('hello')\n")
    assert rules_of(hits) == ["print"]


def test_atomic_write_rule(tmp_path):
    hits = findings_for(
        tmp_path, "f = open(p, 'wb')\ng = open(p, 'r')\nh = open(p)\n")
    assert rules_of(hits) == ["atomic-write"]
    # keyword-mode and io.open spellings must not evade the gate
    hits = findings_for(
        tmp_path, "import io\n"
                  "f = open(p, mode='w')\n"
                  "g = io.open(p, 'a')\n"
                  "h = open(p, mode='r')\n")
    assert rules_of(hits) == ["atomic-write", "atomic-write"]


def test_mktemp_rule(tmp_path):
    hits = findings_for(
        tmp_path, "import tempfile\np = tempfile.mktemp()\n")
    assert rules_of(hits) == ["mktemp"]


def test_bare_except_and_swallow_rules(tmp_path):
    hits = findings_for(tmp_path, (
        "try:\n    x()\nexcept:\n    pass\n"))
    assert set(rules_of(hits)) == {"bare-except", "swallow"}
    # a narrow except with a pass body is tolerated (cleanup idiom)
    quiet = findings_for(tmp_path, (
        "try:\n    x()\nexcept OSError:\n    pass\n"))
    assert not quiet
    # a broad except that DOES something is tolerated
    quiet = findings_for(tmp_path, (
        "try:\n    x()\nexcept Exception as e:\n    log(e)\n"))
    assert not quiet


def test_thread_exc_rule(tmp_path):
    bad = (
        "import threading\n"
        "def worker():\n    run_forever()\n"
        "t = threading.Thread(target=worker)\n")
    assert rules_of(findings_for(tmp_path, bad)) == ["thread-exc"]
    good = (
        "import threading\n"
        "def worker():\n"
        "    try:\n        run_forever()\n"
        "    except BaseException as e:\n        q.put(e)\n"
        "t = threading.Thread(target=worker)\n")
    assert not findings_for(tmp_path, good)
    # Thread subclass run() without a try is the same contract hole
    sub = (
        "import threading\n"
        "class W(threading.Thread):\n"
        "    def run(self):\n        work()\n")
    assert rules_of(findings_for(tmp_path, sub)) == ["thread-exc"]
    # the from-import spelling must not evade the gate
    bare = (
        "from threading import Thread\n"
        "def worker():\n    run_forever()\n"
        "t = Thread(target=worker)\n")
    assert rules_of(findings_for(tmp_path, bare)) == ["thread-exc"]


def test_warn_once_rule(tmp_path):
    bad = (
        "from cxxnet_tpu.monitor import log as mlog\n"
        "def f(items):\n"
        "    for it in items:\n"
        "        mlog.warn('x')\n")
    assert rules_of(findings_for(tmp_path, bad)) == ["warn-once"]
    guarded = (
        "from cxxnet_tpu.monitor import log as mlog\n"
        "def f(items):\n"
        "    warned = False\n"
        "    for it in items:\n"
        "        if not warned:\n"
        "            warned = True\n"
        "            mlog.warn('x')\n")
    assert not findings_for(tmp_path, guarded)
    outside = (
        "from cxxnet_tpu.monitor import log as mlog\n"
        "def f():\n    mlog.warn('x')\n")
    assert not findings_for(tmp_path, outside)


# -------------------------------------------------------------- pragmas

def test_pragma_same_line_and_line_above(tmp_path):
    assert not findings_for(
        tmp_path, "print('x')  # disclint: ok(print)\n")
    assert not findings_for(
        tmp_path, "# disclint: ok(print)\nprint('x')\n")
    # pragma for a DIFFERENT rule does not suppress
    hits = findings_for(
        tmp_path, "print('x')  # disclint: ok(mktemp)\n")
    assert rules_of(hits) == ["print"]


def test_pragma_bare_ok_suppresses_all(tmp_path):
    assert not findings_for(
        tmp_path, "print('x')  # disclint: ok\n")


def test_pragma_ok_file(tmp_path):
    src = ("# disclint: ok-file(print)\n"
           "print('a')\nprint('b')\nf = open(p, 'w')\n")
    assert rules_of(findings_for(tmp_path, src)) == ["atomic-write"]


def test_syntax_error_is_a_finding(tmp_path):
    hits = findings_for(tmp_path, "def broken(:\n")
    assert rules_of(hits) == ["parse"]


# ------------------------------------------------------------ the guard

def test_disclint_exits_zero_on_the_tree():
    """The gate itself: every discipline violation in the shipped tree
    is either fixed or carries an inline, auditable pragma."""
    r = subprocess.run(
        [sys.executable, DISCLINT, "--json"], cwd=REPO,
        capture_output=True, text=True, timeout=300)
    out = json.loads(r.stdout)
    assert r.returncode == 0, json.dumps(out["findings"], indent=2)
    assert out["n_files"] > 50  # it actually walked the tree


def test_disclint_cli_reports_violations(tmp_path):
    p = tmp_path / "viol.py"
    p.write_text("print('x')\n")
    r = subprocess.run(
        [sys.executable, DISCLINT, str(p)], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "print" in r.stdout
