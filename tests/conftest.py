"""Test configuration: force an 8-device virtual CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (XLA host-platform
emulation).  The environment pre-registers a tunneled TPU backend and pins
JAX_PLATFORMS, so we must override through jax.config before any backend
initialization."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
