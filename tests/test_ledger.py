"""Goodput ledger, cross-run diff, and live follow (doc/monitor.md):

* build_ledger folds compile/step/round/ckpt/rollback records into
  categories that tile the measured wall (rollback lost-work, h2d
  overlap clamp, partial dying round);
* the tolerant JSONL reader skips a torn final line with ONE warning;
* the comparison engine's directions, thresholds, and significance
  floors (the one implementation obsv --diff / bench --against /
  test_bench_guard share);
* CPU MNIST e2e: the emitted ledger's category sum lands within 5% of
  the measured run wall, and a TrainingDiverged run still lands one;
* obsv --diff through the real CLI: exit 1 on a degraded run, exit 0
  on self-diff and on an improvement;
* --follow: incremental re-render over an appended file, torn-line
  buffering across polls, anomaly highlighting, ledger-terminated exit;
* bench --against: argv plumbing + verdict exit codes.
"""

import io
import json
import os
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from cxxnet_tpu.monitor import ledger as ledgerlib
from cxxnet_tpu.monitor.diff import (HIGHER_BETTER, LOWER_BETTER, compare,
                                     diff_bench, diff_runs, render_diff)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBSV = os.path.join(REPO, "tools", "obsv.py")
FIXTURE = os.path.join(REPO, "tests", "fixtures", "run_report.jsonl")


def _load_obsv():
    import importlib.util
    spec = importlib.util.spec_from_file_location("obsv_mod", OBSV)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------- ledger fold units

def _base_recs():
    return [
        {"ts": 0.0, "kind": "run"},
        {"ts": 1.0, "kind": "compile", "compile_sec": 2.0},
        # step marks are per-window; the round record that follows
        # carries the SAME round's full sums and supersedes them
        {"ts": 2.0, "kind": "step", "dispatch_sec": 1.0,
         "iter_wait_sec": 0.5, "h2d_sec": 0.2},
        {"ts": 3.0, "kind": "round", "round": 1, "wall_sec": 5.0,
         "eval_sec": 1.0, "dispatch_sec": 3.0, "iter_wait_sec": 1.0,
         "h2d_sec": 0.5},
        {"ts": 3.5, "kind": "ckpt", "blocked_sec": 0.25},
    ]


def test_build_ledger_categories_tile_wall():
    led = ledgerlib.build_ledger(_base_recs(), wall_sec=10.0)
    c = led["categories"]
    assert c["compile"] == 2.0
    assert c["dispatch"] == 3.0, "round record supersedes its step marks"
    assert c["input_wait"] == 1.0
    assert c["eval"] == 1.0
    assert c["ckpt_blocked"] == 0.25
    assert c["h2d_staging"] == 0.5   # fits the residual: critical path
    assert c["rollback_lost"] == 0.0
    assert c["other"] == pytest.approx(10.0 - 7.75)
    assert sum(c.values()) == pytest.approx(10.0)
    assert sum(led["shares"].values()) == pytest.approx(1.0, abs=1e-3)
    assert led["goodput_pct"] == pytest.approx(30.0)
    assert led["rounds"] == 1 and led["source"] == "run"
    assert set(c) == set(ledgerlib.CATEGORIES)


def test_build_ledger_h2d_overlap_clamp():
    """h2d that ran on the prefetch producer thread cost no wall: only
    the residual-fitting part is a category, the rest is reported as
    overlapped."""
    led = ledgerlib.build_ledger(_base_recs(), wall_sec=7.3)
    c = led["categories"]
    assert c["h2d_staging"] == pytest.approx(0.05)
    assert led["h2d_overlapped_sec"] == pytest.approx(0.45)
    assert c["other"] == 0.0
    assert sum(c.values()) == pytest.approx(7.3)


def _round(n, ts, wall=2.0, ev=0.5, disp=1.5, wait=0.2):
    return {"ts": ts, "kind": "round", "round": n, "wall_sec": wall,
            "eval_sec": ev, "dispatch_sec": disp, "iter_wait_sec": wait,
            "h2d_sec": 0.0}


def test_build_ledger_rollback_lost_work():
    """Rounds past the restored snapshot are lost work — their full
    wall moves into rollback_lost (and OUT of their categories), plus
    the dying round's partial step accounting."""
    recs = [
        _round(1, 1.0), _round(2, 2.0),
        # the dying round 3's partial window marks
        {"ts": 2.5, "kind": "step", "dispatch_sec": 0.4,
         "iter_wait_sec": 0.1, "h2d_sec": 0.0},
        {"ts": 3.0, "kind": "rollback", "retry": 1, "max_retry": 2,
         "from_round": 3, "restored_round": 1},
        _round(2, 4.0), _round(3, 5.0),
    ]
    led = ledgerlib.build_ledger(recs, wall_sec=20.0)
    c = led["categories"]
    # lost: round 2's 2.5 s + the dying round's 0.5 s of step marks
    assert c["rollback_lost"] == pytest.approx(3.0)
    assert led["rounds"] == 3 and led["rounds_lost"] == 1
    assert led["rollbacks"] == 1
    assert c["dispatch"] == pytest.approx(3 * 1.5)  # kept rounds only
    assert c["eval"] == pytest.approx(3 * 0.5)
    assert sum(c.values()) == pytest.approx(20.0)


def test_build_ledger_rolled_back_first_round_sheds_compile():
    """Round 1's wall CONTAINS the compile dispatch; when round 1
    itself is rolled back, its lost wall must shed the compile portion
    the `compile` category already booked — or the categories stop
    tiling the wall."""
    recs = [
        {"ts": 0.5, "kind": "compile", "compile_sec": 2.0, "round": 0},
        _round(1, 1.0, wall=5.0, ev=0.5, disp=2.0, wait=0.5),
        {"ts": 2.0, "kind": "rollback", "retry": 1, "max_retry": 1,
         "from_round": 2, "restored_round": 0},
        _round(1, 3.0, wall=3.0, ev=0.5, disp=2.0, wait=0.5),
    ]
    led = ledgerlib.build_ledger(recs, wall_sec=12.0)
    c = led["categories"]
    assert c["compile"] == 2.0
    # lost = round 1's (wall 5 - nested compile 2) + eval 0.5
    assert c["rollback_lost"] == pytest.approx(3.5)
    assert sum(c.values()) == pytest.approx(12.0)


def test_build_ledger_folds_only_past_the_last_ledger():
    """The sink appends: an earlier session's records (bounded by ITS
    ledger record) must not fold into the next session's — while a
    mid-stream `run` record (a rollback rebuild) is NOT a boundary."""
    prior = _base_recs() + [
        {"ts": 4.0, "kind": "ledger", "wall_sec": 10.0,
         "goodput_pct": 30.0}]
    current = [
        {"ts": 5.0, "kind": "run"},
        {"ts": 6.0, "kind": "compile", "compile_sec": 1.0},
        _round(1, 7.0, wall=4.0, ev=0.0, disp=3.0, wait=0.5),
    ]
    led = ledgerlib.build_ledger(prior + current, wall_sec=6.0)
    c = led["categories"]
    assert c["compile"] == 1.0, "prior session's compile not re-counted"
    assert c["dispatch"] == 3.0 and led["rounds"] == 1
    assert sum(c.values()) == pytest.approx(6.0)


def test_build_ledger_posthoc_wall_from_ts_span():
    recs = _base_recs()
    led = ledgerlib.build_ledger(recs, source="posthoc")
    assert led["wall_sec"] == pytest.approx(3.5)  # stream ts span
    assert led["source"] == "posthoc"
    assert ledgerlib.build_ledger([]) is None


def test_format_ledger_line():
    led = ledgerlib.build_ledger(_base_recs(), wall_sec=10.0)
    line = ledgerlib.format_ledger(led)
    assert "goodput 30.0%" in line and "dispatch 3s" in line


# --------------------------------------------------- torn-line tolerance

def test_load_records_torn_tail_warns_once(tmp_path, capsys):
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "step", "examples_per_sec": 1.0})
                + "\n")
        f.write("[1, 2]\n")      # parseable non-record: skipped silently
        f.write('{"kind": "round", "rou')  # torn tail, no newline
    recs = ledgerlib.load_records(str(p))
    assert [r["kind"] for r in recs] == ["step"]
    err = capsys.readouterr().err
    assert err.count("skipped 1 unparseable") == 1
    assert "torn tail" in err
    # a clean file warns nothing
    clean = tmp_path / "c.jsonl"
    clean.write_text(json.dumps({"kind": "run"}) + "\n")
    ledgerlib.load_records(str(clean))
    assert "skipped" not in capsys.readouterr().err


# ------------------------------------------------------ comparison engine

def test_compare_directions_and_floors():
    assert not compare("m", 100, 105, rel=0.10)["regressed"]
    r = compare("m", 100, 115, rel=0.10)
    assert r["regressed"] and not r["improved"]
    assert r["rel_delta"] == pytest.approx(0.15)
    assert compare("m", 100, 85, rel=0.10)["improved"]
    # higher-better flips the bad direction
    assert compare("m", 100, 85, rel=0.10,
                   direction=HIGHER_BETTER)["regressed"]
    assert compare("m", 100, 115, rel=0.10,
                   direction=HIGHER_BETTER)["improved"]
    # the significance floor mutes relative noise on tiny values
    f = compare("share", 0.01, 0.02, rel=0.10, abs_floor=0.05)
    assert not f["regressed"] and f["rel_delta"] == pytest.approx(1.0)
    # no baseline magnitude -> no RELATIVE verdict...
    z = compare("m", 0.0, 5.0)
    assert z["rel_delta"] is None and not z["regressed"]
    assert compare("m", 0.0, 0.0)["rel_delta"] == 0.0
    assert compare("m", None, 5.0)["rel_delta"] is None
    # ...but a metric WITH a significance floor is judged by the
    # absolute move: a clean baseline has rollback_lost == 0.0 exactly,
    # and churn appearing from zero must still gate
    zf = compare("share", 0.0, 0.35, rel=0.10, abs_floor=0.02)
    assert zf["regressed"] and not zf["improved"]
    assert not compare("share", 0.0, 0.01, rel=0.10,
                       abs_floor=0.02)["regressed"]
    assert compare("share", 0.0, 0.35, rel=0.10, direction=HIGHER_BETTER,
                   abs_floor=0.02)["improved"]


def test_diff_runs_rollback_churn_from_clean_baseline():
    """End-to-end through diff_runs: baseline with zero rollback churn,
    candidate losing a third of its wall to rollbacks — must gate."""
    a, b = _run_recs(100.0, 10.0), _run_recs(100.0, 10.0, ts0=10.0)
    b.append({"ts": 13.0, "kind": "ledger", "wall_sec": 3.0,
              "goodput_pct": 30.0,
              "shares": {"rollback_lost": 0.35, "input_wait": 0.03},
              "categories": {}})
    d = diff_runs(a, b, rel=0.10)
    bad = {c["metric"] for c in d["metrics"] if c["regressed"]}
    assert "ledger_share_rollback_lost" in bad


def _run_recs(eps, fc1_ms, ts0=0.0):
    return [
        {"ts": ts0, "kind": "step", "examples_per_sec": eps,
         "dispatch_sec": 1.0, "iter_wait_sec": 0.1, "h2d_sec": 0.0},
        {"ts": ts0 + 1, "kind": "round", "round": 1, "wall_sec": 1.2,
         "eval_sec": 0.1, "dispatch_sec": 1.0, "iter_wait_sec": 0.1,
         "h2d_sec": 0.0, "examples_per_sec": eps},
        {"ts": ts0 + 2, "kind": "layer_profile", "round": 1,
         "rows": [{"layer": "00-fc1", "device_ms": fc1_ms},
                  {"layer": "02-fc2", "device_ms": 1.0}]},
    ]


def test_diff_runs_flags_throughput_and_layer_rows():
    a, b = _run_recs(100.0, 10.0), _run_recs(50.0, 20.0)
    d = diff_runs(a, b, rel=0.10)
    byname = {c["metric"]: c for c in d["metrics"] + d["layers"]}
    assert byname["examples_per_sec_mean"]["regressed"]
    # the final window is ONE sample: context, never judged
    assert byname["examples_per_sec_last"]["direction"] is None
    assert not byname["examples_per_sec_last"]["regressed"]
    assert byname["00-fc1"]["regressed"]  # conn_scope_name join
    assert not byname["02-fc2"]["regressed"]
    assert d["regressions"] >= 2
    # the reverse direction is an improvement, not a regression
    rev = diff_runs(b, a, rel=0.10)
    assert rev["regressions"] == 0 and rev["improvements"] >= 2
    out = render_diff(d, "A", "B")
    assert "REGRESSED" in out and "FAIL" in out
    assert "examples_per_sec_mean" in out


def test_diff_runs_layer_sets_reported_not_judged():
    a, b = _run_recs(100.0, 10.0), _run_recs(100.0, 10.0)
    b[-1]["rows"] = [{"layer": "00-fc1", "device_ms": 10.0},
                     {"layer": "03-conv", "device_ms": 2.0}]
    d = diff_runs(a, b, rel=0.10)
    assert d["layers_only_a"] == ["02-fc2"]
    assert d["layers_only_b"] == ["03-conv"]
    assert d["regressions"] == 0


def test_bench_direction_throughput_not_inverted():
    """Throughput fields end in `_sec` too — the higher-better
    vocabulary must win over the suffix rule, or --against exits 1 on
    an IMPROVEMENT (the wrong-way CI gate)."""
    from cxxnet_tpu.monitor.diff import bench_direction
    for k in ("imgs_per_sec", "tokens_per_sec", "batches_per_sec_on",
              "alexnet_imgs_per_sec_per_chip", "qps", "device_mfu_pct"):
        assert bench_direction(k) == HIGHER_BETTER, k
    for k in ("duration_sec", "step_ms_median", "device_step_ms",
              "compile_sec", "p99_ms"):
        assert bench_direction(k) == LOWER_BETTER, k
    assert bench_direction("trials") is None
    d = diff_bench({"imgs_per_sec": 100.0}, {"imgs_per_sec": 150.0})
    assert d["regressions"] == 0 and d["improvements"] == 1


def test_diff_bench_directions_from_field_names():
    prior = {"parsed": {"metric": "alexnet_imgs_per_sec_per_chip",
                        "value": 26000.0, "device_step_ms": 38.4,
                        "trials": 5, "arms": {"fused": {"step_ms": 30.0}}}}
    worse = {"value": 20000.0, "device_step_ms": 45.0, "trials": 3,
             "arms": {"fused": {"step_ms": 40.0}}}
    d = diff_bench(prior, worse, rel=0.10)
    names = {c["metric"] for c in d["metrics"] if c["regressed"]}
    assert names == {"value", "device_step_ms", "arms.fused.step_ms"}
    assert not any(c["metric"] == "trials" for c in d["metrics"])
    better = {"value": 30000.0, "device_step_ms": 30.0,
              "arms": {"fused": {"step_ms": 20.0}}}
    d2 = diff_bench(prior, better, rel=0.10)
    assert d2["regressions"] == 0 and d2["improvements"] == 3


def test_diff_bench_value_direction_from_headline_metric():
    """`value` means what the sibling `metric` says: the --opt-ab and
    --serve headlines are MILLISECONDS, so a smaller value is an
    improvement there — never judge the literal key."""
    prior = {"metric": "opt_ab_step_ms", "value": 30.0}
    d = diff_bench(prior, {"value": 20.0}, rel=0.10)
    (v,) = d["metrics"]
    assert v["metric"] == "value" and v["improved"]
    d = diff_bench(prior, {"value": 40.0}, rel=0.10)
    assert d["metrics"][0]["regressed"]
    # an unrecognized headline name leaves value uncompared, not guessed
    d = diff_bench({"metric": "mystery", "value": 1.0},
                   {"value": 2.0}, rel=0.10)
    assert d["metrics"] == []


# ----------------------------------------------------------- CPU MNIST e2e

def _train_conf(tmp_path, name="train.conf", extra=""):
    from test_main import MLP_NET, _write_synth_mnist
    _write_synth_mnist(tmp_path, n=64)
    conf = tmp_path / name
    conf.write_text(f"""
dev = cpu:0
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
{MLP_NET}
input_shape = 1,1,144
batch_size = 16
eta = 0.05
num_round = 2
metric = error
model_dir = {tmp_path}/models
save_model = 0
silent = 1
print_step = 2
{extra}
""")
    return conf


@pytest.fixture(scope="module")
def base_run(tmp_path_factory):
    """ONE CPU MNIST training run with a sink, shared by the e2e tests
    below — the jit compile is the dominant cost, paid once (tier-1
    runtime budget; each test reads the same immutable stream)."""
    from cxxnet_tpu.main import LearnTask
    tmp = tmp_path_factory.mktemp("ledger_base")
    sink = tmp / "a.jsonl"
    conf = _train_conf(tmp, "a.conf",
                       extra=f"metrics_sink = jsonl:{sink}\n")
    t0 = time.perf_counter()
    assert LearnTask().run([str(conf)]) == 0
    wall = time.perf_counter() - t0
    return {"tmp": tmp, "sink": sink, "wall": wall}


def test_ledger_record_cpu_e2e_sums_to_wall(base_run):
    """The acceptance gate: the emitted ledger's category sum lands
    within 5% of the run wall the test measured around the task."""
    sink, wall = base_run["sink"], base_run["wall"]
    recs = [json.loads(l) for l in open(sink)]
    assert recs[-1]["kind"] == "ledger", "the stream's last record"
    led = recs[-1]
    assert led["source"] == "run"
    cat_sum = sum(led["categories"].values())
    assert cat_sum == pytest.approx(led["wall_sec"], rel=0.02)
    assert abs(cat_sum - wall) <= 0.05 * wall
    assert led["rounds"] == 2 and led["rounds_lost"] == 0
    assert 0.0 < led["goodput_pct"] <= 100.0
    assert led["goodput_pct"] == pytest.approx(
        led["shares"]["dispatch"] * 100, abs=0.51)
    # the obsv report renders the emitted record, not a recompute
    obsv = _load_obsv()
    rep = obsv.build_report(obsv.load_records(str(sink)))
    assert rep["ledger"]["source"] == "run"
    assert rep["ledger"]["goodput_pct"] == led["goodput_pct"]


def test_diverged_run_still_lands_ledger(tmp_path):
    """A TrainingDiverged run's finally still folds and emits the
    ledger — after the exception path's flight dump."""
    from cxxnet_tpu.main import LearnTask
    from cxxnet_tpu.monitor import TrainingDiverged
    sink = tmp_path / "m.jsonl"
    conf = _train_conf(tmp_path, extra=f"""
print_step = 1
monitor = 1
monitor_interval = 1
monitor_nan = fatal
metrics_sink = jsonl:{sink}
""")
    with pytest.raises(TrainingDiverged):
        LearnTask().run([str(conf), "eta=nan"])
    recs = [json.loads(l) for l in open(sink)]
    kinds = [r["kind"] for r in recs]
    assert "nan" in kinds
    assert kinds[-1] == "ledger"
    led = recs[-1]
    assert led["wall_sec"] > 0 and led["nonfinite_steps"] >= 1
    # the categories still tile the measured wall (the death at step 1
    # leaves no step/round records: the time reads as other/compile)
    assert sum(led["categories"].values()) == pytest.approx(
        led["wall_sec"], rel=0.02)


def test_posthoc_recompute_matches_emitted_fold(base_run, tmp_path):
    """obsv recomputes the SAME fold for a JSONL whose ledger record is
    stripped (a historical run) — categories agree up to the wall
    source (measured task wall vs record ts span)."""
    recs = [json.loads(l) for l in open(base_run["sink"])]
    emitted = recs[-1]
    stripped = tmp_path / "old.jsonl"
    with open(stripped, "w") as f:
        for r in recs[:-1]:
            f.write(json.dumps(r) + "\n")
    obsv = _load_obsv()
    led = obsv.build_report(obsv.load_records(str(stripped)))["ledger"]
    assert led["source"] == "posthoc"
    for cat in ("compile", "dispatch", "input_wait", "eval"):
        assert led["categories"][cat] == pytest.approx(
            emitted["categories"][cat], abs=1e-3)


def test_last_session_slicing():
    led = {"ts": 9.0, "kind": "ledger"}
    s1 = [{"ts": 1.0, "kind": "step"}, dict(led)]
    s2 = [{"ts": 11.0, "kind": "step"}, {"ts": 12.0, "kind": "round"}]
    assert ledgerlib.last_session([]) == []
    assert ledgerlib.last_session(s2) == s2          # no ledger at all
    assert ledgerlib.last_session(s1) == s1          # one whole session
    assert ledgerlib.last_session(s1 + s2) == s2     # trailing live run
    done2 = s2 + [{"ts": 13.0, "kind": "ledger"}]
    assert ledgerlib.last_session(s1 + done2) == done2


def test_diff_runs_ignores_earlier_sessions_in_stream():
    """A reused sink's candidate stream must be judged on its LAST
    session only — a slow dead session in the same file must not drag
    the mean into a phantom regression."""
    slow = _run_recs(10.0, 10.0) + [{"ts": 3.0, "kind": "ledger"}]
    fast = _run_recs(100.0, 10.0, ts0=10.0)
    d = diff_runs(_run_recs(100.0, 10.0), slow + fast, rel=0.10)
    assert d["regressions"] == 0, \
        "the dead slow session leaked into the candidate's metrics"


def test_sink_repairs_torn_tail_on_reopen(tmp_path):
    """A predecessor killed mid-write leaves a newline-less torn tail;
    the reopened sink must restore the line boundary or the new run's
    first record is glued to it and lost to every reader."""
    from cxxnet_tpu.monitor.metrics import MetricsRegistry
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"ts": 1.0, "kind": "step"}) + "\n")
        f.write('{"kind": "round", "rou')  # the kill point
    reg = MetricsRegistry()
    reg.configure_sink(f"jsonl:{p}")
    reg.emit("run", updater="sgd")
    reg.close()
    recs = ledgerlib.load_records(str(p))
    assert [r["kind"] for r in recs] == ["step", "run"], \
        "the new run record must survive next to the torn tail"


def test_reused_sink_second_ledger_covers_only_its_run(base_run,
                                                       tmp_path):
    """Two sessions appending to ONE sink path: the second run's ledger
    must account its own wall only (byte-offset anchor + last-ledger
    slice), not fold the first session's records in again.  The first
    session is the shared base run's stream, copied to a fresh path."""
    import shutil
    from cxxnet_tpu.main import LearnTask
    sink = tmp_path / "m.jsonl"
    shutil.copy(base_run["sink"], sink)
    conf = _train_conf(tmp_path, extra=f"metrics_sink = jsonl:{sink}\n")
    t0 = time.perf_counter()
    assert LearnTask().run([str(conf)]) == 0
    wall2 = time.perf_counter() - t0
    leds = [json.loads(l) for l in open(sink)
            if json.loads(l)["kind"] == "ledger"]
    assert len(leds) == 2
    led2 = leds[1]
    assert led2["rounds"] == 2, "second session's rounds only (a "\
        "doubled fold would read 4)"
    assert abs(sum(led2["categories"].values()) - wall2) <= 0.05 * wall2
    assert led2["wall_sec"] <= wall2 * 1.05


# ------------------------------------------------------- diff CLI e2e

def test_obsv_diff_cli_exit_codes(base_run, tmp_path, capsys):
    """The CI-gate contract through the real CLI entry (obsv.main with
    argv — one true subprocess ride lives in the follow CLI test):
    exit 1 when the candidate run is degraded (batch 4 vs 16: a
    fraction of the throughput), exit 0 on self-diff and when the
    candidate improves."""
    from cxxnet_tpu.main import LearnTask
    obsv = _load_obsv()
    sink_a = str(base_run["sink"])
    sink_b = str(tmp_path / "b.jsonl")
    conf_b = _train_conf(tmp_path, "b.conf",
                         extra=f"metrics_sink = jsonl:{sink_b}\n")
    assert LearnTask().run([str(conf_b), "batch_size=4"]) == 0

    def _diff(a, b, *extra):
        code = obsv.main(["--diff", a, b, *extra])
        return code, capsys.readouterr().out

    code, out = _diff(sink_a, sink_a)
    assert code == 0 and "0 regression(s)" in out
    code, out = _diff(sink_a, sink_b, "--json")
    assert code == 1
    d = json.loads(out)
    regressed = {c["metric"] for c in d["metrics"] if c["regressed"]}
    assert "examples_per_sec_mean" in regressed
    # candidate faster than baseline: improvements never fail the gate
    code, out = _diff(sink_b, sink_a)
    assert code == 0 and "improved" in out
    # rendered table names the loser
    code, out = _diff(sink_a, sink_b)
    assert code == 1
    assert "REGRESSED" in out and "FAIL" in out


def test_obsv_diff_missing_file_exits_2(tmp_path):
    assert _load_obsv().main(
        ["--diff", FIXTURE, str(tmp_path / "nope.jsonl")]) == 2


# ------------------------------------------------------------- live follow

def test_follower_incremental_and_torn_line(tmp_path):
    obsv = _load_obsv()
    p = tmp_path / "m.jsonl"
    p.write_text("")
    f = obsv.Follower(str(p))
    assert f.poll() == ([], [])
    line1 = json.dumps({"ts": 1.0, "kind": "step",
                        "examples_per_sec": 10.0})
    # a mid-write torn line stays buffered until its newline lands
    with open(p, "a") as fo:
        fo.write(line1[:12])
    assert f.poll() == ([], [])
    anom = json.dumps({"ts": 2.0, "kind": "anomaly",
                       "metric": "examples_per_sec",
                       "direction": "drop", "value": 5.0, "ewma": 10.0,
                       "rel_dev": -0.5})
    with open(p, "a") as fo:
        fo.write(line1[12:] + "\n" + anom + "\n")
    new, alerts = f.poll()
    assert [r["kind"] for r in new] == ["step", "anomaly"]
    assert len(alerts) == 1 and alerts[0]["kind"] == "anomaly"
    assert len(f.records) == 2
    with open(p, "a") as fo:
        fo.write(json.dumps({"ts": 3.0, "kind": "ledger",
                             "goodput_pct": 50.0}) + "\n")
    new, alerts = f.poll()
    assert [r["kind"] for r in new] == ["ledger"] and not alerts


def test_follow_renders_and_stops_on_ledger(tmp_path):
    obsv = _load_obsv()
    out = io.StringIO()
    # ticks bound: a file with no ledger record ends after N polls
    p = tmp_path / "m.jsonl"
    p.write_text(json.dumps({"ts": 1.0, "kind": "step",
                             "examples_per_sec": 7.0}) + "\n")
    assert obsv.follow(str(p), interval=0.0, ticks=2, out=out) == 0
    text = out.getvalue()
    assert "throughput" in text and "record(s)" in text


def test_follow_catchup_never_terminal_live_ledger_exits(tmp_path):
    """Pre-existing records — including a previous session's ledger,
    mid-file or stream-ending — are catch-up context and never end the
    follow; only a ledger ARRIVING at the end of the stream on a later
    poll does."""
    import threading
    obsv = _load_obsv()
    p = tmp_path / "m.jsonl"
    with open(p, "w") as fo:
        fo.write(json.dumps({"ts": 1.0, "kind": "ledger",
                             "goodput_pct": 40.0}) + "\n")
        fo.write(json.dumps({"ts": 2.0, "kind": "step",
                             "examples_per_sec": 9.0}) + "\n")
    out = io.StringIO()
    assert obsv.follow(str(p), interval=0.0, ticks=2, out=out) == 0
    assert "run ended" not in out.getvalue(), \
        "the stale mid-stream ledger must not terminate the follow"
    # a file ENDING with the old ledger is still only catch-up
    with open(p, "a") as fo:
        fo.write(json.dumps({"ts": 3.0, "kind": "ledger",
                             "goodput_pct": 50.0}) + "\n")
    out = io.StringIO()
    assert obsv.follow(str(p), interval=0.0, ticks=3, out=out) == 0
    assert "run ended" not in out.getvalue()
    assert "finished run" in out.getvalue()  # the catch-up notice
    # ...but the LIVE run's ledger, landing mid-follow, exits
    def writer():
        time.sleep(0.15)
        with open(p, "a") as fo:
            fo.write(json.dumps({"ts": 4.0, "kind": "step",
                                 "examples_per_sec": 11.0}) + "\n")
            fo.write(json.dumps({"ts": 5.0, "kind": "ledger",
                                 "goodput_pct": 60.0}) + "\n")
    th = threading.Thread(target=writer, daemon=True)
    out = io.StringIO()
    th.start()
    assert obsv.follow(str(p), interval=0.02, ticks=200, out=out) == 0
    th.join()
    assert "run ended" in out.getvalue()


def test_follow_cli_live_ledger_exit_and_alerts(tmp_path):
    """Through the real CLI: catch-up (the fixture's records incl. its
    ledger) flags alerts but keeps following; the live run's ledger,
    appended mid-follow, exits 0 on its own."""
    import shutil
    live = tmp_path / "live.jsonl"
    shutil.copy(FIXTURE, live)
    p = subprocess.Popen(
        [sys.executable, OBSV, str(live), "--follow",
         "--interval", "0.05"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        time.sleep(0.6)  # catch-up poll happens; must not exit
        assert p.poll() is None, "catch-up ledger must not terminate"
        with open(live, "a") as fo:
            fo.write(json.dumps({"ts": 2e9, "kind": "step",
                                 "examples_per_sec": 5.0}) + "\n")
            fo.write(json.dumps({"ts": 2e9 + 1, "kind": "ledger",
                                 "goodput_pct": 10.0}) + "\n")
        # keep staging ledgers until the follower exits: however slow
        # the subprocess's first (catch-up) read was, one of these
        # lands while it is following and ends it — de-races startup
        for _ in range(40):
            time.sleep(0.3)
            if p.poll() is not None:
                break
            with open(live, "a") as fo:
                fo.write(json.dumps({"ts": 2e9 + 2, "kind": "ledger",
                                     "goodput_pct": 10.0}) + "\n")
        out, _ = p.communicate(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == 0
    assert "!! anomaly" in out
    assert "!! nan" in out
    assert "finished run" in out      # the catch-up notice
    assert "run ended (ledger record landed)" in out
    assert "goodput" in out           # the re-rendered report


# ---------------------------------------------------------- bench --against

def test_pop_against_both_forms():
    import bench
    assert bench.pop_against(["--io-ab", "tiny=1"]) == \
        (None, ["--io-ab", "tiny=1"])
    assert bench.pop_against(["--against", "B.json", "x=1"]) == \
        ("B.json", ["x=1"])
    assert bench.pop_against(["x=1", "--against=B.json"]) == \
        ("B.json", ["x=1"])
    # an unset $BASELINE (`--against=`) must fail loudly, not drop
    # the gate and exit 0
    with pytest.raises(SystemExit):
        bench.pop_against(["--against="])
    with pytest.raises(SystemExit):
        bench.pop_against(["--against"])
    # an empty $BASELINE must not swallow the next flag as the path
    with pytest.raises(SystemExit):
        bench.pop_against(["--against", "--opt-ab", "conf"])


def test_obsv_diff_binary_input_exits_2(tmp_path):
    """A corrupt/binary baseline is exit 2 (unreadable), never the
    regression verdict."""
    bad = tmp_path / "garbage.bin"
    bad.write_bytes(b"\xff\xfe\x00binary")
    assert _load_obsv().main(["--diff", str(bad), FIXTURE]) == 2


def test_bench_against_verdict_exit_codes(tmp_path, capsys):
    import bench
    prior = tmp_path / "BENCH_r98.json"
    # the round files wrap the payload in "parsed" — accepted as-is
    prior.write_text(json.dumps(
        {"parsed": {"metric": "alexnet_imgs_per_sec_per_chip",
                    "value": 26000.0, "unit": "imgs/sec",
                    "device_step_ms": 38.4}}))
    bad = {"metric": "alexnet_imgs_per_sec_per_chip", "value": 20000.0,
           "unit": "imgs/sec", "device_step_ms": 45.0}
    assert bench.against_verdict(bad, str(prior)) == 1
    err = capsys.readouterr().err
    assert "REGRESSED" in err and "device_step_ms" in err
    good = dict(bad, value=26500.0, device_step_ms=38.0)
    assert bench.against_verdict(good, str(prior)) == 0
    # unreadable baseline is exit 2 — NOT the regression verdict
    assert bench.against_verdict(good, str(tmp_path / "nope.json")) == 2
    broken = tmp_path / "broken.json"
    broken.write_text("{not json")
    assert bench.against_verdict(good, str(broken)) == 2


def test_bench_main_against_plumbing(tmp_path, monkeypatch, capsys):
    """--against through bench.main(): the mode runs with the flag
    stripped from its argv, and the process exit code is the verdict."""
    import bench
    prior = tmp_path / "BENCH_r99.json"
    prior.write_text(json.dumps({"parsed": {"value": 200.0,
                                            "step_ms_median": 5.0}}))
    seen_argv = []

    def fake_mode(argv):
        seen_argv.append(list(argv))
        return {"metric": "fake", "value": 100.0, "step_ms_median": 10.0}

    monkeypatch.setitem(bench.BENCH_MODES, "--fake", fake_mode)
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--fake", "x=1",
                         "--against", str(prior)])
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 1
    assert seen_argv == [["x=1"]], "--against stripped before the mode"
    capsys.readouterr()
    # matching payload: exit 0
    prior.write_text(json.dumps({"parsed": {"value": 100.0,
                                            "step_ms_median": 10.0}}))
    with pytest.raises(SystemExit) as e:
        bench.main()
    assert e.value.code == 0


# ------------------------------------------------------------- lint rules

def test_lint_ledger_rules():
    from cxxnet_tpu.analysis.conflint import lint_pairs
    # explicit ledger=1 without a sink: nowhere to land
    f = lint_pairs([("task", "train"), ("ledger", "1")])
    assert any(x.key == "ledger" and "metrics_sink" in x.message
               for x in f)
    # off-task: only train/finetune emit one
    f = lint_pairs([("task", "pred"), ("ledger", "1"),
                    ("metrics_sink", "jsonl:/tmp/m.jsonl")])
    assert any(x.key == "ledger" and "task = pred" in x.message
               for x in f)
    # explicitly DISABLING the default-on key off-task is a no-op, not
    # a finding (the user is not trying to enable it)
    f = lint_pairs([("task", "serve"), ("ledger", "0")])
    assert not any(x.key == "ledger" and "task = serve" in x.message
                   for x in f)
    # default-on with defaults applying: silent
    f = lint_pairs([("task", "train")])
    assert not any(x.key == "ledger" for x in f)
    f = lint_pairs([("task", "train"), ("ledger", "1"),
                    ("metrics_sink", "jsonl:/tmp/m.jsonl")])
    assert not any(x.key == "ledger" for x in f)
