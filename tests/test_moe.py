"""Mixture-of-experts layer: routing correctness vs a per-token loop,
capacity drops, aux loss, and expert-parallel training on the CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from cxxnet_tpu.layers.base import ForwardContext
from cxxnet_tpu.layers.registry import create_layer


def make_moe(e=4, h=16, cf=10.0):
    layer = create_layer("moe")
    layer.set_param("num_expert", str(e))
    layer.set_param("nhidden", str(h))
    layer.set_param("capacity_factor", str(cf))
    layer.set_param("init_sigma", "0.2")
    return layer


def _reference_moe(x, params, c):
    """Per-token loop transcription of Switch top-1 routing."""
    t, d = x.shape
    e = params["gate"].shape[1]
    logits = x @ params["gate"]
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expert = probs.argmax(-1)
    counts = np.zeros(e, np.int64)
    y = np.zeros_like(x)
    for i in range(t):
        ei = expert[i]
        if counts[ei] >= c:
            y[i] = x[i]  # dropped: pure residual
            continue
        counts[ei] += 1
        hdn = x[i] @ params["wmat"][ei] + params["bias"][ei]
        hdn = 0.5 * hdn * (1 + np.tanh(np.sqrt(2 / np.pi)
                                       * (hdn + 0.044715 * hdn ** 3)))
        # every token keeps its residual (continuous at capacity boundary)
        y[i] = x[i] + (hdn @ params["wmat2"][ei]
                       + params["bias2"][ei]) * probs[i, ei]
    return y


@pytest.mark.parametrize("cf", [10.0, 0.5])
@pytest.mark.parametrize("dispatch", ["dense", "sorted"])
def test_moe_matches_reference_loop(cf, dispatch):
    rnd = np.random.RandomState(0)
    b, s, d = 2, 8, 12
    layer = make_moe(e=4, h=16, cf=cf)
    layer.set_param("moe_dispatch", dispatch)
    shapes = [(b, 1, s, d)]
    layer.infer_shapes(shapes)
    params = layer.init_params(jax.random.PRNGKey(1), shapes)
    x = rnd.randn(b, 1, s, d).astype(np.float32)
    ctx = ForwardContext(train=False)
    (out,), _ = layer.forward(params, {}, [jnp.asarray(x)], ctx)
    pnp = {k: np.asarray(v) for k, v in params.items()}
    want = _reference_moe(x.reshape(-1, d), pnp,
                          layer._capacity(b * s)).reshape(b, 1, s, d)
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4, atol=2e-5)


def test_moe_sorted_matches_dense_grads():
    """Differential: sorted dispatch must reproduce the dense one-hot
    oracle exactly — outputs AND parameter gradients (routing, capacity
    drops, and the two transposed gathers all agree)."""
    rnd = np.random.RandomState(2)
    b, s, d = 2, 16, 12
    x = jnp.asarray(rnd.randn(b, 1, s, d), jnp.float32)

    outs, grads = {}, {}
    for dispatch in ("dense", "sorted"):
        layer = make_moe(e=4, h=16, cf=0.6)  # tight capacity: drops occur
        layer.set_param("moe_dispatch", dispatch)
        shapes = [(b, 1, s, d)]
        layer.infer_shapes(shapes)
        params = layer.init_params(jax.random.PRNGKey(5), shapes)

        def loss(p):
            ctx = ForwardContext(train=True, loss_scale=1.0 / b)
            (out,), _ = layer.forward(p, {}, [x], ctx)
            return (out ** 2).sum() + ctx.losses[0], out

        (l, out), g = jax.value_and_grad(loss, has_aux=True)(params)
        outs[dispatch], grads[dispatch] = out, g

    np.testing.assert_allclose(np.asarray(outs["sorted"]),
                               np.asarray(outs["dense"]),
                               rtol=1e-5, atol=1e-6)
    for tag in grads["dense"]:
        np.testing.assert_allclose(np.asarray(grads["sorted"][tag]),
                                   np.asarray(grads["dense"][tag]),
                                   rtol=2e-4, atol=1e-5, err_msg=tag)


def test_moe_capacity_boundary_continuity():
    """The ADVICE finding: a token's output must not jump discontinuously
    when it crosses the capacity boundary — with the full residual, a
    dropped token yields exactly x."""
    layer = make_moe(e=2, h=8, cf=0.01)  # capacity 1: almost all dropped
    b, s, d = 1, 8, 6
    shapes = [(b, 1, s, d)]
    layer.infer_shapes(shapes)
    params = layer.init_params(jax.random.PRNGKey(3), shapes)
    x = jnp.asarray(np.random.RandomState(4).randn(b, 1, s, d), jnp.float32)
    (out,), _ = layer.forward(params, {}, [x], ForwardContext(train=False))
    # at most 2 tokens (1 per expert) differ from the pure residual
    diff = np.abs(np.asarray(out) - np.asarray(x)).reshape(s, d).max(axis=1)
    assert (diff > 0).sum() <= 2


def test_moe_aux_loss_and_grads():
    layer = make_moe()
    shapes = [(2, 1, 8, 12)]
    layer.infer_shapes(shapes)
    params = layer.init_params(jax.random.PRNGKey(0), shapes)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 1, 8, 12), jnp.float32)

    def loss(p):
        ctx = ForwardContext(train=True, loss_scale=1.0 / 2)
        (out,), _ = layer.forward(p, {}, [x], ctx)
        assert len(ctx.losses) == 1  # aux load-balance loss appended
        return (out ** 2).sum() + ctx.losses[0]

    grads = jax.grad(loss)(params)
    for tag in ("gate", "wmat", "wmat2", "bias", "bias2"):
        assert float(jnp.abs(grads[tag]).max()) > 0, tag


def test_moe_expert_parallel_trains():
    """One training step over a data x expert mesh; replicas stay
    consistent and the loss is finite."""
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    from cxxnet_tpu.io.data import DataBatch
    CONF = """
netconfig=start
layer[0->1] = embedding
  vocab_size = 32
  nhidden = 16
layer[1->2] = moe
  num_expert = 4
  nhidden = 32
layer[2->3] = seq_fullc
  nhidden = 32
layer[3->3] = softmax_seq
netconfig=end
label_vec[0,8) = label
input_shape = 1,1,8
batch_size = 8
dev = cpu:0-7
mesh = data:2,expert:4
eta = 0.05
updater = adam
metric = error
silent = 1
"""
    t = NetTrainer()
    for k, v in parse_config_string(CONF):
        t.set_param(k, v)
    t.init_model()
    rnd = np.random.RandomState(0)
    toks = rnd.randint(0, 32, (8, 8)).astype(np.float32)
    for _ in range(2):
        t.update(DataBatch(data=toks.reshape(8, 1, 1, 8), label=toks,
                           index=np.arange(8, dtype=np.uint32)))
    assert np.isfinite(float(np.asarray(t._last_loss)))
    assert t.check_weight_consistency() == 0.0
    # expert weights (and their optimizer state) are sharded over the
    # expert axis AT REST — the memory benefit of expert parallelism
    (moe_key,) = [k for k in t.params if "moe" in k]
    from jax.sharding import PartitionSpec as P
    assert t.params[moe_key]["wmat"].sharding.spec == P("expert", None, None)
    m_state = t.opt_state[moe_key]["wmat"]
    any_leaf = next(iter(m_state.values()))
    assert any_leaf.sharding.spec == P("expert", None, None)
    assert t.params[moe_key]["gate"].sharding.spec == P()


def test_moe_model_axis_hosts_experts():
    """On a mesh with no dedicated expert axis (mesh = data:2,model:2 —
    the first-class 2-D config) the MODEL axis hosts the experts: the
    per-expert weights shard over it at rest and the dispatch/combine
    constraints rewrite their canonical "expert" spelling to it
    (moe._expert_axis).  Training stays finite and replica-consistent."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    CONF = """
netconfig=start
layer[0->1] = embedding
  vocab_size = 32
  nhidden = 16
layer[1->2] = moe
  num_expert = 4
  nhidden = 32
layer[2->3] = seq_fullc
  nhidden = 32
layer[3->3] = softmax_seq
netconfig=end
label_vec[0,8) = label
input_shape = 1,1,8
batch_size = 8
dev = cpu:0-3
mesh = data:2,model:2
eta = 0.05
updater = adam
metric = error
silent = 1
"""
    t = NetTrainer()
    for k, v in parse_config_string(CONF):
        t.set_param(k, v)
    t.init_model()
    from jax.sharding import PartitionSpec as P
    (moe_key,) = [k for k in t.params if "moe" in k]
    assert t.params[moe_key]["wmat"].sharding.spec == P("model", None, None)
    assert t.params[moe_key]["gate"].sharding.spec == P()
    rnd = np.random.RandomState(0)
    toks = rnd.randint(0, 32, (8, 8)).astype(np.float32)
    for _ in range(2):
        t.update(DataBatch(data=toks.reshape(8, 1, 1, 8), label=toks,
                           index=np.arange(8, dtype=np.uint32)))
    assert np.isfinite(float(np.asarray(t._last_loss)))
    assert t.check_weight_consistency() == 0.0
