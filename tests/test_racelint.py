"""analysis/racelint.py: the guarded-by concurrency lint (doc/lint.md).

Unit tests drive each rule over synthetic sources; the tree guard runs
the real CLI over the shipped code and asserts exit 0 — a new
cross-thread mutation without a declared policy (or a regression in the
linter itself) fails tier-1 here, the ``tests/test_disclint.py``
pattern applied to the host-side thread fleet.
"""

import json
import os
import subprocess
import sys

from cxxnet_tpu.analysis import racelint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RACELINT = os.path.join(REPO, "cxxnet_tpu", "analysis", "racelint.py")


def findings_for(src):
    return racelint.lint_file("mod.py", src=src)


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------ the rules

def test_undeclared_cross_thread_mutation():
    src = (
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._n = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop,\n"
        "                         name='cxxnet-pump').start()\n"
        "    def _loop(self):\n"
        "        self._n += 1\n"
        "    def stats(self):\n"
        "        return self._n\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_undeclared"]
    assert "Pump._n" in hits[0].message
    # the finding points at the declaration site in __init__
    assert hits[0].line == 4


def test_atomic_policy_silences_single_writer_bump():
    src = (
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._n = 0  # racelint: atomic(single-writer bump)\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop,\n"
        "                         name='cxxnet-pump').start()\n"
        "    def _loop(self):\n"
        "        self._n += 1\n"
        "    def stats(self):\n"
        "        return self._n\n")
    assert not findings_for(src)


def test_rmw_on_atomic_attr_from_shared_context():
    """The GIL-atomic whitelist does not cover lost updates: a += from
    a many-threads context on an ``atomic`` attribute is race_rmw."""
    src = (
        "class Hist:\n"
        "    def __init__(self):\n"
        "        self.n = 0  # racelint: atomic(bump)\n"
        "    # racelint: thread(shared)\n"
        "    def observe(self):\n"
        "        self.n += 1\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_rmw"]
    assert "lost update" in hits[0].message


def test_guarded_by_locked_accesses_are_quiet():
    src = (
        "import threading\n"
        "class Hist:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0  # racelint: guarded-by(self._lock)\n"
        "    # racelint: thread(shared)\n"
        "    def observe(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n")
    assert not findings_for(src)


def test_guarded_by_unlocked_touch_is_race_unguarded():
    src = (
        "import threading\n"
        "class Hist:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0  # racelint: guarded-by(self._lock)\n"
        "    # racelint: thread(shared)\n"
        "    def observe(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def summary(self):\n"
        "        return self.n\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_unguarded"]
    assert hits[0].line == 11


def test_guarded_by_lock_aliases():
    """Several spellings may alias one mutex (a Condition built over the
    lock): holding EITHER declared name satisfies the policy."""
    src = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._idle = threading.Condition(self._lock)\n"
        "        self._pending = 0  "
        "# racelint: guarded-by(self._lock, self._idle)\n"
        "    # racelint: thread(writer)\n"
        "    def _drain(self):\n"
        "        with self._idle:\n"
        "            self._pending -= 1\n"
        "    def submit(self):\n"
        "        with self._lock:\n"
        "            self._pending += 1\n")
    assert not findings_for(src)


def test_check_then_act_across_acquisitions():
    src = (
        "import threading\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition(self._lock)\n"
        "        self._q = []  "
        "# racelint: guarded-by(self._lock, self._cv)\n"
        "    # racelint: thread(worker)\n"
        "    def drain(self):\n"
        "        with self._lock:\n"
        "            if self._q:\n"
        "                with self._cv:\n"
        "                    self._q.pop()\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_check_then_act"]
    assert "stale" in hits[0].message
    # same acquisition covering test and write: quiet
    quiet = (
        "import threading\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = []  # racelint: guarded-by(self._lock)\n"
        "    # racelint: thread(worker)\n"
        "    def drain(self):\n"
        "        with self._lock:\n"
        "            if self._q:\n"
        "                self._q.pop()\n")
    assert not findings_for(quiet)


def test_thread_name_rule():
    bad = ("import threading\n"
           "t = threading.Thread(target=f)\n")
    assert rules_of(findings_for(bad)) == ["race_thread_name"]
    # a dynamic name= the lint cannot verify is still a finding
    dyn = ("import threading\n"
           "t = threading.Thread(target=f, name=some_var)\n")
    assert rules_of(findings_for(dyn)) == ["race_thread_name"]
    good = ("import threading\n"
            "t = threading.Thread(target=f, name='cxxnet-w')\n"
            "u = threading.Thread(target=f, name=f'cxxnet-w-{i}')\n")
    assert not findings_for(good)


def test_container_mutation_counts_as_write():
    """``self._ring.append(x)`` mutates ``_ring`` even though the
    attribute node is only Load-ed."""
    src = (
        "import threading\n"
        "class Bank:\n"
        "    def __init__(self):\n"
        "        self._ring = []\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._tick,\n"
        "                         name='cxxnet-rep').start()\n"
        "    def _tick(self):\n"
        "        self._ring.append(1)\n"
        "    def dump(self):\n"
        "        return list(self._ring)\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_undeclared"]
    assert "Bank._ring" in hits[0].message


def test_construction_window_writes_are_declarations():
    """__init__/init/set_param run before any producer thread exists
    (the iterator contract): their writes never count as mutations."""
    src = (
        "import threading\n"
        "class Iter:\n"
        "    def __init__(self):\n"
        "        self.batch = 0\n"
        "    def set_param(self, v):\n"
        "        self.batch = v\n"
        "    def init(self):\n"
        "        self.batch = int(self.batch)\n"
        "    def before_first(self):\n"
        "        threading.Thread(target=self._produce,\n"
        "                         name='cxxnet-prod').start()\n"
        "    def _produce(self):\n"
        "        return self.batch\n")
    assert not findings_for(src)


def test_thread_subclass_run_is_an_entry():
    src = (
        "import threading\n"
        "class W(threading.Thread):\n"
        "    def __init__(self):\n"
        "        super().__init__(name='cxxnet-w')\n"
        "        self.done = 0\n"
        "    def run(self):\n"
        "        self.done = 1\n"
        "    def poll(self):\n"
        "        return self.done\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_undeclared"]
    assert "W.done" in hits[0].message


def test_nested_handler_class_is_a_shared_context():
    """A BaseHTTPRequestHandler nested in a method reaches the owner
    through an ``alias = self`` binding; its methods run on
    per-connection threads (many at once)."""
    src = (
        "class Srv:\n"
        "    def __init__(self):\n"
        "        self.hits = 0\n"
        "    def build(self):\n"
        "        outer = self\n"
        "        class H(BaseHTTPRequestHandler):\n"
        "            def do_GET(self):\n"
        "                outer.hits += 1\n"
        "        return H\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_undeclared"]
    assert "handler" in hits[0].message


def test_local_closure_thread_target_gets_own_context():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "    def go(self):\n"
        "        def worker():\n"
        "            self.n += 1\n"
        "        threading.Thread(target=worker,\n"
        "                         name='cxxnet-w').start()\n"
        "        return self.n\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_undeclared"]


def test_bad_decl_unknown_lock_and_empty_reason():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.a = 0  # racelint: guarded-by(self._nolock)\n"
        "        self.b = 0  # racelint: atomic()\n")
    hits = findings_for(src)
    assert sorted(rules_of(hits)) == ["race_bad_decl", "race_bad_decl"]
    # an unrecognized directive is a finding, not a silent no-op
    hits = findings_for("x = 1  # racelint: bogus(whatever)\n")
    assert rules_of(hits) == ["race_bad_decl"]
    assert "unrecognized" in hits[0].message


def test_policy_comment_only_attaches_to_line_below():
    src = (
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        # racelint: atomic(single-writer bump)\n"
        "        self._n = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop,\n"
        "                         name='cxxnet-pump').start()\n"
        "    def _loop(self):\n"
        "        self._n += 1\n"
        "    def stats(self):\n"
        "        return self._n\n")
    assert not findings_for(src)


def test_trailing_policy_does_not_leak_to_next_line():
    """A trailing directive covers its own assignment only; the next
    attribute down must not inherit it."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.a = 0  # racelint: atomic(bump)\n"
        "        self.b = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop,\n"
        "                         name='cxxnet-c').start()\n"
        "    def _loop(self):\n"
        "        self.a += 1\n"
        "        self.b += 1\n"
        "    def stats(self):\n"
        "        return (self.a, self.b)\n")
    hits = findings_for(src)
    assert rules_of(hits) == ["race_undeclared"]
    assert "C.b" in hits[0].message


# ------------------------------------------------------------ pragmas

def test_pragma_same_line_and_line_above():
    base = ("import threading\n"
            "t = threading.Thread(target=f)"
            "  # racelint: ok(race_thread_name) — fixture thread\n")
    assert not findings_for(base)
    above = ("import threading\n"
             "# racelint: ok(race_thread_name) — fixture thread\n"
             "t = threading.Thread(target=f)\n")
    assert not findings_for(above)
    # a pragma for a DIFFERENT rule does not suppress
    wrong = ("import threading\n"
             "t = threading.Thread(target=f)"
             "  # racelint: ok(race_rmw) — wrong rule\n")
    assert "race_thread_name" in rules_of(findings_for(wrong))


def test_pragma_without_reason_is_itself_a_finding():
    src = ("import threading\n"
           "t = threading.Thread(target=f)  # racelint: ok(race_thread_name)\n")
    hits = findings_for(src)
    assert "race_pragma_reason" in rules_of(hits)


def test_pragma_ok_file():
    src = ("# racelint: ok-file(race_thread_name) — fixture threads\n"
           "import threading\n"
           "t = threading.Thread(target=f)\n"
           "u = threading.Thread(target=g)\n")
    assert not findings_for(src)


def test_syntax_error_is_a_finding():
    hits = findings_for("def broken(:\n")
    assert rules_of(hits) == ["race_parse"]


# ------------------------------------------------------------ policy API

def test_collect_policies_for_the_witness():
    """monitor/threadcheck.py derives its attr→lock map from this
    function — lint and witness can never disagree."""
    src = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = []  # racelint: guarded-by(self._lock)\n"
        "        self.n = 0  # racelint: atomic(bump)\n")
    pols = racelint.collect_policies("mod.py", src=src)
    assert set(pols) == {"W"}
    assert pols["W"]["_q"].kind == "guarded-by"
    assert pols["W"]["_q"].args == ("self._lock",)
    assert pols["W"]["n"].kind == "atomic"


# ------------------------------------------------------------ the guard

def test_racelint_exits_zero_on_the_tree():
    """The gate itself: every cross-thread attribute in the shipped tree
    carries a declared policy (or an inline, auditable pragma)."""
    r = subprocess.run(
        [sys.executable, RACELINT, "--json"], cwd=REPO,
        capture_output=True, text=True, timeout=300)
    out = json.loads(r.stdout)
    assert r.returncode == 0, json.dumps(out["findings"], indent=2)
    assert out["n_files"] > 50  # it actually walked the tree


def test_racelint_cli_reports_violations(tmp_path):
    p = tmp_path / "viol.py"
    p.write_text("import threading\n"
                 "t = threading.Thread(target=f)\n")
    r = subprocess.run(
        [sys.executable, RACELINT, str(p)], cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "race_thread_name" in r.stdout
