"""Iterator-chain unit tests (round 4+): host-side s2d emission.
Batch-level iterator behaviors live in test_io.py."""

import numpy as np



def test_s2d_emit_iterator_matches_device_transform():
    """Host-side s2d emission (the input_s2d pipeline contract) produces
    exactly the shape/content the device staging transform would, for
    f32 and u8, with and without conv padding; padded u8 passes through
    untransformed (the trainer's device path handles it)."""
    import jax.numpy as jnp
    from cxxnet_tpu.io.data import DataBatch, IIterator
    from cxxnet_tpu.io.iter_proc import S2DEmitIterator, s2d_np
    from cxxnet_tpu.ops import nn as N

    class ListIter(IIterator):
        def __init__(self, batches):
            self.batches = batches
        def before_first(self):
            self.i = 0
        def next(self):
            if self.i >= len(self.batches):
                return None
            self.i += 1
            return self.batches[self.i - 1]

    rnd = np.random.RandomState(3)
    for dtype, (py, px) in [(np.float32, (0, 0)), (np.float32, (2, 2)),
                            (np.uint8, (0, 0))]:
        s, kh, kw = 2, 5, 5
        h = w = 21
        oh = N.conv_out_size(h, kh, s, py)
        ow = N.conv_out_size(w, kw, s, px)
        x = (rnd.randint(0, 255, (4, 3, h, w)).astype(dtype)
             if dtype == np.uint8
             else rnd.randn(4, 3, h, w).astype(dtype))
        b = DataBatch(data=x, label=np.zeros((4, 1), np.float32),
                      index=np.arange(4, dtype=np.uint32))
        it = S2DEmitIterator(ListIter([b]), (s, kh, kw, oh, ow, py, px))
        it.before_first()
        out = it.next()
        want = np.asarray(
            N.s2d_input(jnp.asarray(x), s, kh, kw, oh, ow, py, px)[0])
        np.testing.assert_array_equal(out.data, want)
        assert out.data.dtype == dtype
        assert it.next() is None
    # padded u8: passthrough (trainer normalizes before padding on device)
    x8 = rnd.randint(0, 255, (4, 3, 21, 21)).astype(np.uint8)
    b8 = DataBatch(data=x8, label=np.zeros((4, 1), np.float32),
                   index=np.arange(4, dtype=np.uint32))
    it = S2DEmitIterator(ListIter([b8]), (2, 5, 5, 10, 10, 2, 2))
    it.before_first()
    np.testing.assert_array_equal(it.next().data, x8)


def test_wrap_s2d_splices_beneath_deepest_buffer():
    """main.LearnTask._wrap_s2d must place the s2d emitter BENEATH the
    deepest buffering stage (threadbuffer/membuffer) so the transform
    runs on the producer thread, and wrap the chain directly when no
    buffer exists (round-4 splice logic, previously untested)."""
    from cxxnet_tpu.io.data import IIterator
    from cxxnet_tpu.io.iter_proc import (S2DEmitIterator,
                                         ThreadBufferIterator)
    from cxxnet_tpu.main import LearnTask

    class Base(IIterator):
        base = None

    class Stage(IIterator):
        def __init__(self, base):
            self.base = base

    task = LearnTask.__new__(LearnTask)

    class FakeNet:
        _s2d_args = (2, 5, 5, 9, 9, 0, 0)
    task.net = FakeNet()

    # chain: Stage(ThreadBuffer(Stage(Base))) -> emitter under the buffer
    base = Base()
    chain = Stage(ThreadBufferIterator.__new__(ThreadBufferIterator))
    chain.base.base = Stage(base)
    out = task._wrap_s2d(chain)
    assert out is chain
    assert isinstance(chain.base.base, S2DEmitIterator)
    assert chain.base.base.base is not base  # still the inner Stage
    assert isinstance(chain.base.base.base, Stage)

    # no buffering stage: wrap the whole chain
    plain = Stage(Base())
    out = task._wrap_s2d(plain)
    assert isinstance(out, S2DEmitIterator)
    assert out.base is plain

    # s2d off: untouched
    class PlainNet:
        _s2d_args = None
    task.net = PlainNet()
    it = Stage(Base())
    assert task._wrap_s2d(it) is it
