"""Tail-batch training: pad + mask instead of drop.

The reference trains the last partial batch of an epoch by re-plumbing node
shapes (AdjustBatchSize, neural_net-inl.hpp:266-277).  Here the batch adapter
pads the tail with replicas (DataBatch.tail_mask_padd) and the trainer masks
them out of every loss term — all real instances train, no shape
polymorphism, and the padding content cannot influence the update.
"""

import numpy as np

from cxxnet_tpu.io.data import DataBatch, DataInst, IIterator
from cxxnet_tpu.io.iter_proc import BatchAdaptIterator

from test_trainer import MLP_CONF, make_trainer


class _ListIter(IIterator):
    def __init__(self, insts):
        self.insts = insts
        self.pos = 0

    def before_first(self):
        self.pos = 0

    def next(self):
        if self.pos >= len(self.insts):
            return None
        inst = self.insts[self.pos]
        self.pos += 1
        return inst


def _insts(n, dim=4, seed=0):
    rnd = np.random.RandomState(seed)
    return [DataInst(label=np.array([i % 2], np.float32),
                     data=rnd.rand(1, 1, dim).astype(np.float32),
                     index=i) for i in range(n)]


def test_batch_adapter_pads_tail():
    it = BatchAdaptIterator(_ListIter(_insts(10)))
    it.set_param("batch_size", "4")
    it.set_param("round_batch", "0")
    it.init()
    it.before_first()
    batches = list(iter(it))
    assert len(batches) == 3, "tail must be padded, not dropped"
    assert [b.tail_mask_padd for b in batches] == [0, 0, 2]
    assert [b.num_batch_padd for b in batches] == [0, 0, 2]
    # every real instance appears exactly once among unmasked rows
    seen = [int(i) for b in batches
            for i in b.index[:b.batch_size - b.tail_mask_padd]]
    assert sorted(seen) == list(range(10))
    # replicas copy the last real instance (shape stays uniform)
    assert batches[2].data.shape == batches[0].data.shape
    np.testing.assert_array_equal(batches[2].data[2], batches[2].data[1])


def test_round_batch_unchanged():
    it = BatchAdaptIterator(_ListIter(_insts(10)))
    it.set_param("batch_size", "4")
    it.set_param("round_batch", "1")
    it.init()
    it.before_first()
    batches = list(iter(it))
    assert len(batches) == 3
    # wrap instances are real data: eval-excluded but NOT train-masked
    assert [b.num_batch_padd for b in batches] == [0, 0, 2]
    assert [b.tail_mask_padd for b in batches] == [0, 0, 0]


def _step_params(trainer, batch):
    trainer.update(batch)
    return {k: {t: np.asarray(v) for t, v in g.items()}
            for k, g in trainer.params.items()}


def test_masked_padding_content_invariant():
    """Two padded batches sharing the same real rows but different padding
    content must produce identical parameter updates."""
    rnd = np.random.RandomState(3)
    real_x = rnd.rand(2, 1, 1, 8).astype(np.float32)
    real_y = np.array([[0.0], [1.0]], np.float32)

    def padded(pad_fill):
        x = np.concatenate([real_x, pad_fill], axis=0)
        y = np.concatenate([real_y, np.ones((2, 1), np.float32)], axis=0)
        return DataBatch(data=x, label=y,
                         index=np.arange(4, dtype=np.uint32),
                         num_batch_padd=2, tail_mask_padd=2)

    pa = padded(np.zeros((2, 1, 1, 8), np.float32))
    pb = padded(rnd.rand(2, 1, 1, 8).astype(np.float32) * 50.0)

    ta = make_trainer(MLP_CONF, extra=[("batch_size", "4"), ("seed", "7")])
    tb = make_trainer(MLP_CONF, extra=[("batch_size", "4"), ("seed", "7")])
    params_a = _step_params(ta, pa)
    params_b = _step_params(tb, pb)
    for k in params_a:
        for tag in params_a[k]:
            np.testing.assert_allclose(
                params_a[k][tag], params_b[k][tag], rtol=0, atol=0,
                err_msg=f"padding content leaked into update of {k}/{tag}")


def test_epoch_with_non_dividing_batch_trains_all():
    """An epoch over N instances with batch_size not dividing N must train
    on every instance: memorizing 6 one-hot-separable instances with
    batch 4 drives train error to 0 (impossible if the tail 2 were
    dropped every epoch)."""
    insts = []
    for i in range(6):
        x = np.zeros((1, 1, 8), np.float32)
        x[0, 0, i] = 1.0
        insts.append(DataInst(label=np.array([i % 2], np.float32),
                              data=x, index=i))
    t = make_trainer(MLP_CONF, extra=[("batch_size", "4"), ("eta", "0.5")])
    for _ in range(60):
        it = BatchAdaptIterator(_ListIter(insts))
        it.set_param("batch_size", "4")
        it.init()
        it.before_first()
        for b in iter(it):
            t.update(b)
    # eval on the exact 6 instances (pad excluded from metric path)
    it = BatchAdaptIterator(_ListIter(insts))
    it.set_param("batch_size", "4")
    it.init()
    line = t.evaluate(iter(it), "memorize")
    err = float(line.split("error:")[1])
    assert err == 0.0, f"tail instances failed to train: {line}"
