"""Model zoo + wrapper API tests: configs parse, shapes check out, tiny
variants train."""

import numpy as np
import pytest

from cxxnet_tpu.models import alexnet, googlenet, lenet, mlp
from cxxnet_tpu.nnet.net import Network
from cxxnet_tpu.nnet.netconfig import NetConfig
from cxxnet_tpu.utils.config import parse_config_string


def build(conf_text, batch=2):
    nc = NetConfig()
    nc.configure(parse_config_string(conf_text))
    return Network(nc, batch)


def test_mlp_builder():
    net = build(mlp(num_class=10, input_dim=784, hidden=[100]))
    assert net.node_shapes[net.final_node] == (2, 1, 1, 10)


def test_lenet_builder():
    net = build(lenet())
    assert net.node_shapes[net.final_node] == (2, 1, 1, 10)


def test_alexnet_builder_shapes():
    net = build(alexnet())
    # canonical AlexNet intermediate shapes
    shapes = [net.node_shapes[c.nindex_out[0]] for c in net.connections]
    assert (2, 96, 55, 55) in shapes     # conv1
    assert (2, 256, 27, 27) in shapes    # conv2
    assert (2, 256, 6, 6) in shapes      # pool5
    assert net.node_shapes[net.final_node] == (2, 1, 1, 1000)
    n_params = sum(int(np.prod(p.shape))
                   for g in net.init_params(__import__("jax").random.PRNGKey(0)).values()
                   for p in g.values())
    assert 55_000_000 < n_params < 70_000_000  # ~61M


def test_googlenet_builder_shapes():
    net = build(googlenet())
    shapes = {net.node_shapes[c.nindex_out[0]] for c in net.connections}
    assert (2, 256, 28, 28) in shapes    # inception 3a out
    assert (2, 480, 28, 28) in shapes    # inception 3b out
    assert (2, 832, 7, 7) in shapes      # inception 5a in
    assert (2, 1024, 1, 1) in shapes     # global avg pool
    assert net.node_shapes[net.final_node] == (2, 1, 1, 1000)
    import jax
    n_params = sum(int(np.prod(p.shape))
                   for g in net.init_params(jax.random.PRNGKey(0)).values()
                   for p in g.values())
    # ~7M trunk + ~3.2M per aux head (fc1024 over 4x4x128) = ~13.4M
    assert 12_000_000 < n_params < 15_000_000
    # aux classifier heads present (v1 recipe), tapped at i4a and i4d
    losses = [c for c in net.connections if c.layer.is_loss]
    assert len(losses) == 3
    # single-head variant still available
    net1 = build(googlenet(aux_heads=False))
    losses1 = [c for c in net1.connections if c.layer.is_loss]
    assert len(losses1) == 1


def test_tiny_googlenet_trains():
    """Scaled-down inception net end-to-end: split/ch_concat/padded-pool
    multi-branch graph trains under jit."""
    from cxxnet_tpu.models.zoo import _inception
    lines = [
        "netconfig=start",
        "layer[0->c1] = conv:conv1",
        "  kernel_size = 3", "  stride = 2", "  nchannel = 8",
        "  random_type = xavier",
        "layer[+0] = relu",
    ]
    top = _inception(lines, "ia", "c1", 4, 4, 8, 2, 4, 4)
    lines += [
        f"layer[{top}->gp] = avg_pooling",
        "  kernel_size = 3", "  stride = 2",
        "layer[gp->fl] = flatten",
        "layer[fl->fc] = fullc:fc",
        "  nhidden = 4",
        "layer[fc->fc] = softmax",
        "netconfig=end",
        "input_shape = 3,16,16",
    ]
    conf = "\n".join(lines) + "\nbatch_size = 8\ndev = cpu\neta = 0.1\nmetric = error\nsilent = 1\n"
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.io.data import DataBatch
    t = NetTrainer()
    for k, v in parse_config_string(conf):
        t.set_param(k, v)
    t.init_model()
    rnd = np.random.RandomState(0)
    b = DataBatch(data=rnd.rand(8, 3, 16, 16).astype(np.float32),
                  label=rnd.randint(0, 4, (8, 1)).astype(np.float32),
                  index=np.arange(8, dtype=np.uint32))
    t.start_round(1)
    losses = []
    for _ in range(60):
        t.update(b)
        losses.append(float(t._last_loss))
    assert losses[-1] < losses[0] * 0.7


def test_pooling_pad_shapes():
    """pad on pooling keeps inception pool branch same-size."""
    conf = """
netconfig=start
layer[0->1] = max_pooling
  kernel_size = 3
  stride = 1
  pad = 1
netconfig=end
input_shape = 3,14,14
"""
    net = build(conf)
    assert net.node_shapes[1] == (2, 3, 14, 14)


def test_wrapper_api_numpy_train():
    from cxxnet_tpu.wrapper import Net, train
    conf = mlp(num_class=2, input_dim=8, hidden=[16])
    rnd = np.random.RandomState(0)
    w = rnd.randn(8)
    x = rnd.randn(64, 8).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    net = train(conf, x.reshape(64, 1, 1, 8), 30,
                {"batch_size": 64, "eta": 0.5, "momentum": 0.9,
                 "silent": 1, "metric": "error"},
                label=y, dev="cpu")
    pred = net.predict(x.reshape(64, 1, 1, 8))
    assert (pred == y).mean() > 0.9
    # weight access API
    assert net.get_weight("fc1", "wmat").shape == (16, 8)
    assert net.get_weight("nope", "wmat") is None
    with pytest.raises(ValueError):
        net.get_weight("fc1", "junk")


def test_wrapper_dataiter(tmp_path):
    import gzip
    import struct
    from cxxnet_tpu.wrapper import DataIter
    rnd = np.random.RandomState(0)
    imgs = (rnd.rand(20, 4, 4) * 255).astype(np.uint8)
    labs = rnd.randint(0, 3, 20).astype(np.uint8)
    with gzip.open(tmp_path / "img.gz", "wb") as f:
        f.write(struct.pack(">iiii", 2051, 20, 4, 4))
        f.write(imgs.tobytes())
    with gzip.open(tmp_path / "lab.gz", "wb") as f:
        f.write(struct.pack(">ii", 2049, 20))
        f.write(labs.tobytes())
    it = DataIter(f"""
iter = mnist
path_img = "{tmp_path}/img.gz"
path_label = "{tmp_path}/lab.gz"
batch_size = 10
silent = 1
""")
    assert it.next()
    assert it.get_data().shape == (10, 1, 1, 16)
    assert it.get_label().shape == (10, 1)
    n = 1
    while it.next():
        n += 1
    assert n == 2
    # threadbuffer-wrapped iterator must also be ready right after init
    # (regression: next() on a fresh DataIter used to assert)
    it2 = DataIter(f"""
iter = mnist
path_img = "{tmp_path}/img.gz"
path_label = "{tmp_path}/lab.gz"
batch_size = 10
silent = 1
iter = threadbuffer
""")
    assert it2.next()
    assert it2.get_data().shape == (10, 1, 1, 16)
    it2.before_first()
    n = 0
    while it2.next():
        n += 1
    assert n == 2


def test_resnet_builder_shapes():
    from cxxnet_tpu.models import resnet
    from cxxnet_tpu.nnet.netconfig import NetConfig
    from cxxnet_tpu.utils.config import parse_config_string
    cfg = NetConfig()
    cfg.configure(parse_config_string(resnet(num_class=10, depth=20)))
    # depth 20 = 3 stages x 3 blocks x 2 convs + stem + head fullc
    conv_names = [l.type_name for l in cfg.layers if l.type_name == "conv"]
    assert len(conv_names) == 1 + 18 + 2  # stem + block convs + 2 projections


def test_tiny_resnet_trains():
    """Residual (split/eltsum/batch_norm) family end-to-end under jit."""
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import resnet
    from cxxnet_tpu.nnet.trainer import NetTrainer
    conf = resnet(num_class=4, depth=8, widths=(4, 8, 8), input_side=16) \
        + "batch_size = 8\ndev = cpu\neta = 0.05\nmetric = error\nsilent = 1\n"
    t = NetTrainer()
    for k, v in parse_config_string(conf):
        t.set_param(k, v)
    t.init_model()
    rnd = np.random.RandomState(0)
    b = DataBatch(data=rnd.rand(8, 3, 16, 16).astype(np.float32),
                  label=rnd.randint(0, 4, (8, 1)).astype(np.float32),
                  index=np.arange(8, dtype=np.uint32))
    t.start_round(1)
    losses = []
    for _ in range(60):
        t.update(b)
        losses.append(float(t._last_loss))
    assert losses[-1] < losses[0] * 0.7


def test_vgg_builder_shapes():
    from cxxnet_tpu.models import vgg
    from cxxnet_tpu.nnet.netconfig import NetConfig
    from cxxnet_tpu.utils.config import parse_config_string
    for depth, nconv in ((11, 8), (13, 10), (16, 13), (19, 16)):
        cfg = NetConfig()
        cfg.configure(parse_config_string(vgg(depth=depth)))
        convs = [l for l in cfg.layers if l.type_name == "conv"]
        assert len(convs) == nconv, (depth, len(convs))


def test_tiny_vgg_trains():
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import vgg
    from cxxnet_tpu.nnet.trainer import NetTrainer
    # scale down: 32px input still survives the five 2x pools (32 -> 1)
    conf = vgg(num_class=4, depth=11).replace("input_shape = 3,224,224",
                                              "input_shape = 3,32,32")
    conf = conf.replace("nchannel = 512", "nchannel = 32") \
               .replace("nchannel = 256", "nchannel = 32") \
               .replace("nchannel = 128", "nchannel = 16") \
               .replace("nchannel = 64", "nchannel = 16") \
               .replace("nhidden = 4096", "nhidden = 64") \
               .replace("threshold = 0.5", "threshold = 0.0")
    conf += ("batch_size = 8\ndev = cpu\nupdater = adam\n"
            "eta = 0.003\nmetric = error\nsilent = 1\n")
    t = NetTrainer()
    for k, v in parse_config_string(conf):
        t.set_param(k, v)
    t.init_model()
    rnd = np.random.RandomState(0)
    b = DataBatch(data=rnd.rand(8, 3, 32, 32).astype(np.float32),
                  label=rnd.randint(0, 4, (8, 1)).astype(np.float32),
                  index=np.arange(8, dtype=np.uint32))
    t.start_round(1)
    losses = []
    for _ in range(80):
        t.update(b)
        losses.append(float(t._last_loss))
    assert losses[-1] < losses[0] * 0.8


def test_googlenet_init_threading():
    """googlenet(init=...) must reach every conv (per-layer key) AND,
    since round 5, the fullc heads via the global default line.  (The
    recorded kaiming stream-convergence runs predate the global line —
    their fc heads were gaussian-0.01, as CONVERGENCE.jsonl states;
    this test pins the builder's CURRENT contract.)"""
    from cxxnet_tpu.models import googlenet
    conf = googlenet(init="kaiming")
    assert "xavier" not in conf
    # per-layer sites only (indented); the global tail line is separate
    per_layer = sum(1 for ln in conf.splitlines()
                    if ln != ln.lstrip()
                    and ln.strip() == "random_type = kaiming")
    assert per_layer == 59, per_layer  # 57 trunk/inception + 2 aux convs
    # the global default (outside netconfig) covers the fc heads
    tail = conf.split("netconfig=end", 1)[1]
    assert "random_type = kaiming" in tail
    # default stays xavier
    assert "random_type = xavier" in googlenet()
