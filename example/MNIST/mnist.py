#!/usr/bin/env python3
"""Python-wrapper version of the MNIST example (reference
example/MNIST/mnist.py used the ctypes wrapper; this uses
cxxnet_tpu.wrapper).  Run ./run.sh first to create ./data."""

import sys

sys.path.insert(0, "../..")

from cxxnet_tpu.wrapper import DataIter, Net, train  # noqa: E402

CFG = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1] = sigmoid
layer[+1] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end
input_shape = 1,1,784
"""

ITER = """
iter = mnist
  path_img = ./data/train-images-idx3-ubyte.gz
  path_label = ./data/train-labels-idx1-ubyte.gz
  shuffle = 1
  batch_size = 100
iter = end
"""

EVAL_ITER = ITER.replace("train-images-idx3", "t10k-images-idx3") \
                .replace("train-labels-idx1", "t10k-labels-idx1") \
                .replace("  shuffle = 1\n", "")


def main() -> None:
    dev = sys.argv[1] if len(sys.argv) > 1 else "cpu"
    data = DataIter(ITER)
    eval_data = DataIter(EVAL_ITER)
    net = train(CFG, data, num_round=10,
                param={"eta": "0.1", "momentum": "0.9", "wd": "0.0",
                       "batch_size": "100", "metric": "error"},
                eval_data=eval_data, dev=dev)
    net.save_model("./models/final.model")


if __name__ == "__main__":
    main()
