#!/bin/sh
# Usage: ./run.sh [MNIST.conf|MNIST_CONV.conf|LeNet.conf] [key=value ...]
# Fetches MNIST if possible; falls back to the synthetic generator in
# zero-egress environments (same idx format, trains the same configs).
set -e
conf=${1:-MNIST.conf}
shift 2>/dev/null || true

have_all() {
    for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
             t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
        [ -f "data/$f.gz" ] || return 1
    done
}

fetch_all() {
    command -v wget >/dev/null || return 1
    base=https://ossci-datasets.s3.amazonaws.com/mnist
    for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
             t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
        wget -q --timeout=10 --tries=1 "$base/$f.gz" \
            -O "$tmp/$f.gz" || return 1
    done
}

if ! have_all; then
    tmp=$(mktemp -d)
    if fetch_all; then
        mkdir -p data && mv "$tmp"/*.gz data/
        echo "downloaded MNIST"
    else
        echo "download unavailable; generating synthetic MNIST-format data"
        python ../../tools/make_synth_mnist.py --out ./data \
            --train 2000 --test 500
    fi
    rm -rf "$tmp"
fi

mkdir -p models
PYTHONPATH=../..:$PYTHONPATH python -m cxxnet_tpu "$conf" model_dir=models "$@"
