#!/bin/sh
# Usage: ./run.sh [MNIST.conf|MNIST_CONV.conf|LeNet.conf] [key=value ...]
# Fetches MNIST if possible; falls back to the synthetic generator in
# zero-egress environments (same idx format, trains the same configs).
set -e
conf=${1:-MNIST.conf}
shift 2>/dev/null || true

if [ ! -f data/train-images-idx3-ubyte.gz ]; then
    mkdir -p data
    base=https://ossci-datasets.s3.amazonaws.com/mnist
    if command -v wget >/dev/null && \
       wget -q --timeout=10 "$base/train-images-idx3-ubyte.gz" -O \
           data/train-images-idx3-ubyte.gz 2>/dev/null; then
        for f in train-labels-idx1-ubyte t10k-images-idx3-ubyte \
                 t10k-labels-idx1-ubyte; do
            wget -q "$base/$f.gz" -O "data/$f.gz"
        done
        echo "downloaded MNIST"
    else
        echo "download unavailable; generating synthetic MNIST-format data"
        python ../../tools/make_synth_mnist.py --out ./data \
            --train 2000 --test 500
    fi
fi

mkdir -p models
PYTHONPATH=../..:$PYTHONPATH python -m cxxnet_tpu "$conf" model_dir=models "$@"
