#!/usr/bin/env python3
"""Build an image list (index \t label \t path) for the bowl dataset.

* train: class subfolders under train_folder; class ids follow the column
  order of sampleSubmission.csv (so the submission lines up).
* test: flat folder, label 0.

Usage: gen_img_list.py train|test sampleSubmission.csv image_folder out.lst
"""

import csv
import os
import random
import sys


def main() -> int:
    if len(sys.argv) < 5:
        print("Usage: gen_img_list.py train|test sample_submission.csv "
              "image_folder out.lst")
        return 1
    task, sub_csv, folder, out = sys.argv[1:5]
    random.seed(888)
    with open(sub_csv, newline="") as f:
        classes = next(csv.reader(f))[1:]  # header minus the image column

    rows = []
    if task == "train":
        for cid, cls in enumerate(classes):
            d = os.path.join(folder, cls)
            for img in sorted(os.listdir(d)):
                rows.append((cid, os.path.join(folder, cls, img)))
        random.shuffle(rows)
    else:
        for img in sorted(os.listdir(folder)):
            rows.append((0, os.path.join(folder, img)))

    with open(out, "w") as fo:
        for i, (label, path) in enumerate(rows):
            fo.write(f"{i}\t{label}\t{path}\n")
    print(f"wrote {len(rows)} entries to {out} ({len(classes)} classes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
