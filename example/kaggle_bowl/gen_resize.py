#!/usr/bin/env python3
"""Resize every image under input_folder to 48x48 into output_folder,
preserving one level of class subdirectories (reference gen_train.py /
gen_test.py used ImageMagick; we use cv2)."""

import os
import sys

import cv2

SIZE = 48


def main() -> int:
    if len(sys.argv) < 3:
        print("Usage: gen_resize.py input_folder output_folder")
        return 1
    src, dst = sys.argv[1], sys.argv[2]
    os.makedirs(dst, exist_ok=True)
    n = 0
    for root, _, files in os.walk(src):
        rel = os.path.relpath(root, src)
        outdir = os.path.join(dst, rel) if rel != "." else dst
        os.makedirs(outdir, exist_ok=True)
        for f in files:
            img = cv2.imread(os.path.join(root, f))
            if img is None:
                continue
            img = cv2.resize(img, (SIZE, SIZE),
                             interpolation=cv2.INTER_LINEAR)
            cv2.imwrite(os.path.join(outdir, os.path.splitext(f)[0] + ".jpg"),
                        img)
            n += 1
    print(f"resized {n} images into {dst}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
