#!/usr/bin/env python3
"""Merge test.lst + the pred_raw output into a Kaggle submission csv.

Usage: make_submission.py sampleSubmission.csv test.lst test.txt out.csv
"""

import csv
import os
import sys


def main() -> int:
    if len(sys.argv) < 5:
        print("Usage: make_submission.py sample_submission.csv test.lst "
              "test.txt out.csv")
        return 1
    sub_csv, lst, scores, out = sys.argv[1:5]
    with open(sub_csv, newline="") as f:
        header = next(csv.reader(f))

    names = []
    with open(lst) as f:
        for line in f:
            path = line.rstrip("\n").split("\t")[-1]
            names.append(os.path.basename(path))

    with open(scores) as f:
        score_lines = f.read().splitlines()
    assert len(score_lines) == len(names), \
        f"{len(score_lines)} score rows vs {len(names)} listed images"
    with open(out, "w", newline="") as fo:
        w = csv.writer(fo)
        w.writerow(header)
        for name, line in zip(names, score_lines):
            probs = line.split()
            assert len(probs) == len(header) - 1, \
                f"{len(probs)} scores vs {len(header) - 1} classes"
            w.writerow([name] + probs)
    print(f"wrote submission {out} ({len(names)} rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
