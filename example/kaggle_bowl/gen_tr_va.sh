#!/bin/sh
# split a shuffled train.lst into training and validation lists
head -n 20000 "$1" > tr.lst
tail -n +20001 "$1" > va.lst
wc -l tr.lst va.lst
