#!/bin/sh
# split a shuffled train.lst into training and validation lists (last ~1/6
# held out for validation)
set -e
total=$(wc -l < "$1")
ntr=$(( total * 5 / 6 ))
if [ "$ntr" -lt 1 ] || [ "$ntr" -ge "$total" ]; then
    echo "gen_tr_va.sh: $1 has only $total lines, cannot split" >&2
    exit 1
fi
head -n "$ntr" "$1" > tr.lst
tail -n +"$(( ntr + 1 ))" "$1" > va.lst
wc -l tr.lst va.lst
