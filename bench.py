"""Benchmark: AlexNet training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no quantitative numbers (BASELINE.md); the baseline
constant below is the commonly-cited cuDNN-era single-GPU AlexNet training
throughput (~1000 imgs/sec on a 2015-class GPU, the hardware tier the
reference targeted), so vs_baseline = measured / 1000.  MFU is reported on
stderr using an analytic FLOP count of the traced network (2*MACs forward,
3x forward for fwd+bwd) against the chip's advertised bf16 peak.
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 1000.0

def peak_flops(device_kind: str) -> float:
    # chip peaks live with the analytic cost model (one table for bench
    # MFU, layer attribution, and roofline distance — doc/monitor.md)
    from cxxnet_tpu.analysis.costmodel import peak_flops as _pf
    return _pf(device_kind) or 197e12


def __getattr__(name):  # PEP 562: keep `from bench import PEAK_FLOPS`
    if name == "PEAK_FLOPS":  # (experiments/) without an eager package
        from cxxnet_tpu.analysis.costmodel import PEAK_FLOPS  # import
        return PEAK_FLOPS
    raise AttributeError(name)


def baseline_json(imgs_per_sec: float, extra: dict = None) -> dict:
    """The one-line payload the driver parses from stdout."""
    out = {
        "metric": "alexnet_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 1),
        "unit": "imgs/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
    }
    if extra:
        out.update(extra)
    return out


def metrics_sink_spec(argv=None) -> str:
    """Sink spec for bench records: a ``metrics_sink=jsonl:<path>`` CLI
    arg wins over the CXXNET_METRICS_SINK env var; empty disables."""
    import os
    spec = os.environ.get("CXXNET_METRICS_SINK", "")
    for a in (sys.argv[1:] if argv is None else argv):
        if a.startswith("metrics_sink="):
            spec = a.split("=", 1)[1]
    return spec


def emit_bench_record(payload: dict, argv=None) -> None:
    """Mirror the stdout JSON into the telemetry JSONL sink, so
    BENCH_*.json numbers and monitor records share one field vocabulary
    (device_step_ms, step_ms_median, transformer_device_step_ms, ...)
    and one pandas/gnuplot pipeline reads both."""
    spec = metrics_sink_spec(argv)
    if not spec:
        return
    from cxxnet_tpu.monitor.metrics import MetricsRegistry
    reg = MetricsRegistry()
    reg.configure_sink(spec)
    reg.emit("bench", **payload)
    reg.close()


def conv_flops_per_image(net) -> float:
    """Forward MAC*2 count from the built graph's shapes."""
    from cxxnet_tpu.layers.conv import ConvolutionLayer
    from cxxnet_tpu.layers.fullc import FullConnectLayer
    total = 0.0
    for conn in net.connections:
        l = conn.layer
        if isinstance(l, ConvolutionLayer):
            n, co, oh, ow = net.node_shapes[conn.nindex_out[0]]
            ci = net.node_shapes[conn.nindex_in[0]][1]
            kh, kw = l.param.kernel_height, l.param.kernel_width
            total += 2.0 * co * oh * ow * (ci // l.param.num_group) * kh * kw
        elif isinstance(l, FullConnectLayer):
            _, _, _, nin = net.node_shapes[conn.nindex_in[0]]
            nout = l.param.num_hidden
            total += 2.0 * nin * nout
    return total


def _trace_device_ms(tracedir: str) -> float:
    """Total on-chip XLA-module time in a trace (all modules) — the
    shared parser in cxxnet_tpu/monitor/trace.py (tools/trace_summary.py
    reads the same files for the per-op view)."""
    from cxxnet_tpu.monitor.trace import device_total_ms
    return device_total_ms(tracedir)


def _traced_device_step_ms(t, datas, labels, scan_len, tdir) -> float:
    """One traced update_many dispatch -> on-chip ms/step (shared by the
    AlexNet headline and the transformer secondary)."""
    import shutil

    import jax
    shutil.rmtree(tdir, ignore_errors=True)
    jax.profiler.start_trace(tdir)
    try:
        np.asarray(t.update_many(datas, labels))
    finally:
        jax.profiler.stop_trace()
    return _trace_device_ms(tdir) / scan_len


def bench_lenet() -> float:
    """Secondary BASELINE metric: MNIST LeNet step time (ms)."""
    import jax.numpy as jnp
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import lenet
    net = lenet() + "metric = error\neta = 0.1\nmomentum = 0.9\nsilent = 1\n"
    batch, scan_len = 512, 20
    t = _make_trainer(net, batch, "tpu",
                      extra=[("eval_train", "0")])
    rnd = np.random.RandomState(0)
    datas = jnp.asarray(rnd.rand(scan_len, batch, 1, 28, 28)
                        .astype(np.float32))
    labels = jnp.asarray(
        rnd.randint(0, 10, (scan_len, batch, 1)).astype(np.float32))
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # warmup / compile
    # median of 5: at ~5 ms/step the tunneled dispatch latency dominates
    # single readings (the round-3 "regression" 4.35 -> 4.96 ms was this)
    ms = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(t.update_many(datas, labels))
        ms.append((time.perf_counter() - t0) / scan_len * 1000.0)
    return sorted(ms)[2]


def bench_vgg():
    """Dense-conv MFU secondary: VGG-16 full train step, returning
    ``(imgs_per_sec, mfu)``.  The MXU's home turf — demonstrates the step
    pipeline's MFU ceiling unconstrained by AlexNet's small-channel stem /
    LRN / overlapping pools."""
    import jax
    import jax.numpy as jnp
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import vgg
    batch, scan_len = 128, 10
    t = _make_trainer(
        vgg(depth=16) + "metric = error\neta = 0.01\nmomentum = 0.9\n",
        batch, "tpu", extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("silent", "1")])
    rnd = np.random.RandomState(0)
    datas = jnp.asarray(rnd.rand(scan_len, batch, 3, 224, 224)
                        .astype(np.float32)).astype(jnp.bfloat16)
    labels = jnp.asarray(
        rnd.randint(0, 1000, (scan_len, batch, 1)).astype(np.float32))
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))
    t0 = time.perf_counter()
    np.asarray(t.update_many(datas, labels))
    dt = (time.perf_counter() - t0) / scan_len
    ips = batch / dt
    flops = conv_flops_per_image(t.net)
    dev = jax.devices()[0].device_kind
    peak = peak_flops(dev)
    return ips, 3.0 * flops * ips / peak


def bench_googlenet():
    """Inception-zoo secondary: GoogLeNet b256 full train step under the
    round-5 lowering stack (input_s2d stem, sibling-fused 1x1 reduce
    convs, conv-form band LRN, virtual concat, relu->pool reorder, and
    two overlapped sub-batch chains via batch_split=2).  Returns
    ``(imgs_per_sec, mfu)`` from double-buffered dispatches."""
    from cxxnet_tpu.engine import opts, set_engine_option
    batch, scan_len = 256, 6
    saved = {k: getattr(opts, k)
             for k in ("conv_sibling_fuse", "pallas_lrn", "concat_virtual")}
    try:
        return _bench_googlenet_inner(batch, scan_len)
    finally:
        # engine options are process-global: restore even on failure so a
        # tunnel hiccup here can't silently change what bench_vgg measures
        for k, v in saved.items():
            set_engine_option(k, v)


def _bench_googlenet_inner(batch, scan_len):
    import jax
    import jax.numpy as jnp
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import googlenet
    t = _make_trainer(
        googlenet() + "metric = error\neta = 0.01\nmomentum = 0.9\n"
        "silent = 1\n",
        batch, "tpu", extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("input_s2d", "1"),
                             ("conv_sibling_fuse", "1"),
                             ("pallas_lrn", "bandconv"),
                             ("concat_virtual", "1"),
                             ("batch_split", "2")])
    from cxxnet_tpu.ops.nn import s2d_staged_shape
    s, kh, kw, oh, ow, _, _ = t._s2d_args
    shape = (scan_len, batch) + s2d_staged_shape(3, s, kh, kw, oh, ow)
    kd, kl = jax.random.split(jax.random.PRNGKey(0))
    datas = jax.jit(lambda k: jax.random.uniform(
        k, shape, jnp.float32).astype(jnp.bfloat16))(kd)
    labels = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1), 0, 1000).astype(jnp.float32))(kl)
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # warmup / compile
    pending = t.update_many(datas, labels)
    ms = []
    t_last = time.perf_counter()
    for _ in range(3):
        nxt = t.update_many(datas, labels)
        np.asarray(pending)
        now = time.perf_counter()
        ms.append((now - t_last) / scan_len)
        t_last = now
        pending = nxt
    np.asarray(pending)
    dt = sorted(ms)[1]
    ips = batch / dt
    flops = conv_flops_per_image(t.net)
    mfu = 3.0 * flops * ips / peak_flops(jax.devices()[0].device_kind)
    return ips, mfu


def transformer_flops_per_token(vocab: int, seq: int, dim: int,
                                nlayer: int, ffn_mult: int = 4,
                                causal: bool = True) -> float:
    """Analytic forward model-FLOPs per token (2*MACs; causal attention
    counts the triangle).  Standard convention: backward = 2x forward,
    flash-attention recompute excluded (it inflates hardware FLOPs, not
    model FLOPs)."""
    proj = 4 * 2 * dim * dim                      # q,k,v,out
    attn = 2 * 2 * seq * dim * (0.5 if causal else 1.0)
    ffn = 2 * 2 * dim * ffn_mult * dim
    return nlayer * (proj + attn + ffn) + 2 * dim * vocab


def bench_transformer():
    """Long-context secondary metric: transformer LM at model scale —
    d2048, 12 layers, s4096, flash attention, adam (round-3's d512/4L
    config measured kernel overheads, not a model; VERDICT r3 item 6).
    Returns ``(tokens_per_sec, extras)`` for one chip; MFU is the
    cross-config metric.  ``extras`` always carries the wall tok/s + MFU
    keys, plus the trace-based device step time + device MFU (the
    session-comparable numbers — the round-6 LN and update lowerings are
    judged on them); the two device keys are absent when tracing
    fails."""
    import jax.numpy as jnp
    from cxxnet_tpu.models import transformer
    from __graft_entry__ import _make_trainer
    vocab, seq, dim, nlayer = 8192, 4096, 2048, 12
    batch, scan_len = 4, 4  # b6/L16 exceed HBM at this width
    # dh=128 heads: the MXU is 128 wide, so 64-wide heads leave half the
    # array idle in every attention matmul AND double the per-head softmax
    # VPU work; measured 2.06x on the whole attention layer
    # (experiments/fa_tune.py: 24.0 -> 11.7 ms/layer fwd+bwd)
    t = _make_trainer(
        transformer(vocab=vocab, seq=seq, dim=dim, nlayer=nlayer,
                    nhead=dim // 128),
        batch, "tpu", extra=[("dtype", "bfloat16"), ("updater", "adam"),
                             ("eval_train", "0"), ("silent", "1")])
    import jax
    kd = jax.random.PRNGKey(0)
    # generated on device: token transfer is irrelevant to the metric
    toks = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1, 1, seq), 0, vocab
    ).astype(jnp.float32))(kd)
    # next-token objective: position t is scored against token t+1 (the
    # last position wraps to token 0 — irrelevant for random-token
    # throughput, do not reuse for perplexity)
    labels = jax.jit(lambda a: jnp.roll(a, -1, axis=-1).reshape(
        scan_len, batch, seq))(toks)
    t.start_round(1)
    np.asarray(t.update_many(toks, labels))  # warmup / compile
    ms = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(t.update_many(toks, labels))
        ms.append((time.perf_counter() - t0) / scan_len)
    dt = sorted(ms)[1]
    tok_s = batch * seq / dt
    f_tok = transformer_flops_per_token(vocab, seq, dim, nlayer)
    peak = peak_flops(jax.devices()[0].device_kind)
    mfu = 3.0 * f_tok * tok_s / peak
    print(f"bench: transformer d{dim} L{nlayer} MFU={mfu * 100:.1f}% "
          f"(fwd {f_tok / 1e6:.0f} MFLOPs/token, b{batch})",
          file=sys.stderr)
    extras = {"transformer_tok_s": round(tok_s, 0),
              "transformer_mfu_pct": round(mfu * 100, 1)}
    try:
        dev_ms = _traced_device_step_ms(t, toks, labels, scan_len,
                                        "/tmp/bench_prof_tf")
        dev_mfu = 3.0 * f_tok * batch * seq / (dev_ms / 1e3) / peak
        extras["transformer_device_step_ms"] = round(dev_ms, 2)
        extras["transformer_device_mfu_pct"] = round(dev_mfu * 100, 1)
        print(f"bench: transformer device {dev_ms:.2f} ms/step "
              f"MFU(dev)={dev_mfu * 100:.1f}%", file=sys.stderr)
    except Exception as e:  # tracing must never break the metric
        print(f"bench: transformer device trace failed: {e}",
              file=sys.stderr)
    return tok_s, extras


IO_AB_NET = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 5
  stride = 2
  nchannel = 16
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:fc1
  nhidden = 10
layer[4->4] = softmax
netconfig=end
"""


#: serve-bench model: the io-ab conv net at 24x24 (default), or a tiny
#: MLP under --tiny (CI smoke); random init — the load generator
#: measures the serving plumbing, not model quality
SERVE_TINY_NET = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 32
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 10
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,64
"""


def bench_serve(argv=None) -> dict:
    """``--serve``: closed-loop load generator over the serving
    subsystem (serve/, doc/serve.md).  Sweeps offered QPS: per point,
    ``clients`` paced threads submit single-row requests through the
    micro-batcher for ``duration`` seconds, and the payload reports
    achieved QPS, p50/p95/p99 latency, and the batch-size histogram the
    coalescer produced — the curve that shows batching depth (and
    throughput) rising with load while tail latency stays bounded by
    ``serve_max_wait_ms``.  Overridable ``key=value`` args: ``dev``,
    ``offered_qps`` (csv), ``duration`` (sec/point), ``clients``,
    ``serve_shapes``, ``serve_dtype``, ``serve_max_wait_ms``,
    ``trace_sample`` (span-trace every Nth request and report the
    per-stage p50/p95/p99 request-path decomposition per point —
    doc/monitor.md "Reading a p99 breakdown");
    ``--tiny``/``tiny=1`` swaps in a small MLP and a short sweep for CI
    smokes."""
    import os
    import tempfile
    import threading

    from cxxnet_tpu.monitor.spans import span_records, stage_decomposition
    from cxxnet_tpu.serve import ServeConfig, parse_shapes
    from cxxnet_tpu.serve.host import ServeModel
    from __graft_entry__ import _make_trainer
    args = dict(a.split("=", 1) for a in (argv or []) if "=" in a)
    tiny = args.get("tiny") == "1" or "--tiny" in (argv or [])
    dev = args.get("dev", "tpu")
    duration = float(args.get("duration", "0.5" if tiny else "2.0"))
    clients = int(args.get("clients", "4" if tiny else "8"))
    trace_sample = int(args.get("trace_sample", "0"))
    qps_list = [float(q) for q in args.get(
        "offered_qps", "200" if tiny else "100,400,1600").split(",")]
    cfg = ServeConfig(
        shapes=tuple(parse_shapes(args.get("serve_shapes",
                                           "1,8" if tiny else "1,8,32"))),
        max_wait_ms=float(args.get("serve_max_wait_ms", "2.0")),
        dtype=args.get("serve_dtype", "f32"))
    if tiny:
        t = _make_trainer(SERVE_TINY_NET + "eta = 0.1\nsilent = 1\n",
                          max(cfg.shapes), dev)
        in_shape = (1, 1, 64)
    else:
        side = 24
        t = _make_trainer(
            IO_AB_NET + f"input_shape = 1,{side},{side}\n"
            "eta = 0.1\nsilent = 1\n", max(cfg.shapes), dev)
        in_shape = (1, side, side)
    span_path = None
    if trace_sample > 0:
        # span tracing rides the trainer's own registry: reuse an
        # already-configured sink (CXXNET_METRICS_SINK) or park the
        # span records in a temp JSONL the stage table reads back
        created_sink = not t.metrics.active
        if created_sink:
            fd, span_path = tempfile.mkstemp(
                prefix="bench_serve_spans_", suffix=".jsonl")
            os.close(fd)
            t.metrics.configure_sink(f"jsonl:{span_path}")
        else:
            span_path = t.metrics.sink.path
        t.metrics.configure_tracer(trace_sample)

    def _read_spans():
        if span_path is None:
            return []
        import json as _json
        with open(span_path) as f:
            recs = []
            for line in f:
                try:
                    recs.append(_json.loads(line))
                except ValueError:
                    continue
        return span_records(recs)

    sm = ServeModel(t, cfg, name="bench")
    t0 = time.perf_counter()
    sm.warmup()
    warmup_sec = time.perf_counter() - t0
    rnd = np.random.RandomState(0)
    pool = rnd.randn(256, *in_shape).astype(np.float32)
    points = []
    spans_seen = len(_read_spans())
    try:
        for qps in qps_list:
            lats, errs = [], []
            lock = threading.Lock()
            hist0 = dict(sm.batcher.batch_hist)
            t_start = time.perf_counter()

            def client(cid, rate):
                # closed-loop pacing: each client schedules its next
                # send at 1/rate and, once latency exceeds the interval,
                # naturally degrades to back-to-back (saturation)
                my = []
                nxt = time.perf_counter()
                while True:
                    now = time.perf_counter()
                    if now - t_start >= duration:
                        break
                    if now < nxt:
                        time.sleep(min(nxt - now, 0.005))
                        continue
                    nxt = max(nxt + 1.0 / rate, now)
                    i = (cid * 37 + len(my)) % pool.shape[0]
                    rt0 = time.perf_counter()
                    try:
                        sm.predict(pool[i:i + 1])
                    except BaseException as e:  # noqa: BLE001
                        errs.append(e)
                        return
                    my.append((time.perf_counter() - rt0) * 1e3)
                with lock:
                    lats.extend(my)

            threads = [threading.Thread(target=client,
                                        args=(j, qps / clients),
                                        daemon=True,
                                        name=f"cxxnet-bench-client-{j}")
                       for j in range(clients)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t_start
            if errs:
                raise errs[0]
            hist = {k: v - hist0.get(k, 0)
                    for k, v in sm.batcher.batch_hist.items()
                    if v - hist0.get(k, 0)}
            n = len(lats)
            ls = np.sort(np.asarray(lats)) if n else np.zeros(1)
            rows = sum(k * v for k, v in hist.items())
            points.append({
                "offered_qps": qps,
                "achieved_qps": round(n / max(wall, 1e-9), 1),
                "requests": n,
                "p50_ms": round(float(np.percentile(ls, 50)), 3),
                "p95_ms": round(float(np.percentile(ls, 95)), 3),
                "p99_ms": round(float(np.percentile(ls, 99)), 3),
                "mean_batch": round(rows / max(sum(hist.values()), 1), 2),
                "batch_hist": {str(k): v for k, v in sorted(hist.items())},
            })
            print(f"bench: serve qps={qps:g} -> "
                  f"{points[-1]['achieved_qps']} req/s p50="
                  f"{points[-1]['p50_ms']}ms p95={points[-1]['p95_ms']}ms "
                  f"mean_batch={points[-1]['mean_batch']}",
                  file=sys.stderr)
            if span_path is not None:
                # per-point request-path decomposition: only the spans
                # this offered-QPS point produced
                all_spans = _read_spans()
                dec = stage_decomposition(all_spans[spans_seen:])
                spans_seen = len(all_spans)
                if dec["stages"]:
                    points[-1]["stages"] = dec["stages"]
                    points[-1]["traced_requests"] = dec["requests"]
                    print("bench: serve stage p99 (ms): " + "  ".join(
                        f"{s['stage']}={s['p99_ms']:g}"
                        for s in dec["stages"]), file=sys.stderr)
    finally:
        sm.close()
        if span_path is not None and created_sink:
            t.metrics.close()  # the temp span sink is ours to close
            try:
                os.remove(span_path)
            except OSError:
                pass
    return {
        "metric": "serve_p95_ms",
        "value": points[-1]["p95_ms"] if points else 0.0,
        "unit": "ms",
        "dtype": cfg.dtype,
        "shapes": list(cfg.shapes),
        "clients": clients,
        "warmup_sec": round(warmup_sec, 3),
        "retraces": sm.retraces,
        "trace_sample": trace_sample,
        "points": points,
    }


def bench_io_ab(argv=None) -> dict:
    """``--io-ab``: input-pipeline A/B at the device boundary — the
    ``test_io=1`` twin that KEEPS the device work.  Trains the same small
    conv net over the same synthetic dataset with ``prefetch_device=2``
    vs ``0`` and reports batches/sec plus where the host wall went:
    ``h2d_sec`` (staging, off the critical path when prefetching) and the
    iterator-wait share of the round wall.  Overridable via ``key=value``
    args: ``dev`` (default tpu), ``batch_size``, ``n_inst``,
    ``num_round``."""
    import os
    import tempfile

    from cxxnet_tpu.main import LearnTask
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    import make_synth_mnist as sm
    args = dict(a.split("=", 1)
                for a in (argv or []) if "=" in a)
    dev = args.get("dev", "tpu")
    batch = int(args.get("batch_size", "64"))
    n = int(args.get("n_inst", "2048"))
    num_round = int(args.get("num_round", "3"))
    side = 24
    rnd = np.random.RandomState(0)
    labels = rnd.randint(0, 10, n)
    imgs = np.stack([
        np.clip(sm.class_pattern(l, side, side) * 255
                + rnd.rand(side, side) * 32, 0, 255) for l in labels])
    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        sm.write_idx_images(os.path.join(tmp, "img.gz"), imgs)
        sm.write_idx_labels(os.path.join(tmp, "lbl.gz"), labels)
        conf = os.path.join(tmp, "ab.conf")
        # scratch conf inside a TemporaryDirectory — nothing to tear
        with open(conf, "w") as f:  # disclint: ok(atomic-write)
            f.write(f"""
dev = {dev}
data = train
iter = mnist
  input_flat = 0
  path_img = {tmp}/img.gz
  path_label = {tmp}/lbl.gz
iter = end
{IO_AB_NET}
input_shape = 1,{side},{side}
batch_size = {batch}
eta = 0.01
num_round = {num_round}
metric = error
eval_train = 0
save_model = 0
silent = 1
print_step = 1000000
""")
        for tag, pf in (("on", 2), ("off", 0)):
            sink = os.path.join(tmp, f"metrics_{tag}.jsonl")
            task = LearnTask()
            rc = task.run([conf, f"prefetch_device={pf}",
                           f"metrics_sink=jsonl:{sink}"])
            assert rc == 0, f"io-ab training failed (prefetch={pf})"
            recs = [json.loads(l) for l in open(sink)]
            rounds = [r for r in recs if r["kind"] == "round"]
            # steady state: drop the compile round when more than one ran
            steady = rounds[1:] or rounds
            wall = max(sum(r["wall_sec"] for r in steady), 1e-9)
            batches = sum(r["examples"] for r in steady) / batch
            out[f"batches_per_sec_{tag}"] = round(batches / wall, 2)
            out[f"h2d_sec_{tag}"] = round(
                sum(r["h2d_sec"] for r in steady), 4)
            out[f"iter_wait_share_{tag}"] = round(
                sum(r["iter_wait_sec"] for r in steady) / wall, 4)
            out[f"dispatch_share_{tag}"] = round(
                sum(r["dispatch_sec"] for r in steady) / wall, 4)
    print(f"bench: io-ab {out['batches_per_sec_on']:.1f} batches/sec "
          f"prefetched vs {out['batches_per_sec_off']:.1f} synchronous "
          f"(h2d {out['h2d_sec_on']:.3f}s overlapped vs "
          f"{out['h2d_sec_off']:.3f}s on the critical path)",
          file=sys.stderr)
    return {
        "metric": "io_ab_batches_per_sec",
        "value": out["batches_per_sec_on"],
        "unit": "batches/sec",
        "vs_prefetch_off": round(
            out["batches_per_sec_on"]
            / max(out["batches_per_sec_off"], 1e-9), 3),
        **out,
    }


DP_SCALING_TINY = """
netconfig=start
layer[0->1] = conv:cv1
  kernel_size = 3
  stride = 2
  nchannel = 8
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:fc1
  nhidden = 64
layer[4->5] = relu
layer[5->6] = fullc:fc2
  nhidden = 10
layer[6->6] = softmax
netconfig=end
input_shape = 3,16,16
metric = error
eta = 0.01
momentum = 0.9
silent = 1
"""


#: collective-kind -> mesh-axis attribution for the explicit overlap
#: schedule (parallel/overlap.py): bucketed data reductions lower as
#: all-reduce / reduce-scatter, model-axis weight gathers as all-gather,
#: expert dispatch as all-to-all.  Implicit (GSPMD) runs are attributed
#: by the same table — approximate there, exact for overlap-on runs.
COMM_KIND_AXIS = {
    "all-reduce": "data", "reduce-scatter": "data",
    "all-gather": "model", "all-to-all": "expert",
    "collective-permute": "seq", "collective-broadcast": "other",
}


def _comm_axis_shares(rep, axes=()) -> dict:
    """Per-axis comm share from a comm_report: kind ms -> axis seconds /
    device seconds.  ``axes`` (the mesh's axis names) refines the static
    kind table: collective-permute is the 1F1B stage handoff when the
    mesh has a ``pipe`` axis, ring attention otherwise."""
    dev_sec = rep.get("device_sec", 0.0)
    out = {}
    for kind, ms in rep.get("comm_by_kind", {}).items():
        ax = COMM_KIND_AXIS.get(kind, "other")
        if kind == "collective-permute" and "pipe" in axes:
            ax = "pipe"
        out[ax] = out.get(ax, 0.0) + ms / 1e3
    if dev_sec:
        return {ax: round(sec / dev_sec, 4) for ax, sec in out.items()}
    return {ax: 0.0 for ax in out}


def _hbm_point(t) -> dict:
    """Per-arm memory bytes for the A/B payloads (doc/memory.md).
    Primary: the compiled step's temp/args bytes from
    ``step_memory_stats`` (one extra AOT compile, cached per trainer)
    — deterministic PER ARM, which is what an A/B needs.  The measured
    device high-water (``hbm_peak_bytes``) rides along where the
    backend reports it, but it is the allocator's PROCESS-lifetime
    peak: sequential arms in one process inherit the heaviest earlier
    arm's value, so compare arms on the exec_* columns.  BENCH_r06
    A/Bs read this to show memory wins, not just ms/step."""
    out = {}
    try:
        stats = t.step_memory_stats()
        if stats:
            out.update(exec_temp_bytes=stats["temp_bytes"],
                       exec_args_bytes=stats["args_bytes"])
        out.update(t.memory_gauges())
    except Exception as e:  # memory telemetry must never break the A/B
        print(f"bench: hbm point failed: {e}", file=sys.stderr)
    return out


def _dp_point(net_conf, per_chip_batch, dev, n, overlap, *, data_shape,
              make_data, scan_len, extra=(), bucket_mb="4",
              mesh_str=None):
    """One (model, mesh, overlap-mode) measurement: trainer on the given
    mesh (default the pure ``data:n`` axis), ``update_many`` dispatches
    timed double-buffered, one traced dispatch for the comm/compute
    split.  Returns the point dict for the --dp-scaling /
    --mesh-scaling payloads.  The batch scales with the DATA axis only
    (model/seq/expert axes divide the per-example work, not the
    batch)."""
    import shutil

    import jax
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.monitor.trace import comm_report
    from cxxnet_tpu.parallel.mesh import MeshSpec
    mesh_str = mesh_str or f"data:{n}"
    spec = MeshSpec.parse(mesh_str)
    assert spec.size == n, (mesh_str, n)
    batch = per_chip_batch * spec.axis_size("data")
    mesh_extra = [("fullc_gather", "1")] \
        if spec.axis_size("model") > 1 else []
    n_stage = spec.axis_size("pipe")
    n_micro = 0
    if n_stage > 1:
        user = dict(extra)
        n_micro = int(user.get("pipe_microbatch", 2 * n_stage))
        assert batch % n_micro == 0 and batch % (2 * n_micro) == 0, (
            f"--mesh-scaling pipe point: batch {batch} must divide by "
            f"pipe_microbatch {n_micro} and its doubled bubble-probe "
            f"count {2 * n_micro}")
        mesh_extra += [("pipe_schedule", user.get("pipe_schedule", "1f1b")),
                       ("pipe_microbatch", str(n_micro))]
        extra = tuple(kv for kv in extra
                      if kv[0] not in ("pipe_schedule", "pipe_microbatch"))

    def build(more=()):
        return _make_trainer(
            net_conf, batch, f"{dev}:0-{n - 1}",
            extra=[("mesh", mesh_str),
                   ("dp_overlap", "1" if overlap else "0"),
                   ("dp_bucket_mb", bucket_mb), ("eval_train", "0")]
            + mesh_extra + list(extra) + list(more))

    def timed(t, datas, labels):
        np.asarray(t.update_many(datas, labels))  # warmup / compile
        ms = []
        pending = t.update_many(datas, labels)
        t_last = time.perf_counter()
        for _ in range(3):
            nxt = t.update_many(datas, labels)
            np.asarray(pending)
            now = time.perf_counter()
            ms.append((now - t_last) / scan_len)
            t_last = now
            pending = nxt
        np.asarray(pending)
        return sorted(ms)[1]

    t = build()
    datas, labels = make_data(scan_len, batch, data_shape)
    t.start_round(1)
    dt = timed(t, datas, labels)
    per_chip = batch / dt / n
    point = {"devices": n, "mesh": mesh_str,
             "examples_per_sec_per_chip": round(per_chip, 1),
             "step_sec": round(dt, 5)}
    point.update(_hbm_point(t))
    if n_stage > 1:
        # measured bubble share from a two-point probe: at fixed batch B
        # the 1F1B wall is t(M) ~= tau*B*(1 + (S-1)/M) + c (M+S-1 slots
        # of per-slot cost tau*B/M), so a second run at 2M isolates the
        # fill/drain term: tau*B = (t(M) - t(2M)) / ((S-1)/(2M)) and the
        # share is tau*B*(S-1)/M / t(M) -- which converges on the
        # analytic (S-1)/(M+S-1) as the fixed overhead c vanishes.
        try:
            t2 = build([("pipe_microbatch", str(2 * n_micro))])
            t2.start_round(1)
            dt2 = timed(t2, datas, labels)
            del t2
            analytic = (n_stage - 1) / (n_micro + n_stage - 1)
            try:
                phys = len(os.sched_getaffinity(0))
            except AttributeError:
                phys = os.cpu_count() or 1
            if phys < n:
                # serialized host (fewer physical cores than mesh
                # devices): wall time packs every stage's work onto the
                # same cores, so stage idleness costs nothing and the
                # fill/drain term cancels out of t(M) - t(2M).  What the
                # two-point probe DOES still see is excess executed work
                # (a schedule that runs masked fwd/bwd on idle ticks
                # shows up as ~(2S-2)/M extra wall at M vs 2M) -- so
                # measure that and project the device-time bubble onto
                # the classic (M+S-1)-slot critical path.  A
                # work-efficient schedule measures ~= analytic; a masked
                # one overshoots far past the 20% band.
                measured = analytic + max(dt - dt2, 0.0) * 2 / dt
                probe = "serialized-excess-work"
            else:
                taub = max(dt - dt2, 0.0) * 2 * n_micro / (n_stage - 1)
                measured = taub * (n_stage - 1) / n_micro / dt
                probe = "wall-two-point"
            point.update(
                pipe_microbatch=n_micro,
                pipe_bubble_share_measured=round(measured, 4),
                pipe_bubble_share_analytic=round(analytic, 4),
                pipe_bubble_probe=probe)
        except Exception as e:  # the probe must never break the point
            print(f"bench: pipe bubble probe failed ({mesh_str}): {e}",
                  file=sys.stderr)
    # comm/compute split from a traced dispatch (the number the
    # reference only claimed qualitatively; collective classification in
    # monitor/trace.py).  CPU-runtime traces may carry no XLA-op lines —
    # the shares then report 0 with comm_attributed=false
    tdir = "/tmp/bench_dp_prof"
    try:
        shutil.rmtree(tdir, ignore_errors=True)
        jax.profiler.start_trace(tdir)
        try:
            np.asarray(t.update_many(datas, labels))
        finally:
            jax.profiler.stop_trace()
        rep = comm_report(tdir, steps=scan_len)
        point.update(
            comm_share=rep["comm_share"],
            compute_share=round(max(1.0 - rep["comm_share"], 0.0), 4),
            overlap_frac=rep["overlap_frac"],
            comm_sec=rep["comm_sec"],
            comm_share_per_axis=_comm_axis_shares(rep, tuple(spec.axes)),
            comm_attributed=bool(rep["comm_sec"] or rep["device_sec"]))
    except Exception as e:  # tracing must never break the metric
        print(f"bench: dp-scaling trace failed (n={n}): {e}",
              file=sys.stderr)
        point.update(comm_share=0.0, compute_share=1.0, overlap_frac=0.0,
                     comm_sec=0.0, comm_share_per_axis={},
                     comm_attributed=False)
    del t, datas, labels
    import gc
    gc.collect()
    return point


def _score_model(name, out_models, points, per_chip, counts) -> None:
    """Scaling efficiency vs the SMALLEST measured device count (the
    1-device point under the default ``devices=1,2,4,8``; the payload's
    ``efficiency_baseline_devices`` names the actual baseline when a
    ``devices=`` override omits 1), per overlap mode."""
    base = {tag: points[0][tag]["examples_per_sec_per_chip"]
            for tag in ("overlap_on", "overlap_off")}
    for row in points:
        for tag in ("overlap_on", "overlap_off"):
            row[tag]["scaling_efficiency"] = round(
                row[tag]["examples_per_sec_per_chip"]
                / max(base[tag], 1e-9), 3)
    out_models[name] = {"per_chip_batch": per_chip, "points": points}
    last = points[-1]
    print(f"bench: dp-scaling {name} x{counts[-1]} "
          f"{last['overlap_on']['examples_per_sec_per_chip']:.1f}/chip "
          f"(eff {last['overlap_on']['scaling_efficiency']:.2f}) "
          f"overlap-on vs "
          f"{last['overlap_off']['examples_per_sec_per_chip']:.1f}/chip "
          f"(eff {last['overlap_off']['scaling_efficiency']:.2f}) off",
          file=sys.stderr)


def bench_dp_scaling(argv=None) -> dict:
    """``--dp-scaling``: data-parallel scaling A/B — the AlexNet and
    transformer flagships over 1/2/4/8 devices with the explicit
    bucketed-overlap step (``dp_overlap=1``) vs the implicit-psum step,
    reporting per-chip throughput, scaling efficiency vs the smallest
    measured device count (the 1-device point by default), and
    trace-attributed comm/compute shares.  ``key=value``
    overrides: ``dev`` (default cpu — the acceptance mesh; use tpu on
    hardware), ``devices`` (default 1,2,4,8 clipped to visible),
    ``models`` (alexnet,transformer), ``tiny=1`` swaps in CPU-sized
    stand-ins, ``alexnet_batch``/``tf_batch`` per-chip batch sizes,
    ``dp_bucket_mb``."""
    import os
    args = dict(a.split("=", 1) for a in (argv or []) if "=" in a)
    dev = args.get("dev", "cpu")
    counts = [int(x) for x in args.get("devices", "1,2,4,8").split(",")]
    if dev == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{max(counts)}").strip()
    import jax
    if dev == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    n_avail = len(jax.devices())
    requested = counts
    counts = [n for n in counts if n <= n_avail]
    assert counts, (
        f"--dp-scaling: none of devices={requested} fit the {n_avail} "
        f"visible {dev} device(s); lower devices= or (cpu) make sure no "
        "jax backend initialized before bench could force the host "
        "device count")
    tiny = args.get("tiny", "0") == "1"
    bucket_mb = args.get("dp_bucket_mb", "0.05" if tiny else "4")
    models = args.get("models", "alexnet,transformer").split(",")
    model_spec, _ = _dp_model_table(args, dev, tiny)

    # engine options are process-global: each point sets dp_* through its
    # trainer's config; restore afterwards so later benches in this
    # process measure what they think they measure
    from cxxnet_tpu.engine import opts as eng_opts, set_engine_option
    saved_opts = {k: getattr(eng_opts, k)
                  for k in ("dp_overlap", "dp_bucket_mb")}
    out_models = {}
    try:
        for name in models:
            net, per_chip, shape, make_data, scan_len, extra = \
                model_spec(name)
            points = []
            for n in counts:
                row = {"devices": n}
                for tag, ov in (("overlap_on", True),
                                ("overlap_off", False)):
                    p = _dp_point(net, per_chip, dev, n, ov,
                                  data_shape=shape, make_data=make_data,
                                  scan_len=scan_len, extra=extra,
                                  bucket_mb=bucket_mb)
                    row[tag] = p
                points.append(row)
            _score_model(name, out_models, points, per_chip, counts)
    finally:
        for k, v in saved_opts.items():
            set_engine_option(k, v)
    head = models[0]
    last = out_models[head]["points"][-1]["overlap_on"]
    return {
        "metric": "dp_scaling_examples_per_sec_per_chip",
        "value": last["examples_per_sec_per_chip"],
        "unit": "examples/sec/chip",
        "devices": counts,
        "efficiency_baseline_devices": counts[0],
        "scaling_efficiency": last["scaling_efficiency"],
        "comm_share": last["comm_share"],
        "compute_share": last["compute_share"],
        "models": out_models,
    }


def bench_mesh_scaling(argv=None) -> dict:
    """``--mesh-scaling``: the general form of ``--dp-scaling`` — named
    meshes instead of pure device counts.  Each point trains the
    flagship config(s) on one mesh (``data:N[,model:M]``; model axes
    shard fullc/moe weights via NamedSharding) with the explicit
    overlapped step on vs off, and reports per-chip throughput, scaling
    efficiency vs the FIRST listed mesh, and trace-attributed comm
    share PER AXIS (``comm_share_per_axis``: all-reduce/reduce-scatter
    -> data, all-gather -> model, all-to-all -> expert,
    collective-permute -> pipe on pipelined meshes — exact for
    overlap-on runs, where the schedule places every collective).

    Meshes with a ``pipe`` axis wider than 1 run the 1F1B schedule
    (``pipe_schedule=1f1b``, ``pipe_microbatch`` 2x the axis unless
    overridden) and grow three columns: ``pipe_microbatch``,
    ``pipe_bubble_share_measured`` (two-point probe — a second run at
    double the microbatch count isolates the fill/drain term from the
    per-microbatch cost) and ``pipe_bubble_share_analytic``
    (``(S-1)/(M+S-1)``, the value obsv.py folds into the goodput
    ledger's ``pipe_bubble`` category).  ``pipe_bubble_probe`` names
    the method: ``wall-two-point`` on hosts with at least one physical
    core per mesh device; ``serialized-excess-work`` when the mesh is
    emulated on fewer cores — there stage idleness costs no wall time,
    so the probe instead measures excess executed work (a schedule
    running masked compute on idle ticks overshoots far past the
    analytic) projected onto the classic ``M+S-1``-slot critical path.

    ``key=value`` overrides: ``dev`` (default cpu), ``meshes`` as a
    semicolon list (default
    ``data:1;data:2;data:4;data:2,pipe:2;data:4,model:2`` clipped to
    visible devices), ``models`` (alexnet,transformer), ``tiny=1``
    CPU-sized stand-ins, ``alexnet_batch``/``tf_batch`` per-chip
    batch, ``dp_bucket_mb``."""
    import os
    args = dict(a.split("=", 1) for a in (argv or []) if "=" in a)
    dev = args.get("dev", "cpu")
    from cxxnet_tpu.parallel.mesh import MeshSpec
    mesh_strs = [m for m in args.get(
        "meshes",
        "data:1;data:2;data:4;data:2,pipe:2;data:4,model:2").split(";")
        if m]
    specs = [MeshSpec.parse(m) for m in mesh_strs]
    if dev == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{max(s.size for s in specs)}").strip()
    import jax
    if dev == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    n_avail = len(jax.devices())
    requested = list(mesh_strs)
    keep = [(m, s) for m, s in zip(mesh_strs, specs) if s.size <= n_avail]
    assert keep, (
        f"--mesh-scaling: none of meshes={requested} fit the {n_avail} "
        f"visible {dev} device(s)")
    mesh_strs = [m for m, _ in keep]
    specs = [s for _, s in keep]
    tiny = args.get("tiny", "0") == "1"
    bucket_mb = args.get("dp_bucket_mb", "0.05" if tiny else "4")
    models = args.get("models", "alexnet").split(",")
    model_spec, _counts = _dp_model_table(args, dev, tiny)

    from cxxnet_tpu.engine import opts as eng_opts, set_engine_option
    saved_opts = {k: getattr(eng_opts, k)
                  for k in ("dp_overlap", "dp_bucket_mb")}
    out_models = {}
    try:
        for name in models:
            net, per_chip, shape, make_data, scan_len, extra = \
                model_spec(name)
            points = []
            for m, spec in zip(mesh_strs, specs):
                row = {"mesh": m, "devices": spec.size}
                for tag, ov in (("overlap_on", True),
                                ("overlap_off", False)):
                    row[tag] = _dp_point(
                        net, per_chip, dev, spec.size, ov,
                        data_shape=shape, make_data=make_data,
                        scan_len=scan_len, extra=extra,
                        bucket_mb=bucket_mb, mesh_str=m)
                points.append(row)
            base = {tag: points[0][tag]["examples_per_sec_per_chip"]
                    for tag in ("overlap_on", "overlap_off")}
            for row in points:
                for tag in ("overlap_on", "overlap_off"):
                    row[tag]["scaling_efficiency"] = round(
                        row[tag]["examples_per_sec_per_chip"]
                        / max(base[tag], 1e-9), 3)
            out_models[name] = {"per_chip_batch": per_chip,
                                "points": points}
            last = points[-1]
            print(f"bench: mesh-scaling {name} {last['mesh']} "
                  f"{last['overlap_on']['examples_per_sec_per_chip']:.1f}"
                  f"/chip (eff "
                  f"{last['overlap_on']['scaling_efficiency']:.2f}) "
                  "overlap-on, comm/axis "
                  f"{last['overlap_on']['comm_share_per_axis']}",
                  file=sys.stderr)
            for row in points:
                on = row["overlap_on"]
                if "pipe_bubble_share_measured" in on:
                    print(f"bench: mesh-scaling {name} {row['mesh']} "
                          f"pipe bubble measured "
                          f"{on['pipe_bubble_share_measured']:.3f} vs "
                          f"analytic "
                          f"{on['pipe_bubble_share_analytic']:.3f} at "
                          f"M={on['pipe_microbatch']}",
                          file=sys.stderr)
    finally:
        for k, v in saved_opts.items():
            set_engine_option(k, v)
    head = models[0]
    last = out_models[head]["points"][-1]["overlap_on"]
    pipe_rows = [r["overlap_on"] for r in out_models[head]["points"]
                 if "pipe_bubble_share_measured" in r["overlap_on"]]
    return {
        "metric": "mesh_scaling_examples_per_sec_per_chip",
        "value": last["examples_per_sec_per_chip"],
        "unit": "examples/sec/chip",
        "meshes": mesh_strs,
        "efficiency_baseline_mesh": mesh_strs[0],
        "scaling_efficiency": last["scaling_efficiency"],
        "comm_share": last["comm_share"],
        "comm_share_per_axis": last["comm_share_per_axis"],
        **({"pipe_bubble": {
            "mesh": pipe_rows[-1]["mesh"],
            "pipe_microbatch": pipe_rows[-1]["pipe_microbatch"],
            "measured": pipe_rows[-1]["pipe_bubble_share_measured"],
            "analytic": pipe_rows[-1]["pipe_bubble_share_analytic"],
            "probe": pipe_rows[-1].get("pipe_bubble_probe", ""),
        }} if pipe_rows else {}),
        "models": out_models,
    }


def _dp_model_table(args, dev, tiny):
    """Shared flagship table for --dp-scaling / --mesh-scaling: returns
    ``(model_spec, default_counts)`` where ``model_spec(name)`` yields
    ``(net_conf, per_chip_batch, data_shape, make_data, scan_len,
    extra)``."""
    import jax.numpy as jnp
    f32 = dev == "cpu"

    def conv_data(scan_len, batch, shape):
        rnd = np.random.RandomState(0)
        datas = jnp.asarray(rnd.rand(scan_len, batch, *shape)
                            .astype(np.float32))
        labels = jnp.asarray(rnd.randint(
            0, 10, (scan_len, batch, 1)).astype(np.float32))
        return (datas if f32 else datas.astype(jnp.bfloat16)), labels

    def tf_data(scan_len, batch, shape):
        vocab, seq = shape
        rnd = np.random.RandomState(0)
        toks = rnd.randint(0, vocab, (scan_len, batch, 1, 1, seq))
        labels = np.roll(toks.reshape(scan_len, batch, seq), -1, axis=-1)
        return (jnp.asarray(toks.astype(np.float32)),
                jnp.asarray(labels.astype(np.float32)))

    def model_spec(name):
        from cxxnet_tpu.models import transformer
        from __graft_entry__ import ALEXNET_NET
        if name == "alexnet":
            if tiny:
                return (DP_SCALING_TINY, int(args.get("alexnet_batch", 32)),
                        (3, 16, 16), conv_data, 2, ())
            return (ALEXNET_NET, int(args.get("alexnet_batch", 256)),
                    (3, 227, 227), conv_data, 4,
                    () if f32 else (("dtype", "bfloat16"),))
        assert name == "transformer", name
        vocab, seq, dim, nl = (256, 64, 32, 1) if tiny else \
            (8192, 4096, 2048, 12)
        net = transformer(vocab=vocab, seq=seq, dim=dim, nlayer=nl,
                          nhead=max(dim // 128, 2))
        extra = [("updater", "adam")]
        if not f32:
            extra.append(("dtype", "bfloat16"))
        return (net, int(args.get("tf_batch", 2 if tiny else 1)),
                (vocab, seq), tf_data, 2, tuple(extra))

    return model_spec, [1, 2, 4, 8]


def _lm_chain(shard_pattern, n_shards, seqlen, batch, pack_split=1):
    """text + packseq iterator chain over packed shards."""
    from cxxnet_tpu.io.text import PackedSeqIterator, TextIterator
    it = TextIterator()
    it.set_param("path_tok", shard_pattern)
    it.set_param("tok_count", str(n_shards))
    it.set_param("shuffle", "1")
    it.set_param("silent", "1")
    p = PackedSeqIterator(it)
    p.set_param("seqlen", str(seqlen))
    p.set_param("batch_size", str(batch))
    p.set_param("pack_split", str(pack_split))
    p.init()
    return p


def _lm_nosplit_efficiency(shard_pattern, n_shards, seqlen, batch) -> float:
    """Host-only pass of the whole-document packer over the same shards:
    the padding fraction the split packer avoids."""
    p = _lm_chain(shard_pattern, n_shards, seqlen, batch, pack_split=0)
    p.before_first()
    while p.next() is not None:
        pass
    p.close()
    return p.stats()["packing_efficiency"]


def bench_lm(argv=None) -> dict:
    """``--lm``: tokenized-LM data-path bench over the two flagship
    sequence workloads (example/LM/*.conf shapes) — a long-context
    transformer on ``data:2,seq:2`` and a switch-MoE LM on
    ``data:2,expert:2``.  Generates a synthetic learnable corpus
    (tools/make_synth_text.py), packs it into token shards
    (io/text.py), trains ``steps`` real update dispatches through the
    text+packseq chain, and reports per model: tokens/sec (total and
    per chip), **packing efficiency** (real-token fraction; 1.0 for the
    stream-chop packer, plus the whole-document packer's number on the
    same corpus for comparison), and the trace-attributed **per-axis
    comm shares** (collective-permute → seq, all-to-all → expert; zero
    with ``comm_attributed: false`` on CPU-runtime traces).

    ``key=value`` overrides: ``dev`` (default cpu), ``models``
    (longctx,moe), ``steps``, ``batch``, ``seqlen``, ``vocab``,
    ``docs``; ``--tiny``/``tiny=1`` shrinks everything for CI smoke."""
    import os
    import shutil
    import tempfile

    args = dict(a.split("=", 1) for a in (argv or []) if "=" in a)
    tiny = args.get("tiny") == "1" or "--tiny" in (argv or [])
    dev = args.get("dev", "cpu")
    models = [m for m in args.get("models", "longctx,moe").split(",") if m]
    n_dev = 4
    if dev == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if dev == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    assert len(jax.devices()) >= n_dev, (
        f"--lm needs {n_dev} devices; {len(jax.devices())} visible")
    from cxxnet_tpu.models import transformer
    from cxxnet_tpu.monitor.trace import comm_report
    from __graft_entry__ import _make_trainer
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"))
    from make_synth_text import gen_docs
    from cxxnet_tpu.io.text import write_token_shard

    if tiny:
        vocab, seqlen, dim, nlayer, nhead = 64, 32, 32, 1, 2
        batch, steps, n_docs, mean_len = 4, 4, 200, 24
    else:
        vocab, seqlen, dim, nlayer, nhead = 512, 256, 64, 2, 4
        batch, steps, n_docs, mean_len = 8, 24, 2000, 96
    vocab = int(args.get("vocab", vocab))
    seqlen = int(args.get("seqlen", seqlen))
    batch = int(args.get("batch", batch))
    steps = int(args.get("steps", steps))
    n_docs = int(args.get("docs", n_docs))

    spec = {
        "longctx": ("data:2,seq:2", dict()),
        "moe": ("data:2,expert:2", dict(moe_experts=4)),
    }
    tmp = tempfile.mkdtemp(prefix="bench_lm_")
    out_models = {}
    try:
        docs = gen_docs(n_docs, vocab=vocab, mean_len=mean_len, seed=0)
        n_shards = 4
        pattern = os.path.join(tmp, "c_%d.tok")
        for s in range(n_shards):
            write_token_shard(pattern % s, docs[s::n_shards],
                              itemsize=2 if vocab <= 65536 else 4)
        eff_nosplit = _lm_nosplit_efficiency(pattern, n_shards, seqlen,
                                             batch)
        for name in models:
            assert name in spec, f"--lm: unknown model {name!r}"
            mesh, extra = spec[name]
            # the moe LM needs seqlen % seq axis only for longctx; both
            # meshes are 4 devices
            t = _make_trainer(
                transformer(vocab=vocab, seq=seqlen, dim=dim,
                            nlayer=nlayer, nhead=nhead, packed=True,
                            **extra),
                batch, f"{dev}:0-{n_dev - 1}",
                extra=[("mesh", mesh), ("updater", "adam"),
                       ("eta", "0.001"), ("eval_train", "0"),
                       ("silent", "1")])
            chain = _lm_chain(pattern, n_shards, seqlen, batch)
            t.start_round(1)

            def batches():
                while True:
                    chain.before_first()
                    while True:
                        b = chain.next()
                        if b is None:
                            break
                        yield b

            gen = batches()
            t.update(next(gen))  # warmup / compile
            np.asarray(t._last_loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                t.update(next(gen))
            np.asarray(t._last_loss)
            wall = time.perf_counter() - t0
            tok_s = steps * batch * seqlen / wall
            point = {
                "mesh": mesh, "steps": steps,
                "tokens_per_sec": round(tok_s, 1),
                "tokens_per_sec_per_chip": round(tok_s / n_dev, 1),
                "packing_efficiency": chain.stats()["packing_efficiency"],
                "packing_efficiency_nosplit": eff_nosplit,
                "loss": round(float(np.asarray(t._last_loss)), 4),
            }
            tdir = os.path.join(tmp, f"prof_{name}")
            try:
                jax.profiler.start_trace(tdir)
                try:
                    t.update(next(gen))
                    np.asarray(t._last_loss)
                finally:
                    jax.profiler.stop_trace()
                rep = comm_report(tdir, steps=1)
                point.update(
                    comm_share=rep["comm_share"],
                    overlap_frac=rep["overlap_frac"],
                    comm_share_per_axis=_comm_axis_shares(rep),
                    comm_attributed=bool(rep["comm_sec"]
                                         or rep["device_sec"]))
            except Exception as e:  # tracing must never break the metric
                print(f"bench: lm trace failed ({name}): {e}",
                      file=sys.stderr)
                point.update(comm_share=0.0, overlap_frac=0.0,
                             comm_share_per_axis={},
                             comm_attributed=False)
            chain.close()
            out_models[name] = point
            print(f"bench: lm {name} {mesh} {point['tokens_per_sec']:.0f} "
                  f"tok/s (pack eff {point['packing_efficiency']:.2f} vs "
                  f"{eff_nosplit:.2f} nosplit), comm/axis "
                  f"{point['comm_share_per_axis']}", file=sys.stderr)
            del t
            import gc
            gc.collect()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    head = out_models[models[0]]
    return {
        "metric": "lm_tokens_per_sec",
        "value": head["tokens_per_sec"],
        "unit": "tokens/sec",
        "packing_efficiency": head["packing_efficiency"],
        "comm_share_per_axis": head["comm_share_per_axis"],
        "models": out_models,
    }


def bench_lm_serve(argv=None) -> dict:
    """``--lm-serve``: offered-load sweep over the incremental-decode
    serving path (serve/decode.py + StepScheduler, doc/serve.md
    "Incremental decode").  A tiny transformer LM serves generation
    requests with MIXED target lengths through the KV-cache engine;
    per offered-load point (``clients`` concurrent submitters) the
    payload reports aggregate tokens/sec, per-token step latency
    p50/p95/p99, and the batch-occupancy histogram.  The headline is
    the continuous-vs-request A/B at the highest load: token-level
    admission refills a freed cache slot between decode steps, so the
    short generations in a mixed batch never wait on the longest one —
    ``speedup_continuous`` is that win, and ``retraces`` must stay 0
    across the whole sweep (two executables, PR 8 contract).

    The speculative arm (``spec=1``, default on) additionally trains a
    same-shape flagship plus a small 1-layer draft on a zero-entropy
    Markov corpus and A/Bs draft on/off x continuous/request at the
    highest load — ``speedup_speculative`` with acceptance-rate and
    draft/verify dispatch counts per arm (doc/serve.md "Speculative
    decoding").

    ``key=value`` overrides: ``dev`` (default cpu), ``slots``,
    ``seqlen``, ``requests``, ``clients`` (csv sweep), ``prompt``,
    ``gen_tokens``, ``spec`` (0 skips the speculative arm), ``spec_k``;
    ``--tiny``/``tiny=1`` shrinks everything for CI smoke."""
    import threading

    args = dict(a.split("=", 1) for a in (argv or []) if "=" in a)
    tiny = args.get("tiny") == "1" or "--tiny" in (argv or [])
    dev = args.get("dev", "cpu")
    if dev == "cpu":
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    from cxxnet_tpu.models import transformer
    from cxxnet_tpu.serve.batcher import StepScheduler
    from cxxnet_tpu.serve.decode import DecodeEngine
    from __graft_entry__ import _make_trainer

    if tiny:
        vocab, seqlen, dim, nlayer, nhead = 64, 32, 32, 1, 2
        slots, requests, client_list, cap = 2, 6, [2], 8
        trials = 1
    else:
        # dim 192 keeps the per-step device work well above the
        # Python dispatch+sampling overhead, so the A/B measures
        # scheduling policy, not interpreter noise
        vocab, seqlen, dim, nlayer, nhead = 512, 128, 192, 2, 4
        slots, requests, client_list, cap = 4, 48, [1, 4, 8], 24
        trials = 3
    trials = int(args.get("trials", trials))
    slots = int(args.get("slots", slots))
    seqlen = int(args.get("seqlen", seqlen))
    requests = int(args.get("requests", requests))
    cap = int(args.get("gen_tokens", cap))
    if "clients" in args:
        client_list = [int(c) for c in args["clients"].split(",") if c]
    prompt_len = int(args.get("prompt", max(4, seqlen // 8)))
    prompt_len = min(prompt_len, max(1, seqlen - cap))

    t = _make_trainer(
        transformer(vocab=vocab, seq=seqlen, dim=dim, nlayer=nlayer,
                    nhead=nhead),
        slots, dev, extra=[("updater", "sgd"), ("eta", "0.01"),
                           ("eval_train", "0"), ("silent", "1")])
    engine = DecodeEngine(t, slots=slots, max_seqlen=seqlen,
                          metrics=t.metrics)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_sec = time.perf_counter() - t0
    rnd = np.random.RandomState(0)
    prompts = [rnd.randint(0, vocab, size=(prompt_len,)).astype(np.int32)
               for _ in range(requests)]
    # mixed generation lengths — the workload where request-level
    # batching head-of-line blocks on the longest sequence per batch
    mix = [cap, max(2, cap // 4), max(3, cap // 2), cap]
    lens = [mix[i % len(mix)] for i in range(requests)]

    def run_arm(continuous, clients, eng=None, pr=None, ln=None,
                draft=None, k=0):
        eng = engine if eng is None else eng
        pr = prompts if pr is None else pr
        ln = lens if ln is None else ln
        sched = StepScheduler(eng, max_new_tokens=cap, eos=-1,
                              sample="greedy",
                              queue_depth=requests + 1,
                              continuous=continuous, draft=draft,
                              spec_k=k, metrics=t.metrics,
                              name="bench")
        sched.start()
        lock = threading.Lock()
        idx = [0]
        errs = []
        t_start = time.perf_counter()

        def client():
            while True:
                with lock:
                    i = idx[0]
                    if i >= requests:
                        return
                    idx[0] += 1
                try:
                    sched.submit(pr[i], max_new_tokens=ln[i])
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)
                    return

        threads = [threading.Thread(target=client, daemon=True,
                                    name=f"cxxnet-bench-genclient-{j}")
                   for j in range(clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t_start
        st = sched.stats()
        sched.close()
        if errs:
            raise errs[0]
        st["tokens_per_sec"] = round(st["tokens"] / max(wall, 1e-9), 1)
        st["wall_sec"] = round(wall, 3)
        return st

    # throwaway warm pass: the first executions after AOT compile pay
    # one-time runtime setup that would bias whichever arm runs first
    run_arm(True, min(2, max(1, min(client_list))))

    points = []
    for clients in client_list:
        st = run_arm(True, clients)
        points.append({"clients": clients, **st})
        print(f"bench: lm-serve clients={clients} -> "
              f"{st['tokens_per_sec']} tok/s "
              f"p50={st.get('tok_p50_ms', 0)}ms "
              f"p99={st.get('tok_p99_ms', 0)}ms "
              f"occ={st['mean_occupancy']}", file=sys.stderr)
    # continuous-vs-request A/B at the highest offered load: same
    # engine, same prompts, same mixed lengths — only admission
    # differs.  Interleaved fresh trials, median tokens/sec per arm
    # (run-order and thread-scheduling noise at sub-ms step times
    # otherwise swamps the policy effect)
    hi = max(client_list)
    cont_runs, req_runs = [], []
    for _ in range(max(1, trials)):
        cont_runs.append(run_arm(True, hi))
        req_runs.append(run_arm(False, hi))
    med = (lambda runs: sorted(
        runs, key=lambda s: s["tokens_per_sec"])[len(runs) // 2])
    ab = {"continuous": dict(med(cont_runs), clients=hi),
          "request": dict(med(req_runs), clients=hi)}
    cont_ts = ab["continuous"]["tokens_per_sec"]
    req_ts = ab["request"]["tokens_per_sec"]
    speedup = round(cont_ts / max(req_ts, 1e-9), 3)
    print(f"bench: lm-serve A/B continuous {cont_ts} vs request "
          f"{req_ts} tok/s -> speedup {speedup} "
          f"(retraces {engine.retraces})", file=sys.stderr)

    # ---- speculative arm: draft on/off x continuous/request --------
    # Untrained weights would pin acceptance at ~1/vocab, measuring
    # nothing, so this arm trains a SECOND flagship (same shape) and a
    # much smaller 1-layer draft on a branch=1 Markov corpus — the
    # next token is a fixed function of the current one (conditional
    # entropy 0), so a short run teaches both nets the same transition
    # table and acceptance lands high: the regime speculation targets
    # (doc/serve.md "Speculative decoding").  Same mixed-length
    # workload and client harness; only the round shape differs.
    spec = None
    spec_k = int(args.get("spec_k", 2 if tiny else 4))
    if args.get("spec", "1") == "1":
        import os
        import shutil
        import tempfile
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"))
        from make_synth_text import gen_docs
        from cxxnet_tpu.io.text import write_token_shard
        svocab = 16 if tiny else 64
        ddim, dlayer = (16, 1) if tiny else (64, 1)
        train_steps = 4 if tiny else 80
        tmp = tempfile.mkdtemp(prefix="bench_spec_")
        try:
            docs = gen_docs(60 if tiny else 400, vocab=svocab,
                            mean_len=max(8, seqlen // 2), branch=1,
                            seed=1)
            n_shards = 2
            pattern = os.path.join(tmp, "c_%d.tok")
            for s in range(n_shards):
                write_token_shard(pattern % s, docs[s::n_shards],
                                  itemsize=2)

            def train(net, steps):
                # eta 0.003: the dim-192 flagship diverges at 0.01 on
                # this corpus; both nets reach ~0 loss by 80 steps here
                tr = _make_trainer(net, 8, dev,
                                   extra=[("updater", "adam"),
                                          ("eta", "0.003"),
                                          ("eval_train", "0"),
                                          ("silent", "1")])
                chain = _lm_chain(pattern, n_shards, seqlen, 8)
                tr.start_round(1)
                done = 0
                while done < steps:
                    chain.before_first()
                    while done < steps:
                        b = chain.next()
                        if b is None:
                            break
                        tr.update(b)
                        done += 1
                loss = round(float(np.asarray(tr._last_loss)), 4)
                chain.close()
                return tr, loss

            tf_, f_loss = train(
                transformer(vocab=svocab, seq=seqlen, dim=dim,
                            nlayer=nlayer, nhead=nhead, packed=True),
                train_steps)
            td_, d_loss = train(
                transformer(vocab=svocab, seq=seqlen, dim=ddim,
                            nlayer=dlayer, nhead=2, packed=True),
                train_steps)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        t0 = time.perf_counter()
        eng_s = DecodeEngine(tf_, slots=slots, max_seqlen=seqlen,
                             metrics=tf_.metrics,
                             block_widths=(spec_k + 1,))
        eng_s.warmup()
        eng_d = DecodeEngine(td_, slots=slots, max_seqlen=seqlen,
                             metrics=td_.metrics)
        eng_d.warmup()
        spec_warmup = time.perf_counter() - t0
        # prompts walk the learned table, mixed lengths as the main arm
        a_mul = 2 * (svocab // 3) + 1
        sprompts = []
        for i in range(requests):
            p = np.empty(prompt_len, np.int32)
            p[0] = rnd.randint(0, svocab)
            for j in range(1, prompt_len):
                p[j] = (a_mul * p[j - 1] + 7) % svocab
            sprompts.append(p)
        run_arm(True, min(2, max(1, min(client_list))), eng=eng_s,
                pr=sprompts, draft=eng_d, k=spec_k)  # warm pass
        arms = {"spec_continuous": (True, eng_d, spec_k),
                "plain_continuous": (True, None, 0),
                "spec_request": (False, eng_d, spec_k),
                "plain_request": (False, None, 0)}
        runs = {name: [] for name in arms}
        for _ in range(max(1, trials)):  # interleaved fresh trials
            for name, (cont, d, k) in arms.items():
                runs[name].append(run_arm(cont, hi, eng=eng_s,
                                          pr=sprompts, draft=d, k=k))
        spec_arms = {name: dict(med(rs), clients=hi)
                     for name, rs in runs.items()}
        sp_ts = spec_arms["spec_continuous"]["tokens_per_sec"]
        pl_ts = spec_arms["plain_continuous"]["tokens_per_sec"]
        spec = {
            "vocab": svocab,
            "spec_k": spec_k,
            "train_steps": train_steps,
            "flagship_loss": f_loss,
            "draft_loss": d_loss,
            "draft_dim": ddim,
            "draft_nlayer": dlayer,
            "warmup_sec": round(spec_warmup, 3),
            "retraces": eng_s.retraces + eng_d.retraces,
            "arms": spec_arms,
            "tokens_per_sec": sp_ts,
            "acceptance_rate":
                spec_arms["spec_continuous"].get("acceptance_rate", 0.0),
            "draft_steps":
                spec_arms["spec_continuous"].get("draft_steps", 0),
            "verify_calls":
                spec_arms["spec_continuous"].get("verify_calls", 0),
            "speedup_speculative":
                round(sp_ts / max(pl_ts, 1e-9), 3),
        }
        print(f"bench: lm-serve speculative k={spec_k} "
              f"{sp_ts} vs plain {pl_ts} tok/s -> speedup "
              f"{spec['speedup_speculative']} "
              f"(accept {spec['acceptance_rate']}, "
              f"draft {spec['draft_steps']} / verify "
              f"{spec['verify_calls']}, retraces {spec['retraces']})",
              file=sys.stderr)

    payload = {
        "metric": "lm_serve_tokens_per_sec",
        "value": cont_ts,
        "unit": "tokens/sec",
        "slots": slots,
        "max_seqlen": seqlen,
        "prompt_len": prompt_len,
        "gen_tokens": cap,
        "requests": requests,
        "warmup_sec": round(warmup_sec, 3),
        "retraces": engine.retraces,
        "kv_cache_bytes": engine.kv_cache_bytes(),
        "points": points,
        "ab": ab,
        "speedup_continuous": speedup,
    }
    if spec is not None:
        payload["spec"] = spec
        # headline: the best continuous tokens/sec this round achieved
        # — the speculative arm when the draft pays for itself
        payload["value"] = max(cont_ts, spec["tokens_per_sec"])
    return payload


OPT_AB_ARMS = {
    # arm -> engine/config pairs on top of the flagship transformer
    # (the owed BENCH_r06 session: fused_update and pallas_ln A/Bs,
    # same session, same data — see BASELINE.md round 6)
    "base": (("fused_update", "0"), ("pallas_ln", "1")),
    "fused": (("fused_update", "1"), ("pallas_ln", "1")),
    "ln_x": (("fused_update", "0"), ("pallas_ln", "x")),
    "ln_off": (("fused_update", "0"), ("pallas_ln", "0")),
}


def bench_opt_ab(argv=None) -> dict:
    """``--opt-ab``: the one-command fused_update / pallas_ln A/B.

    Trains the transformer flagship once per arm (engine options set
    through each trainer's own config, process-global hygiene restored
    afterwards) and reports wall ms/step (median of 3 double-buffered
    dispatches) plus the trace-attributed device ms/step per arm, and
    the base/arm speedups.  On TPU this IS the owed BENCH_r06 protocol:

        python bench.py --opt-ab dev=tpu

    ``key=value`` overrides: ``dev`` (default tpu), ``tiny=1``
    (CPU-sized smoke), ``arms`` (comma list from
    base/fused/ln_x/ln_off), ``batch``, ``scan_len``."""
    args = dict(a.split("=", 1) for a in (argv or []) if "=" in a)
    dev = args.get("dev", "tpu")
    tiny = args.get("tiny", "0") == "1"
    arms = [a for a in args.get("arms", "base,fused,ln_x,ln_off")
            .split(",") if a]
    for a in arms:
        assert a in OPT_AB_ARMS, f"--opt-ab: unknown arm {a!r}"
    import jax
    if dev == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    from cxxnet_tpu.engine import _DEFS, opts as eng_opts, \
        set_engine_option
    from __graft_entry__ import _make_trainer
    # the ONE flagship definition all bench modes share
    # (_dp_model_table): --opt-ab must A/B the same transformer
    # --dp-scaling/--mesh-scaling report, or BENCH_r06 comparisons lie
    model_spec, _ = _dp_model_table(args, dev, tiny)
    net, _per_chip, shape, make_data, _sl, tbl_extra = \
        model_spec("transformer")
    batch = int(args.get("batch", "2" if tiny else "4"))
    scan_len = int(args.get("scan_len", "2" if tiny else "4"))
    extra = list(tbl_extra) + [("eval_train", "0"), ("silent", "1")]
    toks, labels = make_data(scan_len, batch, shape)
    saved = {k: getattr(eng_opts, k) for k in _DEFS}
    results = {}
    try:
        for arm in arms:
            t = _make_trainer(net, batch, dev,
                              extra=extra + list(OPT_AB_ARMS[arm]))
            t.start_round(1)
            np.asarray(t.update_many(toks, labels))  # warmup / compile
            ms = []
            pending = t.update_many(toks, labels)
            t_last = time.perf_counter()
            for _ in range(3):
                nxt = t.update_many(toks, labels)
                np.asarray(pending)
                now = time.perf_counter()
                ms.append((now - t_last) / scan_len * 1e3)
                t_last = now
                pending = nxt
            np.asarray(pending)
            entry = {"step_ms": round(sorted(ms)[1], 3),
                     "opts": dict(OPT_AB_ARMS[arm])}
            entry.update(_hbm_point(t))
            try:
                dev_ms = _traced_device_step_ms(
                    t, toks, labels, scan_len, "/tmp/bench_opt_ab")
                entry["device_step_ms"] = round(dev_ms, 3)
            except Exception as e:  # tracing must never break the A/B
                print(f"bench: opt-ab trace failed ({arm}): {e}",
                      file=sys.stderr)
            results[arm] = entry
            print(f"bench: opt-ab {arm} {entry['step_ms']:.2f} ms/step"
                  + (f" ({entry['device_step_ms']:.2f} device)"
                     if "device_step_ms" in entry else ""),
                  file=sys.stderr)
            import gc
            del t, pending
            gc.collect()
    finally:
        for k, v in saved.items():
            set_engine_option(k, v)
    base_ms = results.get("base", {}).get("step_ms", 0.0)
    payload = {
        "metric": "opt_ab_step_ms",
        "value": base_ms,
        "unit": "ms/step",
        "arms": results,
    }
    for arm, entry in results.items():
        if arm != "base" and base_ms:
            payload[f"speedup_{arm}"] = round(
                base_ms / max(entry["step_ms"], 1e-9), 3)
    return payload


def pop_against(argv):
    """Extract ``--against PATH`` (or ``--against=PATH``) from an argv
    list; returns ``(path_or_None, remaining_argv)``."""
    out, path = [], None
    it = iter(argv)
    for a in it:
        if a == "--against":
            path = next(it, None)
            if path is None or path.startswith("--"):
                # an unset $BASELINE must not swallow the next flag as
                # the path (silently running the wrong bench mode)
                raise SystemExit("bench: --against needs a "
                                 "BENCH_rNN.json path")
        elif a.startswith("--against="):
            path = a.split("=", 1)[1]
            if not path:
                # an unset $BASELINE must not silently drop the gate
                raise SystemExit("bench: --against= needs a "
                                 "BENCH_rNN.json path")
        else:
            out.append(a)
    return path, out


def against_verdict(payload: dict, path: str, rel: float = 0.10) -> int:
    """``--against BENCH_rNN.json``: judge this payload against a
    recorded round through the one comparison engine
    (cxxnet_tpu/monitor/diff.py) — the one-command verdict a bench
    session ends with.  Returns the process exit code: 1 on any
    regression past ``rel``, 2 when the baseline file is missing or
    unreadable (distinct from the regression verdict, like obsv's
    --diff), and prints the aligned table to stderr."""
    from cxxnet_tpu.monitor.diff import diff_bench, render_diff
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench: --against {path}: {e}", file=sys.stderr)
        return 2
    d = diff_bench(prior, payload, rel=rel)
    print(render_diff(d, label_a=os.path.basename(path),
                      label_b="this run"), file=sys.stderr)
    return 1 if d["regressions"] else 0


#: --flag -> mode function; each takes the remaining argv and returns
#: the one-line JSON payload (main() owns the sink mirror + print)
BENCH_MODES = {
    "--mesh-scaling": bench_mesh_scaling,
    "--opt-ab": bench_opt_ab,
    "--dp-scaling": bench_dp_scaling,
    "--io-ab": bench_io_ab,
    "--serve": bench_serve,
    "--lm": bench_lm,
    "--lm-serve": bench_lm_serve,
}


def main() -> None:
    # --against BENCH_rNN.json: after ANY mode (or the headline) ran,
    # judge the payload against the recorded round and exit nonzero on
    # regression — the BENCH_r06 protocol's one-command verdict
    against, argv = pop_against(sys.argv[1:])
    for flag, mode in BENCH_MODES.items():
        if flag not in argv:
            continue
        payload = mode([a for a in argv if a != flag])
        try:
            emit_bench_record(payload)
        except Exception as e:  # the sink must never break the payload
            print(f"bench: metrics sink failed: {e}", file=sys.stderr)
        print(json.dumps(payload))
        if against:
            sys.exit(against_verdict(payload, against))
        return
    import jax
    from __graft_entry__ import ALEXNET_NET, _make_trainer

    batch = 1024  # measured +3% imgs/sec over 512 on v5e
    scan_len = 10
    trials = 5
    # input_s2d = 1: the input pipeline delivers space-to-depth batches,
    # so conv1 runs as the dense stride-1 conv (same-session A/B device
    # trace: 46.57 -> 43.45 ms/step, experiments/ab.py round 4)
    t = _make_trainer(ALEXNET_NET, batch, "tpu",
                      extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("input_s2d", "1")])
    import jax.numpy as jnp
    # batches generated and staged ON DEVICE in model dtype (and in the
    # pipeline's s2d delivery shape): this measures chip compute
    # throughput, not host->device link bandwidth (the input pipeline
    # overlaps transfers in real training; over a tunneled link
    # host-side generation + transfer of ~6 GB dominated the run).
    # update_many runs scan_len steps per dispatch, amortizing launch
    # latency the way a real input pipeline keeps the device queue full.
    kd, kl = jax.random.split(jax.random.PRNGKey(0))
    from cxxnet_tpu.ops.nn import s2d_staged_shape
    s, kh, kw, oh, ow, _, _ = t._s2d_args
    data_shape = (scan_len, batch) + s2d_staged_shape(3, s, kh, kw, oh, ow)
    datas = jax.jit(lambda k: jax.random.uniform(
        k, data_shape, jnp.float32
    ).astype(jnp.bfloat16))(kd)
    labels = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1), 0, 1000).astype(jnp.float32))(kl)
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # warmup / compile
    # variance discipline (VERDICT r3 weak 1): per-trial timings, median
    # + spread in the JSON — chip-session/tunnel noise is ±1.5-2 ms, so
    # a single aggregate reading overstates round-over-round deltas.
    # Dispatches are DOUBLE-BUFFERED (issue group k+1 before syncing
    # group k — losses are lazy device arrays and the params dependency
    # lives on device), so the per-dispatch tunnel round trip rides
    # behind device execution instead of serializing with it; this is
    # how a real input pipeline keeps the device queue full.
    trial_ms = []
    pending = t.update_many(datas, labels)  # fill the pipe
    t_last = time.perf_counter()
    for _ in range(trials):
        nxt = t.update_many(datas, labels)
        np.asarray(pending)  # sync the in-flight group
        now = time.perf_counter()
        trial_ms.append((now - t_last) / scan_len * 1000.0)
        t_last = now
        pending = nxt
    np.asarray(pending)
    ts = sorted(trial_ms)
    step_ms = ts[len(ts) // 2]
    imgs_per_sec = batch / (step_ms / 1e3)

    flops_fwd = conv_flops_per_image(t.net)
    train_flops = 3.0 * flops_fwd * imgs_per_sec
    dev_kind = jax.devices()[0].device_kind
    peak = peak_flops(dev_kind)
    mfu = train_flops / peak
    print(f"bench: AlexNet b{batch} step={step_ms:.1f}ms "
          f"[{ts[0]:.1f}..{ts[-1]:.1f}] "
          f"imgs/sec={imgs_per_sec:.1f} fwd_gflops/img={flops_fwd / 1e9:.2f} "
          f"device={dev_kind} MFU={mfu * 100:.1f}%", file=sys.stderr)
    spread = {"step_ms_median": round(step_ms, 2),
              "step_ms_min": round(ts[0], 2),
              "step_ms_max": round(ts[-1], 2),
              "trials": len(ts)}
    # device time from a trace: wall carries per-dispatch tunnel latency
    # that varies 3-10 ms/step BETWEEN sessions (tight within a session),
    # so the on-chip number is the comparable one across rounds
    try:
        dev_ms = _traced_device_step_ms(t, datas, labels, scan_len,
                                        "/tmp/bench_prof")
        spread["device_step_ms"] = round(dev_ms, 2)
        dev_mfu = 3.0 * flops_fwd * batch / (dev_ms / 1e3) / peak
        spread["device_mfu_pct"] = round(dev_mfu * 100, 1)
        print(f"bench: AlexNet device {dev_ms:.2f} ms/step "
              f"MFU(dev)={dev_mfu * 100:.1f}%", file=sys.stderr)
    except Exception as e:  # tracing must never break the headline
        print(f"bench: device-time trace failed: {e}", file=sys.stderr)
    # free HBM before the secondary benches: the trainer sits in reference
    # cycles (step closures <-> trainer), so an explicit collect is what
    # actually releases the device buffers — without it the transformer/
    # GoogLeNet/VGG secondaries die with RESOURCE_EXHAUSTED
    import gc
    del t, datas, labels, pending
    gc.collect()
    try:
        lenet_ms = bench_lenet()
        print(f"bench: LeNet b512 step={lenet_ms:.2f}ms "
              f"(BASELINE secondary metric)", file=sys.stderr)
    except Exception as e:  # secondary metric must never break the headline
        print(f"bench: LeNet secondary metric failed: {e}", file=sys.stderr)
    gc.collect()
    try:
        tok_s, tf_extras = bench_transformer()
        spread.update(tf_extras)
        print(f"bench: transformer LM s4096 {tok_s:.0f} tokens/sec "
              f"(long-context secondary metric)", file=sys.stderr)
    except Exception as e:
        print(f"bench: transformer secondary metric failed: {e}",
              file=sys.stderr)
    gc.collect()
    try:
        g_ips, g_mfu = bench_googlenet()
        print(f"bench: GoogLeNet b256 {g_ips:.0f} imgs/sec "
              f"MFU={g_mfu * 100:.1f}% (inception secondary metric)",
              file=sys.stderr)
    except Exception as e:
        print(f"bench: GoogLeNet secondary metric failed: {e}",
              file=sys.stderr)
    gc.collect()
    try:
        vgg_ips, vgg_mfu = bench_vgg()
        print(f"bench: VGG-16 b128 {vgg_ips:.0f} imgs/sec "
              f"MFU={vgg_mfu * 100:.1f}% (dense-conv secondary metric)",
              file=sys.stderr)
    except Exception as e:
        print(f"bench: VGG secondary metric failed: {e}", file=sys.stderr)
    payload = baseline_json(imgs_per_sec, spread)
    try:
        emit_bench_record(payload)
    except Exception as e:  # the sink must never break the headline
        print(f"bench: metrics sink failed: {e}", file=sys.stderr)
    print(json.dumps(payload))
    if against:
        sys.exit(against_verdict(payload, against))


if __name__ == "__main__":
    main()
