"""Benchmark: AlexNet training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no quantitative numbers (BASELINE.md); the baseline
constant below is the commonly-cited cuDNN-era single-GPU AlexNet training
throughput (~1000 imgs/sec on a 2015-class GPU, the hardware tier the
reference targeted), so vs_baseline = measured / 1000.  MFU is reported on
stderr using an analytic FLOP count of the traced network (2*MACs forward,
3x forward for fwd+bwd) against the chip's advertised bf16 peak.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 1000.0
PEAK_FLOPS = {  # bf16 peak per chip
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
    "TPU v5p": 459e12, "TPU v6e": 918e12,
}


def peak_flops(device_kind: str) -> float:
    return next((v for k, v in PEAK_FLOPS.items() if k in device_kind),
                197e12)


def baseline_json(imgs_per_sec: float) -> dict:
    """The one-line payload the driver parses from stdout."""
    return {
        "metric": "alexnet_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 1),
        "unit": "imgs/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
    }


def conv_flops_per_image(net) -> float:
    """Forward MAC*2 count from the built graph's shapes."""
    from cxxnet_tpu.layers.conv import ConvolutionLayer
    from cxxnet_tpu.layers.fullc import FullConnectLayer
    total = 0.0
    for conn in net.connections:
        l = conn.layer
        if isinstance(l, ConvolutionLayer):
            n, co, oh, ow = net.node_shapes[conn.nindex_out[0]]
            ci = net.node_shapes[conn.nindex_in[0]][1]
            kh, kw = l.param.kernel_height, l.param.kernel_width
            total += 2.0 * co * oh * ow * (ci // l.param.num_group) * kh * kw
        elif isinstance(l, FullConnectLayer):
            _, _, _, nin = net.node_shapes[conn.nindex_in[0]]
            nout = l.param.num_hidden
            total += 2.0 * nin * nout
    return total


def bench_lenet() -> float:
    """Secondary BASELINE metric: MNIST LeNet step time (ms)."""
    import jax.numpy as jnp
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import lenet
    net = lenet() + "metric = error\neta = 0.1\nmomentum = 0.9\nsilent = 1\n"
    batch, scan_len = 512, 20
    t = _make_trainer(net, batch, "tpu",
                      extra=[("eval_train", "0")])
    rnd = np.random.RandomState(0)
    datas = jnp.asarray(rnd.rand(scan_len, batch, 1, 28, 28)
                        .astype(np.float32))
    labels = jnp.asarray(
        rnd.randint(0, 10, (scan_len, batch, 1)).astype(np.float32))
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # warmup / compile
    t0 = time.perf_counter()
    np.asarray(t.update_many(datas, labels))
    return (time.perf_counter() - t0) / scan_len * 1000.0


def bench_vgg():
    """Dense-conv MFU secondary: VGG-16 full train step, returning
    ``(imgs_per_sec, mfu)``.  The MXU's home turf — demonstrates the step
    pipeline's MFU ceiling unconstrained by AlexNet's small-channel stem /
    LRN / overlapping pools."""
    import jax
    import jax.numpy as jnp
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models import vgg
    batch, scan_len = 128, 10
    t = _make_trainer(
        vgg(depth=16) + "metric = error\neta = 0.01\nmomentum = 0.9\n",
        batch, "tpu", extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("silent", "1")])
    rnd = np.random.RandomState(0)
    datas = jnp.asarray(rnd.rand(scan_len, batch, 3, 224, 224)
                        .astype(np.float32)).astype(jnp.bfloat16)
    labels = jnp.asarray(
        rnd.randint(0, 1000, (scan_len, batch, 1)).astype(np.float32))
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))
    t0 = time.perf_counter()
    np.asarray(t.update_many(datas, labels))
    dt = (time.perf_counter() - t0) / scan_len
    ips = batch / dt
    flops = conv_flops_per_image(t.net)
    dev = jax.devices()[0].device_kind
    peak = peak_flops(dev)
    return ips, 3.0 * flops * ips / peak


def transformer_flops_per_token(vocab: int, seq: int, dim: int,
                                nlayer: int, ffn_mult: int = 4,
                                causal: bool = True) -> float:
    """Analytic forward model-FLOPs per token (2*MACs; causal attention
    counts the triangle).  Standard convention: backward = 2x forward,
    flash-attention recompute excluded (it inflates hardware FLOPs, not
    model FLOPs)."""
    proj = 4 * 2 * dim * dim                      # q,k,v,out
    attn = 2 * 2 * seq * dim * (0.5 if causal else 1.0)
    ffn = 2 * 2 * dim * ffn_mult * dim
    return nlayer * (proj + attn + ffn) + 2 * dim * vocab


def bench_transformer() -> float:
    """Long-context secondary metric: transformer LM step time (flash
    attention path), tokens/sec on one chip."""
    import jax.numpy as jnp
    from cxxnet_tpu.models import transformer
    from __graft_entry__ import _make_trainer
    vocab, seq, batch, scan_len = 512, 4096, 16, 4  # b2->16: +49% tok/s
    t = _make_trainer(
        transformer(vocab=vocab, seq=seq, dim=512, nlayer=4, nhead=8),
        batch, "tpu", extra=[("dtype", "bfloat16"), ("updater", "adam"),
                             ("eval_train", "0"), ("silent", "1")])
    rnd = np.random.RandomState(0)
    toks = rnd.randint(0, vocab, (scan_len, batch, 1, 1, seq))
    datas = jnp.asarray(toks.astype(np.float32))
    # next-token objective: position t is scored against token t+1 (the
    # last position wraps to token 0 — irrelevant for random-token
    # throughput, do not reuse for perplexity)
    labels = jnp.asarray(np.roll(toks, -1, axis=-1)
                         .reshape(scan_len, batch, seq).astype(np.float32))
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # warmup / compile
    t0 = time.perf_counter()
    np.asarray(t.update_many(datas, labels))
    dt = (time.perf_counter() - t0) / scan_len
    tok_s = batch * seq / dt
    import jax
    f_tok = transformer_flops_per_token(vocab, seq, 512, 4)
    mfu = 3.0 * f_tok * tok_s / peak_flops(jax.devices()[0].device_kind)
    print(f"bench: transformer MFU={mfu * 100:.1f}% "
          f"(fwd {f_tok / 1e6:.1f} MFLOPs/token, b{batch})",
          file=sys.stderr)
    return tok_s


def main() -> None:
    import jax
    from __graft_entry__ import ALEXNET_NET, _make_trainer

    batch = 1024  # measured +3% imgs/sec over 512 on v5e
    scan_len = 10
    trials = 3
    # input_s2d = 1: the input pipeline delivers space-to-depth batches,
    # so conv1 runs as the dense stride-1 conv (same-session A/B device
    # trace: 46.57 -> 43.45 ms/step, experiments/ab.py round 4)
    t = _make_trainer(ALEXNET_NET, batch, "tpu",
                      extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                             ("input_s2d", "1")])
    import jax.numpy as jnp
    # batches generated and staged ON DEVICE in model dtype (and in the
    # pipeline's s2d delivery shape): this measures chip compute
    # throughput, not host->device link bandwidth (the input pipeline
    # overlaps transfers in real training; over a tunneled link
    # host-side generation + transfer of ~6 GB dominated the run).
    # update_many runs scan_len steps per dispatch, amortizing launch
    # latency the way a real input pipeline keeps the device queue full.
    kd, kl = jax.random.split(jax.random.PRNGKey(0))
    from cxxnet_tpu.ops.nn import s2d_staged_shape
    s, kh, kw, oh, ow, _, _ = t._s2d_args
    data_shape = (scan_len, batch) + s2d_staged_shape(3, s, kh, kw, oh, ow)
    datas = jax.jit(lambda k: jax.random.uniform(
        k, data_shape, jnp.float32
    ).astype(jnp.bfloat16))(kd)
    labels = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1), 0, 1000).astype(jnp.float32))(kl)
    t.start_round(1)
    np.asarray(t.update_many(datas, labels))  # warmup / compile
    t0 = time.perf_counter()
    for _ in range(trials):
        losses = t.update_many(datas, labels)
    np.asarray(losses)  # sync
    dt = time.perf_counter() - t0
    steps = trials * scan_len
    imgs_per_sec = batch * steps / dt
    step_ms = dt / steps * 1000.0

    flops_fwd = conv_flops_per_image(t.net)
    train_flops = 3.0 * flops_fwd * imgs_per_sec
    dev_kind = jax.devices()[0].device_kind
    peak = peak_flops(dev_kind)
    mfu = train_flops / peak
    print(f"bench: AlexNet b{batch} step={step_ms:.1f}ms "
          f"imgs/sec={imgs_per_sec:.1f} fwd_gflops/img={flops_fwd / 1e9:.2f} "
          f"device={dev_kind} MFU={mfu * 100:.1f}%", file=sys.stderr)
    del t, datas, labels, losses  # free HBM before the secondary benches
    try:
        lenet_ms = bench_lenet()
        print(f"bench: LeNet b512 step={lenet_ms:.2f}ms "
              f"(BASELINE secondary metric)", file=sys.stderr)
    except Exception as e:  # secondary metric must never break the headline
        print(f"bench: LeNet secondary metric failed: {e}", file=sys.stderr)
    try:
        tok_s = bench_transformer()
        print(f"bench: transformer LM s4096 {tok_s:.0f} tokens/sec "
              f"(long-context secondary metric)", file=sys.stderr)
    except Exception as e:
        print(f"bench: transformer secondary metric failed: {e}",
              file=sys.stderr)
    try:
        vgg_ips, vgg_mfu = bench_vgg()
        print(f"bench: VGG-16 b128 {vgg_ips:.0f} imgs/sec "
              f"MFU={vgg_mfu * 100:.1f}% (dense-conv secondary metric)",
              file=sys.stderr)
    except Exception as e:
        print(f"bench: VGG secondary metric failed: {e}", file=sys.stderr)
    print(json.dumps(baseline_json(imgs_per_sec)))


if __name__ == "__main__":
    main()
