#!/usr/bin/env python
"""Synthetic LM corpus generator: zipf-ish document lengths + learnable
first-order n-gram structure (the text analogue of make_synth_mnist.py).

Documents are token-id sequences drawn from a sparse first-order Markov
chain: from token ``t`` the next token is one of a handful of fixed
successors ``(a*t + b + j) mod vocab`` (j < branch), chosen uniformly.
The conditional entropy is therefore ``log(branch)`` nats — far below
the unigram ``log(vocab)`` a model starts at — so a causal LM's loss
demonstrably falls as it learns the transition table (the CONVERGENCE
signal), while document lengths follow a truncated zipf so the packer
(`io/text.py::PackedSeqIterator`) sees realistic length skew.

Writes a plain-text corpus (one document per line, space-separated
integer token ids — the ``tools/tok2bin.py`` input format), and with
``--pack N`` also packs it straight into N token shards.

    python tools/make_synth_text.py --out corpus.txt --docs 2000 \
        --vocab 512 --pack 4 --shard-prefix corpus_%d.tok
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def gen_docs(n_docs: int, vocab: int, mean_len: int, branch: int = 2,
             zipf_a: float = 1.5, seed: int = 0, min_len: int = 4,
             max_len: int = 0):
    """List of int32 token arrays with zipf-ish lengths and Markov
    structure (module docstring).  ``max_len`` 0 = 8x mean."""
    assert vocab >= 4 and branch >= 1 and branch < vocab
    rnd = np.random.RandomState(seed)
    max_len = max_len or 8 * mean_len
    a_mul = 2 * (vocab // 3) + 1  # odd multiplier: good token mixing
    docs = []
    for _ in range(n_docs):
        # zipf over "length units", scaled to the mean: heavy-tailed like
        # real document collections, truncated so one doc can't swallow
        # an epoch
        ln = int(min(min_len + (rnd.zipf(zipf_a) - 1) * (mean_len // 2),
                     max_len))
        toks = np.empty(ln, np.int64)
        toks[0] = rnd.randint(0, vocab)
        for i in range(1, ln):
            j = rnd.randint(0, branch)
            toks[i] = (a_mul * toks[i - 1] + 7 + j) % vocab
        docs.append(toks.astype(np.int32))
    return docs


def write_corpus(path: str, docs) -> None:
    from cxxnet_tpu.utils.serializer import atomic_write
    atomic_write(path, lambda f: f.writelines(
        (" ".join(str(int(t)) for t in d) + "\n").encode()
        for d in docs))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True, help="corpus .txt output path")
    ap.add_argument("--docs", type=int, default=2000)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--mean-len", type=int, default=64)
    ap.add_argument("--branch", type=int, default=2,
                    help="successors per token; conditional entropy = "
                         "log(branch) nats")
    ap.add_argument("--zipf-a", type=float, default=1.5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--pack", type=int, default=0, metavar="N",
                    help="also pack into N token shards via tok2bin")
    ap.add_argument("--shard-prefix", default="",
                    help="shard path with %%d (default: <out>_%%d.tok)")
    args = ap.parse_args()

    docs = gen_docs(args.docs, args.vocab, args.mean_len, args.branch,
                    args.zipf_a, args.seed)
    write_corpus(args.out, docs)
    ntok = sum(d.size for d in docs)
    print(f"make_synth_text: {len(docs)} docs / {ntok} tokens "
          f"(vocab {args.vocab}, branch {args.branch} -> conditional "
          f"entropy {np.log(args.branch):.3f} nats) -> {args.out}")
    if args.pack > 0:
        from tok2bin import pack_shards
        prefix = args.shard_prefix or \
            os.path.splitext(args.out)[0] + "_%d.tok"
        n = pack_shards(docs, prefix, args.pack, vocab=args.vocab)
        print(f"make_synth_text: packed {n} docs into {args.pack} "
              f"shard(s) at {prefix}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
