#!/usr/bin/env python
"""spans2trace: export span records as Chrome trace-event JSON.

The Perfetto leg of the request-path observatory (doc/monitor.md
"Reading a p99 breakdown"): point it at the ``metrics_sink`` JSONL of a
run traced with ``trace_sample = N`` and get a timeline loadable in
Perfetto (ui.perfetto.dev) or ``chrome://tracing`` — one track per host
thread (client threads show queue_wait → coalesce → … → respond, the
dispatcher shows dispatch with pad/device/unpad nested, the checkpoint
writer its shard/manifest/prune sequence), with flow arrows linking
every request to the coalesced batch dispatch that served it.  Load it
next to the device-trace windows (``prof = <dir>``) to see host and
chip sides of the same incident.

    python tools/spans2trace.py metrics.jsonl -o trace.json
    python tools/spans2trace.py metrics.jsonl            # stdout

Format: the Trace Event Format's JSON-object form —
``{"traceEvents": [...]}`` with complete (``ph = X``) slices in µs,
thread-name metadata (``ph = M``), and flow start/finish pairs
(``ph = s`` / ``ph = f``, ``bp = e``) from each rider's coalesce slice
to its dispatch slice.  The exporter is schema-coupled to the ``span``
record (monitor/spans.py): tools/lint.sh runs it over the checked-in
fixture, so drift in either breaks the lint gate, not a triage.
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)

#: single-process export: every track hangs off one pid
PID = 1

#: thread-name prefix -> Perfetto sort rank, so tracks group by role
#: instead of first-span order: the dispatch/scheduler plane on top,
#: client threads next, then the background planes (checkpoint writer,
#: admin/control threads, sentinel reporter, io producers).  Matched
#: longest-prefix-first; unknown names sort after every known role.
#: Keep in step with the racelint thread-naming rule (race_thread_name:
#: every Thread carries a literal ``cxxnet-*`` name).
THREAD_SORT_RANKS = (
    ("cxxnet-serve-batcher", 0),
    ("cxxnet-decode-sched", 0),
    ("cxxnet-serve-client", 10),
    ("cxxnet-serve-gen", 10),
    ("cxxnet-bench-client", 10),
    ("cxxnet-bench-genclient", 10),
    ("cxxnet-ckpt-writer", 20),
    ("cxxnet-serve-admin", 30),
    ("cxxnet-serve-sentinel", 40),
    ("cxxnet-serve-producer", 50),
    ("cxxnet-imbin", 50),
    ("cxxnet-io-buffer-producer", 50),
    ("cxxnet-device-prefetch", 50),
)


def sort_rank(name: str) -> int:
    best = 90    # unknown roles (incl. MainThread) sort last
    best_len = -1
    for prefix, rank in THREAD_SORT_RANKS:
        if name.startswith(prefix) and len(prefix) > best_len:
            best, best_len = rank, len(prefix)
    return best


def load_spans(path: str) -> List[dict]:
    from obsv import load_records
    from cxxnet_tpu.monitor.spans import span_records
    return span_records(load_records(path))


def build_trace(spans: List[dict]) -> dict:
    """Span records -> one Trace Event Format object."""
    events: List[dict] = []
    tids: Dict[str, int] = {}

    def tid_of(name: str) -> int:
        if name not in tids:
            tids[name] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": PID,
                           "tid": tids[name], "args": {"name": name}})
            # within-rank tiebreak on tid keeps e.g. client-0..N in order
            events.append({"ph": "M", "name": "thread_sort_index",
                           "pid": PID, "tid": tids[name],
                           "args": {"sort_index":
                                    sort_rank(name) * 1000 + tids[name]}})
        return tids[name]

    # rider trace_id -> its coalesce span (the flow arrow's tail: the
    # last thing that happened to the request before the batch closed)
    coalesce_of: Dict[int, dict] = {}
    for s in spans:
        if s["span"] == "coalesce" and s.get("trace_id") is not None:
            coalesce_of[s["trace_id"]] = s

    for s in spans:
        tid = tid_of(str(s.get("tid", "?")))
        args = {k: v for k, v in s.items()
                if k not in ("kind", "span", "us", "dur_us", "tid", "ts")}
        events.append({"ph": "X", "name": s["span"], "cat": "host",
                       "pid": PID, "tid": tid, "ts": s["us"],
                       "dur": max(s["dur_us"], 1), "args": args})
        if s["span"] == "dispatch" and s.get("riders"):
            # flow arrows: every rider's coalesce slice -> this
            # dispatch slice.  The start event must sit INSIDE a slice
            # on the rider's track, so anchor it at the coalesce end.
            for rid in s["riders"]:
                c = coalesce_of.get(rid)
                if c is None:
                    continue
                events.append({
                    "ph": "s", "cat": "request", "name": "batched",
                    "id": rid, "pid": PID,
                    "tid": tid_of(str(c.get("tid", "?"))),
                    "ts": c["us"] + max(c["dur_us"] - 1, 0)})
                events.append({
                    "ph": "f", "bp": "e", "cat": "request",
                    "name": "batched", "id": rid, "pid": PID,
                    "tid": tid, "ts": s["us"] + 1})
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"exporter": "cxxnet_tpu tools/spans2trace.py",
                          "n_spans": len(spans)}}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="export span records as Chrome trace-event JSON "
                    "(Perfetto / chrome://tracing)")
    ap.add_argument("jsonl", help="metrics_sink JSONL file")
    ap.add_argument("-o", "--out", default="",
                    help="output .json path (default: stdout)")
    args = ap.parse_args(argv)
    try:
        spans = load_spans(args.jsonl)
    except OSError as e:
        print(f"spans2trace: {e}", file=sys.stderr)
        return 1
    if not spans:
        print(f"spans2trace: no span records in {args.jsonl} "
              "(was the run traced? trace_sample = N + metrics_sink)",
              file=sys.stderr)
        return 1
    trace = build_trace(spans)
    if args.out:
        from cxxnet_tpu.utils.serializer import atomic_write
        atomic_write(args.out,
                     lambda f: f.write(json.dumps(trace).encode()))
        n = len(trace["traceEvents"])
        print(f"spans2trace: wrote {n} events from {len(spans)} spans "
              f"to {args.out}", file=sys.stderr)
    else:
        json.dump(trace, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
