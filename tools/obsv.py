#!/usr/bin/env python
"""obsv: run report, cross-run diff, and live follow over metrics JSONLs.

The training observatory's read side (doc/monitor.md "Reading a run
report"): point it at the ``metrics_sink`` file of any run and get the
throughput trend, the goodput ledger, the compile/comm/idle breakdown,
the top-k layers by attributed device time with roofline distance,
inference latency percentiles, and every anomaly the sentinels fired —
as aligned terminal tables or one ``--json`` object for CI.

    python tools/obsv.py metrics.jsonl
    python tools/obsv.py metrics.jsonl --json | jq .layers
    python tools/obsv.py metrics.jsonl --top 20
    python tools/obsv.py metrics.jsonl --trace /tmp/prof   # re-attribute
    python tools/obsv.py --diff A.jsonl B.jsonl            # CI gate
    python tools/obsv.py metrics.jsonl --follow            # live tail
    python tools/obsv.py --live host:9100                  # scrape once

``--diff`` aligns two runs through the one comparison engine
(cxxnet_tpu/monitor/diff.py) and **exits 1 on any regression** past
``--rel`` (default 10%) — wire it into CI, don't read it by hand.
``--follow`` tails a growing file (train or serve), re-renders as
records land, tolerates the torn final line of a mid-write file, and
flags ``anomaly``/``flight``/``nan``/``rollback`` records immediately;
it exits on its own when the watched run's ``ledger`` record lands at
the end of the stream.  Records already present at start (a reused
append-mode sink, including the previous session's ledger) are
catch-up context, never terminal.

``--trace`` re-runs layer attribution directly on a profiler trace via
the scope paths embedded in its op metadata (TPU traces; CPU-runtime
traces carry none — there the in-run ``layer_profile`` record, which
joins through the compiled HLO, is the authoritative table).
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_records(path: str) -> List[dict]:
    """Tolerant JSONL read — the one shared implementation
    (cxxnet_tpu/monitor/ledger.py): a torn final line from a killed run
    is skipped with a one-shot warning, never a JSONDecodeError."""
    from cxxnet_tpu.monitor.ledger import load_records as _load
    return _load(path, who="obsv")


def _by_kind(recs: List[dict]) -> Dict[str, List[dict]]:
    from cxxnet_tpu.monitor.ledger import by_kind
    return by_kind(recs)


def build_report(recs: List[dict], top: int = 10) -> dict:
    # an append-mode sink carries earlier sessions; the report (like
    # the diff) describes the LAST one — the session its ledger bounds
    from cxxnet_tpu.monitor.ledger import last_session
    recs = last_session(recs)
    by = _by_kind(recs)
    rep: dict = {"n_records": len(recs),
                 "kinds": {k: len(v) for k, v in sorted(by.items())}}
    if by.get("run"):
        run = by["run"][-1]
        rep["run"] = {k: run.get(k) for k in
                      ("updater", "batch_size", "dtype", "mesh",
                       "monitor") if k in run}
    if by.get("compile"):
        rep["compile_sec"] = by["compile"][-1].get("compile_sec")

    steps = by.get("step", [])
    if steps:
        eps = [r["examples_per_sec"] for r in steps
               if r.get("examples_per_sec")]
        if eps:
            rep["throughput"] = {
                "windows": len(eps),
                "first": eps[0], "last": eps[-1],
                "best": max(eps), "worst": min(eps),
                "mean": round(sum(eps) / len(eps), 1),
                "last_vs_best": round(eps[-1] / max(eps), 3),
            }

    rounds = by.get("round", [])
    if rounds:
        rep["rounds"] = [
            {k: r.get(k) for k in
             ("round", "examples_per_sec", "wall_sec", "eval_sec",
              "iter_wait_sec", "dispatch_sec", "h2d_sec",
              "hbm_peak_bytes", "train_step_traces") if k in r}
            for r in rounds]
        wall = sum(r.get("wall_sec", 0.0) for r in rounds)
        disp = sum(r.get("dispatch_sec", 0.0) for r in rounds)
        wait = sum(r.get("iter_wait_sec", 0.0) for r in rounds)
        rep["breakdown"] = {
            "train_wall_sec": round(wall, 3),
            "dispatch_sec": round(disp, 3),
            "iter_wait_sec": round(wait, 3),
            "h2d_sec": round(sum(r.get("h2d_sec", 0.0)
                                 for r in rounds), 3),
            "eval_sec": round(sum(r.get("eval_sec", 0.0)
                                  for r in rounds), 3),
            # loop wall the host spent neither dispatching nor blocked
            # on input: metric math, logging, staging bookkeeping
            "other_sec": round(max(wall - disp - wait, 0.0), 3),
            "compile_sec": rep.get("compile_sec"),
        }

    # goodput ledger: the emitted end-of-run record when present, else
    # recomputed post-hoc from the stream — the same fold either way
    # (monitor/ledger.py), so historical JSONLs get the same accounting
    if by.get("ledger"):
        rep["ledger"] = {k: v for k, v in by["ledger"][-1].items()
                         if k not in ("ts", "kind")}
    elif steps or rounds:
        from cxxnet_tpu.monitor.ledger import build_ledger
        led = build_ledger(recs, source="posthoc")
        if led:
            rep["ledger"] = led

    if by.get("trace"):
        t = by["trace"][-1]
        rep["comm"] = {k: t.get(k) for k in
                       ("round", "steps", "device_sec", "comm_sec",
                        "comm_share", "overlap_frac", "comm_by_kind")
                       if k in t}
    if by.get("layer_profile"):
        lp = by["layer_profile"][-1]
        rep["layers"] = {
            "round": lp.get("round"),
            "device_total_ms": lp.get("device_total_ms"),
            "attributed_ms": lp.get("attributed_ms"),
            "coverage": lp.get("coverage"),
            "rows": (lp.get("rows") or [])[:top],
            "dropped_rows": max(len(lp.get("rows") or []) - top, 0),
        }
    if by.get("mem_profile"):
        mp = by["mem_profile"][-1]
        rep["memory"] = {
            "round": mp.get("round"),
            "peak_live_bytes": mp.get("peak_live_bytes"),
            "peak_frac": mp.get("peak_frac"),
            "coverage": mp.get("coverage"),
            "exec": mp.get("exec"),
            "model": mp.get("model"),
            "hbm_capacity_bytes": mp.get("hbm_capacity_bytes"),
            "hbm_peak_bytes": mp.get("hbm_peak_bytes"),
            "hbm_peak_spread_pct": mp.get("hbm_peak_spread_pct"),
            "timeline": mp.get("timeline") or [],
            "rows": (mp.get("rows") or [])[:top],
            "dropped_rows": max(len(mp.get("rows") or []) - top, 0),
        }
    if by.get("serve"):
        rep["serving"] = [
            {k: r.get(k) for k in
             ("model", "requests", "duration_sec", "qps", "offered_qps",
              "batches", "mean_batch", "batch_hist", "queue_depth_mean",
              "queue_depth_max", "dtype", "shapes", "clients", "retraces",
              "quant_rel_err", "footprint") if k in r}
            for r in by["serve"]]
    if by.get("serve_gen"):
        # incremental-decode generation runs (doc/serve.md "Incremental
        # decode"): aggregate tokens/sec, batch occupancy, per-token
        # percentiles, and the zero-retrace contract
        rep["generation"] = [
            {k: r.get(k) for k in
             ("model", "duration_sec", "tokens_per_sec", "slots",
              "max_seqlen", "gen_tokens", "clients", "sample",
              "retraces", "requests", "tokens", "steps", "prefills",
              "mean_occupancy", "occupancy_hist", "batching",
              "spec_k", "acceptance_rate", "draft_steps",
              "verify_calls", "draft_ms", "verify_ms",
              "prefill_chunk", "prefill_chunks",
              "tok_p50_ms", "tok_p95_ms", "tok_p99_ms", "footprint")
             if k in r}
            for r in by["serve_gen"]]
    if by.get("span"):
        # request-path p99 decomposition (doc/monitor.md "Reading a
        # p99 breakdown"): per-stage latency percentiles + share of
        # total request wall, computed from the span records
        from cxxnet_tpu.monitor.spans import stage_decomposition
        dec = stage_decomposition(by["span"])
        if dec["stages"]:
            rep["serve_stages"] = dec
    if by.get("serve_window"):
        wins = by["serve_window"]
        qps = [w["qps"] for w in wins if w.get("qps") is not None]
        p99 = [w["p99_ms"] for w in wins if w.get("p99_ms") is not None]
        rep["serve_windows"] = {
            "windows": len(wins),
            "qps_min": min(qps) if qps else None,
            "qps_max": max(qps) if qps else None,
            "p99_ms_max": max(p99) if p99 else None,
            "queue_depth_max": max((w.get("queue_depth") or 0
                                    for w in wins), default=0),
        }
    if by.get("latency"):
        rep["latency"] = [
            {k: r.get(k) for k in
             ("op", "count", "mean", "p50", "p95", "p99", "max", "unit")
             if k in r} for r in by["latency"]]
    ckpts = by.get("ckpt", [])
    if ckpts:
        n_async = sum(1 for r in ckpts if r.get("async_write"))
        rep["checkpoints"] = {
            "saves": len(ckpts),
            "async": n_async,
            "bytes_last": ckpts[-1].get("bytes"),
            "bytes_total": sum(r.get("bytes") or 0 for r in ckpts),
            # off-thread write wall vs what the train loop actually paid
            # (host pull + backpressure block) — the async win is the gap
            "write_sec": round(sum(r.get("write_sec") or 0.0
                                   for r in ckpts), 3),
            "blocked_sec": round(sum(r.get("blocked_sec") or 0.0
                                     for r in ckpts), 3),
            "pruned": sum(r.get("pruned") or 0 for r in ckpts),
            "last_round": ckpts[-1].get("round"),
        }
    if by.get("rollback"):
        rep["rollbacks"] = [
            {k: r.get(k) for k in
             ("retry", "max_retry", "from_round", "restored_round",
              "path", "reason") if k in r} for r in by["rollback"]]
    if by.get("anomaly"):
        rep["anomalies"] = [
            {k: r.get(k) for k in
             ("metric", "direction", "value", "ewma", "rel_dev",
              "round", "step", "window") if k in r}
            for r in by["anomaly"]]
    if by.get("slo"):
        # SLO burn-rate alerts from the serving control plane
        # (doc/monitor.md "slo" record): one row per rising edge
        rep["slo"] = [
            {k: r.get(k) for k in
             ("model", "tier", "burn", "threshold", "budget",
              "error_rate", "requests", "viol", "window_sec") if k in r}
            for r in by["slo"]]
    if by.get("serve_flight"):
        # anomaly/SLO-triggered flight captures (doc/monitor.md
        # "serve_flight" record): boosted-trace windows around a fire
        rep["serve_flights"] = [
            {k: r.get(k) for k in
             ("model", "reason", "requests_boosted", "sample_boost",
              "trace_first", "trace_last", "n_windows") if k in r}
            for r in by["serve_flight"]]
    rep["flights"] = len(by.get("flight", []))
    if by.get("nan"):
        rep["nonfinite_steps"] = len(by["nan"])
    return rep


# ----------------------------------------------------------- rendering

def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}".rstrip("0").rstrip(".")
    return str(v)


def _mb(v) -> str:
    """Bytes -> a compact MB string (memory tables stay readable)."""
    if v is None:
        return "-"
    return f"{v / 1e6:.2f}M"


def _table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:>{w}}}" for w in widths)
    lines = [fmt.format(*headers)]
    for r in rows:
        lines.append(fmt.format(*r))
    return "\n".join(lines)


def render(rep: dict) -> str:
    out = []
    run = rep.get("run")
    if run:
        out.append("run: " + "  ".join(f"{k}={v}" for k, v in run.items()))
    live = rep.get("live")
    if live:
        out.append(f"live: {live['url']}  "
                   f"ready={live.get('ready')}  "
                   f"uptime={_fmt(live.get('uptime_sec'), 1)}s  "
                   f"flights={live.get('flights', 0)}")
        sv = live.get("slo")
        if sv and sv.get("active"):
            rows = []
            for tier in ("fast", "slow"):
                t = sv.get(tier) or {}
                rows.append([tier, _fmt(t.get("burn")),
                             _fmt(t.get("threshold")),
                             _fmt(t.get("window_sec")),
                             "FIRING" if t.get("firing") else "ok"])
            out.append(f"slo: p99<={_fmt(sv.get('p99_ms_target'))}ms "
                       f"avail>={_fmt(sv.get('avail_target'), 4)} "
                       f"({'ok' if sv.get('ok') else 'BURNING'})")
            out.append(_table(
                ["tier", "burn", "threshold", "win_s", "state"], rows))
    th = rep.get("throughput")
    if th:
        out.append(
            f"throughput: last {_fmt(th['last'], 1)} ex/s over "
            f"{th['windows']} windows (best {_fmt(th['best'], 1)}, "
            f"mean {_fmt(th['mean'], 1)}; last/best "
            f"{th['last_vs_best']:.0%})")
    bd = rep.get("breakdown")
    if bd:
        out.append("breakdown (train wall "
                   f"{_fmt(bd['train_wall_sec'])} s): "
                   f"dispatch {_fmt(bd['dispatch_sec'])} s, "
                   f"input wait {_fmt(bd['iter_wait_sec'])} s, "
                   f"other {_fmt(bd['other_sec'])} s; "
                   f"h2d {_fmt(bd['h2d_sec'])} s, "
                   f"eval {_fmt(bd['eval_sec'])} s, "
                   f"compile {_fmt(bd.get('compile_sec'))} s")
    led = rep.get("ledger")
    if led:
        out.append("")
        src = "" if led.get("source") == "run" else \
            f" [{led.get('source')}]"
        line = (f"goodput{src}: {_fmt(led.get('goodput_pct'), 2)}% of "
                f"{_fmt(led.get('wall_sec'))} s wall")
        if led.get("h2d_overlapped_sec"):
            line += (f"; h2d overlapped "
                     f"{_fmt(led['h2d_overlapped_sec'])} s (off the "
                     "critical path)")
        if led.get("rounds_lost"):
            line += (f"; {led['rounds_lost']} round(s) lost to "
                     f"{led.get('rollbacks')} rollback(s)")
        out.append(line)
        from cxxnet_tpu.monitor.ledger import CATEGORIES
        cats = led.get("categories") or {}
        shares = led.get("shares") or {}
        out.append(_table(
            ["category", "sec", "share"],
            [[c, _fmt(cats.get(c)),
              (f"{shares[c]:.1%}" if c in shares else "-")]
             for c in CATEGORIES if cats.get(c) is not None]))
        if cats.get("pipe_bubble"):
            in_step = cats["pipe_bubble"] / max(
                cats["pipe_bubble"] + (cats.get("dispatch") or 0.0), 1e-9)
            out.append(f"pipe bubble: {in_step:.1%} of the dispatched "
                       "step wall is fill/drain idle (analytic "
                       "(S-1)/(M+S-1) — raise pipe_microbatch to shrink "
                       "it; measured share: bench.py --mesh-scaling)")
    rounds = rep.get("rounds")
    if rounds:
        out.append("")
        out.append(_table(
            ["round", "ex/s", "wall_s", "eval_s", "wait_s", "hbm_peak"],
            [[_fmt(r.get("round")), _fmt(r.get("examples_per_sec"), 1),
              _fmt(r.get("wall_sec")), _fmt(r.get("eval_sec")),
              _fmt(r.get("iter_wait_sec")),
              _fmt(r.get("hbm_peak_bytes"))] for r in rounds]))
    comm = rep.get("comm")
    if comm:
        kinds = ", ".join(f"{k} {_fmt(ms)} ms" for k, ms in
                          (comm.get("comm_by_kind") or {}).items())
        out.append("")
        out.append(
            f"comm (round {comm.get('round')}, {comm.get('steps')} "
            f"steps): share {_fmt(comm.get('comm_share'))}, overlap "
            f"{_fmt(comm.get('overlap_frac'))}"
            + (f" [{kinds}]" if kinds else ""))
    lp = rep.get("layers")
    if lp:
        out.append("")
        out.append(
            f"layers (round {lp.get('round')}): "
            f"{_fmt(lp.get('attributed_ms'))} of "
            f"{_fmt(lp.get('device_total_ms'))} ms/step attributed "
            f"(coverage {_fmt(lp.get('coverage'))})")
        rows = [[r.get("layer", "?"), _fmt(r.get("device_ms")),
                 _fmt(r.get("share")), _fmt(r.get("comm_ms")),
                 _fmt(r.get("mfu_pct"), 1), _fmt(r.get("roofline_ms")),
                 _fmt(r.get("roofline_x"), 1)]
                for r in lp.get("rows") or []]
        if rows:
            out.append(_table(
                ["layer", "ms/step", "share", "comm_ms", "mfu%",
                 "roofline_ms", "x_roof"], rows))
        if lp.get("dropped_rows"):
            out.append(f"... {lp['dropped_rows']} more rows "
                       "(--top to widen)")
    mem = rep.get("memory")
    if mem:
        out.append("")
        cap = mem.get("hbm_capacity_bytes")
        line = (f"memory (round {mem.get('round')}): peak live "
                f"{_mb(mem.get('peak_live_bytes'))} temps at "
                f"{_fmt(mem.get('peak_frac'))} of the step "
                f"(coverage {_fmt(mem.get('coverage'))})")
        ex = mem.get("exec") or {}
        if ex:
            line += (f"; exec args {_mb(ex.get('args_bytes'))} + out "
                     f"{_mb(ex.get('out_bytes'))} + temps "
                     f"{_mb(ex.get('temp_bytes'))}")
        out.append(line)
        hbm = mem.get("hbm_peak_bytes")
        if hbm or cap:
            l2 = "hbm: "
            if hbm:
                l2 += f"measured peak {_mb(hbm)}"
                if mem.get("hbm_peak_spread_pct"):
                    l2 += (" (device spread "
                           f"{_fmt(mem['hbm_peak_spread_pct'], 1)}%)")
            if cap:
                l2 += ("" if not hbm else ", ") + f"capacity {_mb(cap)}"
                mdl = (mem.get("model") or {}).get("est_peak_bytes")
                if mdl:
                    l2 += (f", modeled peak {_mb(mdl)} "
                           f"({mdl / cap:.0%} full)")
            out.append(l2)
        tl = mem.get("timeline") or []
        if tl and max(tl) > 0:
            blocks = " ▁▂▃▄▅▆▇█"
            out.append("live temps over the step: " + "".join(
                blocks[min(int(v / max(tl) * 8), 8)] for v in tl))
        rows = [[r.get("layer", "?"), _mb(r.get("param_bytes")),
                 _mb(r.get("opt_bytes")), _mb(r.get("act_bytes")),
                 _mb(r.get("total_bytes")), _fmt(r.get("share")),
                 _fmt(r.get("model_x"), 2)]
                for r in mem.get("rows") or []]
        if rows:
            out.append(_table(
                ["layer", "param", "opt", "act@peak", "total",
                 "share", "x_model"], rows))
        if mem.get("dropped_rows"):
            out.append(f"... {mem['dropped_rows']} more rows "
                       "(--top to widen)")
    srv = rep.get("serving")
    if srv:
        out.append("")
        n_retr = sum(r.get("retraces") or 0 for r in srv)
        out.append(
            f"serving: {len(srv)} run(s); retraces past warmup: {n_retr}"
            + ("" if not n_retr else "  <-- a request shape escaped "
               "the declared buckets"))
        out.append(_table(
            ["model", "dtype", "qps", "requests", "batches", "mean_b",
             "q_mean", "q_max", "footprint"],
            [[str(r.get("model", "?")), str(r.get("dtype", "?")),
              _fmt(r.get("qps"), 1), _fmt(r.get("requests")),
              _fmt(r.get("batches")), _fmt(r.get("mean_batch")),
              _fmt(r.get("queue_depth_mean")),
              _fmt(r.get("queue_depth_max")),
              _mb((r.get("footprint") or {}).get("total_bytes"))]
             for r in srv]))
        hist = srv[-1].get("batch_hist") or {}
        if hist:
            total = sum(hist.values()) or 1
            out.append("batch sizes (last run): " + "  ".join(
                f"{k}x{v} ({v / total:.0%})"
                for k, v in sorted(hist.items(), key=lambda kv:
                                   int(kv[0]))))
        errs = [r["quant_rel_err"] for r in srv
                if r.get("quant_rel_err") is not None]
        if errs:
            out.append(f"quantization pairtest vs f32: max rel err "
                       f"{_fmt(max(errs), 4)}")
    gen = rep.get("generation")
    if gen:
        out.append("")
        n_retr = sum(r.get("retraces") or 0 for r in gen)
        out.append(
            f"generation: {len(gen)} run(s); decode retraces past "
            f"warmup: {n_retr}"
            + ("" if not n_retr else "  <-- a shape escaped the "
               "pinned executable set"))
        out.append(_table(
            ["model", "batching", "tok/s", "requests", "tokens",
             "steps", "occ", "tok_p99", "kv_cache"],
            [[str(r.get("model", "?")), str(r.get("batching", "?")),
              _fmt(r.get("tokens_per_sec"), 1), _fmt(r.get("requests")),
              _fmt(r.get("tokens")), _fmt(r.get("steps")),
              _fmt(r.get("mean_occupancy")), _fmt(r.get("tok_p99_ms")),
              _mb((r.get("footprint") or {}).get("kv_cache_bytes"))]
             for r in gen]))
        spec = [r for r in gen if r.get("spec_k")]
        if spec:
            # speculative decoding telemetry (doc/serve.md): accepted
            # draft tokens per flagship verify dispatch is the whole
            # speedup story
            out.append(_table(
                ["model", "spec_k", "accept", "draft_steps",
                 "verify_calls", "draft_ms", "verify_ms"],
                [[str(r.get("model", "?")), _fmt(r.get("spec_k")),
                  (f"{r['acceptance_rate']:.0%}"
                   if r.get("acceptance_rate") is not None else "-"),
                  _fmt(r.get("draft_steps")),
                  _fmt(r.get("verify_calls")),
                  _fmt(r.get("draft_ms")), _fmt(r.get("verify_ms"))]
                 for r in spec]))
        chunked = [r for r in gen if r.get("prefill_chunk")]
        if chunked:
            out.append("chunked prefill: " + "  ".join(
                f"{r.get('model', '?')}: {_fmt(r.get('prefill_chunks'))}"
                f" tick(s) of {_fmt(r.get('prefill_chunk'))} col(s)"
                for r in chunked))
        hist = gen[-1].get("occupancy_hist") or {}
        if hist:
            total = sum(hist.values()) or 1
            out.append("batch occupancy (last run): " + "  ".join(
                f"{k}x{v} ({v / total:.0%})"
                for k, v in sorted(hist.items(),
                                   key=lambda kv: int(kv[0]))))
    dec = rep.get("serve_stages")
    if dec:
        out.append("")
        out.append(
            f"request-path p99 decomposition ({dec['requests']} traced "
            "request(s); share = fraction of total request wall — "
            "pad/device/unpad nest inside dispatch):")
        out.append(_table(
            ["stage", "count", "p50_ms", "p95_ms", "p99_ms", "share"],
            [[s["stage"], _fmt(s["count"]), _fmt(s["p50_ms"]),
              _fmt(s["p95_ms"]), _fmt(s["p99_ms"]),
              (f"{s['share']:.0%}" if s.get("share") is not None
               else "-")] for s in dec["stages"]]))
    sw = rep.get("serve_windows")
    if sw:
        out.append(
            f"sentinel windows: {sw['windows']} (qps "
            f"{_fmt(sw['qps_min'], 1)}..{_fmt(sw['qps_max'], 1)}, "
            f"p99 max {_fmt(sw['p99_ms_max'])} ms, queue depth max "
            f"{_fmt(sw['queue_depth_max'])})")
    lat = rep.get("latency")
    if lat:
        out.append("")
        out.append(_table(
            ["op", "count", "mean_ms", "p50", "p95", "p99", "max_ms"],
            [[r.get("op", "?"), _fmt(r.get("count")),
              _fmt(r.get("mean")), _fmt(r.get("p50")),
              _fmt(r.get("p95")), _fmt(r.get("p99")),
              _fmt(r.get("max"))] for r in lat]))
    ck = rep.get("checkpoints")
    if ck:
        out.append("")
        out.append(
            f"checkpoints: {ck['saves']} save(s) "
            f"({ck['async']} async), last {_fmt(ck['bytes_last'])} bytes "
            f"at round {_fmt(ck['last_round'])}; write "
            f"{_fmt(ck['write_sec'])} s off-thread, loop blocked "
            f"{_fmt(ck['blocked_sec'])} s"
            + (f"; pruned {ck['pruned']}" if ck.get("pruned") else ""))
    rbs = rep.get("rollbacks")
    if rbs:
        out.append("")
        out.append(f"ROLLBACKS: {len(rbs)}")
        out.append(_table(
            ["retry", "from", "restored", "reason"],
            [[_fmt(r.get("retry")), _fmt(r.get("from_round")),
              _fmt(r.get("restored_round")),
              str(r.get("reason", "?"))[:60]] for r in rbs]))
    anoms = rep.get("anomalies")
    if anoms:
        out.append("")
        out.append(f"anomalies: {len(anoms)} "
                   f"(flight dumps: {rep.get('flights', 0)})")
        out.append(_table(
            ["metric", "dir", "value", "ewma", "rel_dev", "round",
             "step", "win"],
            [[r.get("metric", "?"), r.get("direction", "?"),
              _fmt(r.get("value")), _fmt(r.get("ewma")),
              _fmt(r.get("rel_dev")), _fmt(r.get("round")),
              _fmt(r.get("step")), _fmt(r.get("window"))]
             for r in anoms]))
    elif rep.get("kinds", {}).get("step"):
        out.append("")
        out.append("anomalies: none")
    slo = rep.get("slo")
    if slo:
        out.append("")
        out.append(f"SLO BURNS: {len(slo)}")
        out.append(_table(
            ["model", "tier", "burn", "threshold", "err_rate",
             "requests", "viol", "win_s"],
            [[str(r.get("model", "?")), str(r.get("tier", "?")),
              _fmt(r.get("burn")), _fmt(r.get("threshold")),
              _fmt(r.get("error_rate"), 4), _fmt(r.get("requests")),
              _fmt(r.get("viol")), _fmt(r.get("window_sec"))]
             for r in slo]))
    sfl = rep.get("serve_flights")
    if sfl:
        out.append("")
        out.append(f"SERVE FLIGHTS: {len(sfl)}")
        out.append(_table(
            ["model", "reason", "boosted", "sample", "traces", "wins"],
            [[str(r.get("model", "?")),
              str(r.get("reason", "?"))[:48],
              _fmt(r.get("requests_boosted")),
              _fmt(r.get("sample_boost")),
              f"{r.get('trace_first', 0)}..{r.get('trace_last', 0)}",
              _fmt(r.get("n_windows"))] for r in sfl]))
    if rep.get("nonfinite_steps"):
        out.append(f"NON-FINITE LOSS steps: {rep['nonfinite_steps']}")
    return "\n".join(out)


def trace_report(path: str, top: int) -> dict:
    """Standalone re-attribution of a trace by its embedded scope paths
    (no trainer, no HLO join — see module docstring)."""
    from cxxnet_tpu.monitor import attribution
    from cxxnet_tpu.monitor.trace import (comm_report_in, find_xplane,
                                          parse_xspace)
    xplane = find_xplane(path)
    planes = parse_xspace(xplane)
    scopes = attribution.scopes_from_planes(planes)
    table = attribution.layer_table(planes, scopes)
    table["rows"] = table["rows"][:top]
    return {"trace": xplane, "scopes_found": len(scopes),
            "comm": comm_report_in(planes), "layers": table}


# ------------------------------------------------------------ live follow

class Follower:
    """Incremental tail of a growing metrics JSONL (``--follow``).

    ``poll()`` reads whatever landed since the last call and returns
    ``(new_records, alerts)``.  The torn final line of a mid-write file
    stays buffered until its newline arrives — a record split across
    two polls parses once, whole.  Alerts are the record kinds an
    operator wants flagged the moment they land."""

    ALERT_KINDS = ("anomaly", "flight", "nan", "rollback", "slo",
                   "serve_flight")

    def __init__(self, path: str):
        self.path = path
        self.records: List[dict] = []
        self._pos = 0
        self._buf = ""

    def poll(self):
        try:
            with open(self.path) as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except FileNotFoundError:
            return [], []
        if not chunk:
            return [], []
        self._buf += chunk
        lines = self._buf.split("\n")
        self._buf = lines.pop()  # the torn tail ("" after a whole line)
        from cxxnet_tpu.monitor.ledger import parse_record_line
        new: List[dict] = []
        for line in lines:
            try:
                r = parse_record_line(line)  # the one shared parse
            except ValueError:
                continue  # a complete-but-broken line: skip, don't die
            if r is not None:
                new.append(r)
        self.records.extend(new)
        return new, [r for r in new if r["kind"] in self.ALERT_KINDS]


def _alert_line(r: dict) -> str:
    k = r.get("kind")
    if k == "anomaly":
        body = (f"{r.get('metric')} {r.get('direction')} to "
                f"{_fmt(r.get('value'))} (ewma {_fmt(r.get('ewma'))}, "
                f"rel_dev {_fmt(r.get('rel_dev'))})")
    elif k == "flight":
        body = (f"{r.get('n_records')} step record(s) dumped: "
                f"{r.get('reason')}")
    elif k == "nan":
        body = f"non-finite loss at round {r.get('round')} " \
               f"step {r.get('step')} ({r.get('action')})"
    elif k == "rollback":
        body = (f"retry {r.get('retry')}/{r.get('max_retry')}: restored "
                f"round {r.get('restored_round')} ({r.get('reason')})")
    elif k == "slo":
        body = (f"{r.get('model')} {r.get('tier')} burn "
                f"{_fmt(r.get('burn'))} >= {_fmt(r.get('threshold'))} "
                f"({r.get('viol')}/{r.get('requests')} over "
                f"{_fmt(r.get('window_sec'))}s)")
    elif k == "serve_flight":
        body = (f"{r.get('model')}: traces "
                f"{r.get('trace_first')}..{r.get('trace_last')} captured "
                f"({r.get('reason')})")
    else:
        body = json.dumps({k2: v for k2, v in r.items() if k2 != "ts"})
    return f"!! {k}: {body}"


def follow(path: str, interval: float = 1.0, top: int = 10,
           ticks: int = 0, out=None) -> int:
    """Tail ``path``: re-render the report whenever new records land,
    print alert lines immediately, stop when the watched run's
    end-of-run ``ledger`` record lands (or after ``ticks`` polls, the
    CI bound).

    Records already in the file when the follow starts are CATCH-UP
    context: rendered and alert-flagged, but never terminal — a reused
    append-mode sink ends with the *previous* session's ledger, and
    exiting on it would abandon the live run during its first compile.
    Only a ledger that arrives at the end of the stream on a later
    poll ends the follow.

    Each re-render rebuilds the report over the whole accumulated
    stream — O(records) per poll, bounded in cadence by ``interval``;
    at sink cadences (print_step / round / window records) that is
    milliseconds even for day-long streams."""
    out = out or sys.stdout
    color = hasattr(out, "isatty") and out.isatty()
    f = Follower(path)
    n = 0
    try:
        while True:
            new, alerts = f.poll()
            for a in alerts:
                line = _alert_line(a)
                if color:
                    line = f"\x1b[31m{line}\x1b[0m"
                print(line, file=out, flush=True)
            if new:
                rep = build_report(f.records, top=top)
                print(f"\n--- {path}: {len(f.records)} record(s) ---",
                      file=out)
                print(render(rep), file=out, flush=True)
            if new and new[-1].get("kind") == "ledger":
                if n == 0:
                    print("\n(stream already ends with a ledger — a "
                          "finished run; watching for a new session "
                          "to append)", file=out, flush=True)
                else:
                    print("\nrun ended (ledger record landed); "
                          "follow exiting", file=out)
                    return 0
            n += 1
            if ticks and n >= ticks:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


# ------------------------------------------------------------------- diff

def run_diff(path_a: str, path_b: str, rel: float,
             as_json: bool) -> int:
    """``--diff A B``: the CI gate — exit 1 on any regression of B
    (candidate) vs A (baseline) past ``rel`` (monitor/diff.py)."""
    from cxxnet_tpu.monitor.diff import diff_runs, render_diff
    try:
        recs_a, recs_b = load_records(path_a), load_records(path_b)
    except (OSError, ValueError) as e:
        # ValueError covers UnicodeDecodeError: a binary/corrupt input
        # must exit 2 (unreadable), never 1 (the regression verdict)
        print(f"obsv: {e}", file=sys.stderr)
        return 2
    for path, recs in ((path_a, recs_a), (path_b, recs_b)):
        if not recs:
            print(f"obsv: no records in {path}", file=sys.stderr)
            return 2
    d = diff_runs(recs_a, recs_b, rel=rel)
    if as_json:
        print(json.dumps(d))
    else:
        print(render_diff(d, label_a=os.path.basename(path_a),
                          label_b=os.path.basename(path_b)))
    return 1 if d["regressions"] else 0


def live_report(url: str, top: int = 10) -> dict:
    """One-shot scrape of a live serve host's admin endpoint
    (doc/serve.md "Operating a serve host"): fetch ``/statusz`` +
    ``/metrics`` once and map them into the same report shapes the
    JSONL path builds, so ``render()`` produces the familiar tables.

    Stdlib-only on the wire (urllib) and lazy on the parse import —
    pointing obsv at a remote host must not drag jax in.
    """
    import urllib.request

    from cxxnet_tpu.monitor import promtext

    base = url.rstrip("/")
    if "://" not in base:
        base = "http://" + base
    with urllib.request.urlopen(base + "/statusz", timeout=5) as r:
        status = json.loads(r.read().decode("utf-8"))
    with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
        text = r.read().decode("utf-8")
    tables = promtext.live_tables(promtext.parse(text))

    rep: dict = {"live": {
        "url": base,
        "ready": status.get("ready"),
        "uptime_sec": status.get("uptime_sec"),
        "flights": status.get("flights", 0),
        "slo": status.get("slo"),
        "counters": tables["counters"],
        "gauges": tables["gauges"],
    }}
    serving, generation, wins = [], [], []
    for name, st in sorted((status.get("models") or {}).items()):
        row = {"model": name, "retraces": st.get("retraces"),
               "dtype": st.get("dtype")}
        if isinstance(st.get("footprint"), dict):
            row["footprint"] = st["footprint"]
        if st.get("kind") == "generate":
            row.update({k: st.get(k) for k in
                        ("requests", "tokens", "steps", "prefills",
                         "mean_occupancy", "occupancy_hist")
                        if k in st})
            generation.append(row)
        else:
            row.update({k: st.get(k) for k in
                        ("requests", "batches", "mean_batch",
                         "batch_hist", "queue_depth_max") if k in st})
            serving.append(row)
        if st.get("last_window"):
            wins.append(st["last_window"])
    if serving:
        rep["serving"] = serving
    if generation:
        rep["generation"] = generation
    if wins:
        qps = [w["qps"] for w in wins if w.get("qps") is not None]
        p99 = [w["p99_ms"] for w in wins if w.get("p99_ms") is not None]
        rep["serve_windows"] = {
            "windows": len(wins),
            "qps_min": min(qps) if qps else None,
            "qps_max": max(qps) if qps else None,
            "p99_ms_max": max(p99) if p99 else None,
            "queue_depth_max": max((w.get("queue_depth") or 0
                                    for w in wins), default=0),
        }
    # request-latency summary back in the ms unit the JSONL tables use
    lat = tables["summaries"].get("serve_latency_sec")
    if lat and lat.get("count"):
        rep["latency"] = [{
            "op": "serve_latency", "count": int(lat["count"]),
            "mean": round(lat["sum"] / lat["count"] * 1e3, 3),
            "p50": round(lat.get("p50", 0.0) * 1e3, 3),
            "p95": round(lat.get("p95", 0.0) * 1e3, 3),
            "p99": round(lat.get("p99", 0.0) * 1e3, 3),
            "unit": "ms"}]
    return rep


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run report / cross-run diff / live follow over "
                    "metrics JSONLs")
    ap.add_argument("jsonl", nargs="?", default="",
                    help="metrics_sink JSONL file")
    ap.add_argument("--trace", default="",
                    help="profiler log dir / xplane.pb: re-attribute "
                    "per-layer device time from the trace's own scope "
                    "metadata")
    ap.add_argument("--top", type=int, default=10,
                    help="layer rows to show")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON object instead of tables")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="compare run B (candidate) against run A "
                    "(baseline); exits 1 on any regression past --rel")
    ap.add_argument("--rel", type=float, default=0.10,
                    help="relative regression threshold for --diff "
                    "(default 0.10)")
    ap.add_argument("--follow", action="store_true",
                    help="tail a growing metrics JSONL: re-render as "
                    "records land, flag anomaly/flight/nan/rollback "
                    "immediately, exit when the watched run's ledger "
                    "record lands (pre-existing records are catch-up, "
                    "never terminal)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="--follow poll interval in seconds")
    ap.add_argument("--follow-ticks", type=int, default=0,
                    help="--follow: stop after N polls (0 = until the "
                    "ledger record or Ctrl-C; CI smoke uses a bound)")
    ap.add_argument("--live", default="", metavar="URL",
                    help="scrape a live serve host's admin endpoint "
                    "(host:port or http://host:port) once — /statusz + "
                    "/metrics — and render the same serving tables")
    args = ap.parse_args(argv)
    if args.diff:
        return run_diff(args.diff[0], args.diff[1], rel=args.rel,
                        as_json=args.json)
    if args.live:
        try:
            rep = live_report(args.live, top=args.top)
        except OSError as e:
            print(f"obsv: live: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(rep))
        else:
            print(render(rep))
        return 0
    if not args.jsonl:
        ap.error("a metrics JSONL is required (or use --diff A B, "
                 "or --live URL)")
    if args.follow:
        return follow(args.jsonl, interval=args.interval, top=args.top,
                      ticks=args.follow_ticks)
    try:
        recs = load_records(args.jsonl)
    except OSError as e:
        print(f"obsv: {e}", file=sys.stderr)
        return 1
    if not recs:
        print(f"obsv: no records in {args.jsonl}", file=sys.stderr)
        return 1
    rep = build_report(recs, top=args.top)
    if args.trace:
        try:
            rep["trace_reattribution"] = trace_report(args.trace,
                                                      args.top)
        except (FileNotFoundError, ValueError) as e:
            print(f"obsv: trace: {e}", file=sys.stderr)
            return 1
    if args.json:
        print(json.dumps(rep))
        return 0
    print(render(rep))
    tr = rep.get("trace_reattribution")
    if tr:
        # a bare trace dir carries no dispatch count, so these are
        # whole-window totals — unlike the layer_profile table above,
        # whose ms/step divides by the window's traced dispatches
        print(f"\ntrace re-attribution ({tr['trace']}, "
              f"{tr['scopes_found']} scopes; window totals):")
        rows = [[r.get("layer", "?"), _fmt(r.get("device_ms")),
                 _fmt(r.get("share")), _fmt(r.get("comm_ms"))]
                for r in tr["layers"]["rows"]]
        if rows:
            print(_table(["layer", "ms/window", "share", "comm_ms"],
                         rows))
        else:
            print("  (no scope metadata in this trace — use the run's "
                  "layer_profile record instead)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
