"""Generate a synthetic MNIST-format dataset (idx-ubyte .gz files).

The real MNIST download is unavailable in a zero-egress environment; this
writes class-conditional images (each class = a distinct blob pattern plus
noise) in the exact idx format the mnist iterator reads, so the full
CLI-train path (example/MNIST/*.conf) can run and converge.
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import argparse
import gzip
import os
import struct

import numpy as np


def class_pattern(label: int, rows: int = 28, cols: int = 28) -> np.ndarray:
    rnd = np.random.RandomState(1234 + label)
    yy, xx = np.mgrid[0:rows, 0:cols]
    img = np.zeros((rows, cols))
    for _ in range(3):
        cy, cx = rnd.randint(4, rows - 4), rnd.randint(4, cols - 4)
        r = rnd.randint(2, 6)
        img += np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * r * r))
    return img / img.max()


def write_idx_images(path: str, imgs: np.ndarray) -> None:
    with gzip.open(path, "wb") as f:
        n, r, c = imgs.shape
        f.write(struct.pack(">iiii", 2051, n, r, c))
        f.write(imgs.astype(np.uint8).tobytes())


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">ii", 2049, len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


def make_split(n: int, seed: int, rows=28, cols=28, num_class=10):
    rnd = np.random.RandomState(seed)
    labels = rnd.randint(0, num_class, n)
    pats = np.stack([class_pattern(k, rows, cols) for k in range(num_class)])
    imgs = pats[labels] * 200.0
    imgs += rnd.rand(n, rows, cols) * 55.0
    return np.clip(imgs, 0, 255), labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="./data")
    ap.add_argument("--train", type=int, default=6000)
    ap.add_argument("--test", type=int, default=1000)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)
    imgs, labels = make_split(args.train, 0)
    write_idx_images(os.path.join(args.out, "train-images-idx3-ubyte.gz"), imgs)
    write_idx_labels(os.path.join(args.out, "train-labels-idx1-ubyte.gz"), labels)
    imgs, labels = make_split(args.test, 1)
    write_idx_images(os.path.join(args.out, "t10k-images-idx3-ubyte.gz"), imgs)
    write_idx_labels(os.path.join(args.out, "t10k-labels-idx1-ubyte.gz"), labels)
    print(f"wrote synthetic mnist to {args.out}: "
          f"{args.train} train / {args.test} test")


if __name__ == "__main__":
    main()
