#!/usr/bin/env python
"""tok2bin: pack tokenized documents into CXTPUTOK token shards.

The im2bin analogue for the LM data path (`cxxnet_tpu/io/text.py` has
the format spec): input is a plain-text corpus — one document per line,
space-separated integer token ids (what `tools/make_synth_text.py`
writes, and what any external tokenizer can trivially emit) — output is
``--num-shards`` memory-mappable token shards with a doc-offset index.
Documents round-robin across shards so every shard sees the full length
distribution (the partition_maker discipline).

    python tools/tok2bin.py --corpus corpus.txt --out corpus_%d.tok \
        --num-shards 4

``--vocab`` (optional) validates ids and picks the narrowest itemsize
(uint16 when vocab <= 65536, else uint32).
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def read_corpus(path: str):
    """Token-id documents from a one-doc-per-line text corpus."""
    docs = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            toks = line.split()
            if not toks:
                continue
            try:
                docs.append(np.asarray([int(t) for t in toks], np.int64))
            except ValueError as e:
                raise ValueError(
                    f"{path} line {lineno}: expected space-separated "
                    f"integer token ids ({e})")
    return docs


def pack_shards(docs, out_pattern: str, num_shards: int,
                vocab: int = 0) -> int:
    """Round-robin ``docs`` into ``num_shards`` CXTPUTOK files at
    ``out_pattern`` (must contain %d when num_shards > 1).  Returns the
    number of documents packed."""
    from cxxnet_tpu.io.text import write_token_shard
    assert num_shards >= 1
    if num_shards > 1:
        assert "%d" in out_pattern, \
            "--out must contain %d when --num-shards > 1"
    maxid = max((int(d.max()) for d in docs if len(d)), default=0)
    if vocab:
        assert maxid < vocab, \
            f"token id {maxid} out of range for vocab {vocab}"
    itemsize = 2 if max(maxid + 1, vocab) <= (1 << 16) else 4
    n = 0
    for s in range(num_shards):
        shard_docs = docs[s::num_shards]
        path = out_pattern % s if "%d" in out_pattern else out_pattern
        n += write_token_shard(path, shard_docs, itemsize=itemsize)
    return n


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--corpus", required=True,
                    help="one doc per line, space-separated token ids")
    ap.add_argument("--out", required=True,
                    help="shard path; %%d substituted when sharding")
    ap.add_argument("--num-shards", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=0,
                    help="validate ids < vocab and size the itemsize")
    args = ap.parse_args()
    docs = read_corpus(args.corpus)
    assert docs, f"{args.corpus}: no documents"
    n = pack_shards(docs, args.out, args.num_shards, vocab=args.vocab)
    ntok = sum(d.size for d in docs)
    print(f"tok2bin: {n} docs / {ntok} tokens -> {args.num_shards} "
          f"shard(s) at {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
