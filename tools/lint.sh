#!/usr/bin/env bash
# Static-analysis gate (runs before any device work, no data files):
#   1. graftlint over every shipped example config — zero error-severity
#      findings required (the key registry and the configs must agree;
#      tests/test_analysis.py mirrors this as the golden guard);
#   2. the pytest collection guard — import breaks must not hide behind
#      tier-1's --continue-on-collection-errors;
#   3. the run-report CLI over the checked-in metrics fixture — a schema
#      drift between the sink's record kinds and tools/obsv.py's parser
#      breaks loudly here, not in the middle of a perf triage;
#   4. the span->Perfetto exporter over the same fixture — drift in the
#      span record or tools/spans2trace.py fails the gate the same way.
# Companion to tools/tier1.sh (the runtime gate); see doc/check.md.
cd "$(dirname "$0")/.." || exit 1
set -e
env JAX_PLATFORMS=cpu python tools/graftlint.py example/*/*.conf
env JAX_PLATFORMS=cpu python -m pytest tests/ -q --collect-only \
    -p no:cacheprovider >/dev/null
env JAX_PLATFORMS=cpu python tools/obsv.py tests/fixtures/run_report.jsonl \
    --json >/dev/null
env JAX_PLATFORMS=cpu python tools/spans2trace.py \
    tests/fixtures/run_report.jsonl | python -c \
    'import json,sys; t=json.load(sys.stdin); assert t["traceEvents"]'
echo "lint OK"
