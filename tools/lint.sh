#!/usr/bin/env bash
# Static-analysis gate (runs before any device work, no data files):
#   1. disclint — the repo-discipline AST lint over the framework's own
#      code (doc/lint.md): direct prints, non-atomic writes, swallowed
#      thread exceptions, warn-once violations.  Zero findings required;
#      deliberate exceptions carry inline `# disclint: ok(...)` pragmas;
#   1b. racelint — the guarded-by concurrency lint over the host-side
#      thread fleet (doc/lint.md): every cross-thread-mutated attribute
#      carries a declared policy, guarded accesses hold their lock,
#      every Thread carries a cxxnet-* name.  Zero findings required;
#      suppressions need a written reason;
#   2. graftlint --spmd over every shipped example config — zero
#      error-severity findings required (the key registry and the
#      configs must agree; tests/test_analysis.py mirrors this as the
#      golden guard), including the SPMD deep lint (collective
#      consistency, donation audit, dtype flow — doc/check.md);
#   3. the pytest collection guard — import breaks must not hide behind
#      tier-1's --continue-on-collection-errors;
#   4. the run-report CLI over the checked-in metrics fixture — a schema
#      drift between the sink's record kinds and tools/obsv.py's parser
#      breaks loudly here, not in the middle of a perf triage;
#   5. the span->Perfetto exporter over the same fixture — drift in the
#      span record or tools/spans2trace.py fails the gate the same way;
#   6. the cross-run comparator self-diffed over the fixture — a run
#      must never regress against itself (exit 0, zero regressions), so
#      drift in the diff engine or the ledger fold fails here;
#   7. the Prometheus exposition round-trip — render a synthetic
#      registry snapshot (counters + summaries + an exact histogram)
#      through monitor/promtext.py and parse it back with the same
#      module's grammar-checking parser; a drift between what /metrics
#      emits and what scrapers accept fails here, not on a live host.
# Companion to tools/tier1.sh (the runtime gate); see doc/check.md.
cd "$(dirname "$0")/.." || exit 1
set -e
python tools/disclint.py
python cxxnet_tpu/analysis/racelint.py
env JAX_PLATFORMS=cpu python tools/graftlint.py --spmd example/*/*.conf
env JAX_PLATFORMS=cpu python -m pytest tests/ -q --collect-only \
    -p no:cacheprovider >/dev/null
env JAX_PLATFORMS=cpu python tools/obsv.py tests/fixtures/run_report.jsonl \
    --json >/dev/null
env JAX_PLATFORMS=cpu python tools/spans2trace.py \
    tests/fixtures/run_report.jsonl | python -c \
    'import json,sys; t=json.load(sys.stdin); assert t["traceEvents"]'
env JAX_PLATFORMS=cpu python tools/obsv.py --diff \
    tests/fixtures/run_report.jsonl tests/fixtures/run_report.jsonl \
    --json | python -c \
    'import json,sys; d=json.load(sys.stdin); assert d["regressions"] == 0'
env JAX_PLATFORMS=cpu python -c '
from cxxnet_tpu.monitor import promtext
snap = {"counters": {"serve_requests": 42, "serve/odd name": 1},
        "gauges": {"queue_depth": 3},
        "histograms": {"serve_latency_sec": {
            "count": 3, "sum": 0.008, "min": 0.001, "max": 0.005,
            "mean": 0.00267, "last": 0.005,
            "p50": 0.002, "p95": 0.005, "p99": 0.005}}}
text = promtext.render(snap, hists={"serve_batch_hist": {8: 6, 4: 2}})
fams = promtext.parse(text)
assert promtext.counter_values(fams)["cxxnet_serve_requests_total"] == 42
assert fams["cxxnet_serve_batch_hist"]["type"] == "histogram"
tabs = promtext.live_tables(fams)
assert tabs["counters"]["serve_requests"] == 42
assert tabs["summaries"]["serve_latency_sec"]["p99"] == 0.005'
echo "lint OK"
