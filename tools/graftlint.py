#!/usr/bin/env python
"""graftlint: static config + traced-graph lint for cxxnet_tpu configs.

The standalone CLI twin of ``task = check`` (doc/check.md): lint one or
more ``.conf`` files against the declared-key registry and — unless
``--no-trace`` — abstract-trace each configured train step on CPU and
lint the jaxpr (closure-captured constants, f64 promotions, weak-typed
state leaves, dp-reduction escapes) plus the SPMD deep lint
(collective-consistency, donation audit, dtype-flow — spmdlint.py;
``--spmd`` forces it on, ``--no-spmd`` off, default follows each
config's ``spmd_check`` key).  No device work, no data files.

    python tools/graftlint.py [--json] [--no-trace] [--spmd|--no-spmd] \
        conf [conf ...]

Exit status: 1 iff any config produced an error-severity finding.
``--json`` prints one machine-readable object (schema in doc/check.md).
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# mesh configs trace on an N-device host-platform mesh; the flag must be
# set before the first backend initialization, i.e. here at process start
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()


def main() -> int:
    ap = argparse.ArgumentParser(
        description="static config + traced-graph lint (task=check twin)")
    ap.add_argument("configs", nargs="+", help=".conf files to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (doc/check.md schema)")
    ap.add_argument("--no-trace", action="store_true",
                    help="config lint only; skip the jaxpr pass")
    ap.add_argument("--spmd", dest="spmd", action="store_true",
                    default=None,
                    help="force the SPMD deep lint on (default: each "
                         "config's spmd_check key, on)")
    ap.add_argument("--no-spmd", dest="spmd", action="store_false",
                    help="skip the SPMD deep lint")
    args = ap.parse_args()

    from cxxnet_tpu.analysis import run_check
    from cxxnet_tpu.utils.config import ConfigError, parse_config_file

    worst = 0
    report = []
    for path in args.configs:
        try:
            pairs = parse_config_file(path)
        except (OSError, ConfigError) as e:
            findings, code = [], 1
            entry = {"config": path, "parse_error": str(e),
                     "n_error": 1, "n_warn": 0, "n_info": 0, "findings": []}
            if not args.as_json:
                print(f"{path}: parse error: {e}")
            report.append(entry)
            worst = max(worst, code)
            continue
        findings, code = run_check(pairs, path=path,
                                   trace=not args.no_trace,
                                   spmd=args.spmd)
        worst = max(worst, code)
        counts = {"error": 0, "warn": 0, "info": 0}
        for f in findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        report.append({"config": path, "n_error": counts["error"],
                       "n_warn": counts["warn"], "n_info": counts["info"],
                       "findings": [f.to_dict() for f in findings]})
        if not args.as_json:
            print(f"{path}: {counts['error']} error(s), "
                  f"{counts['warn']} warning(s), {counts['info']} info")
            for f in findings:
                print("  " + f.format())
    if args.as_json:
        json.dump({"kind": "graftlint", "exit": worst, "configs": report},
                  sys.stdout, indent=2)
        print()
    return worst


if __name__ == "__main__":
    sys.exit(main())
