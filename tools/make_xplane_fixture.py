#!/usr/bin/env python
"""Regenerate tests/fixtures/minimal.xplane.pb deterministically.

A hand-rolled protobuf wire ENCODER matching the decoder in
cxxnet_tpu/monitor/trace.py (field numbers from xplane.proto:
XSpace.planes=1; XPlane.name=2/lines=3/event_metadata=4; XLine.name=2/
events=4; XEvent.metadata_id=1/offset_ps=2/duration_ps=3;
XEventMetadata.id=1/name=2).  The fixture carries:

* a TPU plane with an "XLA Modules" line (jit_step, 5 ms) and an
  "XLA Ops" line holding compute ops (fusion.1 x2 = 1.5 ms, copy.2
  0.2 ms, convolution.3 3.0 ms), an async collective PAIR
  (all-reduce-start.1 / all-reduce-done.1, in-flight 0.5..2.3 ms,
  exposed 0.3 ms), a sync collective (reduce-scatter.2, 0.4 ms), and a
  substring TRAP (loop-all-reduce-fusion.3: a fusion whose NAME contains
  "all-reduce" — the classifier must not book it as comm; this is the
  round-5 "copy-done" bug class, BASELINE.md round 5);
* a host plane the default TPU filters must exclude (7 ms).

The compute ops carry XEventMetadata.display_name framework-op paths
with the NN-name scopes the net builder stamps (layers/base.py
conn_scope_name) — convolution.3's path is wrapped in
``transpose(jvp(...))`` the way jax.grad transposes render, so layer
attribution's substring matching (monitor/attribution.py) is exercised;
collectives and the module event carry none.  Expected attribution with
scopes {00-conv, 03-fullc}: 00-conv 4.5 ms (fusion.1 x2 +
convolution.3), 03-fullc 0.8 ms (copy.2 + the trap fusion),
(collectives) 0.8 ms.

Run from the repo root:  python tools/make_xplane_fixture.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cxxnet_tpu.monitor import log as mlog  # noqa: E402
from cxxnet_tpu.utils.serializer import atomic_write  # noqa: E402

MS = 10 ** 9  # milliseconds -> picoseconds


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(num: int, val: int) -> bytes:
    return _varint(num << 3 | 0) + _varint(val)


def _field_len(num: int, payload: bytes) -> bytes:
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def event(mid: int, dur_ps: int, off_ps: int = 0) -> bytes:
    out = _field_varint(1, mid)
    if off_ps:
        out += _field_varint(2, off_ps)
    return out + _field_varint(3, dur_ps)


def line(name: str, events: list) -> bytes:
    out = _field_len(2, name.encode())
    for e in events:
        out += _field_len(4, e)
    return out


def metadata_entry(mid: int, name: str, display: str = "") -> bytes:
    meta = _field_varint(1, mid) + _field_len(2, name.encode())
    if display:
        meta += _field_len(3, display.encode())
    return _field_varint(1, mid) + _field_len(2, meta)


def plane(name: str, lines: list, names: dict, displays: dict = None
          ) -> bytes:
    out = _field_len(2, name.encode())
    for ln in lines:
        out += _field_len(3, ln)
    for mid, nm in sorted(names.items()):
        out += _field_len(4, metadata_entry(
            mid, nm, (displays or {}).get(mid, "")))
    return out


def build() -> bytes:
    tpu_names = {
        1: "fusion.1", 2: "copy.2", 3: "convolution.3", 4: "jit_step",
        5: "all-reduce-start.1", 6: "all-reduce-done.1",
        7: "reduce-scatter.2", 8: "loop-all-reduce-fusion.3",
    }
    tpu_displays = {
        1: "jit(step)/jit(main)/00-conv/add.1",
        2: "jit(step)/03-fullc/copy",
        3: "jit(step)/transpose(jvp(00-conv))/conv_general_dilated",
        8: "jit(step)/03-fullc/while/body/add",
    }
    tpu = plane("/device:TPU:0", [
        line("XLA Modules", [event(4, 5 * MS)]),
        line("XLA Ops", [
            event(1, MS, 0),
            event(5, MS // 10, MS // 2),          # start: 0.5..0.6 ms
            event(1, MS // 2, MS),
            event(6, 3 * MS // 10, 2 * MS),       # done: 2.0..2.3 ms
            event(2, MS // 5, 2 * MS + MS // 2),
            event(3, 3 * MS, 4 * MS),
            event(7, 2 * MS // 5, 8 * MS),        # sync reduce-scatter
            event(8, 3 * MS // 5, 9 * MS),        # the substring trap
        ]),
    ], tpu_names, tpu_displays)
    host = plane("/host:CPU", [
        line("XLA Ops", [event(1, 7 * MS)]),
    ], {1: "host-loop"})
    return _field_len(1, tpu) + _field_len(1, host)


def main() -> None:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "tests", "fixtures", "minimal.xplane.pb")
    # atomic: a ctrl-C mid-regeneration must not leave a torn fixture
    # for the whole trace-parser test suite to chase
    atomic_write(path, lambda f: f.write(build()))
    mlog.info(f"wrote {path} ({os.path.getsize(path)} bytes)")


if __name__ == "__main__":
    main()
