"""Import externally-trained weights into a cxxnet_tpu model checkpoint.

The reference's caffe plugin had two roles: a differential-testing oracle
(covered here by ``plugin/torch_adapter``) and a path for
externally-trained parameters to enter a net — the wrapped caffe layer
carried its trained blobs as weights
(``src/plugin/caffe_adapter-inl.hpp:172-183``, blob exposure ``:45-66``).
This tool is the TPU-native equivalent of that second role: the graph
stays native, and external weights flow in through the public
get/set_weight surface, then save as a normal model checkpoint loadable
with ``model_in =`` / ``continue = 1`` / ``task = finetune``.

Usage::

  python tools/import_pretrained.py net.conf weights.pt map.conf out.model

``weights`` may be a torch state_dict (``.pt``/``.pth``, loaded
CPU-side) or a numpy ``.npz``.  ``map.conf`` uses the framework's
key=value syntax, one line per tensor::

  conv1/wmat = features.0.weight
  conv1/bias = features.0.bias
  fc6/wmat   = classifier.1.weight

Layouts line up with torch natively: conv ``wmat`` is
(out, in/group, kh, kw) = ``torch.nn.Conv2d.weight``; fullc ``wmat`` is
(nhidden, nin) = ``torch.nn.Linear.weight``.  Shapes must match exactly
— mismatches abort with both shapes printed.
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface
import sys

import numpy as np

sys.path.insert(0, "/root/repo")


def load_external(path):
    if path.endswith(".npz"):
        return dict(np.load(path))
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):  # a full module was saved
        sd = sd.state_dict()
    return {k: v.detach().numpy() for k, v in sd.items()}


def import_pretrained(conf_path, weights_path, map_path, out_path,
                      dev="cpu"):
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_file

    t = NetTrainer()
    for k, v in parse_config_file(conf_path):
        t.set_param(k, v)
    t.set_param("dev", dev)
    t.init_model()

    ext = load_external(weights_path)
    n = 0
    for k, v in parse_config_file(map_path):
        layer, _, tag = k.partition("/")
        assert tag, f"map line {k!r}: expected <layer>/<tag> = <ext key>"
        assert v in ext, (
            f"{v!r} not in {weights_path} "
            f"(available: {sorted(ext)[:8]}...)")
        src = np.asarray(ext[v])
        cur = t.get_weight(layer, tag)
        assert tuple(src.shape) == tuple(cur.shape), (
            f"{layer}/{tag}: external {v} has shape {tuple(src.shape)}, "
            f"net expects {tuple(cur.shape)}")
        t.set_weight(src.astype(cur.dtype), layer, tag)
        n += 1
    t.save_model(out_path)
    print(f"imported {n} tensors from {weights_path} -> {out_path}")
    return t


if __name__ == "__main__":
    if len(sys.argv) != 5:
        print(__doc__)
        sys.exit(1)
    import_pretrained(*sys.argv[1:5])
