#!/usr/bin/env python
"""disclint: AST lint for the framework's own code disciplines.

Nine PRs of review passes kept re-finding the same hand-checked
contracts; this tool makes them machine-enforced (tools/lint.sh runs it,
tests/test_disclint.py asserts it exits 0 on the tree).  Rules:

* ``print``        — direct ``print()`` outside cxxnet_tpu/monitor/log.py.
                     All user-facing output rides the log surface
                     (``info``/``notice``/``result``/``warn``) so
                     ``silent = 1``, stream redirection, and pytest
                     capture behave identically everywhere.
* ``atomic-write`` — ``open(..., "w"/"a"/"x")`` outside
                     utils/serializer.py.  Persistent artifacts go
                     through ``serializer.atomic_write`` (tmp + fsync +
                     rename) so a kill mid-write can never leave a
                     half-written file; streams (JSONL sinks, prediction
                     output) are deliberate exceptions — pragma them.
* ``mktemp``       — ``tempfile.mktemp`` is a filename race; use
                     ``mkstemp``/``NamedTemporaryFile`` or atomic_write.
* ``bare-except``  — ``except:`` catches SystemExit/KeyboardInterrupt;
                     name the exceptions (``except Exception`` with a
                     reason comment at minimum).
* ``swallow``      — a broad handler (bare/Exception/BaseException)
                     whose body is just ``pass``/``continue`` drops the
                     error on the floor; log it or latch it for reraise.
* ``thread-exc``   — a ``threading.Thread`` target (or Thread subclass
                     ``run``) without a try/except: a worker that dies
                     silently strands its consumer.  The house contract
                     is catch-and-enqueue with reraise on the consuming
                     thread (io/device_prefetch.ProducerError,
                     ckpt/writer poll()).
* ``warn-once``    — ``mlog.warn`` inside a loop with no warn-once
                     guard floods the log; latch with a ``_warned``
                     flag/set (trainer._dp_warn_once pattern).

Escape hatches, inline and auditable:

    do_it()  # disclint: ok(print)          — this line (or line above)
    # disclint: ok-file(print)              — whole file, one rule
    # disclint: ok                           — this line, every rule

Usage:  python tools/disclint.py [--json] [path ...]
Default paths: cxxnet_tpu/ tools/ bench.py (repo-relative).  Exit 1 iff
any finding survives the pragmas.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = ("cxxnet_tpu", "tools", "bench.py")

#: files whose whole purpose exempts them from one rule
RULE_EXEMPT_FILES = {
    "print": ("cxxnet_tpu/monitor/log.py",),
    "atomic-write": ("cxxnet_tpu/utils/serializer.py",),
}

RULES = ("print", "atomic-write", "mktemp", "bare-except", "swallow",
         "thread-exc", "warn-once")

_PRAGMA = re.compile(r"#\s*disclint:\s*(ok-file|ok)\s*(?:\(([^)]*)\))?")


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


def _pragmas(src: str):
    """(per-line {lineno: set(rules)}, file-wide set(rules)); an empty
    rule list in a pragma means 'every rule'."""
    per_line: Dict[int, set] = {}
    file_wide: set = set()
    for i, line in enumerate(src.splitlines(), 1):
        m = _PRAGMA.search(line)
        if not m:
            continue
        rules = {r.strip() for r in (m.group(2) or "").split(",")
                 if r.strip()} or set(RULES)
        if m.group(1) == "ok-file":
            file_wide |= rules
        else:
            per_line.setdefault(i, set()).update(rules)
    return per_line, file_wide


def _is_broad_catch(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for node in ([t.elts if isinstance(t, ast.Tuple) else [t]][0]):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in ("Exception", "BaseException") for n in names)


def _has_try(fn: ast.AST) -> bool:
    return any(isinstance(n, ast.Try) for n in ast.walk(fn))


def _thread_target_name(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "target":
            v = kw.value
            if isinstance(v, ast.Name):
                return v.id
            if isinstance(v, ast.Attribute):
                return v.attr
    return None


def _is_thread_ctor(fn: ast.AST) -> bool:
    """``threading.Thread(...)`` or bare ``Thread(...)`` (from-import)."""
    if isinstance(fn, ast.Attribute):
        return fn.attr == "Thread" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading"
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The mode string of an ``open``/``io.open`` call opened for
    writing — positional OR ``mode=`` keyword form — else None."""
    fn = call.func
    is_open = (isinstance(fn, ast.Name) and fn.id == "open") or (
        isinstance(fn, ast.Attribute) and fn.attr == "open"
        and isinstance(fn.value, ast.Name) and fn.value.id == "io")
    if not is_open:
        return None
    mode = call.args[1] if len(call.args) >= 2 else next(
        (kw.value for kw in call.keywords if kw.arg == "mode"), None)
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and set(mode.value) & set("wax"):
        return mode.value
    return None


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, src: str):
        self.path = path
        self.findings: List[Finding] = []
        self.per_line, self.file_wide = _pragmas(src)
        self._loops: List[ast.AST] = []
        self._ifs: List[ast.If] = []
        # every function/method in the file by bare name (thread targets
        # resolve through self.<name> or module <name>)
        self.functions: Dict[str, ast.AST] = {}
        self.rel = os.path.relpath(path, REPO).replace(os.sep, "/")

    # ------------------------------------------------------------ report
    def _add(self, node: ast.AST, rule: str, message: str) -> None:
        if rule in self.file_wide:
            return
        if any(self.rel.endswith(f) or self.rel == f
               for f in RULE_EXEMPT_FILES.get(rule, ())):
            return
        line = getattr(node, "lineno", 0)
        for ln in (line, line - 1):
            if rule in self.per_line.get(ln, ()):
                return
        self.findings.append(Finding(self.rel, line, rule, message))

    # ----------------------------------------------------------- visits
    def collect_functions(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        mode = _open_write_mode(node)
        if isinstance(fn, ast.Name) and fn.id == "print":
            self._add(node, "print",
                      "direct print(); route through "
                      "cxxnet_tpu.monitor.log (info/notice/result/warn)")
        elif mode is not None:
            self._add(node, "atomic-write",
                      f"open(..., {mode!r}) bypasses "
                      "serializer.atomic_write; a kill mid-write leaves "
                      "a torn file (pragma deliberate streams)")
        elif isinstance(fn, ast.Attribute) and fn.attr == "mktemp" \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "tempfile":
            self._add(node, "mktemp",
                      "tempfile.mktemp is a filename race; use mkstemp/"
                      "NamedTemporaryFile or serializer.atomic_write")
        elif _is_thread_ctor(fn):
            tname = _thread_target_name(node)
            target = self.functions.get(tname) if tname else None
            if target is not None and not _has_try(target):
                self._add(node, "thread-exc",
                          f"Thread target {tname!r} has no try/except: "
                          "a silent worker death strands the consumer — "
                          "catch and enqueue for reraise (ProducerError "
                          "contract)")
        elif isinstance(fn, ast.Attribute) and fn.attr == "warn" \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id in ("mlog", "log"):
            if self._loops and not self._warn_guarded():
                self._add(node, "warn-once",
                          "mlog.warn inside a loop without a warn-once "
                          "guard floods the log; latch with a _warned "
                          "flag/set")
        self.generic_visit(node)

    def _warn_guarded(self) -> bool:
        """True when an enclosing if-test mentions a warn latch."""
        return any("warn" in ast.dump(i.test).lower() for i in self._ifs)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._add(node, "bare-except",
                      "bare 'except:' catches SystemExit/"
                      "KeyboardInterrupt; name the exceptions")
        if _is_broad_catch(node) and node.body and all(
                isinstance(s, (ast.Pass, ast.Continue))
                for s in node.body):
            self._add(node, "swallow",
                      "broad except with a pass/continue body swallows "
                      "the error; log it or latch it for reraise")
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        if "Thread" in bases:
            run = next((n for n in node.body
                        if isinstance(n, ast.FunctionDef)
                        and n.name == "run"), None)
            if run is not None and not _has_try(run):
                self._add(run, "thread-exc",
                          f"Thread subclass {node.name}.run has no "
                          "try/except: a silent worker death strands "
                          "the consumer")
        self.generic_visit(node)

    def _visit_loop(self, node) -> None:
        self._loops.append(node)
        self.generic_visit(node)
        self._loops.pop()

    visit_For = visit_While = visit_AsyncFor = _visit_loop

    def visit_If(self, node: ast.If) -> None:
        self._ifs.append(node)
        self.generic_visit(node)
        self._ifs.pop()


def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        rel = os.path.relpath(path, REPO).replace(os.sep, "/")
        return [Finding(rel, e.lineno or 0, "parse",
                        f"syntax error: {e.msg}")]
    linter = _Linter(path, src)
    linter.collect_functions(tree)
    linter.visit(tree)
    return linter.findings


def iter_py_files(paths: Sequence[str]):
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(REPO, p)
        if os.path.isfile(full):
            yield full
        else:
            for root, dirs, files in os.walk(full):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="repo-discipline AST lint (doc/lint.md)")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/dirs to lint (default: %s)"
                         % " ".join(DEFAULT_PATHS))
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    findings: List[Finding] = []
    n_files = 0
    for path in iter_py_files(args.paths or DEFAULT_PATHS):
        n_files += 1
        findings.extend(lint_file(path))
    findings.sort(key=lambda f: (f.path, f.line))
    if args.as_json:
        json.dump({"kind": "disclint", "n_files": n_files,
                   "exit": 1 if findings else 0,
                   "findings": [dataclasses.asdict(f) for f in findings]},
                  sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in findings:
            sys.stdout.write(f.format() + "\n")
        sys.stdout.write(
            f"disclint: {n_files} files, {len(findings)} finding(s)\n")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
