#!/usr/bin/env python3
"""Shard an image list into N partitions for distributed training.

Reference: ``tools/imgbin-partition-maker.py`` — shuffles a .lst, groups it
into partitions, and emits a Makefile whose rules pack each partition with
im2bin (so ``make -j`` packs shards in parallel).  Same capability here,
updated: partitions can be sized by instance count or by total image bytes,
packing can run inline (python packer) or via an emitted Makefile driving
the native ``im2bin`` tool, and the shard naming matches what the imgbin
iterator's multi-part/``dist_worker_rank`` sharding consumes.

Usage:
  python tools/partition_maker.py --img_list all.lst --img_root images/ \
      --out parts/ --prefix train --num_parts 8 [--shuffle 1] [--pack 1]
  python tools/partition_maker.py ... --makefile Gen.mk --im2bin native/im2bin
"""
# disclint: ok-file(print) — standalone CLI; stdout is the product surface

from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cxxnet_tpu.utils.serializer import atomic_write  # noqa: E402


def read_list(path: str):
    with open(path) as f:
        return [ln for ln in f if ln.strip()]


def partition(lines, num_parts=0, part_bytes=0, img_root=""):
    """Split into shards: equal-count round blocks, or greedy by on-disk
    image size when --part_mb is given."""
    if part_bytes > 0:
        parts, cur, cur_sz = [], [], 0
        for ln in lines:
            fname = ln.split("\t")[-1].strip()
            try:
                sz = os.path.getsize(os.path.join(img_root, fname))
            except OSError:
                sz = 0
            if cur and cur_sz + sz > part_bytes:
                parts.append(cur)
                cur, cur_sz = [], 0
            cur.append(ln)
            cur_sz += sz
        if cur:
            parts.append(cur)
        return parts
    assert num_parts > 0, "give --num_parts or --part_mb"
    base, rem = divmod(len(lines), num_parts)
    parts, pos = [], 0
    for i in range(num_parts):
        n = base + (1 if i < rem else 0)
        parts.append(lines[pos:pos + n])
        pos += n
    return parts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--img_list", required=True)
    ap.add_argument("--img_root", default="")
    ap.add_argument("--out", required=True, help="output directory")
    ap.add_argument("--prefix", required=True, help="shard name prefix")
    ap.add_argument("--num_parts", type=int, default=0)
    ap.add_argument("--part_mb", type=int, default=0,
                    help="target partition size in MB of source images")
    ap.add_argument("--shuffle", type=int, default=0)
    ap.add_argument("--seed", type=int, default=888)
    ap.add_argument("--pack", type=int, default=0,
                    help="1 = pack each shard to .bin inline (python packer)")
    ap.add_argument("--makefile", default="",
                    help="emit a Makefile with one im2bin rule per shard")
    ap.add_argument("--im2bin", default="native/im2bin")
    args = ap.parse_args(argv)

    lines = read_list(args.img_list)
    if args.shuffle:
        random.Random(args.seed).shuffle(lines)
    parts = partition(lines, args.num_parts, args.part_mb * (1 << 20),
                      args.img_root)

    os.makedirs(args.out, exist_ok=True)
    lst_paths = []
    for i, part in enumerate(parts):
        p = os.path.join(args.out, f"{args.prefix}_{i}.lst")
        atomic_write(p, lambda f, part=part: f.write(
            "".join(part).encode()))
        lst_paths.append(p)
    print(f"wrote {len(parts)} shard lists under {args.out}")

    if args.makefile:
        bins = [p[:-4] + ".bin" for p in lst_paths]
        rules = "all: " + " ".join(bins) + "\n\n" + "".join(
            f"{bin_}: {lst}\n"
            f"\t{args.im2bin} {lst} {args.img_root} {bin_}\n\n"
            for lst, bin_ in zip(lst_paths, bins))
        atomic_write(args.makefile, lambda f: f.write(rules.encode()))
        print(f"emitted {args.makefile}; run: make -f {args.makefile} -j")
    if args.pack:
        from cxxnet_tpu.io.imbin import pack_imbin
        for lst in lst_paths:
            out = lst[:-4] + ".bin"
            pack_imbin(lst, args.img_root, out)
            print(f"packed {out}")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(main())
