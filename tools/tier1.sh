#!/usr/bin/env bash
# Tier-1 verify: the ROADMAP.md command, verbatim.  Run from anywhere;
# prints DOTS_PASSED=<n> and exits with pytest's status.
# The static gate (tools/lint.sh: graftlint over example/ + the pytest
# collection guard) catches config typos and import breaks in seconds —
# run it first; it needs no device and no data files (doc/check.md).
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
