#!/usr/bin/env python
"""Top-k ops by device time from a jax profiler trace.

Shares the xplane parser with bench.py and the telemetry layer
(cxxnet_tpu/monitor/trace.py) — one implementation of the parse the
round-6 BASELINE work hand-rolled twice.

    python tools/trace_summary.py /tmp/prof                 # newest trace
    python tools/trace_summary.py trace.xplane.pb --top 30
    python tools/trace_summary.py /tmp/prof --plane CPU --line XLA
    python tools/trace_summary.py /tmp/prof --json          # machine-readable

Typical triage: run training with ``prof = /tmp/prof`` (optionally
``prof_start_step``/``prof_num_steps`` for an exact window), then point
this tool at the directory.  The per-op table names the line to attack;
``device total`` is the bench-comparable on-chip step time.

Output rides ``cxxnet_tpu.monitor.log`` (doc/lint.md: no direct
``print`` outside the log surface — tools/disclint.py enforces it):
the table lands on stdout via ``info``, errors on stderr via ``warn``,
with the same stream-lookup indirection the rest of the framework gets
(pipe redirection after import, pytest capture).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cxxnet_tpu.monitor import log as mlog  # noqa: E402
from cxxnet_tpu.monitor.trace import (collective_kind,  # noqa: E402
                                      comm_summary_in, find_xplane,
                                      op_totals_in, parse_xspace,
                                      total_ms_in)


def summarize(path: str, top: int, plane: str, line: str) -> dict:
    xplane = find_xplane(path)
    planes = parse_xspace(xplane)  # parse ONCE; all views read from it
    totals = op_totals_in(planes, plane_filter=plane, line_filter=line)
    ranked = sorted(((name, ms, n) for name, (ms, n) in totals.items()),
                    key=lambda t: -t[1])

    def comm_tag(name):
        ck = collective_kind(name)
        return ck[0] if ck else ""

    comm = comm_summary_in(planes, plane_filter=plane, line_filter=line)
    out = {
        "trace": xplane,
        "plane_filter": plane,
        "line_filter": line,
        "device_total_ms": round(
            total_ms_in(planes, plane_filter=plane), 3),
        "ops_total_ms": round(sum(ms for _, (ms, _) in totals.items()), 3),
        "top_ops": [{"op": name, "total_ms": round(ms, 3), "count": n,
                     "comm": comm_tag(name)}
                    for name, ms, n in ranked[:top]],
        "dropped_ops": max(len(ranked) - top, 0),
        # collectives in their own bucket (start/done pairs counted once
        # by in-flight span; see trace.comm_summary_in)
        "comm_total_ms": round(comm["comm_ms"], 3),
        "comm_exposed_ms": round(comm["exposed_ms"], 3),
        "comm_overlap_frac": round(comm["overlap_frac"], 4),
        "comm_by_kind": {k: (round(ms, 3), n)
                         for k, (ms, n) in comm["by_kind"].items()},
    }
    if not ranked:
        # nothing matched the filters (e.g. a CPU-runtime trace whose
        # lines aren't named "XLA Ops"): show what IS there instead of a
        # silent empty table
        out["available"] = [
            {"plane": p.name, "lines": [l.name for l in p.lines]}
            for p in planes]
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="top-k ops by device time from a profiler trace")
    ap.add_argument("trace", help="profiler log dir or *.xplane.pb file")
    ap.add_argument("--top", type=int, default=20, help="rows to print")
    ap.add_argument("--plane", default="TPU",
                    help="substring filter on plane names (default TPU; "
                    "use CPU for host-emulated traces)")
    ap.add_argument("--line", default="XLA Ops",
                    help="substring filter on line names")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON object instead of the table")
    args = ap.parse_args(argv)
    try:
        s = summarize(args.trace, args.top, args.plane, args.line)
    except FileNotFoundError as e:
        mlog.warn(f"trace_summary: {e}")
        return 1
    if args.json:
        mlog.info(json.dumps(s))
        return 0
    mlog.info(f"trace: {s['trace']}")
    mlog.info(f"device total (XLA Modules, plane~{args.plane}): "
              f"{s['device_total_ms']:.3f} ms")
    if s["comm_total_ms"]:
        kinds = ", ".join(f"{k} {ms:.3f} ms x{n}"
                          for k, (ms, n) in s["comm_by_kind"].items())
        mlog.info(f"comm total: {s['comm_total_ms']:.3f} ms "
                  f"(exposed {s['comm_exposed_ms']:.3f} ms, "
                  f"overlap_frac {s['comm_overlap_frac']:.2f}) [{kinds}]")
    ops_total = s["ops_total_ms"] or 1e-12
    mlog.info(f"{'total_ms':>12} {'count':>8} {'%ops':>6} {'comm':>15}  op")
    for row in s["top_ops"]:
        mlog.info(f"{row['total_ms']:12.3f} {row['count']:8d} "
                  f"{100.0 * row['total_ms'] / ops_total:6.1f} "
                  f"{row['comm'] or '-':>15}  {row['op']}")
    if s["dropped_ops"]:
        mlog.info(f"... {s['dropped_ops']} more ops below top-{args.top} "
                  f"(--top to widen)")
    if not s["top_ops"] and s.get("available"):
        mlog.info(f"no events matched --plane {args.plane!r} "
                  f"--line {args.line!r}; the trace contains:")
        for a in s["available"]:
            mlog.info(f"  plane {a['plane']!r}: lines {a['lines']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
