"""Pairtest-on-TPU sweep of the shipping lowering stack (VERDICT r5 #7).

The reference validates alternative layer implementations with PairTest
(``src/layer/pairtest_layer-inl.hpp:161-198``: run master and slave on the
same weights/inputs, compare outputs and gradients).  This harness applies
that methodology to the WHOLE-NET lowering stack on real TPU hardware: one
trainer built with reference-semantics lowerings (every engine option at its
most literal setting) and one per shipping variant, weights synced, then

  * per-NODE forward relative error (one eval step returning every named
    node, read-fixups applied — this also exercises the deferred-node
    extract correction on hardware), and
  * per-PARAM one-step weight-delta relative error (plain SGD, momentum 0:
    delta = -eta * grad, so delta rel-err == grad rel-err per tensor).

Engine options are process-global and read at trace time, so each variant
is built AND fully traced before the next one is constructed (the ab.py
discipline); every option is set explicitly on every variant.

Usage:
  python experiments/pairtest_tpu.py [model] [batch] [dtype]
e.g.
  python experiments/pairtest_tpu.py alexnet 64 float32
  python experiments/pairtest_tpu.py googlenet 32 bfloat16
"""
import sys

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# every engine option, at its most reference-literal value
REF = {"pool_bwd": "eq", "pool_layout": "nchw", "fast_wgrad": "off",
       "group_conv": "split", "conv1_fwd": "conv", "pallas_lrn": "0",
       "relu_vjp": "xla", "pool_relu_reorder": "0",
       "conv_sibling_fuse": "0", "concat_virtual": "0", "input_s2d": "0"}

# the shipping stack, as bench.py runs it
SHIP = {"pool_bwd": "sas", "pool_layout": "nchw", "fast_wgrad": "s2d",
        "group_conv": "fgc", "conv1_fwd": "conv", "pallas_lrn": "band",
        "relu_vjp": "out", "pool_relu_reorder": "1",
        "conv_sibling_fuse": "0", "concat_virtual": "0", "input_s2d": "1"}

# GoogLeNet additionally ships the inception lowerings bench_googlenet
# and example/ImageNet/GoogLeNet.conf set: sibling fusion, conv-form band
# LRN, virtual concat.  batch_split (also shipped) is deliberately NOT
# set here: its per-chunk rng folds give dropout masks that differ from
# the unsplit ref variant, which would turn the grad comparison into
# dropout noise on every param behind the aux/main-head dropouts.
SHIP_GOOGLENET = dict(SHIP, conv_sibling_fuse="1", pallas_lrn="bandconv",
                      concat_virtual="1")


def rel_err(a: np.ndarray, b: np.ndarray) -> float:
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    denom = np.abs(a).max()
    if denom == 0.0:
        return float(np.abs(b).max())
    return float(np.abs(a - b).max() / denom)


def snap_weights(t):
    """{param-path: float64 array}: the optimizer's f32 masters when
    present (bf16 runs: raw param deltas quantize to bf16 ULPs, so a
    delta comparison on them measures rounding, not gradients), else the
    params themselves."""
    out = {}

    def rec(pg, sg, prefix):
        for tag in sorted(pg):
            p = pg[tag]
            if isinstance(p, dict):
                rec(p, sg.get(tag, {}) if isinstance(sg, dict) else {},
                    f"{prefix}{tag}:")
            else:
                s = sg.get(tag) if isinstance(sg, dict) else None
                src = s["w32"] if isinstance(s, dict) and "w32" in s else p
                out[f"{prefix}{tag}"] = np.asarray(src, np.float64)
    for k in sorted(t.params):
        rec(t.params[k], t.opt_state.get(k, {}), f"{k}/")
    return out


def run_variant(model: str, batch: int, dtype: str, name: str,
                keys: dict, data: np.ndarray, label: np.ndarray):
    """Build a trainer under `keys`, trace everything it needs, and return
    (node_outs, w_before, w_after)."""
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    from cxxnet_tpu.io.data import DataBatch
    import time
    if model == "alexnet":
        conf = ALEXNET_NET
    else:
        from cxxnet_tpu.models import zoo
        conf = getattr(zoo, model)() + \
            "metric = error\neta = 0.01\nmomentum = 0.9\nsilent = 1\n"
    t0 = time.perf_counter()
    t = _make_trainer(conf, batch, "tpu",
                      extra=[("dtype", dtype), ("eval_train", "0"),
                             ("silent", "1"), ("updater", "sgd"),
                             ("eta", "0.01"), ("momentum", "0"),
                             ("wd", "0")] + list(keys.items()))
    w_before = snap_weights(t)

    # one eval step returning EVERY named node (single compile)
    name_map = dict(t.net.cfg.node_name_map)
    nids = tuple(sorted(set(name_map.values())))
    estep = t._get_eval_step(nids)
    outs = estep(t.params, t.buffers,
                 t._s2d_transform(t._device_batch(data)), ())
    node_outs = {}
    for nm, nid in name_map.items():
        node_outs[nm] = t._apply_read_fixup(nid, np.asarray(outs[nid]))

    t.start_round(1)
    t.update(DataBatch(data=data, label=label,
                       index=np.arange(batch)))
    w_after = snap_weights(t)
    print(f"  [{name}] traced+ran in {time.perf_counter() - t0:.0f}s",
          file=sys.stderr, flush=True)
    del t
    import gc
    gc.collect()  # trainer sits in step-closure cycles; collect to free HBM
    return node_outs, w_before, w_after


def main():
    model = sys.argv[1] if len(sys.argv) > 1 else "alexnet"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    dtype = sys.argv[3] if len(sys.argv) > 3 else "float32"
    if dtype == "float32":
        # TPU matmuls default to bf16 passes even on f32 operands; that
        # rounding differs BETWEEN equivalent lowerings (measured up to
        # 8.6e-2 on one-step grad deltas), drowning the semantic
        # comparison this harness exists for.  Force true-f32 MXU passes
        # so residual differences are lowering semantics, not precision.
        jax.config.update("jax_default_matmul_precision", "highest")
    ship = SHIP_GOOGLENET if model == "googlenet" else SHIP
    ref = dict(REF)
    if "ties=off" in sys.argv[4:]:
        # isolate NON-tie deltas: give the reference variant the same
        # one-winner pool backward as the shipping stack, so remaining
        # differences are the other lowerings + dtype rounding only
        ref["pool_bwd"] = "sas"
    variants = [("ref", ref), ("ship", ship)]

    rnd = np.random.RandomState(7)
    # input shape from the model conf
    from __graft_entry__ import ALEXNET_NET
    if model == "alexnet":
        conf = ALEXNET_NET
    else:
        from cxxnet_tpu.models import zoo
        conf = getattr(zoo, model)()
    sline = next(ln for ln in conf.splitlines()
                 if ln.strip().startswith("input_shape"))
    shape = tuple(int(x) for x in sline.split("=", 1)[1].strip().split(","))
    data = rnd.rand(batch, *shape).astype(np.float32)
    label = rnd.randint(0, 1000, (batch, 1)).astype(np.float32)

    results = {}
    for name, keys in variants:
        results[name] = run_variant(model, batch, dtype, name, keys,
                                    data, label)

    ref_nodes, ref_wb, ref_wa = results["ref"]
    print(f"\n== {model} b{batch} {dtype}: shipping stack vs "
          f"reference-semantics lowerings ==")
    for name, _ in variants[1:]:
        nodes, wb, wa = results[name]
        # weights must be bit-identical before the step (same seed/init)
        winit = max(rel_err(ref_wb[k], wb[k]) for k in ref_wb)
        print(f"[{name}] init-weight max rel err: {winit:.2e} "
              f"(must be 0)")
        print(f"--- forward per node (max |a-b| / max|ref|):")
        rows = []
        for nm in ref_nodes:
            if nm in nodes and ref_nodes[nm].shape == nodes[nm].shape:
                rows.append((rel_err(ref_nodes[nm], nodes[nm]), nm))
        rows.sort(reverse=True)
        for e, nm in rows[:12]:
            print(f"  {e:.3e}  {nm}")
        print(f"  fwd max over {len(rows)} nodes: {rows[0][0]:.3e}")
        print(f"--- one-step weight delta per param (== grad rel err):")
        prow = [(rel_err(ref_wa[k] - ref_wb[k], wa[k] - wb[k]), k)
                for k in ref_wb]
        prow.sort(reverse=True)
        for e, k in prow[:12]:
            print(f"  {e:.3e}  {k}")
        print(f"  grad max over {len(prow)} params: {prow[0][0]:.3e}")


if __name__ == "__main__":
    main()
