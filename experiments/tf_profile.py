"""Trace the transformer LM flagship step and print the per-op
breakdown + timeline occupancy (compute-busy vs copy-blocked), feeding
the per-phase roofline comparison (roofline_v2.analyze_transformer).

Usage: python experiments/tf_profile.py [d,nlayer,batch] [key=val ...]
"""
import glob
import os
import sys
from collections import defaultdict

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def run_traced(tracedir, dim=2048, nlayer=12, batch=4, vocab=8192,
               seq=4096, scan_len=4, extra=()):
    from __graft_entry__ import _make_trainer
    from bench import transformer_flops_per_token, peak_flops
    from cxxnet_tpu.models import transformer
    import time
    t = _make_trainer(
        transformer(vocab=vocab, seq=seq, dim=dim, nlayer=nlayer,
                    nhead=dim // 128),
        batch, "tpu", extra=[("dtype", "bfloat16"), ("updater", "adam"),
                             ("eval_train", "0"),
                             ("silent", "1")] + list(extra))
    kd = jax.random.PRNGKey(0)
    toks = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1, 1, seq), 0, vocab).astype(jnp.float32))(kd)
    labels = jax.jit(lambda a: jnp.roll(a, -1, axis=-1).reshape(
        scan_len, batch, seq))(toks)
    t.start_round(1)
    np.asarray(t.update_many(toks, labels))
    t0 = time.perf_counter()
    np.asarray(t.update_many(toks, labels))
    wall = (time.perf_counter() - t0) / scan_len * 1e3
    f_tok = transformer_flops_per_token(vocab, seq, dim, nlayer)
    tok_s = batch * seq / (wall / 1e3)
    mfu = 3.0 * f_tok * tok_s / peak_flops(jax.devices()[0].device_kind)
    print(f"d{dim} L{nlayer} b{batch}: wall {wall:.1f} ms/step "
          f"{tok_s/1e3:.1f}k tok/s MFU {mfu*100:.1f}%", flush=True)
    jax.profiler.start_trace(tracedir)
    np.asarray(t.update_many(toks, labels))
    jax.profiler.stop_trace()
    return scan_len


def parse(tracedir, nsteps):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = glob.glob(os.path.join(tracedir, "**", "*.xplane.pb"),
                      recursive=True)
    xs = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "TPU" not in plane.name:
            continue
        ev_names = plane.event_metadata
        for line in plane.lines:
            if "XLA Ops" not in line.name:
                continue
            tot = defaultdict(float)
            cnt = defaultdict(int)
            comp, copy = [], []
            for ev in line.events:
                # classify on the OP name only: the full text includes
                # operand names, so matching "copy-done" against it
                # misclassifies compute fusions that CONSUME async-copy
                # results as copies (this inflated "copy-blocked" from
                # ~10 to 358 ms/step on the d2048 flagship)
                name = ev_names[ev.metadata_id].name.split(" = ")[0]
                if name.startswith("%while"):
                    continue
                dur = ev.duration_ps / 1e9
                iv = (ev.offset_ps, ev.offset_ps + ev.duration_ps)
                if ("copy-start" in name or "copy-done" in name
                        or "slice-start" in name or "slice-done" in name):
                    copy.append(iv)
                else:
                    comp.append(iv)
                    tot[name] += dur
                    cnt[name] += 1

            def union(ivs):
                ivs = sorted(ivs)
                out = 0
                cs = ce = None
                for s, e in ivs:
                    if ce is None or s > ce:
                        if ce is not None:
                            out += ce - cs
                        cs, ce = s, e
                    else:
                        ce = max(ce, e)
                if ce is not None:
                    out += ce - cs
                return out / 1e9
            span = (max(e for _, e in comp + copy)
                    - min(s for s, _ in comp + copy)) / 1e9
            cu, au = union(comp), union(comp + copy)
            print(f"span {span/nsteps:.2f} ms/step | compute-busy "
                  f"{cu/nsteps:.2f} | copy-blocked {(au-cu)/nsteps:.2f} | "
                  f"idle {(span-au)/nsteps:.2f}")
            print(f"--- top compute ops (ms/step over {nsteps}):")
            for name, d in sorted(tot.items(), key=lambda kv: -kv[1])[:35]:
                print(f"  {d/nsteps:7.3f} {cnt[name]//nsteps:4d}x  "
                      f"{name[:90]}")


if __name__ == "__main__":
    cfg = sys.argv[1] if len(sys.argv) > 1 else "2048,12,4"
    d, nl, b = (int(v) for v in cfg.split(","))
    extra = [tuple(a.split("=", 1)) for a in sys.argv[2:]]
    tracedir = f"/tmp/cxprof_tf_d{d}"
    os.system(f"rm -rf {tracedir}")
    n = run_traced(tracedir, d, nl, b, extra=extra)
    parse(tracedir, n)
