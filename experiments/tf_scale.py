"""Scale the transformer LM flagship (VERDICT r3 item 6): d>=1024,
>=12 layers, s4096, flash attention (+ optional remat); report tok/s and
model-FLOPs MFU per config.

Usage: python experiments/tf_scale.py [configs...]
  config := d,nlayer,batch,remat  e.g. 1024,12,8,0
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def run(dim, nlayer, batch, remat, vocab=8192, seq=4096, scan_len=4):
    from __graft_entry__ import _make_trainer
    from bench import transformer_flops_per_token, peak_flops
    from cxxnet_tpu.models import transformer
    extra = [("dtype", "bfloat16"), ("updater", "adam"),
             ("eval_train", "0"), ("silent", "1")]
    if remat:
        extra.append(("remat", str(remat)))
    t = _make_trainer(
        transformer(vocab=vocab, seq=seq, dim=dim, nlayer=nlayer,
                    nhead=dim // 64),
        batch, "tpu", extra=extra)
    kd = jax.random.PRNGKey(0)
    toks = jax.jit(lambda k: jax.random.randint(
        k, (scan_len, batch, 1, 1, seq), 0, vocab).astype(jnp.float32))(kd)
    labels = jax.jit(lambda a: jnp.roll(a, -1, axis=-1).reshape(
        scan_len, batch, seq))(toks)
    t.start_round(1)
    c0 = time.perf_counter()
    np.asarray(t.update_many(toks, labels))
    print(f"  compile+warm {time.perf_counter()-c0:.0f}s",
          file=sys.stderr, flush=True)
    ms = []
    for _ in range(4):
        t0 = time.perf_counter()
        np.asarray(t.update_many(toks, labels))
        ms.append((time.perf_counter() - t0) / scan_len * 1e3)
    med = sorted(ms)[len(ms) // 2]
    tok_s = batch * seq / (med / 1e3)
    f_tok = transformer_flops_per_token(vocab, seq, dim, nlayer)
    mfu = 3.0 * f_tok * tok_s / peak_flops(jax.devices()[0].device_kind)
    print(f"d{dim} L{nlayer} b{batch} remat={remat}: "
          f"step {med:.1f} ms [{min(ms):.1f}..{max(ms):.1f}]  "
          f"{tok_s/1e3:.1f}k tok/s  MFU {mfu*100:.1f}% "
          f"({f_tok/1e6:.0f} MF/tok)", flush=True)
    del t, toks, labels


if __name__ == "__main__":
    cfgs = sys.argv[1:] or ["1024,12,8,0"]
    for cfg in cfgs:
        d, nl, b, rm = (int(v) for v in cfg.split(","))
        try:
            run(d, nl, b, rm)
        except Exception as e:
            print(f"{cfg}: FAILED {str(e).splitlines()[0][:140]}",
                  flush=True)
