"""AlexNet memorization probe: drive softmax loss from ln(1000) to << 1.

VERDICT r2 weak #2: recorded AlexNet curves sat at chance; this script
finds a recipe that *actually memorizes* a fixed <=512-sample synthetic
set (loss < 0.5), which becomes the recorded CONVERGENCE.jsonl artifact.
All data is generated/staged on device once; each dispatch runs k steps.

Usage: python experiments/memorize.py [eta] [steps] [batch] [nsamp] [extra...]
  extra tokens: clip=<v> noaug (strip dropout) net=googlenet s2d
"""
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp


def main():
    argv = sys.argv[1:]
    eta = float(argv[0]) if len(argv) > 0 else 0.01
    steps = int(argv[1]) if len(argv) > 1 else 2000
    batch = int(argv[2]) if len(argv) > 2 else 128
    nsamp = int(argv[3]) if len(argv) > 3 else 512
    opts = argv[4:]
    clip = next((t.split("=")[1] for t in opts if t.startswith("clip=")),
                None)
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    net = ALEXNET_NET
    shape = (3, 227, 227)
    if "net=googlenet" in opts:
        from cxxnet_tpu.models import googlenet
        net = googlenet() + "metric = error\neta = 0.01\nmomentum = 0.9\n" \
            "random_type = xavier\nsilent = 1\n"
        shape = (3, 224, 224)
    net = net.replace("eta = 0.01", f"eta = {eta}")
    if "noaug" in opts:
        net = "\n".join(l for l in net.splitlines()
                        if "dropout" not in l and "threshold" not in l)
    extra = [("dtype", "bfloat16"), ("eval_train", "0"), ("silent", "1")]
    if "s2d" in opts:
        # round-4 default bench config: input-boundary space-to-depth
        # (device-fallback transform path; correctness, not throughput)
        extra.append(("input_s2d", "1"))
    if clip:
        extra.append(("clip_gradient", clip))
    t = _make_trainer(net, batch, "tpu", extra=extra)

    assert nsamp % batch == 0
    k = nsamp // batch
    key = jax.random.PRNGKey(0)
    kd, kl = jax.random.split(key)
    # learnable synthetic set: per-class 8x8 prototypes + mild noise,
    # generated ON DEVICE (tunnel-friendly)
    nclass = 1000

    @jax.jit
    def gen(kd, kl):
        labels = jax.random.randint(kl, (k, batch), 0, nclass)
        protos = jax.random.uniform(kd, (nclass, shape[0], 8, 8))
        ry, rx = -(-shape[1] // 8), -(-shape[2] // 8)
        pat = jnp.repeat(jnp.repeat(protos[labels], ry, axis=3), rx, axis=4)
        pat = pat[:, :, :, :shape[1], :shape[2]]
        noise = jax.random.uniform(
            jax.random.fold_in(kd, 1), (k, batch) + shape) * 0.25
        data = ((pat - 0.5) * 2 + noise).astype(jnp.bfloat16)
        return data, labels[..., None].astype(jnp.float32)

    datas, labs = gen(kd, kl)
    t.start_round(1)
    t0 = time.time()
    curve = []
    for it in range(steps // k):
        losses = np.asarray(t.update_many(datas, labs))
        curve.extend(float(x) for x in losses)
        if it % max(1, (steps // k) // 20) == 0 or it == steps // k - 1:
            print(f"step {len(curve):5d}: loss {curve[-1]:.4f} "
                  f"(min {min(curve):.4f}) [{time.time()-t0:.0f}s]",
                  flush=True)
        if curve[-1] < 0.3:
            print("memorized early; stopping")
            break
    print(f"FINAL eta={eta} steps={len(curve)}: loss={curve[-1]:.4f} "
          f"min={min(curve):.4f}")


if __name__ == "__main__":
    main()
