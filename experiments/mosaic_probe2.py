"""Probe 2: Mosaic dot_general ranks + lane-merging reshapes (the forms
the conv1-wgrad kernel design needs)."""
import sys

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def run(name, kern, out_shape, *args, dtype=jnp.float32):
    try:
        f = pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct(out_shape, dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)
                      for _ in args],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
        r = jax.jit(f)(*args)
        r.block_until_ready()
        print(f"{name:44s} OK   {r.shape}")
    except Exception as e:
        msg = str(e).split("\n")[0][:100]
        print(f"{name:44s} FAIL {msg}")


def main():
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (8, 4, 128), jnp.float32)

    def k_merge_lane(x_ref, o_ref):
        o_ref[...] = x_ref[...].reshape(8, 512)

    run("reshape merge (4,128lane)->(512)", k_merge_lane, (8, 512), a)

    def k_split_lane(x_ref, o_ref):
        o_ref[...] = x_ref[...].reshape(8, 4, 128)

    run("reshape split (512)->(4,128)", k_split_lane, (8, 4, 128),
        jax.random.normal(key, (8, 512), jnp.float32))

    b1 = jax.random.normal(key, (4, 64, 128), jnp.float32)
    b2 = jax.random.normal(key, (4, 128, 64), jnp.float32)

    def k_batched_dot(x_ref, y_ref, o_ref):
        o_ref[...] = lax.dot_general(
            x_ref[...], y_ref[...], (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    run("dot_general rank3 batched", k_batched_dot, (4, 64, 64), b1, b2)

    c1 = jax.random.normal(key, (96, 3072), jnp.bfloat16)
    c2 = jax.random.normal(key, (3072, 432), jnp.bfloat16)

    def k_bigk(x_ref, y_ref, o_ref):
        o_ref[...] = lax.dot_general(
            x_ref[...], y_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    run("dot 2D (96,3072)@(3072,432) bf16", k_bigk, (96, 432), c1, c2)

    # contraction over the LANE dim (outer-product accumulate form)
    d1 = jax.random.normal(key, (96, 128), jnp.bfloat16)
    d2 = jax.random.normal(key, (432, 128), jnp.bfloat16)

    def k_lane_contract(x_ref, y_ref, o_ref):
        o_ref[...] = lax.dot_general(
            x_ref[...], y_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    run("dot 2D contract-lane (96,128)x(432,128)", k_lane_contract,
        (96, 432), d1, d2)

    # merge (55, 128) -> 7040 with non-pow2 sublane count
    e = jax.random.normal(key, (8, 55, 128), jnp.float32)

    def k_merge55(x_ref, o_ref):
        o_ref[...] = x_ref[...].reshape(8, 55 * 128)

    run("reshape merge (55,128lane)->(7040)", k_merge55, (8, 7040), e)

    # 4D block row/col dynamic indexing + 2D extraction
    f4 = jax.random.normal(key, (96, 8, 16, 128), jnp.bfloat16)

    def k_4d_extract(x_ref, o_ref):
        acc = jnp.zeros((96, 128), jnp.float32)
        def body(i, acc):
            return acc + x_ref[:, 2, i].astype(jnp.float32)
        acc = lax.fori_loop(0, 16, body, acc)
        o_ref[...] = acc

    run("4D major dyn-index (96,128) extract", k_4d_extract, (96, 128),
        f4)


if __name__ == "__main__":
    main()


def extra():
    key = jax.random.PRNGKey(1)
    # strided slice on a MAJOR dim (dim 0 of a 3D block) — pool-over-W
    # in (H, W, C, N) layout needs this
    g = jax.random.normal(key, (55, 16, 128), jnp.bfloat16)

    def k_major_stride(x_ref, o_ref):
        o_ref[...] = lax.slice(x_ref[...], (0, 0, 0), (53, 16, 128),
                               (2, 1, 1))

    run("strided slice MAJOR dim (55,16,128)[::2]", k_major_stride,
        (27, 16, 128), g, dtype=jnp.bfloat16)

    def k_major_stride_jnp(x_ref, o_ref):
        o_ref[...] = x_ref[...][0:53:2]

    run("jnp [0:53:2] MAJOR dim", k_major_stride_jnp, (27, 16, 128), g,
        dtype=jnp.bfloat16)

    # sublane shifted slices on dim1 of rank-3 (LRN channel window form)
    h = jax.random.normal(key, (8, 96, 128), jnp.bfloat16)

    def k_sublane_shift(x_ref, o_ref):
        v = x_ref[...]
        o_ref[...] = v[:, 0:92] + v[:, 1:93] + v[:, 2:94]

    run("sublane shifted sums (8,96,128)", k_sublane_shift, (8, 92, 128),
        h, dtype=jnp.bfloat16)

    # 4D: strided slice on dim0+dim1 of (55,55,16,128)
    i4 = jax.random.normal(key, (55, 55, 16, 128), jnp.bfloat16)

    def k_4d_stride(x_ref, o_ref):
        v = x_ref[...]
        o_ref[...] = v[0:53:2, 1:54:2]

    run("4D strided both major dims", k_4d_stride, (27, 27, 16, 128), i4,
        dtype=jnp.bfloat16)


if __name__ == "__main__":
    main()
    extra()
