"""Dump the optimized HLO of the AlexNet multi-step train program."""
import sys

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    batch, scan_len = 1024, 2
    from __graft_entry__ import ALEXNET_NET, _make_trainer
    t = _make_trainer(ALEXNET_NET, batch, "tpu",
                      extra=[("dtype", "bfloat16"), ("eval_train", "0")])
    fn = t._build_multi_step(scan_len)
    rnd = np.random.RandomState(0)
    datas = jnp.zeros((scan_len, batch, 3, 227, 227), jnp.bfloat16)
    labels = jnp.zeros((scan_len, batch, 1), jnp.float32)
    lowered = fn.lower(t.params, t.opt_state, t.buffers,
                       jnp.int32(0), t._rng_base, datas, labels)
    compiled = lowered.compile()
    out = "/tmp/alexnet_step.hlo"
    with open(out, "w") as f:
        f.write(compiled.as_text())
    print("wrote", out)


if __name__ == "__main__":
    main()
