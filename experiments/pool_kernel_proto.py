"""Fused relu+maxpool Pallas kernels in (C, H, W, N) — batch in lanes.

Mosaic on v5e rejects strided sublane slices (they lower to gather), but
supports reshape-SPLITTING the sublane dim ((C, W, N) -> (C, W/s, s, N))
and stack+reshape interleaving back — measured by
experiments/mosaic_probe.py.  So stride-s window access is expressed as
phase deinterleave + unit-stride shifted slices, and the backward's
strided placement as per-phase accumulators + interleave.

Blocks carry FULL (H, W) per (C-tile, N-tile) program (H*W*128 fits VMEM
for every geometry in the zoo), so row access is static indexing.

Timed against XLA reduce_window / select-and-scatter in the same CHWN
layout, AlexNet pool1 geometry by default.

Usage: python experiments/pool_kernel_proto.py [C H W N k s]
"""
import functools
import sys

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:
    pltpu = None

from experiments.mb_util import bench_op

NEG = -1e30


def pool_out(i, k, s):
    return min(i - k + s - 1, i - 1) // s + 1


def _pick_cb(c, h, w, n_lanes, itemsize, budget=3 << 20):
    cb = max(1, budget // max(h * w * n_lanes * itemsize, 1))
    cb = min(cb, c)
    while c % cb:
        cb -= 1
    return cb


def _phases(row, s, wpad, fill):
    """(CB, W, N) -> s phase views (CB, W/s, N): row[c, p + s*q, n] =
    phases[p][c, q, n].  Pads W up to wpad (multiple of s) with fill."""
    cb, w, n = row.shape
    if w < wpad:
        pad = jnp.full((cb, wpad - w, n), fill, row.dtype)
        row = jnp.concatenate([row, pad], axis=1)
    v = row.reshape(cb, wpad // s, s, n)
    return [v[:, :, p, :] for p in range(s)]


# ---------------------------------------------------------------- kernels
def _fwd_kernel(x_ref, o_ref, *, k, s, oh, ow, wpad):
    """relu + k x k / s max pool over full-(H, W) blocks."""
    for r in range(oh):
        acc = None
        for i in range(k):
            row = jnp.maximum(x_ref[:, s * r + i], 0.0)   # (CB, W, NB)
            ph = _phases(row, s, wpad, NEG)
            for j in range(k):
                v = ph[j % s][:, j // s:j // s + ow]
                acc = v if acc is None else jnp.maximum(acc, v)
        o_ref[:, r] = acc.astype(o_ref.dtype)


def _bwd_kernel(x_ref, p_ref, dp_ref, dx_ref, *, k, s, oh, ow, wpad):
    """eq-mask (all-ties mshadow unpool) + relu mask, one pass.

    For each input row h, dx[h] sums contributions from output rows r
    with s*r <= h < s*r + k; within a row, contributions to position
    w = j + s*t accumulate per phase (w mod s) and interleave back.
    """
    h = x_ref.shape[1]
    wq = wpad // s
    for hrow in range(h):
        x_row = x_ref[:, hrow]
        # compare in f32: Mosaic rejects bf16 eq on the deinterleaved
        # (sublane-split) vector layout ("target does not support this
        # comparison"); the cast is free relative to the HBM traffic
        a_row = jnp.maximum(x_row.astype(jnp.float32), 0.0)
        ph = _phases(a_row, s, wpad, NEG)
        acc = [None] * s
        for i in range(k):
            r = hrow - i
            if r < 0 or r % s or r // s >= oh:
                continue
            r //= s
            pv = p_ref[:, r].astype(jnp.float32)           # (CB, OW, NB)
            dv = dp_ref[:, r].astype(jnp.float32)
            for j in range(k):
                q = j // s
                av = ph[j % s][:, q:q + ow]
                contrib = jnp.where(av == pv, dv, 0.0)
                # place at phase j%s, offset q: pad to (CB, wq, NB);
                # zero-width parts are dropped (Mosaic rejects 0-sized
                # vectors)
                cb, _, nb = contrib.shape
                parts = []
                if q:
                    parts.append(jnp.zeros((cb, q, nb), jnp.float32))
                parts.append(contrib)
                if wq - q - ow:
                    parts.append(jnp.zeros((cb, wq - q - ow, nb),
                                           jnp.float32))
                placed = parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts, axis=1)
                acc[j % s] = placed if acc[j % s] is None \
                    else acc[j % s] + placed
        zeros = jnp.zeros((x_row.shape[0], wq, x_row.shape[2]),
                          jnp.float32)
        parts = [zeros if a is None else a for a in acc]
        wide = jnp.stack(parts, axis=2).reshape(
            x_row.shape[0], wpad, x_row.shape[2])[:, :x_row.shape[1]]
        dx_ref[:, hrow] = jnp.where(x_row.astype(jnp.float32) > 0.0,
                                    wide, 0.0).astype(dx_ref.dtype)


def _call(kern, x, outs_shape, in_arrays, cb, nb, interpret):
    c, h, w, n = x.shape
    grid = (n // nb, c // cb)
    vmem = pltpu.VMEM if (pltpu and not interpret) else None

    def spec(shape4):
        imap = lambda bn, bc: (bc, 0, 0, bn)  # noqa: E731
        if vmem is None:
            return pl.BlockSpec(shape4, imap)
        return pl.BlockSpec(shape4, imap, memory_space=vmem)

    in_specs = [spec((cb,) + a.shape[1:3] + (nb,)) for a in in_arrays]
    out_spec = spec((cb,) + outs_shape[1:3] + (nb,))
    return pl.pallas_call(
        kern, grid=grid,
        in_specs=in_specs, out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct(outs_shape, x.dtype),
        interpret=interpret,
    )(*in_arrays)


def pallas_relu_pool_fwd(x, k, s, *, nb=128, interpret=False):
    c, h, w, n = x.shape
    oh, ow = pool_out(h, k, s), pool_out(w, k, s)
    assert (oh - 1) * s + k == h and (ow - 1) * s + k == w, \
        "prototype: exact-cover pools only"
    wpad = -(-w // s) * s
    cb = _pick_cb(c, h, w, nb, x.dtype.itemsize)
    kern = functools.partial(_fwd_kernel, k=k, s=s, oh=oh, ow=ow, wpad=wpad)
    return _call(kern, x, (c, oh, ow, n), [x], cb, nb, interpret)


def pallas_relu_pool_bwd(x, p, dp, k, s, *, nb=128, interpret=False):
    c, h, w, n = x.shape
    oh, ow = p.shape[1], p.shape[2]
    wpad = -(-w // s) * s
    cb = _pick_cb(c, h, w, nb, 4)  # f32 accumulators dominate
    kern = functools.partial(_bwd_kernel, k=k, s=s, oh=oh, ow=ow, wpad=wpad)
    return _call(kern, x, x.shape, [x, p, dp], cb, nb, interpret)


# ------------------------------------------------------------- baselines
def xla_relu_pool_chwn(x, k, s):
    return lax.reduce_window(jnp.maximum(x, 0.0), -jnp.inf, lax.max,
                             (1, k, k, 1), (1, s, s, 1), "VALID")


def xla_relu_pool_nchw(x, k, s):
    return lax.reduce_window(jnp.maximum(x, 0.0), -jnp.inf, lax.max,
                             (1, 1, k, k), (1, 1, s, s), "VALID")


def main():
    args = [int(a) for a in sys.argv[1:]] or [96, 55, 55, 1024]
    c, h, w, n = args[:4]
    k = args[4] if len(args) > 4 else 3
    s = args[5] if len(args) > 5 else 2
    on_tpu = jax.default_backend() == "tpu"
    x = jax.random.normal(jax.random.PRNGKey(0), (c, h, w, n),
                          jnp.float32).astype(jnp.bfloat16)

    # correctness first (small slice; interpret off-TPU)
    xs = x[:8, :, :, :256]
    want = xla_relu_pool_chwn(xs, k, s)
    got = pallas_relu_pool_fwd(xs, k, s, interpret=not on_tpu)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=1e-2)
    print("fwd correctness ok")

    p = want
    dp = jax.random.normal(jax.random.PRNGKey(1), p.shape,
                           jnp.float32).astype(jnp.bfloat16)
    got_dx = pallas_relu_pool_bwd(xs, p, dp, k, s, interpret=not on_tpu)
    xf = np.maximum(np.asarray(xs, np.float32), 0.0)
    pf = np.asarray(p, np.float32)
    df = np.asarray(dp, np.float32)
    oh, ow = pf.shape[1], pf.shape[2]
    want_dx = np.zeros_like(xf)
    for r in range(oh):
        for cc in range(ow):
            win = xf[:, s * r:s * r + k, s * cc:s * cc + k, :]
            m = win == pf[:, r:r + 1, cc:cc + 1, :]
            want_dx[:, s * r:s * r + k, s * cc:s * cc + k, :] += \
                m * df[:, r:r + 1, cc:cc + 1, :]
    want_dx *= (np.asarray(xs, np.float32) > 0)
    np.testing.assert_allclose(np.asarray(got_dx, np.float32), want_dx,
                               atol=5e-2)
    print("bwd correctness ok (all-ties eq-mask + relu mask)")

    if not on_tpu:
        print("CPU: skipping timing")
        return

    t = bench_op(lambda a: xla_relu_pool_chwn(a, k, s), x)
    print(f"XLA  relu+pool fwd CHWN: {t:.3f} ms")
    t = bench_op(lambda a: pallas_relu_pool_fwd(a, k, s), x)
    print(f"PALL relu+pool fwd CHWN: {t:.3f} ms")

    x_nchw = jnp.transpose(x, (3, 0, 1, 2))
    t = bench_op(lambda a: xla_relu_pool_nchw(a, k, s), x_nchw)
    print(f"XLA  relu+pool fwd NCHW: {t:.3f} ms")
    t = bench_op(
        lambda a: pallas_relu_pool_fwd(
            jnp.transpose(a, (1, 2, 3, 0)), k, s), x_nchw)
    print(f"PALL fwd w/ NCHW->CHWN transpose in-line: {t:.3f} ms")

    p_full = xla_relu_pool_chwn(x, k, s)
    dp_full = jax.random.normal(jax.random.PRNGKey(2), p_full.shape,
                                jnp.float32).astype(jnp.bfloat16)

    def sas_bwd(a, g):
        _, vjp = jax.vjp(lambda v: xla_relu_pool_chwn(v, k, s), a)
        return vjp(g)[0]

    t = bench_op(sas_bwd, x, dp_full)
    print(f"XLA  SAS bwd CHWN:       {t:.3f} ms")

    def sas_bwd_nchw(a, g):
        _, vjp = jax.vjp(lambda v: xla_relu_pool_nchw(v, k, s), a)
        return vjp(g)[0]

    t = bench_op(sas_bwd_nchw, x_nchw, jnp.transpose(dp_full, (3, 0, 1, 2)))
    print(f"XLA  SAS bwd NCHW:       {t:.3f} ms")
    t = bench_op(lambda a, pp, g: pallas_relu_pool_bwd(a, pp, g, k, s),
                 x, p_full, dp_full)
    print(f"PALL eq bwd CHWN:        {t:.3f} ms")


if __name__ == "__main__":
    main()
