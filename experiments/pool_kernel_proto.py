"""Feasibility probe: fused relu+maxpool Pallas kernel in (C, H, W, N).

The round-3 kernel plan puts batch in lanes (N=128 multiples) and spatial
dims on freely-sliced major/sublane axes.  Blocks carry FULL (H, W) per
(C-tile, N-tile) program — H*W*128 fits VMEM for every geometry in the
zoo — so windows are all-static slices; the only Mosaic unknown is the
STRIDED sublane access along W (x[..., j::s, :]).

Times, on the AlexNet pool1 geometry (96, 55, 55, 1024):
  1. XLA reduce_window relu+pool in CHWN        (the no-kernel baseline)
  2. Pallas fused relu+pool fwd                 (strided sublane slices)
  3. Pallas fused bwd: eq-mask all-ties unpool + relu mask
  4. XLA select-and-scatter bwd in CHWN         (the SAS baseline)

Usage: python experiments/pool_kernel_proto.py [C H W N k s]
"""
import functools
import sys

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:
    pltpu = None

from experiments.mb_util import bench_op


def pool_out(i, k, s):
    return min(i - k + s - 1, i - 1) // s + 1


def _pick_cb(c, h, w, n_lanes, itemsize, budget=3 << 20):
    cb = max(1, budget // max(h * w * n_lanes * itemsize, 1))
    while c % cb:
        cb -= 1
    return cb


# ---------------------------------------------------------------- kernels
def _fwd_kernel(x_ref, o_ref, *, k, s, oh, ow):
    a = jnp.maximum(x_ref[...], 0.0)          # (CB, H, W, NB)
    rows = []
    for r in range(oh):
        acc = None
        for i in range(k):
            xr = a[:, s * r + i]              # (CB, W, NB)
            for j in range(k):
                v = xr[:, j:j + (ow - 1) * s + 1:s]   # strided sublane
                acc = v if acc is None else jnp.maximum(acc, v)
        rows.append(acc)
    o_ref[...] = jnp.stack(rows, axis=1).astype(o_ref.dtype)


def _bwd_kernel(x_ref, p_ref, dp_ref, dx_ref, *, k, s, oh, ow):
    """eq-mask (all-ties) unpool + relu mask: one pass, full H in block."""
    x = x_ref[...]
    a = jnp.maximum(x, 0.0)
    zero = jnp.zeros((), jnp.float32)
    h = x.shape[1]
    row_acc = [None] * h
    for r in range(oh):
        pv = p_ref[:, r]                      # (CB, OW, NB)
        dv = dp_ref[:, r].astype(jnp.float32)
        for i in range(k):
            hrow = s * r + i
            ar = a[:, hrow]
            for j in range(k):
                av = ar[:, j:j + (ow - 1) * s + 1:s]
                contrib = jnp.where(av == pv, dv, zero)
                # place back on the row at strided positions: build a
                # full-width row via interleave (scatter-free): positions
                # j + s*t for t in [0, ow)
                wide = jnp.zeros(ar.shape, jnp.float32)
                wide = wide.at[:, j:j + (ow - 1) * s + 1:s].add(contrib)
                row_acc[hrow] = wide if row_acc[hrow] is None \
                    else row_acc[hrow] + wide
    rows = [jnp.zeros(a[:, 0].shape, jnp.float32) if rc is None else rc
            for rc in row_acc]
    dx = jnp.stack(rows, axis=1)
    dx_ref[...] = jnp.where(x > 0.0, dx, zero).astype(dx_ref.dtype)


def _call(kern, x, outs_shape, in_arrays, cb, nb, interpret):
    c, h, w, n = x.shape
    grid = (n // nb, c // cb)
    vmem = pltpu.VMEM if (pltpu and not interpret) else None

    def spec(shape4):
        imap = lambda bn, bc: (bc, 0, 0, bn)  # noqa: E731
        if vmem is None:
            return pl.BlockSpec(shape4, imap)
        return pl.BlockSpec(shape4, imap, memory_space=vmem)

    in_specs = [spec((cb,) + a.shape[1:3] + (nb,)) for a in in_arrays]
    out_spec = spec((cb,) + outs_shape[1:3] + (nb,))
    return pl.pallas_call(
        kern, grid=grid,
        in_specs=in_specs, out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct(outs_shape, x.dtype),
        interpret=interpret,
    )(*in_arrays)


def pallas_relu_pool_fwd(x, k, s, *, nb=128, interpret=False):
    c, h, w, n = x.shape
    oh, ow = pool_out(h, k, s), pool_out(w, k, s)
    assert (oh - 1) * s + k == h and (ow - 1) * s + k == w, \
        "prototype: exact-cover pools only"
    cb = _pick_cb(c, h, w, nb, x.dtype.itemsize)
    kern = functools.partial(_fwd_kernel, k=k, s=s, oh=oh, ow=ow)
    return _call(kern, x, (c, oh, ow, n), [x], cb, nb, interpret)


def pallas_relu_pool_bwd(x, p, dp, k, s, *, nb=128, interpret=False):
    c, h, w, n = x.shape
    oh, ow = p.shape[1], p.shape[2]
    cb = _pick_cb(c, h, w, nb, 4)  # f32 accumulator dominates
    kern = functools.partial(_bwd_kernel, k=k, s=s, oh=oh, ow=ow)
    return _call(kern, x, x.shape, [x, p, dp], cb, nb, interpret)


# ------------------------------------------------------------- baselines
def xla_relu_pool_chwn(x, k, s):
    return lax.reduce_window(jnp.maximum(x, 0.0), -jnp.inf, lax.max,
                             (1, k, k, 1), (1, s, s, 1), "VALID")


def main():
    args = [int(a) for a in sys.argv[1:]] or [96, 55, 55, 1024]
    c, h, w, n = args[:4]
    k = args[4] if len(args) > 4 else 3
    s = args[5] if len(args) > 5 else 2
    on_tpu = jax.default_backend() == "tpu"
    x = jax.random.normal(jax.random.PRNGKey(0), (c, h, w, n),
                          jnp.float32).astype(jnp.bfloat16)

    # correctness vs XLA first (small slice, interpret off-TPU)
    xs = x[:8, :, :, :256]
    want = xla_relu_pool_chwn(xs, k, s)
    got = pallas_relu_pool_fwd(xs, k, s, interpret=not on_tpu)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), atol=1e-2)
    print("fwd correctness ok")

    p = want
    dp = jax.random.normal(jax.random.PRNGKey(1), p.shape,
                           jnp.float32).astype(jnp.bfloat16)
    got_dx = pallas_relu_pool_bwd(xs, p, dp, k, s, interpret=not on_tpu)
    xf = np.maximum(np.asarray(xs, np.float32), 0.0)
    pf = np.asarray(p, np.float32)
    df = np.asarray(dp, np.float32)
    oh, ow = pf.shape[1], pf.shape[2]
    want_dx = np.zeros_like(xf)
    for r in range(oh):
        for cc in range(ow):
            win = xf[:, s * r:s * r + k, s * cc:s * cc + k, :]
            m = win == pf[:, r:r + 1, cc:cc + 1, :]
            want_dx[:, s * r:s * r + k, s * cc:s * cc + k, :] += \
                m * df[:, r:r + 1, cc:cc + 1, :]
    want_dx *= (np.asarray(xs, np.float32) > 0)
    np.testing.assert_allclose(np.asarray(got_dx, np.float32), want_dx,
                               atol=5e-2)
    print("bwd correctness ok (all-ties eq-mask + relu mask)")

    if not on_tpu:
        print("CPU: skipping timing")
        return

    t = bench_op(lambda a: xla_relu_pool_chwn(a, k, s), x)
    print(f"XLA  relu+pool fwd CHWN: {t:.3f} ms")
    t = bench_op(lambda a: pallas_relu_pool_fwd(a, k, s), x)
    print(f"PALL relu+pool fwd CHWN: {t:.3f} ms")

    p_full = xla_relu_pool_chwn(x, k, s)
    dp_full = jax.random.normal(jax.random.PRNGKey(2), p_full.shape,
                                jnp.float32).astype(jnp.bfloat16)

    def sas_bwd(a, g):
        _, vjp = jax.vjp(lambda v: xla_relu_pool_chwn(v, k, s), a)
        return vjp(g)[0]

    t = bench_op(sas_bwd, x, dp_full)
    print(f"XLA  SAS bwd CHWN:       {t:.3f} ms")
    t = bench_op(lambda a, pp, g: pallas_relu_pool_bwd(a, pp, g, k, s),
                 x, p_full, dp_full)
    print(f"PALL eq bwd CHWN:        {t:.3f} ms")


if __name__ == "__main__":
    main()
