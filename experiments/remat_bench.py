"""remat = K memory/throughput trade on real models.

Usage: python experiments/remat_bench.py [model] [batch] [K]
Prints step time + XLA memory analysis with and without remat.
"""
import sys

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp


def run(model="vgg16", batch=256, k=4):
    from __graft_entry__ import _make_trainer
    from cxxnet_tpu.models.zoo import googlenet, vgg
    if model == "googlenet":
        # aux heads ON: partitionable since the multi-node-frontier
        # partitioner (round 4); the depth-22 trunk needs them to train
        conf = googlenet(num_class=1000, aux_heads=True)
    else:
        conf = vgg(depth=16)
    conf += "metric = error\neta = 0.01\nmomentum = 0.9\nsilent = 1\n"
    shape = (3, 224, 224)
    for remat in (0, k):
        try:
            t = _make_trainer(
                conf, batch, "tpu",
                extra=[("dtype", "bfloat16"), ("eval_train", "0"),
                       ("remat", str(remat))])
            kd, kl = jax.random.split(jax.random.PRNGKey(0))
            data = jax.jit(lambda kk: jax.random.uniform(
                kk, (batch,) + shape, jnp.float32).astype(jnp.bfloat16))(kd)
            lab = jax.jit(lambda kk: jax.random.randint(
                kk, (batch, 1), 0, 1000).astype(jnp.float32))(kl)
            t.start_round(1)
            step = t._train_step
            lowered = step.lower(t.params, t.opt_state, t.buffers, data,
                                 lab, (), jnp.int32(0), t._rng_base)
            comp = lowered.compile()
            mem = comp.memory_analysis()
            tmp = getattr(mem, "temp_size_in_bytes", 0) / 1e9
            # NOTE: timing through the donated-compiled handle is not
            # meaningful (donated buffers can't be re-fed); the static
            # memory analysis is the result here
            print(f"remat={remat}: XLA temp {tmp:5.2f} GB", flush=True)
            del t
        except Exception as e:
            print(f"remat={remat}: FAILED {str(e).splitlines()[0][:120]}",
                  flush=True)


if __name__ == "__main__":
    run(model=sys.argv[1] if len(sys.argv) > 1 else "vgg16",
        batch=int(sys.argv[2]) if len(sys.argv) > 2 else 256,
        k=int(sys.argv[3]) if len(sys.argv) > 3 else 4)
