"""On-device microbench harness for the axon-tunneled TPU.

Per-dispatch latency over the tunnel is ~ms, so time k iterations inside ONE
jitted fori_loop and divide.  The carry perturbs the inputs each iteration
(x * (1 + tiny*i)) so XLA cannot hoist the measured op out of the loop, and
the output is reduced into the carry so nothing is dead-code-eliminated.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np


def _sync(r):
    leaf = jax.tree.leaves(r)[-1]
    np.asarray(jnp.ravel(leaf)[:1])


def bench_op(f, *args, k1=4, k2=24, n=4):
    """Mean ms per call of f(*args), free of dispatch/sync constants.

    Times a k-iteration device loop at two k values and divides the time
    difference by the iteration difference, cancelling the (large, ~tens of
    ms) per-dispatch + D2H-sync round-trip of the tunneled TPU.
    """
    def make(k):
        def loop(*args):
            def body(i, acc):
                s = 1.0 + 1e-6 * jnp.float32(i)
                perturbed = jax.tree.map(
                    lambda a: a * s.astype(a.dtype), tuple(args))
                r = f(*perturbed)
                leaves = jax.tree.leaves(r)
                return acc + sum(jnp.sum(l).astype(jnp.float32)
                                 for l in leaves)
            return jax.lax.fori_loop(0, k, body, jnp.float32(0.0),
                                     unroll=False)
        return jax.jit(loop)

    j1, j2 = make(k1), make(k2)
    _sync(j1(*args))
    _sync(j2(*args))
    t1 = t2 = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        _sync(j1(*args))
        t1 = min(t1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        _sync(j2(*args))
        t2 = min(t2, time.perf_counter() - t0)
    return (t2 - t1) / (k2 - k1) * 1e3


def bench_empty():
    """The harness floor: perturb+reduce with an identity op."""
    x = jnp.ones((8, 128), jnp.bfloat16)
    return bench_op(lambda a: a, x)
