"""Probe which strided-access forms Mosaic supports on real TPU.

Each candidate is a tiny kernel; print compile ok/fail + a timing.
The pool kernels need: strided READ along the sublane (W) axis, and
ideally a strided WRITE (or a cheap interleave) for the backward.
"""
import functools
import sys

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

C, W, N = 8, 64, 128
OW = W // 2


def run(name, kern, out_shape, *args):
    try:
        f = pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct(out_shape, jnp.bfloat16),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)
                      for _ in args],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
        r = jax.jit(f)(*args)
        r.block_until_ready()
        print(f"{name:40s} OK   {r.shape}")
        return r
    except Exception as e:
        msg = str(e).split("\n")[0][:110]
        print(f"{name:40s} FAIL {msg}")
        return None


def main():
    x = jax.random.normal(jax.random.PRNGKey(0), (C, W, N),
                          jnp.float32).astype(jnp.bfloat16)

    def k_lax_slice(x_ref, o_ref):
        v = lax.slice(x_ref[...], (0, 0, 0), (C, W - 1, N), (1, 2, 1))
        o_ref[...] = v

    run("lax.slice stride2 sublane 3D", k_lax_slice, (C, OW, N), x)

    def k_lax_slice2d(x_ref, o_ref):
        for c in range(C):
            v = lax.slice(x_ref[c], (0, 0), (W - 1, N), (2, 1))
            o_ref[c] = v

    run("lax.slice stride2 sublane 2D/chan", k_lax_slice2d, (C, OW, N), x)

    def k_jnp_idx2d(x_ref, o_ref):
        for c in range(C):
            o_ref[c] = x_ref[c][0:W - 1:2]

    run("jnp [0:W-1:2] 2D per chan", k_jnp_idx2d, (C, OW, N), x)

    def k_ref_strided_read(x_ref, o_ref):
        o_ref[...] = x_ref[:, 0:W - 1:2, :]

    run("ref strided read 3D", k_ref_strided_read, (C, OW, N), x)

    def k_roll(x_ref, o_ref):
        o_ref[...] = jnp.maximum(x_ref[...],
                                 pltpu.roll(x_ref[...], -1, 1))[:, :OW]

    run("pltpu.roll sublane", k_roll, (C, OW, N), x)

    # strided WRITE forms
    y = jax.random.normal(jax.random.PRNGKey(1), (C, OW, N),
                          jnp.float32).astype(jnp.bfloat16)

    def k_strided_store(y_ref, o_ref):
        o_ref[...] = jnp.zeros((C, W, N), jnp.bfloat16)
        o_ref[:, 0:W - 1:2, :] = y_ref[...]

    run("ref strided store 3D", k_strided_store, (C, W, N), y)

    def k_at_add(y_ref, o_ref):
        z = jnp.zeros((C, W, N), jnp.float32)
        z = z.at[:, 0:W - 1:2, :].add(y_ref[...].astype(jnp.float32))
        o_ref[...] = z.astype(jnp.bfloat16)

    run("jnp .at[::2].add 3D", k_at_add, (C, W, N), y)

    # interleave two phases via reshape (W/2, 2) -> W on sublane-major
    def k_interleave(y_ref, o_ref):
        a = y_ref[...]
        b = a * 2.0
        st = jnp.stack([a, b], axis=2)          # (C, OW, 2, N)
        o_ref[...] = st.reshape(C, W, N)

    run("stack+reshape interleave", k_interleave, (C, W, N), y)

    # dynamic row index (needed for bwd p-block rows)
    def k_dyn_row(x_ref, o_ref):
        i = pl.program_id(0) if False else 3
        o_ref[...] = x_ref[:, pl.ds(i, OW), :]

    run("pl.ds row window", k_dyn_row, (C, OW, N), x)


def extra():
    x = jax.random.normal(jax.random.PRNGKey(0), (C, W, N),
                          jnp.float32).astype(jnp.bfloat16)

    def k_deinterleave(x_ref, o_ref):
        v = x_ref[...].reshape(C, W // 2, 2, N)
        o_ref[...] = v[:, :, 0, :]

    run("reshape-split deinterleave", k_deinterleave, (C, OW, N), x)

    def k_deinterleave_both(x_ref, o_ref):
        v = x_ref[...].reshape(C, W // 2, 2, N)
        o_ref[...] = jnp.maximum(v[:, :, 0, :], v[:, :, 1, :])

    run("deinterleave both phases + max", k_deinterleave_both,
        (C, OW, N), x)

    def k_roll_pos(x_ref, o_ref):
        o_ref[...] = jnp.maximum(x_ref[...],
                                 pltpu.roll(x_ref[...], 1, 1))[:, :OW]

    run("pltpu.roll +1 sublane", k_roll_pos, (C, OW, N), x)

    def k_shift_slice(x_ref, o_ref):
        # static slice (shift by 1 along sublane, no stride)
        o_ref[...] = jnp.maximum(x_ref[:, 0:OW, :], x_ref[:, 1:OW + 1, :])

    run("unit-stride shifted slices + max", k_shift_slice, (C, OW, N), x)


if __name__ == "__main__":
    main()
    extra()
