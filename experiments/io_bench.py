"""Input-pipeline throughput: native C++ loader vs Python imgbin chain.

Generates synthetic 256x256 JPEGs, packs them with the native im2bin, then
measures imgs/sec of:
  1. native loader (iter=imbin_native, C++ decode+batch assembly)
  2. python imgbin + augment chain (decode_thread_num=0 and =8)
at AlexNet geometry (227 crop, mirror, b256).

The device side consumes ~19.4k imgs/sec (bench.py b1024); the loader must
match that on a real TPU host to keep the chip fed (VERDICT #3).
"""
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def make_dataset(work, n=2048):
    import cv2
    img_dir = os.path.join(work, "img")
    os.makedirs(img_dir, exist_ok=True)
    rnd = np.random.RandomState(0)
    lst = os.path.join(work, "train.lst")
    with open(lst, "w") as f:
        for i in range(n):
            # blurred noise: photographic-ish entropy (raw noise jpegs
            # decode ~3x slower than natural images and would understate
            # the pipeline)
            arr = cv2.GaussianBlur(
                rnd.randint(0, 255, (256, 256, 3), np.uint8), (9, 9), 3)
            name = f"{i:05d}.jpg"
            cv2.imwrite(os.path.join(img_dir, name), arr,
                        [cv2.IMWRITE_JPEG_QUALITY, 80])
            f.write(f"{i}\t{i % 10}\t{name}\n")
    binpath = os.path.join(work, "train.bin")
    subprocess.run([os.path.join(ROOT, "native", "im2bin"),
                    lst, img_dir + "/", binpath], check=True)
    return lst, img_dir, binpath


def bench_iter(it, n_epochs=3):
    from cxxnet_tpu.io.data import DataBatch
    # warm epoch
    count = 0
    it.before_first()
    while it.next() is not None:
        pass
    t0 = time.perf_counter()
    for _ in range(n_epochs):
        it.before_first()
        while True:
            b = it.next()
            if b is None:
                break
            count += b.batch_size if hasattr(b, "batch_size") else 1
    dt = time.perf_counter() - t0
    it.close()
    return count / dt


def native_iter(lst, binpath, threads):
    # the native loader decodes at source resolution (augmentation lives in
    # the Python chain or offline preprocessing)
    from cxxnet_tpu.io.native import NativeImageBinIterator
    it = NativeImageBinIterator()
    for k, v in [("image_list", lst), ("image_bin", binpath),
                 ("batch_size", "256"), ("input_shape", "3,256,256"),
                 ("decode_thread_num", str(threads)), ("silent", "1"),
                 ("round_batch", "1")]:
        it.set_param(k, v)
    it.init()
    return it


def python_iter(lst, binpath, threads):
    from cxxnet_tpu.io.factory import create_iterator, init_iterator
    cfg = [("iter", "imgbin"),
           ("image_list", lst), ("image_bin", binpath),
           ("decode_thread_num", str(threads)),
           ("iter", "end")]
    it = create_iterator(cfg)
    init_iterator(it, [("batch_size", "256"),
                       ("input_shape", "3,227,227"),
                       ("rand_crop", "1"), ("rand_mirror", "1"),
                       ("round_batch", "1"), ("silent", "1")])
    return it


def make_raw_dataset(work, n=2048, shape=(3, 227, 227)):
    """Pack raw-u8 CHW records (no jpeg): measures the non-decode pipeline
    ceiling — page streaming, batch assembly, normalization — on a box
    whose single CPU core saturates jpeg decode at ~570 imgs/sec.  The
    native record rules (imbin_iter.cc: len == c*h*w -> raw u8) make this
    a first-class path, the operating mode for pre-decoded datasets."""
    from cxxnet_tpu.io.imbin import BinaryPageWriter
    rnd = np.random.RandomState(0)
    lst = os.path.join(work, "raw.lst")
    binpath = os.path.join(work, "raw.bin")
    w = BinaryPageWriter(binpath)
    with open(lst, "w") as f:
        for i in range(n):
            w.push(rnd.randint(0, 255, shape, np.uint8).tobytes())
            f.write(f"{i}\t{i % 10}\traw{i}\n")
    w.close()
    return lst, binpath


def native_raw_iter(lst, binpath, threads, shape=(3, 227, 227), u8=False):
    from cxxnet_tpu.io.native import NativeImageBinIterator
    it = NativeImageBinIterator()
    for k, v in [("image_list", lst), ("image_bin", binpath),
                 ("batch_size", "256"),
                 ("input_shape", ",".join(map(str, shape))),
                 ("decode_thread_num", str(threads)), ("silent", "1"),
                 ("round_batch", "1"), ("output_u8", str(int(u8)))]:
        it.set_param(k, v)
    it.init()
    return it


def main():
    work = tempfile.mkdtemp()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    raw_only = len(sys.argv) > 2 and sys.argv[2] == "raw"
    # raw-u8 records: the decode-free ceiling (VERDICT r2 #8)
    rlst, rbin = make_raw_dataset(work, n)
    print(f"raw-u8 dataset: {n} insts, "
          f"{os.path.getsize(rbin)/1e6:.0f} MB packed")
    for threads in (0, 2, 4):
        r = bench_iter(native_raw_iter(rlst, rbin, threads))
        print(f"native loader RAW->f32, {threads:2d} threads: "
              f"{r:8.0f} imgs/sec")
    # output_u8: no float conversion on the host at all (device-side
    # normalization path) — the pure page-stream + memcpy ceiling
    for threads in (0, 2):
        r = bench_iter(native_raw_iter(rlst, rbin, threads, u8=True))
        print(f"native loader RAW->u8,  {threads:2d} threads: "
              f"{r:8.0f} imgs/sec")
    if raw_only:
        return
    lst, img_dir, binpath = make_dataset(work, n)
    print(f"dataset: {n} jpegs, {os.path.getsize(binpath)/1e6:.0f} MB packed")
    for threads in (4, 8, 16):
        r = bench_iter(native_iter(lst, binpath, threads))
        print(f"native loader, {threads:2d} threads: {r:8.0f} imgs/sec")
    for threads in (0, 8):
        r = bench_iter(python_iter(lst, binpath, threads))
        print(f"python imgbin, {threads:2d} threads: {r:8.0f} imgs/sec")


if __name__ == "__main__":
    main()
